"""Data-plane collective bench: implicit psum vs explicit reduce-scatter
vs bucketed-overlap accumulation, on flat and hierarchical meshes.

Three arms of the SAME model/optimizer/batch under ZeRO-1 moment sharding,
interleaved-window paired in one process (the bench.py / bench_pipeline.py
honest-accounting convention):

- ``psum`` — the implicit data plane (seed behavior): XLA all-reduces the
  full gradient and all-gathers the updated params behind the moment
  sharding. Analytic bytes/chip/step: AR(grads) + AG(params) =
  3·P·(N−1)/N.
- ``reduce_scatter`` — the explicit plane (``grad_sync="reduce_scatter"``):
  gradients pinned to their ZeRO shard layout before the optimizer update,
  so the reduction lowers as reduce-scatter, the update runs on 1/N
  shards, and one all-gather rebuilds the params. 2·P·(N−1)/N — the
  strict-inequality invariant this artifact commits.
- ``bucketed_overlap`` — the explicit plane under scan-based gradient
  accumulation (``grad_accum_microbatches``): microbatch k's gradient
  buckets reduce with no data dependence on microbatch k+1's backward.
  Per-bucket byte accounting from `Trainer.data_plane`.

Every record carries BOTH the measured step wall time and the analytic
bytes-on-wire from `parallel.collective.collective_bytes` (the closed
form validated leaf-by-leaf in tests/test_collective.py), per mesh tier —
on the hierarchical ``("dcn", "data")`` mesh the DCN row shows the
cross-slice hop staying at shard size under the explicit plane.

CPU-sim caveat (same stance as bench_pipeline.py): the 8 forced host
devices share one memory system, so "collectives" are local copies —
measured ms establish that the explicit plane costs no compute-side
regression and exact numerics parity holds, while the committed
bytes-on-wire numbers are the analytic truth the fabric will see. Point
EDL_BENCH_PLATFORM at the chip when the tunnel opens.

Env: EDL_COLL_DEVICES (8), EDL_COLL_MESHES (JSON list of axis dicts,
default [{"data": 8}, {"dcn": 2, "data": 4}]), EDL_COLL_BATCH (64),
EDL_COLL_ACCUM (4), EDL_COLL_BUCKET_MB (0.25),
EDL_COLL_VOCAB/D_MODEL/LAYERS/HEADS/D_FF/SEQ (model dims),
EDL_COLL_OPT (adam), EDL_BENCH_WINDOWS (3), EDL_BENCH_STEPS (5),
EDL_COLL_OUT (output path), EDL_BENCH_PLATFORM (cpu). Writes
BENCH_COLLECTIVE.json next to this file and prints one summary JSON line.
"""

from __future__ import annotations

import json
import os
import statistics
import time


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, str(default)))


def _env_float(name: str, default: float) -> float:
    return float(os.environ.get(name, str(default)))


def _env_json(name: str, default):
    val = json.loads(os.environ.get(name, "null"))
    return default if val is None else val


def main() -> dict:
    n_dev = _env_int("EDL_COLL_DEVICES", 8)
    os.environ.setdefault("EDL_BENCH_PLATFORM", "cpu")
    if os.environ["EDL_BENCH_PLATFORM"] == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n_dev}"
            ).strip()

    import jax
    import numpy as np

    from bench import probe_or_exit

    devices, init_attempts = probe_or_exit("collective_data_plane", "ms/step")

    from edl_tpu.models import transformer
    from edl_tpu.parallel import MeshSpec, build_hierarchical_mesh, build_mesh
    from edl_tpu.runtime import Trainer, TrainerConfig

    meshes = _env_json(
        "EDL_COLL_MESHES", [{"data": n_dev}, {"dcn": 2, "data": n_dev // 2}]
    )
    batch_size = _env_int("EDL_COLL_BATCH", 64)
    accum = _env_int("EDL_COLL_ACCUM", 4)
    bucket_mb = _env_float("EDL_COLL_BUCKET_MB", 0.25)
    windows = _env_int("EDL_BENCH_WINDOWS", 3)
    steps = max(1, _env_int("EDL_BENCH_STEPS", 5))
    optimizer = os.environ.get("EDL_COLL_OPT", "adam")

    base = dict(
        vocab_size=_env_int("EDL_COLL_VOCAB", 256),
        d_model=_env_int("EDL_COLL_D_MODEL", 64),
        n_layers=_env_int("EDL_COLL_LAYERS", 4),
        n_heads=_env_int("EDL_COLL_HEADS", 8),
        d_ff=_env_int("EDL_COLL_D_FF", 256),
        seq_len=_env_int("EDL_COLL_SEQ", 64),
    )
    model = transformer.make_model(**base)
    rng = np.random.default_rng(0)
    host_batch = model.synthetic_batch(rng, batch_size)

    ARMS = ("psum", "reduce_scatter", "bucketed_overlap")

    records = []
    crossover = {}
    for axes in meshes:
        axes = {k: int(v) for k, v in axes.items()}
        spec = MeshSpec(axes)
        use = devices[: spec.size()]
        mesh = (
            build_hierarchical_mesh(spec, use)
            if axes.get("dcn", 1) > 1
            else build_mesh(spec, use)
        )
        batch_axis = ("dcn", "data") if "dcn" in mesh.axis_names else "data"
        mesh_key = "x".join(f"{k}{v}" for k, v in axes.items())

        def make_arm(arm: str):
            cfg = TrainerConfig(
                optimizer=optimizer,
                shard_opt_state=True,
                batch_axis=batch_axis,
                grad_sync="psum" if arm == "psum" else "reduce_scatter",
                grad_accum_microbatches=accum if arm == "bucketed_overlap" else 1,
                grad_bucket_mb=bucket_mb,
            )
            trainer = Trainer(model, mesh, cfg)
            state = trainer.init_state()
            placed = trainer.place_batch(host_batch)
            return {"trainer": trainer, "state": state, "placed": placed,
                    "loss": None}

        def window(arm_state, n=steps):
            state, loss = arm_state["state"], arm_state["loss"]
            for _ in range(n):
                state, loss = arm_state["trainer"].train_step(
                    state, arm_state["placed"]
                )
            jax.block_until_ready(loss)
            arm_state["state"], arm_state["loss"] = state, loss
            return loss

        arms = {name: make_arm(name) for name in ARMS}
        for a in arms.values():  # compile + warm outside the timed windows
            window(a, n=2)
        # exact-numerics check rides the warmup: psum and rs arms saw the
        # identical batch/seed, so their losses must agree to fp32 exactness
        parity = {
            name: float(arms[name]["loss"]) for name in ("psum", "reduce_scatter")
        }

        walls = {name: [] for name in ARMS}
        for k in range(windows):
            # rotate arm order per window so drift cancels from the pairs
            order = list(ARMS[k % len(ARMS):]) + list(ARMS[: k % len(ARMS)])
            for name in order:
                t0 = time.perf_counter()
                window(arms[name])
                walls[name].append((time.perf_counter() - t0) / steps)

        for name in ARMS:
            plane = arms[name]["trainer"].data_plane(arms[name]["state"].params)
            rec = {
                "mesh": axes,
                "mesh_key": mesh_key,
                "arm": name,
                "grad_sync": plane["grad_sync"],
                "grad_accum_microbatches": plane["grad_accum_microbatches"],
                "step_ms": round(1e3 * statistics.median(walls[name]), 2),
                "step_ms_windows": [round(1e3 * w, 2) for w in walls[name]],
                "grad_bytes_per_step": plane["grad_bytes_per_step"],
                "param_bytes_per_step": plane["param_bytes_per_step"],
                "bytes_per_step": plane["bytes_per_step"],
                "per_tier_bytes": plane["per_tier_bytes"],
                "collective_ms_est": round(
                    1e3 * plane["collective_seconds"], 4
                ),
                "n_buckets": plane["n_buckets"],
                "bucket_nbytes": plane["bucket_nbytes"],
            }
            records.append(rec)
            print(json.dumps(rec), flush=True)

        by_arm = {r["arm"]: r for r in records if r["mesh_key"] == mesh_key}
        rs, ps = by_arm["reduce_scatter"], by_arm["psum"]
        assert rs["bytes_per_step"] < ps["bytes_per_step"], (
            "explicit reduce-scatter must move strictly fewer bytes than "
            f"implicit psum; got {rs['bytes_per_step']} vs "
            f"{ps['bytes_per_step']}"
        )
        crossover[mesh_key] = {
            "rs_vs_psum_bytes_ratio": round(
                rs["bytes_per_step"] / ps["bytes_per_step"], 4
            ),
            "rs_vs_psum_step_ratio": round(
                rs["step_ms"] / ps["step_ms"], 3
            ),
            "bucketed_vs_psum_step_ratio": round(
                by_arm["bucketed_overlap"]["step_ms"] / ps["step_ms"], 3
            ),
            "dcn_bytes_rs_vs_psum": (
                round(
                    rs["per_tier_bytes"]["dcn"] / ps["per_tier_bytes"]["dcn"],
                    4,
                )
                if "dcn" in rs["per_tier_bytes"]
                else None
            ),
            "loss_parity_abs_diff": abs(
                parity["psum"] - parity["reduce_scatter"]
            ),
        }

    summary = {
        "metric": "collective_data_plane",
        "unit": "ms/step",
        "backend": devices[0].platform,
        "meshes": meshes,
        "model": base,
        "optimizer": optimizer,
        "batch": batch_size,
        "grad_accum_microbatches": accum,
        "grad_bucket_mb": bucket_mb,
        "steps": steps,
        "windows": windows,
        "timing_caveat": (
            "CPU-sim numbers: forced host devices share one memory system, "
            "so measured ms establish numerics parity and the absence of a "
            "compute-side regression; the committed bytes-on-wire columns "
            "are the analytic closed form the fabric will see"
        ),
        "crossover": crossover,
        "init_attempts": init_attempts,
        "records": records,
    }
    here = os.path.dirname(os.path.abspath(__file__))
    out = os.environ.get(
        "EDL_COLL_OUT", os.path.join(here, "BENCH_COLLECTIVE.json")
    )
    with open(out, "w") as f:
        json.dump(summary, f, indent=1)
    print(json.dumps({
        "metric": summary["metric"],
        "backend": summary["backend"],
        "configs": len(records),
        "crossover": crossover,
    }))
    return summary


if __name__ == "__main__":
    main()
