"""Microbenchmark: Pallas flash attention vs the dense einsum path.

Times causal self-attention forward+backward at transformer-realistic
shapes on the live backend and prints one JSON line per shape with the
paired speedup (interleaved windows, same methodology as bench.py — on
the tunneled chip only same-run paired ratios mean anything,
BENCH_NOTES.md). Dense materializes the (S, S) score matrix, so its
memory grows O(S^2) and it eventually OOMs where flash keeps O(S);
shapes that fail on one arm are reported as such rather than crashed on.

Usage:
  python bench_flash.py                   # on the live backend
  EDL_BENCH_PLATFORM=cpu python bench_flash.py   # interpret-mode smoke
  EDL_FLASH_SHAPES='[[1,2048,8,64]]' python bench_flash.py
"""

from __future__ import annotations

import json
import os
import statistics
import time

#: (B, S, H, D) — S sweeps past where dense's S^2 scores dominate HBM
_DEFAULT_SHAPES = [
    [4, 1024, 8, 64],
    [4, 2048, 8, 64],
    [2, 4096, 8, 64],
    [1, 8192, 8, 128],
]


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench import probe_or_exit

    devices, init_attempts = probe_or_exit("flash_attention_speedup")

    from edl_tpu.ops import flash_attention
    from edl_tpu.parallel.ring_attention import dense_attention

    shapes = json.loads(os.environ.get("EDL_FLASH_SHAPES", "null")) \
        or _DEFAULT_SHAPES
    windows = max(1, int(os.environ.get("EDL_BENCH_WINDOWS", "5")))
    # clamped: this tool has no zero-step probe mode (bench.py's
    # EDL_BENCH_STEPS=0 convention), and 0 would divide the ms-per-step
    steps = max(1, int(os.environ.get("EDL_BENCH_STEPS", "10")))

    def arm(fn, q, k, v):
        # Full training direction: grads w.r.t. q AND k/v. Grad-of-q alone
        # would let XLA dead-code-eliminate the flash dk/dv backward kernel
        # (it is a separate pallas_call) and overstate MFU by ~50%.
        loss = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(fn(q, k, v) ** 2), argnums=(0, 1, 2)
        ))

        def window():
            t0 = time.perf_counter()
            for _ in range(steps):
                g = loss(q, k, v)
            jax.block_until_ready(g)
            return time.perf_counter() - t0

        jax.block_until_ready(loss(q, k, v))  # compile + warm
        return window

    from edl_tpu.tools.mfu import peak_tflops_per_chip

    peak = peak_tflops_per_chip(devices[0])

    def attn_train_flops(B, S, H, D):
        """fwd+bwd matmul FLOPs of causal attention (MFU convention:
        QK^T and PV are 2*S*D/token each, halved by the mask, x3 for the
        backward; the flash backward's score recompute is excluded like
        any remat)."""
        return 3.0 * 0.5 * (4 * S * D) * B * S * H

    rng = np.random.default_rng(0)
    for B, S, H, D in shapes:
        q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.bfloat16)
        k = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.bfloat16)
        v = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.bfloat16)
        record = {"metric": "flash_attention_speedup",
                  "shape_BSHD": [B, S, H, D], "steps": steps,
                  "init_attempts": init_attempts}
        try:
            run_flash = arm(lambda q, k, v: flash_attention(q, k, v), q, k, v)
        except Exception as e:  # noqa: BLE001 — report, don't crash the sweep
            record["flash_error"] = str(e)[:200]
            print(json.dumps(record))
            continue
        try:
            run_dense = arm(
                lambda q, k, v: dense_attention(q, k, v), q, k, v
            )
        except Exception as e:  # noqa: BLE001 — dense OOMs first at long S
            record["dense_error"] = str(e)[:200]
            record["note"] = "dense arm failed (expected at long S); flash ran"
            ts = [run_flash() for _ in range(windows)]
            flash_ms = 1e3 * statistics.median(ts) / steps
            flops = attn_train_flops(B, S, H, D)
            achieved = flops / (flash_ms / 1e3) / 1e12
            record.update(
                flash_ms_per_step=round(flash_ms, 3),
                model_flops=flops,
                flops_method="analytic",
                tflops_per_sec=round(achieved, 3),
                peak_tflops=peak,
                mfu=round(achieved / peak, 4) if peak else None,
            )
            print(json.dumps(record))
            continue
        fl, dn, ratios = [], [], []
        for i in range(windows):
            if i % 2 == 0:
                f, d = run_flash(), run_dense()
            else:
                d, f = run_dense(), run_flash()
            fl.append(f)
            dn.append(d)
            ratios.append(d / f)
        flash_ms = 1e3 * statistics.median(fl) / steps
        flops = attn_train_flops(B, S, H, D)
        achieved = flops / (flash_ms / 1e3) / 1e12
        record.update(
            flash_ms_per_step=round(flash_ms, 3),
            dense_ms_per_step=round(1e3 * statistics.median(dn) / steps, 3),
            speedup=round(statistics.median(ratios), 3),
            paired_ratios=[round(r, 3) for r in ratios],
            model_flops=flops,
            flops_method="analytic",
            tflops_per_sec=round(achieved, 3),
            peak_tflops=peak,
            mfu=round(achieved / peak, 4) if peak else None,
        )
        print(json.dumps(record))


if __name__ == "__main__":
    main()
