"""Control-plane load bench: one coordinator under 100/1k/10k workers.

Drives a REAL coordinator process (the native C++ binary, spawned via
`edl_tpu.coordinator.server.CoordinatorServer`) with an event-driven
client multiplexer: one nonblocking TCP socket per simulated worker,
closed-loop (each worker keeps exactly one control-plane "beat" in
flight), all multiplexed through one `selectors` loop — NO 10k threads.
The emitted BENCH_COORD.json is the artifact behind the control-plane
section of doc/performance.md.

Arms (both run the same binary; the delta is protocol + poller):

- ``before`` — the pre-batching protocol shape under the poll(2) event
  loop (``EDL_COORD_FORCE_POLL=1``): each beat is THREE separate frames
  — heartbeat, kv_put (the worker's routine publish), and a dedicated
  ``status`` round-trip for epoch discovery (what ``client.epoch()``
  used before replies carried the epoch). Note this still understates
  the seed server: the per-worker lease index and the deadline-cached
  expiry scan benefit both arms, so the measured gap is conservative.
- ``after`` — the batched/coalesced protocol on epoll: ONE ``batch``
  frame per beat carrying [heartbeat, kv_put]; epoch discovery rides
  the epoch stamped on every reply, so the dedicated poll disappears.

Reported per (arm, N): worker beats/sec, server ops/sec, beat-latency
p50/p99 (ms), journal fsyncs/sec and ops-per-fsync (group-commit
amortization — fsyncs/sec should stay ~flat as N grows), ops-per-turn,
snapshot compactions, and server CPU-seconds per kop (from
/proc/<pid>/stat). Single-core caveat: bench and server share the
machine, so absolute throughput is a floor and CPU-seconds/op plus the
BETWEEN-ARM ratios are the meaningful numbers.

Env: EDL_COORD_NS ([100,1000,10000]), EDL_COORD_SECS (4.0 measured
window), EDL_COORD_WARMUP (0.5), EDL_COORD_ARMS (["before","after"]),
EDL_COORD_WAVE (128 — registration wave size, bounded by the server's
listen backlog), EDL_COORD_OUT (output path). Writes BENCH_COORD.json
next to this file and prints a one-line summary JSON.
"""

from __future__ import annotations

import json
import os
import resource
import selectors
import socket
import statistics
import tempfile
import time

REPO = os.path.dirname(os.path.abspath(__file__))


def _env_float(name: str, default: float) -> float:
    return float(os.environ.get(name, str(default)))


def _env_list(name: str, default: list) -> list:
    val = json.loads(os.environ.get(name, "null"))
    if val is None or val == []:
        return default
    return val if isinstance(val, list) else [val]


def _frame(obj: dict) -> bytes:
    return (json.dumps(obj, separators=(",", ":")) + "\n").encode()


class Sim:
    """One simulated worker: a socket plus its closed-loop beat state.

    A beat is a SEQUENCE of request-response stages, because that is what
    the client transport does: ``CoordinatorClient.call`` is strictly
    sequential, so the pre-batching worker's heartbeat + kv_put + epoch
    poll are three dependent round trips, not three pipelined frames.
    The batched beat is one stage.
    """

    __slots__ = ("sock", "name", "out", "expect", "t_send", "stages",
                 "stage", "beats", "raw", "capture")

    def __init__(self, sock: socket.socket, name: str):
        self.sock = sock
        self.name = name
        self.out = b""       # unflushed bytes of the current frame
        self.expect = 0      # reply lines outstanding for the current stage
        self.t_send = 0.0    # beat start (stage 0 send time)
        self.stages = []     # [(frame bytes, reply lines), ...] per beat
        self.stage = -1      # index of the stage in flight (-1 = idle)
        self.beats = 0
        self.raw = b""       # reply capture (registration validation only)
        self.capture = False


def _flush(sel: selectors.DefaultSelector, s: Sim) -> None:
    """Send what we can; arm EVENT_WRITE only while bytes remain queued."""
    while s.out:
        try:
            n = s.sock.send(s.out)
        except (BlockingIOError, InterruptedError):
            break
        s.out = s.out[n:]
    want = selectors.EVENT_READ | (selectors.EVENT_WRITE if s.out else 0)
    if sel.get_key(s.sock).events != want:
        sel.modify(s.sock, want, s)


def _send_stage(sel: selectors.DefaultSelector, s: Sim, idx: int) -> None:
    payload, nreplies = s.stages[idx]
    s.stage = idx
    s.out += payload
    s.expect = nreplies
    if idx == 0:
        s.t_send = time.monotonic()
    _flush(sel, s)


def _handle(sel, key, mask, lats, reissue: bool) -> None:
    s: Sim = key.data
    if mask & selectors.EVENT_WRITE:
        _flush(sel, s)
    if mask & selectors.EVENT_READ:
        try:
            data = s.sock.recv(1 << 16)
        except (BlockingIOError, InterruptedError):
            return
        if not data:
            raise RuntimeError(f"coordinator closed connection to {s.name}")
        if s.capture:
            s.raw += data
        k = data.count(b"\n")
        if k and s.expect > 0:
            s.expect -= k
            if s.expect <= 0:
                if s.stage + 1 < len(s.stages):
                    _send_stage(sel, s, s.stage + 1)  # next round trip
                else:
                    s.beats += 1
                    s.stage = -1
                    if lats is not None:
                        lats.append(time.monotonic() - s.t_send)
                    if reissue:
                        _send_stage(sel, s, 0)


def _pump(sel, sims, seconds: float, lats=None) -> None:
    """Closed-loop drive for ``seconds``: idle sims get their next beat."""
    t_end = time.monotonic() + seconds
    for s in sims:
        if s.stage < 0:
            _send_stage(sel, s, 0)
    while True:
        left = t_end - time.monotonic()
        if left <= 0:
            return
        for key, mask in sel.select(timeout=min(0.05, left)):
            _handle(sel, key, mask, lats, reissue=True)


def _connect_and_register(sel, port: int, n: int, wave: int):
    """Open + register ``n`` worker sockets in waves bounded by the server's
    listen backlog, validating every register reply."""
    sims = []
    for base in range(0, n, wave):
        batch = []
        for i in range(base, min(base + wave, n)):
            name = f"w{i:05d}"
            sk = socket.create_connection(("127.0.0.1", port), timeout=10.0)
            sk.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sk.setblocking(False)
            s = Sim(sk, name)
            s.capture = True
            sel.register(sk, selectors.EVENT_READ, s)
            s.stages = [(_frame({"op": "register", "worker": name}), 1)]
            _send_stage(sel, s, 0)
            batch.append(s)
        deadline = time.monotonic() + 60.0
        while any(s.expect > 0 for s in batch):
            if time.monotonic() > deadline:
                raise RuntimeError(f"registration stalled at {len(sims)}")
            for key, mask in sel.select(timeout=0.5):
                _handle(sel, key, mask, None, reissue=False)
        for s in batch:
            if b'"ok":true' not in s.raw:
                raise RuntimeError(f"register failed for {s.name}: {s.raw!r}")
            s.raw = b""
            s.capture = False
        sims += batch
    return sims


def _server_cpu_seconds(pid: int) -> float:
    with open(f"/proc/{pid}/stat") as fh:
        parts = fh.read().rsplit(")", 1)[1].split()
    return (int(parts[11]) + int(parts[12])) / os.sysconf("SC_CLK_TCK")


def _counters(status: dict) -> dict:
    keys = ("ops", "batch_frames", "batch_subops", "fsyncs", "snapshots",
            "journal_records", "turns")
    return {k: int(status.get(k, 0)) for k in keys}


def _beat_stages(arm: str, name: str) -> list:
    """Request-response stages of one control-plane beat."""
    hb = {"op": "heartbeat", "worker": name}
    kv = {"op": "kv_put", "worker": name, "key": f"bench/{name}", "value": "x"}
    if arm == "before":
        # Pre-batching shape: three DEPENDENT round trips (the sequential
        # client transport), including the dedicated epoch poll that
        # reply-stamping makes obsolete.
        return [(_frame(hb), 1), (_frame(kv), 1),
                (_frame({"op": "status"}), 1)]
    return [(_frame({
        "op": "batch", "worker": name,
        "ops": [json.dumps(hb, separators=(",", ":")),
                json.dumps(kv, separators=(",", ":"))],
    }), 1)]


def run_cell(arm: str, n: int, mode: str, secs: float, warmup: float,
             wave: int, active: int, tmpdir: str) -> dict:
    """One measured window.

    ``mode="saturated"`` drives all N workers closed-loop — the ceiling
    measurement: max sustainable ops/sec, group-commit amortization,
    CPU per op. ``mode="duty"`` drives only ``active`` workers while the
    other N-active stay REGISTERED BUT IDLE — the realistic regime (a
    worker beats ~1/s and a beat lasts ~1ms, so <1% of a 10k fleet is
    mid-RPC at any instant) and the one that exposes the poll(2) tax:
    every turn scans all N descriptors to find the few ready ones.
    """
    from edl_tpu.coordinator.server import CoordinatorServer

    if arm == "before":
        os.environ["EDL_COORD_FORCE_POLL"] = "1"
    else:
        os.environ.pop("EDL_COORD_FORCE_POLL", None)
    # Long TTL/lease: the bench measures steady-state RPC handling, not
    # expiry churn (expiry behavior has its own tests).
    server = CoordinatorServer(
        task_lease_sec=600.0, heartbeat_ttl_sec=600.0, auth_token="",
        state_file=os.path.join(tmpdir, f"{arm}-{n}-{mode}.state"))
    server.start()
    sel = selectors.DefaultSelector()
    try:
        ctl = server.client("bench-ctl")
        sims = _connect_and_register(sel, server.port, n, wave)
        if mode == "duty":
            # Spread the active subset across the fd range so neither
            # poller gets a locality gift.
            stride = max(1, n // min(active, n))
            sims = sims[::stride][:active]
        for s in sims:
            s.stages = _beat_stages(arm, s.name)
        _pump(sel, sims, warmup)

        pid = server._proc.pid
        c0, cpu0 = _counters(ctl.status()), _server_cpu_seconds(pid)
        lats: list = []
        t0 = time.monotonic()
        _pump(sel, sims, secs, lats)
        dt = time.monotonic() - t0
        c1, cpu1 = _counters(ctl.status()), _server_cpu_seconds(pid)
        ctl.close()

        d = {k: c1[k] - c0[k] for k in c0}
        beats = len(lats)
        lats_ms = sorted(x * 1000.0 for x in lats)
        ops = d["ops"]
        return {
            "arm": arm, "n": n, "mode": mode,
            "active_workers": len(sims), "seconds": round(dt, 3),
            "poller": "poll" if arm == "before" else "epoll",
            "beats": beats,
            "beats_per_sec": round(beats / dt, 1),
            "ops_per_sec": round(ops / dt, 1),
            "p50_ms": round(statistics.median(lats_ms), 3) if lats_ms else None,
            "p99_ms": round(lats_ms[max(0, int(len(lats_ms) * 0.99) - 1)], 3)
            if lats_ms else None,
            "fsyncs_per_sec": round(d["fsyncs"] / dt, 2),
            "ops_per_fsync": round(ops / d["fsyncs"], 1) if d["fsyncs"] else None,
            "ops_per_turn": round(ops / d["turns"], 2) if d["turns"] else None,
            "batch_frames": d["batch_frames"],
            "batch_subops": d["batch_subops"],
            "journal_records": d["journal_records"],
            "snapshots": d["snapshots"],
            "server_cpu_sec": round(cpu1 - cpu0, 3),
            "server_cpu_sec_per_kop": round((cpu1 - cpu0) / ops * 1000.0, 4)
            if ops else None,
        }
    finally:
        for key in list(sel.get_map().values()):
            key.fileobj.close()
        sel.close()
        server.stop()
        os.environ.pop("EDL_COORD_FORCE_POLL", None)


def main() -> dict:
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < hard:
        resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))

    ns = [int(x) for x in _env_list("EDL_COORD_NS", [100, 1000, 10000])]
    arms = _env_list("EDL_COORD_ARMS", ["before", "after"])
    modes = _env_list("EDL_COORD_MODES", ["saturated", "duty"])
    secs = _env_float("EDL_COORD_SECS", 4.0)
    warmup = _env_float("EDL_COORD_WARMUP", 0.5)
    wave = int(_env_float("EDL_COORD_WAVE", 128))
    active = int(_env_float("EDL_COORD_ACTIVE", 64))

    results = []
    with tempfile.TemporaryDirectory(prefix="edl-bench-coord-") as tmpdir:
        for n in ns:
            for mode in modes:
                for arm in arms:
                    cell = run_cell(arm, n, mode, secs, warmup, wave,
                                    active, tmpdir)
                    print(json.dumps(cell))
                    results.append(cell)

    by = {(c["arm"], c["n"], c["mode"]): c for c in results}
    crossover = []
    for n in ns:
        for mode in modes:
            b = by.get(("before", n, mode))
            a = by.get(("after", n, mode))
            if not (b and a):
                continue
            crossover.append({
                "n": n, "mode": mode,
                "beats_speedup":
                round(a["beats_per_sec"] / b["beats_per_sec"], 2)
                if b["beats_per_sec"] else None,
                "p99_ratio": round(b["p99_ms"] / a["p99_ms"], 2)
                if b["p99_ms"] and a["p99_ms"] else None,
                "cpu_per_kop_ratio":
                round(b["server_cpu_sec_per_kop"]
                      / a["server_cpu_sec_per_kop"], 2)
                if b["server_cpu_sec_per_kop"] and a["server_cpu_sec_per_kop"]
                else None,
            })
    out = {
        "bench": "coordinator_control_plane",
        "config": {"ns": ns, "arms": arms, "modes": modes, "seconds": secs,
                   "warmup": warmup, "active_workers_duty": active,
                   "cpus": os.cpu_count(),
                   "note": "bench and server share the host; ratios between "
                           "arms are the meaningful numbers. The before arm "
                           "understates the seed server (lease index + tick "
                           "cache benefit both arms)."},
        "results": results,
        "crossover": crossover,
    }
    path = os.environ.get("EDL_COORD_OUT", os.path.join(REPO, "BENCH_COORD.json"))
    with open(path, "w") as fh:
        json.dump(out, fh, indent=1)
        fh.write("\n")
    print(json.dumps({"wrote": path, "crossover": crossover}))
    return out


if __name__ == "__main__":
    main()
