"""Control-plane load bench: one coordinator under 100/1k/10k workers.

Drives a REAL coordinator process (the native C++ binary, spawned via
`edl_tpu.coordinator.server.CoordinatorServer`) with an event-driven
client multiplexer: one nonblocking TCP socket per simulated worker,
closed-loop (each worker keeps exactly one control-plane "beat" in
flight), all multiplexed through one `selectors` loop — NO 10k threads.
The emitted BENCH_COORD.json is the artifact behind the control-plane
section of doc/performance.md.

Arms (both run the same binary; the delta is protocol + poller):

- ``before`` — the pre-batching protocol shape under the poll(2) event
  loop (``EDL_COORD_FORCE_POLL=1``): each beat is THREE separate frames
  — heartbeat, kv_put (the worker's routine publish), and a dedicated
  ``status`` round-trip for epoch discovery (what ``client.epoch()``
  used before replies carried the epoch). Note this still understates
  the seed server: the per-worker lease index and the deadline-cached
  expiry scan benefit both arms, so the measured gap is conservative.
- ``after`` — the batched/coalesced protocol on epoll: ONE ``batch``
  frame per beat carrying [heartbeat, kv_put]; epoch discovery rides
  the epoch stamped on every reply, so the dedicated poll disappears.

Reported per (arm, N): worker beats/sec, server ops/sec, beat-latency
p50/p99 (ms), journal fsyncs/sec and ops-per-fsync (group-commit
amortization — fsyncs/sec should stay ~flat as N grows), ops-per-turn,
snapshot compactions, and server CPU-seconds per kop (from
/proc/<pid>/stat). Single-core caveat: bench and server share the
machine, so absolute throughput is a floor and CPU-seconds/op plus the
BETWEEN-ARM ratios are the meaningful numbers.

Beyond the protocol arms, two further sections (EDL_COORD_SECTIONS):

- ``topology`` — single coordinator vs the sharded control plane
  (`ShardedCoordinator`: thin root + hash-partitioned shard servers) at
  N in {10k, 50k, 100k} LOGICAL workers. Per-worker sockets hit the fd
  rlimit long before 100k, so this section multiplexes logical workers
  over a bounded connection pool (EDL_COORD_MAX_CONNS): server-side
  state and per-op work scale with N while the socket count stays
  fixed — state-size scaling is what single-vs-sharded differ on, not
  fd count. Sharded beats go straight to the owning shard (the real
  client routes there after its first redirect) and carry the same
  batch[heartbeat, kv_put] frame; liveness refresh is delegated to the
  shard the worker's traffic lands on, while the root holds the global
  membership of record + shard map (registered untimed at setup) — the
  thin-root design point: pushing every beat through the root would
  just re-centralize it. Beat kv values carry EDL_COORD_KV_BYTES of
  payload (default 1 KiB): the traffic sharding exists for is
  checkpoint-plane/state publishes whose journal bytes dominate, not
  bare heartbeats — with tiny values neither server is the bottleneck
  behind the bench's own client loop and the cell measures nothing.
- ``propagation`` — pull-vs-push epoch discovery latency: N workers
  heartbeat at the configured period (phase-spread, the pull baseline)
  or hold ``watch`` subscriptions (push); one bump_epoch, and the
  per-worker delay from bump to discovery is the distribution. Push
  must land well under the heartbeat period (the worker acts in ~one
  RTT instead of waiting out its poll cadence).

Env: EDL_COORD_NS ([100,1000,10000]), EDL_COORD_SECS (4.0 measured
window), EDL_COORD_WARMUP (0.5), EDL_COORD_ARMS (["before","after"]),
EDL_COORD_WAVE (128 — registration wave size, bounded by the server's
listen backlog), EDL_COORD_SECTIONS (["arms","topology","propagation"]),
EDL_COORD_SHARD_NS ([10000,50000,100000]), EDL_COORD_MAX_CONNS (1024),
EDL_COORD_KV_BYTES (1024 — topology beat kv payload size),
EDL_COORD_PROP_WORKERS (200), EDL_COORD_PROP_PERIOD (1.0 s),
EDL_COORD_OUT (output path). Writes BENCH_COORD.json next to this file
and prints a one-line summary JSON. ``--smoke`` runs a <60 s sanity
slice (N=500, both topologies, plus a fast propagation pair) to a
throwaway path and exits nonzero if any cell is implausible — the
`make verify` hook for this harness.
"""

from __future__ import annotations

import json
import os
import resource
import selectors
import socket
import statistics
import tempfile
import time

REPO = os.path.dirname(os.path.abspath(__file__))


def _env_float(name: str, default: float) -> float:
    return float(os.environ.get(name, str(default)))


def _env_list(name: str, default: list) -> list:
    val = json.loads(os.environ.get(name, "null"))
    if val is None or val == []:
        return default
    return val if isinstance(val, list) else [val]


def _frame(obj: dict) -> bytes:
    return (json.dumps(obj, separators=(",", ":")) + "\n").encode()


class Sim:
    """One simulated worker: a socket plus its closed-loop beat state.

    A beat is a SEQUENCE of request-response stages, because that is what
    the client transport does: ``CoordinatorClient.call`` is strictly
    sequential, so the pre-batching worker's heartbeat + kv_put + epoch
    poll are three dependent round trips, not three pipelined frames.
    The batched beat is one stage.
    """

    __slots__ = ("sock", "name", "out", "expect", "t_send", "stages",
                 "stage", "beats", "raw", "capture", "gen", "next_due")

    def __init__(self, sock: socket.socket, name: str):
        self.sock = sock
        self.name = name
        self.out = b""       # unflushed bytes of the current frame
        self.expect = 0      # reply lines outstanding for the current stage
        self.t_send = 0.0    # beat start (stage 0 send time)
        self.stages = []     # [(frame bytes, reply lines), ...] per beat
        self.stage = -1      # index of the stage in flight (-1 = idle)
        self.beats = 0
        self.raw = b""       # reply capture (registration validation only)
        self.capture = False
        self.gen = None      # optional () -> stages, rebuilt per beat (mux)
        self.next_due = 0.0  # paced (open-loop) send time; propagation only


def _flush(sel: selectors.DefaultSelector, s: Sim) -> None:
    """Send what we can; arm EVENT_WRITE only while bytes remain queued."""
    while s.out:
        try:
            n = s.sock.send(s.out)
        except (BlockingIOError, InterruptedError):
            break
        s.out = s.out[n:]
    want = selectors.EVENT_READ | (selectors.EVENT_WRITE if s.out else 0)
    if sel.get_key(s.sock).events != want:
        sel.modify(s.sock, want, s)


def _send_stage(sel: selectors.DefaultSelector, s: Sim, idx: int) -> None:
    payload, nreplies = s.stages[idx]
    s.stage = idx
    s.out += payload
    s.expect = nreplies
    if idx == 0:
        s.t_send = time.monotonic()
    _flush(sel, s)


def _handle(sel, key, mask, lats, reissue: bool) -> None:
    s: Sim = key.data
    if mask & selectors.EVENT_WRITE:
        _flush(sel, s)
    if mask & selectors.EVENT_READ:
        try:
            data = s.sock.recv(1 << 16)
        except (BlockingIOError, InterruptedError):
            return
        if not data:
            raise RuntimeError(f"coordinator closed connection to {s.name}")
        if s.capture:
            s.raw += data
        k = data.count(b"\n")
        if k and s.expect > 0:
            s.expect -= k
            if s.expect <= 0:
                if s.stage + 1 < len(s.stages):
                    _send_stage(sel, s, s.stage + 1)  # next round trip
                else:
                    s.beats += 1
                    s.stage = -1
                    if lats is not None:
                        lats.append(time.monotonic() - s.t_send)
                    if reissue:
                        if s.gen is not None:
                            s.stages = s.gen()  # next logical worker's beat
                        _send_stage(sel, s, 0)


def _pump(sel, sims, seconds: float, lats=None) -> None:
    """Closed-loop drive for ``seconds``: idle sims get their next beat."""
    t_end = time.monotonic() + seconds
    for s in sims:
        if s.stage < 0:
            _send_stage(sel, s, 0)
    while True:
        left = t_end - time.monotonic()
        if left <= 0:
            return
        for key, mask in sel.select(timeout=min(0.05, left)):
            _handle(sel, key, mask, lats, reissue=True)


def _connect_and_register(sel, port: int, n: int, wave: int):
    """Open + register ``n`` worker sockets in waves bounded by the server's
    listen backlog, validating every register reply."""
    sims = []
    for base in range(0, n, wave):
        batch = []
        for i in range(base, min(base + wave, n)):
            name = f"w{i:05d}"
            sk = socket.create_connection(("127.0.0.1", port), timeout=10.0)
            sk.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sk.setblocking(False)
            s = Sim(sk, name)
            s.capture = True
            sel.register(sk, selectors.EVENT_READ, s)
            s.stages = [(_frame({"op": "register", "worker": name}), 1)]
            _send_stage(sel, s, 0)
            batch.append(s)
        deadline = time.monotonic() + 60.0
        while any(s.expect > 0 for s in batch):
            if time.monotonic() > deadline:
                raise RuntimeError(f"registration stalled at {len(sims)}")
            for key, mask in sel.select(timeout=0.5):
                _handle(sel, key, mask, None, reissue=False)
        for s in batch:
            if b'"ok":true' not in s.raw:
                raise RuntimeError(f"register failed for {s.name}: {s.raw!r}")
            s.raw = b""
            s.capture = False
        sims += batch
    return sims


def _server_cpu_seconds(pid: int) -> float:
    with open(f"/proc/{pid}/stat") as fh:
        parts = fh.read().rsplit(")", 1)[1].split()
    return (int(parts[11]) + int(parts[12])) / os.sysconf("SC_CLK_TCK")


def _counters(status: dict) -> dict:
    keys = ("ops", "batch_frames", "batch_subops", "fsyncs", "snapshots",
            "journal_records", "turns")
    return {k: int(status.get(k, 0)) for k in keys}


def _beat_stages(arm: str, name: str) -> list:
    """Request-response stages of one control-plane beat."""
    hb = {"op": "heartbeat", "worker": name}
    kv = {"op": "kv_put", "worker": name, "key": f"bench/{name}", "value": "x"}
    if arm == "before":
        # Pre-batching shape: three DEPENDENT round trips (the sequential
        # client transport), including the dedicated epoch poll that
        # reply-stamping makes obsolete.
        return [(_frame(hb), 1), (_frame(kv), 1),
                (_frame({"op": "status"}), 1)]
    return [(_frame({
        "op": "batch", "worker": name,
        "ops": [json.dumps(hb, separators=(",", ":")),
                json.dumps(kv, separators=(",", ":"))],
    }), 1)]


def run_cell(arm: str, n: int, mode: str, secs: float, warmup: float,
             wave: int, active: int, tmpdir: str) -> dict:
    """One measured window.

    ``mode="saturated"`` drives all N workers closed-loop — the ceiling
    measurement: max sustainable ops/sec, group-commit amortization,
    CPU per op. ``mode="duty"`` drives only ``active`` workers while the
    other N-active stay REGISTERED BUT IDLE — the realistic regime (a
    worker beats ~1/s and a beat lasts ~1ms, so <1% of a 10k fleet is
    mid-RPC at any instant) and the one that exposes the poll(2) tax:
    every turn scans all N descriptors to find the few ready ones.
    """
    from edl_tpu.coordinator.server import CoordinatorServer

    if arm == "before":
        os.environ["EDL_COORD_FORCE_POLL"] = "1"
    else:
        os.environ.pop("EDL_COORD_FORCE_POLL", None)
    # Long TTL/lease: the bench measures steady-state RPC handling, not
    # expiry churn (expiry behavior has its own tests).
    server = CoordinatorServer(
        task_lease_sec=600.0, heartbeat_ttl_sec=600.0, auth_token="",
        state_file=os.path.join(tmpdir, f"{arm}-{n}-{mode}.state"))
    server.start()
    sel = selectors.DefaultSelector()
    try:
        ctl = server.client("bench-ctl")
        sims = _connect_and_register(sel, server.port, n, wave)
        if mode == "duty":
            # Spread the active subset across the fd range so neither
            # poller gets a locality gift.
            stride = max(1, n // min(active, n))
            sims = sims[::stride][:active]
        for s in sims:
            s.stages = _beat_stages(arm, s.name)
        _pump(sel, sims, warmup)

        pid = server._proc.pid
        c0, cpu0 = _counters(ctl.status()), _server_cpu_seconds(pid)
        lats: list = []
        t0 = time.monotonic()
        _pump(sel, sims, secs, lats)
        dt = time.monotonic() - t0
        c1, cpu1 = _counters(ctl.status()), _server_cpu_seconds(pid)
        ctl.close()

        d = {k: c1[k] - c0[k] for k in c0}
        beats = len(lats)
        lats_ms = sorted(x * 1000.0 for x in lats)
        ops = d["ops"]
        return {
            "arm": arm, "n": n, "mode": mode,
            "active_workers": len(sims), "seconds": round(dt, 3),
            "poller": "poll" if arm == "before" else "epoll",
            "beats": beats,
            "beats_per_sec": round(beats / dt, 1),
            "ops_per_sec": round(ops / dt, 1),
            "p50_ms": round(statistics.median(lats_ms), 3) if lats_ms else None,
            "p99_ms": round(lats_ms[max(0, int(len(lats_ms) * 0.99) - 1)], 3)
            if lats_ms else None,
            "fsyncs_per_sec": round(d["fsyncs"] / dt, 2),
            "ops_per_fsync": round(ops / d["fsyncs"], 1) if d["fsyncs"] else None,
            "ops_per_turn": round(ops / d["turns"], 2) if d["turns"] else None,
            "batch_frames": d["batch_frames"],
            "batch_subops": d["batch_subops"],
            "journal_records": d["journal_records"],
            "snapshots": d["snapshots"],
            "server_cpu_sec": round(cpu1 - cpu0, 3),
            "server_cpu_sec_per_kop": round((cpu1 - cpu0) / ops * 1000.0, 4)
            if ops else None,
        }
    finally:
        for key in list(sel.get_map().values()):
            key.fileobj.close()
        sel.close()
        server.stop()
        os.environ.pop("EDL_COORD_FORCE_POLL", None)


def _open_conns(sel, port: int, count: int) -> list:
    """``count`` raw multiplexer connections (no per-socket registration)."""
    conns = []
    for i in range(count):
        sk = socket.create_connection(("127.0.0.1", port), timeout=10.0)
        sk.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sk.setblocking(False)
        s = Sim(sk, f"conn{i:04d}")
        sel.register(sk, selectors.EVENT_READ, s)
        conns.append(s)
    return conns


def _register_logical(sel, conns: list, assignment: list) -> None:
    """Register every logical worker, pipelined over its connection.

    ``assignment[i]`` is the name list conn ``i`` registers (and later
    beats for). One stage per conn: all register frames concatenated,
    replies counted by line — validated by scanning for ok:true exactly
    ``len(names)`` times.
    """
    for s, names in zip(conns, assignment):
        if not names:
            continue
        payload = b"".join(
            _frame({"op": "register", "worker": nm}) for nm in names)
        s.stages = [(payload, len(names))]
        s.capture = True
        _send_stage(sel, s, 0)
    deadline = time.monotonic() + 120.0
    while any(s.expect > 0 for s in conns):
        if time.monotonic() > deadline:
            stuck = sum(1 for s in conns if s.expect > 0)
            raise RuntimeError(f"logical registration stalled ({stuck} conns)")
        for key, mask in sel.select(timeout=0.5):
            _handle(sel, key, mask, None, reissue=False)
    for s, names in zip(conns, assignment):
        acked = s.raw.count(b'"ok":true')
        if names and acked != len(names):
            raise RuntimeError(
                f"{s.name}: {acked}/{len(names)} registrations acked ok")
        s.raw = b""
        s.capture = False
        s.beats = 0


def _mux_gen(names: list, kv_bytes: int):
    """Beat generator cycling a connection's logical workers: each beat is
    the NEXT worker's batch[heartbeat, kv_put] — the batched protocol
    shape, identical under both topologies. The kv value carries
    ``kv_bytes`` of payload: the traffic class sharding exists for is
    checkpoint-plane/state publishes (KB-scale values that dominate the
    journal), not bare heartbeats — tiny values leave the server far from
    saturated behind the bench's own client loop and measure nothing."""
    state = {"i": 0}
    val = "x" * max(1, kv_bytes)

    def gen():
        nm = names[state["i"] % len(names)]
        state["i"] += 1
        hb = {"op": "heartbeat", "worker": nm}
        kv = {"op": "kv_put", "worker": nm, "key": f"bench/{nm}",
              "value": val}
        return [(_frame({
            "op": "batch", "worker": nm,
            "ops": [json.dumps(hb, separators=(",", ":")),
                    json.dumps(kv, separators=(",", ":"))],
        }), 1)]

    return gen


def _sum_counters(clients: list) -> dict:
    total: dict = {}
    for c in clients:
        for k, v in _counters(c.status()).items():
            total[k] = total.get(k, 0) + v
    return total


def run_topology_cell(topology: str, n: int, secs: float, warmup: float,
                      max_conns: int, tmpdir: str,
                      kv_bytes: int = 1024) -> dict:
    """One measured window of the single-vs-sharded comparison at ``n``
    LOGICAL workers multiplexed over ``min(n, max_conns)`` connections."""
    from edl_tpu.coordinator.server import CoordinatorServer, ShardedCoordinator
    from edl_tpu.coordinator.sharding import shard_of

    os.environ.pop("EDL_COORD_FORCE_POLL", None)
    # Window scaled with N so steady state includes snapshot compaction:
    # the journal compacts every ~2N appended records, and writing an
    # O(state)-sized snapshot is exactly the stall that grows with fleet
    # size (and that partitioning halves + overlaps). A short window at
    # large N would sample only the append-path steady state where the
    # topologies tie, and silently miss the tail event being measured.
    secs = max(secs, n / 2500.0)
    names = [f"w{i:06d}" for i in range(n)]
    nconns = min(n, max_conns)
    sel = selectors.DefaultSelector()
    cleanup = []
    try:
        if topology == "single":
            server = CoordinatorServer(
                task_lease_sec=600.0, heartbeat_ttl_sec=600.0, auth_token="",
                state_file=os.path.join(tmpdir, f"single-{n}.state"))
            server.start()
            cleanup.append(server.stop)
            conns = _open_conns(sel, server.port, nconns)
            _register_logical(sel, conns,
                              [names[i::nconns] for i in range(nconns)])
            for s, chunk in zip(conns, [names[i::nconns]
                                        for i in range(nconns)]):
                s.gen = _mux_gen(chunk, kv_bytes)
                s.stages = s.gen()
            ctls = [server.client("bench-ctl")]
            pids = [server._proc.pid]
        else:
            sc = ShardedCoordinator(
                num_shards=2, task_lease_sec=600.0, heartbeat_ttl_sec=600.0,
                auth_token="", state_dir=os.path.join(tmpdir, f"sh-{n}"))
            os.makedirs(os.path.join(tmpdir, f"sh-{n}"), exist_ok=True)
            sc.start()
            cleanup.append(sc.stop)
            nsh = len(sc.shards)
            # Partition logical workers by the shard owning their kv key —
            # exactly where the routed client sends this beat's keyspace op.
            by_shard: list = [[] for _ in range(nsh)]
            for nm in names:
                by_shard[shard_of(f"bench/{nm}", nsh)].append(nm)
            # Root holds the global membership of record (untimed setup).
            root_conns = _open_conns(sel, sc.root.port, min(nconns, 32))
            _register_logical(
                sel, root_conns,
                [names[i::len(root_conns)] for i in range(len(root_conns))])
            for s in root_conns:
                sel.unregister(s.sock)
                s.sock.close()
            conns = []
            per = max(1, nconns // nsh)
            for si, shard in enumerate(sc.shards):
                shard_conns = _open_conns(sel, shard.port, per)
                chunks = [by_shard[si][j::per] for j in range(per)]
                _register_logical(sel, shard_conns, chunks)
                for s, chunk in zip(shard_conns, chunks):
                    if chunk:
                        s.gen = _mux_gen(chunk, kv_bytes)
                        s.stages = s.gen()
                conns += [s for s, chunk in zip(shard_conns, chunks) if chunk]
            ctls = [sv.client("bench-ctl") for sv in [sc.root] + sc.shards]
            pids = [sv._proc.pid for sv in [sc.root] + sc.shards]

        _pump(sel, conns, warmup)
        c0 = _sum_counters(ctls)
        cpu0 = sum(_server_cpu_seconds(p) for p in pids)
        lats: list = []
        t0 = time.monotonic()
        _pump(sel, conns, secs, lats)
        dt = time.monotonic() - t0
        c1 = _sum_counters(ctls)
        cpu1 = sum(_server_cpu_seconds(p) for p in pids)
        for c in ctls:
            c.close()

        d = {k: c1[k] - c0[k] for k in c0}
        beats = len(lats)
        lats_ms = sorted(x * 1000.0 for x in lats)
        ops = d["ops"]
        return {
            "topology": topology, "n": n, "mode": "saturated",
            "kv_bytes": kv_bytes,
            "connections": len(conns), "seconds": round(dt, 3),
            "servers": len(pids),
            "beats": beats,
            "beats_per_sec": round(beats / dt, 1),
            "ops_per_sec": round(ops / dt, 1),
            "p50_ms": round(statistics.median(lats_ms), 3) if lats_ms else None,
            "p99_ms": round(lats_ms[max(0, int(len(lats_ms) * 0.99) - 1)], 3)
            if lats_ms else None,
            "fsyncs_per_sec": round(d["fsyncs"] / dt, 2),
            "ops_per_fsync": round(ops / d["fsyncs"], 1) if d["fsyncs"] else None,
            "journal_records": d["journal_records"],
            "snapshots": d["snapshots"],
            "server_cpu_sec": round(cpu1 - cpu0, 3),
            "server_cpu_sec_per_kop": round((cpu1 - cpu0) / ops * 1000.0, 4)
            if ops else None,
        }
    finally:
        for key in list(sel.get_map().values()):
            key.fileobj.close()
        sel.close()
        for fn in cleanup:
            fn()


def run_propagation(workers: int, period: float, tmpdir: str) -> dict:
    """Pull-vs-push epoch propagation: one bump_epoch, per-worker delay
    from bump to discovery.

    Pull: every worker heartbeats open-loop at ``period`` with uniformly
    spread phases (the fleet's real cadence after jitter de-correlates
    it); discovery is the first reply stamped with the new epoch. Push:
    every worker holds a ``watch`` subscription; discovery is the
    notification frame's arrival.
    """
    from edl_tpu.coordinator.server import CoordinatorServer

    os.environ.pop("EDL_COORD_FORCE_POLL", None)
    server = CoordinatorServer(
        task_lease_sec=600.0, heartbeat_ttl_sec=600.0, auth_token="",
        state_file=os.path.join(tmpdir, "prop.state"))
    server.start()
    sel = selectors.DefaultSelector()
    try:
        ctl = server.client("bench-ctl")

        def quantiles(lat: list) -> dict:
            ms = sorted(x * 1000.0 for x in lat)
            return {
                "discovered": len(ms),
                "mean_ms": round(sum(ms) / len(ms), 3) if ms else None,
                "p50_ms": round(statistics.median(ms), 3) if ms else None,
                "p99_ms": round(ms[max(0, int(len(ms) * 0.99) - 1)], 3)
                if ms else None,
            }

        # -- pull arm ---------------------------------------------------------
        sims = _connect_and_register(sel, server.port, workers, 128)
        e1 = int(ctl.status()["epoch"]) + 1
        marker = f'"epoch":{e1}'.encode()
        for s in sims:
            s.stages = [(_frame({"op": "heartbeat", "worker": s.name}), 1)]
            s.capture = True
        assert int(ctl.bump_epoch()) == e1
        t_bump = time.monotonic()
        # Poll phases uniform over (0, period) relative to the bump: real
        # fleets de-correlate heartbeats with jitter, so a rescale lands at
        # a uniformly random point of each worker's cycle — mean discovery
        # delay period/2, p99 ~ period. Each worker's first paced beat
        # after the bump already carries the new epoch stamp.
        for i, s in enumerate(sims):
            s.next_due = t_bump + (i + 0.5) / workers * period
        pull_lat: list = []
        pending = set(range(len(sims)))
        deadline = t_bump + 3.0 * period + 2.0
        while pending and time.monotonic() < deadline:
            now = time.monotonic()
            for i in list(pending):
                s = sims[i]
                if s.stage < 0 and now >= s.next_due:
                    s.raw = b""
                    _send_stage(sel, s, 0)
                    s.next_due += period
            for key, mask in sel.select(timeout=0.01):
                _handle(sel, key, mask, None, reissue=False)
            now = time.monotonic()
            for i in list(pending):
                if marker in sims[i].raw:
                    pull_lat.append(now - t_bump)
                    pending.discard(i)
        if pending:
            raise RuntimeError(
                f"pull arm: {len(pending)} workers never saw epoch {e1}")
        for s in sims:
            sel.unregister(s.sock)
            s.sock.close()

        # -- push arm ---------------------------------------------------------
        e1 = int(ctl.status()["epoch"])
        e2 = e1 + 1
        marker = f'"epoch":{e2}'.encode()
        watchers = []
        for i in range(workers):
            sk = socket.create_connection(("127.0.0.1", server.port),
                                          timeout=10.0)
            sk.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sk.settimeout(10.0)
            sk.sendall(_frame({"op": "watch", "worker": f"w{i:05d}",
                               "cursor": e1}))
            ack = b""
            while b"\n" not in ack:
                chunk = sk.recv(4096)
                if not chunk:
                    raise RuntimeError("watch subscribe: connection closed")
                ack += chunk
            if b'"watch":true' not in ack.split(b"\n", 1)[0]:
                raise RuntimeError(f"watch subscribe failed: {ack!r}")
            sk.setblocking(False)
            s = Sim(sk, f"w{i:05d}")
            s.capture = True
            s.raw = ack.split(b"\n", 1)[1]
            sel.register(sk, selectors.EVENT_READ, s)
            watchers.append(s)
        assert int(ctl.bump_epoch()) == e2
        t_bump = time.monotonic()
        push_lat: list = []
        pending = set(range(workers))
        deadline = t_bump + 10.0
        while pending and time.monotonic() < deadline:
            for key, mask in sel.select(timeout=0.01):
                s: Sim = key.data
                try:
                    data = s.sock.recv(1 << 16)
                except (BlockingIOError, InterruptedError):
                    continue
                if not data:
                    raise RuntimeError("watch connection closed mid-wait")
                s.raw += data
            now = time.monotonic()
            for i in list(pending):
                if marker in watchers[i].raw:
                    push_lat.append(now - t_bump)
                    pending.discard(i)
        if pending:
            raise RuntimeError(
                f"push arm: {len(pending)} watchers never got epoch {e2}")
        ctl.close()

        pull, push = quantiles(pull_lat), quantiles(push_lat)
        return {
            "workers": workers,
            "heartbeat_period_s": period,
            "pull": pull,
            "push": push,
            "push_speedup_mean":
            round(pull["mean_ms"] / push["mean_ms"], 1)
            if push["mean_ms"] else None,
            # the acceptance ratio: push p99 as a fraction of the
            # heartbeat period the pull path is bound by
            "push_p99_over_period":
            round(push["p99_ms"] / (period * 1000.0), 4)
            if push["p99_ms"] is not None else None,
        }
    finally:
        for key in list(sel.get_map().values()):
            key.fileobj.close()
        sel.close()
        server.stop()


def main() -> dict:
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < hard:
        resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))

    sections = _env_list("EDL_COORD_SECTIONS",
                         ["arms", "topology", "propagation"])
    ns = [int(x) for x in _env_list("EDL_COORD_NS", [100, 1000, 10000])]
    arms = _env_list("EDL_COORD_ARMS", ["before", "after"])
    modes = _env_list("EDL_COORD_MODES", ["saturated", "duty"])
    secs = _env_float("EDL_COORD_SECS", 4.0)
    warmup = _env_float("EDL_COORD_WARMUP", 0.5)
    wave = int(_env_float("EDL_COORD_WAVE", 128))
    active = int(_env_float("EDL_COORD_ACTIVE", 64))
    shard_ns = [int(x) for x in
                _env_list("EDL_COORD_SHARD_NS", [10000, 50000, 100000])]
    max_conns = int(_env_float("EDL_COORD_MAX_CONNS", 1024))
    kv_bytes = int(_env_float("EDL_COORD_KV_BYTES", 1024))
    prop_workers = int(_env_float("EDL_COORD_PROP_WORKERS", 200))
    prop_period = _env_float("EDL_COORD_PROP_PERIOD", 1.0)

    results: list = []
    topo_results: list = []
    propagation = None
    with tempfile.TemporaryDirectory(prefix="edl-bench-coord-") as tmpdir:
        if "arms" in sections:
            for n in ns:
                for mode in modes:
                    for arm in arms:
                        cell = run_cell(arm, n, mode, secs, warmup, wave,
                                        active, tmpdir)
                        print(json.dumps(cell))
                        results.append(cell)
        if "topology" in sections:
            for n in shard_ns:
                for topology in ("single", "sharded"):
                    cell = run_topology_cell(topology, n, secs, warmup,
                                             max_conns, tmpdir, kv_bytes)
                    print(json.dumps(cell))
                    topo_results.append(cell)
        if "propagation" in sections:
            propagation = run_propagation(prop_workers, prop_period, tmpdir)
            print(json.dumps(propagation))

    by = {(c["arm"], c["n"], c["mode"]): c for c in results}
    crossover = []
    for n in ns:
        for mode in modes:
            b = by.get(("before", n, mode))
            a = by.get(("after", n, mode))
            if not (b and a):
                continue
            crossover.append({
                "n": n, "mode": mode,
                "beats_speedup":
                round(a["beats_per_sec"] / b["beats_per_sec"], 2)
                if b["beats_per_sec"] else None,
                "p99_ratio": round(b["p99_ms"] / a["p99_ms"], 2)
                if b["p99_ms"] and a["p99_ms"] else None,
                "cpu_per_kop_ratio":
                round(b["server_cpu_sec_per_kop"]
                      / a["server_cpu_sec_per_kop"], 2)
                if b["server_cpu_sec_per_kop"] and a["server_cpu_sec_per_kop"]
                else None,
            })

    tby = {(c["topology"], c["n"]): c for c in topo_results}
    topo_crossover = []
    for n in shard_ns:
        s1 = tby.get(("single", n))
        sh = tby.get(("sharded", n))
        if not (s1 and sh):
            continue
        topo_crossover.append({
            "n": n,
            "beats_speedup":
            round(sh["beats_per_sec"] / s1["beats_per_sec"], 2)
            if s1["beats_per_sec"] else None,
            "p99_ratio": round(s1["p99_ms"] / sh["p99_ms"], 2)
            if s1["p99_ms"] and sh["p99_ms"] else None,
            "cpu_per_kop_ratio":
            round(s1["server_cpu_sec_per_kop"]
                  / sh["server_cpu_sec_per_kop"], 2)
            if s1["server_cpu_sec_per_kop"] and sh["server_cpu_sec_per_kop"]
            else None,
        })

    out = {
        "bench": "coordinator_control_plane",
        "config": {"sections": sections, "ns": ns, "arms": arms,
                   "modes": modes, "seconds": secs,
                   "warmup": warmup, "active_workers_duty": active,
                   "shard_ns": shard_ns, "max_conns": max_conns,
                   "kv_bytes": kv_bytes,
                   "propagation_workers": prop_workers,
                   "propagation_period_s": prop_period,
                   "cpus": os.cpu_count(),
                   "note": "bench and server share the host; ratios between "
                           "arms are the meaningful numbers. The before arm "
                           "understates the seed server (lease index + tick "
                           "cache benefit both arms). Topology cells "
                           "multiplex logical workers over a bounded "
                           "connection pool (fd rlimit); on a 1-core host "
                           "the sharded win comes from overlapping journal "
                           "fsync waits and smaller per-shard state, not "
                           "parallel compute."},
        "results": results,
        "crossover": crossover,
        "topology_results": topo_results,
        "topology_crossover": topo_crossover,
        "propagation": propagation,
    }
    path = os.environ.get("EDL_COORD_OUT", os.path.join(REPO, "BENCH_COORD.json"))
    with open(path, "w") as fh:
        json.dump(out, fh, indent=1)
        fh.write("\n")
    print(json.dumps({"wrote": path, "crossover": crossover,
                      "topology_crossover": topo_crossover}))
    return out


def smoke() -> int:
    """<60 s sanity slice for `make verify`: both topologies at N=500 plus
    a fast pull-vs-push propagation pair, written to a throwaway path.
    Returns a nonzero exit code on implausible results; skips (0) when the
    native toolchain is absent."""
    from edl_tpu.coordinator.server import CoordinatorError, ensure_built

    try:
        ensure_built()
    except (CoordinatorError, OSError) as exc:
        print(f"bench-coord smoke: skipped (no native toolchain: {exc})")
        return 0

    os.environ["EDL_COORD_SECTIONS"] = '["topology", "propagation"]'
    os.environ["EDL_COORD_SHARD_NS"] = "[500]"
    os.environ["EDL_COORD_SECS"] = "0.8"
    os.environ["EDL_COORD_WARMUP"] = "0.2"
    os.environ["EDL_COORD_MAX_CONNS"] = "128"
    os.environ["EDL_COORD_PROP_WORKERS"] = "50"
    os.environ["EDL_COORD_PROP_PERIOD"] = "0.5"
    os.environ.setdefault(
        "EDL_COORD_OUT",
        os.path.join(tempfile.gettempdir(), "bench-coord-smoke.json"))
    out = main()

    failures = []
    for cell in out["topology_results"]:
        if cell["beats"] <= 0:
            failures.append(f"{cell['topology']}@{cell['n']}: no beats")
        if cell["ops_per_sec"] <= 0:
            failures.append(f"{cell['topology']}@{cell['n']}: no server ops")
    prop = out["propagation"]
    if not prop:
        failures.append("propagation section missing")
    else:
        if prop["pull"]["discovered"] != prop["workers"]:
            failures.append("pull arm lost workers")
        if prop["push"]["discovered"] != prop["workers"]:
            failures.append("push arm lost watchers")
        # Push must beat the polling cadence by a wide margin even in a
        # smoke slice; mean (not p99) keeps the assertion stable on a
        # loaded 1-core host.
        if prop["push"]["mean_ms"] >= prop["pull"]["mean_ms"]:
            failures.append(
                f"push no faster than pull ({prop['push']['mean_ms']} ms "
                f"vs {prop['pull']['mean_ms']} ms)")
    if failures:
        print(json.dumps({"bench_coord_smoke": "FAIL", "failures": failures}))
        return 1
    print(json.dumps({"bench_coord_smoke": "ok"}))
    return 0


if __name__ == "__main__":
    import sys
    if "--smoke" in sys.argv[1:]:
        raise SystemExit(smoke())
    main()
