"""On-chip flash block-size sweep: find and persist the fastest VMEM tiles.

Sweeps ``block_q`` x ``block_k`` over {128, 256, 512}^2 for each
benchmark shape (fwd+bwd, the training direction), on the LIVE backend
only — interpret mode has no VMEM and its timings are meaningless. The
winners land in two places:

- ``FLASH_SWEEP.json`` — the full grid with per-config ms/step (artifact);
- ``edl_tpu/ops/flash_blocks.json`` — the tuning table the kernel's
  default path consults (`ops/flash_tuning.lookup`); commit both.

Configs whose VMEM demand exceeds the chip fail to lower — recorded as
such and skipped (that's the graceful-fallback evidence, not an error).
Timing within one process on one shape: relative ranking is stable even
on the flaky tunnel because kernels dominate and transfers are constant
across configs (BENCH_NOTES.md noise applies to absolute numbers).

Usage: run by onchip_campaign.py; EDL_SWEEP_SHAPES / EDL_SWEEP_BLOCKS
override the grid.
"""

from __future__ import annotations

import itertools
import json
import os
import statistics
import time

#: (B, S, H, D) — the bench_flash shapes plus the LM-bench attention shape
_DEFAULT_SHAPES = [
    [4, 1024, 8, 64],
    [4, 2048, 8, 64],
    [2, 4096, 8, 64],
    [1, 8192, 8, 128],
]
_DEFAULT_BLOCKS = [128, 256, 512]


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench import probe_or_exit

    devices, init_attempts = probe_or_exit("flash_block_sweep")
    backend = devices[0].platform
    if backend == "cpu" and os.environ.get("EDL_SWEEP_ALLOW_CPU") != "1":
        print(json.dumps({
            "metric": "flash_block_sweep",
            "error": "refusing to tune VMEM tiles in interpret mode on CPU "
                     "(timings meaningless); EDL_SWEEP_ALLOW_CPU=1 to force "
                     "a harness smoke",
        }))
        return

    from edl_tpu.ops import flash_attention, flash_tuning

    shapes = json.loads(os.environ.get("EDL_SWEEP_SHAPES", "null")) \
        or _DEFAULT_SHAPES
    grid = json.loads(os.environ.get("EDL_SWEEP_BLOCKS", "null")) \
        or _DEFAULT_BLOCKS
    steps = max(1, int(os.environ.get("EDL_BENCH_STEPS", "10")))
    reps = max(1, int(os.environ.get("EDL_BENCH_WINDOWS", "3")))

    rng = np.random.default_rng(0)
    records = []
    winners = {}
    for B, S, H, D in shapes:
        q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.bfloat16)
        k = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.bfloat16)
        v = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.bfloat16)
        best = None
        for bq, bk in itertools.product(grid, grid):
            if bq > S or bk > S:
                continue
            rec = {"shape_BSHD": [B, S, H, D], "block_q": bq, "block_k": bk}
            try:
                step = jax.jit(jax.grad(
                    lambda q: jnp.sum(flash_attention(
                        q, k, v, block_q=bq, block_k=bk) ** 2)
                ))
                step(q).block_until_ready()  # compile + lowering check
                times = []
                for _ in range(reps):
                    t0 = time.perf_counter()
                    for _ in range(steps):
                        g = step(q)
                    jax.block_until_ready(g)
                    times.append((time.perf_counter() - t0) / steps)
                ms = 1e3 * statistics.median(times)
                rec["ms_per_step"] = round(ms, 3)
                if best is None or ms < best[0]:
                    best = (ms, bq, bk)
            except Exception as e:  # noqa: BLE001 — VMEM overflow is data
                rec["error"] = str(e)[:300]
            records.append(rec)
            print(json.dumps(rec), flush=True)
        if best is not None:
            key = flash_tuning._key(flash_tuning._bucket(S), D, "bfloat16")
            # keep the better winner if two shapes share a bucket
            if key not in winners or best[0] < winners[key][0]:
                winners[key] = best

    meta = {
        "backend": backend,
        "device_kind": str(getattr(devices[0], "device_kind", "")),
        "steps": steps,
        "reps": reps,
        "note": "fwd+bwd ms/step medians; see FLASH_SWEEP.json for the grid",
    }
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "FLASH_SWEEP.json"), "w") as f:
        json.dump({"metric": "flash_block_sweep", "meta": meta,
                   "grid": records,
                   "winners": {k: {"ms_per_step": round(v[0], 3),
                                   "blocks": [v[1], v[2]]}
                               for k, v in winners.items()}}, f, indent=1)
    if backend != "cpu":
        flash_tuning.save_table(
            {k: (v[1], v[2]) for k, v in winners.items()}, meta
        )
    print(json.dumps({
        "metric": "flash_block_sweep",
        "winners": {k: [v[1], v[2]] for k, v in winners.items()},
        "configs_timed": sum(1 for r in records if "ms_per_step" in r),
        "configs_failed": sum(1 for r in records if "error" in r),
        "table_written": backend != "cpu",
        "init_attempts": init_attempts,
    }))


if __name__ == "__main__":
    main()
