// edl-coordinator: elastic-training coordination service.
//
// TPU-native consolidation of three reference components (SURVEY §2.2):
//   * /usr/bin/master     — fault-tolerant chunked task queue with leases
//                           (-chunk-per-task, -task-timout-dur 16s;
//                           docker/paddle_k8s:26-32)
//   * etcd sidecar        — service discovery / KV / membership
//                           (pkg/jobparser.go:167-184)
//   * /usr/bin/pserver's  — self-registration & peer-count discovery
//     registration role     (docker/paddle_k8s:18-23)
//
// One process, one event loop (epoll on Linux, level-triggered, with a
// poll() fallback — EDL_COORD_FORCE_POLL=1 forces it), zero dependencies.
// Protocol: newline-delimited JSON over TCP. Workers register (-> rank,
// membership epoch), heartbeat (leases expire like etcd TTLs), lease
// data-shard tasks (expired leases requeue: at-least-once, exactly the
// master's semantics), hit named barriers (replacing the reference's
// `sleep 20` + poll loops, docker/paddle_k8s:128-130,178), and read/write
// a small KV namespace (checkpoint metadata, coordinator bootstrap info).
//
// Control-plane scale (bench_coord.py, BENCH_COORD.json): a `batch` op
// carries many sub-ops in one frame with positional per-sub-op replies, so
// a worker's heartbeat+complete_task+kv_put cost one round-trip instead of
// three; every reply is stamped with the current membership epoch, so
// epoch discovery piggybacks on traffic that is happening anyway instead
// of dedicated per-worker status polls; the journal group-commits (one
// fsync per event-loop turn covers every mutation that turn) and lease
// renewal is O(worker's own leases) via a per-worker index, not a scan of
// every lease in the job.
//
// Membership epochs drive elasticity: any join/leave/expiry bumps the epoch;
// trainers see the new epoch on their next heartbeat and enter the
// checkpoint -> rebuild-mesh -> restore rescale path (edl_tpu.runtime.elastic).
//
// Durability: --state-file persists the task queue (todo+leased merged, a
// restart requeues live leases for at-least-once replay), the done-set, the
// KV namespace, and the membership epoch — replacing the reference's
// etcd-sidecar persistence (pkg/jobparser.go:167-184). The file is JSONL:
// a full snapshot plus appended delta records (one per mutation), fsynced
// BEFORE the mutating request is acknowledged, so a client that saw
// complete_task/kv_put succeed can rely on the write surviving kill -9.
// The delta log compacts back into a snapshot when it dwarfs the live state.
// --run-id stamps the file with the job run's identity: a coordinator booted
// with a different run-id discards the file instead of resuming another
// run's done-set (which would silently "complete" a fresh job untrained).
//
// Build: make (or cmake).
// Run: edl-coordinator --port 7164 [--host 0.0.0.0] [--task-lease-sec 16]
//      [--heartbeat-ttl-sec 10] [--state-file /path/state.jsonl]
//      [--run-id ID]

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>
#ifdef __linux__
#include <sys/epoll.h>
#endif

#include <chrono>
#include <deque>
#include <map>
#include <memory>
#include <set>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

double now_sec() {
  using namespace std::chrono;
  return duration<double>(steady_clock::now().time_since_epoch()).count();
}

// ---------------------------------------------------------------------------
// Minimal JSON: enough for flat objects with string / double / bool values
// and arrays of strings. Task payloads and KV values are opaque strings.
// ---------------------------------------------------------------------------

struct JsonValue {
  enum Kind { kNull, kString, kNumber, kBool, kStrArray } kind = kNull;
  std::string str;
  double num = 0;
  bool b = false;
  std::vector<std::string> arr;
};

using JsonObject = std::map<std::string, JsonValue>;

struct JsonParser {
  const char* p;
  const char* end;
  bool ok = true;

  explicit JsonParser(const std::string& s) : p(s.data()), end(s.data() + s.size()) {}

  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\r' || *p == '\n')) p++;
  }
  bool consume(char c) {
    skip_ws();
    if (p < end && *p == c) { p++; return true; }
    return false;
  }
  bool parse_string(std::string* out) {
    skip_ws();
    if (p >= end || *p != '"') return false;
    p++;
    out->clear();
    while (p < end && *p != '"') {
      if (*p == '\\' && p + 1 < end) {
        p++;
        switch (*p) {
          case 'n': out->push_back('\n'); break;
          case 't': out->push_back('\t'); break;
          case 'r': out->push_back('\r'); break;
          case '"': out->push_back('"'); break;
          case '\\': out->push_back('\\'); break;
          case '/': out->push_back('/'); break;
          case 'u': {
            // \uXXXX -> UTF-8 (BMP only; surrogate pairs unsupported —
            // clients send raw UTF-8 for non-ASCII, this path mainly
            // round-trips our own \u00XX control-char escapes).
            if (end - p >= 5) {
              char hex[5] = {p[1], p[2], p[3], p[4], 0};
              unsigned cp = (unsigned)strtoul(hex, nullptr, 16);
              p += 4;
              if (cp < 0x80) {
                out->push_back((char)cp);
              } else if (cp < 0x800) {
                out->push_back((char)(0xC0 | (cp >> 6)));
                out->push_back((char)(0x80 | (cp & 0x3F)));
              } else {
                out->push_back((char)(0xE0 | (cp >> 12)));
                out->push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
                out->push_back((char)(0x80 | (cp & 0x3F)));
              }
            }
            break;
          }
          default: out->push_back(*p); break;
        }
      } else {
        out->push_back(*p);
      }
      p++;
    }
    if (p >= end) return false;
    p++;  // closing quote
    return true;
  }
  bool parse_value(JsonValue* v) {
    skip_ws();
    if (p >= end) return false;
    if (*p == '"') {
      v->kind = JsonValue::kString;
      return parse_string(&v->str);
    }
    if (*p == 't') {
      if (end - p >= 4 && strncmp(p, "true", 4) == 0) { p += 4; v->kind = JsonValue::kBool; v->b = true; return true; }
      return false;
    }
    if (*p == 'f') {
      if (end - p >= 5 && strncmp(p, "false", 5) == 0) { p += 5; v->kind = JsonValue::kBool; v->b = false; return true; }
      return false;
    }
    if (*p == 'n') {
      if (end - p >= 4 && strncmp(p, "null", 4) == 0) { p += 4; v->kind = JsonValue::kNull; return true; }
      return false;
    }
    if (*p == '[') {
      p++;
      v->kind = JsonValue::kStrArray;
      skip_ws();
      if (p < end && *p == ']') { p++; return true; }
      while (true) {
        std::string s;
        if (!parse_string(&s)) return false;
        v->arr.push_back(std::move(s));
        skip_ws();
        if (p < end && *p == ',') { p++; continue; }
        if (p < end && *p == ']') { p++; return true; }
        return false;
      }
    }
    // number
    char* numend = nullptr;
    v->num = strtod(p, &numend);
    if (numend == p) return false;
    v->kind = JsonValue::kNumber;
    p = numend;
    return true;
  }
  bool parse_object(JsonObject* obj) {
    if (!consume('{')) return false;
    skip_ws();
    if (p < end && *p == '}') { p++; return true; }
    while (true) {
      std::string key;
      if (!parse_string(&key)) return false;
      if (!consume(':')) return false;
      JsonValue v;
      if (!parse_value(&v)) return false;
      (*obj)[std::move(key)] = std::move(v);
      skip_ws();
      if (p < end && *p == ',') { p++; continue; }
      if (p < end && *p == '}') { p++; return true; }
      return false;
    }
  }
};

void json_escape(const std::string& in, std::string* out) {
  for (char c : in) {
    switch (c) {
      case '"': *out += "\\\""; break;
      case '\\': *out += "\\\\"; break;
      case '\n': *out += "\\n"; break;
      case '\t': *out += "\\t"; break;
      case '\r': *out += "\\r"; break;
      default:
        if ((unsigned char)c < 0x20) {
          // Strict JSON readers reject raw control chars; payloads are
          // documented as opaque strings, so escape them.
          char tmp[8];
          snprintf(tmp, sizeof tmp, "\\u%04x", c);
          *out += tmp;
        } else {
          out->push_back(c);
        }
        break;
    }
  }
}

class JsonWriter {
 public:
  JsonWriter() { buf_ = "{"; }
  JsonWriter& field(const std::string& k, const std::string& v) {
    key(k); buf_ += '"'; json_escape(v, &buf_); buf_ += '"'; return *this;
  }
  // Without this, a string literal binds to the bool overload (pointer ->
  // bool conversion outranks const char* -> std::string).
  JsonWriter& field(const std::string& k, const char* v) {
    return field(k, std::string(v));
  }
  JsonWriter& field(const std::string& k, double v) {
    key(k);
    char tmp[32];
    if (v == (long long)v) snprintf(tmp, sizeof tmp, "%lld", (long long)v);
    else snprintf(tmp, sizeof tmp, "%.17g", v);
    buf_ += tmp;
    return *this;
  }
  JsonWriter& field(const std::string& k, bool v) {
    key(k); buf_ += v ? "true" : "false"; return *this;
  }
  JsonWriter& field_null(const std::string& k) { key(k); buf_ += "null"; return *this; }
  JsonWriter& field(const std::string& k, const std::vector<std::string>& v) {
    key(k);
    buf_ += '[';
    for (size_t i = 0; i < v.size(); i++) {
      if (i) buf_ += ',';
      buf_ += '"'; json_escape(v[i], &buf_); buf_ += '"';
    }
    buf_ += ']';
    return *this;
  }
  std::string done() { return buf_ + "}\n"; }

 private:
  void key(const std::string& k) {
    if (buf_.size() > 1) buf_ += ',';
    buf_ += '"'; json_escape(k, &buf_); buf_ += "\":";
  }
  std::string buf_;
};

// ---------------------------------------------------------------------------
// Coordinator state
// ---------------------------------------------------------------------------

struct Member {
  int rank = -1;
  double last_heartbeat = 0;
};

struct Lease {
  std::string task;
  std::string worker;
  double deadline = 0;
};

// A pending advance-notice revocation: the scheduler told us this worker's
// capacity dies in notice_s seconds. seq dedups at-least-once frame delivery
// across watch resubscribes (the role epoch plays for epoch frames).
struct Preempt {
  double notice_s = 0;
  std::string reason;
  long long seq = 0;
};

struct BarrierWaiter {
  int fd;
  std::string worker;
};

struct Barrier {
  int want = 0;
  std::set<std::string> arrived;
  std::vector<BarrierWaiter> waiters;
  long long generation = 0;  // completed cycles, for reuse across steps
};

struct Conn {
  int fd = -1;
  std::string inbuf;
  std::string outbuf;
  bool want_write = false;  // registered for writable events (EAGAIN backlog)
};

class Coordinator {
 public:
  Coordinator(double task_lease_sec, double heartbeat_ttl_sec,
              std::string state_file = "", std::string run_id = "",
              std::string auth_token = "")
      : task_lease_sec_(task_lease_sec), heartbeat_ttl_sec_(heartbeat_ttl_sec),
        state_file_(std::move(state_file)), run_id_(std::move(run_id)),
        auth_token_(std::move(auth_token)) {
    // EDL010 crash-injection hooks (env-gated, test-only): the model
    // checker's native-oracle lane arms these to realize a modeled crash
    // point inside the real binary — die after the Nth append frame
    // (optionally tearing the tail first), or inside the Nth snapshot
    // write before its rename. Unset/zero = disabled.
    const char* e;
    if ((e = getenv("EDL_COORD_CRASH_AFTER_APPENDS"))) crash_after_appends_ = atoll(e);
    if ((e = getenv("EDL_COORD_CRASH_TORN"))) crash_torn_ = atoll(e) != 0;
    if ((e = getenv("EDL_COORD_CRASH_IN_SNAPSHOT"))) crash_in_snapshot_ = atoll(e);
    if ((e = getenv("EDL_COORD_COMPACT_EVERY"))) compact_every_override_ = atoll(e);
    if (!state_file_.empty()) load_state();
  }

  // Returns the response line (possibly empty when the reply is deferred,
  // e.g. a barrier waiter parked until the barrier fills).
  std::string handle(const JsonObject& req, int fd);

  // Expire heartbeats and task leases; returns seconds until next deadline.
  // The O(members+leases) scan is deadline-cached: heartbeats only push
  // deadlines FORWARD, so rescanning before the cached earliest deadline
  // cannot find anything expired. Ops that create a NEW (possibly earlier)
  // deadline — a registration or a lease grant — reset the cache.
  double tick();

  // Event-loop turn accounting: ops/turn and fsyncs/turn are the group-
  // commit amortization numbers bench_coord.py reads via op_status.
  void note_turn() { turns_++; }

  // Deferred barrier releases accumulated by handle()/tick(): fd -> line.
  std::vector<std::pair<int, std::string>> take_deferred() {
    auto out = std::move(deferred_);
    deferred_.clear();
    return out;
  }

  void on_disconnect(int fd);

  // Fail fast on a misconfigured state path: with ack-after-durability a
  // never-writable log would hold every reply forever; a pod that cannot
  // persist must crash loudly at boot, not run silently non-durable.
  bool state_writable() {
    if (state_file_.empty()) return true;
    if (!append_fp_) append_fp_ = fopen(state_file_.c_str(), "a");
    return append_fp_ != nullptr;
  }

  // Persist durable state (queue/done/kv/epoch) if anything changed since the
  // last save. Called from the event loop after each batch of requests and
  // BEFORE their replies flush: a client that saw a mutating op succeed can
  // rely on the write having hit disk (ack-after-durability). Returns false
  // while un-durable mutations are still pending — the caller must then hold
  // reply flushes so no ack outruns the disk.
  bool maybe_save_state();

  // Root mode: the ordered shard endpoints ("host:port") the keyspace is
  // hash-partitioned over. Non-empty turns every keyspace op into a
  // redirect (the root keeps membership + routing only).
  void set_shards(std::vector<std::string> endpoints) {
    shard_endpoints_ = std::move(endpoints);
  }
  // Shard mode: this server's slot in the partition, reported via
  // op_shard_map so clients/tools can confirm they dialed the right slice.
  void set_shard_identity(long long index, long long count) {
    shard_index_ = index;
    num_shards_ = count;
  }

 private:
  void load_state();
  bool save_snapshot();
  // Delta records: one JSONL line per mutation, appended + fsynced by
  // maybe_save_state(). Pending lines are retained (and retried) when a
  // write fails, never silently dropped.
  void record(const std::string& line) {
    if (!state_file_.empty()) pending_ += line;
  }
  void record_epoch() {
    record(JsonWriter().field("k", "meta").field("epoch", (double)epoch_)
               .field("run_id", run_id_).done());
  }
  void record_done(const std::string& task) {
    record(JsonWriter().field("k", "done")
               .field("tasks", std::vector<std::string>{task}).done());
  }
  void record_todo(const std::vector<std::string>& tasks) {
    if (!tasks.empty())
      record(JsonWriter().field("k", "todo").field("tasks", tasks).done());
  }
  void record_kv(const std::string& key, const std::string& value) {
    record(JsonWriter().field("k", "kv").field("key", key)
               .field("value", value).done());
  }
  // Lease ownership journal: worker="" clears (requeue). Persisting leases
  // means a coordinator restart preserves who holds what — a live worker
  // reconnecting within the lease TTL keeps its shards, so an outage can
  // never hand a shard that is mid-training to a second worker (the
  // exactly-once half of the chaos criterion). Truly-dead holders still
  // requeue via normal TTL expiry after the restart.
  // The req_id rides the lease record (EDL010): the acquire dedup cache is
  // durable state — an unjournaled cache would hand a retried acquire a
  // SECOND task after a restart, an exactly-once violation across crash.
  void record_lease(const std::string& task, const std::string& worker,
                    const std::string& req_id = "") {
    record(JsonWriter().field("k", "lease").field("task", task)
               .field("worker", worker).field("req_id", req_id).done());
  }
  void record_kv_del(const std::string& key) {
    record(JsonWriter().field("k", "kvdel").field("key", key).done());
  }
  std::string op_register(const JsonObject& req);
  std::string op_heartbeat(const JsonObject& req);
  std::string op_leave(const JsonObject& req);
  std::string op_members();
  std::string op_add_tasks(const JsonObject& req);
  std::string op_acquire_task(const JsonObject& req);
  std::string op_complete_task(const JsonObject& req);
  std::string op_fail_task(const JsonObject& req);
  std::string op_barrier(const JsonObject& req, int fd);
  std::string op_sync(const JsonObject& req, int fd);
  std::string op_kv_put(const JsonObject& req);
  std::string op_kv_get(const JsonObject& req);
  std::string op_kv_del(const JsonObject& req);
  std::string op_kv_incr(const JsonObject& req);
  std::string op_shard_put(const JsonObject& req);
  std::string op_shard_get(const JsonObject& req);
  std::string op_shard_meta(const JsonObject& req);
  std::string op_shard_drop(const JsonObject& req);
  std::string op_bump_epoch();
  std::string op_preempt_notice(const JsonObject& req);
  std::string op_watch(const JsonObject& req, int fd);
  std::string op_watch_cancel(const JsonObject& req, int fd);
  std::string op_shard_map(const JsonObject& req);
  std::string redirect_reply(const std::string& key);
  std::string op_status();
  std::string op_batch(const JsonObject& req, int fd);
  // Post-auth single-op dispatch; shared by handle() and batch sub-ops.
  std::string dispatch(const std::string& op, const JsonObject& req, int fd);
  // Insert ,"epoch":N before the closing brace of a reply line: every
  // reply carries the current membership epoch (coalesced watch-style
  // notification), so workers piggyback epoch discovery on RPCs they were
  // already making instead of issuing dedicated status/epoch polls.
  std::string stamp_epoch(std::string line) {
    if (line.size() < 2 || line[line.size() - 2] != '}') return line;  // deferred
    char tmp[40];
    snprintf(tmp, sizeof tmp, "%s\"epoch\":%lld",
             line.size() >= 3 && line[line.size() - 3] == '{' ? "" : ",", epoch_);
    line.insert(line.size() - 2, tmp);
    return line;
  }

  // Epoch is persisted so monotonicity survives restarts. Every bump also
  // pushes a notification frame to the watch subscribers (the push path —
  // a rescale reaches watchers in one RTT instead of a heartbeat period).
  void bump_epoch() { epoch_++; record_epoch(); notify_watchers(); }
  void notify_watchers();
  void push_notify(int fd, long long e);
  void push_preempt(int fd, const std::string& worker, const Preempt& p);
  // FNV-1a 64-bit over the routing key. The constants are mirrored in
  // edl_tpu/coordinator/sharding.py — both sides MUST agree, or the client
  // routes a key to one shard while the root redirects it to another.
  size_t key_shard(const std::string& key) const {
    unsigned long long h = 1469598103934665603ull;
    for (unsigned char c : key) {
      h ^= (unsigned long long)c;
      h *= 1099511628211ull;
    }
    return shard_endpoints_.empty() ? 0
                                    : (size_t)(h % shard_endpoints_.size());
  }
  // Release all parked sync waiters: ok=true when the epoch rendezvous
  // completed, ok=false (resync) when membership moved underneath them.
  void release_sync(bool ok);
  // A live worker keeps its leases: heartbeats (and sync arrivals) extend
  // its lease deadlines like etcd keepalives, so completion-lag holds
  // (shards completed only after a covering checkpoint) can outlive the
  // lease TTL without healthy runs retraining shards. Expiry then fires
  // only for workers whose HEARTBEAT also stopped — real failures.
  // Requeue every lease held under ``worker``. Callers: member drop
  // (expiry/leave) and TAKEOVER registration — a fresh process claiming a
  // pod name whose dead predecessor's uncovered shards must replay
  // (without it, the successor's heartbeats would renew its predecessor's
  // leases forever and rank 0 deadlocks on leases that are its own). A
  // plain refresh register does NOT come here: a live worker
  // re-registering mid-run keeps the shards it is training. (No
  // durability record: leases are requeued on restart anyway, see the
  // snapshot format note.)
  void requeue_worker_leases(const std::string& worker) {
    auto wit = leases_by_worker_.find(worker);
    if (wit == leases_by_worker_.end()) return;
    std::vector<std::string> back(wit->second.begin(), wit->second.end());
    leases_by_worker_.erase(wit);
    for (auto& t : back) {
      leased_.erase(t);
      todo_.push_back(t);
      todo_set_.insert(t);
      record_lease(t, "");
    }
  }

  // O(this worker's leases) via the per-worker index — renew runs on EVERY
  // heartbeat, so a full leased_ scan here was O(workers x leases) across
  // the job, the first thing bench_coord.py's 10k-worker arm exposed.
  void renew_leases(const std::string& worker) {
    auto wit = leases_by_worker_.find(worker);
    if (wit == leases_by_worker_.end()) return;
    double deadline = now_sec() + task_lease_sec_;
    for (auto& t : wit->second) {
      auto lit = leased_.find(t);
      if (lit != leased_.end()) lit->second.deadline = deadline;
    }
  }
  void lease_index_add(const std::string& worker, const std::string& task) {
    leases_by_worker_[worker].insert(task);
    next_scan_ = 0;  // a fresh lease deadline may precede the cached horizon
  }
  void lease_index_del(const std::string& worker, const std::string& task) {
    auto wit = leases_by_worker_.find(worker);
    if (wit == leases_by_worker_.end()) return;
    wit->second.erase(task);
    if (wit->second.empty()) leases_by_worker_.erase(wit);
  }
  void drop_member(const std::string& name);
  void requeue_expired_leases(double now);
  std::string membership_reply(const std::string& worker, bool ok_rank);

  static std::string get_str(const JsonObject& o, const std::string& k) {
    auto it = o.find(k);
    return (it != o.end() && it->second.kind == JsonValue::kString) ? it->second.str : "";
  }
  static double get_num(const JsonObject& o, const std::string& k, double dflt) {
    auto it = o.find(k);
    return (it != o.end() && it->second.kind == JsonValue::kNumber) ? it->second.num : dflt;
  }

  double task_lease_sec_;
  double heartbeat_ttl_sec_;
  long long epoch_ = 0;
  int next_rank_ = 0;
  std::map<std::string, Member> members_;
  std::deque<std::string> todo_;
  std::set<std::string> todo_set_;  // mirrors todo_ for O(log n) dedup
  std::map<std::string, Lease> leased_;   // task -> lease
  // worker -> tasks it holds: the heartbeat-path index (renew_leases /
  // requeue_worker_leases without scanning every lease in the job).
  std::map<std::string, std::set<std::string>> leases_by_worker_;
  // Last acquire per worker: worker -> (req_id, task). Lets a retried
  // acquire (lost reply) return the same lease instead of a second task.
  std::map<std::string, std::pair<std::string, std::string>> acquire_cache_;
  std::set<std::string> done_;
  std::map<std::string, Barrier> barriers_;
  // Epoch-synchronized rendezvous (the rescale sync point): workers call
  // op_sync with the epoch they observed; released when every current
  // member has arrived at that epoch, or with resync when the epoch moves.
  std::set<std::string> sync_arrived_;
  std::vector<BarrierWaiter> sync_waiters_;
  std::map<std::string, std::string> kv_;
  // Memory-resident checkpoint plane: the latest replicated ZeRO-1 shard
  // per owner worker, chunked. DELIBERATELY not journaled — the plane is a
  // volatile cache of peer state (the blob-store checkpoint stays the
  // durable tier); after a coordinator restart it is simply empty and
  // restores fall back to blob. Member drop does NOT clear an owner's
  // blob: surviving a dead owner is the whole point of the plane.
  struct ShardBlob {
    long long step = -1;
    long long chunks = 0;
    long long nbytes = 0;
    std::vector<std::string> group;          // replica-holder worker names
    std::map<long long, std::string> data;   // chunk index -> payload
  };
  std::map<std::string, ShardBlob> shards_;  // owner -> latest blob
  // put_id dedup (exactly-once under client retry / outbox replay),
  // FIFO-capped so a long run cannot grow the marker set unboundedly.
  std::set<std::string> shard_put_seen_;
  std::deque<std::string> shard_put_order_;
  static const size_t kShardPutSeenCap = 4096;
  std::vector<std::pair<int, std::string>> deferred_;
  // Watch subscriptions: fds that get a push_notify frame on every bump.
  // Connection-scoped (a dead fd is just erased in on_disconnect) — resume
  // across reconnects is the CLIENT's job via the watch cursor.
  std::unordered_set<int> watchers_;
  // fd -> worker name given at subscribe time: lets a revocation notice be
  // pushed only to the doomed worker's watch connections (epoch frames stay
  // broadcast). Connection-scoped like watchers_ itself.
  std::unordered_map<int, std::string> watcher_names_;
  // Pending advance-notice revocations, worker -> live notice. DELIBERATELY
  // volatile (not journaled): a restarted coordinator forgets notices and
  // the scheduler re-issues them — the EDL010 ladder proves the recovery
  // wipe is honest. Cleared when the worker actually departs (drop_member).
  std::map<std::string, Preempt> preempts_;
  long long preempt_seq_ = 0;
  std::vector<std::string> shard_endpoints_;  // root mode: addr per shard slot
  long long shard_index_ = -1;                // shard mode: this server's slot
  long long num_shards_ = 0;
  std::string state_file_;
  std::string run_id_;
  std::string auth_token_;  // empty = auth disabled (loopback-only dev runs)
  FILE* append_fp_ = nullptr;      // state file held open for delta appends
  std::string pending_;            // delta lines not yet durable
  long long appended_records_ = 0; // deltas since the last snapshot
  long long journal_appends_ = 0;  // lifetime delta records (monotonic)
  bool need_snapshot_ = false;     // e.g. run-id mismatch discarded the file
  // EDL010 crash-injection state (see the constructor's env hooks).
  // Counts are 1-based: the Nth matching event dies with _exit(2).
  long long crash_after_appends_ = 0;
  bool crash_torn_ = false;
  long long crash_in_snapshot_ = 0;
  long long compact_every_override_ = 0;  // test threshold: records >= N
  long long appends_done_ = 0;            // committed append frames
  long long snapshot_attempts_ = 0;       // save_snapshot entries
  double next_scan_ = 0;           // earliest time tick() must rescan deadlines
  // Control-plane telemetry (op_status): bench_coord.py derives ops/sec,
  // batch amortization, and journal fsyncs-per-op from deltas of these.
  long long ops_handled_ = 0;      // single ops + batch sub-ops
  long long batch_frames_ = 0;
  long long batch_subops_ = 0;
  long long fsyncs_ = 0;           // group-commit appends + snapshots
  long long snapshots_ = 0;        // compactions (and identity rewrites)
  long long turns_ = 0;            // event-loop wakeups
  double boot_sec_ = now_sec();    // uptime_seconds origin (op_status)
};

// Durable state is JSON-lines so it reuses the wire parser/writer. A file is
// a snapshot prefix plus appended delta records; load replays them in order:
//   {"k":"meta","epoch":N,"run_id":R}
//   {"k":"todo","tasks":[...]}
//   {"k":"done","tasks":[...]}
//   {"k":"lease","task":T,"worker":W}  (W="" clears; last record wins)
//   {"k":"kv","key":K,"value":V}    (one line per entry)
//   {"k":"kvdel","key":K}           (delta only)
bool Coordinator::save_snapshot() {
  if (append_fp_) { fclose(append_fp_); append_fp_ = nullptr; }
  snapshot_attempts_++;
  std::string tmp = state_file_ + ".tmp";
  FILE* f = fopen(tmp.c_str(), "w");
  if (!f) { perror("state-file open"); return false; }
  std::string out;
  out += JsonWriter().field("k", "meta").field("epoch", (double)epoch_)
             .field("run_id", run_id_).done();
  std::vector<std::string> todo(todo_.begin(), todo_.end());
  out += JsonWriter().field("k", "todo").field("tasks", todo).done();
  // Live leases persist WITH their holder: a restarted coordinator grants
  // each lease a fresh TTL, so a worker that rode out the outage keeps its
  // shards (no double-assign) and a dead worker's shards requeue on expiry.
  // The holder's cached acquire req_id rides along (EDL010: dedup tables
  // are durable state), so a retried acquire still answers from the cache
  // after a restart instead of popping a second task.
  for (auto& [task, lease] : leased_) {
    std::string req;
    auto cit = acquire_cache_.find(lease.worker);
    if (cit != acquire_cache_.end() && cit->second.second == task)
      req = cit->second.first;
    out += JsonWriter().field("k", "lease").field("task", task)
               .field("worker", lease.worker).field("req_id", req).done();
  }
  std::vector<std::string> done(done_.begin(), done_.end());
  out += JsonWriter().field("k", "done").field("tasks", done).done();
  for (auto& [key, value] : kv_)
    out += JsonWriter().field("k", "kv").field("key", key).field("value", value).done();
  // The snapshot is one committed frame: close it with the same marker the
  // append path writes, so the tail-commit scan accepts a freshly-compacted
  // file without a legacy-fallback special case.
  out += JsonWriter().field("k", "c").done();
  bool ok = fwrite(out.data(), 1, out.size(), f) == out.size();
  ok = fflush(f) == 0 && ok;
  ok = fsync(fileno(f)) == 0 && ok;
  fclose(f);
  if (!ok) { fprintf(stderr, "state-file write failed\n"); return false; }
  // Crash point: the tmp file is fully written but the rename never runs —
  // recovery must replay the untouched journal and show NONE of the frame
  // that triggered this compaction (it died with the snapshot).
  if (crash_in_snapshot_ > 0 && snapshot_attempts_ >= crash_in_snapshot_)
    _exit(2);
  if (rename(tmp.c_str(), state_file_.c_str()) != 0) {
    perror("state-file rename");
    return false;
  }
  appended_records_ = 0;
  fsyncs_++;
  snapshots_++;
  return true;
}

void Coordinator::load_state() {
  FILE* f = fopen(state_file_.c_str(), "r");
  if (!f) {
    // First boot of this run: stamp the (empty) log with our identity so a
    // restart can tell whose state it is resuming.
    record_epoch();
    return;
  }
  std::string content;
  char buf[65536];
  size_t n;
  while ((n = fread(buf, 1, sizeof buf, f)) > 0) content.append(buf, n);
  fclose(f);
  // Tail-commit scan (EDL010): frames are closed by {"k":"c"} marker
  // lines; only the prefix up to the LAST marker is durable. Everything
  // after it is a torn frame (power died mid-write) and is dropped WHOLE —
  // all-or-nothing is the frame contract; replaying a frame's first
  // records without its last (e.g. a kv_incr value without its op_id
  // marker) silently double-applies on retry. The torn bytes are also
  // truncated off disk so the next append cannot concatenate onto a
  // half-written line. Files from the pre-marker format (no "c" records
  // at all) are taken whole — legacy fallback.
  {
    size_t committed_end = 0;
    bool has_marker = false;
    size_t p = 0;
    while (p < content.size()) {
      size_t nl = content.find('\n', p);
      if (nl == std::string::npos) nl = content.size();
      std::string line = content.substr(p, nl - p);
      size_t end = nl < content.size() ? nl + 1 : nl;
      if (!line.empty()) {
        JsonObject obj;
        JsonParser parser(line);
        if (parser.parse_object(&obj) && get_str(obj, "k") == "c") {
          has_marker = true;
          committed_end = end;
        }
      }
      p = end;
    }
    if (has_marker && committed_end < content.size()) {
      fprintf(stderr,
              "edl-coordinator: state file %s has a torn tail frame "
              "(%zu uncommitted byte(s)); truncating\n",
              state_file_.c_str(), content.size() - committed_end);
      if (truncate(state_file_.c_str(), (off_t)committed_end) != 0)
        perror("state-file torn-tail truncate");
      content.resize(committed_end);
    }
  }
  // Two-phase replay: deltas mean a task can appear in a "todo" line and a
  // later "done" line — collect everything first, then rebuild the queue
  // excluding completed work.
  std::vector<std::string> todo_order;
  std::set<std::string> todo_seen;
  std::map<std::string, std::string> lease_of;  // last lease record wins
  std::string file_run_id;
  long long file_epoch = 0;
  long long file_records = 0;
  int restored_kv = 0;
  size_t pos = 0;
  while (pos < content.size()) {
    size_t nl = content.find('\n', pos);
    if (nl == std::string::npos) nl = content.size();
    std::string line = content.substr(pos, nl - pos);
    pos = nl + 1;
    if (line.empty()) continue;
    file_records++;
    JsonObject obj;
    JsonParser parser(line);
    if (!parser.parse_object(&obj)) continue;
    std::string kind = get_str(obj, "k");
    if (kind == "meta") {
      file_epoch = std::max(file_epoch, (long long)get_num(obj, "epoch", 0));
      std::string rid = get_str(obj, "run_id");
      if (!rid.empty()) file_run_id = rid;
    } else if (kind == "todo" || kind == "done") {
      auto it = obj.find("tasks");
      if (it == obj.end() || it->second.kind != JsonValue::kStrArray) continue;
      for (auto& t : it->second.arr) {
        if (kind == "done") {
          done_.insert(t);
        } else if (todo_seen.insert(t).second) {
          todo_order.push_back(t);
        }
      }
    } else if (kind == "lease") {
      std::string t = get_str(obj, "task");
      if (!t.empty()) {
        std::string w = get_str(obj, "worker");
        std::string req = get_str(obj, "req_id");
        lease_of[t] = w;
        // A lease implies the task exists even if its todo line predates
        // this file's snapshot horizon.
        if (todo_seen.insert(t).second) todo_order.push_back(t);
        // Rebuild the acquire dedup cache (EDL010): the req_id journaled
        // with the grant survives restart, so a client retrying a lost
        // acquire reply still gets its ORIGINAL lease back, not a second
        // task. Last record wins, matching the live cache's semantics.
        if (!w.empty() && !req.empty()) acquire_cache_[w] = {req, t};
      }
    } else if (kind == "kv") {
      kv_[get_str(obj, "key")] = get_str(obj, "value");
      restored_kv++;
    } else if (kind == "kvdel") {
      kv_.erase(get_str(obj, "key"));
    }
  }
  // Run identity check: resuming ANOTHER run's file would restore its
  // done-set and silently "complete" this run having trained nothing. An
  // un-stamped file is equally unidentifiable — discard that too. The epoch
  // is kept monotonic either way so stale clients can never see it move
  // backwards.
  if (!run_id_.empty() && file_run_id != run_id_) {
    fprintf(stderr,
            "edl-coordinator: state file %s belongs to run '%s' (this is run "
            "'%s'); discarding its queue/done/kv\n",
            state_file_.c_str(), file_run_id.c_str(), run_id_.c_str());
    done_.clear();
    kv_.clear();
    acquire_cache_.clear();
    epoch_ = file_epoch + 1;
    need_snapshot_ = true;  // rewrite the file under our identity
    return;
  }
  double lease_deadline = now_sec() + task_lease_sec_;
  for (auto& t : todo_order) {
    if (done_.count(t)) continue;
    auto lit = lease_of.find(t);
    if (lit != lease_of.end() && !lit->second.empty()) {
      // Restore the lease under its holder with a fresh TTL: the worker
      // reconnects (register/heartbeat renews) or expiry requeues it.
      leased_[t] = Lease{t, lit->second, lease_deadline};
      lease_index_add(lit->second, t);
    } else {
      todo_.push_back(t);
      todo_set_.insert(t);
    }
  }
  // A restart IS a membership event (every registration is gone): bump the
  // epoch so reconnecting workers observe the move and re-rendezvous rather
  // than trusting pre-restart ranks.
  epoch_ = file_epoch + 1;
  record_epoch();
  // Seed the compaction counter from the replayed history: a counter that
  // restarted at 0 every boot would let a periodically-restarting
  // coordinator grow the log ~one compaction window per incarnation, forever
  // (O(total mutations ever) disk + parse time).
  appended_records_ = file_records;
  fprintf(stderr,
          "edl-coordinator restored state: epoch=%lld todo=%zu leased=%zu "
          "done=%zu kv=%d\n",
          epoch_, todo_.size(), leased_.size(), done_.size(), restored_kv);
}

bool Coordinator::maybe_save_state() {
  if (state_file_.empty()) return true;
  if (need_snapshot_) {
    if (!save_snapshot()) return false;  // retried next iteration; pending_ kept
    need_snapshot_ = false;
    pending_.clear();  // snapshot already contains everything pending said
    return true;
  }
  if (pending_.empty()) return true;
  // Compact once the delta log dwarfs a fresh snapshot: O(live state) rewrite
  // amortized over >= as many mutations, instead of the old O(dataset)
  // full rewrite on EVERY dirty event-loop iteration.
  long long base = (long long)(todo_.size() + leased_.size() + done_.size() +
                               kv_.size()) + 1;
  bool want_compact = appended_records_ > 1024 && appended_records_ > 2 * base;
  // Test override (EDL010): a fixed low threshold so crash-during-
  // compaction schedules reach the snapshot path in a handful of ops.
  if (compact_every_override_ > 0)
    want_compact = appended_records_ >= compact_every_override_;
  if (want_compact) {
    if (save_snapshot()) {
      pending_.clear();
      return true;
    }
    // Snapshot failed: fall through and keep appending — durability first.
  }
  if (!append_fp_) {
    append_fp_ = fopen(state_file_.c_str(), "a");
    if (!append_fp_) { perror("state-file append open"); return false; }  // retry
  }
  // Close the frame with its commit marker: recovery replays a frame
  // all-or-nothing — records after the last marker are a torn tail and
  // are truncated away by load_state()'s tail-commit scan.
  std::string frame = pending_;
  frame += JsonWriter().field("k", "c").done();
  long long nrec = 0;
  for (char c : frame) nrec += (c == '\n');
  fseeko(append_fp_, 0, SEEK_END);
  off_t pre_append = ftello(append_fp_);  // rollback point for partial writes
  bool ok = fwrite(frame.data(), 1, frame.size(), append_fp_) == frame.size();
  ok = fflush(append_fp_) == 0 && ok;
  // Group commit: ONE fsync covers every mutation this event-loop turn
  // accumulated into pending_ — with N concurrent clients the per-op fsync
  // cost is 1/N'th of a synchronous journal's, which is what keeps
  // fsyncs/sec sublinear in worker count (BENCH_COORD.json).
  ok = fsync(fileno(append_fp_)) == 0 && ok;
  if (!ok) {
    // Keep pending_ — the deltas stay queued until a write succeeds, so a
    // transient failure cannot silently drop acknowledged-later mutations.
    // A failed fwrite/fflush may have left a PARTIAL line on disk; truncate
    // back to the pre-append offset, otherwise the retry would concatenate
    // the fragment with a fresh copy of the same record into one garbage
    // line that load_state() would silently skip.
    fprintf(stderr, "state-file append failed (will retry)\n");
    fclose(append_fp_);
    append_fp_ = nullptr;
    if (pre_append >= 0 && truncate(state_file_.c_str(), pre_append) != 0)
      perror("state-file truncate");
    return false;
  }
  appended_records_ += nrec;
  journal_appends_ += nrec;
  fsyncs_++;
  pending_.clear();
  appends_done_++;
  if (crash_after_appends_ > 0 && appends_done_ >= crash_after_appends_) {
    // Crash point (EDL010): the frame IS durable (fsync returned), the
    // reply never flushes. Torn mode first rewinds the file to mid-frame —
    // the commit marker and half of the final data record gone — the
    // on-disk shape of power dying inside the write instead of after it.
    if (crash_torn_) {
      size_t marker_len = JsonWriter().field("k", "c").done().size();
      size_t data_len = frame.size() - marker_len;
      if (data_len > 0) {
        size_t prev_nl = data_len >= 2 ? frame.rfind('\n', data_len - 2)
                                       : std::string::npos;
        size_t last_start = prev_nl == std::string::npos ? 0 : prev_nl + 1;
        size_t cut = last_start + (data_len - last_start) / 2;
        fclose(append_fp_);
        append_fp_ = nullptr;
        if (truncate(state_file_.c_str(), pre_append + (off_t)cut) != 0)
          perror("state-file tear");
      }
    }
    _exit(2);
  }
  return true;
}

void Coordinator::release_sync(bool ok) {
  if (sync_waiters_.empty() && sync_arrived_.empty()) return;
  JsonWriter w;
  w.field("ok", ok);
  if (!ok) w.field("resync", true);
  w.field("epoch", (double)epoch_);
  w.field("world", (double)members_.size());
  std::string line = w.done();
  for (auto& waiter : sync_waiters_) deferred_.push_back({waiter.fd, line});
  sync_waiters_.clear();
  sync_arrived_.clear();
}

// Push-path notification frame (op "watch"): pushed to every subscribed fd
// the moment the membership epoch moves, and replayed once per missed epoch
// when a subscription resumes with a cursor. "cursor" mirrors "epoch" so a
// client can persist it verbatim as its resume point. Rides the deferred_
// queue like barrier releases, so notifications observe the
// durability-before-flush ordering.
void Coordinator::push_notify(int fd, long long e) {
  deferred_.push_back({fd, JsonWriter().field("ok", true)
      .field("notify", "epoch").field("epoch", (double)e)
      .field("cursor", (double)e)
      .field("world", (double)members_.size()).done()});
}

// Push path: one frame per watcher the moment the epoch moves (the pull
// path discovers the same bump a heartbeat period later).
void Coordinator::notify_watchers() {
  for (int fd : watchers_) push_notify(fd, epoch_);
}

// Targeted push (op "preempt_notice"): unlike epoch frames, a revocation
// notice goes only to the doomed worker's watch connections. Frames carry
// no wall clock — the client anchors the drain deadline to its own
// monotonic arrival time plus notice_s, so clock skew between scheduler,
// coordinator, and worker never shortens the budget.
void Coordinator::push_preempt(int fd, const std::string& worker,
                               const Preempt& p) {
  deferred_.push_back({fd, JsonWriter().field("ok", true)
      .field("notify", "preempt").field("worker", worker)
      .field("notice_s", p.notice_s).field("reason", p.reason)
      .field("seq", (double)p.seq).field("epoch", (double)epoch_)
      .field("cursor", (double)epoch_)
      .field("world", (double)members_.size()).done()});
}

// Root shard routing: the root owns membership only, so a keyspace op is
// answered with the owning shard's endpoint + slot instead of being served.
// Clients cache the shard map and re-resolve when they see this reply.
std::string Coordinator::redirect_reply(const std::string& key) {
  size_t idx = key_shard(key);
  return JsonWriter().field("ok", false).field("error", "wrong shard")
      .field("redirect", shard_endpoints_[idx])
      .field("shard", (double)idx).done();
}

void Coordinator::drop_member(const std::string& name) {
  if (members_.erase(name)) {
    // Re-rank compactly: ranks are 0..N-1 in registration order of survivors
    // (the reference recomputed ranks from the sorted live-pod list,
    // docker/k8s_tools.py:127-151 — same effect: dense, stable order).
    std::map<int, std::string> by_rank;
    for (auto& [n, m] : members_) by_rank[m.rank] = n;
    int r = 0;
    for (auto& [_, n] : by_rank) members_[n].rank = r++;
    next_rank_ = r;
    bump_epoch();
    // Requeue this worker's leases immediately: a departed trainer's chunk
    // goes back to the queue (master semantics on task timeout).
    requeue_worker_leases(name);
    acquire_cache_.erase(name);
    // The departure a notice predicted has happened: the revocation is
    // consumed (a re-registered successor under this name is fresh capacity).
    preempts_.erase(name);
    release_sync(false);
  }
}

void Coordinator::requeue_expired_leases(double now) {
  std::vector<std::pair<std::string, std::string>> back;  // task, worker
  for (auto& [task, lease] : leased_)
    if (lease.deadline <= now) back.push_back({task, lease.worker});
  for (auto& [t, w] : back) {
    lease_index_del(w, t);
    leased_.erase(t);
    todo_.push_back(t);
    todo_set_.insert(t);
    record_lease(t, "");
  }
}

double Coordinator::tick() {
  double now = now_sec();
  // Deadline cache: heartbeats/renewals only move deadlines FORWARD, so
  // until the cached earliest deadline nothing can have expired and the
  // O(members+leases) scan below is pure overhead — at 10k workers it was
  // the dominant per-turn cost (every wakeup walked every member and every
  // lease). Registration and lease grants reset next_scan_ because they
  // introduce deadlines the cache has not seen.
  if (now < next_scan_) return next_scan_ - now;
  // Heartbeat expiry -> membership change -> epoch bump.
  std::vector<std::string> dead;
  for (auto& [name, m] : members_)
    if (m.last_heartbeat + heartbeat_ttl_sec_ <= now) dead.push_back(name);
  for (auto& name : dead) drop_member(name);
  requeue_expired_leases(now);

  double next = 60.0;
  for (auto& [_, m] : members_)
    next = std::min(next, m.last_heartbeat + heartbeat_ttl_sec_ - now);
  for (auto& [_, l] : leased_) next = std::min(next, l.deadline - now);
  next = std::max(0.05, next);
  next_scan_ = now + next;
  return next;
}

// No explicit epoch field: handle()/op_batch() stamp every reply with it.
std::string Coordinator::membership_reply(const std::string& worker, bool ok) {
  JsonWriter w;
  w.field("ok", ok);
  auto it = members_.find(worker);
  w.field("rank", it != members_.end() ? (double)it->second.rank : -1.0);
  w.field("world", (double)members_.size());
  return w.done();
}

std::string Coordinator::op_register(const JsonObject& req) {
  std::string worker = get_str(req, "worker");
  if (worker.empty()) return JsonWriter().field("ok", false).field("error", "worker required").done();
  if (get_num(req, "takeover", 0) != 0) {
    // Incarnation boundary (a fresh process claiming this name): the
    // predecessor's uncovered shards must replay.
    requeue_worker_leases(worker);
  }
  auto it = members_.find(worker);
  if (it == members_.end()) {
    members_[worker] = Member{next_rank_++, now_sec()};
    next_scan_ = 0;  // new TTL deadline behind the tick() cache horizon
    bump_epoch();
    release_sync(false);
  } else {
    it->second.last_heartbeat = now_sec();  // re-register == refresh
    renew_leases(worker);
  }
  return membership_reply(worker, true);
}

std::string Coordinator::op_heartbeat(const JsonObject& req) {
  std::string worker = get_str(req, "worker");
  auto it = members_.find(worker);
  if (it == members_.end())
    return JsonWriter().field("ok", false).field("error", "unknown worker").done();
  it->second.last_heartbeat = now_sec();
  renew_leases(worker);
  return membership_reply(worker, true);
}

std::string Coordinator::op_leave(const JsonObject& req) {
  std::string worker = get_str(req, "worker");
  drop_member(worker);
  return JsonWriter().field("ok", true).done();
}

std::string Coordinator::op_members() {
  std::map<int, std::string> by_rank;
  for (auto& [n, m] : members_) by_rank[m.rank] = n;
  std::vector<std::string> names;
  for (auto& [_, n] : by_rank) names.push_back(n);
  return JsonWriter().field("ok", true).field("members", names).done();
}

std::string Coordinator::op_add_tasks(const JsonObject& req) {
  if (!shard_endpoints_.empty()) {
    // Roots don't own the task space. The client partitions tasks by hash
    // before sending, so redirecting by the first task is exact for
    // well-routed frames and still points a naive client at a real shard.
    auto rit = req.find("tasks");
    std::string first;
    if (rit != req.end() && rit->second.kind == JsonValue::kStrArray &&
        !rit->second.arr.empty())
      first = rit->second.arr[0];
    return redirect_reply(first);
  }
  auto it = req.find("tasks");
  if (it == req.end() || it->second.kind != JsonValue::kStrArray)
    return JsonWriter().field("ok", false).field("error", "tasks array required").done();
  int added = 0;
  std::vector<std::string> fresh;
  for (auto& t : it->second.arr) {
    if (done_.count(t) || leased_.count(t) || todo_set_.count(t)) continue;
    todo_.push_back(t);
    todo_set_.insert(t);
    fresh.push_back(t);
    added++;
  }
  record_todo(fresh);
  return JsonWriter().field("ok", true).field("added", (double)added)
      .field("queued", (double)todo_.size()).done();
}

std::string Coordinator::op_acquire_task(const JsonObject& req) {
  std::string worker = get_str(req, "worker");
  std::string req_id = get_str(req, "req_id");
  // Root mode: leases live on the shards (tasks are hash-partitioned by
  // name). Redirect by worker hash — a stable starting slot; the client
  // rotates across all shards until one has work.
  if (!shard_endpoints_.empty()) return redirect_reply(worker);
  // Dedup: a client that lost the reply retries the SAME logical acquire
  // (same req_id). Without this, the retry would pop a second task while
  // the first sits leased forever — renewed by every heartbeat, never
  // trained, so the queue never drains. Answer from the cache as long as
  // the cached task is still this worker's lease.
  if (!req_id.empty()) {
    auto cit = acquire_cache_.find(worker);
    if (cit != acquire_cache_.end() && cit->second.first == req_id) {
      auto lit = leased_.find(cit->second.second);
      if (lit != leased_.end() && lit->second.worker == worker) {
        lit->second.deadline = now_sec() + task_lease_sec_;
        return JsonWriter().field("ok", true).field("task", cit->second.second)
            .field("lease_sec", task_lease_sec_).field("duplicate", true).done();
      }
    }
  }
  if (todo_.empty()) {
    bool all_done = leased_.empty();
    return JsonWriter().field("ok", true).field_null("task")
        .field("exhausted", all_done).done();
  }
  std::string task = todo_.front();
  todo_.pop_front();
  todo_set_.erase(task);
  leased_[task] = Lease{task, worker, now_sec() + task_lease_sec_};
  lease_index_add(worker, task);
  record_lease(task, worker, req_id);
  if (!req_id.empty()) acquire_cache_[worker] = {req_id, task};
  return JsonWriter().field("ok", true).field("task", task)
      .field("lease_sec", task_lease_sec_).done();
}

std::string Coordinator::op_complete_task(const JsonObject& req) {
  std::string task = get_str(req, "task");
  std::string worker = get_str(req, "worker");
  if (!shard_endpoints_.empty()) return redirect_reply(task);
  // Idempotent: outbox replay after a reconnect (or a retry whose first
  // send did land) re-delivers completions. Already-done is success, not
  // an error — anything else forces callers to special-case replays.
  if (done_.count(task))
    return JsonWriter().field("ok", true).field("duplicate", true)
        .field("done", (double)done_.size())
        .field("queued", (double)todo_.size()).done();
  auto it = leased_.find(task);
  if (it == leased_.end()) {
    // Requeued-but-unleased (lease expired during an outage, or a restart
    // pushed live leases back to todo): the completing worker trained the
    // shard and has a durable covering checkpoint — that is the only
    // reason workers ever call complete — so accepting here prevents a
    // pointless second training pass. A task this run has never heard of
    // is still an error.
    if (todo_set_.count(task)) {
      todo_set_.erase(task);
      for (auto dit = todo_.begin(); dit != todo_.end(); ++dit)
        if (*dit == task) { todo_.erase(dit); break; }
      done_.insert(task);
      record_done(task);
      return JsonWriter().field("ok", true).field("requeued", true)
          .field("done", (double)done_.size())
          .field("queued", (double)todo_.size()).done();
    }
    return JsonWriter().field("ok", false).field("error", "not leased").done();
  }
  // A stale worker (lease expired, task re-leased elsewhere) must not be able
  // to complete another worker's lease out from under it.
  if (it->second.worker != worker)
    return JsonWriter().field("ok", false).field("error", "lease not owned").done();
  lease_index_del(it->second.worker, task);
  leased_.erase(it);
  done_.insert(task);
  record_done(task);
  return JsonWriter().field("ok", true).field("done", (double)done_.size())
      .field("queued", (double)todo_.size()).done();
}

std::string Coordinator::op_fail_task(const JsonObject& req) {
  std::string task = get_str(req, "task");
  std::string worker = get_str(req, "worker");
  if (!shard_endpoints_.empty()) return redirect_reply(task);
  auto it = leased_.find(task);
  if (it == leased_.end())
    return JsonWriter().field("ok", false).field("error", "not leased").done();
  if (it->second.worker != worker)
    return JsonWriter().field("ok", false).field("error", "lease not owned").done();
  lease_index_del(it->second.worker, task);
  leased_.erase(it);
  todo_.push_back(task);
  todo_set_.insert(task);
  record_lease(task, "");
  return JsonWriter().field("ok", true).done();
}

std::string Coordinator::op_barrier(const JsonObject& req, int fd) {
  std::string name = get_str(req, "name");
  std::string worker = get_str(req, "worker");
  int want = (int)get_num(req, "count", 0);
  if (name.empty() || want <= 0)
    return JsonWriter().field("ok", false).field("error", "name+count required").done();
  Barrier& b = barriers_[name];
  if (b.arrived.empty()) {
    // First arrival of a cycle fixes the count; later arrivals must agree.
    // Last-writer-wins here would let two cohorts sharing a barrier name
    // with different counts release each other incorrectly.
    b.want = want;
  } else if (want != b.want) {
    return JsonWriter().field("ok", false)
        .field("error", "barrier count mismatch")
        .field("want", (double)b.want).done();
  }
  b.arrived.insert(worker);
  b.waiters.push_back(BarrierWaiter{fd, worker});
  if ((int)b.arrived.size() >= b.want) {
    // Deferred lines bypass handle()'s stamping: carry the epoch here too
    // so barrier returns also double as coalesced epoch observations.
    std::string line = JsonWriter().field("ok", true).field("barrier", name)
        .field("generation", (double)b.generation)
        .field("epoch", (double)epoch_).done();
    for (auto& waiter : b.waiters) deferred_.push_back({waiter.fd, line});
    b.generation++;
    b.arrived.clear();
    b.waiters.clear();
    return "";  // this fd's reply is in deferred_ too
  }
  return "";  // parked
}

std::string Coordinator::op_sync(const JsonObject& req, int fd) {
  std::string worker = get_str(req, "worker");
  long long epoch = (long long)get_num(req, "epoch", -1);
  auto it = members_.find(worker);
  if (it == members_.end())
    return JsonWriter().field("ok", false).field("error", "unknown worker")
        .field("world", (double)members_.size()).done();
  it->second.last_heartbeat = now_sec();  // arrival refreshes the TTL
  renew_leases(worker);
  if (epoch != epoch_)
    return JsonWriter().field("ok", false).field("resync", true)
        .field("world", (double)members_.size()).done();
  sync_arrived_.insert(worker);
  sync_waiters_.push_back(BarrierWaiter{fd, worker});
  bool all = true;
  for (auto& [name, m] : members_)
    if (!sync_arrived_.count(name)) { all = false; break; }
  if (all) release_sync(true);
  return "";  // reply delivered via deferred_ when released
}

std::string Coordinator::op_kv_put(const JsonObject& req) {
  std::string key = get_str(req, "key");
  if (!shard_endpoints_.empty()) return redirect_reply(key);
  if (key.empty()) return JsonWriter().field("ok", false).field("error", "key required").done();
  kv_[key] = get_str(req, "value");
  record_kv(key, kv_[key]);
  return JsonWriter().field("ok", true).done();
}

std::string Coordinator::op_kv_get(const JsonObject& req) {
  if (!shard_endpoints_.empty()) return redirect_reply(get_str(req, "key"));
  auto it = kv_.find(get_str(req, "key"));
  JsonWriter w;
  w.field("ok", true);
  if (it == kv_.end()) w.field_null("value");
  else w.field("value", it->second);
  return w.done();
}

std::string Coordinator::op_kv_del(const JsonObject& req) {
  std::string del_key = get_str(req, "key");
  if (!shard_endpoints_.empty()) return redirect_reply(del_key);
  if (kv_.erase(del_key)) record_kv_del(del_key);
  return JsonWriter().field("ok", true).done();
}

std::string Coordinator::op_kv_incr(const JsonObject& req) {
  // Atomic counter: read-modify-write under the server's single-threaded
  // event loop, so concurrent clients (e.g. trainers bumping the job-wide
  // failure count) can never lose increments the way kv_get+kv_put can.
  std::string key = get_str(req, "key");
  if (!shard_endpoints_.empty()) return redirect_reply(key);
  if (key.empty()) return JsonWriter().field("ok", false).field("error", "key required").done();
  long long delta = (long long)get_num(req, "delta", 1.0);
  // Exactly-once under retries AND restarts: an op_id marker is persisted
  // through the same KV journal as the counter itself, so a replayed
  // increment (client retry after a lost reply, outbox replay after the
  // coordinator came back) returns the originally-recorded value instead
  // of double-counting — failure budgets stay honest across outages.
  std::string op_id = get_str(req, "op_id");
  std::string marker = op_id.empty() ? "" : "__edl_op/" + op_id;
  if (!marker.empty()) {
    auto mit = kv_.find(marker);
    if (mit != kv_.end()) {
      long long seen = 0;
      try { seen = std::stoll(mit->second); } catch (...) { seen = 0; }
      return JsonWriter().field("ok", true).field("value", (double)seen)
          .field("duplicate", true).done();
    }
  }
  long long cur = 0;
  auto it = kv_.find(key);
  if (it != kv_.end()) {
    try { cur = std::stoll(it->second); } catch (...) {
      return JsonWriter().field("ok", false).field("error", "value not an integer").done();
    }
  }
  cur += delta;
  kv_[key] = std::to_string(cur);
  record_kv(key, kv_[key]);
  if (!marker.empty()) {
    kv_[marker] = std::to_string(cur);
    record_kv(marker, kv_[marker]);
  }
  return JsonWriter().field("ok", true).field("value", (double)cur).done();
}

std::string Coordinator::op_shard_put(const JsonObject& req) {
  // Checkpoint-plane replication: a worker pushes one chunk of its ZeRO-1
  // optimizer-state shard into the memory-resident plane. step supersedes:
  // the plane keeps only the latest replicated step per owner (a restore
  // wants the freshest covered state; history lives in blob storage).
  std::string owner = get_str(req, "owner");
  if (!shard_endpoints_.empty()) return redirect_reply(owner);
  long long step = (long long)get_num(req, "step", -1);
  long long chunk = (long long)get_num(req, "chunk", -1);
  long long chunks = (long long)get_num(req, "chunks", 0);
  if (owner.empty() || step < 0 || chunks < 1 || chunk < 0 || chunk >= chunks)
    return JsonWriter().field("ok", false)
        .field("error", "shard_put requires owner, step>=0, 0<=chunk<chunks")
        .done();
  // Exactly-once under retries: a replayed put (lost reply, outbox replay)
  // acks without re-applying — same contract as acquire req_id / kv_incr
  // op_id. Marked seen only after a successful apply, so duplicate implies
  // the original chunk landed.
  std::string put_id = get_str(req, "put_id");
  if (!put_id.empty() && shard_put_seen_.count(put_id))
    return JsonWriter().field("ok", true).field("duplicate", true)
        .field("stored", true).done();
  auto& blob = shards_[owner];
  if (step < blob.step) {
    // Stale chunk racing a newer replication pass: not an error (the
    // replicator keeps going), just not stored.
    return JsonWriter().field("ok", true).field("duplicate", false)
        .field("stored", false).done();
  }
  if (step > blob.step) {
    blob.step = step;
    blob.data.clear();
    blob.group.clear();
  }
  blob.chunks = chunks;
  blob.nbytes = (long long)get_num(req, "nbytes", 0);
  auto git = req.find("group");
  if (git != req.end() && git->second.kind == JsonValue::kStrArray)
    blob.group = git->second.arr;
  blob.data[chunk] = get_str(req, "data");
  if (!put_id.empty()) {
    shard_put_seen_.insert(put_id);
    shard_put_order_.push_back(put_id);
    if (shard_put_order_.size() > kShardPutSeenCap) {
      shard_put_seen_.erase(shard_put_order_.front());
      shard_put_order_.pop_front();
    }
  }
  return JsonWriter().field("ok", true).field("duplicate", false)
      .field("stored", true).done();
}

std::string Coordinator::op_shard_get(const JsonObject& req) {
  // Recovery path: fetch one chunk of a (possibly dead) owner's replicated
  // shard. step<0 means "latest"; a specific step must match exactly, so a
  // restorer never silently mixes chunks from two replication passes.
  std::string owner = get_str(req, "owner");
  if (!shard_endpoints_.empty()) return redirect_reply(owner);
  long long step = (long long)get_num(req, "step", -1);
  long long chunk = (long long)get_num(req, "chunk", 0);
  auto it = shards_.find(owner);
  if (it == shards_.end() || (step >= 0 && it->second.step != step))
    return JsonWriter().field("ok", true).field("found", false)
        .field("data", std::string()).field("chunks", (double)0).done();
  auto cit = it->second.data.find(chunk);
  if (cit == it->second.data.end())
    return JsonWriter().field("ok", true).field("found", false)
        .field("data", std::string()).field("chunks", (double)it->second.chunks)
        .done();
  return JsonWriter().field("ok", true).field("found", true)
      .field("data", cit->second).field("chunks", (double)it->second.chunks)
      .done();
}

std::string Coordinator::op_shard_meta(const JsonObject& req) {
  // What does the plane hold for this owner? complete=true only when every
  // chunk of the latest step is present — the restorer's go/no-go signal
  // before it starts pulling chunks (partial replication = blob fallback).
  std::string owner = get_str(req, "owner");
  if (!shard_endpoints_.empty()) return redirect_reply(owner);
  auto it = shards_.find(owner);
  if (it == shards_.end() || it->second.step < 0)
    return JsonWriter().field("ok", true).field("found", false)
        .field("step", (double)-1).field("chunks", (double)0)
        .field("nbytes", (double)0).field("complete", false)
        .field("group", std::vector<std::string>{}).done();
  const ShardBlob& b = it->second;
  bool complete = b.chunks > 0 && (long long)b.data.size() == b.chunks;
  return JsonWriter().field("ok", true).field("found", true)
      .field("step", (double)b.step).field("chunks", (double)b.chunks)
      .field("nbytes", (double)b.nbytes).field("complete", complete)
      .field("group", b.group).done();
}

std::string Coordinator::op_shard_drop(const JsonObject& req) {
  // Epoch/placement invalidation: drop an owner's replicated state (step<0:
  // unconditionally; step>=0: only if the plane still holds exactly that
  // step — a drop racing a newer put must not destroy the newer blob).
  std::string owner = get_str(req, "owner");
  if (!shard_endpoints_.empty()) return redirect_reply(owner);
  long long step = (long long)get_num(req, "step", -1);
  bool dropped = false;
  auto it = shards_.find(owner);
  if (it != shards_.end() && (step < 0 || it->second.step == step)) {
    shards_.erase(it);
    dropped = true;
  }
  return JsonWriter().field("ok", true).field("dropped", dropped).done();
}

std::string Coordinator::op_bump_epoch() {
  // Control-plane membership nudge (autoscaler actuation): force every
  // parked sync waiter to resync so live workers observe a rescale without
  // waiting for a membership event (new-pod register / lease expiry).
  bump_epoch();
  release_sync(false);
  return JsonWriter().field("ok", true).done();
}

std::string Coordinator::op_preempt_notice(const JsonObject& req) {
  // Advance-notice revocation (spot/preemptible capacity): the scheduler
  // names the doomed workers and the notice budget; each target's live
  // watch connections get a targeted push within the same event-loop turn.
  // No membership change happens here — the notice is a policy INPUT; the
  // drain it triggers ends in leave -> drop_member like any departure.
  auto it = req.find("targets");
  if (it == req.end() || it->second.kind != JsonValue::kStrArray ||
      it->second.arr.empty())
    return JsonWriter().field("ok", false)
        .field("error", "targets array required").done();
  double notice_s = get_num(req, "notice_s", 0);
  std::string reason = get_str(req, "reason");
  if (reason.empty()) reason = "preempt";
  std::vector<std::string> revoked;
  revoked.reserve(it->second.arr.size());
  for (const std::string& t : it->second.arr) {
    Preempt p;
    p.notice_s = notice_s;
    p.reason = reason;
    p.seq = ++preempt_seq_;
    preempts_[t] = p;
    for (auto& [fd, name] : watcher_names_)
      if (name == t) push_preempt(fd, t, p);
    revoked.push_back(t);
  }
  return JsonWriter().field("ok", true).field("revoked", revoked).done();
}

std::string Coordinator::op_watch(const JsonObject& req, int fd) {
  // Push subscription: this fd now receives a notification frame on every
  // epoch bump. cursor >= 0 resumes a subscription after a reconnect:
  // every epoch in (cursor, epoch_] is replayed exactly once, in order,
  // BEFORE the ack — a watcher that missed bumps during an outage observes
  // each one rather than only the endpoint. The ack's cursor equals the
  // current epoch: "you are caught up as of here".
  long long cursor = (long long)get_num(req, "cursor", -1);
  std::string worker = get_str(req, "worker");
  watchers_.insert(fd);
  if (!worker.empty()) watcher_names_[fd] = worker;
  if (cursor >= 0) {
    for (long long e = cursor + 1; e <= epoch_; e++) push_notify(fd, e);
  }
  // A notice posted before this subscription (or lost across a reconnect)
  // is replayed here — delivery is at-least-once; clients dedup on seq.
  auto pit = preempts_.find(worker);
  if (!worker.empty() && pit != preempts_.end())
    push_preempt(fd, worker, pit->second);
  deferred_.push_back({fd, JsonWriter().field("ok", true)
      .field("watch", true).field("cursor", (double)epoch_)
      .field("epoch", (double)epoch_).done()});
  return "";  // ack + replay ride deferred_
}

std::string Coordinator::op_watch_cancel(const JsonObject& req, int fd) {
  (void)req;
  bool cancelled = watchers_.erase(fd) > 0;
  watcher_names_.erase(fd);
  return JsonWriter().field("ok", true).field("cancelled", cancelled).done();
}

std::string Coordinator::op_shard_map(const JsonObject& req) {
  (void)req;
  // The routing artifact clients cache: root=true + the endpoint list on a
  // root, root=false + this server's slot on a shard (or a plain single
  // process, where nshards is 0 and routing is a no-op).
  long long n = shard_endpoints_.empty() ? num_shards_
                                         : (long long)shard_endpoints_.size();
  return JsonWriter().field("ok", true)
      .field("root", !shard_endpoints_.empty())
      .field("nshards", (double)n)
      .field("shards", shard_endpoints_)
      .field("shard_index", (double)shard_index_).done();
}

std::string Coordinator::op_status() {
  // The ops/fsyncs/turns counters let bench_coord.py measure group-commit
  // amortization (fsyncs per op, ops per event-loop turn) without strace.
  // lease_holders rides the flat wire format as "worker=count" strings —
  // the Python-side metrics bridge splits them back into labeled gauges.
  std::vector<std::string> holders;
  holders.reserve(leases_by_worker_.size());
  for (auto& [worker, tasks] : leases_by_worker_)
    if (!tasks.empty())
      holders.push_back(worker + "=" + std::to_string(tasks.size()));
  // Pending revocations ride the same flat "worker=value" encoding as
  // lease_holders; notice_s is integer-truncated so the string is
  // deterministic across backends (the twin formats with int()).
  std::vector<std::string> pending;
  pending.reserve(preempts_.size());
  for (auto& [worker, p] : preempts_)
    pending.push_back(worker + "=" + std::to_string((long long)p.notice_s));
  return JsonWriter()
      .field("ok", true)
      .field("world", (double)members_.size())
      .field("queued", (double)todo_.size())
      .field("leased", (double)leased_.size())
      .field("done", (double)done_.size())
      .field("ops", (double)ops_handled_)
      .field("batch_frames", (double)batch_frames_)
      .field("batch_subops", (double)batch_subops_)
      .field("fsyncs", (double)fsyncs_)
      .field("snapshots", (double)snapshots_)
      .field("journal_records", (double)journal_appends_)
      .field("turns", (double)turns_)
      .field("uptime_seconds", now_sec() - boot_sec_)
      .field("lease_holders", holders)
      .field("preempts", pending)
      .done();
}

std::string Coordinator::op_batch(const JsonObject& req, int fd) {
  auto it = req.find("ops");
  if (it == req.end() || it->second.kind != JsonValue::kStrArray)
    return JsonWriter().field("ok", false).field("error", "ops array required").done();
  batch_frames_++;
  std::string worker = get_str(req, "worker");
  std::vector<std::string> replies;
  replies.reserve(it->second.arr.size());
  for (const std::string& sub : it->second.arr) {
    // Sub-ops are JSON-encoded strings inside the frame's "ops" array (the
    // wire parser is flat-objects-only, so nesting rides on string escapes).
    JsonObject subreq;
    JsonParser parser(sub);
    std::string line;
    if (!parser.parse_object(&subreq)) {
      line = JsonWriter().field("ok", false).field("error", "bad json").done();
    } else {
      // Sub-ops inherit the frame's worker identity unless they carry their
      // own; the frame's token has already cleared auth for all of them.
      if (!worker.empty() && !subreq.count("worker")) {
        JsonValue wv;
        wv.kind = JsonValue::kString;
        wv.str = worker;
        subreq["worker"] = std::move(wv);
      }
      std::string subop = get_str(subreq, "op");
      if (subop == "batch" || subop == "barrier" || subop == "sync" ||
          subop == "watch") {
        // barrier/sync park the fd and reply via deferred_ — a parked reply
        // cannot be threaded into a frame's positional reply array; a watch
        // ack rides deferred_ the same way. Nested frames are disallowed
        // outright.
        line = JsonWriter().field("ok", false)
            .field("error", "op not batchable: " + subop).done();
      } else {
        // Same handlers as single-op frames: req_id acquire dedup, op_id
        // kv_incr markers, and idempotent complete_task hold PER SUB-OP —
        // batching changes framing, not semantics.
        line = dispatch(subop, subreq, fd);
        ops_handled_++;
      }
    }
    line = stamp_epoch(std::move(line));
    if (!line.empty() && line.back() == '\n') line.pop_back();
    batch_subops_++;
    replies.push_back(std::move(line));
  }
  return JsonWriter().field("ok", true).field("replies", replies).done();
}

std::string Coordinator::handle(const JsonObject& req, int fd) {
  std::string op = get_str(req, "op");
  // Per-job shared-secret auth (EDL_COORD_TOKEN): with pods binding
  // 0.0.0.0 so cross-host trainers can dial in, any pod in a shared
  // cluster could otherwise add_tasks/bump_epoch/poison KV for any job —
  // the reference's etcd sidecar was equally open (pkg/jobparser.go:
  // 167-184); this closes that hole. "ping" stays open: it is the
  // liveness probe and touches no state. Every other op, read or write,
  // requires the exact token (constant semantics beat a read/write split
  // nobody can audit).
  if (!auth_token_.empty() && op != "ping" && get_str(req, "token") != auth_token_) {
    return JsonWriter()
        .field("ok", false)
        .field("error", "unauthorized: bad or missing token")
        .field("unauthorized", true)
        .done();
  }
  if (op == "batch") {
    // Sub-op accounting happens inside op_batch; the envelope itself is
    // framing, not an op.
    return stamp_epoch(op_batch(req, fd));
  }
  ops_handled_++;
  return stamp_epoch(dispatch(op, req, fd));
}

std::string Coordinator::dispatch(const std::string& op, const JsonObject& req,
                                  int fd) {
  if (op == "register") return op_register(req);
  if (op == "heartbeat") return op_heartbeat(req);
  if (op == "leave") return op_leave(req);
  if (op == "members") return op_members();
  if (op == "add_tasks") return op_add_tasks(req);
  if (op == "acquire_task") return op_acquire_task(req);
  if (op == "complete_task") return op_complete_task(req);
  if (op == "fail_task") return op_fail_task(req);
  if (op == "barrier") return op_barrier(req, fd);
  if (op == "sync") return op_sync(req, fd);
  if (op == "kv_put") return op_kv_put(req);
  if (op == "kv_get") return op_kv_get(req);
  if (op == "kv_del") return op_kv_del(req);
  if (op == "kv_incr") return op_kv_incr(req);
  if (op == "shard_put") return op_shard_put(req);
  if (op == "shard_get") return op_shard_get(req);
  if (op == "shard_meta") return op_shard_meta(req);
  if (op == "shard_drop") return op_shard_drop(req);
  if (op == "bump_epoch") return op_bump_epoch();
  if (op == "preempt_notice") return op_preempt_notice(req);
  if (op == "watch") return op_watch(req, fd);
  if (op == "watch_cancel") return op_watch_cancel(req, fd);
  if (op == "shard_map") return op_shard_map(req);
  if (op == "status") return op_status();
  if (op == "ping") return JsonWriter().field("ok", true).field("pong", true).done();
  return JsonWriter().field("ok", false).field("error", "unknown op: " + op).done();
}

void Coordinator::on_disconnect(int fd) {
  // A watch subscription is connection-scoped: the client resumes on its
  // next connection with the cursor it last observed.
  watchers_.erase(fd);
  watcher_names_.erase(fd);
  // Withdraw the worker's pending barrier arrival along with its waiter
  // entry: a crashed/disconnected worker must not count toward the barrier
  // (matches the Python twin's timeout withdrawal) — otherwise survivors
  // would pass a sync point the dead worker never completed.
  for (auto& [_, b] : barriers_) {
    auto& w = b.waiters;
    for (size_t i = 0; i < w.size();) {
      if (w[i].fd == fd) {
        b.arrived.erase(w[i].worker);
        w.erase(w.begin() + i);
      } else {
        i++;
      }
    }
  }
  for (size_t i = 0; i < sync_waiters_.size();) {
    if (sync_waiters_[i].fd == fd) {
      sync_arrived_.erase(sync_waiters_[i].worker);
      sync_waiters_.erase(sync_waiters_.begin() + i);
    } else {
      i++;
    }
  }
}

// ---------------------------------------------------------------------------
// Event loop: epoll (level-triggered) on Linux, poll() fallback elsewhere or
// when epoll_create fails; EDL_COORD_FORCE_POLL=1 forces the fallback (the
// bench's "before" arm and the fallback's own test coverage).
//
// Why it matters at 10k conns: the old loop rebuilt a pollfd vector of every
// connection and had the kernel scan all of them on EVERY wakeup — O(conns)
// per turn even when one fd was ready. epoll registers interest once and
// wakeups are O(ready). Level-triggered keeps the read/write code identical
// between the two backends (no drain-until-EAGAIN obligations beyond what
// the poll path already did).
// ---------------------------------------------------------------------------

struct PollerEvent {
  int fd = -1;
  bool readable = false;
  bool writable = false;
  bool err = false;
};

class Poller {
 public:
  virtual ~Poller() = default;
  virtual void add(int fd) = 0;
  virtual void set_write(int fd, bool want_write) = 0;
  virtual void remove(int fd) = 0;
  virtual void wait(int timeout_ms, std::vector<PollerEvent>* out) = 0;
  virtual const char* name() const = 0;
};

#ifdef __linux__
class EpollPoller : public Poller {
 public:
  static EpollPoller* create() {
    int ep = epoll_create1(EPOLL_CLOEXEC);
    return ep < 0 ? nullptr : new EpollPoller(ep);
  }
  ~EpollPoller() override { close(ep_); }
  void add(int fd) override { ctl(EPOLL_CTL_ADD, fd, EPOLLIN); }
  void set_write(int fd, bool want_write) override {
    ctl(EPOLL_CTL_MOD, fd, EPOLLIN | (want_write ? (unsigned)EPOLLOUT : 0u));
  }
  void remove(int fd) override { epoll_ctl(ep_, EPOLL_CTL_DEL, fd, nullptr); }
  void wait(int timeout_ms, std::vector<PollerEvent>* out) override {
    int n = epoll_wait(ep_, evs_, kMaxEvents, timeout_ms);
    for (int i = 0; i < n; i++) {
      PollerEvent e;
      e.fd = evs_[i].data.fd;
      e.readable = (evs_[i].events & EPOLLIN) != 0;
      e.writable = (evs_[i].events & EPOLLOUT) != 0;
      e.err = (evs_[i].events & (EPOLLERR | EPOLLHUP)) != 0;
      out->push_back(e);
    }
  }
  const char* name() const override { return "epoll"; }

 private:
  explicit EpollPoller(int ep) : ep_(ep) {}
  void ctl(int cop, int fd, unsigned events) {
    epoll_event ev{};
    ev.events = events;
    ev.data.fd = fd;
    epoll_ctl(ep_, cop, fd, &ev);
  }
  static constexpr int kMaxEvents = 1024;
  int ep_;
  epoll_event evs_[kMaxEvents];
};
#endif  // __linux__

class PollPoller : public Poller {
 public:
  void add(int fd) override { interest_[fd] = POLLIN; }
  void set_write(int fd, bool want_write) override {
    auto it = interest_.find(fd);
    if (it != interest_.end())
      it->second = POLLIN | (want_write ? POLLOUT : 0);
  }
  void remove(int fd) override { interest_.erase(fd); }
  void wait(int timeout_ms, std::vector<PollerEvent>* out) override {
    pfds_.clear();
    for (auto& [fd, ev] : interest_) pfds_.push_back({fd, ev, 0});
    int n = poll(pfds_.data(), pfds_.size(), timeout_ms);
    if (n <= 0) return;
    for (auto& p : pfds_) {
      if (!p.revents) continue;
      PollerEvent e;
      e.fd = p.fd;
      e.readable = (p.revents & POLLIN) != 0;
      e.writable = (p.revents & POLLOUT) != 0;
      e.err = (p.revents & (POLLERR | POLLHUP | POLLNVAL)) != 0;
      out->push_back(e);
    }
  }
  const char* name() const override { return "poll"; }

 private:
  std::map<int, short> interest_;
  std::vector<pollfd> pfds_;
};

Poller* make_poller() {
  const char* force = getenv("EDL_COORD_FORCE_POLL");
  bool force_poll = force && *force && strcmp(force, "0") != 0;
#ifdef __linux__
  if (!force_poll) {
    Poller* p = EpollPoller::create();
    if (p) return p;
    fprintf(stderr, "edl-coordinator: epoll_create failed, using poll()\n");
  }
#else
  (void)force_poll;
#endif
  return new PollPoller();
}

}  // namespace

int make_listener(const char* host, int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) { perror("socket"); exit(1); }
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  // Default 127.0.0.1: the protocol is unauthenticated, so exposure beyond
  // loopback must be an explicit deployment decision — the pod launcher
  // passes --host 0.0.0.0 because trainers on OTHER hosts dial the
  // coordinator's service address (a loopback-only bind would make
  // multi-host jobs undialable).
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    fprintf(stderr, "bad --host %s (want an IPv4 address)\n", host);
    exit(1);
  }
  addr.sin_port = htons(port);
  if (bind(fd, (sockaddr*)&addr, sizeof addr) < 0) { perror("bind"); exit(1); }
  if (listen(fd, 128) < 0) { perror("listen"); exit(1); }
  fcntl(fd, F_SETFL, O_NONBLOCK);
  return fd;
}

int main(int argc, char** argv) {
  int port = 7164;
  std::string host = "127.0.0.1";
  std::string state_file;
  std::string run_id;
  double task_lease = 16.0;   // ref: -task-timout-dur 16s (docker/paddle_k8s:30)
  double hb_ttl = 10.0;
  std::string shards_arg;     // root mode: comma-separated shard endpoints
  long long shard_index = -1; // shard mode: this server's slot
  long long num_shards = 0;
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    auto next = [&]() -> const char* { return i + 1 < argc ? argv[++i] : ""; };
    if (a == "--port") port = atoi(next());
    else if (a == "--host") host = next();
    else if (a == "--state-file") state_file = next();
    else if (a == "--run-id") run_id = next();
    else if (a == "--task-lease-sec") task_lease = atof(next());
    else if (a == "--heartbeat-ttl-sec") hb_ttl = atof(next());
    else if (a == "--shards") shards_arg = next();
    else if (a == "--shard-index") shard_index = atoll(next());
    else if (a == "--num-shards") num_shards = atoll(next());
    else if (a == "--help") {
      printf("edl-coordinator --port N [--host A] [--state-file P] "
             "[--run-id ID] [--task-lease-sec S] [--heartbeat-ttl-sec S] "
             "[--shards H:P,H:P,...] [--shard-index I --num-shards N]\n");
      return 0;
    }
  }
  signal(SIGPIPE, SIG_IGN);

  // Token via environment, never argv: /proc/<pid>/cmdline is world-
  // readable on shared nodes. The controller stamps EDL_COORD_TOKEN into
  // every pod of the job (jobparser make_env), so coordinator and
  // trainers agree by construction.
  const char* tok_env = getenv("EDL_COORD_TOKEN");
  std::string auth_token = tok_env ? tok_env : "";
  if (auth_token.empty() && host != "127.0.0.1" && host != "localhost") {
    fprintf(stderr,
            "edl-coordinator: WARNING: bound to %s with no EDL_COORD_TOKEN — "
            "any peer that can reach this port can drive the job\n",
            host.c_str());
  }

  int listener = make_listener(host.c_str(), port);
  fprintf(stderr, "edl-coordinator listening on %s:%d (task-lease %.1fs, hb-ttl %.1fs%s%s%s)\n",
          host.c_str(), port, task_lease, hb_ttl,
          state_file.empty() ? "" : ", state-file ", state_file.c_str(),
          auth_token.empty() ? "" : ", auth on");
  fflush(stderr);

  Coordinator coord(task_lease, hb_ttl, state_file, run_id, auth_token);
  if (!shards_arg.empty()) {
    std::vector<std::string> eps;
    size_t start = 0;
    while (start <= shards_arg.size()) {
      size_t comma = shards_arg.find(',', start);
      if (comma == std::string::npos) comma = shards_arg.size();
      if (comma > start) eps.push_back(shards_arg.substr(start, comma - start));
      start = comma + 1;
    }
    fprintf(stderr, "edl-coordinator: root mode over %zu shard(s)\n",
            eps.size());
    coord.set_shards(std::move(eps));
  }
  if (shard_index >= 0) coord.set_shard_identity(shard_index, num_shards);
  if (!coord.state_writable()) {
    fprintf(stderr, "edl-coordinator: --state-file %s not writable\n",
            state_file.c_str());
    return 1;
  }
  std::unique_ptr<Poller> poller(make_poller());
  fprintf(stderr, "edl-coordinator event loop: %s\n", poller->name());
  fflush(stderr);

  std::unordered_map<int, Conn> conns;
  // Connections with queued output: replies held for durability plus
  // EAGAIN backlogs. Flushing walks THIS set, not every connection —
  // the other O(conns)-per-turn cost of the old loop.
  std::unordered_set<int> unflushed;
  poller->add(listener);
  bool was_durable = true;
  std::vector<PollerEvent> events;

  while (true) {
    double wait = coord.tick();
    // A journal outage holds replies: retry the write soon, don't sleep
    // until the next membership deadline with clients hanging.
    if (!was_durable) wait = 0.05;
    // Heartbeat expiry inside tick() can release sync waiters (resync):
    // deliver those before blocking in the poller.
    for (auto& [fd, line] : coord.take_deferred()) {
      auto it = conns.find(fd);
      if (it != conns.end() && !line.empty()) {
        it->second.outbuf += line;
        unflushed.insert(fd);
      }
    }
    events.clear();
    poller->wait((int)(wait * 1000), &events);
    coord.note_turn();

    std::vector<int> to_close;
    for (auto& ev : events) {
      if (ev.fd == listener) {
        while (true) {
          int cfd = accept(listener, nullptr, nullptr);
          if (cfd < 0) break;
          fcntl(cfd, F_SETFL, O_NONBLOCK);
          int one = 1;
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
          Conn conn;
          conn.fd = cfd;
          conns.emplace(cfd, std::move(conn));
          poller->add(cfd);
        }
        continue;
      }
      auto it = conns.find(ev.fd);
      if (it == conns.end()) continue;
      Conn& c = it->second;
      if (ev.err && !ev.readable) {
        // Pure error/hangup. A readable HUP (peer sent then closed) still
        // drains below — its final requests parse and the fd closes on
        // read()==0, matching the poll-path behavior.
        to_close.push_back(ev.fd);
        continue;
      }
      if (ev.readable) {
        bool eof = false;
        char buf[65536];
        while (true) {
          ssize_t n = read(ev.fd, buf, sizeof buf);
          if (n > 0) c.inbuf.append(buf, n);
          else if (n == 0) { eof = true; break; }
          else {
            // A hard error (ECONNRESET...) must close the fd: level-
            // triggered polling would otherwise re-report it forever.
            if (errno != EAGAIN && errno != EWOULDBLOCK) eof = true;
            break;
          }
        }
        size_t pos;
        while ((pos = c.inbuf.find('\n')) != std::string::npos) {
          std::string line = c.inbuf.substr(0, pos);
          c.inbuf.erase(0, pos + 1);
          if (line.empty()) continue;
          JsonObject req;
          JsonParser parser(line);
          if (!parser.parse_object(&req)) {
            c.outbuf += JsonWriter().field("ok", false).field("error", "bad json").done();
            continue;
          }
          c.outbuf += coord.handle(req, ev.fd);
        }
        if (!c.outbuf.empty()) unflushed.insert(ev.fd);
        if (eof) to_close.push_back(ev.fd);
      }
      if (ev.writable && !c.outbuf.empty()) unflushed.insert(ev.fd);
    }

    // Barrier/sync releases from this round of requests.
    for (auto& [fd, line] : coord.take_deferred()) {
      auto cit = conns.find(fd);
      if (cit != conns.end() && !line.empty()) {
        cit->second.outbuf += line;
        unflushed.insert(fd);
      }
    }

    // Durability point BEFORE the acks flush: a client that reads a
    // mutating op's success reply can rely on the delta being fsynced
    // (group commit: the one fsync inside covers every mutation handled
    // this turn). While a write is failing, replies are held (and retried
    // next iteration) rather than acknowledging un-durable state.
    bool durable = coord.maybe_save_state();
    was_durable = durable;
    if (!durable) usleep(50 * 1000);  // fs outage: don't busy-spin

    if (durable && !unflushed.empty()) {
      std::vector<int> flushed;
      for (int fd : unflushed) {
        auto cit = conns.find(fd);
        if (cit == conns.end()) { flushed.push_back(fd); continue; }
        Conn& c = cit->second;
        while (!c.outbuf.empty()) {
          ssize_t n = write(fd, c.outbuf.data(), c.outbuf.size());
          if (n > 0) c.outbuf.erase(0, n);
          else break;
        }
        if (c.outbuf.empty()) {
          flushed.push_back(fd);
          if (c.want_write) {
            c.want_write = false;
            poller->set_write(fd, false);
          }
        } else if (!c.want_write) {
          // Kernel buffer full: wake on writable instead of spinning.
          c.want_write = true;
          poller->set_write(fd, true);
        }
      }
      for (int fd : flushed) unflushed.erase(fd);
    }

    for (int fd : to_close) {
      coord.on_disconnect(fd);
      poller->remove(fd);
      close(fd);
      conns.erase(fd);
      unflushed.erase(fd);
    }
  }
  return 0;
}
