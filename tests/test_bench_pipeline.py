"""bench_pipeline.py harness: smoke the sweep in-process at tiny shapes.

The committed BENCH_PIPELINE.json comes from the full `make bench-pipeline`
sweep; these tests pin the harness contract (every schedule present,
analytic fields populated, crossover summary well-formed) without paying
for it — the fuller configuration is slow-marked out of tier-1.
"""

import json

import pytest


def _run_sweep(monkeypatch, tmp_path, ms, vs, layers):
    import bench_pipeline

    out = tmp_path / "BENCH_PIPELINE.json"
    monkeypatch.setenv("EDL_BENCH_PLATFORM", "cpu")
    monkeypatch.setenv("EDL_PIPE_OUT", str(out))
    monkeypatch.setenv("EDL_PIPE_MS", json.dumps(ms))
    monkeypatch.setenv("EDL_PIPE_VS", json.dumps(vs))
    monkeypatch.setenv("EDL_PIPE_LAYERS", str(layers))
    monkeypatch.setenv("EDL_PIPE_D_MODEL", "32")
    monkeypatch.setenv("EDL_PIPE_D_FF", "64")
    monkeypatch.setenv("EDL_PIPE_SEQ", "16")
    monkeypatch.setenv("EDL_BENCH_WINDOWS", "1")
    monkeypatch.setenv("EDL_BENCH_STEPS", "1")
    summary = bench_pipeline.main()
    assert out.exists()
    assert json.loads(out.read_text())["metric"] == summary["metric"]
    return summary


def test_sweep_smoke(monkeypatch, tmp_path):
    summary = _run_sweep(monkeypatch, tmp_path, ms=[4], vs=[2], layers=8)
    recs = summary["records"]
    assert {r["schedule"] for r in recs} == {
        "gpipe", "1f1b", "1f1b-interleaved"
    }
    for r in recs:
        assert r["step_ms"] > 0
        assert 0 < r["bubble_fraction"] < 1
        assert r["stash_slots"] > 0
        assert r["stash_bytes_per_device"] > 0
    # the acceptance invariant the committed artifact must also show:
    # interleaved bubble strictly below plain 1f1b at equal M for v >= 2
    f = next(r for r in recs if r["schedule"] == "1f1b")
    il = next(r for r in recs if r["schedule"] == "1f1b-interleaved")
    assert il["bubble_fraction"] < f["bubble_fraction"]
    # gpipe stashes O(M), the combined schedules O(n*v)
    g = next(r for r in recs if r["schedule"] == "gpipe")
    assert f["stash_bytes_per_device"] <= g["stash_bytes_per_device"]
    cross = summary["crossover"]["4"]
    assert cross["fastest"] in {"gpipe", "1f1b", "1f1b-interleaved"}
    assert cross["best_interleaved_vs_1f1b_step_ratio"] is not None


@pytest.mark.slow
def test_sweep_fuller_configuration(monkeypatch, tmp_path):
    summary = _run_sweep(
        monkeypatch, tmp_path, ms=[4, 8, 16], vs=[2, 4], layers=16
    )
    # 3 + 3 + 3*2 configurations
    assert len(summary["records"]) == 12
    for m in ("4", "8", "16"):
        assert m in summary["crossover"]
