"""Composed cross-axis chaos: SIGKILL × apiserver faults × partitions, overlapping.

The single-axis chaos suites each break one thing at a time
(``test_coordinator_outage.py``: partitions; ``test_chaos.py``: pod kills;
``test_k8s.py``: 409/410). Real incidents compose — a network partition
storm arrives *while* a trainer is being replaced *while* the apiserver is
rejecting status writes. This test runs all three axes overlapping under
one :class:`ChaosScenario` (deterministic: every fault gates on observed
workload state, never wall clock) and checks the combined invariants:

- job alpha (trainer-SIGKILL axis) converges through its replacement pod;
- job beta (partition axis) rides three blips, then checkpoint-and-parks
  a sustained partition — the adaptive fault-tolerance policy must choose
  at least two distinct recovery modes, visible in ``edl_ft_policy_*``
  metrics scraped live from ``/metrics`` and in per-decision trace spans;
- the K8s status updater and informer survive the 409s and mid-stream 410;
- exactly-once holds on both queues, and beta's final loss matches an
  unfaulted twin run (faults cost time, never training math).
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from edl_tpu.coordinator import (
    CoordinatorServer,
    InProcessCoordinator,
    RetryPolicy,
)
from edl_tpu.coordinator.client import CoordinatorClient
from edl_tpu.obs.metrics import parse_prometheus
from edl_tpu.runtime.ft_policy import PARK, RECONNECT, WAIT, FTPolicyConfig
from edl_tpu.testing import ChaosProxy
from edl_tpu.testing.chaosproxy import ChaosScenario

from tests.test_coordinator import has_toolchain

needs_native = pytest.mark.skipif(
    not has_toolchain(), reason="native toolchain unavailable"
)

# Composed chaos is tier-2 (`make chaos-composed`); the interleavings are
# prime sanitizer food, so the TSan lane picks it up too.
pytestmark = [pytest.mark.chaos, pytest.mark.slow, pytest.mark.sanitizer]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_ALPHA = 6          # shards on the SIGKILL-axis job
N_BETA = 12          # shards on the partition-axis job
BETA_BATCHES = 4
BETA_PACE = 0.2      # seconds/batch: keeps beta's queue alive through all
                     # three blips + the storm (gates, not sleeps, do the
                     # actual synchronization — this only sets the floor)


def _scrape(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.read().decode()


@needs_native
def test_composed_cross_axis_chaos(tmp_path):
    import jax

    from edl_tpu.api.types import JobPhase
    from edl_tpu.k8s import ApiClient, K8sJobStore, KubeConfig
    from edl_tpu.models import fit_a_line
    from edl_tpu.runtime import SyntheticShardSource
    from edl_tpu.runtime.data import shard_names
    from edl_tpu.runtime.elastic import (
        FT_POLICY_KEY,
        ElasticConfig,
        ElasticWorker,
    )
    from edl_tpu.runtime.train_loop import TrainerConfig
    from tests.fake_apiserver import FakeApiServer
    from tests.test_elastic import WORKER_CRASH_SRC
    from tests.test_k8s import _client, _job

    model = fit_a_line.MODEL

    # -- axis 1: trainer SIGKILL (job alpha, subprocess workers) ---------------
    server_a = CoordinatorServer(task_lease_sec=60.0, heartbeat_ttl_sec=60.0)
    # -- axis 2: coordinator partition (job beta, in-thread, adaptive policy) --
    server_b = CoordinatorServer(task_lease_sec=120.0, heartbeat_ttl_sec=120.0)
    # -- axis 3: apiserver 409/410 (status updater + informer) -----------------
    srv = FakeApiServer()
    base = srv.serve()

    alpha_procs = []

    def spawn_alpha(name):
        env = dict(os.environ)
        env.update(PORT=str(server_a.port), NAME=name,
                   CKPT=str(tmp_path / "ck-alpha"))
        return subprocess.Popen(
            [sys.executable, "-c", WORKER_CRASH_SRC.format(repo=REPO)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )

    def kill_alpha():
        p = alpha_procs[0]
        p.kill()  # SIGKILL: no atexit, no finally, leases left dangling
        p.wait()
        # explicit leave in lieu of waiting out the heartbeat TTL: the dead
        # worker's leases requeue so the replacement can drain them
        server_a.client(alpha_names[0]).leave()

    def respawn_alpha():
        alpha_procs.append(spawn_alpha(alpha_names[1]))

    alpha_names = ("w-a0", "w-a1")

    counts = {}

    class PacedCounting(SyntheticShardSource):
        def read(self, shard):
            counts[shard] = counts.get(shard, 0) + 1
            for b in super().read(shard):
                time.sleep(BETA_PACE)
                yield b

    watch_events = []

    class Recorder:
        def on_add(self, job):
            watch_events.append(("add", job.name, job.status.phase))

        def on_update(self, job):
            watch_events.append(("update", job.name, job.status.phase))

        def on_del(self, job):
            watch_events.append(("del", job.name, job.status.phase))

    stop_updater = threading.Event()
    update_ok = [0]

    store = K8sJobStore(_client(base), watch_timeout_seconds=5.0)
    store.create(_job())

    def updater():
        # a controller's status writeback loop: keeps PATCHing /status
        # through whatever the apiserver throws (armed 409s are absorbed
        # by the store's conflict retry, invisibly to us)
        while not stop_updater.is_set():
            status = store.get("demo").status
            status.phase = JobPhase.RUNNING
            store.update_status("demo", status)
            update_ok[0] += 1
            stop_updater.wait(0.25)

    try:
        server_a.start()
        server_b.start()
        admin_a = server_a.client("admin")
        admin_a.add_tasks(shard_names("ax", N_ALPHA))
        admin_b = server_b.client("admin")
        shards_b = shard_names("bx", N_BETA)
        admin_b.add_tasks(shards_b)

        store.watch(Recorder(), replay=True)
        updater_t = threading.Thread(target=updater, daemon=True)
        updater_t.start()

        with ChaosProxy(server_b.port, seed=11) as proxy:
            raw_b = CoordinatorClient(
                port=proxy.port, worker="w-beta",
                # fail fast so even a ~1 s blip registers as an incident
                retry=RetryPolicy(deadline=0.5, seed=11))
            source_b = PacedCounting(model, batch_size=8,
                                     batches_per_shard=BETA_BATCHES)
            cfg_b = ElasticConfig(
                checkpoint_dir=str(tmp_path / "ck-beta"),
                checkpoint_interval=4,
                heartbeat_interval=0.0,  # poll the epoch every batch
                metrics_port=0,          # ephemeral /metrics for the scrape
                # budget 6 s: blips (~1.2 s) ride inside it during the
                # cold-start static fallback; the storm blows through the
                # adaptive threshold (quantile of the three closed blips)
                ft_policy=FTPolicyConfig(
                    outage_budget=6.0, min_history=3, min_wait=1.0,
                    storm_retry_deadline=0.5),
                trainer=TrainerConfig(optimizer="sgd", learning_rate=0.05),
            )
            worker_b = ElasticWorker(model, raw_b, source_b, cfg_b,
                                     device_planner=lambda w: jax.devices())

            beta_out = {}

            def run_beta():
                try:
                    beta_out["metrics"] = worker_b.run()
                except BaseException as e:  # edl: noqa[EDL005] re-raised via assert in the main thread
                    beta_out["error"] = e

            scraped = threading.Event()
            policy = worker_b.policy

            def hist(k):
                return lambda: policy.state()["history"] >= k

            def make_demo2():
                job2 = _job()
                job2.name = "demo2"
                store.create(job2)

            sc = (
                ChaosScenario("composed")
                .register_proxy("beta", proxy)
                .register("alpha.kill", kill_alpha)
                .register("alpha.respawn", respawn_alpha)
                .register("api.conflicts",
                          lambda n: setattr(srv, "status_conflicts", n))
                .register("api.break_watch",
                          lambda: setattr(srv, "watch_error_410_after", 1))
                .register("api.create_demo2", make_demo2)
                .predicate("alpha_progress",
                           lambda: int(admin_a.status().get("done", 0)) >= 2)
                .predicate("beta_warm", lambda: worker_b.steps_done >= 2)
                .predicate("hist1", hist(1))
                .predicate("hist2", hist(2))
                .predicate("hist3", hist(3))
                .predicate("scraped", scraped.is_set)
                # every fault gates on workload state: reproducible on any
                # machine speed. The axes overlap by construction — alpha's
                # replacement drains and the 409s are live while beta's
                # partitions land.
                .add("api.conflicts", n=2, note="arm /status 409s")
                .add("alpha.kill", when="alpha_progress",
                     note="SIGKILL the trainer mid-queue")
                .add("alpha.respawn", after=0.2,
                     note="Job-controller reconcile: replacement pod")
                .add("beta.partition", when="beta_warm", note="blip 1")
                .add("beta.heal", after=1.2)
                .add("beta.partition", when="hist1", note="blip 2")
                .add("beta.heal", after=1.2)
                .add("api.break_watch", note="410 mid-stream: etcd compaction")
                .add("beta.partition", when="hist2", note="blip 3")
                .add("beta.heal", after=1.2)
                .add("api.create_demo2",
                     note="the relisted informer must deliver this")
                .add("beta.partition", when="hist3",
                     note="the storm: held until beta parks")
                .add("beta.heal", when="scraped", timeout=180.0,
                     note="heal only after checkpoint-and-park + live scrape")
            )

            alpha_procs.append(spawn_alpha(alpha_names[0]))
            beta_t = threading.Thread(target=run_beta, daemon=True)
            beta_t.start()
            sc.start()

            # main thread: wait for the park decision, then scrape the live
            # worker while it is parked (its /metrics thread keeps serving
            # through the partition — that's the point of the probe).
            deadline = time.time() + 300
            while time.time() < deadline:
                if policy.decisions[PARK] >= 1:
                    break
                assert sc.failed is None, (sc.failed, sc.spec())
                assert "error" not in beta_out, beta_out
                time.sleep(0.05)
            else:
                pytest.fail(f"beta never parked: {policy.state()} "
                            f"scenario={sc.events}")

            url = getattr(worker_b, "metrics_url", None)
            assert url, "metrics server never came up"
            families = parse_prometheus(_scrape(url + "/metrics"))
            fam_names = {n for n in families if n.startswith("edl_ft_policy_")}
            assert {"edl_ft_policy_decisions_total", "edl_ft_policy_mode",
                    "edl_ft_policy_incidents_total",
                    "edl_ft_policy_park_threshold_seconds",
                    }.issubset(fam_names), fam_names
            health = json.loads(_scrape(url + "/healthz"))
            assert health["ft_policy"]["mode"] == PARK, health["ft_policy"]
            scraped.set()

            sc.join(timeout=180)
            assert sc.completed, (sc.failed, sc.events, sc.spec())

            beta_t.join(timeout=300)
            assert not beta_t.is_alive(), "beta never drained after heal"
            assert "error" not in beta_out, beta_out["error"]
            metrics_b = beta_out["metrics"]

        st_b = admin_b.status()
        # the policy's KV audit record survived the chaos (buffered through
        # the outbox during the very outage it describes)
        audit_raw = admin_b.kv_get(FT_POLICY_KEY.format(worker="w-beta"))
        admin_b.close()

        # alpha's replacement converges
        out, err = alpha_procs[1].communicate(timeout=240)
        assert alpha_procs[1].returncode == 0, (
            f"alpha replacement failed:\n{err[-3000:]}")
        st_a = admin_a.status()
        admin_a.close()
    finally:
        stop_updater.set()
        store.stop()
        for p in alpha_procs:
            if p.poll() is None:
                p.kill()
        server_a.stop()
        server_b.stop()
        srv.close()

    # -- axis 1: exactly-once through the kill ---------------------------------
    assert int(st_a["done"]) == N_ALPHA, st_a
    assert int(st_a["queued"]) == 0 and int(st_a["leased"]) == 0, st_a

    # -- axis 2: the adaptive policy adjudicated every incident ----------------
    # >= 2 distinct recovery modes actually chosen (blips reconnect in
    # place, the storm parks); >= 4 incidents (3 blips + storm)
    used = [m for m, n in worker_b.policy.decisions.items() if n > 0]
    assert len(used) >= 2, worker_b.policy.decisions
    assert worker_b.policy.decisions[RECONNECT] >= 3, worker_b.policy.decisions
    assert worker_b.policy.decisions[PARK] >= 1, worker_b.policy.decisions
    assert worker_b.policy.incidents >= 4
    assert metrics_b["policy_park"] >= 1.0, metrics_b
    # every decision left a span carrying the inputs it was computed from
    spans = worker_b.tracer.find(name="ft_decision")
    assert len(spans) >= worker_b.policy.incidents
    for s in spans:
        for key in ("mode", "threshold", "elapsed", "park_breakeven",
                    "failure_rate_per_min"):
            assert key in s.attrs, s.attrs
    assert {s.attrs["mode"] for s in spans} >= {WAIT, RECONNECT, PARK}
    audit = json.loads(audit_raw)
    assert audit["policy"] == "adaptive" and audit["incidents"] >= 4, audit

    # exactly-once on beta: ledger balanced; every shard completed once.
    # Reads: blips never force a re-read (leases ride them out), and the
    # park may re-open only the single shard in flight when it fired — the
    # carry skips its consumed batches, so the re-read retrains nothing
    # (proven below: step count and loss match the unfaulted twin).
    assert int(st_b["done"]) == N_BETA, st_b
    assert int(st_b["queued"]) == 0 and int(st_b["leased"]) == 0, st_b
    assert set(counts) == set(shards_b), counts
    replayed = [s for s, n in counts.items() if n > 1]
    assert len(replayed) <= 1 and all(counts[s] == 2 for s in replayed), counts

    # -- axis 3: the apiserver faults were absorbed, not crashed through ------
    assert update_ok[0] >= 3, "status updater made no progress"
    assert srv.status_conflicts == 0, "armed 409s never exercised"
    assert any(e[0] == "add" and e[1] == "demo2" for e in watch_events), (
        "informer never resumed after the mid-stream 410", watch_events)
    assert any(e[0] == "update" and e[2] == JobPhase.RUNNING
               for e in watch_events), watch_events

    # -- loss parity: chaos cost time, not training math -----------------------
    coord = InProcessCoordinator(task_lease_sec=120.0, heartbeat_ttl_sec=120.0)
    twin_admin = coord.client("admin")
    twin_admin.register()
    twin_admin.add_tasks(shards_b)
    twin_cfg = ElasticConfig(
        checkpoint_dir=str(tmp_path / "ck-twin"),
        checkpoint_interval=4,
        heartbeat_interval=0.0,
        trainer=TrainerConfig(optimizer="sgd", learning_rate=0.05),
    )
    twin = ElasticWorker(
        model, coord.client("w-twin"),
        SyntheticShardSource(model, batch_size=8,
                             batches_per_shard=BETA_BATCHES),
        twin_cfg, device_planner=lambda w: jax.devices())
    metrics_twin = twin.run()
    # at-least-once on the park path: the shard in flight when the park
    # fired may replay its uncovered batches — never more than one shard's
    # worth, never fewer steps than the clean run
    extra = metrics_b["steps"] - metrics_twin["steps"]
    assert 0 <= extra <= BETA_BATCHES, (metrics_b, metrics_twin)
    assert metrics_b["final_loss"] == pytest.approx(
        metrics_twin["final_loss"], rel=0.05), (metrics_b, metrics_twin)
