"""Example scripts run end-to-end hermetically (standalone demo modes)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_example(*argv, timeout=300):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    env["PYTHONPATH"] = REPO
    proc = subprocess.run(
        [sys.executable, *argv], capture_output=True, text=True,
        timeout=timeout, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, f"stderr:\n{proc.stderr[-2000:]}"
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_word2vec_train_ft_standalone():
    out = run_example("examples/word2vec/train_ft.py")
    assert out["steps"] == 80.0
    assert out["final_loss"] < 7.7  # below uniform log(2074)
    assert out["profile_steady_steps"] == 79.0


def test_mnist_train_then_infer(tmp_path):
    model_dir = str(tmp_path / "ck")
    out = run_example("examples/mnist/train.py", "train",
                      "--steps", "15", "--model-dir", model_dir)
    assert out["steps"] == 15.0
    inf = run_example("examples/mnist/train.py", "infer", "--model-dir", model_dir)
    assert inf["step"] == 15
    assert inf["accuracy"] > 0.9  # synthetic quadrant digits are separable


def test_resnet_train_then_infer(tmp_path):
    """The BASELINE.json vision config's example: elastic ResNet (tiny
    config) trains through coordinator leases, then infer mode restores
    the checkpoint and classifies above chance."""
    model_dir = str(tmp_path / "ck")
    out = run_example("examples/resnet/train.py", "train",
                      "--batch-size", "32", "--batches-per-shard", "4",
                      "--model-dir", model_dir, timeout=420)
    assert out["steps"] == 24.0  # 6 shards x 4 batches
    assert out["final_loss"] < 2.0  # well below uniform log(10) ~ 2.30
    inf = run_example("examples/resnet/train.py", "infer",
                      "--model-dir", model_dir)
    assert inf["accuracy"] > 0.2  # 10 classes; separable patterns


def test_lm_multi_axis_standalone():
    """The transformer-LM capstone: dp x sp x tp mesh with remat + ZeRO-1 +
    multi-pass, through the elastic worker's local twin."""
    out = run_example(
        "examples/lm/train.py",
        "--axes", '{"seq": 2, "model": 2}',
        "--vocab-size", "128", "--d-model", "32", "--n-layers", "2",
        "--d-ff", "64", "--seq-len", "32", "--shards", "2",
        "--batches-per-shard", "2", "--remat", "--zero1",
        "--num-passes", "2", timeout=420,
    )
    assert out["steps"] == 8.0  # 2 shards x 2 batches x 2 passes
    assert out["passes_trained"] == 2.0
    import math

    assert out["final_loss"] < math.log(128) + 0.5  # near-uniform start


def test_ctr_export_then_infer(tmp_path):
    """Reference save-then-infer flow (`ctr/train.py:169-180`): training
    periodically writes the serving artifact; --infer loads and scores."""
    export_dir = str(tmp_path / "serve")
    out = run_example(
        "examples/ctr/train.py",
        "--batch-size", "256", "--batches-per-shard", "3",
        "--sparse-feature-dim", "4096",
        "--export-dir", export_dir, "--export-interval", "4",
        timeout=420,
    )
    assert out["steps"] == 12.0  # 4 shards x 3 batches
    inf = run_example(
        "examples/ctr/train.py", "--infer",
        "--batch-size", "256", "--sparse-feature-dim", "4096",
        "--export-dir", export_dir,
    )
    assert inf["step"] == 12
    assert inf["examples"] == 256
    assert 0.0 < inf["mean_ctr"] < 1.0
    assert inf["logloss"] < 0.69  # better than ln 2 coin-flip


@pytest.mark.parametrize("yaml_path", [
    "examples/fit_a_line/job.yaml",
    "examples/ctr/job.yaml",
    "examples/word2vec/job.yaml",
    "examples/mnist/job.yaml",
    "examples/lm/job.yaml",
    "examples/resnet/job.yaml",
])
def test_job_yamls_pass_admission(yaml_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "edl_tpu", "validate", "-f", yaml_path],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-1000:]


def test_elastic_rebalance_demo():
    """The reference's published experiment (boss_tutorial utilization
    trajectory) reproduced on the hermetic control plane."""
    out = run_example("examples/elastic_demo.py", timeout=180)
    assert out["ok"] is True
    assert out["trajectory"][0] == 0.0
    assert out["trajectory"][-1] > 0.5
    assert len(out["final_trainers"]) == 3
