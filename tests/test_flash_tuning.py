"""Tuned block-size table (ops/flash_tuning) and its kernel wiring."""

import jax.numpy as jnp
import numpy as np

from edl_tpu.ops import flash_attention, flash_tuning
from edl_tpu.parallel.ring_attention import dense_attention


def test_bucket_rounds_down_to_power_of_two():
    assert flash_tuning._bucket(128) == 128
    assert flash_tuning._bucket(1000) == 512
    assert flash_tuning._bucket(1024) == 1024
    assert flash_tuning._bucket(1500) == 1024


def test_lookup_default_when_table_absent(tmp_path):
    path = str(tmp_path / "missing.json")
    flash_tuning._load_table.cache_clear()
    assert flash_tuning.lookup(2048, 64, "bfloat16", path=path) == \
        flash_tuning.DEFAULT_BLOCKS
    flash_tuning._load_table.cache_clear()


def test_save_then_lookup_roundtrip(tmp_path):
    path = str(tmp_path / "blocks.json")
    flash_tuning.save_table(
        {flash_tuning._key(2048, 64, "bfloat16"): (256, 512),
         flash_tuning._key(1024, 64, "any"): (256, 128)},
        {"note": "test"}, path=path,
    )
    flash_tuning._load_table.cache_clear()
    # exact dtype match at the bucket
    assert flash_tuning.lookup(2048, 64, "bfloat16", path=path) == (256, 512)
    # S between buckets falls to the lower bucket's dtype-agnostic entry
    assert flash_tuning.lookup(1500, 64, "bfloat16", path=path) == (256, 128)
    # f32 at 2048 misses the bf16 entry, falls through to 1024's "any"
    assert flash_tuning.lookup(2048, 64, "float32", path=path) == (256, 128)
    # unknown head dim: conservative default
    assert flash_tuning.lookup(2048, 128, "bfloat16", path=path) == \
        flash_tuning.DEFAULT_BLOCKS
    flash_tuning._load_table.cache_clear()


def test_kernel_correct_with_explicit_nondefault_blocks():
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((1, 512, 2, 64)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 512, 2, 64)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, 512, 2, 64)), jnp.float32)
    want = dense_attention(q, k, v, causal=True)
    for bq, bk in ((256, 128), (128, 256), (256, 256)):
        got = flash_attention(q, k, v, causal=True, block_q=bq, block_k=bk)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-3, rtol=2e-3)
