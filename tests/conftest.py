"""Test harness: force an 8-device virtual CPU mesh before JAX import.

Mirrors the reference's testing insight (SURVEY §4): multi-"node" behavior is
tested hermetically on one host — the reference used fake clientsets
(`pkg/client/.../fake`); we use fake cluster providers plus a virtual 8-device
CPU platform so every sharding/collective path compiles and runs without TPUs.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
