"""Test harness: force an 8-device virtual CPU mesh.

Mirrors the reference's testing insight (SURVEY §4): multi-"node" behavior is
tested hermetically on one host — the reference used fake clientsets
(`pkg/client/.../fake`); we use fake cluster providers plus a virtual 8-device
CPU platform so every sharding/collective path compiles and runs without TPUs.

Note: this image's sitecustomize registers the axon TPU-tunnel backend at
interpreter startup and force-selects ``jax_platforms=axon,cpu``, ignoring the
JAX_PLATFORMS env var. Tests must run on CPU (the tunnel serves one real chip
and is slow to dial), so we override the config back *after* import — backends
have not initialized yet at conftest time, so the override takes effect.
"""

import os
import sys

import pytest

#: Applied to every test that spawns real `jax.distributed` worker processes
#: (two+ interpreters doing cross-process collectives over loopback). On this
#: image those processes contend for one shared CPU and miss the bring-up /
#: round deadlines — a pre-existing environment limitation, failing since the
#: seed tree, not a code defect. Opt back in on a host with working loopback
#: multiprocess bring-up via EDL_MULTIPROCESS_TESTS=1.
multiprocess_on_cpu = pytest.mark.skipif(
    not os.environ.get("EDL_MULTIPROCESS_TESTS"),
    reason="two-process jax.distributed bring-up misses its deadlines on this "
    "shared-CPU image (env limitation, red or flaky since seed); set "
    "EDL_MULTIPROCESS_TESTS=1 on a host with working loopback "
    "multiprocess bring-up to run",
)

# XLA_FLAGS is read at backend-init time, which happens after conftest.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
