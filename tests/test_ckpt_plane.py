"""Checkpoint-plane tests: placement, shard codec, zero-blob recovery.

The memory-resident plane must be byte-exact (recovered state EQUALS the
replicated state — deterministic CPU math turns any serialization defect
into a hard inequality), must demote cleanly (any gap -> None -> blob),
and must re-shard across world changes including non-dividing ones
(6 -> 4) through the same spec machinery the blob restore uses.
"""

import logging

import jax
import numpy as np
import pytest

from edl_tpu.ckpt_plane import (
    CkptPlane,
    assemble_leaves,
    chunk_blob,
    leaf_slice,
    owner_key,
    parse_shard,
    placement_map,
    read_placement,
    replica_group,
    serialize_shard,
)
from edl_tpu.coordinator import InProcessCoordinator
from edl_tpu.models import fit_a_line
from edl_tpu.parallel import MeshSpec, build_mesh
from edl_tpu.runtime import Trainer, TrainerConfig
from edl_tpu.runtime.data import SyntheticShardSource, shard_names
from edl_tpu.runtime.elastic import ElasticConfig, ElasticWorker
from edl_tpu.runtime.checkpoint import live_state_specs


def plane_on(coord, name="w0", **kw):
    client = coord.client(name)
    client.register()
    return CkptPlane(client, **kw)


def np_state():
    return {
        "a": np.arange(48, dtype=np.float32).reshape(12, 4),
        "b": np.float32(3.5),  # scalar: owned whole by rank 0
        "c": np.arange(35, dtype=np.int32).reshape(5, 7),  # nothing divides
    }


def np_template():
    return {
        "a": np.zeros((12, 4), np.float32),
        "b": np.float32(0),
        "c": np.zeros((5, 7), np.int32),
    }


# -- placement -----------------------------------------------------------------


def test_replica_group_is_a_ring():
    assert replica_group(0, 4, 1) == [1]
    assert replica_group(3, 4, 2) == [0, 1]  # wraps
    assert replica_group(0, 1, 3) == []  # no peers to hold replicas
    assert replica_group(1, 3, 5) == [2, 0]  # k capped at world - 1


def test_placement_map_covers_every_rank():
    m = placement_map(4, 2)
    assert sorted(m) == [0, 1, 2, 3]
    for r, holders in m.items():
        assert r not in holders and len(holders) == 2


def test_publish_placement_invalidates_previous_epoch():
    coord = InProcessCoordinator()
    plane = plane_on(coord, replicas=2)
    plane.on_epoch(3, world=4, rank=0)
    doc = read_placement(plane.client, 3)
    assert doc["world"] == 4 and doc["groups"][1] == [2, 3]
    plane.on_epoch(4, world=2, rank=0)
    assert read_placement(plane.client, 3) is None
    assert read_placement(plane.client, 4)["world"] == 2


# -- shard codec ---------------------------------------------------------------


def test_leaf_slice_mirrors_zero_shard_layout():
    arr = np.arange(48, dtype=np.float32).reshape(12, 4)
    piece, dim = leaf_slice(arr, 2, 6)
    assert dim == 0
    np.testing.assert_array_equal(piece, arr[4:6])
    # nothing divides -> rank 0 owns the whole leaf, others contribute nothing
    odd = np.arange(35).reshape(5, 7)
    whole, dim = leaf_slice(odd, 0, 6)
    assert dim is None
    np.testing.assert_array_equal(whole, odd)
    assert leaf_slice(odd, 3, 6) == (None, None)


def test_serialize_parse_chunk_roundtrip():
    leaves = list(np_state().values())
    blob = serialize_shard(leaves, step=9, rank=1, world=6)
    manifest, payload = parse_shard(blob)
    assert manifest["step"] == 9 and manifest["world"] == 6
    assert sum(m["nbytes"] for m in manifest["leaves"]) == len(payload)
    # chunking reassembles exactly, and an empty blob still makes one chunk
    import base64

    chunks = chunk_blob(blob, chunk_bytes=16)
    assert b"".join(base64.b64decode(c) for c in chunks) == blob
    assert chunk_blob(b"") == [base64.b64encode(b"").decode("ascii")]


def test_parse_shard_rejects_blob_without_manifest():
    with pytest.raises(ValueError, match="no manifest line"):
        parse_shard(b"raw bytes only, no newline")


def test_assemble_across_non_dividing_world_change_6_to_4():
    """Satellite: shards written under world=6 reassemble into full leaves,
    which re-slice under world=4 exactly as slicing the original would —
    the non-dividing (6 -> 4) rescale path of `zero_shard_spec`'s layout."""
    leaves = list(np_state().values())
    parts = {
        r: parse_shard(serialize_shard(leaves, step=1, rank=r, world=6))
        for r in range(6)
    }
    full = assemble_leaves(parts)
    for orig, got in zip(leaves, full):
        np.testing.assert_array_equal(np.asarray(orig), got)
    # re-shard the reassembled leaves for the new world
    for orig, got in zip(leaves, full):
        for rank in range(4):
            want, wdim = leaf_slice(np.asarray(orig), rank, 4)
            have, hdim = leaf_slice(got, rank, 4)
            assert wdim == hdim
            if want is None:
                assert have is None
            else:
                np.testing.assert_array_equal(want, have)


# -- replicate / restore through the coordinator -------------------------------


def test_replicate_restore_roundtrip_is_byte_exact():
    coord = InProcessCoordinator()
    plane = plane_on(coord, chunk_bytes=64)  # tiny chunks: force batching
    state = np_state()
    info = plane.replicate_all(state, step=7, world=2)
    assert info is not None and info["chunks"] > 2
    restored, rinfo = plane.restore(np_template())
    assert rinfo["step"] == 7 and rinfo["source"] == "peer"
    for k in state:
        np.testing.assert_array_equal(np.asarray(restored[k]), state[k])


def test_restore_reshards_world_6_shards_onto_4_device_mesh():
    """Replicated at plane-world 6, restored onto a 4-device mesh: the spec
    machinery re-shards, training continues, values byte-exact."""
    coord = InProcessCoordinator()
    plane = plane_on(coord)
    model = fit_a_line.MODEL
    mesh8 = build_mesh(MeshSpec({"data": 8}))
    tr8 = Trainer(model, mesh8, TrainerConfig(optimizer="adam",
                                              shard_opt_state=True))
    rng = np.random.default_rng(3)
    state = tr8.init_state()
    state, _ = tr8.train_step(state,
                              tr8.place_batch(model.synthetic_batch(rng, 16)))
    assert plane.replicate_all(state, step=1, world=6) is not None

    mesh4 = build_mesh(MeshSpec({"data": 4}), jax.devices()[:4])
    tr4 = Trainer(model, mesh4, TrainerConfig(optimizer="adam",
                                              shard_opt_state=True))
    fresh = tr4.init_state()
    restored, rinfo = plane.restore(fresh, mesh4, live_state_specs(fresh))
    assert rinfo["world_at_save"] == 6
    for a, b in zip(jax.tree_util.tree_leaves(state),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the restored state actually steps on the new mesh
    tr4.train_step(restored, tr4.place_batch(model.synthetic_batch(rng, 16)))


def test_single_lost_owner_demotes_to_none(caplog):
    coord = InProcessCoordinator()
    plane = plane_on(coord)
    plane.replicate_all(np_state(), step=5, world=4)
    plane.drop_owner(2)
    with caplog.at_level(logging.WARNING, logger="edl_tpu.ckpt_plane"):
        assert plane.restore(np_template()) is None
    assert any("falling back to blob restore" in r.message
               for r in caplog.records)


def test_whole_group_death_demotes_to_none():
    coord = InProcessCoordinator()
    plane = plane_on(coord)
    plane.replicate_all(np_state(), step=5, world=3)
    for r in range(3):
        plane.drop_owner(r)
    assert plane.restore(np_template()) is None


def test_min_step_floor_rejects_stale_plane():
    """The plane must never move training backwards past the blob store."""
    coord = InProcessCoordinator()
    plane = plane_on(coord)
    plane.replicate_all(np_state(), step=5, world=2)
    assert plane.restore(np_template(), min_step=6) is None
    restored, rinfo = plane.restore(np_template(), min_step=5)
    assert rinfo["step"] == 5


def test_duplicate_put_replay_is_idempotent():
    """Re-sending a chunk with the same put_id (transport retry) must not
    corrupt the stored shard; restore stays byte-exact."""
    coord = InProcessCoordinator()
    plane = plane_on(coord, chunk_bytes=64)
    state = np_state()
    plane.replicate_all(state, step=3, world=2)
    meta = plane.client.shard_meta(owner_key(0))
    reply = plane.client.shard_put(
        owner_key(0), 3, 0, int(meta["chunks"]), "Z0JBRA==",  # wrong payload
        put_id="z0.s3.c0",  # ...but a replayed id: must dedup, not overwrite
    )
    assert reply.get("ok") and reply.get("duplicate")
    restored, _ = plane.restore(np_template())
    for k in state:
        np.testing.assert_array_equal(np.asarray(restored[k]), state[k])


def test_stale_step_put_does_not_regress_latest():
    coord = InProcessCoordinator()
    plane = plane_on(coord)
    plane.replicate_all(np_state(), step=9, world=2)
    old = {"a": np.ones((12, 4), np.float32), "b": np.float32(0),
           "c": np.zeros((5, 7), np.int32)}
    plane.replicate_all(old, step=4, world=2)  # late-arriving stale writer
    restored, rinfo = plane.restore(np_template())
    assert rinfo["step"] == 9
    np.testing.assert_array_equal(np.asarray(restored["a"]),
                                  np_state()["a"])


def test_ckpt_plane_rejects_zero_replicas():
    coord = InProcessCoordinator()
    with pytest.raises(ValueError, match="replicas"):
        plane_on(coord, replicas=0)


# -- worker integration --------------------------------------------------------


def test_elastic_worker_replicates_then_peer_restores(tmp_path):
    """e2e: a plane-enabled worker covers its checkpoints with peer shards;
    a successor worker restores from coordinator memory, not the blob."""
    coord = InProcessCoordinator(task_lease_sec=60.0, heartbeat_ttl_sec=60.0)
    model = fit_a_line.MODEL
    admin = coord.client("admin")
    admin.add_tasks(shard_names("fit", 3))
    cfg = ElasticConfig(
        checkpoint_dir=str(tmp_path / "ck"),
        checkpoint_interval=4,
        heartbeat_interval=0.0,
        trainer=TrainerConfig(optimizer="sgd", learning_rate=0.05),
        peer_replicas=1,
    )
    w1 = ElasticWorker(model, coord.client("trainer-0"),
                       SyntheticShardSource(model, batch_size=8,
                                            batches_per_shard=4), cfg)
    w1.run()
    # the final checkpoint was covered by a complete plane shard — probe
    # with an UNregistered client: a registered bystander would join the
    # membership and stall w2's rescale sync barrier until it times out
    meta = coord.client("probe").shard_meta(owner_key(0))
    assert meta.get("found") and meta.get("complete"), meta

    # explicit leave in lieu of waiting out the heartbeat TTL: a lingering
    # trainer-0 membership would park w2's epoch sync until it times out
    coord.client("trainer-0").leave()
    admin.add_tasks(shard_names("more", 2))
    w2 = ElasticWorker(model, coord.client("trainer-1"),
                       SyntheticShardSource(model, batch_size=8,
                                            batches_per_shard=4), cfg)
    w2.run()
    assert w2._last_restore["source"] == "peer", w2._last_restore
    assert w2._last_restore["bytes"] > 0
