"""bench.py backend-init retry loop — no real backend dialing.

The single 300 s init window used to convert a transient tunnel flap into
a bare 0.0 artifact; the retry loop must instead either succeed late or
fail with the full per-attempt history in the record.
"""

import time

import bench

_FLAP = "accelerator backend unavailable: flap"


def test_retry_succeeds_after_flap(monkeypatch):
    calls = {"n": 0}

    def fake_probe(init_timeout, allow_cpu):
        calls["n"] += 1
        if calls["n"] < 3:
            return None, _FLAP
        return ["dev0"], None

    sleeps = []
    monkeypatch.setattr(bench, "probe_devices", fake_probe)
    monkeypatch.setattr(time, "sleep", sleeps.append)
    monkeypatch.setenv("EDL_BENCH_INIT_BUDGET_S", "1500")

    devices, attempts, reason = bench.probe_devices_with_retry(allow_cpu=True)
    assert devices == ["dev0"]
    assert reason is None
    assert [a["outcome"] for a in attempts] == [_FLAP, _FLAP, "ok"]
    assert all("at_unix" in a and "elapsed_s" in a for a in attempts)
    assert sleeps == [15.0, 22.5]  # geometric backoff between attempts


def test_retry_exhausts_budget_with_attempt_history(monkeypatch):
    def fake_probe(init_timeout, allow_cpu):
        return None, _FLAP

    monkeypatch.setattr(bench, "probe_devices", fake_probe)
    monkeypatch.setattr(time, "sleep", lambda s: None)
    # first backoff (15 s) already exceeds the budget: exactly one attempt
    monkeypatch.setenv("EDL_BENCH_INIT_BUDGET_S", "10")

    devices, attempts, reason = bench.probe_devices_with_retry(allow_cpu=True)
    assert devices is None
    assert reason == _FLAP
    assert len(attempts) == 1
    assert attempts[0]["outcome"] == _FLAP


def test_attempt_window_clamps_to_remaining_budget(monkeypatch):
    seen = []

    def fake_probe(init_timeout, allow_cpu):
        seen.append(init_timeout)
        return ["dev0"], None

    monkeypatch.setattr(bench, "probe_devices", fake_probe)
    monkeypatch.setenv("EDL_BENCH_INIT_BUDGET_S", "120")
    monkeypatch.setenv("EDL_BENCH_INIT_TIMEOUT", "300")

    devices, attempts, _ = bench.probe_devices_with_retry(allow_cpu=True)
    assert devices == ["dev0"]
    # per-attempt window never exceeds what's left of the total budget
    assert seen[0] <= 120.0
