"""DevicePrefetcher + pipelined hot loop + rescale warm-compile coverage.

The contract under test (`edl_tpu/runtime/pipeline.py`): source order is
preserved at any depth, exceptions (including WireRestartRequired and a
rescale SystemExit) re-raise in the consumer, an abandoned consumer leaks
no pump threads, and placement of batch N+1 genuinely overlaps step N
(the CPU-only overlap assertion with an instrumented slow source + slow
fake step). Plus the trainer-level integrations: pipelined `Trainer.run`
matches the synchronous loop, and `warm_compile` hands the first step a
ready executable.
"""

import threading
import time

import jax
import numpy as np
import pytest

from edl_tpu.models import fit_a_line
from edl_tpu.parallel import local_mesh
from edl_tpu.runtime import Trainer, TrainerConfig
from edl_tpu.runtime.pipeline import DevicePrefetcher, PlacedItem
from edl_tpu.runtime.wire import WireRestartRequired

PUMP_PREFIX = "edl-place-pump"


def pump_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith(PUMP_PREFIX) and t.is_alive()]


def assert_no_leaked_pumps():
    deadline = time.monotonic() + 5.0
    while pump_threads() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert not pump_threads(), threading.enumerate()


# -- pump contract -------------------------------------------------------------


def test_ordering_preserved_at_depth_3():
    items = [{"x": np.full((4, 1), i)} for i in range(20)]
    out = [item for item in DevicePrefetcher(items, depth=3)]
    assert [int(i.payload["x"][0, 0]) for i in out] == list(range(20))
    assert all(isinstance(i, PlacedItem) and i.samples == 4 for i in out)
    assert_no_leaked_pumps()


def test_place_fn_runs_on_pump_and_times_itself():
    pump_names = set()

    def place(batch):
        pump_names.add(threading.current_thread().name)
        time.sleep(0.01)
        return ("placed", batch)

    items = [{"x": np.zeros((2, 1))} for _ in range(5)]
    out = list(DevicePrefetcher(items, place, depth=2))
    assert all(i.payload[0] == "placed" for i in out)
    assert all(i.place_seconds >= 0.005 for i in out)
    assert all(n.startswith(PUMP_PREFIX) for n in pump_names)
    assert_no_leaked_pumps()


def test_source_exception_reraises_in_consumer():
    def source():
        yield {"x": np.zeros((2, 1))}
        raise WireRestartRequired("sparse_id")

    got = []
    with pytest.raises(WireRestartRequired):
        for item in DevicePrefetcher(source(), depth=2):
            got.append(item)
    assert len(got) == 1
    assert_no_leaked_pumps()


def test_place_fn_exception_reraises_in_consumer():
    def place(batch):
        raise ValueError("bad placement")

    with pytest.raises(ValueError, match="bad placement"):
        list(DevicePrefetcher([{"x": np.zeros((2, 1))}], place, depth=2))
    assert_no_leaked_pumps()


def test_rescale_system_exit_relays_to_consumer():
    def source():
        yield {"x": np.zeros((2, 1))}
        raise SystemExit(42)

    with pytest.raises(SystemExit) as e:
        list(DevicePrefetcher(source(), depth=1))
    assert e.value.code == 42
    assert_no_leaked_pumps()


def test_early_break_shuts_pump_down():
    """Abandoning the iterator (rescale interrupt / exception in the training
    loop) must stop and join the pump — no leaked threads, no parked put."""

    def source():
        for i in range(10_000):
            yield {"x": np.full((2, 1), i)}

    pf = DevicePrefetcher(source(), depth=2)
    for item in pf:
        break  # generator finalizer -> close() -> pump joined
    assert_no_leaked_pumps()


def test_close_is_idempotent_and_reentrant():
    pf = DevicePrefetcher([{"x": np.zeros((2, 1))}], depth=1)
    pf.close()
    pf.close()
    assert list(pf) == []  # closed stream ends cleanly
    assert_no_leaked_pumps()


def test_early_source_return_drains_cleanly():
    """A source that ends early (LeaseReader hitting a rescale interrupt)
    ends the stream normally; already-placed batches are still delivered."""

    def source():
        yield {"x": np.full((2, 1), 0)}
        yield {"x": np.full((2, 1), 1)}
        return  # interrupted: lease failed back, replay covers the rest

    out = list(DevicePrefetcher(source(), depth=4))
    assert [int(i.payload["x"][0, 0]) for i in out] == [0, 1]
    assert_no_leaked_pumps()


def test_overlap_pipelined_faster_than_sync():
    """The tentpole's point, proven on CPU with an instrumented slow source
    and a slow fake step: wall(pipelined) < wall(sync) - 0.5 * total
    placement time, i.e. placement of batch N+1 overlapped step N."""
    n, place_s, step_s = 15, 0.02, 0.02

    def place(batch):
        time.sleep(place_s)  # stands in for wire encode + H2D transfer
        return batch

    def step(batch):
        time.sleep(step_s)  # stands in for dispatched device compute

    batches = [{"x": np.zeros((2, 1))} for _ in range(n)]

    t0 = time.perf_counter()
    place_total = 0.0
    for item in DevicePrefetcher(batches, place, depth=2):
        step(item.payload)
        place_total += item.place_seconds
    pipe_wall = time.perf_counter() - t0

    t0 = time.perf_counter()
    for batch in batches:
        step(place(batch))
    sync_wall = time.perf_counter() - t0

    assert pipe_wall < sync_wall - 0.5 * place_total, (
        f"pipelined {pipe_wall:.3f}s vs sync {sync_wall:.3f}s "
        f"(placement total {place_total:.3f}s): no overlap"
    )
    assert_no_leaked_pumps()


# -- prefetch_iter delegation --------------------------------------------------


def test_prefetch_iter_yields_raw_items_and_relays_errors():
    from edl_tpu.runtime.data import prefetch_iter

    assert list(prefetch_iter(iter(range(7)))) == list(range(7))

    def source():
        yield 0
        raise SystemExit(3)

    it = prefetch_iter(source())
    assert next(it) == 0
    with pytest.raises(SystemExit):
        next(it)
    assert_no_leaked_pumps()


# -- trainer integration -------------------------------------------------------


def _batches(model, rng, batch_size, n):
    for _ in range(n):
        yield model.synthetic_batch(rng, batch_size)


def test_trainer_run_pipelined_matches_sync():
    model = fit_a_line.MODEL
    mesh = local_mesh()

    def losses(depth):
        trainer = Trainer(model, mesh,
                          TrainerConfig(optimizer="sgd", learning_rate=0.1))
        rng = np.random.default_rng(0)
        _, metrics = trainer.run(
            trainer.init_state(), _batches(model, rng, 64, 30),
            pipeline_depth=depth,
        )
        return metrics

    sync, piped = losses(0), losses(2)
    assert piped["steps"] == sync["steps"] == 30
    np.testing.assert_allclose(piped["final_loss"], sync["final_loss"],
                               rtol=1e-5)
    assert piped["place_seconds"] > 0
    assert_no_leaked_pumps()


def test_trainer_run_pipelined_wire_transport():
    """Wire encode happens on the pump; the bound step callable routes each
    batch to the codec generation that encoded it."""
    model = fit_a_line.MODEL
    trainer = Trainer(model, local_mesh(),
                      TrainerConfig(optimizer="sgd", learning_rate=0.1,
                                    wire_transport=True, pipeline_depth=2))
    rng = np.random.default_rng(0)
    _, metrics = trainer.run(trainer.init_state(),
                             _batches(model, rng, 64, 20))
    assert metrics["steps"] == 20
    assert np.isfinite(metrics["final_loss"])
    assert metrics["retraces"] == 0
    assert_no_leaked_pumps()


def test_warm_compile_preempts_first_step_compile():
    model = fit_a_line.MODEL
    mesh = local_mesh()
    trainer = Trainer(model, mesh,
                      TrainerConfig(optimizer="sgd", learning_rate=0.1))
    state = trainer.init_state()
    batch = model.synthetic_batch(np.random.default_rng(0), 64)
    avals = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
             for k, v in batch.items()}

    seconds = trainer.warm_compile(state, avals)
    assert seconds > 0 and trainer._warm is not None

    placed = trainer.place_batch(batch)
    assert trainer._step_callable(placed) == trainer._warm_step
    state2, loss = trainer.train_step(state, placed)
    # the warm executable ran: the lazy jit's dispatch cache is still empty
    size = trainer._jit_cache_size()
    if size is not None:
        assert size == 0

    # matches the plain-jit trainer bit-for-bit on the same inputs
    ref = Trainer(model, mesh,
                  TrainerConfig(optimizer="sgd", learning_rate=0.1))
    rstate, rloss = ref.train_step(ref.init_state(), ref.place_batch(batch))
    np.testing.assert_allclose(float(loss), float(rloss), rtol=1e-6)
    assert int(state2.step) == int(rstate.step) == 1


def test_warm_step_retires_on_shape_mismatch():
    """A batch the warm executable was not specialized to must fall back to
    the lazy jit (signature mismatch -> plain path; executable rejection ->
    retire + retry), never crash the loop."""
    model = fit_a_line.MODEL
    trainer = Trainer(model, local_mesh(),
                      TrainerConfig(optimizer="sgd", learning_rate=0.1))
    state = trainer.init_state()
    rng = np.random.default_rng(0)
    b64 = model.synthetic_batch(rng, 64)
    trainer.warm_compile(
        state, {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                for k, v in b64.items()})
    other = trainer.place_batch(model.synthetic_batch(rng, 32))
    assert trainer._step_callable(other) == trainer._jit_step
    state, loss = trainer.train_step(state, other)  # lazy-jit path
    assert np.isfinite(float(loss))


def test_cache_probe_unavailability_memoized():
    model = fit_a_line.MODEL
    trainer = Trainer(model, local_mesh(),
                      TrainerConfig(optimizer="sgd", learning_rate=0.1))
    # Simulate a JAX version without the private API: one probe flips the
    # memo, after which check_retrace never reflects again.
    trainer._jit_step = object()  # no _cache_size attribute
    assert trainer._jit_cache_size() is None
    assert trainer._cache_probe_broken
    assert trainer.check_retrace(5) is False
