"""Chaos e2e: SIGKILL random trainer pods mid-run; both jobs still converge.

The closest this image gets to a minikube soak (VERDICT r4 weak #4): two
real training jobs on a ProcessCluster, each of whose pods is killed
without warning mid-queue — no SIGTERM, no drain, no termination log, the
whole process group at once (a node crash / OOM kill). Recovery is the
production path end to end: the dead worker's membership and leases expire
by TTL, the Job-controller reconcile (`ProcessCluster.restart_failed`)
spawns a replacement pod, whose launcher gates on the failure budget,
whose worker re-registers under a fresh name, restores the durable
checkpoint, re-leases the requeued shards, and drains the queue.

Timing notes: one CPU core (see .claude/skills/verify) — generous lease
TTLs absorb first-jit compile stalls; the kill lands only after observed
progress so a checkpoint exists to restore.
"""

import json
import random
import sys
import time

import pytest

from conftest import multiprocess_on_cpu
from edl_tpu.api.quantity import ResourceList
from edl_tpu.api.types import TrainingJob
from edl_tpu.api.validation import normalize
from edl_tpu.controller.cluster import NodeInfo
from edl_tpu.controller.jobparser import parse_to_trainer
from edl_tpu.controller.process_cluster import ProcessCluster
from edl_tpu.coordinator import CoordinatorServer
from edl_tpu.coordinator.server import ensure_built, free_port

from tests.test_actuation import LAUNCHER_SRC
from tests.test_multihost import REPO, WORKER_SRC

N_SHARDS = 8


def _job(name, server, entry, launcher, ckpt, tmp_path):
    return normalize(TrainingJob.from_dict({
        "metadata": {"name": name},
        "spec": {
            "fault_tolerant": True,
            "tpu": {"chips_per_trainer": 4},
            "trainer": {
                "min_instance": 1,
                "max_instance": 1,
                "entrypoint": f"{sys.executable} {launcher}",
                "resources": {"requests": {"cpu": 1}},
                "env": {
                    "EDL_COORDINATOR_ENDPOINT": server.address,
                    "EDL_ENTRY": f"{sys.executable} {entry}",
                    "CKPT_DIR": ckpt,
                    "CKPT_INTERVAL": "2",  # durable early: the kill must
                    # find a checkpoint to restore
                    "MODEL": "ctr_small",
                    "BATCHES_PER_SHARD": "4",
                    "BATCH_SLEEP": "0.1",  # paces the queue so the kill
                    # lands mid-run, not post-drain
                    "PYTHONUNBUFFERED": "1",
                    "EDL_TERMINATION_LOG": str(tmp_path / f"term-{name}"),
                },
            },
        },
    }))


@pytest.mark.chaos
@multiprocess_on_cpu
def test_two_jobs_survive_random_pod_kills(tmp_path):
    ensure_built()
    rng = random.Random(0)
    launcher_py = tmp_path / "launcher.py"
    launcher_py.write_text(LAUNCHER_SRC.format(repo=REPO))
    names = ("alpha", "beta")
    ports = {n: free_port() for n in names}
    entries = {}
    for n in names:
        p = tmp_path / f"entry_{n}.py"
        p.write_text(WORKER_SRC.format(repo=REPO, jax_port=ports[n]))
        entries[n] = p

    # Short member TTL: the killed pod's leases requeue when its heartbeats
    # stop; task leases stay long (renewed by heartbeats) so compile stalls
    # never look like failures.
    servers = {
        n: CoordinatorServer(task_lease_sec=120.0, heartbeat_ttl_sec=15.0)
        for n in names
    }
    admins = {}
    cluster = ProcessCluster(
        [NodeInfo(name=f"h{i}",
                  allocatable=ResourceList.make({"cpu": 16, "tpu": 4}))
         for i in range(2)],
        log_dir=str(tmp_path / "logs"),
    )
    try:
        for n in names:
            servers[n].start()
            admins[n] = servers[n].client("admin")
            admins[n].add_tasks([f"{n}/part-{i:05d}" for i in range(N_SHARDS)])
            job = _job(n, servers[n], entries[n], launcher_py,
                       str(tmp_path / f"ck-{n}"), tmp_path)
            trainer = parse_to_trainer(job)
            cluster.create_role(n, "trainer", 1, trainer.requests,
                                trainer.limits, workload=trainer)

        # wait for real progress on both queues, then the chaos strikes
        deadline = time.time() + 300
        killed = {}
        while time.time() < deadline:
            if all(int(admins[n].status().get("done", 0)) >= 2
                   for n in names):
                break
            time.sleep(0.5)
        else:
            pytest.fail({n: admins[n].status() for n in names})

        for n in names:
            pods = [p for p in cluster.job_pods(n, "trainer")
                    if p.phase == "Running"]
            victim = rng.choice(pods)
            cluster.kill_pod(victim.name)
            killed[n] = victim.name
        assert all(
            any(p.phase == "Failed" for p in cluster.job_pods(n, "trainer"))
            for n in names
        )
        # nothing drains while the pods are dead and unreplaced
        assert any(int(admins[n].status()["queued"]) > 0
                   or int(admins[n].status()["leased"]) > 0 for n in names)

        # the Job controller notices and replaces (staggered, like real
        # reconcile loops)
        for n in names:
            assert cluster.restart_failed(n) == 1
            time.sleep(1.0)

        # both jobs drain to completion through the replacement pods
        try:
            cluster.wait_all(timeout=420)
        except Exception:
            pods = [(p.info.name, p.info.phase) for p in cluster.pods]
            pytest.fail(
                f"jobs never drained after chaos: "
                f"{ {n: admins[n].status() for n in names} } pods={pods}"
            )
        for n in names:
            st = admins[n].status()
            assert int(st["queued"]) == 0 and int(st["leased"]) == 0, (n, st)
            assert int(st["done"]) == N_SHARDS, (n, st)
            pods = cluster.job_pods(n, "trainer")
            assert len(pods) == 1 and pods[0].phase == "Succeeded", (n, pods)
            assert pods[0].name != killed[n]  # it IS the replacement
    finally:
        cluster.shutdown()
        for s in servers.values():
            s.stop()

    # the replacement worker really trained (restored + drained the rest):
    # every pod log's last METRICS line reports steps > 0 at world 1
    finals = {}
    for log_file in (tmp_path / "logs").iterdir():
        lines = [l for l in log_file.read_text().splitlines()
                 if l.startswith("METRICS ")]
        if lines:
            finals[log_file.name] = json.loads(lines[-1][len("METRICS "):])
    for n in names:
        rep = [m for f, m in finals.items()
               if f.startswith(f"{n}-trainer") and not f.startswith(killed[n])]
        assert any(m["world"] == 1.0 and m["steps"] > 0 for m in rep), finals
