"""Train-loop tests: every model family steps and learns on the 8-dev mesh."""

import jax
import numpy as np
import pytest

from edl_tpu.models import ctr, fit_a_line, mnist, word2vec
from edl_tpu.parallel import MeshSpec, build_mesh, local_mesh
from edl_tpu.runtime import Trainer, TrainerConfig


def batches(model, rng, batch_size, n):
    for _ in range(n):
        yield model.synthetic_batch(rng, batch_size)


def test_fit_a_line_converges():
    mesh = local_mesh()
    trainer = Trainer(fit_a_line.MODEL, mesh, TrainerConfig(optimizer="sgd", learning_rate=0.1))
    state = trainer.init_state()
    rng = np.random.default_rng(0)
    state, metrics = trainer.run(state, batches(fit_a_line.MODEL, rng, 64, 200))
    assert metrics["final_loss"] < 0.05, metrics
    # learned weights approach the generating ones
    w = np.asarray(state.params["w"]).ravel()
    np.testing.assert_allclose(w, fit_a_line._TRUE_W, atol=0.1)


def test_ctr_deep_wide_steps_and_descends():
    mesh = local_mesh()
    trainer = Trainer(ctr.MODEL, mesh, TrainerConfig(optimizer="adagrad", learning_rate=0.05))
    state = trainer.init_state()
    rng = np.random.default_rng(1)
    state, metrics = trainer.run(state, batches(ctr.MODEL, rng, 32, 8))
    assert np.isfinite(metrics["final_loss"])
    assert metrics["final_loss"] < metrics["mean_loss"] + 0.1  # not diverging
    # sparse tables sharded: 8 shards of the padded vocab
    table = state.params["deep_table"]
    assert table.shape[0] % 8 == 0
    assert int(state.step) == 8


def test_ctr_on_multiaxis_mesh():
    """CTR with a dedicated expert axis: table sharded 4-way, batch 2-way."""
    mesh = build_mesh(MeshSpec({"data": 2, "expert": 4}))
    model = ctr.make_model(shard_axis="expert", batch_axis="data", sparse_dim=10007)
    trainer = Trainer(model, mesh, TrainerConfig())
    state = trainer.init_state()
    rng = np.random.default_rng(2)
    state, metrics = trainer.run(state, batches(model, rng, 16, 2))
    assert np.isfinite(metrics["final_loss"])
    assert state.params["deep_table"].shape[0] == 10240  # rescale-stable padding


def test_word2vec_steps():
    mesh = local_mesh()
    trainer = Trainer(word2vec.MODEL, mesh, TrainerConfig(learning_rate=1e-2))
    state = trainer.init_state()
    rng = np.random.default_rng(3)
    state, metrics = trainer.run(state, batches(word2vec.MODEL, rng, 64, 10))
    assert np.isfinite(metrics["final_loss"])
    assert metrics["final_loss"] < np.log(word2vec.VOCAB) + 1.0


def test_mnist_learns_synthetic_digits():
    mesh = local_mesh()
    trainer = Trainer(mnist.MODEL, mesh, TrainerConfig(learning_rate=1e-3))
    state = trainer.init_state()
    rng = np.random.default_rng(4)
    first_loss = None

    def on_step(i, loss):
        nonlocal first_loss
        if i == 1:
            first_loss = loss

    state, metrics = trainer.run(
        state, batches(mnist.MODEL, rng, 64, 30), on_step=on_step
    )
    assert metrics["final_loss"] < first_loss * 0.7, (first_loss, metrics)
    test_batch = mnist.MODEL.synthetic_batch(rng, 256)
    acc = float(
        jax.jit(mnist.accuracy)(state.params, trainer.place_batch(test_batch))
    )
    assert acc > 0.5, acc  # far above the 0.1 random baseline


def test_zero1_sharded_opt_state_matches_replicated():
    """ZeRO-1 (shard_opt_state): moments shard over the data axis — each
    chip holds 1/N — while the training trajectory stays identical to the
    replicated-optimizer run, and the sharding survives the jitted update
    (donated buffers keep the layout step over step)."""
    from jax.sharding import NamedSharding

    from edl_tpu.models import transformer
    from edl_tpu.parallel import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec({"data": 8}))
    model = transformer.make_model(
        vocab_size=64, d_model=32, n_layers=2, n_heads=8, d_ff=64, seq_len=16
    )
    rng = np.random.default_rng(0)
    batches = [model.synthetic_batch(rng, 8) for _ in range(3)]

    losses = {}
    final_states = {}
    for tag, zero1 in (("rep", False), ("zero1", True)):
        trainer = Trainer(
            model, mesh,
            TrainerConfig(optimizer="adam", learning_rate=1e-3,
                          shard_opt_state=zero1),
        )
        state = trainer.init_state()
        ls = []
        for b in batches:
            state, loss = trainer.train_step(state, trainer.place_batch(b))
            ls.append(float(loss))
        losses[tag] = ls
        final_states[tag] = state

    # identical math
    assert losses["rep"] == pytest.approx(losses["zero1"], rel=1e-6)

    def shardable(leaf):
        return (
            getattr(leaf, "ndim", 0) > 0
            and any(s > 0 and s % 8 == 0 for s in leaf.shape)
        )

    def sharded_flags(state):
        """is-sharded flag for every moment tensor that COULD shard."""
        out = []
        for leaf in jax.tree_util.tree_leaves(state.opt_state):
            sh = getattr(leaf, "sharding", None)
            if isinstance(sh, NamedSharding) and shardable(leaf):
                out.append(any(s is not None for s in sh.spec))
        return out

    # replicated run: every moment fully replicated; zero1 run: EVERY moment
    # with a divisible dim is sharded — a partial fallback to replication
    # would silently forfeit the HBM savings.
    assert not any(sharded_flags(final_states["rep"]))
    z = sharded_flags(final_states["zero1"])
    assert z and all(z), f"moments fell back to replicated: {z}"
    # ...the layout survived 3 donated jitted updates (not just init), and
    # params themselves stay replicated (ZeRO-1, not ZeRO-3)
    for p in jax.tree_util.tree_leaves(final_states["zero1"].params):
        sh = getattr(p, "sharding", None)
        if isinstance(sh, NamedSharding):
            assert all(s is None for s in sh.spec)
