"""Train-loop tests: every model family steps and learns on the 8-dev mesh."""

import jax
import numpy as np
import pytest

from edl_tpu.models import ctr, fit_a_line, mnist, word2vec
from edl_tpu.parallel import MeshSpec, build_mesh, local_mesh
from edl_tpu.runtime import Trainer, TrainerConfig


def batches(model, rng, batch_size, n):
    for _ in range(n):
        yield model.synthetic_batch(rng, batch_size)


def test_fit_a_line_converges():
    mesh = local_mesh()
    trainer = Trainer(fit_a_line.MODEL, mesh, TrainerConfig(optimizer="sgd", learning_rate=0.1))
    state = trainer.init_state()
    rng = np.random.default_rng(0)
    state, metrics = trainer.run(state, batches(fit_a_line.MODEL, rng, 64, 200))
    assert metrics["final_loss"] < 0.05, metrics
    # learned weights approach the generating ones
    w = np.asarray(state.params["w"]).ravel()
    np.testing.assert_allclose(w, fit_a_line._TRUE_W, atol=0.1)


def test_ctr_deep_wide_steps_and_descends():
    mesh = local_mesh()
    trainer = Trainer(ctr.MODEL, mesh, TrainerConfig(optimizer="adagrad", learning_rate=0.05))
    state = trainer.init_state()
    rng = np.random.default_rng(1)
    state, metrics = trainer.run(state, batches(ctr.MODEL, rng, 32, 8))
    assert np.isfinite(metrics["final_loss"])
    assert metrics["final_loss"] < metrics["mean_loss"] + 0.1  # not diverging
    # sparse tables sharded: 8 shards of the padded vocab
    table = state.params["deep_table"]
    assert table.shape[0] % 8 == 0
    assert int(state.step) == 8


def test_ctr_on_multiaxis_mesh():
    """CTR with a dedicated expert axis: table sharded 4-way, batch 2-way."""
    mesh = build_mesh(MeshSpec({"data": 2, "expert": 4}))
    model = ctr.make_model(shard_axis="expert", batch_axis="data", sparse_dim=10007)
    trainer = Trainer(model, mesh, TrainerConfig())
    state = trainer.init_state()
    rng = np.random.default_rng(2)
    state, metrics = trainer.run(state, batches(model, rng, 16, 2))
    assert np.isfinite(metrics["final_loss"])
    assert state.params["deep_table"].shape[0] == 10240  # rescale-stable padding


def test_word2vec_steps():
    mesh = local_mesh()
    trainer = Trainer(word2vec.MODEL, mesh, TrainerConfig(learning_rate=1e-2))
    state = trainer.init_state()
    rng = np.random.default_rng(3)
    state, metrics = trainer.run(state, batches(word2vec.MODEL, rng, 64, 10))
    assert np.isfinite(metrics["final_loss"])
    assert metrics["final_loss"] < np.log(word2vec.VOCAB) + 1.0


def test_mnist_learns_synthetic_digits():
    mesh = local_mesh()
    trainer = Trainer(mnist.MODEL, mesh, TrainerConfig(learning_rate=1e-3))
    state = trainer.init_state()
    rng = np.random.default_rng(4)
    first_loss = None

    def on_step(i, loss):
        nonlocal first_loss
        if i == 1:
            first_loss = loss

    state, metrics = trainer.run(
        state, batches(mnist.MODEL, rng, 64, 30), on_step=on_step
    )
    assert metrics["final_loss"] < first_loss * 0.7, (first_loss, metrics)
    test_batch = mnist.MODEL.synthetic_batch(rng, 256)
    acc = float(
        jax.jit(mnist.accuracy)(state.params, trainer.place_batch(test_batch))
    )
    assert acc > 0.5, acc  # far above the 0.1 random baseline
