"""Controller-stack tests: store watch semantics, job materialization order,
the per-job phase machine with fault-tolerance rules, deletion GC, and the
controller+autoscaler integration — the controller-loop tests the reference's
fake clientset machinery was built for but never grew
(`pkg/client/clientset/versioned/fake/clientset_generated.go:32-69`).
"""

import time

import pytest

from edl_tpu.api import ResourceList, TrainingJob
from edl_tpu.api.types import JobPhase
from edl_tpu.controller import (
    Controller,
    FakeCluster,
    JobStore,
    NodeInfo,
    ROLE_COORDINATOR,
    ROLE_TRAINER,
    UpdaterConfig,
    make_env,
    parse_job,
)
from edl_tpu.controller.autoscaler import AutoscalerConfig


FAST = UpdaterConfig(convert_seconds=0.05, poll_seconds=0.02, create_timeout=5.0)


def make_job_dict(name, min_i=1, max_i=1, chips=0, cpu="1", mem="1Gi",
                  fault_tolerant=False):
    return {
        "metadata": {"name": name},
        "spec": {
            "image": "edl-tpu:test",
            "fault_tolerant": fault_tolerant,
            "tpu": {"chips_per_trainer": chips},
            "trainer": {
                "entrypoint": "python train.py",
                "min_instance": min_i,
                "max_instance": max_i,
                "resources": {
                    "requests": {"cpu": cpu, "memory": mem},
                    "limits": {"cpu": cpu, "memory": mem},
                },
            },
        },
    }


def nodes(n=2, cpu=8, mem_gi=32, tpu=8):
    return [
        NodeInfo(
            name=f"host{i}",
            allocatable=ResourceList.make({"cpu": cpu, "memory": f"{mem_gi}Gi", "tpu": tpu}),
        )
        for i in range(n)
    ]


def wait_until(pred, timeout=5.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


@pytest.fixture
def controller():
    cluster = FakeCluster(nodes())
    ctl = Controller(
        cluster,
        store=JobStore(),
        autoscaler_config=AutoscalerConfig(loop_seconds=0.05, max_load_desired=0.97),
        updater_config=FAST,
    )
    ctl.start()
    yield ctl
    ctl.stop()


# -- JobStore ----------------------------------------------------------------


class TestJobStore:
    def test_crud_and_watch_replay(self):
        store = JobStore()
        job = TrainingJob.from_dict(make_job_dict("a"))
        store.create(job)
        assert store.get("a").name == "a"
        with pytest.raises(KeyError):
            store.create(job)

        seen = []
        from edl_tpu.controller import FuncWatcher

        store.watch(FuncWatcher(on_add=lambda j: seen.append(j.name)), replay=True)
        assert seen == ["a"]  # informer initial-list replay

        store.delete("a")
        assert store.list() == []
        with pytest.raises(KeyError):
            store.get("a")

    def test_status_is_a_subresource(self):
        """update() must not clobber stored status; update_status must."""
        store = JobStore()
        job = TrainingJob.from_dict(make_job_dict("a"))
        store.create(job)
        st = store.get("a").status
        st.phase = JobPhase.RUNNING
        store.update_status("a", st)

        newer = store.get("a")
        newer.status.phase = JobPhase.NONE  # caller's copy, should be ignored
        newer.spec.passes = 7
        store.update(newer)
        got = store.get("a")
        assert got.spec.passes == 7
        assert got.status.phase == JobPhase.RUNNING

    def test_copies_are_isolated(self):
        store = JobStore()
        store.create(TrainingJob.from_dict(make_job_dict("a")))
        j1 = store.get("a")
        j1.spec.image = "mutated"
        assert store.get("a").spec.image == "edl-tpu:test"


# -- job parser / env protocol ------------------------------------------------


class TestJobParser:
    def test_creation_order_and_env(self):
        from edl_tpu.api.validation import normalize

        job = normalize(TrainingJob.from_dict(make_job_dict("ctr", min_i=2, max_i=4, chips=4)))
        workloads = parse_job(job)
        assert [w.role for w in workloads] == [ROLE_COORDINATOR, ROLE_TRAINER]
        trainer = workloads[1]
        assert trainer.replicas == 2  # starts at min_instance
        assert trainer.requests.get_q("tpu") == 4.0

        env = make_env(job, ROLE_TRAINER)
        assert env["EDL_JOB_NAME"] == "ctr"
        assert env["EDL_COORDINATOR_ENDPOINT"] == "ctr-coordinator.default:7164"
        assert env["EDL_FAULT_TOLERANT"] == "1"  # elastic ⇒ fault tolerant
        assert env["EDL_ENTRY"] == "python train.py"
        # Rank-free by design: ranks are leased from the coordinator.
        assert not any(k.endswith("TRAINER_ID") for k in env)

    def test_user_env_wins(self):
        from edl_tpu.api.validation import normalize

        d = make_job_dict("a")
        d["spec"]["trainer"]["env"] = {"EDL_PASSES": "99", "CUSTOM": "x"}
        env = make_env(normalize(TrainingJob.from_dict(d)), ROLE_TRAINER)
        assert env["EDL_PASSES"] == "99"
        assert env["CUSTOM"] == "x"


# -- controller + updater lifecycle -------------------------------------------


class TestLifecycle:
    def test_submit_materializes_and_runs(self, controller):
        controller.submit(TrainingJob.from_dict(make_job_dict("j1", min_i=2, max_i=2)))
        assert wait_until(
            lambda: controller.job_status("j1").status.phase == JobPhase.RUNNING
        )
        # Coordinator was created first and is running; trainers follow.
        assert len(controller.cluster.job_pods("j1", ROLE_COORDINATOR)) == 1
        assert len(controller.cluster.job_pods("j1", ROLE_TRAINER)) == 2

    def test_success_releases_coordinator(self, controller):
        controller.submit(TrainingJob.from_dict(make_job_dict("j1", min_i=2, max_i=2)))
        wait_until(lambda: controller.job_status("j1").status.phase == JobPhase.RUNNING)
        for p in controller.cluster.job_pods("j1", ROLE_TRAINER):
            p.phase = "Succeeded"
        assert wait_until(
            lambda: controller.job_status("j1").status.phase == JobPhase.SUCCEEDED
        )
        # Coordinator GC'd on completion; trainer pod history kept.
        assert controller.cluster.job_pods("j1", ROLE_COORDINATOR) == []
        assert len(controller.cluster.job_pods("j1", ROLE_TRAINER)) == 2
        status = controller.job_status("j1").status
        assert set(status.replica_statuses.values()) == {"Succeeded"}

    def test_strict_job_fails_on_any_trainer_failure(self, controller):
        controller.submit(TrainingJob.from_dict(make_job_dict("j1", min_i=3, max_i=3)))
        wait_until(lambda: controller.job_status("j1").status.phase == JobPhase.RUNNING)
        controller.cluster.job_pods("j1", ROLE_TRAINER)[0].phase = "Failed"
        assert wait_until(
            lambda: controller.job_status("j1").status.phase == JobPhase.FAILED
        )
        assert "1/3" in controller.job_status("j1").status.reason

    def test_fault_tolerant_job_survives_partial_failure(self, controller):
        controller.submit(
            TrainingJob.from_dict(make_job_dict("j1", min_i=3, max_i=3, fault_tolerant=True))
        )
        wait_until(lambda: controller.job_status("j1").status.phase == JobPhase.RUNNING)
        pods = controller.cluster.job_pods("j1", ROLE_TRAINER)
        pods[0].phase = "Failed"
        time.sleep(0.2)  # several convert ticks
        assert controller.job_status("j1").status.phase == JobPhase.RUNNING
        for p in pods:
            p.phase = "Failed"
        assert wait_until(
            lambda: controller.job_status("j1").status.phase == JobPhase.FAILED
        )
        assert controller.job_status("j1").status.reason == "all trainers failed"

    def test_admission_rejection_sets_failed_status(self, controller):
        bad = make_job_dict("bad", min_i=3, max_i=1)  # inverted range
        controller.submit(TrainingJob.from_dict(bad))
        assert wait_until(
            lambda: controller.job_status("bad").status.phase == JobPhase.FAILED
        )
        assert "admission" in controller.job_status("bad").status.reason
        assert controller.cluster.job_pods("bad", ROLE_TRAINER) == []

    def test_delete_gcs_all_roles(self, controller):
        controller.submit(TrainingJob.from_dict(make_job_dict("j1", min_i=2, max_i=2)))
        wait_until(lambda: controller.job_status("j1").status.phase == JobPhase.RUNNING)
        controller.delete("j1")
        assert wait_until(
            lambda: controller.cluster.job_pods("j1", ROLE_TRAINER) == []
            and controller.cluster.job_pods("j1", ROLE_COORDINATOR) == []
        )


class TestRestartReplay:
    """A restarted controller replays the store: running jobs are adopted
    (no duplicate pods), terminal jobs are left alone."""

    def test_replay_adopts_running_and_skips_terminal(self):
        cluster = FakeCluster(nodes())
        store = JobStore()
        c1 = Controller(cluster, store=store,
                        autoscaler_config=AutoscalerConfig(loop_seconds=0.05),
                        updater_config=FAST).start()
        c1.submit(TrainingJob.from_dict(make_job_dict("run", min_i=2, max_i=2)))
        c1.submit(TrainingJob.from_dict(make_job_dict("done", min_i=1, max_i=1)))
        wait_until(lambda: c1.job_status("run").status.phase == JobPhase.RUNNING)
        wait_until(lambda: c1.job_status("done").status.phase == JobPhase.RUNNING)
        for p in cluster.job_pods("done", ROLE_TRAINER):
            p.phase = "Succeeded"
        assert wait_until(
            lambda: c1.job_status("done").status.phase == JobPhase.SUCCEEDED
        )
        c1.stop()

        c2 = Controller(cluster, store=store,
                        autoscaler_config=AutoscalerConfig(loop_seconds=0.05),
                        updater_config=FAST).start()
        try:
            assert wait_until(
                lambda: c2.job_status("run").status.phase == JobPhase.RUNNING
            )
            # Adopted, not duplicated.
            assert len(cluster.job_pods("run", ROLE_TRAINER)) == 2
            assert len(cluster.job_pods("run", ROLE_COORDINATOR)) == 1
            # Terminal job untouched: no coordinator resurrected.
            time.sleep(0.2)
            assert c2.job_status("done").status.phase == JobPhase.SUCCEEDED
            assert cluster.job_pods("done", ROLE_COORDINATOR) == []
        finally:
            c2.stop()


# -- controller + autoscaler integration --------------------------------------


class TestElasticIntegration:
    def test_elastic_job_scales_to_capacity(self, controller):
        """An elastic job on an idle 2-host x 8-chip cluster grows from
        min_instance toward max_instance as the autoscaler finds free chips."""
        controller.submit(
            TrainingJob.from_dict(make_job_dict("e1", min_i=1, max_i=8, chips=4))
        )
        wait_until(lambda: controller.job_status("e1").status.phase == JobPhase.RUNNING)
        # 2 hosts x 8 chips = 16 chips, 4 per trainer -> 4 trainers max by quota.
        assert wait_until(
            lambda: controller.cluster.get_trainer_parallelism("e1") == 4, timeout=8.0
        )
        # History persists via the updater's next status write (async); it may
        # arrive over several loop passes but must end at 4.
        assert wait_until(
            lambda: controller.job_status("e1").status.scale_history
            and controller.job_status("e1").status.scale_history[-1].to_replicas == 4
        )

    def test_two_jobs_share_chips(self, controller):
        controller.submit(
            TrainingJob.from_dict(make_job_dict("e1", min_i=1, max_i=8, chips=4))
        )
        controller.submit(
            TrainingJob.from_dict(make_job_dict("e2", min_i=1, max_i=8, chips=4))
        )
        wait_until(lambda: controller.job_status("e2").status.phase == JobPhase.RUNNING)
        # 16 chips / 4 per trainer = 4 trainers total across both jobs.
        def settled():
            p1 = controller.cluster.get_trainer_parallelism("e1")
            p2 = controller.cluster.get_trainer_parallelism("e2")
            return p1 + p2 == 4 and p1 >= 1 and p2 >= 1

        assert wait_until(settled, timeout=8.0)
