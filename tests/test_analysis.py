"""Tests for the edl_tpu.analysis static-analysis suite.

Three layers:

- per-rule fixture pairs: every EDL rule has at least one snippet that
  triggers it and one that must NOT (the false-positive guard matters as
  much as the detection — a noisy checker gets noqa'd into oblivion);
- mechanism tests: suppression comments, baseline round-trip + ratchet,
  CLI exit codes;
- the repo gate: the committed tree must be clean against the committed
  baseline. This is the tier-1 teeth of the whole suite.
"""

import json
import logging
import subprocess
import sys
import textwrap
from pathlib import Path

import numpy as np
import pytest

from edl_tpu.analysis import (
    analyze,
    apply_baseline,
    fingerprint,
    load_baseline,
    write_baseline,
)
from edl_tpu.analysis.cli import main as cli_main

REPO_ROOT = Path(__file__).resolve().parents[1]


def check(tmp_path, source, rules, name="snippet.py", config=None):
    """Analyze one dedented snippet with a rule subset; return the Report."""
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return analyze([str(p)], root=str(tmp_path), rules=rules, config=config)


def rules_of(report):
    return [f.rule for f in report.findings]


# -- EDL001: lock discipline ---------------------------------------------------


def test_edl001_flags_unlocked_write(tmp_path):
    report = check(
        tmp_path,
        """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.value = 0

            def bump(self):
                self.value += 1
        """,
        ["EDL001"],
    )
    assert rules_of(report) == ["EDL001"]
    (f,) = report.findings
    assert "value" in f.message and f.symbol.endswith("bump")


def test_edl001_accepts_locked_write_and_locked_helper(tmp_path):
    """Writes under `with self._lock` pass — including writes in a private
    helper only ever called while the lock is held (call-graph, not just
    lexical scope)."""
    report = check(
        tmp_path,
        """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.value = 0

            def bump(self):
                with self._lock:
                    self._bump_locked()

            def _bump_locked(self):
                self.value += 1
        """,
        ["EDL001"],
    )
    assert report.findings == []


def test_edl001_thread_target_escape_makes_private_method_an_entry(tmp_path):
    """`Thread(target=self._run)` publishes _run to another thread: its
    writes need the lock even though no public method calls it."""
    report = check(
        tmp_path,
        """
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self.ticks = 0

            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                self.ticks += 1
        """,
        ["EDL001"],
    )
    assert rules_of(report) == ["EDL001"]
    assert report.findings[0].symbol.endswith("_run")


def test_edl001_ignores_lockless_classes(tmp_path):
    report = check(
        tmp_path,
        """
        class Plain:
            def __init__(self):
                self.value = 0

            def bump(self):
                self.value += 1
        """,
        ["EDL001"],
    )
    assert report.findings == []


# -- EDL002: trace hygiene -----------------------------------------------------


def test_edl002_flags_host_clock_in_jitted_fn(tmp_path):
    report = check(
        tmp_path,
        """
        import time
        import jax

        @jax.jit
        def step(x):
            return x * time.time()
        """,
        ["EDL002"],
    )
    assert rules_of(report) == ["EDL002"]
    assert "time.time" in report.findings[0].message


def test_edl002_flags_branch_on_traced_value(tmp_path):
    report = check(
        tmp_path,
        """
        import jax

        @jax.jit
        def relu_ish(x):
            if x > 0:
                return x
            return -x
        """,
        ["EDL002"],
    )
    assert rules_of(report) == ["EDL002"]


def test_edl002_allows_static_shape_branch_and_host_code(tmp_path):
    """Branching on .shape/.ndim is static (fine under jit); host-side
    time.time() outside any traced function is the normal case."""
    report = check(
        tmp_path,
        """
        import time
        import jax

        @jax.jit
        def maybe_sum(x):
            if x.ndim > 1:
                return x.sum()
            return x

        def host_timer():
            return time.time()
        """,
        ["EDL002"],
    )
    assert report.findings == []


def test_edl002_finds_fn_passed_to_jit_call(tmp_path):
    """jit used as a call, not a decorator: jax.jit(step) marks step."""
    report = check(
        tmp_path,
        """
        import numpy as np
        import jax

        def step(x):
            return x + np.random.rand()

        fast_step = jax.jit(step)
        """,
        ["EDL002"],
    )
    assert rules_of(report) == ["EDL002"]
    assert "np.random" in report.findings[0].message


# -- EDL003: sharding consistency ---------------------------------------------

_EDL003_CONFIG = {
    "sharding_axes": ["data", "model"],
    "sharding_all_files": True,
}


def test_edl003_flags_undeclared_axis(tmp_path):
    report = check(
        tmp_path,
        """
        from jax.sharding import PartitionSpec as P

        SPEC = P("data", "modle")
        """,
        ["EDL003"],
        config=_EDL003_CONFIG,
    )
    assert rules_of(report) == ["EDL003"]
    assert "'modle'" in report.findings[0].message


def test_edl003_accepts_declared_axes_and_collective_kwargs(tmp_path):
    report = check(
        tmp_path,
        """
        import jax
        from jax.sharding import PartitionSpec as P

        SPEC = P(("data",), "model")

        def reduce_loss(loss, batch_axis: str = "data"):
            return jax.lax.psum(loss, axis_name=batch_axis)
        """,
        ["EDL003"],
        config=_EDL003_CONFIG,
    )
    assert report.findings == []


def test_edl003_flags_bad_axis_default(tmp_path):
    report = check(
        tmp_path,
        """
        def shard(x, shard_axis: str = "experts"):
            return x
        """,
        ["EDL003"],
        config=_EDL003_CONFIG,
    )
    assert rules_of(report) == ["EDL003"]


def test_edl003_scope_is_parallel_and_models_by_default(tmp_path):
    """Without the all-files override, only parallel/ and models/ paths are
    in scope — examples and tests may name foreign axes freely."""
    (tmp_path / "examples").mkdir()
    (tmp_path / "examples" / "demo.py").write_text(
        'from jax.sharding import PartitionSpec as P\nS = P("zzz")\n'
    )
    report = analyze(
        [str(tmp_path / "examples")],
        root=str(tmp_path),
        rules=["EDL003"],
        config={"sharding_axes": ["data"]},
    )
    assert report.findings == []


def test_edl003_collective_helpers_are_clean():
    """The data-plane helpers (`zero_shard_spec` builds PartitionSpecs,
    `split_microbatches` takes an `axis` default) live under parallel/, so
    EDL003's default scope covers them with no config — pin that they pass."""
    report = analyze(
        [str(REPO_ROOT / "edl_tpu" / "parallel" / "collective.py")],
        root=str(REPO_ROOT),
        rules=["EDL003"],
    )
    assert report.parse_errors == []
    assert report.findings == []


def test_edl003_flags_typoed_axis_in_collective_style_helper(tmp_path):
    """A zero_shard_spec-style helper with a misspelled axis under parallel/
    is in the default scope and gets flagged — no sharding_all_files needed."""
    pkg = tmp_path / "parallel"
    pkg.mkdir()
    (pkg / "collective.py").write_text(
        textwrap.dedent(
            """
            from jax.sharding import PartitionSpec as P

            def zero_shard_spec(shape, batch_axis: str = "dada"):
                spec = [None] * len(shape)
                spec[0] = batch_axis
                return P(*spec)
            """
        )
    )
    report = analyze(
        [str(pkg)],
        root=str(tmp_path),
        rules=["EDL003"],
        config={"sharding_axes": ["data", "dcn"]},
    )
    assert rules_of(report) == ["EDL003"]
    assert "'dada'" in report.findings[0].message


# -- EDL004: blocking while holding a lock ------------------------------------


def test_edl004_flags_sleep_under_lock(tmp_path):
    report = check(
        tmp_path,
        """
        import threading
        import time

        class Server:
            def __init__(self):
                self._lock = threading.Lock()

            def handle(self):
                with self._lock:
                    time.sleep(0.1)
        """,
        ["EDL004"],
    )
    assert rules_of(report) == ["EDL004"]
    assert "time.sleep" in report.findings[0].message


def test_edl004_allows_sleep_outside_lock(tmp_path):
    report = check(
        tmp_path,
        """
        import threading
        import time

        class Server:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def handle(self):
                with self._lock:
                    self.n += 1
                time.sleep(0.1)
        """,
        ["EDL004"],
    )
    assert report.findings == []


def test_edl004_flags_subprocess_under_module_lock(tmp_path):
    report = check(
        tmp_path,
        """
        import subprocess
        import threading

        _cache_lock = threading.Lock()

        def refresh():
            with _cache_lock:
                subprocess.run(["kubectl", "get", "pods"])
        """,
        ["EDL004"],
    )
    assert rules_of(report) == ["EDL004"]


# -- EDL005: exception hygiene -------------------------------------------------


def test_edl005_flags_silent_broad_except(tmp_path):
    report = check(
        tmp_path,
        """
        def load():
            try:
                risky()
            except Exception:
                pass
        """,
        ["EDL005"],
    )
    assert rules_of(report) == ["EDL005"]


def test_edl005_accepts_logged_reraised_or_narrow(tmp_path):
    report = check(
        tmp_path,
        """
        import logging

        log = logging.getLogger(__name__)

        def logged():
            try:
                risky()
            except Exception:
                log.exception("risky failed")

        def reraised():
            try:
                risky()
            except Exception as e:
                raise RuntimeError("wrapped") from e

        def narrow():
            try:
                risky()
            except ValueError:
                pass

        def delegated(e=None):
            try:
                risky()
            except Exception as e:
                _warn_failure(e)
        """,
        ["EDL005"],
    )
    assert report.findings == []


# -- suppression comments ------------------------------------------------------


def test_noqa_suppresses_exact_rule_on_exact_line(tmp_path):
    report = check(
        tmp_path,
        """
        def load():
            try:
                risky()
            except Exception:  # edl: noqa[EDL005] probe result is optional
                pass
        """,
        ["EDL005"],
    )
    assert report.findings == []
    assert rules_of(report) == [] and len(report.suppressed) == 1
    assert report.suppressed[0].rule == "EDL005"


def test_noqa_for_wrong_rule_does_not_suppress(tmp_path):
    report = check(
        tmp_path,
        """
        def load():
            try:
                risky()
            except Exception:  # edl: noqa[EDL001] wrong rule entirely
                pass
        """,
        ["EDL005"],
    )
    assert rules_of(report) == ["EDL005"]


def test_blanket_noqa_suppresses_any_rule(tmp_path):
    report = check(
        tmp_path,
        """
        def load():
            try:
                risky()
            except Exception:  # edl: noqa
                pass
        """,
        ["EDL005"],
    )
    assert report.findings == [] and len(report.suppressed) == 1


# -- baseline round-trip and ratchet ------------------------------------------

_BAD_EDL005 = """
def load():
    try:
        risky()
    except Exception:
        pass
"""


def test_baseline_round_trip_accepts_then_goes_stale(tmp_path):
    report = check(tmp_path, _BAD_EDL005, ["EDL005"])
    assert len(report.findings) == 1

    bpath = tmp_path / "baseline.json"
    write_baseline(str(bpath), report.findings)
    baseline = load_baseline(str(bpath))
    assert baseline.total() == 1

    # same tree: the finding is accepted, nothing new, nothing stale
    new, accepted, stale = apply_baseline(report.findings, baseline)
    assert (new, stale) == ([], []) and len(accepted) == 1

    # debt fixed: the entry turns stale (which also fails the run — the
    # ratchet only ever tightens)
    fixed = check(tmp_path, "def load():\n    return risky()\n", ["EDL005"])
    new, accepted, stale = apply_baseline(fixed.findings, baseline)
    assert new == [] and accepted == []
    assert len(stale) == 1 and stale[0]["rule"] == "EDL005"


def test_baseline_count_caps_identical_findings(tmp_path):
    """Two identical findings in one symbol share a fingerprint; the count
    caps acceptance, so a third occurrence is new debt."""
    one = check(tmp_path, _BAD_EDL005, ["EDL005"])
    baseline = load_baseline(
        str(write_baseline_to(tmp_path, one.findings))
    )
    doubled = check(
        tmp_path,
        """
        def load():
            try:
                risky()
        """
        + "    except Exception:\n        pass\n" * 0
        + """
            except Exception:
                pass
            try:
                risky()
            except Exception:
                pass
        """,
        ["EDL005"],
    )
    assert len(doubled.findings) == 2
    assert fingerprint(doubled.findings[0]) == fingerprint(doubled.findings[1])
    new, accepted, stale = apply_baseline(doubled.findings, baseline)
    assert len(accepted) == 1 and len(new) == 1 and stale == []


def write_baseline_to(tmp_path, findings):
    bpath = tmp_path / "baseline.json"
    write_baseline(str(bpath), findings)
    return bpath


def test_baseline_rejects_unknown_version(tmp_path):
    bpath = tmp_path / "baseline.json"
    bpath.write_text(json.dumps({"version": 99, "findings": {}}))
    with pytest.raises(ValueError):
        load_baseline(str(bpath))


# -- CLI -----------------------------------------------------------------------


def test_cli_exit_codes_and_json_shape(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(_BAD_EDL005))

    rc = cli_main([str(bad), "--format", "json", "--baseline", "none"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert payload["summary"]["new"] == 1
    assert payload["findings"][0]["rule"] == "EDL005"
    assert payload["findings"][0]["baselined"] is False

    # baseline it: same tree now exits 0 and reports it as baselined
    bpath = tmp_path / "baseline.json"
    rc = cli_main([str(bad), "--baseline", str(bpath), "--write-baseline"])
    capsys.readouterr()
    assert rc == 0
    rc = cli_main([str(bad), "--format", "json", "--baseline", str(bpath)])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert payload["summary"] == dict(
        payload["summary"], new=0, baselined=1
    )


def test_cli_clean_tree_exits_zero(tmp_path, capsys):
    good = tmp_path / "good.py"
    good.write_text("def f():\n    return 1\n")
    rc = cli_main([str(good), "--baseline", "none"])
    capsys.readouterr()
    assert rc == 0


def test_cli_parse_error_exits_two(tmp_path, capsys):
    broken = tmp_path / "broken.py"
    broken.write_text("def f(:\n")
    rc = cli_main([str(broken), "--baseline", "none"])
    capsys.readouterr()
    assert rc == 2


def test_cli_list_rules_names_all_ten(capsys):
    rc = cli_main(["--list-rules"])
    out = capsys.readouterr().out
    assert rc == 0
    for rule in ("EDL001", "EDL002", "EDL003", "EDL004", "EDL005",
                 "EDL006", "EDL007", "EDL008", "EDL009", "EDL010"):
        assert rule in out


def test_module_entrypoint_runs():
    """`python -m edl_tpu.analysis --list-rules` — the CI/pre-commit form."""
    proc = subprocess.run(
        [sys.executable, "-m", "edl_tpu.analysis", "--list-rules"],
        cwd=str(REPO_ROOT),
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "EDL001" in proc.stdout


# -- the repo gate -------------------------------------------------------------


def test_repo_tree_is_clean_against_committed_baseline():
    """Tier-1 teeth: the committed tree carries zero non-baselined findings
    and zero stale baseline entries. New debt → fix it, noqa it with a
    justification, or consciously --write-baseline."""
    report = analyze([str(REPO_ROOT / "edl_tpu")], root=str(REPO_ROOT))
    assert report.parse_errors == [], report.parse_errors
    baseline = load_baseline(str(REPO_ROOT / "analysis_baseline.json"))
    new, _accepted, stale = apply_baseline(report.findings, baseline)
    assert new == [], "new findings:\n" + "\n".join(
        f"{f.location()}: {f.rule} {f.message}" for f in new
    )
    assert stale == [], "stale baseline entries (run --write-baseline):\n" + "\n".join(
        f"{e['rule']} {e['path']} '{e['symbol']}'" for e in stale
    )


# -- retrace canary (runtime complement of EDL002) ----------------------------


def test_retrace_canary_counts_recompiles(caplog):
    from edl_tpu.models import fit_a_line
    from edl_tpu.parallel import local_mesh
    from edl_tpu.runtime import Trainer, TrainerConfig

    mesh = local_mesh()
    trainer = Trainer(
        fit_a_line.MODEL, mesh, TrainerConfig(optimizer="sgd", learning_rate=0.1)
    )
    state = trainer.init_state()
    rng = np.random.default_rng(5)

    def batches(n, bs):
        for _ in range(n):
            yield fit_a_line.MODEL.synthetic_batch(rng, bs)

    state, metrics = trainer.run(state, batches(3, 64))
    if trainer._jit_cache_size() is None:
        pytest.skip("jit _cache_size() unavailable on this jax version")
    # steady shapes: the one compile at step 1 is not a retrace
    assert metrics["retraces"] == 0.0
    assert trainer.retraces == 0

    # a changed batch shape forces a recompile — the canary must see it
    batch = fit_a_line.MODEL.synthetic_batch(rng, 32)
    state, _ = trainer.train_step(state, trainer.place_batch(batch))
    with caplog.at_level(logging.WARNING, logger="edl_tpu.runtime.train_loop"):
        tripped = trainer.check_retrace(step=4)
    assert tripped is True
    assert trainer.retraces >= 1
    assert any("RECOMPILED" in r.message for r in caplog.records)


# -- EDL006: cross-root lockset races -----------------------------------------



def test_edl006_flags_attr_written_from_two_roots_without_lock(tmp_path):
    report = check(
        tmp_path,
        """
        import threading

        class Worker:
            def __init__(self):
                self.count = 0

            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                self.count += 1

            def bump(self):
                self.count += 1
        """,
        ["EDL006"],
    )
    assert rules_of(report) == ["EDL006"]
    assert "Worker.count" in report.findings[0].message
    assert "no common lock" in report.findings[0].message


def test_edl006_accepts_common_lock_on_both_roots(tmp_path):
    report = check(
        tmp_path,
        """
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                with self._lock:
                    self.count += 1

            def bump(self):
                with self._lock:
                    self.count += 1
        """,
        ["EDL006"],
    )
    assert report.findings == []


def test_edl006_single_root_and_init_writes_are_clean(tmp_path):
    # __init__ publishes before the thread starts; only one root writes after.
    report = check(
        tmp_path,
        """
        import threading

        class Worker:
            def __init__(self):
                self.count = 0

            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                self.count += 1

            def read(self):
                return 1
        """,
        ["EDL006"],
    )
    assert report.findings == []


def test_edl006_condition_aliases_its_wrapped_lock(tmp_path):
    # Condition(self._lock) and self._lock are the SAME mutex: one root
    # holding the condition and the other the raw lock is race-free.
    report = check(
        tmp_path,
        """
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)
                self.count = 0

            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                with self._cv:
                    self.count += 1

            def bump(self):
                with self._lock:
                    self.count += 1
        """,
        ["EDL006"],
    )
    assert report.findings == []


def test_edl006_lockset_propagates_through_call_chain(tmp_path):
    # Both roots take the lock BEFORE calling the shared helper: the helper's
    # entry lockset (meet over callers) carries the guard to the write.
    report = check(
        tmp_path,
        """
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self.x = 0

            def start(self):
                threading.Thread(target=self.run).start()

            def run(self):
                with self._lock:
                    self._bump()

            def grow(self):
                with self._lock:
                    self._bump()

            def _bump(self):
                self.x += 1
        """,
        ["EDL006"],
    )
    assert report.findings == []


def test_edl006_unlocked_caller_poisons_helper_lockset(tmp_path):
    # One caller forgets the lock: the meet at _bump's entry goes empty and
    # the write is flagged even though the OTHER root locked correctly.
    report = check(
        tmp_path,
        """
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self.x = 0

            def start(self):
                threading.Thread(target=self.run).start()

            def run(self):
                with self._lock:
                    self._bump()

            def grow(self):
                self._bump()

            def _bump(self):
                self.x += 1
        """,
        ["EDL006"],
    )
    assert rules_of(report) == ["EDL006"]


def test_edl006_http_handler_methods_are_thread_roots(tmp_path):
    report = check(
        tmp_path,
        """
        from http.server import BaseHTTPRequestHandler

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                self.hits = self.hits + 1

            def reset(self):
                self.hits = 0
        """,
        ["EDL006"],
    )
    assert rules_of(report) == ["EDL006"]
    assert "Handler.hits" in report.findings[0].message


def test_edl006_collector_callback_is_a_thread_root(tmp_path):
    report = check(
        tmp_path,
        """
        class Probe:
            def __init__(self):
                self.last = None

            def attach(self, reg):
                reg.register_collector(self._collect)

            def _collect(self):
                self.last = 1

            def poll(self):
                self.last = 2
        """,
        ["EDL006"],
    )
    assert rules_of(report) == ["EDL006"]


def test_edl006_noqa_on_anchor_line_suppresses(tmp_path):
    report = check(
        tmp_path,
        """
        import threading

        class Worker:
            def __init__(self):
                self.count = 0

            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                self.count += 1  # edl: noqa[EDL006] GIL-atomic int bump, drift tolerated

            def bump(self):
                self.count += 1
        """,
        ["EDL006"],
    )
    assert report.findings == []
    assert len(report.suppressed) == 1 and report.suppressed[0].rule == "EDL006"


# -- EDL007: wire-protocol conformance ----------------------------------------

from edl_tpu.analysis.checkers.wire_protocol import (  # noqa: E402
    extract_native_schema,
)

_TOY_CC = """
// Toy coordinator: dispatch table + handlers, for EDL007 fixtures.

Json Coordinator::membership_reply() {
  Json r;
  r.field("ok");
  r.field("rank");
  return r;
}

Json Coordinator::op_join(const Json& req) {
  get_str(req, "name");
  return membership_reply();
}

Json Coordinator::op_put(const Json& req) {
  get_str(req, "key");
  get_str(req, "value");
  Json r;
  r.field("ok");
  return r;
}

Json Coordinator::dispatch(const Json& req) {
  std::string op = get_str(req, "op");
  if (op == "join") return op_join(req);
  if (op == "put") return op_put(req);
  if (op == "ping") return Json().field("ok", true);
  return err();
}

Json Coordinator::handle(const Json& req) {
  Json reply = dispatch(req);
  stamp_epoch(dispatch, reply);
  return reply;
}
"""

_EDL007_CONFIG = {
    "edl007_native_source": "coord.cc",
    "edl007_schema": "schema.json",
    "edl007_prefixes": [""],  # every analyzed .py speaks the protocol
}


def _toy_schema():
    return extract_native_schema(textwrap.dedent(_TOY_CC), "coord.cc")


def wire_check(tmp_path, py_files, cc=_TOY_CC, schema="fresh"):
    """Analyze a toy cross-language pair: ``coord.cc`` + python files, with
    the committed-schema artifact either up to date ('fresh'), absent
    (None), or an explicit dict."""
    (tmp_path / "coord.cc").write_text(textwrap.dedent(cc))
    if schema == "fresh":
        schema = extract_native_schema(textwrap.dedent(cc), "coord.cc")
    if schema is not None:
        (tmp_path / "schema.json").write_text(json.dumps(schema))
    paths = []
    for name, src in py_files.items():
        p = tmp_path / name
        p.write_text(textwrap.dedent(src))
        paths.append(str(p))
    return analyze(
        paths, root=str(tmp_path), rules=["EDL007"], config=_EDL007_CONFIG
    )


def test_edl007_extracts_dispatch_table_from_cc():
    schema = _toy_schema()
    assert set(schema["ops"]) == {"join", "put", "ping"}
    assert schema["epoch_stamped"] is True
    assert schema["unstamped_deferred_ops"] == []
    # helper expansion (membership_reply) + the implicit epoch stamp
    assert schema["ops"]["join"]["request"] == ["name"]
    assert schema["ops"]["join"]["reply"] == ["epoch", "ok", "rank"]
    assert schema["ops"]["put"]["request"] == ["key", "value"]
    # inline arm (ping): no handler function, fields from the return stmt
    assert schema["ops"]["ping"]["reply"] == ["epoch", "ok"]


def test_edl007_comments_do_not_leak_into_schema():
    cc = _TOY_CC + """
// if (op == "ghost") return op_ghost(req);
/* r.field("phantom"); deferred_ */
"""
    schema = extract_native_schema(textwrap.dedent(cc), "coord.cc")
    assert "ghost" not in schema["ops"]
    assert all("phantom" not in s["reply"] for s in schema["ops"].values())


def test_edl007_conformant_pair_is_clean(tmp_path):
    report = wire_check(
        tmp_path,
        {
            "client.py": """
            class Client:
                def join(self):
                    return self._t.call("join", name="w0")

                def put(self):
                    return self._t.call("put", key="k", value="v")
            """,
        },
    )
    assert report.findings == []


def test_edl007_flags_unknown_op_and_unread_field(tmp_path):
    report = wire_check(
        tmp_path,
        {
            "client.py": """
            class Client:
                def join(self):
                    return self._t.call("jion", name="w0")

                def put(self):
                    return self._t.call("put", key="k", value="v", mode="fast")
            """,
        },
    )
    msgs = sorted(f.message for f in report.findings)
    assert len(msgs) == 2
    assert "call('jion') is not in the native dispatch table" in msgs[0]
    assert "never reads: mode" in msgs[1]


def test_edl007_missing_schema_artifact_is_a_finding(tmp_path):
    report = wire_check(
        tmp_path, {"client.py": "X = 1\n"}, schema=None
    )
    assert len(report.findings) == 1
    assert "run --write-protocol" in report.findings[0].message


def test_edl007_schema_drift_is_ratcheted(tmp_path):
    stale = _toy_schema()
    del stale["ops"]["put"]
    report = wire_check(tmp_path, {"client.py": "X = 1\n"}, schema=stale)
    assert len(report.findings) == 1
    f = report.findings[0]
    assert "op 'put' is in the dispatch table but not in" in f.message
    assert "run --write-protocol" in f.message and f.symbol == "put"


def test_edl007_deferred_reply_must_carry_epoch():
    cc = _TOY_CC.replace(
        'if (op == "join")',
        'if (op == "wait") { op_wait(req, fd); return Json(); }\n'
        '  if (op == "join")',
    ).replace(
        "Json Coordinator::dispatch",
        """void Coordinator::op_wait(const Json& req, int fd) {
  deferred_.push_back(fd);
}

Json Coordinator::dispatch""",
    )
    schema = extract_native_schema(textwrap.dedent(cc), "coord.cc")
    assert schema["ops"]["wait"]["deferred"] is True
    assert schema["unstamped_deferred_ops"] == ["wait"]


def test_edl007_shim_missing_op_and_reply_drift(tmp_path):
    report = wire_check(
        tmp_path,
        {
            "inproc.py": """
            class InProcessClient:
                def call(self, op, timeout=None, **fields):
                    if op == "ping":
                        return self._stamp({"ok": True})
                    if op == "join":
                        return self._stamp({"ok": True})
                    raise ValueError(op)
            """,
        },
    )
    msgs = sorted(f.message for f in report.findings)
    assert len(msgs) == 2
    assert "does not handle op 'put'" in msgs[0]
    assert "in-process reply for 'join' diverges" in msgs[1]
    assert "missing: rank" in msgs[1]


def test_edl007_shim_covering_all_ops_is_clean(tmp_path):
    report = wire_check(
        tmp_path,
        {
            "inproc.py": """
            class InProcessClient:
                def call(self, op, timeout=None, **fields):
                    if op == "ping":
                        return self._stamp({"ok": True})
                    if op == "join":
                        return self._stamp({"ok": True, "rank": 0})
                    if op == "put":
                        return self._stamp({"ok": True})
                    raise ValueError(op)
            """,
        },
    )
    assert report.findings == []


def test_write_protocol_cli_round_trip(tmp_path, monkeypatch, capsys):
    native = tmp_path / "native" / "coordinator"
    native.mkdir(parents=True)
    (native / "coordinator.cc").write_text(textwrap.dedent(_TOY_CC))
    monkeypatch.chdir(tmp_path)
    rc = cli_main(["--write-protocol"])
    out = capsys.readouterr().out
    assert rc == 0 and "3 op(s)" in out
    written = json.loads((tmp_path / "protocol_schema.json").read_text())
    assert written == extract_native_schema(
        textwrap.dedent(_TOY_CC), "native/coordinator/coordinator.cc"
    )


def test_repo_protocol_schema_matches_native_source():
    """The committed artifact IS the extraction of the committed .cc — the
    ratchet's premise. Fails whenever one is edited without the other.
    ``state_effects`` is the hand-authored EDL009 behavioral annotation,
    not part of the extraction; it must exist and cover the op set."""
    cc = (REPO_ROOT / "native" / "coordinator" / "coordinator.cc").read_text()
    committed = json.loads((REPO_ROOT / "protocol_schema.json").read_text())
    effects = committed.pop("state_effects")
    assert committed == extract_native_schema(
        cc, "native/coordinator/coordinator.cc"
    )
    assert len(committed["ops"]) >= 18
    assert committed["epoch_stamped"] is True
    assert set(effects) == set(committed["ops"])


# -- parallel engine -----------------------------------------------------------


def test_parallel_jobs_produce_identical_findings(tmp_path):
    for i in range(3):
        (tmp_path / f"mod{i}.py").write_text(textwrap.dedent(_BAD_EDL005))
    serial = analyze([str(tmp_path)], root=str(tmp_path), jobs=1)
    forked = analyze([str(tmp_path)], root=str(tmp_path), jobs=2)
    as_tuples = lambda r: [  # noqa: E731
        (f.path, f.line, f.col, f.rule, f.message) for f in r.findings
    ]
    assert as_tuples(serial) == as_tuples(forked)
    assert serial.jobs == 1 and forked.jobs == 2


def test_report_carries_per_rule_timings(tmp_path):
    (tmp_path / "m.py").write_text("def f():\n    return 1\n")
    report = analyze([str(tmp_path)], root=str(tmp_path), rules=["EDL005"])
    assert "EDL005" in report.timings
    assert report.timings["EDL005"] >= 0.0


# -- EDL008: elastic determinism -----------------------------------------------

_EDL008_CONFIG = {"edl008_all_files": True}


def test_edl008_flags_rng_seeded_from_process_index(tmp_path):
    report = check(
        tmp_path,
        """
        import jax

        def make_key():
            return jax.random.PRNGKey(jax.process_index())
        """,
        ["EDL008"],
        config=_EDL008_CONFIG,
    )
    assert rules_of(report) == ["EDL008"]
    (f,) = report.findings
    assert "process_index" in f.message and f.symbol.endswith("make_key")


def test_edl008_tracks_taint_through_assignment_and_fstring(tmp_path):
    """The live-tree shape: identity -> f-string -> seed string -> RNG."""
    report = check(
        tmp_path,
        """
        import random
        import socket

        def make_rng():
            host = socket.gethostname()
            seed_str = f"trainer:{host}"
            return random.Random(seed_str)
        """,
        ["EDL008"],
        config=_EDL008_CONFIG,
    )
    assert rules_of(report) == ["EDL008"]
    assert "gethostname" in report.findings[0].message


def test_edl008_flags_worker_identity_attribute(tmp_path):
    report = check(
        tmp_path,
        """
        import random

        class Loader:
            def __init__(self, client):
                self.rng = random.Random(f"shuffle:{client.worker}")
        """,
        ["EDL008"],
        config=_EDL008_CONFIG,
    )
    assert rules_of(report) == ["EDL008"]
    assert "'worker'" in report.findings[0].message


def test_edl008_accepts_logical_seed_derivation(tmp_path):
    """Seeds from config values, shard indices, and step counters are the
    sanctioned pattern and must not fire."""
    report = check(
        tmp_path,
        """
        import random

        import jax
        import numpy as np

        def make_keys(config, shard_index, step):
            base = jax.random.PRNGKey(config.seed)
            k = jax.random.fold_in(base, step)
            rng = np.random.default_rng((config.seed ^ shard_index) & 0xFF)
            shuffle = random.Random(config.shuffle_seed + shard_index)
            return k, rng, shuffle
        """,
        ["EDL008"],
        config=_EDL008_CONFIG,
    )
    assert report.findings == []


def test_edl008_flags_accumulation_over_set_iteration(tmp_path):
    report = check(
        tmp_path,
        """
        def total_loss(losses):
            pending = set(losses)
            total = 0.0
            for item in pending:
                total += item.loss
            return total
        """,
        ["EDL008"],
        config=_EDL008_CONFIG,
    )
    assert rules_of(report) == ["EDL008"]
    assert "order varies" in report.findings[0].message
    assert "'total'" in report.findings[0].message


def test_edl008_flags_accumulation_over_membership_values(tmp_path):
    report = check(
        tmp_path,
        """
        class Aggregator:
            def grad_norm(self):
                norm = 0.0
                for shard in self._members.values():
                    norm = norm + shard.sq()
                return norm
        """,
        ["EDL008"],
        config=_EDL008_CONFIG,
    )
    assert rules_of(report) == ["EDL008"]
    assert "_members.values()" in report.findings[0].message


def test_edl008_accepts_sorted_and_list_iteration(tmp_path):
    report = check(
        tmp_path,
        """
        def totals(shards, members):
            total = 0.0
            for s in sorted(set(shards)):
                total += s.loss
            for name in sorted(members.values()):
                total += len(name)
            for s in shards:
                total += s.weight
            return total
        """,
        ["EDL008"],
        config=_EDL008_CONFIG,
    )
    assert report.findings == []


def test_edl008_scope_defaults_to_training_surface(tmp_path):
    """Outside runtime//parallel//models/ the rule is silent without the
    edl008_all_files override."""
    bad = """
    import jax

    def make_key():
        return jax.random.PRNGKey(jax.process_index())
    """
    silent = check(tmp_path, bad, ["EDL008"], name="tools.py")
    assert silent.findings == []
    (tmp_path / "edl_tpu" / "runtime").mkdir(parents=True)
    scoped = check(
        tmp_path, bad, ["EDL008"], name="edl_tpu/runtime/loader.py"
    )
    assert rules_of(scoped) == ["EDL008"]


def test_edl008_respects_line_noqa(tmp_path):
    report = check(
        tmp_path,
        """
        import random

        def jitter(worker):
            return random.Random(f"hb:{worker}")  # edl: noqa[EDL008] heartbeat jitter, not training state
        """,
        ["EDL008"],
        config=_EDL008_CONFIG,
    )
    assert report.findings == []
    assert [f.rule for f in report.suppressed] == ["EDL008"]


# -- EDL009: protocol model check ----------------------------------------------


def test_edl009_green_on_the_real_coordinator():
    report = analyze(
        [str(REPO_ROOT / "edl_tpu" / "coordinator" / "inprocess.py")],
        root=str(REPO_ROOT),
        rules=["EDL009"],
    )
    assert report.findings == []


def test_edl009_skips_trees_without_the_oracle_module(tmp_path):
    """Fixture trees never pay the exploration cost: no target file, no
    reduce work, no findings."""
    report = check(tmp_path, "x = 1\n", ["EDL009"])
    assert report.findings == []


def test_edl009_reports_state_effects_coverage_drift(tmp_path):
    """An op in the dispatch table without a state_effects entry (and vice
    versa) is a finding on the schema artifact."""
    target = tmp_path / "edl_tpu" / "coordinator"
    target.mkdir(parents=True)
    (target / "inprocess.py").write_text("x = 1\n")
    (tmp_path / "protocol_schema.json").write_text(json.dumps({
        "ops": {"ping": {}, "register": {}},
        "state_effects": {"ping": {}, "vanished_op": {}},
    }))
    report = analyze(
        [str(target / "inprocess.py")], root=str(tmp_path), rules=["EDL009"]
    )
    messages = sorted(f.message for f in report.findings)
    assert len(messages) == 2
    assert "register" in messages[0] and "no state_effects entry" in messages[0]
    assert "vanished_op" in messages[1] and "stale" in messages[1]
    assert all(f.path == "protocol_schema.json" for f in report.findings)


def test_edl009_reports_missing_state_effects_block(tmp_path):
    target = tmp_path / "edl_tpu" / "coordinator"
    target.mkdir(parents=True)
    (target / "inprocess.py").write_text("x = 1\n")
    (tmp_path / "protocol_schema.json").write_text(json.dumps({"ops": {}}))
    report = analyze(
        [str(target / "inprocess.py")], root=str(tmp_path), rules=["EDL009"]
    )
    (f,) = report.findings
    assert "state_effects" in f.message


# -- EDL010: durability model check ---------------------------------------------


def test_edl010_green_on_the_real_coordinator():
    """The committed twin + schema pass the crash-recovery exploration:
    all six durability schedules, zero findings."""
    report = analyze(
        [str(REPO_ROOT / "edl_tpu" / "coordinator" / "inprocess.py")],
        root=str(REPO_ROOT),
        rules=["EDL010"],
    )
    assert report.findings == []


def test_edl010_skips_trees_without_the_twin_module(tmp_path):
    report = check(tmp_path, "x = 1\n", ["EDL010"])
    assert report.findings == []


def test_edl010_reports_malformed_durability_tags(tmp_path):
    """An untagged op and a tag naming an unknown journal record kind are
    findings on the schema artifact, and block exploration (a spec the
    model cannot read proves nothing)."""
    target = tmp_path / "edl_tpu" / "coordinator"
    target.mkdir(parents=True)
    (target / "inprocess.py").write_text("x = 1\n")
    (tmp_path / "protocol_schema.json").write_text(json.dumps({
        "ops": {"ping": {}, "register": {}, "kv_put": {}},
        "state_effects": {
            "ping": {"durability": "none"},
            "register": {},  # untagged
            "kv_put": {"durability": "journal:blob"},  # unknown kind
        },
    }))
    report = analyze(
        [str(target / "inprocess.py")], root=str(tmp_path), rules=["EDL010"]
    )
    messages = sorted(f.message for f in report.findings)
    assert len(messages) == 2
    assert "kv_put" in messages[0] and "unknown record kind" in messages[0]
    assert "register" in messages[1] and "missing" in messages[1]
    assert all(f.path == "protocol_schema.json" for f in report.findings)


def test_validate_durability_tag_vocabulary():
    from edl_tpu.analysis.checkers.durability import validate_durability_tag

    assert validate_durability_tag("none") is None
    assert validate_durability_tag("volatile") is None
    assert validate_durability_tag("composite") is None
    assert validate_durability_tag("journal:kv") is None
    assert validate_durability_tag("journal:meta,lease") is None
    assert validate_durability_tag(None) is not None
    assert validate_durability_tag("") is not None
    assert validate_durability_tag("journal:") is not None
    assert validate_durability_tag("journal:quantum") is not None
    assert validate_durability_tag("durable-ish") is not None


def test_write_protocol_preserves_durability_tags(tmp_path, monkeypatch,
                                                  capsys):
    """--write-protocol regenerates the extraction but must carry the
    hand-authored state_effects block — including EDL010's durability
    tags — through unchanged."""
    native = tmp_path / "native" / "coordinator"
    native.mkdir(parents=True)
    (native / "coordinator.cc").write_text(textwrap.dedent(_TOY_CC))
    effects = {
        "ping": {"durability": "none"},
        "register": {"epoch": "bump", "durability": "journal:meta,lease"},
        "kv_put": {"durability": "journal:kv"},
    }
    (tmp_path / "protocol_schema.json").write_text(json.dumps({
        "ops": {"stale": {}},
        "state_effects": effects,
    }))
    monkeypatch.chdir(tmp_path)
    rc = cli_main(["--write-protocol"])
    capsys.readouterr()
    assert rc == 0
    written = json.loads((tmp_path / "protocol_schema.json").read_text())
    assert written["state_effects"] == effects
    assert "stale" not in written["ops"]  # extraction replaced the op set


# -- SARIF output ---------------------------------------------------------------


def test_sarif_round_trip_on_known_findings(tmp_path):
    from edl_tpu.analysis.sarif import from_sarif, to_sarif

    report = check(
        tmp_path,
        """
        import jax

        def make_key():
            return jax.random.PRNGKey(jax.process_index())
        """,
        ["EDL008"],
        config=_EDL008_CONFIG,
    )
    assert report.findings
    doc = to_sarif(report.findings, baselined=[])
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert any(r["id"] == "EDL009" for r in run["tool"]["driver"]["rules"])
    new, baselined = from_sarif(json.loads(json.dumps(doc)))
    assert baselined == []
    assert new == report.findings


def test_sarif_marks_baselined_findings_as_suppressed(tmp_path):
    from edl_tpu.analysis.sarif import from_sarif, to_sarif

    report = check(
        tmp_path,
        """
        def total(pending):
            total = 0.0
            for item in set(pending):
                total += item
            return total
        """,
        ["EDL008"],
        config=_EDL008_CONFIG,
    )
    doc = to_sarif([], baselined=report.findings)
    result = doc["runs"][0]["results"][0]
    assert result["suppressions"][0]["kind"] == "external"
    assert result["partialFingerprints"]["edlFingerprint/v1"] == fingerprint(
        report.findings[0]
    )
    new, baselined = from_sarif(doc)
    assert new == [] and baselined == report.findings


def test_cli_format_sarif(tmp_path, capsys):
    clean = tmp_path / "clean.py"
    clean.write_text("def f():\n    return 1\n")
    rc = cli_main(["--format", "sarif", "--baseline", "none", str(clean)])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["version"] == "2.1.0"
    assert doc["runs"][0]["results"] == []


# -- parallel parity / reduce timings for the new program rules -----------------


def test_new_program_rules_jobs_parity(tmp_path):
    """EDL008 map/reduce across a process pool produces byte-identical
    findings to the serial path (EDL009 has no target file here and must
    stay silent in both)."""
    bad = textwrap.dedent(
        """
        import jax

        def make_key():
            return jax.random.PRNGKey(jax.process_index())
        """
    )
    for i in range(4):
        (tmp_path / f"mod{i}.py").write_text(bad)
    kw = dict(
        root=str(tmp_path),
        rules=["EDL008", "EDL009"],
        config=_EDL008_CONFIG,
    )
    serial = analyze([str(tmp_path)], jobs=1, **kw)
    forked = analyze([str(tmp_path)], jobs=2, **kw)
    as_tuples = lambda r: [  # noqa: E731
        (f.path, f.line, f.col, f.rule, f.message) for f in r.findings
    ]
    assert as_tuples(serial) == as_tuples(forked)
    assert len(serial.findings) == 4
    assert serial.jobs == 1 and forked.jobs == 2


def test_edl009_jobs_parity_on_the_real_tree():
    coord = str(REPO_ROOT / "edl_tpu" / "coordinator")
    kw = dict(root=str(REPO_ROOT), rules=["EDL009"])
    serial = analyze([coord], jobs=1, **kw)
    forked = analyze([coord], jobs=2, **kw)
    assert serial.findings == forked.findings == []


def test_report_splits_reduce_timings_from_map_timings(tmp_path):
    (tmp_path / "m.py").write_text("x = 1\n")
    report = analyze(
        [str(tmp_path)], root=str(tmp_path), rules=["EDL005", "EDL008"]
    )
    assert "EDL005" in report.timings
    assert "EDL005" not in report.reduce_timings  # file rules never reduce
    assert "EDL008" in report.reduce_timings
    assert report.reduce_timings["EDL008"] >= 0.0
