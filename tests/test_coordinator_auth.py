"""Coordinator authentication: per-job EDL_COORD_TOKEN on both backends.

The coordinator binds 0.0.0.0 in pods (cross-host trainers dial in), so
without auth any pod in a shared cluster could add_tasks/bump_epoch/poison
KV for any job — the reference's etcd sidecar was exactly that open
(`pkg/jobparser.go:167-184`). These tests pin the contract on the native
binary AND the in-process twin: wrong/missing token -> typed
CoordinatorAuthError on every state-touching op; ping (the liveness probe)
stays open; controller stamps the secret into every pod's env.
"""

import pytest

from edl_tpu.coordinator import (
    CoordinatorAuthError, CoordinatorClient, CoordinatorServer,
    InProcessCoordinator,
)

TOKEN = "per-job-secret-123"


def test_native_rejects_wrong_and_missing_token():
    with CoordinatorServer(auth_token=TOKEN) as server:
        good = server.client("w0")
        assert good.register()["ok"]
        assert good.add_tasks(["s0"]) == 1

        for bad_token in ("wrong", ""):
            bad = CoordinatorClient(port=server.port, worker="intruder",
                                    token=bad_token)
            assert bad.ping()  # liveness stays open
            for call in (bad.register, bad.acquire_task, bad.bump_epoch,
                         lambda: bad.add_tasks(["x"]),
                         lambda: bad.kv_put("k", "v"), bad.status):
                with pytest.raises(CoordinatorAuthError):
                    call()
            bad.close()

        # the intruder changed nothing: the real worker still owns the queue
        assert good.acquire_task() == "s0"
        assert good.status()["queued"] == 0
        good.close()


def test_native_auth_disabled_without_token():
    with CoordinatorServer() as server:
        anon = CoordinatorClient(port=server.port, worker="w", token="")
        assert anon.register()["ok"]
        anon.close()


def test_native_barrier_sync_raise_not_timeout():
    """Auth failures must surface as CoordinatorAuthError, not be masked
    as barrier/sync timeouts (a deployment bug would look like a hang)."""
    with CoordinatorServer(auth_token=TOKEN) as server:
        bad = CoordinatorClient(port=server.port, worker="w", token="nope")
        with pytest.raises(CoordinatorAuthError):
            bad.barrier("b", 1, timeout=5.0)
        with pytest.raises(CoordinatorAuthError):
            bad.sync(0, timeout=5.0)
        bad.close()


def test_inprocess_twin_same_contract():
    coord = InProcessCoordinator(auth_token=TOKEN)
    good = coord.client("w0")  # inherits the coordinator's token
    assert good.register()["ok"]
    bad = coord.client("intruder", token="wrong")
    assert bad.ping()
    for call in (bad.register, bad.acquire_task, bad.bump_epoch,
                 lambda: bad.add_tasks(["x"]), lambda: bad.kv_put("k", "v"),
                 bad.status):
        with pytest.raises(CoordinatorAuthError):
            call()
    # twin without a token: open, like the binary
    open_coord = InProcessCoordinator()
    assert open_coord.client("w", token="").register()["ok"]


def test_controller_stamps_token_and_pods_inherit_it():
    """Admission generates the secret once, persists it, and every role's
    env carries it — coordinator and trainers agree by construction."""
    from edl_tpu.api import ResourceList
    from edl_tpu.api.types import TrainingJob
    from edl_tpu.controller import FakeCluster, JobStore, NodeInfo, make_env
    from edl_tpu.controller.updater import JobUpdater

    job = TrainingJob.from_dict({
        "metadata": {"name": "j1", "namespace": "default"},
        "spec": {"fault_tolerant": True,
                 "trainer": {"min_instance": 1, "max_instance": 2,
                             "entrypoint": "python train.py"}},
    })
    store = JobStore()
    store.create(job)
    cluster = FakeCluster([NodeInfo(
        "n0", ResourceList.make({"cpu": "8", "memory": "16Gi"}))])
    updater = JobUpdater(job, cluster, store)
    updater._ensure_auth_token()
    tok = updater.job.spec.auth_token
    assert len(tok) == 32  # secrets.token_hex(16)
    # persisted: a controller restart replays the same token
    assert store.get("j1").spec.auth_token == tok
    # second call is a no-op (no token churn under running pods)
    updater._ensure_auth_token()
    assert updater.job.spec.auth_token == tok
    for role in ("trainer", "coordinator"):
        assert make_env(updater.job, role)["EDL_COORD_TOKEN"] == tok


def test_token_round_trips_spec_serialization():
    from edl_tpu.api.types import TrainingJobSpec

    spec = TrainingJobSpec.from_dict({"auth_token": "abc"})
    assert spec.auth_token == "abc"
    assert TrainingJobSpec.from_dict(spec.to_dict()).auth_token == "abc"


def test_actuator_authenticates_with_job_token():
    """The controller's own rescale writes (publish/nudge) must carry the
    job token — review regression: an actuator without it would silently
    degrade every auth-enabled job's rescale to the slow fallback path."""
    from edl_tpu.controller.actuation import CoordinatorActuator

    with CoordinatorServer(auth_token=TOKEN) as server:
        ok = CoordinatorActuator()
        ok.set_endpoint("job1", "127.0.0.1", server.port, token=TOKEN)
        assert ok.publish_expected_world("job1", 4)
        assert ok.nudge("job1")
        assert ok.publish_and_nudge("job1", 2)

        anon = CoordinatorActuator()
        anon.set_endpoint("job1", "127.0.0.1", server.port)
        assert not anon.publish_expected_world("job1", 4)

        with server.client("w") as c:
            assert c.kv_get("edl/expected_world") == "2"


def test_actuator_track_refreshes_token_after_admission():
    from edl_tpu.api.types import TrainingJob
    from edl_tpu.controller.actuation import CoordinatorActuator

    job = TrainingJob.from_dict({
        "metadata": {"name": "j2", "namespace": "default"},
        "spec": {"fault_tolerant": True},
    })
    act = CoordinatorActuator()
    act.track(job)  # admission-time: no token yet
    assert act._tokens.get("j2") is None
    job.spec.auth_token = "late-minted"
    act.track(job)  # the spec-update echo re-tracks with the token
    assert act._tokens["j2"] == "late-minted"
    # endpoint stays sticky (setdefault), token refreshed
    assert act._endpoints["j2"][0].startswith("j2-coordinator")
