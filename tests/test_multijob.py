"""Multi-job cluster: CTR + ResNet concurrent under one autoscaler.

The driver brief's cluster configuration (`BASELINE.json` configs: "Multi-job
cluster: CTR + ResNet concurrent (autoscaler global-util fairness)"), run
with REAL training processes: a CTR job fills the cluster, a ResNet job
arrives with nowhere to go, and the autoscaler's make-room pass (ref
`pkg/autoscaler.go:406-422`; narrative `doc/boss_tutorial.md:289-301`)
shrinks the running job so the newcomer trains instead of starving —
shrink-to-admit fairness over first-come-takes-all.
"""

import json
import sys
import time

import pytest

from conftest import multiprocess_on_cpu
from edl_tpu.api.quantity import ResourceList
from edl_tpu.controller.actuation import EXPECTED_WORLD_KEY, CoordinatorActuator
from edl_tpu.controller.autoscaler import Autoscaler, AutoscalerConfig
from edl_tpu.controller.cluster import NodeInfo
from edl_tpu.controller.jobparser import parse_to_trainer
from edl_tpu.controller.process_cluster import ProcessCluster
from edl_tpu.api.types import TrainingJob
from edl_tpu.api.validation import normalize
from edl_tpu.coordinator import CoordinatorServer
from edl_tpu.coordinator.server import ensure_built, free_port

from tests.test_actuation import LAUNCHER_SRC
from tests.test_multihost import REPO, WORKER_SRC


def _job(name, min_i, max_i, launcher, server, entry, ckpt, extra_env=None):
    env = {
        "EDL_COORDINATOR_ENDPOINT": server.address,
        "EDL_ENTRY": f"{sys.executable} {entry}",
        "CKPT_DIR": ckpt,
        "CKPT_INTERVAL": "60",
        "PYTHONUNBUFFERED": "1",  # pod logs must survive a hang diagnosis
        **(extra_env or {}),
    }
    return normalize(TrainingJob.from_dict({
        "metadata": {"name": name},
        "spec": {
            "fault_tolerant": True,
            "tpu": {"chips_per_trainer": 4},
            "trainer": {
                "min_instance": min_i,
                "max_instance": max_i,
                "entrypoint": f"{sys.executable} {launcher}",
                "resources": {"requests": {"cpu": 1}},
                "env": env,
            },
        },
    }))


@multiprocess_on_cpu
def test_ctr_and_resnet_share_cluster_fairly(tmp_path):
    """CTR at world 2 fills both hosts; a ResNet job lands Pending; the
    autoscaler shrinks CTR 2->1 (make-room), the freed chips place ResNet,
    and BOTH queues drain to completion — global-utilization fairness with
    two different real model families training concurrently."""
    ensure_built()
    launcher_py = tmp_path / "launcher.py"
    launcher_py.write_text(LAUNCHER_SRC.format(repo=REPO))

    ports = {"ctr": free_port(), "resnet": free_port()}
    entries = {}
    for tag in ("ctr", "resnet"):
        p = tmp_path / f"entry_{tag}.py"
        p.write_text(WORKER_SRC.format(repo=REPO, jax_port=ports[tag]))
        entries[tag] = p

    scale_records = []
    # Generous TTLs: first-jit compile stalls on one CPU core.
    with CoordinatorServer(task_lease_sec=120.0, heartbeat_ttl_sec=60.0) \
            as ctr_server, \
            CoordinatorServer(task_lease_sec=120.0, heartbeat_ttl_sec=60.0) \
            as rn_server:
        ctr_admin = ctr_server.client("admin")
        # Paced so the CTR job is mid-queue when the shrink lands, and world
        # 1 still drains the rest inside the test budget.
        ctr_admin.add_tasks([f"ctr/part-{i:05d}" for i in range(30)])
        rn_admin = rn_server.client("admin")
        rn_admin.add_tasks([f"rn/part-{i:05d}" for i in range(2)])

        ctr_job = _job(
            "ctrjob", 1, 2, launcher_py, ctr_server, entries["ctr"],
            str(tmp_path / "ck-ctr"),
            extra_env={"MODEL": "ctr_small", "BATCHES_PER_SHARD": "6",
                       "BATCH_SLEEP": "0.05",
                       "EDL_TERMINATION_LOG": str(tmp_path / "term-ctr")},
        )
        # min == max: not elastic, so never a shrink victim — but its
        # pending pod is exactly what triggers make-room on the CTR job.
        rn_job = _job(
            "rnjob", 1, 1, launcher_py, rn_server, entries["resnet"],
            str(tmp_path / "ck-rn"),
            extra_env={"MODEL": "resnet_tiny", "BATCHES_PER_SHARD": "2",
                       "EDL_TERMINATION_LOG": str(tmp_path / "term-rn")},
        )

        # 2 hosts x 4 chips: capacity for exactly 2 trainers at 4 chips.
        cluster = ProcessCluster(
            [NodeInfo(name=f"h{i}",
                      allocatable=ResourceList.make({"cpu": 16, "tpu": 4}))
             for i in range(2)],
            log_dir=str(tmp_path / "logs"),
        )
        try:
            ctr_trainer = parse_to_trainer(ctr_job)
            cluster.create_role("ctrjob", "trainer", 2, ctr_trainer.requests,
                                ctr_trainer.limits, workload=ctr_trainer)

            # real progress at world 2 before the contender shows up
            deadline = time.time() + 240
            while time.time() < deadline:
                if int(ctr_admin.status().get("done", 0)) >= 2:
                    break
                time.sleep(0.5)
            else:
                pytest.fail("CTR job never made progress at world 2")

            # ResNet arrives: no chips free -> its pod stays Pending.
            rn_trainer = parse_to_trainer(rn_job)
            cluster.create_role("rnjob", "trainer", 1, rn_trainer.requests,
                                rn_trainer.limits, workload=rn_trainer)
            assert [p.phase for p in cluster.job_pods("rnjob", "trainer")] \
                == ["Pending"]

            actuator = CoordinatorActuator()
            actuator.set_endpoint("ctrjob", "127.0.0.1", ctr_server.port)
            actuator.set_endpoint("rnjob", "127.0.0.1", rn_server.port)
            scaler = Autoscaler(cluster, AutoscalerConfig(loop_seconds=0.5))
            scaler.actuator = actuator
            scaler.on_scaled = lambda name, rec: scale_records.append((name, rec))
            scaler.on_add(ctr_job)
            scaler.on_add(rn_job)
            scaler.start()
            try:
                deadline = time.time() + 90
                while time.time() < deadline:
                    pods = cluster.job_pods("rnjob", "trainer")
                    if pods and all(p.phase == "Running" for p in pods):
                        break
                    time.sleep(0.3)
                else:
                    pytest.fail(
                        f"ResNet pod never placed; records={scale_records}"
                    )
            finally:
                scaler.stop()

            # the decision was the make-room shrink of the elastic CTR job
            assert any(
                name == "ctrjob"
                and (rec.from_replicas, rec.to_replicas) == (2, 1)
                and rec.reason == "make-room"
                for name, rec in scale_records
            ), scale_records
            assert ctr_admin.kv_get(EXPECTED_WORLD_KEY) == "1"

            # both jobs drain to completion, concurrently
            try:
                cluster.wait_all(timeout=420)
            except Exception:
                pods = [(p.info.name, p.info.phase) for p in cluster.pods]
                pytest.fail(
                    f"jobs never drained: ctr={ctr_admin.status()} "
                    f"rn={rn_admin.status()} pods={pods} "
                    f"records={scale_records}"
                )
            assert all(p.phase == "Succeeded"
                       for p in cluster.job_pods("rnjob", "trainer"))
            ctr_pods = cluster.job_pods("ctrjob", "trainer")
            assert len(ctr_pods) == 1  # the post-shrink survivor
            assert ctr_pods[0].phase == "Succeeded"
            ctr_st = ctr_admin.status()
            rn_st = rn_admin.status()
            assert int(ctr_st["queued"]) == 0 and int(ctr_st["leased"]) == 0
            assert int(rn_st["queued"]) == 0 and int(rn_st["leased"]) == 0
        finally:
            cluster.shutdown()

    # final incarnations: CTR survivor reports world 1; ResNet world 1
    finals = {}
    for log_file in (tmp_path / "logs").iterdir():
        lines = [l for l in log_file.read_text().splitlines()
                 if l.startswith("METRICS ")]
        if lines:
            finals[log_file.name] = json.loads(lines[-1][len("METRICS "):])
    ctr_finals = [m for n, m in finals.items() if n.startswith("ctrjob")]
    rn_finals = [m for n, m in finals.items() if n.startswith("rnjob")]
    assert any(m["world"] == 1.0 and m["steps"] > 0 for m in ctr_finals)
    assert len(rn_finals) == 1 and rn_finals[0]["world"] == 1.0
    assert rn_finals[0]["steps"] == 4.0  # 2 shards x 2 batches
