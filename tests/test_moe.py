"""Mixture-of-experts transformer FFN: the expert axis for dense models.

The reference's closest thing to expert parallelism is pserver-sharded
embedding tables (SURVEY §2.3 marks MoE itself absent); this extends the
`expert` mesh axis to transformer FFNs — switch routing with an
`all_to_all` dispatch inside the shard_map kernel — and pins the
invariants that make it trustworthy: expert parallelism changes layout,
never math; one expert degenerates to the dense FFN; capacity drops are
total, not corrupting.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.models import transformer
from edl_tpu.parallel import MeshSpec, build_mesh
from edl_tpu.runtime import Trainer, TrainerConfig

CFG = transformer.TransformerConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=8, d_ff=64, seq_len=16,
    moe_experts=4,
    # no-drop capacity: layout invariance is only exact when no token is
    # ever dropped (capacity is per-device-group, hence layout-dependent)
    moe_capacity_factor=8.0,
)


def _run(axes, cfg, batch, n_dev=None):
    devs = jax.devices()[: n_dev or 8]
    mesh = build_mesh(MeshSpec(axes), devs)
    model = transformer.make_model(cfg)
    params = model.init(jax.random.PRNGKey(0), mesh)
    placed = {
        k: jax.device_put(
            jnp.asarray(v),
            jax.sharding.NamedSharding(mesh, model.batch_spec(mesh)[k]),
        )
        for k, v in batch.items()
    }
    loss, grads = jax.jit(jax.value_and_grad(
        lambda p, b: model.loss_fn(p, b, mesh)
    ))(params, placed)
    return float(loss), grads


def test_expert_parallel_changes_layout_not_math():
    batch = transformer.synthetic_batch(CFG, np.random.default_rng(0), 8)
    l_ref, g_ref = _run({"data": 1}, CFG, batch, n_dev=1)
    sharded = dataclasses.replace(CFG, batch_axis=("data", "expert"))
    l_ep, g_ep = _run({"data": 2, "expert": 4}, sharded, batch)
    assert l_ep == pytest.approx(l_ref, rel=2e-2)
    for a, b in zip(jax.tree_util.tree_leaves(g_ref),
                    jax.tree_util.tree_leaves(g_ep)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=8e-2, atol=1.5e-3)


def test_tokens_replicated_over_expert_axis_also_correct():
    """batch_axis without the expert axis (tokens replicated across it) is
    the redundant-but-legal layout — same loss as the oracle."""
    batch = transformer.synthetic_batch(CFG, np.random.default_rng(0), 8)
    l_ref, _ = _run({"data": 1}, CFG, batch, n_dev=1)
    l_rep, _ = _run({"data": 2, "expert": 4}, CFG, batch)
    assert l_rep == pytest.approx(l_ref, rel=2e-2)


def test_single_expert_equals_dense_ffn():
    """E=1 with no drops IS the dense FFN (gate = softmax over one logit
    = 1): same loss with the dense weights copied in."""
    moe_cfg = dataclasses.replace(CFG, moe_experts=1)
    dense_cfg = dataclasses.replace(CFG, moe_experts=0)
    mesh = build_mesh(MeshSpec({"data": 1}), jax.devices()[:1])
    moe = transformer.make_model(moe_cfg)
    dense = transformer.make_model(dense_cfg)
    mp = moe.init(jax.random.PRNGKey(0), mesh)
    dp = dense.init(jax.random.PRNGKey(1), mesh)
    # graft the single expert's weights into the dense slots
    dp["blocks"]["win"] = mp["blocks"]["w_up"][:, 0]
    dp["blocks"]["bin"] = mp["blocks"]["b_up"][:, 0]
    dp["blocks"]["wout"] = mp["blocks"]["w_down"][:, 0]
    dp["blocks"]["bout"] = mp["blocks"]["b_down"][:, 0]
    for k in ("embed", "pos", "lnf", "head"):
        dp[k] = mp[k]
    for k in ("ln1", "wqkv", "bqkv", "wo", "bo", "ln2"):
        dp["blocks"][k] = mp["blocks"][k]
    batch = transformer.synthetic_batch(moe_cfg, np.random.default_rng(0), 4)
    placed = {k: jnp.asarray(v) for k, v in batch.items()}
    l_moe = float(moe.loss_fn(mp, placed, mesh))
    l_dense = float(dense.loss_fn(dp, placed, mesh))
    assert l_moe == pytest.approx(l_dense, rel=1e-3)


def test_moe_trains_on_expert_mesh():
    cfg = dataclasses.replace(CFG, moe_capacity_factor=2.0,
                              batch_axis=("data", "expert"))
    mesh = build_mesh(MeshSpec({"data": 2, "expert": 4}))
    model = transformer.make_model(cfg)
    trainer = Trainer(model, mesh,
                      TrainerConfig(optimizer="adam", learning_rate=1e-3,
                                    batch_axis=("data", "expert")))
    state = trainer.init_state()
    batch = model.synthetic_batch(np.random.default_rng(1), 8)
    placed = trainer.place_batch(batch)
    losses = []
    for _ in range(8):
        state, loss = trainer.train_step(state, placed)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_capacity_drops_are_total_not_corrupting():
    """A tiny capacity drops tokens (their FFN output is zero; the
    residual passes through) — loss stays finite and close to the
    no-drop loss at this scale, never NaN."""
    tight = dataclasses.replace(CFG, moe_capacity_factor=0.25)
    batch = transformer.synthetic_batch(tight, np.random.default_rng(0), 4)
    l_tight, g = _run({"data": 1}, tight, batch, n_dev=1)
    assert np.isfinite(l_tight)
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.isfinite(np.asarray(leaf, np.float32)).all()


def test_moe_flops_accounting():
    dense = transformer.make_model(dataclasses.replace(CFG, moe_experts=0))
    moe = transformer.make_model(CFG)
    # top-1 routing: only the router matmul is extra
    extra = 3.0 * 2 * CFG.d_model * CFG.moe_experts * CFG.n_layers \
        * CFG.seq_len * 4
    assert moe.flops_per_step(4) - dense.flops_per_step(4) == \
        pytest.approx(extra)


def test_indivisible_experts_raise():
    bad = dataclasses.replace(CFG, moe_experts=3)
    mesh = build_mesh(MeshSpec({"expert": 4, "data": 2}))
    with pytest.raises(ValueError, match="moe_experts"):
        transformer.make_model(bad).init(jax.random.PRNGKey(0), mesh)


def test_aux_loss_value_and_training():
    """Switch aux = E * sum_e f_e p_e: 1.0 at uniform routing, up to E when
    collapsed. With the weight on, the loss carries the term and the model
    still trains on the expert mesh."""
    aux_cfg = dataclasses.replace(CFG, moe_aux_weight=0.05,
                                  batch_axis=("data", "expert"),
                                  moe_capacity_factor=2.0)
    mesh = build_mesh(MeshSpec({"data": 2, "expert": 4}))
    model = transformer.make_model(aux_cfg)
    plain = transformer.make_model(dataclasses.replace(aux_cfg,
                                                       moe_aux_weight=0.0))
    params = model.init(jax.random.PRNGKey(0), mesh)
    batch = model.synthetic_batch(np.random.default_rng(0), 8)
    placed = {
        k: jax.device_put(
            jnp.asarray(v),
            jax.sharding.NamedSharding(mesh, model.batch_spec(mesh)[k]),
        )
        for k, v in batch.items()
    }
    l_aux = float(model.loss_fn(params, placed, mesh))
    l_plain = float(plain.loss_fn(params, placed, mesh))
    # the aux term is positive and bounded by weight * E
    assert l_plain < l_aux <= l_plain + 0.05 * aux_cfg.moe_experts + 1e-4

    trainer = Trainer(model, mesh,
                      TrainerConfig(optimizer="adam", learning_rate=1e-3,
                                    batch_axis=("data", "expert")))
    state = trainer.init_state()
    losses = []
    for _ in range(6):
        state, loss = trainer.train_step(state, trainer.place_batch(batch))
        losses.append(float(loss))
    assert losses[-1] < losses[0]


_AUX_ORACLE: dict = {}


@pytest.mark.parametrize(
    "schedule,v",
    [("gpipe", 1), ("1f1b", 1), ("1f1b-interleaved", 2)],
)
def test_aux_rides_every_pipeline_schedule(schedule, v):
    """The load-balance aux term is computed per stage inside the stage
    function and psum'd over the pipe axis, so moe_aux_weight > 0 composes
    with every schedule. Loss and grads must match the no-pipe oracle
    (per-microbatch aux averaged over M vs whole-batch aux differ only by
    routing-stat reassociation at this scale)."""
    cfg = dataclasses.replace(
        CFG, n_layers=4, moe_aux_weight=0.01,
        batch_axis=("data", "expert"), pipeline_schedule=schedule,
        virtual_stages=v, microbatches=4,
    )
    batch = transformer.synthetic_batch(cfg, np.random.default_rng(0), 16)
    if not _AUX_ORACLE:  # identical across params — compile/run it once
        oracle = dataclasses.replace(
            cfg, pipeline_schedule="gpipe", virtual_stages=1,
            microbatches=None,
        )
        _AUX_ORACLE["ref"] = _run({"data": 1}, oracle, batch, n_dev=1)
    l_ref, g_ref = _AUX_ORACLE["ref"]
    l_pp, g_pp = _run({"pipe": 2, "data": 2, "expert": 2}, cfg, batch)
    assert l_pp == pytest.approx(l_ref, rel=2e-2)
    if schedule == "1f1b-interleaved" and v > 1:
        from edl_tpu.parallel.pipeline import interleaved_layout

        inv = np.argsort(interleaved_layout(cfg.n_layers, 2, v))
        g_pp = dict(g_pp)
        g_pp["blocks"] = {k: a[inv] for k, a in g_pp["blocks"].items()}
    flat_ref, _ = jax.tree_util.tree_flatten_with_path(g_ref)
    flat_pp = jax.tree_util.tree_leaves(g_pp)
    for (path, a), b in zip(flat_ref, flat_pp):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=1e-1, atol=2e-3,
                                   err_msg=str(path))


def test_aux_trains_under_interleaved_pipeline():
    """End-to-end Trainer loop: MoE + aux loss + interleaved 1f1b on a
    pipe x data x expert mesh — the composition the guard used to forbid."""
    cfg = dataclasses.replace(
        CFG, n_layers=4, moe_aux_weight=0.01, moe_capacity_factor=2.0,
        batch_axis=("data", "expert"),
        pipeline_schedule="1f1b-interleaved", virtual_stages=2,
        microbatches=4,
    )
    mesh = build_mesh(MeshSpec({"pipe": 2, "data": 2, "expert": 2}))
    model = transformer.make_model(cfg)
    trainer = Trainer(model, mesh,
                      TrainerConfig(optimizer="adam", learning_rate=1e-3,
                                    batch_axis=("data", "expert")))
    state = trainer.init_state()
    batch = model.synthetic_batch(np.random.default_rng(1), 16)
    placed = trainer.place_batch(batch)
    losses = []
    for _ in range(8):
        state, loss = trainer.train_step(state, placed)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_moe_composes_with_sequence_parallelism():
    """Ring attention on the seq axis + expert dispatch on the expert axis
    in one kernel — the composition must still match the oracle."""
    cfg = dataclasses.replace(CFG, batch_axis=("data", "expert"))
    batch = transformer.synthetic_batch(cfg, np.random.default_rng(0), 8)
    l_ref, g_ref = _run({"data": 1}, cfg, batch, n_dev=1)
    l_mix, g_mix = _run({"expert": 4, "seq": 2}, cfg, batch)
    assert l_mix == pytest.approx(l_ref, rel=2e-2)
    for a, b in zip(jax.tree_util.tree_leaves(g_ref),
                    jax.tree_util.tree_leaves(g_mix)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=8e-2, atol=1.5e-3)


def test_top2_with_all_experts_equals_soft_mixture():
    """E=2, k=2, no drops: every token visits both experts and the
    renormalized gates are exactly the softmax probs — the layer must
    equal the dense soft mixture computed directly from the weights."""
    cfg = dataclasses.replace(CFG, moe_experts=2, moe_top_k=2,
                              moe_capacity_factor=4.0)
    mesh = build_mesh(MeshSpec({"data": 1}), jax.devices()[:1])
    model = transformer.make_model(cfg)
    params = model.init(jax.random.PRNGKey(0), mesh)
    rng = np.random.default_rng(3)
    h = jnp.asarray(rng.standard_normal((2, 16, cfg.d_model)), jnp.bfloat16)
    bp = jax.tree_util.tree_map(lambda a: a[0], params["blocks"])

    got, _ = transformer._moe_ffn(cfg, mesh, h, bp)

    tok = h.reshape(-1, cfg.d_model)
    probs = jax.nn.softmax(
        jnp.einsum("td,de->te", tok.astype(jnp.float32), bp["router"]), -1
    )
    def expert(e):
        up = tok @ bp["w_up"][e].astype(jnp.bfloat16) \
            + bp["b_up"][e].astype(jnp.bfloat16)
        return jax.nn.gelu(up) @ bp["w_down"][e].astype(jnp.bfloat16) \
            + bp["b_down"][e].astype(jnp.bfloat16)
    want = sum(probs[:, e:e + 1].astype(jnp.bfloat16) * expert(e)
               for e in range(2)).reshape(got.shape)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=5e-2, atol=5e-2)


def test_top2_layout_invariance_and_training():
    cfg = dataclasses.replace(CFG, moe_top_k=2, moe_capacity_factor=8.0,
                              batch_axis=("data", "expert"))
    batch = transformer.synthetic_batch(cfg, np.random.default_rng(0), 8)
    l_ref, g_ref = _run({"data": 1}, cfg, batch, n_dev=1)
    l_ep, g_ep = _run({"data": 2, "expert": 4}, cfg, batch)
    assert l_ep == pytest.approx(l_ref, rel=2e-2)
    for a, b in zip(jax.tree_util.tree_leaves(g_ref),
                    jax.tree_util.tree_leaves(g_ep)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=8e-2, atol=1.5e-3)

    mesh = build_mesh(MeshSpec({"data": 2, "expert": 4}))
    model = transformer.make_model(
        dataclasses.replace(cfg, moe_capacity_factor=1.5))
    trainer = Trainer(model, mesh,
                      TrainerConfig(optimizer="adam", learning_rate=1e-3,
                                    batch_axis=("data", "expert")))
    state = trainer.init_state()
    placed = trainer.place_batch(batch)
    losses = []
    for _ in range(6):
        state, loss = trainer.train_step(state, placed)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_top_k_out_of_range_raises():
    bad = dataclasses.replace(CFG, moe_top_k=5)  # > moe_experts=4
    mesh = build_mesh(MeshSpec({"data": 8}))
    with pytest.raises(ValueError, match="moe_top_k"):
        transformer.make_model(bad).init(jax.random.PRNGKey(0), mesh)
