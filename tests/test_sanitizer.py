"""Sanitizer lane: drive the TSan/ASan-instrumented coordinator binary.

These tests only run when ``EDL_COORD_SANITIZER`` is set (``make tsan-smoke``
exports ``tsan``); in a plain tier-1 run they skip, so the lane costs nothing
unless explicitly requested. With the env var set, every
:class:`CoordinatorServer` in the process — including the chaos/outage/batch
tests that share the ``sanitizer`` mark — builds and spawns the instrumented
variant, the child exits 66 on a sanitizer report (TSAN_OPTIONS/ASAN_OPTIONS
set by ``server.start()``), and :meth:`CoordinatorServer.sanitizer_report`
surfaces the stderr so the assertion failure carries the actual report.

The hammer here is deliberately contention-heavy: concurrent registration,
KV increments on a shared key, task queue churn, and barriers — the code
paths where the dispatch thread, TTL sweeper, and deferred-release logic
interleave.
"""

import os
import threading

import pytest

from edl_tpu.coordinator import CoordinatorServer
from edl_tpu.coordinator.client import CoordinatorError
from edl_tpu.coordinator.server import (
    SANITIZER_VARIANTS,
    ensure_built,
    sanitizer_variant,
)

pytestmark = pytest.mark.sanitizer

_ACTIVE = os.environ.get("EDL_COORD_SANITIZER", "").strip().lower()

needs_sanitizer = pytest.mark.skipif(
    not _ACTIVE,
    reason="EDL_COORD_SANITIZER not set (run via `make tsan-smoke`)",
)


def _server(**kw) -> CoordinatorServer:
    try:
        ensure_built()
    except CoordinatorError as e:
        pytest.skip(f"sanitizer toolchain unavailable: {str(e)[:200]}")
    return CoordinatorServer(**kw)


def _assert_clean(server: CoordinatorServer) -> None:
    report = server.sanitizer_report()
    assert "ThreadSanitizer" not in report, report[-4000:]
    assert "AddressSanitizer" not in report, report[-4000:]
    assert "runtime error:" not in report, report[-4000:]  # UBSan


@needs_sanitizer
def test_variant_selects_instrumented_binary():
    variant = sanitizer_variant()
    assert variant in SANITIZER_VARIANTS and variant != ""
    binary = ensure_built()
    assert binary.endswith(SANITIZER_VARIANTS[variant])


@needs_sanitizer
def test_concurrent_clients_hammer_is_race_free():
    """N threads × (register, heartbeat, shared kv_incr, queue churn) — the
    hottest mutex neighborhoods in the dispatcher, under the sanitizer."""
    n_workers, iters = 4, 12  # TSan is ~10x slower; keep the soak bounded
    with _server(task_lease_sec=2.0, heartbeat_ttl_sec=5.0) as server:
        with server.client("seed") as seeder:
            seeder.register()
            seeder.add_tasks([f"t{i}" for i in range(n_workers * iters)])
        errors = []

        def churn(i: int) -> None:
            try:
                with server.client(f"ham-{i}") as c:
                    c.register()
                    for _ in range(iters):
                        c.heartbeat()
                        c.kv_incr("shared-counter")
                        task = c.acquire_task()
                        if task is not None:
                            c.complete_task(task)
                    c.leave()
            except Exception as e:  # surface, don't deadlock the join below
                errors.append(e)

        threads = [
            threading.Thread(target=churn, args=(i,)) for i in range(n_workers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        with server.client("check") as c:
            c.register()
            assert int(c.kv_get("shared-counter")) == n_workers * iters
        assert server.poll() is None, (
            f"coordinator died under load (rc={server.poll()}): "
            + server.sanitizer_report()[-4000:]
        )
    _assert_clean(server)


@needs_sanitizer
def test_barrier_rendezvous_under_sanitizer():
    """Barriers park fds for deferred release — the cross-thread handoff the
    epoch-stamp conformance pass (EDL007) models; prove it data-race-free."""
    n = 3
    with _server() as server:
        clients = [server.client(f"bar-{i}") for i in range(n)]
        for c in clients:
            c.register()
        results = [None] * n

        def arrive(i: int) -> None:
            results[i] = clients[i].barrier("san-step", n, timeout=30.0)

        threads = [threading.Thread(target=arrive, args=(i,)) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert all(r is not None and r["ok"] for r in results), results
        for c in clients:
            c.leave()
            c.close()
    _assert_clean(server)


@needs_sanitizer
def test_kill_restart_cycle_reports_accumulate(tmp_path):
    """SIGKILL mid-flight then restart on the same state file: the report
    harvest must survive the respawn (a TSan hit in incarnation 1 may only
    print at exit) and the resumed process must stay clean."""
    state = str(tmp_path / "san-state.jsonl")
    server = _server(state_file=state, run_id="san-run")
    server.start()
    try:
        with server.client("w0") as c:
            c.register()
            c.add_tasks(["a", "b", "c"])
            c.kv_put("k", "v1")
        server.kill()
        server.restart()
        with server.client("w0") as c:
            c.register(takeover=True)
            assert c.kv_get("k") == "v1"
            assert c.status()["queued"] >= 1
    finally:
        server.stop()
    assert server.poll() != 66, (
        "sanitizer exit code: " + server.sanitizer_report()[-4000:]
    )
    _assert_clean(server)
