"""Telemetry-plane acceptance test (ISSUE 9): drive a REAL elastic rescale
while scraping the worker's live `/metrics` endpoint over HTTP.

Asserts the two ends of the tentpole in one run:

- the scrape parses as Prometheus text exposition (``parse_prometheus``
  raising is a failure) and carries metric families from all three layers —
  worker runtime, coordinator client transport, and the BRIDGED native
  coordinator's status counters — on one page;
- the rescale trace contains every lifecycle phase (drain, checkpoint,
  warm_compile, restore, first_step) with strictly positive durations, all
  under ONE shared rescale trace id, with the worker-side spans correlated
  purely through the membership epoch.
"""

import threading
import time

from edl_tpu.coordinator import CoordinatorServer
from edl_tpu.models import fit_a_line
from edl_tpu.obs.http import scrape_metrics
from edl_tpu.obs.metrics import parse_prometheus
from edl_tpu.obs.tracing import RESCALE_PHASES, Tracer, rescale_timeline
from edl_tpu.runtime import TrainerConfig
from edl_tpu.runtime.data import SyntheticShardSource, shard_names
from edl_tpu.runtime.elastic import ElasticConfig, ElasticWorker
from edl_tpu.tools.profiler import StepProfiler

#: at least one family per instrumented layer must appear on the one scrape.
WORKER_FAMILIES = ("edl_worker_epoch", "edl_worker_steps_total",
                   "edl_worker_heartbeat_latency_seconds")
CLIENT_FAMILIES = ("edl_client_calls_total",)
COORDINATOR_FAMILIES = ("edl_coordinator_up", "edl_coordinator_ops",
                        "edl_coordinator_journal_records")


def test_rescale_scraped_live_with_full_lifecycle_trace(tmp_path):
    model = fit_a_line.MODEL
    tracer = Tracer()
    scrape = {"text": ""}
    stop_flag = threading.Event()

    with CoordinatorServer(task_lease_sec=60.0,
                           heartbeat_ttl_sec=60.0) as server:
        admin = server.client("admin")
        admin.add_tasks(shard_names("obs", 6))
        cfg = ElasticConfig(
            checkpoint_dir=str(tmp_path / "ck"),
            checkpoint_interval=5,
            heartbeat_interval=0.0,  # check epoch every batch
            rescale_barrier_timeout=30.0,
            metrics_port=0,  # embedded endpoint on an ephemeral port
            trainer=TrainerConfig(optimizer="sgd", learning_rate=0.05),
        )
        worker = ElasticWorker(
            model,
            server.client("trainer-0"),
            SyntheticShardSource(model, batch_size=32, batches_per_shard=8),
            cfg,
            profiler=StepProfiler(warmup=1),
            tracer=tracer,
        )

        def scraper():
            # keep the LAST successful scrape: the endpoint only exists while
            # the worker runs, so success here proves scrape-during-training.
            while not stop_flag.is_set():
                url = getattr(worker, "metrics_url", None)
                if url:
                    try:
                        scrape["text"] = scrape_metrics(url, timeout=5.0)
                    except OSError:
                        pass  # booting or already torn down
                time.sleep(0.05)

        def joiner():
            # the second trainer arrives mid-run: membership event -> epoch
            # bump -> the worker's 4->8 device rescale (test_elastic's flow).
            while worker.steps_done < 5 and not stop_flag.is_set():
                time.sleep(0.05)
            c = server.client("trainer-1")
            epoch = c.register()["epoch"]
            while not stop_flag.is_set():
                reply = c.sync(epoch, timeout=5.0)
                if reply.get("ok"):
                    break
                epoch = reply.get("epoch", epoch)
            while not stop_flag.is_set():
                hb = c.heartbeat()
                if hb.get("ok") and hb["epoch"] != epoch:
                    epoch = hb["epoch"]
                    c.sync(epoch, timeout=5.0)
                time.sleep(0.3)

        threads = [threading.Thread(target=scraper, daemon=True),
                   threading.Thread(target=joiner, daemon=True)]
        for t in threads:
            t.start()
        try:
            metrics = worker.run()
        finally:
            stop_flag.set()
            for t in threads:
                t.join(timeout=10)

    assert metrics["rescales"] >= 1, metrics

    # -- (a) live scrape parses and carries all three layers -------------------
    assert scrape["text"], "no successful /metrics scrape during the run"
    families = parse_prometheus(scrape["text"])  # ValueError == malformed
    for fam in WORKER_FAMILIES + CLIENT_FAMILIES + COORDINATOR_FAMILIES:
        assert fam in families, (fam, sorted(families))
    # the bridge's scrape-time status poll actually reached the coordinator
    assert families["edl_coordinator_up"]["samples"][
        "edl_coordinator_up"] == 1.0

    # -- (b) full lifecycle under one shared rescale id -------------------------
    timeline = rescale_timeline(tracer.spans)
    complete = {
        tid: t for tid, t in timeline.items()
        if all(p in t["phases"] for p in RESCALE_PHASES)
    }
    assert complete, {tid: sorted(t["phases"]) for tid, t in timeline.items()}
    tid, t = sorted(complete.items())[-1]  # latest epoch = the rescale
    for phase in RESCALE_PHASES:
        assert t["phases"][phase]["seconds"] > 0.0, (phase, t)
        assert t["phases"][phase]["component"] == "worker"
    # phases of ONE rescale nest inside its wall interval
    assert t["wall_seconds"] > 0.0
    assert t["span_count"] >= len(RESCALE_PHASES)
    # warm_compile deliberately overlaps restore (it runs on a background
    # thread); both must still start after the checkpoint that drained.
    assert t["phases"]["warm_compile"]["start"] >= \
        t["phases"]["checkpoint"]["start"]
    assert t["phases"]["first_step"]["end"] >= \
        t["phases"]["restore"]["end"]
