"""Two-slice (DCN-hierarchical) meshes: construction + cross-slice training.

SURVEY §2.4 promises multi-slice scale-out: an outer ``dcn`` data axis whose
once-per-step gradient reduction crosses the data-center network while every
other collective stays inside one ICI slice. These tests build that mesh on
the virtual 8-device host (2 fictional slices x 4) and prove the training
math is layout-invariant — the same guarantee `test_loss_identical_across_
mesh_layouts` gives for single-slice meshes.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.models import ctr, transformer
from edl_tpu.parallel import MeshSpec, build_hierarchical_mesh, build_mesh
from edl_tpu.runtime import Trainer, TrainerConfig


def test_hierarchical_mesh_shape_and_slice_locality():
    mesh = build_hierarchical_mesh(MeshSpec({"dcn": 2, "data": 2, "model": 2}))
    assert mesh.axis_names == ("dcn", "data", "model")
    assert dict(mesh.shape) == {"dcn": 2, "data": 2, "model": 2}
    # inner axes never straddle the slice boundary: with the virtual even
    # split, slice 0 holds the 4 lowest-id devices
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    assert set(ids[0].ravel()) == {0, 1, 2, 3}
    assert set(ids[1].ravel()) == {4, 5, 6, 7}


def test_hierarchical_mesh_rejects_bad_split():
    with pytest.raises(ValueError):
        build_hierarchical_mesh(MeshSpec({"dcn": 2, "data": 2}))  # 4 != 8


def test_dcn1_falls_back_to_flat_mesh():
    mesh = build_hierarchical_mesh(MeshSpec({"data": 8}))
    assert mesh.axis_names == ("data",)


def test_ctr_trains_across_slices():
    """XLA-partitioner path: batch sharded over ("dcn", "data") makes the
    gradient all-reduce hierarchical; embedding tables stay slice-internal
    on the expert axis."""
    mesh = build_hierarchical_mesh(MeshSpec({"dcn": 2, "data": 2, "expert": 2}))
    model = ctr.make_model(shard_axis="expert",
                           batch_axis=("dcn", "data"), sparse_dim=4097)
    trainer = Trainer(
        model, mesh,
        TrainerConfig(optimizer="adagrad", learning_rate=0.05,
                      batch_axis=("dcn", "data")),
    )
    state = trainer.init_state()
    batch = model.synthetic_batch(np.random.default_rng(0), 32)
    placed = trainer.place_batch(batch)
    first = placed["dense"].sharding.spec
    assert first[0] in (("dcn", "data"), "dcn")  # leading dim crosses slices
    losses = []
    for _ in range(4):
        state, loss = trainer.train_step(state, placed)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_transformer_loss_matches_across_slice_layouts():
    """shard_map path: dp over ("dcn", "data") with sp+tp inside the slice
    must reproduce the flat-mesh loss AND gradients."""
    cfg = transformer.TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=8, d_ff=64, seq_len=16,
    )
    batch = transformer.synthetic_batch(cfg, np.random.default_rng(0), 8)

    def run(mesh, model):
        params = model.init(jax.random.PRNGKey(0), mesh)
        placed = {
            k: jax.device_put(
                jnp.asarray(v),
                jax.sharding.NamedSharding(mesh, model.batch_spec(mesh)[k]),
            )
            for k, v in batch.items()
        }
        loss, grads = jax.jit(jax.value_and_grad(
            lambda p, b: model.loss_fn(p, b, mesh)
        ))(params, placed)
        return float(loss), grads

    l_ref, g_ref = run(build_mesh(MeshSpec({"data": 8})),
                       transformer.make_model(cfg))
    two_slice = dataclasses.replace(cfg, batch_axis=("dcn", "data"))
    l_dcn, g_dcn = run(
        build_hierarchical_mesh(MeshSpec({"dcn": 2, "data": 2, "model": 2})),
        transformer.make_model(two_slice),
    )
    assert l_dcn == pytest.approx(l_ref, rel=2e-2)
    # cross-LAYOUT comparison: bf16 matmuls reduce in different orders on
    # the two meshes, so small-magnitude grads wobble ~1e-3 absolute
    for a, b in zip(jax.tree_util.tree_leaves(g_ref),
                    jax.tree_util.tree_leaves(g_dcn)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=8e-2, atol=1.5e-3)


def test_zero1_shards_over_slice_hierarchy():
    """ZeRO-1 moment sharding spreads over the full ("dcn", "data")
    hierarchy, not just the inner data axis."""
    mesh = build_hierarchical_mesh(MeshSpec({"dcn": 2, "data": 4}))
    cfg = transformer.TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=8, d_ff=64, seq_len=16,
        batch_axis=("dcn", "data"),
    )
    model = transformer.make_model(cfg)
    trainer = Trainer(
        model, mesh,
        TrainerConfig(optimizer="adam", learning_rate=1e-3,
                      batch_axis=("dcn", "data"), shard_opt_state=True),
    )
    state = trainer.init_state()
    mu_embed = state.opt_state[0].mu["embed"]
    spec = mu_embed.sharding.spec
    assert tuple(spec)[0] == ("dcn", "data"), spec
    batch = model.synthetic_batch(np.random.default_rng(0), 8)
    state, loss = trainer.train_step(state, trainer.place_batch(batch))
    assert np.isfinite(float(loss))
