"""Launcher + discovery + collector + CLI tests (ref components C11-C13, C1).

The reference had no automated coverage for `paddle_k8s`/`k8s_tools.py`; we
exercise the equivalents end-to-end against the in-process coordinator and
FakeCluster.
"""

import io
import json
import os
import sys
import time

import pytest

from edl_tpu.api import ResourceList, TrainingJob
from edl_tpu.api.types import JobPhase
from edl_tpu.controller import Controller, FakeCluster, JobStore, NodeInfo
from edl_tpu.controller.autoscaler import AutoscalerConfig
from edl_tpu.controller.updater import UpdaterConfig
from edl_tpu.coordinator.inprocess import InProcessCoordinator
from edl_tpu.launcher.launch import (
    FAILED_COUNT_KEY,
    LaunchContext,
    check_failed_count,
    map_exit_code,
)
from edl_tpu.tools.collector import Collector


class TestLaunchContext:
    def test_from_env_roundtrip(self):
        env = {
            "EDL_JOB_NAME": "ctr",
            "EDL_ROLE": "trainer",
            "EDL_COORDINATOR_ENDPOINT": "ctr-coordinator.default:7164",
            "EDL_NUM_TRAINERS": "4",
            "EDL_MAX_TRAINERS": "10",
            "EDL_FAULT_TOLERANT": "1",
            "EDL_MESH_AXES": json.dumps({"data": 4, "expert": 2}),
            "EDL_DATA_SHARDS": json.dumps(["s0", "s1"]),
            "EDL_ENTRY": "python train.py",
        }
        ctx = LaunchContext.from_env(env)
        assert ctx.job_name == "ctr"
        assert ctx.num_trainers == 4
        assert ctx.mesh_axes == {"data": 4, "expert": 2}
        assert ctx.data_shards == ["s0", "s1"]
        # FT budget = largest trainer count; strict budget = 0
        # (ref: paddle_k8s:123,147, adapted for elastic scale-up).
        assert ctx.failure_threshold == 10
        ctx.fault_tolerant = False
        assert ctx.failure_threshold == 0

    def test_exit_code_mapping(self):
        # ref: docker/paddle_k8s:44-60; both shell (128+N) and subprocess (-N)
        # encodings of a signal death must map.
        assert "Floating point" in map_exit_code(136)
        assert "Segmentation" in map_exit_code(139)
        assert "Abort" in map_exit_code(134)
        assert "Segmentation" in map_exit_code(-11)
        assert "Abort" in map_exit_code(-6)
        assert map_exit_code(0) == "Succeeded"
        assert "3" in map_exit_code(3)


class TestFailureBudget:
    def test_gate_and_bump(self):
        coord = InProcessCoordinator()
        client = coord.client("w0")
        assert check_failed_count(client, threshold=0) == 0
        client.kv_put(FAILED_COUNT_KEY, "1")
        with pytest.raises(RuntimeError, match="budget exhausted"):
            check_failed_count(client, threshold=0)
        # FT job with budget 4 tolerates it.
        assert check_failed_count(client, threshold=4) == 1

    def test_kv_incr_is_atomic_under_concurrency(self):
        import threading

        coord = InProcessCoordinator()

        def bump():
            c = coord.client("w")
            for _ in range(50):
                c.kv_incr("n")

        threads = [threading.Thread(target=bump) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert coord.client("w").kv_get("n") == "200"


class TestTrainerExec:
    def test_start_trainer_runs_entry_and_accounts_failure(self, tmp_path):
        """Full trainer-role flow against a live in-process coordinator server
        socket is covered by test_coordinator; here we drive start_trainer
        against the native server via localhost."""
        from edl_tpu.coordinator.server import CoordinatorServer
        from edl_tpu.launcher.launch import start_trainer

        with CoordinatorServer() as server:
            term = tmp_path / "term.log"
            ok = tmp_path / "ok.txt"
            ctx = LaunchContext(
                job_name="t",
                coordinator_endpoint=server.address,
                entry=f"{sys.executable} -c \"open(r'{ok}','w').write('hi')\"",
                termination_log=str(term),
            )
            assert start_trainer(ctx) == 0
            assert ok.read_text() == "hi"
            assert term.read_text() == "Succeeded"

            # Failing entry bumps the job-wide failure counter.
            ctx_fail = LaunchContext(
                job_name="t",
                coordinator_endpoint=server.address,
                entry=f"{sys.executable} -c 'raise SystemExit(3)'",
                termination_log=str(term),
            )
            assert start_trainer(ctx_fail) == 3
            assert "3" in term.read_text()
            with server.client("check") as c:
                assert c.kv_get(FAILED_COUNT_KEY) == "1"

            # Strict job (budget 0) now refuses to start new trainers.
            assert start_trainer(ctx) == 1
            assert "budget exhausted" in term.read_text()

    def test_start_trainer_sets_persistent_compile_cache(self, tmp_path,
                                                         monkeypatch):
        """Warm restarts re-run the same XLA program; the launcher points
        the entry at a pod-local persistent compile cache so the rescale
        budget pays the compile once. Explicit env (incl. empty = opt out)
        wins."""
        from edl_tpu.coordinator.server import CoordinatorServer
        from edl_tpu.launcher.launch import start_trainer

        monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
        with CoordinatorServer() as server:
            out = tmp_path / "env.txt"
            entry = (f"{sys.executable} -c \"import os; open(r'{out}','w')"
                     f".write(os.environ.get('JAX_COMPILATION_CACHE_DIR',''))\"")
            ctx = LaunchContext(
                job_name="cachejob", coordinator_endpoint=server.address,
                entry=entry, workspace=str(tmp_path),
                termination_log=str(tmp_path / "term"),
            )
            assert start_trainer(ctx) == 0
            cache_dir = out.read_text()
            assert cache_dir == str(tmp_path / "edl-xla-cache-cachejob")

            monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", "")
            assert start_trainer(ctx) == 0
            assert out.read_text() == ""  # explicit opt-out respected


def _nodes(n=2):
    return [
        NodeInfo(name=f"h{i}", allocatable=ResourceList.make(
            {"cpu": 8, "memory": "32Gi", "tpu": 8}))
        for i in range(n)
    ]


def _job(name, min_i=1, max_i=1, chips=4):
    return TrainingJob.from_dict({
        "metadata": {"name": name},
        "spec": {
            "image": "x",
            "tpu": {"chips_per_trainer": chips},
            "trainer": {
                "entrypoint": "python t.py",
                "min_instance": min_i,
                "max_instance": max_i,
                "resources": {"requests": {"cpu": 1, "memory": "1Gi"}},
            },
        },
    })


class TestCollector:
    def test_samples_jobs_and_utilization(self):
        cluster = FakeCluster(_nodes())
        ctl = Controller(
            cluster,
            store=JobStore(),
            autoscaler_config=AutoscalerConfig(loop_seconds=0.05),
            updater_config=UpdaterConfig(convert_seconds=0.05, poll_seconds=0.02),
        )
        ctl.start()
        sink = io.StringIO()
        collector = Collector(ctl.store, cluster, period_seconds=0.05, sink=sink)
        try:
            ctl.submit(_job("a", min_i=2, max_i=2))
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if ctl.job_status("a").status.phase == JobPhase.RUNNING:
                    break
                time.sleep(0.02)
            s = collector.sample()
            assert s.submitted_jobs == 1
            assert s.running_jobs == 1
            assert s.running_trainers["a"] == 2
            # 2 trainers x 4 chips over 16 chips = 50% TPU utilization.
            assert s.tpu_utilization == pytest.approx(0.5)
            line = json.loads(sink.getvalue().splitlines()[-1])
            assert line["running_trainers"]["a"] == 2
        finally:
            collector.stop()
            ctl.stop()


class TestCLI:
    def test_validate_and_run(self, tmp_path, capsys):
        from edl_tpu.cli import main

        yaml_path = tmp_path / "job.yaml"
        yaml_path.write_text(
            """
metadata: {name: demo}
spec:
  image: edl-tpu:test
  tpu: {chips_per_trainer: 4}
  trainer:
    entrypoint: python train.py
    min_instance: 2
    max_instance: 2
    resources:
      requests: {cpu: 1, memory: 1Gi}
"""
        )
        assert main(["validate", "-f", str(yaml_path)]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["metadata"]["name"] == "demo"
        assert out["spec"]["port"] == 7164  # defaulted

        bad = tmp_path / "bad.yaml"
        bad.write_text("metadata: {name: x}\nspec:\n  trainer: {min_instance: 5, max_instance: 1}\n")
        assert main(["validate", "-f", str(bad)]) == 1

    def test_train_smoke(self, capsys):
        from edl_tpu.cli import main

        rc = main(["train", "--model", "fit_a_line", "--steps", "5",
                   "--batch-size", "64"])
        assert rc == 0
        metrics = json.loads(capsys.readouterr().out)
        assert metrics["steps"] == 5


def test_start_coordinator_restart_resumes_queue(tmp_path):
    """Launcher-level durability: a coordinator role restarted in the same
    workspace restores its queue/done state and seeding is idempotent."""
    from edl_tpu.launcher.launch import LaunchContext, start_coordinator

    ctx = LaunchContext(
        job_name="j",
        workspace=str(tmp_path),
        port=0,  # replaced below; CoordinatorServer picks a free one if falsy
        data_shards=[f"s{i}" for i in range(4)],
    )
    from edl_tpu.coordinator.server import free_port

    ctx.port = free_port()
    server = start_coordinator(ctx, block=False)
    try:
        w = server.client("w")
        w.register()
        done = w.acquire_task()
        w.complete_task(done)
        import time as _t
        _t.sleep(0.3)  # event-loop save point
    finally:
        server.kill()

    server2 = start_coordinator(ctx, block=False)  # same workspace: resumes
    try:
        st = server2.client("probe").status()
        assert int(st["done"]) == 1          # survived the crash
        assert int(st["queued"]) == 3        # re-seed added nothing new
    finally:
        server2.stop()


def test_passes_trains_each_shard_per_pass(tmp_path):
    """spec.passes drives REAL multi-pass training (VERDICT r3 missing #1):
    the launcher seeds every pass's visit of every shard; a worker draining
    the queue reads each shard exactly `passes` times, and per-pass metrics
    come back. Ref: --num_passes wiring, docker/paddle_k8s:205-216."""
    from collections import Counter

    from edl_tpu.coordinator.client import CoordinatorClient
    from edl_tpu.coordinator.server import free_port
    from edl_tpu.models import fit_a_line
    from edl_tpu.runtime import (
        ElasticConfig, ElasticWorker, SyntheticShardSource, split_pass,
    )
    from edl_tpu.runtime.train_loop import TrainerConfig
    from edl_tpu.launcher.launch import LaunchContext, start_coordinator

    shards = [f"mp/part-{i:05d}" for i in range(3)]
    ctx = LaunchContext(
        job_name="multipass", workspace=str(tmp_path), port=free_port(),
        data_shards=shards, passes=2,
    )
    server = start_coordinator(ctx, block=False)
    try:
        reads = Counter()
        base = SyntheticShardSource(fit_a_line.MODEL, batch_size=8,
                                    batches_per_shard=2)

        class CountingSource:
            def read(self, task):
                reads[task] += 1
                return base.read(task)

        client = CoordinatorClient(port=ctx.port, worker="w0")
        client.register()
        worker = ElasticWorker(
            fit_a_line.MODEL, client, CountingSource(),
            ElasticConfig(checkpoint_dir=str(tmp_path / "ck"),
                          checkpoint_interval=100,
                          trainer=TrainerConfig(optimizer="sgd",
                                                learning_rate=0.05)),
            device_planner=lambda w: __import__("jax").devices(),
        )
        metrics = worker.run()
        st = client.status()
    finally:
        server.stop()

    # each base shard visited exactly once per pass, under distinct task ids
    per_base = Counter(split_pass(t)[0] for t in reads)
    assert per_base == {s: 2 for s in shards}, per_base
    passes_seen = {split_pass(t)[1] for t in reads}
    assert passes_seen == {0, 1}
    assert int(st["done"]) == 6 and int(st["queued"]) == 0
    assert metrics["passes_trained"] == 2.0
    assert metrics["steps"] == 12.0  # 3 shards x 2 batches x 2 passes
