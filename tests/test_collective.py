"""Explicit data-plane tests: ZeRO shard placement, gradient buckets, the
closed-form bytes-on-wire model, and reduce-scatter/psum numerics parity.

The parity tests are the tentpole's contract: the explicit plane
(``grad_sync="reduce_scatter"`` — reduce-scatter → sharded update →
all-gather) must produce the SAME params and moments as the implicit psum
step, because the only float-level difference is reduction reassociation.
The byte tests pin `collective_bytes` to the ring closed forms and the
acceptance invariant (explicit strictly below implicit at equal config)
that BENCH_COLLECTIVE.json commits.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from edl_tpu.models import transformer
from edl_tpu.parallel import MeshSpec, build_hierarchical_mesh, build_mesh
from edl_tpu.parallel.collective import (
    assign_buckets,
    collective_bytes,
    ring_bytes,
    split_microbatches,
    zero1_step_bytes,
    zero_shard_dim,
    zero_shard_spec,
)
from edl_tpu.runtime import Trainer, TrainerConfig


def small_model(**kw):
    base = dict(
        vocab_size=64, d_model=32, n_layers=2, n_heads=8, d_ff=64, seq_len=16
    )
    base.update(kw)
    return transformer.make_model(**base)


def _mesh(axes):
    spec = MeshSpec(dict(axes))
    if axes.get("dcn", 1) > 1:
        return build_hierarchical_mesh(spec)
    return build_mesh(spec)


def _leaves_allclose(a, b, **tol):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), **tol)


# -- ZeRO shard-dim choice -----------------------------------------------------


def test_zero_shard_dim_prefers_largest_divisible():
    # first-divisible (the seed behavior) would split (8, 4096) into 1-row
    # slivers; largest-divisible keeps shards contiguous runs of dim 1
    assert zero_shard_dim((8, 4096), 8) == 1
    assert zero_shard_dim((4096, 8), 8) == 0
    assert zero_shard_dim((16, 16), 8) == 0  # tie -> lowest index
    assert zero_shard_dim((6, 10), 8) is None  # nothing divides
    assert zero_shard_dim((64,), 1) is None  # nothing to split


def test_zero_shard_spec_flat_and_hierarchical():
    mesh = _mesh({"data": 8})
    assert zero_shard_spec((8, 4096), mesh, "data") == P(None, "data")
    assert zero_shard_spec((3, 5), mesh, "data") is None
    # absent hierarchy axes drop out to the bare present axis
    assert zero_shard_spec((64,), mesh, ("dcn", "data")) == P("data")
    hier = _mesh({"dcn": 2, "data": 4})
    assert zero_shard_spec((64, 32), hier, ("dcn", "data")) == P(
        ("dcn", "data"), None
    )


def test_zero_shard_spec_across_non_dividing_world_change():
    """The 6 -> 4 rescale: neither world divides the other, so every leaf's
    shard dim is re-derived per mesh — some leaves change layout (divisible
    by 6 only), some pick a different dim, some go replicated. The spec
    must be consistent per (shape, mesh), which is all the checkpoint
    plane's reassemble-then-reshard recovery relies on."""
    import jax as _jax

    mesh6 = build_mesh(MeshSpec({"data": 6}), _jax.devices()[:6])
    mesh4 = build_mesh(MeshSpec({"data": 4}), _jax.devices()[:4])
    # divides both worlds, but on a different dim (24 % 6 == 24 % 4 == 0)
    assert zero_shard_spec((24, 4), mesh6, "data") == P("data", None)
    assert zero_shard_spec((24, 4), mesh4, "data") == P("data", None)
    # divides 6 only -> replicated at world 4 (the blob/plane restore path
    # must therefore never assume the shard dim survives a rescale)
    assert zero_shard_spec((18, 5), mesh6, "data") == P("data", None)
    assert zero_shard_spec((18, 5), mesh4, "data") is None
    # divides 4 only -> sharded only after the shrink
    assert zero_shard_spec((8, 3), mesh6, "data") is None
    assert zero_shard_spec((8, 3), mesh4, "data") == P("data", None)
    # largest-divisible dim FLIPS across the change: 12 wins at world 6
    # (16 % 6 != 0), 16 wins at world 4
    assert zero_shard_spec((12, 16), mesh6, "data") == P("data", None)
    assert zero_shard_spec((12, 16), mesh4, "data") == P(None, "data")


def test_shard_opt_state_shards_largest_dim():
    """`Trainer._shard_opt_state` places every moment on its
    `zero_shard_spec` layout — the LARGEST divisible dim, not the first.
    The position embedding moment (seq 16, d 32) is the discriminating
    case: both dims divide 8, first-divisible would pick dim 0."""
    mesh = _mesh({"data": 8})
    trainer = Trainer(
        small_model(), mesh,
        TrainerConfig(optimizer="adam", shard_opt_state=True),
    )
    state = trainer.init_state()
    assert zero_shard_spec((16, 32), mesh, "data") == P(None, "data")
    checked = 0
    for leaf in jax.tree_util.tree_leaves(state.opt_state):
        sh = getattr(leaf, "sharding", None)
        if not isinstance(sh, NamedSharding) or getattr(leaf, "ndim", 0) == 0:
            continue
        expect = zero_shard_spec(leaf.shape, mesh, "data")
        if expect is None:
            assert all(s is None for s in sh.spec), (leaf.shape, sh.spec)
        else:
            assert tuple(sh.spec) == tuple(expect), (leaf.shape, sh.spec)
            checked += 1
    assert checked > 0  # the layout assertions actually ran


# -- gradient buckets ----------------------------------------------------------


def test_assign_buckets_reverse_greedy():
    sizes = [100, 200, 300, 1000, 50]
    buckets = assign_buckets(sizes, 400)
    # reverse traversal order (backward finishes last params first); the
    # oversize leaf gets its own bucket, never split
    assert [b.indices for b in buckets] == [(4,), (3,), (2,), (1, 0)]
    assert [b.nbytes for b in buckets] == [50, 1000, 300, 300]
    covered = sorted(i for b in buckets for i in b.indices)
    assert covered == list(range(len(sizes)))  # every leaf exactly once


def test_assign_buckets_rejects_nonpositive_target():
    with pytest.raises(ValueError, match="bucket_bytes"):
        assign_buckets([1, 2], 0)


# -- closed-form bytes on wire -------------------------------------------------


def test_ring_bytes_closed_forms():
    nbytes = 1024.0
    assert ring_bytes(nbytes, 8, "reduce_scatter") == nbytes * 7 / 8
    assert ring_bytes(nbytes, 8, "all_gather") == nbytes * 7 / 8
    assert ring_bytes(nbytes, 8, "all_reduce") == 2 * nbytes * 7 / 8
    assert ring_bytes(nbytes, 1, "all_reduce") == 0.0
    with pytest.raises(ValueError, match="broadcast"):
        ring_bytes(nbytes, 8, "broadcast")


def test_collective_bytes_flat_matches_ring():
    for op in ("reduce_scatter", "all_gather", "all_reduce"):
        acct = collective_bytes(4096, [("data", 8)], op)
        assert acct["data"] == acct["total"] == ring_bytes(4096, 8, op)


def test_collective_bytes_hierarchical_all_reduce():
    # the lowering XLA emits for a psum over ("dcn", "data"): intra-slice
    # reduce-scatter at full size, inter-slice all-reduce on the 1/4
    # shard (the DCN hop at shard size), intra-slice all-gather
    nbytes = 4096.0
    acct = collective_bytes(nbytes, [("dcn", 2), ("data", 4)], "all_reduce")
    assert acct["data"] == 2 * nbytes * 3 / 4  # inner RS + inner AG
    assert acct["dcn"] == 2 * (nbytes / 4) * (1 / 2)  # AR on the shard
    assert acct["total"] == acct["data"] + acct["dcn"]


def test_collective_bytes_ar_decomposes_into_rs_plus_ag():
    # all-reduce = reduce-scatter + all-gather, tier by tier — the
    # identity the explicit plane exploits by keeping the gather half
    # for params only
    tiers = [("dcn", 2), ("data", 4)]
    ar = collective_bytes(999.0, tiers, "all_reduce")
    rs = collective_bytes(999.0, tiers, "reduce_scatter")
    ag = collective_bytes(999.0, tiers, "all_gather")
    for key in ("dcn", "data", "total"):
        assert ar[key] == pytest.approx(rs[key] + ag[key])


def test_zero1_step_bytes_rs_strictly_below_psum():
    for tiers in ([("data", 8)], [("dcn", 2), ("data", 4)]):
        ps = zero1_step_bytes(1e6, 0.0, tiers, "psum")
        rs = zero1_step_bytes(1e6, 0.0, tiers, "reduce_scatter")
        assert rs["total"] < ps["total"], tiers
        for name, _ in tiers:  # every tier moves fewer bytes, DCN included
            assert rs[name] < ps[name], (tiers, name)
    # flat, all-sharded: AR(2 units) + AG(1) vs RS(1) + AG(1) -> exactly 2/3
    flat_ps = zero1_step_bytes(1e6, 0.0, [("data", 8)], "psum")
    flat_rs = zero1_step_bytes(1e6, 0.0, [("data", 8)], "reduce_scatter")
    assert flat_rs["total"] == pytest.approx(flat_ps["total"] * 2 / 3)
    # leaves with no divisible dim all-reduce either way: modes tie
    rep_ps = zero1_step_bytes(0.0, 1e6, [("data", 8)], "psum")
    rep_rs = zero1_step_bytes(0.0, 1e6, [("data", 8)], "reduce_scatter")
    assert rep_ps["total"] == rep_rs["total"]


# -- Trainer integration: resolution, accounting -------------------------------


def test_grad_sync_resolution_and_validation():
    mesh = _mesh({"data": 8})
    model = small_model()
    assert Trainer(
        model, mesh, TrainerConfig(shard_opt_state=True)
    ).grad_sync == "reduce_scatter"  # auto + ZeRO layout -> explicit
    assert Trainer(model, mesh, TrainerConfig()).grad_sync == "psum"
    assert Trainer(
        model, mesh, TrainerConfig(shard_opt_state=True, grad_sync="psum")
    ).grad_sync == "psum"  # explicit opt-out honored
    with pytest.raises(ValueError, match="ZeRO-1 layout"):
        Trainer(model, mesh, TrainerConfig(grad_sync="reduce_scatter"))
    with pytest.raises(ValueError, match="grad_sync"):
        Trainer(model, mesh, TrainerConfig(grad_sync="ring"))
    with pytest.raises(ValueError, match="grad_accum_microbatches"):
        Trainer(model, mesh, TrainerConfig(grad_accum_microbatches=0))


def test_data_plane_accounting_invariant():
    """The committed acceptance invariant, asserted at the Trainer level:
    the explicit plane's analytic bytes-on-wire is strictly below the
    implicit psum plane's at equal config, by exactly the reduce-scatter
    cost of the sharded fraction (AR = 2xRS; one RS unit is never paid)."""
    mesh = _mesh({"data": 8})
    model = small_model()
    planes = {}
    for mode in ("psum", "reduce_scatter"):
        trainer = Trainer(
            model, mesh,
            TrainerConfig(
                optimizer="adam", shard_opt_state=True, grad_sync=mode,
                grad_bucket_mb=0.01,
            ),
        )
        state = trainer.init_state()
        planes[mode] = trainer.data_plane(state.params)
    rs, ps = planes["reduce_scatter"], planes["psum"]
    assert rs["bytes_per_step"] < ps["bytes_per_step"]
    assert rs["param_bytes_per_step"] == ps["param_bytes_per_step"]
    saved = collective_bytes(
        rs["sharded_bytes"], [("data", 8)], "reduce_scatter"
    )["total"]
    assert ps["grad_bytes_per_step"] - rs["grad_bytes_per_step"] == (
        pytest.approx(saved)
    )
    # bucket accounting covers every gradient byte exactly once
    total = sum(
        int(np.prod(jnp.shape(x))) * np.dtype(jnp.result_type(x)).itemsize
        for x in jax.tree_util.tree_leaves(
            Trainer(model, mesh, TrainerConfig()).init_state().params
        )
    )
    assert sum(rs["bucket_nbytes"]) == total
    assert rs["n_buckets"] > 1  # 0.01 MiB target actually fragments


# -- numerics parity: explicit reduce-scatter vs implicit-psum oracle ----------


@pytest.mark.parametrize(
    "axes,opt,clip",
    [
        ({"data": 8}, "adam", 0.0),
        ({"data": 8}, "adam", 1.0),
        ({"data": 8}, "adagrad", 0.0),
        ({"data": 8}, "adagrad", 1.0),
        ({"dcn": 2, "data": 4}, "adam", 1.0),
        ({"dcn": 2, "data": 4}, "adagrad", 0.0),
    ],
    ids=["flat-adam", "flat-adam-clip", "flat-adagrad", "flat-adagrad-clip",
         "dcn-adam-clip", "dcn-adagrad"],
)
def test_explicit_rs_matches_psum_oracle(axes, opt, clip):
    """Identical params AND moments after K steps: the explicit plane is a
    lowering change (where the reduction happens), not a math change."""
    mesh = _mesh(axes)
    batch_axis = ("dcn", "data") if "dcn" in axes else "data"
    model = small_model()
    rng = np.random.default_rng(0)
    batches = [model.synthetic_batch(rng, 16) for _ in range(3)]

    def run(grad_sync):
        trainer = Trainer(
            model, mesh,
            TrainerConfig(
                optimizer=opt, grad_clip_norm=clip, batch_axis=batch_axis,
                shard_opt_state=True, grad_sync=grad_sync,
            ),
        )
        assert trainer.grad_sync == grad_sync
        state = trainer.init_state()
        losses = []
        for b in batches:
            state, loss = trainer.train_step(state, trainer.place_batch(b))
            losses.append(float(loss))
        return state, losses

    st_ps, l_ps = run("psum")
    st_rs, l_rs = run("reduce_scatter")
    assert l_ps == pytest.approx(l_rs, rel=1e-6, abs=1e-7)
    _leaves_allclose(st_ps.params, st_rs.params, rtol=1e-6, atol=1e-7)
    _leaves_allclose(st_ps.opt_state, st_rs.opt_state, rtol=1e-6, atol=1e-7)


def test_grad_accum_matches_single_step_sgd():
    """Scan-based accumulation == whole-batch step for a linear-in-grads
    optimizer (sgd): the microbatch partition only reassociates the mean
    (equal-sized chunks -> mean of means IS the batch mean). ONE step, so
    the param delta is lr x the gradient difference — pure reassociation
    noise, with no step-over-step amplification through the loss surface
    (multi-step trajectory equivalence of the explicit plane itself is
    test_explicit_rs_matches_psum_oracle's job). flash=False: the flash
    kernel blocks over the batch dim, so a different microbatch size
    changes its accumulation order — dense attention keeps per-sample
    math bit-identical across the split."""
    mesh = _mesh({"data": 8})
    model = small_model(flash=False)
    rng = np.random.default_rng(0)
    batch = model.synthetic_batch(rng, 32)

    def run(accum):
        trainer = Trainer(
            model, mesh,
            TrainerConfig(
                optimizer="sgd", learning_rate=0.1, shard_opt_state=True,
                grad_accum_microbatches=accum,
            ),
        )
        state = trainer.init_state()
        state, loss = trainer.train_step(state, trainer.place_batch(batch))
        return state, float(loss)

    st1, l1 = run(1)
    st4, l4 = run(4)
    assert l4 == pytest.approx(l1, rel=1e-5)
    # atol scale: the cross-sample mean cancels (batch-mean grads ~1e-4
    # from per-sample grads ~1e-1), so reassociation error rides the TERM
    # magnitude — observed max 1.1e-6 on params at lr=0.1, bound at 4x
    _leaves_allclose(st1.params, st4.params, rtol=1e-5, atol=5e-6)


def test_split_microbatches_shapes_and_divisibility():
    mesh = _mesh({"data": 8})
    batch = {"x": jnp.zeros((32, 5))}
    out = jax.jit(lambda b: split_microbatches(b, 4, mesh, "data"))(batch)
    assert out["x"].shape == (4, 8, 5)
    with pytest.raises(ValueError, match="divisible"):
        jax.jit(lambda b: split_microbatches(b, 5, mesh, "data"))(batch)
