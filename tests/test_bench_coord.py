"""Batched control-plane protocol + bench_coord harness.

The exactly-once contract (PR 3's req_id/op_id dedup, outbox replay,
journal durability) must survive the batching/coalescing rework — a
batch frame is transport framing, not new semantics. These tests pin
that, plus the epoch stamping / heartbeat piggybacking the workers'
coalesced epoch discovery rides on, the seeded heartbeat jitter, and
the bench harness contract (slow-marked 1k-worker smoke).
"""

import json
import time

import pytest

from edl_tpu.coordinator import (
    CoordinatorServer,
    InProcessCoordinator,
    OutboxClient,
    RetryPolicy,
)
from edl_tpu.coordinator.client import CoordinatorClient, CoordinatorError
from edl_tpu.runtime.elastic import heartbeat_schedule
from edl_tpu.testing import ChaosProxy

from tests.test_coordinator import has_toolchain

needs_native = pytest.mark.skipif(
    not has_toolchain(), reason="native toolchain unavailable"
)


# -- batch framing: exactly-once preserved -------------------------------------


@needs_native
@pytest.mark.sanitizer
def test_batch_roundtrip_and_subop_dedup_inside_frame():
    """Two acquire sub-ops with the SAME req_id in ONE frame: the dedup
    cache resolves the second to the first's lease — the lost-reply retry
    contract holds even when the retry rides the same batch."""
    with CoordinatorServer() as server:
        c = server.client("w0")
        c.register()
        c.add_tasks(["t0", "t1"])
        first, retry, fresh = c.call_batch([
            ("acquire_task", {"req_id": "r-1"}),
            ("acquire_task", {"req_id": "r-1"}),
            ("acquire_task", {"req_id": "r-2"}),
        ])
        assert first["task"] == "t0"
        assert retry["task"] == "t0" and retry.get("duplicate")
        assert fresh["task"] == "t1"
        assert int(c.status()["leased"]) == 2  # no zombie third lease
        c.close()


@needs_native
@pytest.mark.sanitizer
def test_batch_subops_inherit_frame_worker_and_reject_unbatchable():
    with CoordinatorServer() as server:
        c = server.client("w0")
        c.register()
        # heartbeat sub-op without an explicit worker inherits the frame's
        hb, bad = c.call_batch([
            ("heartbeat", {}),
            ("barrier", {"key": "b", "count": 1}),
        ])
        assert hb.get("ok")
        assert not bad.get("ok") and "not batchable" in bad.get("error", "")
        c.close()


@needs_native
@pytest.mark.sanitizer
def test_batch_replies_carry_epoch_and_update_observed():
    with CoordinatorServer() as server:
        c = server.client("w0")
        c.register()
        e0 = c.observed_epoch
        assert e0 is not None
        c.bump_epoch()
        hb, = c.call_batch([("heartbeat", {})])
        assert int(hb["epoch"]) == e0 + 1
        assert c.observed_epoch == e0 + 1
        assert c.last_membership is not None \
            and int(c.last_membership["world"]) == 1
        c.close()


def test_inprocess_call_batch_parity():
    coord = InProcessCoordinator()
    c = coord.client("w0")
    c.register()
    c.add_tasks(["t0"])
    hb, got, bad = c.call_batch([
        ("heartbeat", {}),
        ("acquire_task", {"req_id": "r"}),
        ("barrier", {"key": "b", "count": 1}),
    ])
    assert hb.get("ok")
    assert got["task"] == "t0"
    assert not bad.get("ok") and "not batchable" in bad.get("error", "")
    assert c.observed_epoch is not None


@pytest.mark.chaos
@needs_native
@pytest.mark.sanitizer
def test_batched_outbox_replay_across_kill_and_restart(tmp_path):
    """Mutations buffered through a partition + coordinator SIGKILL replay
    as batch frames after restart and land exactly once."""
    state = str(tmp_path / "state.jsonl")
    server = CoordinatorServer(state_file=state, run_id="r1",
                               task_lease_sec=60.0, heartbeat_ttl_sec=60.0)
    server.start()
    try:
        with ChaosProxy(server.port, seed=3) as proxy:
            raw = CoordinatorClient(port=proxy.port, worker="w0",
                                    retry=RetryPolicy(deadline=1.0, seed=1))
            c = OutboxClient(raw)
            c.register()
            c.add_tasks(["s0"])
            assert c.acquire_task() == "s0"
            # one durable op_id'd increment BEFORE the partition: its replay
            # after the restart must dedup against the journaled marker
            assert c.call("kv_incr", key="ctr", delta=1,
                          op_id="op-pre")["value"] == 1

            proxy.partition()
            assert c.complete_task("s0").get("buffered")
            c.kv_put("during", "x")
            c.outbox.add("kv_incr", key="ctr", delta=1, op_id="op-pre")
            c.outbox.add("kv_incr", key="ctr", delta=1, op_id="op-out")
            assert len(c.outbox) == 4

            server.kill()  # SIGKILL: only the journal survives
            server.restart()
            proxy.heal()

            deadline = time.monotonic() + 20.0
            while len(c.outbox) and time.monotonic() < deadline:
                c.heartbeat()
                time.sleep(0.05)
            assert len(c.outbox) == 0

            st = c.status()
            # the replay went through the batch path, not op-by-op
            assert int(st["batch_frames"]) >= 1
            assert int(st["done"]) == 1  # completion applied once
            assert c.kv_get("during") == "x"
            # op-pre deduped against the restart-surviving marker; op-out
            # applied exactly once
            assert c.kv_get("ctr") == "2"
            rep = c.call("kv_incr", key="ctr", delta=1, op_id="op-out")
            assert rep["value"] == 2 and rep.get("duplicate")
            raw.close()
    finally:
        server.stop()


@pytest.mark.chaos
@needs_native
@pytest.mark.sanitizer
def test_snapshot_compaction_under_batched_load_survives_kill(tmp_path):
    """Enough batched mutations to cross the compaction threshold, then
    SIGKILL: the compacted snapshot + tail journal restore full state."""
    state = str(tmp_path / "state.jsonl")
    server = CoordinatorServer(state_file=state, run_id="r1")
    server.start()
    try:
        c = server.client("w0")
        c.register()
        snaps = 0
        for i in range(40):  # 40 frames x 64 kv_puts > 1024-record threshold
            frame = [("kv_put", {"key": f"k{j % 128}", "value": f"v{i}"})
                     for j in range(64)]
            for rep in c.call_batch(frame):
                assert rep.get("ok")
            snaps = int(c.status()["snapshots"])
            if snaps >= 1 and i >= 20:
                break
        assert snaps >= 1, "compaction never triggered"
        records = int(c.status()["journal_records"])
        assert records >= 1024  # monotonic lifetime counter, not reset by
        c.close()               # compaction

        server.kill()
        server.restart()
        c = server.client("w0")
        assert c.kv_get("k0") is not None  # state survived the compaction
        assert int(c.status()["epoch"]) >= 1
        c.close()
    finally:
        server.stop()


# -- heartbeat piggybacking ----------------------------------------------------


@needs_native
@pytest.mark.sanitizer
def test_piggyback_heartbeat_wraps_calls_into_batches():
    with CoordinatorServer(heartbeat_ttl_sec=60.0) as server:
        c = CoordinatorClient(port=server.port, worker="w0",
                              piggyback_heartbeat=0.01)
        c.register()
        time.sleep(0.02)
        c.kv_put("a", "1")  # eligible call: rides a batch with a heartbeat
        st = c.status()
        assert int(st["batch_frames"]) >= 1
        assert int(st["batch_subops"]) >= 2
        assert c.last_membership is not None
        assert c.kv_get("a") == "1"  # the wrapped op still applied
        c.close()


# -- heartbeat jitter ----------------------------------------------------------


def test_heartbeat_jitter_decorrelates_workers():
    a = heartbeat_schedule("w0", base=1.0, jitter=0.2, n=64)
    b = heartbeat_schedule("w1", base=1.0, jitter=0.2, n=64)
    # deterministic per worker (stable across processes: str seeding)
    assert a == heartbeat_schedule("w0", base=1.0, jitter=0.2, n=64)
    # different workers draw different schedules
    assert a != b
    # bounded: every interval within +/- 20% of base
    for x in a + b:
        assert 0.8 <= x <= 1.2
    # de-correlation: beat TIMES drift apart, so the fleet cannot stay
    # phase-locked — the max pairwise phase offset grows past any fixed
    # sync window as beats accumulate
    ta = tb = 0.0
    offsets = []
    for xa, xb in zip(a, b):
        ta += xa
        tb += xb
        offsets.append(abs(ta - tb))
    assert max(offsets) > 0.25
    # zero jitter degenerates to the fixed interval (storms return)
    flat = heartbeat_schedule("w0", base=1.0, jitter=0.0, n=8)
    assert flat == [1.0] * 8


def test_worker_heartbeats_coalesce_onto_piggybacked_observations():
    """An ElasticWorker-style beat consumes a fresh piggybacked membership
    observation instead of issuing a dedicated RPC (InProcess twin)."""
    coord = InProcessCoordinator()
    c = coord.client("w0")
    c.register()
    assert c.last_membership is not None
    before = c.last_membership_at
    # a membership-shaped reply refreshes the observation
    c.heartbeat()
    assert c.last_membership_at >= before


# -- bench harness -------------------------------------------------------------


@needs_native
def test_bench_cell_contract(monkeypatch, tmp_path):
    """Tiny in-process run of one bench cell per arm: counters move, the
    latency fields populate, and the before arm really runs on poll."""
    import bench_coord

    before = bench_coord.run_cell("before", 16, "saturated", 0.4, 0.1,
                                  16, 8, str(tmp_path))
    after = bench_coord.run_cell("after", 16, "saturated", 0.4, 0.1,
                                 16, 8, str(tmp_path))
    for cell in (before, after):
        assert cell["beats"] > 0
        assert cell["ops_per_sec"] > 0
        assert cell["p99_ms"] is not None and cell["p99_ms"] > 0
        assert cell["server_cpu_sec"] >= 0
    assert before["poller"] == "poll" and before["batch_frames"] == 0
    assert after["poller"] == "epoll" and after["batch_frames"] > 0
    assert after["batch_subops"] == 2 * after["batch_frames"]


@needs_native
def test_bench_topology_cell_contract(tmp_path):
    """Tiny single-vs-sharded cells through the multiplexed logical-worker
    path: more logical workers than connections, counters move on every
    server, and the cells report comparable fields."""
    import bench_coord

    single = bench_coord.run_topology_cell("single", 64, 0.4, 0.1, 8,
                                           str(tmp_path), kv_bytes=64)
    sharded = bench_coord.run_topology_cell("sharded", 64, 0.4, 0.1, 8,
                                            str(tmp_path), kv_bytes=64)
    for cell in (single, sharded):
        assert cell["beats"] > 0
        assert cell["ops_per_sec"] > 0
        assert cell["p99_ms"] is not None and cell["p99_ms"] > 0
        assert cell["connections"] <= 8  # 64 logical workers multiplexed
    assert single["servers"] == 1
    assert sharded["servers"] == 3  # root + 2 shards


@needs_native
def test_bench_propagation_pull_vs_push(tmp_path):
    """One epoch bump against a paced-pull fleet and a watch fleet: every
    worker discovers it, and push lands far inside the polling period."""
    import bench_coord

    rep = bench_coord.run_propagation(16, 0.4, str(tmp_path))
    assert rep["pull"]["discovered"] == 16
    assert rep["push"]["discovered"] == 16
    # pull pays the polling cadence; push is an RTT. Generous bound so a
    # loaded CI host can't flake it.
    assert rep["push"]["mean_ms"] < rep["pull"]["mean_ms"]
    assert rep["push_p99_over_period"] < 0.5


@pytest.mark.slow
@needs_native
def test_bench_coord_smoke_1k(monkeypatch, tmp_path):
    """1k simulated workers end to end through main(): both arms, duty
    mode, artifact written with the crossover summary."""
    import bench_coord

    out = tmp_path / "BENCH_COORD.json"
    monkeypatch.setenv("EDL_COORD_SECTIONS", '["arms"]')
    monkeypatch.setenv("EDL_COORD_NS", "[1000]")
    monkeypatch.setenv("EDL_COORD_MODES", '["duty"]')
    monkeypatch.setenv("EDL_COORD_SECS", "1.0")
    monkeypatch.setenv("EDL_COORD_WARMUP", "0.2")
    monkeypatch.setenv("EDL_COORD_ACTIVE", "32")
    monkeypatch.setenv("EDL_COORD_OUT", str(out))
    summary = bench_coord.main()
    assert out.exists()
    disk = json.loads(out.read_text())
    assert disk["results"] == summary["results"]
    assert {c["arm"] for c in summary["results"]} == {"before", "after"}
    for cell in summary["results"]:
        assert cell["n"] == 1000 and cell["active_workers"] == 32
        assert cell["beats"] > 0 and cell["p99_ms"] > 0
    (cross,) = summary["crossover"]
    assert cross["n"] == 1000
    assert cross["beats_speedup"] > 0 and cross["p99_ratio"] > 0
