"""Layout-planner oracle: the cost-model argmin must strictly beat a
data-only resize at every point of the committed sweep, deterministically.

The sweep points and profile are imported from bench_rescale so the tier-1
oracle and the committed BENCH_RESCALE.json replan_sweep section can never
drift apart — a planner regression fails here before it fails the bench.
"""

import pytest

from bench_rescale import REPLAN_SWEEP, _sweep_profile
from edl_tpu.parallel import (
    ModelProfile,
    Topology,
    data_only_plan,
    plan_layout,
)
from edl_tpu.parallel.planner import (
    data_only_step_seconds,
    enumerate_candidates,
)


@pytest.mark.parametrize("chips,slices", REPLAN_SWEEP)
def test_planner_strictly_beats_data_only(chips, slices):
    topo = Topology(slices=slices)
    plan = plan_layout(chips, topo, _sweep_profile(), 1536)
    base = data_only_step_seconds(chips, topo, _sweep_profile(), 1536)
    assert plan.step_seconds < base, (
        f"{plan.describe()} at {chips} chips on {slices}: "
        f"{plan.step_seconds * 1e3:.3f}ms !< data-only {base * 1e3:.3f}ms")
    assert plan.baseline_step_seconds == pytest.approx(base)


def test_plan_is_deterministic():
    topo = Topology(slices=(4, 4))
    a = plan_layout(8, topo, _sweep_profile(), 1536)
    b = plan_layout(8, topo, _sweep_profile(), 1536)
    assert a.to_dict() == b.to_dict()
    # The table is sorted by modeled step time, chosen first: a stable tie
    # break means every gang member lands on the same layout independently.
    assert a.table[0].candidate.describe() == a.describe()


def test_multi_slice_chip_count_adopts_hierarchical_dp():
    # 8 chips over two 4-chip slices: a flat data ring would cross DCN on
    # every hop, so the planner must pick a {dcn: 2, ...} layout whose
    # cross-slice traffic is one gradient reduction.
    plan = plan_layout(8, Topology(slices=(4, 4)), _sweep_profile(), 1536)
    assert plan.axes_dict.get("dcn") == 2
    assert plan.batch_axis[0] == "dcn"
    assert plan.hierarchical


def test_single_slice_shrink_goes_flat():
    plan = plan_layout(
        6, Topology(slices=(6,)),
        ModelProfile(param_bytes=400e6, flops_per_sample=2e7), 240,
        schedules=())
    assert plan.axes_dict == {"data": 6}
    assert not plan.hierarchical
    assert plan.schedule is None
    assert plan.batch_axis == "data"


def test_schedules_empty_forbids_pipelining():
    plan = plan_layout(8, Topology(slices=(4, 4)), _sweep_profile(), 1536,
                       schedules=())
    assert "pipe" not in plan.axes_dict
    for scored in plan.table:
        assert scored.candidate.schedule is None


def test_infeasible_candidates_carry_reasons_and_lose():
    # 400 MB of HBM cannot hold the deep-pipeline candidates' activation
    # stash; infeasible rows must stay in the table with a reason and
    # never be chosen.
    topo = Topology(slices=(4, 4), hbm_bytes=400_000_000)
    plan = plan_layout(8, topo, _sweep_profile(), 1536)
    infeasible = [s for s in plan.table if not s.feasible]
    assert infeasible, "expected at least one memory-infeasible candidate"
    assert all(s.reason for s in infeasible)
    assert plan.chosen().feasible


def test_plan_layout_raises_when_nothing_fits():
    with pytest.raises(ValueError):
        plan_layout(4, Topology(slices=(4,), hbm_bytes=1 << 20),
                    _sweep_profile(), 1536)


def test_data_only_plan_matches_its_step_model():
    topo = Topology(slices=(4, 4))
    scored = data_only_plan(8, topo, _sweep_profile(), 1536)
    assert scored.candidate.axes_dict == {"data": 8}
    assert scored.candidate.schedule is None
    assert scored.step_seconds == pytest.approx(
        data_only_step_seconds(8, topo, _sweep_profile(), 1536))


def test_enumerate_covers_flat_and_hierarchical_dp():
    cands = enumerate_candidates(8, Topology(slices=(4, 4)),
                                 _sweep_profile(), 1536)
    layouts = {tuple(sorted(c.axes_dict.items())) for c in cands}
    assert (("data", 8),) in layouts
    assert (("data", 4), ("dcn", 2)) in layouts
