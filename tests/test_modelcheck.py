"""Tests for the EDL009 protocol model checker (edl_tpu.analysis.modelcheck).

Layers:

- the acceptance configuration: exhaustive DFS over the default 2-worker
  faulty schedule (crash+restart, duplicate acquire, duplicate kv_incr, a
  batch frame) is green, every trace replayed against InProcessCoordinator;
- teeth: a deliberately mutated twin (request dedup disabled via the
  test-only ``_test_disable_dedup`` flag) is caught, through both the
  model/oracle divergence and the exactly-once monitor;
- the fuzz mode's soundness contract: any violation the seeded random walk
  reports is also reported by the exhaustive run at the same depth;
- parked-op handling: barrier/sync release and bounded-progress deadlock
  detection.
"""

import json
import time
from pathlib import Path

import pytest

from edl_tpu.analysis.modelcheck import (
    LAST_TASK,
    ModelCheckError,
    ProtocolModel,
    ScriptOp,
    default_scripts,
    explore,
    load_state_effects,
    main as modelcheck_main,
    run_default,
)

mk = ScriptOp.make

REPO_ROOT = str(Path(__file__).resolve().parents[1])


def _mutant_factory():
    """The deliberately broken twin: replay dedup disabled. Duplicate
    acquire req_ids hand out a second task; duplicate kv_incr op_ids
    double-apply."""
    from edl_tpu.coordinator.inprocess import InProcessCoordinator

    c = InProcessCoordinator(task_lease_sec=1e9, heartbeat_ttl_sec=1e9)
    c._test_disable_dedup = True
    return c


def _effects():
    effects, ops, err = load_state_effects(REPO_ROOT)
    assert err is None, err
    return effects


# -- the acceptance configuration ----------------------------------------------


def test_default_exhaustive_is_green_and_fully_replayed():
    """2 workers, 13 ops incl. batch, crash+restart, two duplicate
    deliveries: every interleaving model-checked AND oracle-replayed,
    zero violations, comfortably under the 60 s budget."""
    t0 = time.monotonic()
    result = run_default()
    elapsed = time.monotonic() - t0
    assert result.violations == []
    # C(13, 6) interleavings of the default scripts + C(8, 4) of the
    # checkpoint-plane schedule + C(11, 3) watch/notify + C(10, 4)
    # redirect-during-watch (run_default merges all four)
    assert result.traces == 1716 + 70 + 165 + 210
    assert result.replays == result.traces
    assert result.ok()
    assert elapsed < 90.0


def test_default_scripts_meet_the_bounded_config_contract():
    scripts = default_scripts()
    assert set(scripts) == {"w0", "w1"}
    ops = [op.op for s in scripts.values() for op in s]
    assert len(ops) >= 6 and "batch" in ops
    notes = [op.note for s in scripts.values() for op in s]
    assert "restart" in notes  # crash+restart
    assert notes.count("dup") == 2  # duplicate deliveries


def test_state_effects_cover_the_full_op_set():
    effects, ops, err = load_state_effects(REPO_ROOT)
    assert err is None
    assert set(effects) == ops
    assert len(ops) >= 21


# -- teeth: the mutated twin ----------------------------------------------------


def test_mutant_twin_with_dedup_disabled_is_caught():
    result = run_default(coordinator_factory=_mutant_factory,
                         max_violations=10)
    assert result.violations, "mutant twin must not pass"
    kinds = {v.kind for v in result.violations}
    # the duplicate acquire shows up both as a model/oracle reply
    # divergence and as a second grant for the same req_id
    assert kinds & {"oracle-divergence", "exactly-once"}


def test_mutant_violation_messages_name_the_replayed_request():
    result = run_default(coordinator_factory=_mutant_factory,
                         max_violations=50)
    blob = " ".join(v.message for v in result.violations)
    assert "w0-a1" in blob or "w1-i1" in blob or "duplicate" in blob


# -- fuzz mode ------------------------------------------------------------------


def test_fuzz_on_green_twin_stays_green():
    result = run_default(fuzz_samples=40, fuzz_seed=7)
    assert result.violations == []
    # 40 samples per schedule (default, ckpt-plane, watch, redirect),
    # identical ones dedup
    assert 0 < result.traces <= 160
    assert result.replays == result.traces


def test_fuzz_findings_are_subset_of_exhaustive_at_equal_depth():
    """The soundness contract of --fuzz: same per-trace checking, sampled
    schedule set — so on the mutant twin every fuzz violation key appears
    in the exhaustive run's violation set."""
    exhaustive = run_default(coordinator_factory=_mutant_factory,
                             max_violations=10 ** 6)
    fuzz = run_default(coordinator_factory=_mutant_factory,
                       fuzz_samples=30, fuzz_seed=3,
                       max_violations=10 ** 6)
    assert fuzz.violations, "fuzz must hit the planted bug at this budget"
    assert fuzz.violation_keys() <= exhaustive.violation_keys()
    assert len(exhaustive.violation_keys()) > len(fuzz.violation_keys())


def test_fuzz_is_deterministic_per_seed():
    a = run_default(fuzz_samples=25, fuzz_seed=11)
    b = run_default(fuzz_samples=25, fuzz_seed=11)
    assert a.traces == b.traces
    assert a.violation_keys() == b.violation_keys()


# -- parked ops: barrier / sync -------------------------------------------------


def _barrier_scripts(count):
    return {
        "w0": [mk("register", worker="w0"),
               mk("barrier", name="b", count=count, worker="w0")],
        "w1": [mk("register", worker="w1"),
               mk("barrier", name="b", count=count, worker="w1")],
    }


def test_barrier_release_explored_and_green():
    result = explore(_barrier_scripts(count=2), _effects())
    assert result.traces == 6  # C(4, 2) interleavings
    assert result.violations == []
    assert result.replays == result.traces


def test_unsatisfiable_barrier_is_a_progress_violation():
    """count=3 with two workers: every complete interleaving deadlocks, and
    the model reports it WITHOUT replaying (replay would hang)."""
    result = explore(_barrier_scripts(count=3), _effects())
    assert result.traces == 6
    assert result.violations
    assert {v.kind for v in result.violations} == {"progress"}
    assert result.replays == 0


def test_sync_parking_detects_the_stranded_worker():
    """sync(epoch=2) issued before the second register gets an immediate
    resync and drains; interleavings where it parks after both registers
    but the peer already drained deadlock — the checker must see exactly
    those."""
    scripts = {
        "w0": [mk("register", worker="w0"),
               mk("sync", epoch=2, worker="w0")],
        "w1": [mk("register", worker="w1"),
               mk("sync", epoch=2, worker="w1")],
    }
    result = explore(scripts, _effects())
    assert result.traces == 6
    deadlocks = [v for v in result.violations if v.kind == "progress"]
    assert len(deadlocks) == 2
    assert len(result.violations) == 2  # nothing besides the deadlocks


# -- model plumbing -------------------------------------------------------------


def test_scriptop_make_freezes_nested_fields():
    op = mk("batch", ops=[{"op": "ping"}], worker="w0")
    assert isinstance(op.fields, tuple)
    d = op.field_dict()
    assert d["ops"] == [{"op": "ping"}]
    assert hash(op) is not None  # frozen dataclass stays hashable


def test_unknown_effect_tag_is_a_spec_error_not_a_violation():
    effects = dict(_effects())
    effects["ping"] = {"quantum": "entangle"}
    with pytest.raises(ModelCheckError):
        ProtocolModel(effects)


def test_load_state_effects_reports_missing_block(tmp_path):
    (tmp_path / "protocol_schema.json").write_text(
        json.dumps({"ops": {"ping": {}}})
    )
    effects, ops, err = load_state_effects(str(tmp_path))
    assert effects is None
    assert ops == {"ping"}
    assert "state_effects" in err


def test_load_state_effects_reports_missing_file(tmp_path):
    effects, ops, err = load_state_effects(str(tmp_path))
    assert effects is None and ops is None
    assert "missing" in err


# -- CLI ------------------------------------------------------------------------


def test_cli_exhaustive_exits_zero(capsys):
    rc = modelcheck_main([])
    out = capsys.readouterr().out
    assert rc == 0
    assert "2161 trace(s)" in out and "0 violation(s)" in out


def test_cli_json_fuzz(capsys):
    rc = modelcheck_main(["--fuzz", "10", "--seed", "5", "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert payload["violations"] == []
    assert payload["replays"] == payload["traces"] > 0
