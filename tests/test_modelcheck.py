"""Tests for the EDL009/EDL010 protocol model checker
(edl_tpu.analysis.modelcheck).

Layers:

- the acceptance configuration: exhaustive DFS over the default 2-worker
  faulty schedule (crash+restart, duplicate acquire, duplicate kv_incr, a
  batch frame) is green, every trace replayed against InProcessCoordinator;
- the EDL010 durability lanes: crash points enumerated between persistence
  effects (clean / pre-ack / torn tail / during compaction) with recovery
  replay, checked against the file-backed persistence twin — and the
  sleep-set POR's soundness (reduced exploration reaches the same
  violation set as unreduced);
- teeth: a deliberately mutated twin (request dedup disabled via the
  test-only ``_test_disable_dedup`` flag) is caught, through both the
  model/oracle divergence and the exactly-once monitor; a twin that skips
  torn-tail detection (``skip_tail_scan``) replays partial frames and is
  caught by the acked-durability invariant;
- the fuzz mode's soundness contract: any violation the seeded random walk
  reports is also reported by the exhaustive run at the same depth;
- parked-op handling: barrier/sync release and bounded-progress deadlock
  detection;
- the --dump-trace / --replay-trace round trip on a violating
  interleaving.
"""

import json
import time
from pathlib import Path

import pytest

from edl_tpu.analysis.modelcheck import (
    LAST_TASK,
    DurableTwinOracle,
    ModelCheckError,
    ProtocolModel,
    Schedule,
    ScriptOp,
    default_scripts,
    dump_trace_spec,
    durability_base_scripts,
    durability_dedup_scripts,
    durability_schedules,
    durability_torn_scripts,
    explore,
    load_state_effects,
    main as modelcheck_main,
    replay_trace_spec,
    run_default,
)

mk = ScriptOp.make

REPO_ROOT = str(Path(__file__).resolve().parents[1])


def _mutant_factory():
    """The deliberately broken twin: replay dedup disabled. Duplicate
    acquire req_ids hand out a second task; duplicate kv_incr op_ids
    double-apply."""
    from edl_tpu.coordinator.inprocess import InProcessCoordinator

    c = InProcessCoordinator(task_lease_sec=1e9, heartbeat_ttl_sec=1e9)
    c._test_disable_dedup = True
    return c


def _effects():
    effects, ops, err = load_state_effects(REPO_ROOT)
    assert err is None, err
    return effects


# -- the acceptance configuration ----------------------------------------------


def test_default_exhaustive_is_green_and_fully_replayed():
    """2 workers, 13 ops incl. batch, crash+restart, two duplicate
    deliveries: every interleaving model-checked AND oracle-replayed,
    zero violations, comfortably under the 60 s budget."""
    t0 = time.monotonic()
    result = run_default()
    elapsed = time.monotonic() - t0
    assert result.violations == []
    # C(13, 6) interleavings of the default scripts + C(8, 4) of the
    # checkpoint-plane schedule + C(11, 3) watch/notify + the preempt
    # notice/watch/leave lane + C(10, 4) redirect-during-watch + the
    # EDL010 durability rows (POR-reduced except durability-compact,
    # which runs unreduced at C(13, 6)):
    # 118 + 50 + 28 + 1716 + 21 + 196 + 38 = 2167. run_default merges
    # all twelve.
    assert result.traces == 1716 + 70 + 165 + 210 + 210 + 2167
    assert result.replays == result.traces
    assert result.ok()
    assert elapsed < 90.0


def test_default_scripts_meet_the_bounded_config_contract():
    scripts = default_scripts()
    assert set(scripts) == {"w0", "w1"}
    ops = [op.op for s in scripts.values() for op in s]
    assert len(ops) >= 6 and "batch" in ops
    notes = [op.note for s in scripts.values() for op in s]
    assert "restart" in notes  # crash+restart
    assert notes.count("dup") == 2  # duplicate deliveries


def test_state_effects_cover_the_full_op_set():
    effects, ops, err = load_state_effects(REPO_ROOT)
    assert err is None
    assert set(effects) == ops
    assert len(ops) >= 22


def test_every_op_carries_a_valid_durability_tag():
    """The EDL010 ratchet, pinned to the repo schema: every op in the
    dispatch table declares what it persists, with a well-formed tag —
    and the journaled core is tagged as such."""
    from edl_tpu.analysis.checkers.durability import validate_durability_tag

    effects, ops, err = load_state_effects(REPO_ROOT)
    assert err is None
    assert len(ops) >= 22
    for op in sorted(ops):
        tag = (effects.get(op) or {}).get("durability")
        assert validate_durability_tag(tag) is None, (
            f"op {op!r}: bad durability tag {tag!r}")
    assert effects["acquire_task"]["durability"] == "journal:lease"
    assert effects["kv_incr"]["durability"] == "journal:kv"
    assert effects["register"]["durability"] == "journal:meta,lease"
    assert effects["shard_put"]["durability"] == "volatile"  # unjournaled


# -- teeth: the mutated twin ----------------------------------------------------


def test_mutant_twin_with_dedup_disabled_is_caught():
    result = run_default(coordinator_factory=_mutant_factory,
                         max_violations=10)
    assert result.violations, "mutant twin must not pass"
    kinds = {v.kind for v in result.violations}
    # the duplicate acquire shows up both as a model/oracle reply
    # divergence and as a second grant for the same req_id
    assert kinds & {"oracle-divergence", "exactly-once"}


def test_mutant_violation_messages_name_the_replayed_request():
    result = run_default(coordinator_factory=_mutant_factory,
                         max_violations=50)
    blob = " ".join(v.message for v in result.violations)
    assert "w0-a1" in blob or "w1-i1" in blob or "duplicate" in blob


# -- fuzz mode ------------------------------------------------------------------


def test_fuzz_on_green_twin_stays_green():
    result = run_default(fuzz_samples=40, fuzz_seed=7)
    assert result.violations == []
    # 40 samples per schedule (5 legacy + 7 durability rows), identical
    # ones dedup
    assert 0 < result.traces <= 480
    assert result.replays == result.traces


def test_fuzz_findings_are_subset_of_exhaustive_at_equal_depth():
    """The soundness contract of --fuzz: same per-trace checking, sampled
    schedule set — so on the mutant twin every fuzz violation key appears
    in the exhaustive run's violation set."""
    exhaustive = run_default(coordinator_factory=_mutant_factory,
                             max_violations=10 ** 6)
    fuzz = run_default(coordinator_factory=_mutant_factory,
                       fuzz_samples=30, fuzz_seed=3,
                       max_violations=10 ** 6)
    assert fuzz.violations, "fuzz must hit the planted bug at this budget"
    assert fuzz.violation_keys() <= exhaustive.violation_keys()
    assert len(exhaustive.violation_keys()) > len(fuzz.violation_keys())


def test_fuzz_is_deterministic_per_seed():
    a = run_default(fuzz_samples=25, fuzz_seed=11)
    b = run_default(fuzz_samples=25, fuzz_seed=11)
    assert a.traces == b.traces
    assert a.violation_keys() == b.violation_keys()


# -- EDL010: crash-point durability schedules -----------------------------------


def test_durability_schedules_green_with_pinned_trace_counts():
    """Each durability lane explored in isolation, every trace replayed
    against the file-backed persistence twin — per-schedule trace counts
    pinned so a schedule silently shrinking (lost crash points) fails."""
    result = run_default(schedules=[s.name for s in durability_schedules()])
    assert result.violations == []
    assert result.replays == result.traces
    counts = {name: traces for name, traces, _s in result.timings}
    assert counts == {
        "durability-base": 118,           # clean crash, POR-reduced
        "durability-dedup": 50,           # pre_ack + straddling dups
        "durability-torn": 28,            # torn tail, all-or-nothing
        "durability-compact": 1716,       # snapshot path, unreduced C(13,6)
        "durability-crash-compact": 21,   # crash inside snapshot write
        "durability-shard": 196,          # unjournaled shard-store honesty
        "durability-preempt": 38,         # volatile notices forgotten by crash
    }
    assert sum(counts.values()) == 2167


def test_schedule_name_filter_rejects_unknown_names():
    with pytest.raises(ModelCheckError, match="unknown schedule"):
        run_default(schedules=["durability-base", "no-such-lane"])


def test_nonclean_crash_with_compaction_is_a_spec_error():
    """torn / pre_ack / during_compaction crash points assume the inflight
    frame is the journal tail; under an active compaction threshold the
    tail may be a snapshot instead, so the combination is rejected up
    front rather than modeled wrong."""
    mk2 = ScriptOp.make
    scripts = {"w0": [mk2("register", worker="w0"),
                      mk2("crash", mode="torn", worker="w0",
                          inflight=[{"op": "kv_put", "key": "k",
                                     "value": "v"}])]}
    with pytest.raises(ModelCheckError):
        explore(scripts, _effects(),
                coordinator_factory=lambda: DurableTwinOracle(compact_every=4),
                durable=True, compact_every=4)


def test_por_soundness_reduced_equals_unreduced_on_green_twin():
    """Sleep-set POR prunes interleavings that only reorder independent
    ops; on the green twin both runs must be empty AND the reduction must
    actually reduce."""
    full = explore(durability_base_scripts(), _effects(),
                   coordinator_factory=lambda: DurableTwinOracle(),
                   durable=True, por=False)
    reduced = explore(durability_base_scripts(), _effects(),
                      coordinator_factory=lambda: DurableTwinOracle(),
                      durable=True, por=True)
    assert full.violations == [] and reduced.violations == []
    assert reduced.traces == 118
    assert reduced.traces < full.traces


def test_por_soundness_reduced_catches_what_unreduced_catches():
    """On the dedup-disabled mutant the reduced exploration must reach
    the same violation KINDS as the unreduced one, and every reduced
    violation key must exist in the unreduced set (POR may drop redundant
    witnesses, never bug classes)."""
    mutant = lambda: DurableTwinOracle(disable_dedup=True)  # noqa: E731
    full = explore(durability_dedup_scripts(), _effects(),
                   coordinator_factory=mutant, durable=True, por=False,
                   max_violations=10 ** 6)
    reduced = explore(durability_dedup_scripts(), _effects(),
                      coordinator_factory=mutant, durable=True, por=True,
                      max_violations=10 ** 6)
    assert reduced.violations, "POR must not hide the planted bug"
    assert reduced.violation_keys() <= full.violation_keys()
    assert ({v.kind for v in reduced.violations}
            == {v.kind for v in full.violations})


def test_torn_tail_mutant_skip_tail_scan_is_caught():
    """The mutant-teeth scenario: a twin whose recovery skips torn-tail
    frame detection replays the half-written kv_incr value record without
    its op_id marker — the post-crash retry double-applies, caught as an
    acked-durability divergence (and/or exactly-once)."""
    mutant = lambda: DurableTwinOracle(skip_tail_scan=True)  # noqa: E731
    result = explore(durability_torn_scripts(), _effects(),
                     coordinator_factory=mutant, durable=True, por=True,
                     max_violations=100)
    assert result.violations, "torn-tail-blind twin must not pass"
    kinds = {v.kind for v in result.violations}
    assert kinds & {"acked-durability", "exactly-once"}


def test_dedup_mutant_is_caught_across_the_crash():
    """Replay dedup disabled: the duplicate acquire AFTER recovery hands
    out a second grant for the same req_id — exactly-once must hold
    across the crash, not merely within one incarnation."""
    mutant = lambda: DurableTwinOracle(disable_dedup=True)  # noqa: E731
    result = explore(durability_dedup_scripts(), _effects(),
                     coordinator_factory=mutant, durable=True, por=True,
                     max_violations=100)
    assert result.violations
    assert {v.kind for v in result.violations} & {
        "acked-durability", "exactly-once", "oracle-divergence"}


def test_fuzz_with_durability_schedules_is_deterministic():
    a = run_default(schedules=["durability-base", "durability-torn"],
                    fuzz_samples=20, fuzz_seed=13)
    b = run_default(schedules=["durability-base", "durability-torn"],
                    fuzz_samples=20, fuzz_seed=13)
    assert a.violations == [] and b.violations == []
    assert a.traces == b.traces > 0
    assert ([(n, tr) for n, tr, _s in a.timings]
            == [(n, tr) for n, tr, _s in b.timings])
    assert a.violation_keys() == b.violation_keys()


# -- trace spec round trip (--dump-trace / --replay-trace) ----------------------


def test_dump_and_replay_trace_spec_roundtrip():
    """A violating interleaving dumped as a JSON spec re-executes in
    isolation — exact step order, no exploration — and reproduces the
    violation on the same mutant."""
    mutant = lambda: DurableTwinOracle(skip_tail_scan=True)  # noqa: E731
    sched = Schedule("durability-torn", durability_torn_scripts(), mutant,
                     durable=True, por=True)
    result = explore(sched.scripts, _effects(), coordinator_factory=mutant,
                     durable=True, por=True, max_violations=10,
                     name="durability-torn")
    assert result.violations
    spec = dump_trace_spec(result.violations[0], schedules=[sched])
    spec = json.loads(json.dumps(spec))  # must survive JSON round trip
    assert spec["schedule"] == "durability-torn"
    assert spec["durable"] is True
    assert spec["order"], "dumped spec must carry the worker step order"
    repro = replay_trace_spec(spec, _effects(), coordinator_factory=mutant)
    assert repro, "dumped interleaving must reproduce on the mutant"
    assert {v.kind for v in repro} & {"acked-durability", "exactly-once"}


def test_replayed_spec_is_green_on_the_fixed_twin():
    """The same dumped interleaving replayed against the HEALTHY twin
    (the spec's default factory) passes — the bug is in the mutant, not
    the schedule."""
    mutant = lambda: DurableTwinOracle(skip_tail_scan=True)  # noqa: E731
    sched = Schedule("durability-torn", durability_torn_scripts(), mutant,
                     durable=True, por=True)
    result = explore(sched.scripts, _effects(), coordinator_factory=mutant,
                     durable=True, por=True, max_violations=10,
                     name="durability-torn")
    spec = dump_trace_spec(result.violations[0], schedules=[sched])
    assert replay_trace_spec(spec, _effects()) == []


def test_cli_schedules_filter_and_timings(capsys):
    rc = modelcheck_main(["--schedules", "durability-torn", "--timings"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "28 trace(s)" in out and "0 violation(s)" in out
    assert "durability-torn:" in out


# -- parked ops: barrier / sync -------------------------------------------------


def _barrier_scripts(count):
    return {
        "w0": [mk("register", worker="w0"),
               mk("barrier", name="b", count=count, worker="w0")],
        "w1": [mk("register", worker="w1"),
               mk("barrier", name="b", count=count, worker="w1")],
    }


def test_barrier_release_explored_and_green():
    result = explore(_barrier_scripts(count=2), _effects())
    assert result.traces == 6  # C(4, 2) interleavings
    assert result.violations == []
    assert result.replays == result.traces


def test_unsatisfiable_barrier_is_a_progress_violation():
    """count=3 with two workers: every complete interleaving deadlocks, and
    the model reports it WITHOUT replaying (replay would hang)."""
    result = explore(_barrier_scripts(count=3), _effects())
    assert result.traces == 6
    assert result.violations
    assert {v.kind for v in result.violations} == {"progress"}
    assert result.replays == 0


def test_sync_parking_detects_the_stranded_worker():
    """sync(epoch=2) issued before the second register gets an immediate
    resync and drains; interleavings where it parks after both registers
    but the peer already drained deadlock — the checker must see exactly
    those."""
    scripts = {
        "w0": [mk("register", worker="w0"),
               mk("sync", epoch=2, worker="w0")],
        "w1": [mk("register", worker="w1"),
               mk("sync", epoch=2, worker="w1")],
    }
    result = explore(scripts, _effects())
    assert result.traces == 6
    deadlocks = [v for v in result.violations if v.kind == "progress"]
    assert len(deadlocks) == 2
    assert len(result.violations) == 2  # nothing besides the deadlocks


# -- model plumbing -------------------------------------------------------------


def test_scriptop_make_freezes_nested_fields():
    op = mk("batch", ops=[{"op": "ping"}], worker="w0")
    assert isinstance(op.fields, tuple)
    d = op.field_dict()
    assert d["ops"] == [{"op": "ping"}]
    assert hash(op) is not None  # frozen dataclass stays hashable


def test_unknown_effect_tag_is_a_spec_error_not_a_violation():
    effects = dict(_effects())
    effects["ping"] = {"quantum": "entangle"}
    with pytest.raises(ModelCheckError):
        ProtocolModel(effects)


def test_load_state_effects_reports_missing_block(tmp_path):
    (tmp_path / "protocol_schema.json").write_text(
        json.dumps({"ops": {"ping": {}}})
    )
    effects, ops, err = load_state_effects(str(tmp_path))
    assert effects is None
    assert ops == {"ping"}
    assert "state_effects" in err


def test_load_state_effects_reports_missing_file(tmp_path):
    effects, ops, err = load_state_effects(str(tmp_path))
    assert effects is None and ops is None
    assert "missing" in err


# -- CLI ------------------------------------------------------------------------


def test_cli_exhaustive_exits_zero(capsys):
    rc = modelcheck_main([])
    out = capsys.readouterr().out
    assert rc == 0
    assert "4538 trace(s)" in out and "0 violation(s)" in out


def test_cli_json_fuzz(capsys):
    rc = modelcheck_main(["--fuzz", "10", "--seed", "5", "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert payload["violations"] == []
    assert payload["replays"] == payload["traces"] > 0


# -- native crash-injected oracle (make modelcheck-native's lane) ---------------


@pytest.mark.sanitizer
def test_native_oracle_replays_torn_tail_lane():
    """One full durability lane against the REAL binary: each trace boots
    an edl-coordinator armed to _exit(2) at the modeled crash point (torn
    mode rewinds the journal tail first), then restarts it and checks
    recovery against the model. Small lane (28 traces) so the per-trace
    server boots stay inside the tier-1 budget."""
    from tests.test_coordinator import has_toolchain

    if not has_toolchain():
        pytest.skip("native toolchain unavailable")
    result = run_default(schedules=["durability-torn"], native=True)
    assert result.violations == []
    assert result.traces == 28
    assert result.replays == 28
