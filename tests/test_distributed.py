"""Distributed-identity derivation tests (hermetic, in-process coordinator)."""

import threading
import time

import pytest

from conftest import multiprocess_on_cpu
from edl_tpu.coordinator.inprocess import InProcessCoordinator
from edl_tpu.launcher.launch import LaunchContext
from edl_tpu.runtime.distributed import (
    JAX_COORD_KEY,
    derive_identity,
    distributed_init,
    local_host_ip,
)


def ctx_with(num_trainers, port=7164):
    return LaunchContext.from_env({
        "EDL_JOB_NAME": "t",
        "EDL_NUM_TRAINERS": str(num_trainers),
        "EDL_PORT": str(port),
    })


def test_rank0_publishes_and_peer_reads():
    coord = InProcessCoordinator()
    c0 = coord.client("w0")
    c1 = coord.client("w1")
    c0.register(), c1.register()
    ctx = ctx_with(2)

    got = {}

    def peer():
        got["ident"] = derive_identity(ctx, c1, timeout=10.0)

    t = threading.Thread(target=peer, daemon=True)
    t.start()
    ident0 = derive_identity(ctx, c0, timeout=10.0)
    t.join(timeout=10)
    assert ident0.process_id == 0
    assert ident0.num_processes == 2
    assert ident0.coordinator_address.endswith(":7165")  # port + offset
    assert got["ident"].process_id == 1
    assert got["ident"].coordinator_address == ident0.coordinator_address
    epoch = c0.register()["epoch"]
    assert c0.kv_get(f"{JAX_COORD_KEY}/{epoch}") == ident0.coordinator_address


def test_peer_times_out_without_rank0():
    coord = InProcessCoordinator()
    c0 = coord.client("w0")
    c1 = coord.client("w1")
    c0.register(), c1.register()  # w1 gets rank 1
    with pytest.raises(TimeoutError):
        derive_identity(ctx_with(2), c1, timeout=0.5)


def test_single_process_is_noop():
    coord = InProcessCoordinator()
    c = coord.client("w0")
    c.register()
    assert distributed_init(ctx_with(1), c) is None
    assert distributed_init(ctx_with(4), None) is None


def test_explicit_jax_port():
    coord = InProcessCoordinator()
    c = coord.client("w0")
    c.register()
    ident = derive_identity(ctx_with(1), c, jax_port=9999)
    assert ident.coordinator_address.endswith(":9999")


def test_expected_world_kv_overrides_stale_env():
    """After a rescale the pod env's EDL_NUM_TRAINERS is stale; the control
    plane's published target wins."""
    from edl_tpu.runtime.distributed import EXPECTED_WORLD_KEY, expected_world

    coord = InProcessCoordinator()
    c = coord.client("w0")
    c.register()
    ctx = ctx_with(4)
    assert expected_world(ctx, c) == 4
    c.kv_put(EXPECTED_WORLD_KEY, "2")
    assert expected_world(ctx, c) == 2


def test_epoch_scoped_address_ignores_stale_key():
    """A dead rank 0's address from a previous epoch must never be read."""
    coord = InProcessCoordinator()
    c0 = coord.client("w0")
    c0.register()
    # a previous incarnation published under an old epoch
    c0.kv_put(f"{JAX_COORD_KEY}/0", "10.0.0.99:7165")
    ident = derive_identity(ctx_with(1), c0, timeout=10.0)
    assert ident.coordinator_address != "10.0.0.99:7165"


def test_local_host_ip_shape():
    ip = local_host_ip()
    assert ip.count(".") == 3


@multiprocess_on_cpu
def test_two_process_jax_distributed_bringup(tmp_path):
    """THE multi-host proof: two OS processes, each with 2 virtual CPU
    devices, form one 4-device jax.distributed world via the real C++
    coordinator — rank from registration, rank 0's address via KV."""
    import os
    import subprocess
    import sys

    from edl_tpu.coordinator import CoordinatorServer
    from edl_tpu.coordinator.server import ensure_built, free_port

    ensure_built()
    jax_port = free_port()
    worker_src = f"""
import os, sys
sys.path.insert(0, {repr(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))})
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
from edl_tpu.launcher.launch import LaunchContext
from edl_tpu.launcher.discovery import wait_coordinator
from edl_tpu.runtime.distributed import distributed_init

ctx = LaunchContext.from_env()
client = wait_coordinator(ctx.coordinator_endpoint)
client.worker = "w-" + sys.argv[1]
ident = distributed_init(ctx, client, timeout=60.0, jax_port={jax_port})
assert ident is not None
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 4, jax.device_count()
assert len(jax.local_devices()) == 2
from jax.experimental import multihost_utils
ranks = multihost_utils.process_allgather(__import__("numpy").array([jax.process_index()]))
assert sorted(ranks.ravel().tolist()) == [0, 1], ranks
print("WORKER-OK", ident.process_id)
"""
    with CoordinatorServer() as server:
        env = dict(os.environ)
        env["EDL_COORDINATOR_ENDPOINT"] = server.address
        env["EDL_NUM_TRAINERS"] = "2"
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", worker_src, str(i)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            )
            for i in range(2)
        ]
        outs = [p.communicate(timeout=180) for p in procs]
        for p, (out, err) in zip(procs, outs):
            assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
            assert "WORKER-OK" in out


def test_launcher_relaunches_on_rescale_exit(tmp_path):
    """An entry exiting RESCALE_EXIT_CODE is warm-restarted without burning
    the job failure budget; a normal exit ends the loop."""
    import os

    from edl_tpu.coordinator import CoordinatorServer
    from edl_tpu.launcher.launch import (
        FAILED_COUNT_KEY,
        LaunchContext,
        RESCALE_EXIT_CODE,
        start_trainer,
    )

    marker = tmp_path / "ran"
    entry = tmp_path / "entry.sh"
    entry.write_text(
        "#!/bin/sh\n"
        f"if [ -f {marker} ]; then exit 0; fi\n"
        f"touch {marker}\n"
        f"exit {RESCALE_EXIT_CODE}\n"
    )
    entry.chmod(0o755)

    with CoordinatorServer() as server:
        ctx = LaunchContext.from_env({
            "EDL_JOB_NAME": "t",
            "EDL_COORDINATOR_ENDPOINT": server.address,
            "EDL_ENTRY": f"sh {entry}",
            "EDL_TERMINATION_LOG": str(tmp_path / "term"),
        })
        rc = start_trainer(ctx)
        assert rc == 0
        assert marker.exists()  # first run happened, second run returned 0
        failed = server.client("probe").kv_get(FAILED_COUNT_KEY)
        assert not failed or int(failed) == 0


def test_elastic_worker_exits_for_restart_on_rescale(tmp_path):
    """restart_on_rescale: a membership change makes the worker checkpoint
    durably and exit with RESCALE_EXIT_CODE instead of remeshing in-process."""
    import numpy as np

    from edl_tpu.launcher.launch import RESCALE_EXIT_CODE
    from edl_tpu.models import fit_a_line
    from edl_tpu.runtime import (
        Checkpointer,
        ElasticConfig,
        ElasticWorker,
        SyntheticShardSource,
        shard_names,
    )
    from edl_tpu.runtime.train_loop import TrainerConfig

    coord = InProcessCoordinator(task_lease_sec=60.0, heartbeat_ttl_sec=60.0)
    admin = coord.client("admin")
    admin.add_tasks(shard_names("fit", 50))  # plenty: queue never drains

    worker_client = coord.client("trainer-0")
    worker = ElasticWorker(
        fit_a_line.MODEL,
        worker_client,
        SyntheticShardSource(fit_a_line.MODEL, batch_size=16, batches_per_shard=4),
        ElasticConfig(
            checkpoint_dir=str(tmp_path / "ck"),
            checkpoint_interval=1000,  # only the rescale checkpoint happens
            heartbeat_interval=0.0,
            restart_on_rescale=True,
            trainer=TrainerConfig(optimizer="sgd", learning_rate=0.05),
        ),
    )

    def joiner():
        while worker.steps_done < 3:
            time.sleep(0.02)
        coord.client("trainer-1").register()  # epoch bump

    t = threading.Thread(target=joiner, daemon=True)
    t.start()
    with pytest.raises(SystemExit) as exc:
        worker.run()
    t.join(timeout=5)
    assert exc.value.code == RESCALE_EXIT_CODE
    # the pre-exit checkpoint is durable and restorable
    assert Checkpointer(str(tmp_path / "ck")).latest_step() is not None


def test_late_joiner_exits_cleanly_when_job_drained():
    """A pod scaled up in the job's last seconds: peers completed and left,
    the queue is fully drained — the joiner must exit 0 ('nothing to do'),
    not time out as a failure waiting for a world that never assembles."""
    coord = InProcessCoordinator()
    finisher = coord.client("w-old")
    finisher.register()
    finisher.add_tasks(["s0", "s1"])
    assert finisher.acquire_task() and finisher.acquire_task()
    finisher.complete_task("s0"), finisher.complete_task("s1")
    finisher.leave()

    joiner = coord.client("w-new")
    with pytest.raises(SystemExit) as exc:
        derive_identity(ctx_with(2), joiner, timeout=10.0)
    assert exc.value.code == 0
    st = joiner.status()
    assert int(st["queued"]) == 0 and int(st["done"]) == 2
