"""An in-process fake kube-apiserver for hermetic K8s-backend tests.

Plays the role the generated fake clientset plays for the reference
(`/root/reference/pkg/client/clientset/versioned/fake/clientset_generated.go:
32-69`): an in-memory object tracker behind the real client code paths —
except ours sits behind actual HTTP, so `edl_tpu.k8s`'s REST client, watch
streaming, auth headers, and error mapping are all exercised for real.

Implements the subset the K8s backend touches:

- nodes (seeded by tests), pods (list by labelSelector, deletecollection)
- apps/v1 Deployments, batch/v1 Jobs (parallelism patch reconciles pods),
  v1 Services
- the ``trainingjobs.edl.tpu`` CRD: CRUD + ``/status`` subresource + chunked
  watch streams with resourceVersion resume

Pod lifecycle is simulated K8s-scheduler-style: pods materialize from
workload templates, get first-fit node assignment against allocatable
capacity, and run with phase Running (or stay Pending when nothing fits).
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple


def _match_selector(labels: Dict[str, str], selector: str) -> bool:
    for clause in filter(None, selector.split(",")):
        key, _, value = clause.partition("=")
        if labels.get(key) != value:
            return False
    return True


def _quantity_to_float(value) -> float:
    s = str(value)
    suffixes = {
        "m": 1e-3, "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12,
        "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40,
    }
    for suffix in sorted(suffixes, key=len, reverse=True):
        if s.endswith(suffix):
            return float(s[: -len(suffix)]) * suffixes[suffix]
    return float(s)


class FakeApiServer:
    """State + HTTP server. Start with ``serve()``, stop with ``close()``."""

    def __init__(self, token: Optional[str] = None):
        self.lock = threading.RLock()
        self.rv_counter = 0
        self.token = token  # when set, requests must carry it
        self.auth_seen: List[str] = []
        # (namespace, name) -> object dicts
        self.nodes: Dict[str, dict] = {}
        self.pods: Dict[Tuple[str, str], dict] = {}
        self.deployments: Dict[Tuple[str, str], dict] = {}
        self.jobs: Dict[Tuple[str, str], dict] = {}
        self.services: Dict[Tuple[str, str], dict] = {}
        self.trainingjobs: Dict[Tuple[str, str], dict] = {}
        self.tj_events: List[dict] = []  # {"type","object","rv"}
        self.event_cond = threading.Condition(self.lock)
        self.pod_counter = 0
        self._server: Optional[ThreadingHTTPServer] = None
        self._closing = False
        # -- fault injection (real-apiserver failure modes) --------------------
        #: fail the next N /status PATCHes with 409 Conflict (rv races)
        self.status_conflicts = 0
        #: end each watch stream with an ERROR/410 event after N data events
        #: (etcd compaction mid-stream); None = never
        self.watch_error_410_after: Optional[int] = None
        #: sleep this long before answering LISTs (a loaded apiserver)
        self.list_delay_sec = 0.0
        #: emit a BOOKMARK event on idle watch waits (rv-progress markers
        #: real apiservers send; clients must advance rv without notifying)
        self.send_bookmarks = False

    # -- lifecycle -------------------------------------------------------------

    def serve(self) -> str:
        handler = _make_handler(self)
        self._server = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        self._server.daemon_threads = True
        threading.Thread(target=self._server.serve_forever, daemon=True).start()
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def close(self) -> None:
        with self.event_cond:
            self._closing = True
            self.event_cond.notify_all()
        if self._server:
            self._server.shutdown()
            self._server.server_close()

    # -- state helpers ---------------------------------------------------------

    def next_rv(self) -> str:
        self.rv_counter += 1
        return str(self.rv_counter)

    def add_node(self, name: str, allocatable: Dict[str, str]) -> None:
        with self.lock:
            self.nodes[name] = {
                "metadata": {"name": name},
                "status": {"allocatable": dict(allocatable)},
            }

    def _stamp(self, obj: dict, namespace: str) -> dict:
        meta = obj.setdefault("metadata", {})
        meta.setdefault("namespace", namespace)
        meta["resourceVersion"] = self.next_rv()
        return obj

    def record_tj_event(self, kind: str, obj: dict) -> None:
        with self.event_cond:
            self.tj_events.append(
                {"type": kind, "object": json.loads(json.dumps(obj)),
                 "rv": int(obj["metadata"]["resourceVersion"])}
            )
            self.event_cond.notify_all()

    # -- pod simulation --------------------------------------------------------

    def _node_free(self, node_name: str) -> Dict[str, float]:
        free = {
            k: _quantity_to_float(v)
            for k, v in self.nodes[node_name]["status"]["allocatable"].items()
        }
        for pod in self.pods.values():
            if pod["spec"].get("nodeName") == node_name and (
                pod["status"]["phase"] not in ("Succeeded", "Failed")
            ):
                for c in pod["spec"].get("containers", []):
                    for k, v in (c.get("resources", {}).get("requests") or {}).items():
                        free[k] = free.get(k, 0.0) - _quantity_to_float(v)
        return free

    def _fit_node(self, requests: Dict[str, str]) -> Optional[str]:
        need = {k: _quantity_to_float(v) for k, v in (requests or {}).items()}
        for name in self.nodes:
            free = self._node_free(name)
            if all(free.get(k, 0.0) >= v for k, v in need.items()):
                return name
        return None

    def spawn_pod(self, namespace: str, owner_name: str, template: dict) -> dict:
        self.pod_counter += 1
        template = json.loads(json.dumps(template))
        labels = template.get("metadata", {}).get("labels", {})
        spec = template.get("spec", {})
        requests = {}
        for c in spec.get("containers", []):
            requests.update(c.get("resources", {}).get("requests") or {})
        pod = {
            "metadata": {
                "name": f"{owner_name}-{self.pod_counter}",
                "namespace": namespace,
                "labels": labels,
            },
            "spec": spec,
            "status": {"phase": "Pending"},
        }
        node = self._fit_node(requests)
        if node is not None:
            pod["spec"]["nodeName"] = node
            pod["status"]["phase"] = "Running"
        self._stamp(pod, namespace)
        self.pods[(namespace, pod["metadata"]["name"])] = pod
        return pod

    def reconcile_job_pods(self, namespace: str, job: dict) -> None:
        """Match live pods of a batch Job to spec.parallelism."""
        name = job["metadata"]["name"]
        selector = job["spec"]["template"]["metadata"].get("labels", {})
        want = int(job["spec"].get("parallelism", 0))
        live = [
            key for key, pod in self.pods.items()
            if key[0] == namespace
            and _match_selector(
                pod["metadata"].get("labels", {}),
                ",".join(f"{k}={v}" for k, v in selector.items()),
            )
            and pod["status"]["phase"] in ("Pending", "Running")
        ]
        if len(live) > want:
            for key in live[want:]:
                del self.pods[key]
        else:
            for _ in range(want - len(live)):
                self.spawn_pod(namespace, name, job["spec"]["template"])

    def set_pod_phase(self, namespace: str, name: str, phase: str) -> None:
        with self.lock:
            self.pods[(namespace, name)]["status"]["phase"] = phase


def _make_handler(srv: FakeApiServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):  # quiet
            pass

        # -- plumbing ----------------------------------------------------------

        def _send(self, code: int, obj: dict) -> None:
            payload = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def _error(self, code: int, message: str) -> None:
            self._send(code, {"kind": "Status", "code": code, "message": message})

        def _body(self) -> dict:
            length = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(length)) if length else {}

        def _route(self) -> Tuple[List[str], Dict[str, str]]:
            parsed = urllib.parse.urlsplit(self.path)
            params = dict(urllib.parse.parse_qsl(parsed.query))
            return [p for p in parsed.path.split("/") if p], params

        def _authorized(self) -> bool:
            auth = self.headers.get("Authorization", "")
            srv.auth_seen.append(auth)
            if srv.token and auth != f"Bearer {srv.token}":
                self._error(401, "unauthorized")
                return False
            return True

        # -- dispatch ----------------------------------------------------------

        def do_GET(self):
            if not self._authorized():
                return
            parts, params = self._route()
            with srv.lock:
                # /api/v1/nodes
                if parts == ["api", "v1", "nodes"]:
                    return self._send(200, {"items": list(srv.nodes.values())})
                # /api/v1/pods (all namespaces)
                if parts == ["api", "v1", "pods"]:
                    return self._list(srv.pods, None, params)
                # /api/v1/namespaces/{ns}/pods
                if len(parts) == 5 and parts[:3] == ["api", "v1", "namespaces"] \
                        and parts[4] == "pods":
                    return self._list(srv.pods, parts[3], params)
                # batch jobs get
                if len(parts) == 7 and parts[:2] == ["apis", "batch"] \
                        and parts[5] == "jobs":
                    job = srv.jobs.get((parts[4], parts[6]))
                    if job is None:
                        return self._error(404, "job not found")
                    return self._send(200, job)
                # trainingjobs
                if parts[:3] == ["apis", "edl.tpu", "v1"]:
                    return self._get_tj(parts[3:], params)
            self._error(404, f"no route {self.path}")

        def _list(self, table, namespace, params):
            if srv.list_delay_sec:
                import time as _t

                _t.sleep(srv.list_delay_sec)
            selector = params.get("labelSelector", "")
            items = [
                obj for (ns, _), obj in table.items()
                if (namespace is None or ns == namespace)
                and _match_selector(obj["metadata"].get("labels", {}), selector)
            ]
            self._send(200, {"items": items,
                             "metadata": {"resourceVersion": str(srv.rv_counter)}})

        def _get_tj(self, rest: List[str], params: Dict[str, str]):
            # rest: [trainingjobs] | [namespaces, ns, trainingjobs, name?]
            if rest and rest[0] == "trainingjobs":
                if params.get("watch") == "true":
                    return self._watch_tj(params)
                return self._list(srv.trainingjobs, None, params)
            if len(rest) >= 3 and rest[0] == "namespaces" and rest[2] == "trainingjobs":
                ns = rest[1]
                if len(rest) == 3:
                    if params.get("watch") == "true":
                        return self._watch_tj(params, namespace=ns)
                    return self._list(srv.trainingjobs, ns, params)
                obj = srv.trainingjobs.get((ns, rest[3]))
                if obj is None:
                    return self._error(404, "trainingjob not found")
                return self._send(200, obj)
            self._error(404, "no trainingjob route")

        def _watch_tj(self, params: Dict[str, str], namespace: Optional[str] = None):
            try:
                since = int(params.get("resourceVersion") or srv.rv_counter)
            except ValueError:
                since = srv.rv_counter
            timeout = float(params.get("timeoutSeconds", 30))
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            def emit(event: dict) -> bool:
                data = json.dumps(
                    {"type": event["type"], "object": event["object"]}
                ).encode() + b"\n"
                try:
                    self.wfile.write(hex(len(data))[2:].encode() + b"\r\n"
                                     + data + b"\r\n")
                    self.wfile.flush()
                    return True
                except OSError:
                    return False

            import time
            deadline = time.monotonic() + timeout
            cursor = since
            emitted = 0
            while True:
                bookmark = None
                with srv.event_cond:
                    pending = [
                        e for e in srv.tj_events
                        if e["rv"] > cursor and (
                            namespace is None
                            or e["object"]["metadata"]["namespace"] == namespace
                        )
                    ]
                    if not pending:
                        if srv._closing or time.monotonic() >= deadline:
                            break
                        if srv.send_bookmarks:
                            # rv-progress marker on an idle stream, exactly
                            # what a real apiserver's allowWatchBookmarks
                            # path emits: metadata-only object, current rv.
                            # Built here, WRITTEN outside the lock: wfile
                            # can block on a slow client, and event_cond
                            # shares the server's global lock.
                            bookmark = {"type": "BOOKMARK", "object": {
                                "metadata": {
                                    "resourceVersion": str(srv.rv_counter),
                                    "namespace": namespace or "default",
                                },
                            }}
                        else:
                            srv.event_cond.wait(
                                timeout=min(0.2, max(0.0,
                                                     deadline - time.monotonic()))
                            )
                            continue
                if bookmark is not None:
                    if not emit(bookmark):
                        return
                    time.sleep(0.2)
                    continue
                for event in pending:
                    cursor = event["rv"]
                    if not emit(event):
                        return
                    emitted += 1
                    if (srv.watch_error_410_after is not None
                            and emitted >= srv.watch_error_410_after):
                        # etcd compacted past the client's rv mid-stream:
                        # the standard Gone error event, then stream end —
                        # the informer must relist, not crash or spin.
                        emit({"type": "ERROR", "object": {
                            "kind": "Status", "code": 410, "reason": "Gone",
                            "message": "too old resource version",
                        }})
                        try:
                            self.wfile.write(b"0\r\n\r\n")
                            self.wfile.flush()
                        except OSError:
                            pass
                        return
            try:
                self.wfile.write(b"0\r\n\r\n")
                self.wfile.flush()
            except OSError:
                pass

        def do_POST(self):
            if not self._authorized():
                return
            parts, _ = self._route()
            body = self._body()
            with srv.lock:
                if len(parts) >= 5 and parts[-1] == "deployments":
                    return self._create(srv.deployments, parts[-2], body,
                                        kind="deployment")
                if len(parts) >= 5 and parts[-1] == "jobs":
                    return self._create(srv.jobs, parts[-2], body, kind="job")
                if len(parts) >= 5 and parts[-1] == "services":
                    return self._create(srv.services, parts[-2], body,
                                        kind="service")
                if len(parts) >= 5 and parts[-1] == "trainingjobs":
                    return self._create(srv.trainingjobs, parts[-2], body,
                                        kind="trainingjob")
            self._error(404, f"no POST route {self.path}")

        def _create(self, table, namespace, body, kind):
            name = body.get("metadata", {}).get("name")
            if not name:
                return self._error(400, "metadata.name required")
            if (namespace, name) in table:
                return self._error(409, f"{kind} {name} already exists")
            srv._stamp(body, namespace)
            table[(namespace, name)] = body
            if kind == "deployment":
                for _ in range(int(body["spec"].get("replicas", 1))):
                    srv.spawn_pod(namespace, name, body["spec"]["template"])
            elif kind == "job":
                srv.reconcile_job_pods(namespace, body)
            elif kind == "trainingjob":
                body.setdefault("status", {})
                srv.record_tj_event("ADDED", body)
            self._send(201, body)

        def do_PATCH(self):
            if not self._authorized():
                return
            parts, _ = self._route()
            body = self._body()
            with srv.lock:
                if len(parts) == 7 and parts[1] == "batch" and parts[5] == "jobs":
                    job = srv.jobs.get((parts[4], parts[6]))
                    if job is None:
                        return self._error(404, "job not found")
                    _merge(job, body)
                    srv._stamp(job, parts[4])
                    srv.reconcile_job_pods(parts[4], job)
                    return self._send(200, job)
                if parts[:3] == ["apis", "edl.tpu", "v1"] and len(parts) >= 7:
                    ns, name = parts[4], parts[6]
                    is_status = len(parts) == 8 and parts[7] == "status"
                    obj = srv.trainingjobs.get((ns, name))
                    if obj is None:
                        return self._error(404, "trainingjob not found")
                    if is_status and srv.status_conflicts > 0:
                        srv.status_conflicts -= 1
                        return self._error(
                            409, "Operation cannot be fulfilled: object "
                                 "has been modified"
                        )
                    if is_status:
                        # status subresource: only .status is applied
                        obj["status"] = body.get("status", {})
                    else:
                        body.pop("status", None)
                        _merge(obj, body)
                    srv._stamp(obj, ns)
                    srv.record_tj_event("MODIFIED", obj)
                    return self._send(200, obj)
            self._error(404, f"no PATCH route {self.path}")

        def do_DELETE(self):
            if not self._authorized():
                return
            parts, params = self._route()
            with srv.lock:
                # deletecollection of pods by selector
                if len(parts) == 5 and parts[4] == "pods":
                    selector = params.get("labelSelector", "")
                    doomed = [
                        key for key, pod in srv.pods.items()
                        if key[0] == parts[3] and _match_selector(
                            pod["metadata"].get("labels", {}), selector)
                    ]
                    for key in doomed:
                        del srv.pods[key]
                    return self._send(200, {"kind": "Status", "status": "Success"})
                for table, kind in (
                    (srv.deployments, "deployments"),
                    (srv.jobs, "jobs"),
                    (srv.services, "services"),
                ):
                    if len(parts) >= 2 and parts[-2] == kind:
                        ns, name = parts[-3], parts[-1]
                        if (ns, name) not in table:
                            return self._error(404, f"{kind} {name} not found")
                        del table[(ns, name)]
                        return self._send(200, {"kind": "Status",
                                                "status": "Success"})
                if parts[:3] == ["apis", "edl.tpu", "v1"] and len(parts) == 7:
                    ns, name = parts[4], parts[6]
                    obj = srv.trainingjobs.pop((ns, name), None)
                    if obj is None:
                        return self._error(404, "trainingjob not found")
                    srv._stamp(obj, ns)
                    srv.record_tj_event("DELETED", obj)
                    return self._send(200, obj)
            self._error(404, f"no DELETE route {self.path}")

    return Handler


def _merge(dst: dict, patch: dict) -> None:
    """RFC 7386 merge patch."""
    for key, value in patch.items():
        if value is None:
            dst.pop(key, None)
        elif isinstance(value, dict) and isinstance(dst.get(key), dict):
            _merge(dst[key], value)
        else:
            dst[key] = value
