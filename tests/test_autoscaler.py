"""Autoscaler core tests.

Mirrors the reference's pure-logic table tests
(`pkg/autoscaler_internal_test.go:96-438`): scale up/down under CPU/TPU/memory
pressure, fixed-point convergence, fulfillment math, sort order — all against
hand-built ClusterResource fixtures, no cluster.
"""

from edl_tpu.api import ResourceList, TrainingJob
from edl_tpu.api.validation import normalize
from edl_tpu.controller import (
    Autoscaler,
    AutoscalerConfig,
    FakeCluster,
    JobState,
    NodeInfo,
    fulfillment,
    scale_all_dry_run,
    scale_dry_run,
    sorted_jobs_by_fulfillment,
)
from edl_tpu.controller.cluster import inquire_resource


def make_job(name, min_i=2, max_i=10, chips=4, cpu="1", mem="1Gi", cur=2):
    """Job factory (ref: makeJob, autoscaler_internal_test.go:56-94)."""
    job = TrainingJob.from_dict(
        {
            "metadata": {"name": name},
            "spec": {
                "tpu": {"chips_per_trainer": chips},
                "trainer": {
                    "min_instance": min_i,
                    "max_instance": max_i,
                    "resources": {
                        "requests": {"cpu": cpu, "memory": mem},
                        "limits": {"cpu": cpu, "memory": mem},
                    },
                },
            },
        }
    )
    return JobState(job=normalize(job), current=cur)


def tpu_cluster(n_hosts=4, chips_per_host=4, cpu=16, mem_gi=64):
    """A v5e-pod-like fixture: n hosts x chips."""
    return [
        NodeInfo(
            name=f"host{i}",
            allocatable=ResourceList.make(
                {"cpu": cpu, "memory": f"{mem_gi}Gi", "tpu": chips_per_host}
            ),
        )
        for i in range(n_hosts)
    ]


def snapshot(nodes, pods=()):
    return inquire_resource(list(nodes), list(pods))


def test_fulfillment_math():
    # ref: autoscaler_internal_test.go:366-375
    assert fulfillment(make_job("a", min_i=2, max_i=10, cur=2)) == 0.0
    assert fulfillment(make_job("a", min_i=2, max_i=10, cur=10)) == 1.0
    assert fulfillment(make_job("a", min_i=2, max_i=6, cur=4)) == 0.5
    assert fulfillment(make_job("a", min_i=3, max_i=3, cur=3)) == 1.0


def test_sort_order_starved_first_with_hunger_tiebreak():
    # ref: autoscaler_internal_test.go:377-438
    starved = make_job("starved", min_i=2, max_i=10, cur=2)
    happy = make_job("happy", min_i=2, max_i=10, cur=10)
    mid_small = make_job("mid-small", min_i=2, max_i=6, cur=4, chips=4)
    mid_big = make_job("mid-big", min_i=2, max_i=6, cur=4, chips=8)
    order = [s.name for s in sorted_jobs_by_fulfillment([happy, mid_small, mid_big, starved])]
    assert order == ["starved", "mid-big", "mid-small", "happy"]


def test_scale_up_when_chips_free():
    r = snapshot(tpu_cluster(n_hosts=4))
    s = make_job("j", cur=2)
    # 2 trainers already placed -> account them
    r.assign("host0", s.request())
    r.assign("host1", s.request())
    assert scale_dry_run(r, s, 0, 0.97, scale_down=False) == 1
    assert r.requested["tpu"] == 12.0


def test_scale_up_blocked_by_chip_exhaustion():
    r = snapshot(tpu_cluster(n_hosts=2))  # 8 chips total
    s = make_job("j", cur=2)
    r.assign("host0", s.request())
    r.assign("host1", s.request())  # all 8 chips used
    assert scale_dry_run(r, s, 0, 0.97, scale_down=False) == 0


def test_scale_up_blocked_by_fragmentation():
    """6 chips free cluster-wide but only 2 per host: a 4-chip granule must NOT fit."""
    nodes = tpu_cluster(n_hosts=3, chips_per_host=4)
    r = snapshot(nodes)
    for h in ("host0", "host1", "host2"):
        r.assign(h, ResourceList.make({"tpu": 2}))  # fragment every host
    s = make_job("j", chips=4, cur=0)
    assert scale_dry_run(r, s, 0, 0.97, scale_down=False) == 0


def test_scale_up_blocked_by_cpu_ceiling():
    # ref: CPU headroom vs maxLoadDesired, autoscaler.go:271-273
    nodes = tpu_cluster(n_hosts=1, chips_per_host=16, cpu=10)
    r = snapshot(nodes)
    s = make_job("j", cpu="4", cur=0)
    assert scale_dry_run(r, s, 0, 0.97, scale_down=False) == 1
    assert scale_dry_run(r, s, 1, 0.97, scale_down=False) == 1
    # third trainer would need 12 > 0.97*10 CPUs
    assert scale_dry_run(r, s, 2, 0.97, scale_down=False) == 0


def test_scale_up_blocked_by_memory():
    nodes = tpu_cluster(n_hosts=1, chips_per_host=16, mem_gi=2)
    r = snapshot(nodes)
    s = make_job("j", mem="3Gi", cur=0)
    assert scale_dry_run(r, s, 0, 0.97, scale_down=False) == 0


def test_scale_down_on_overcommit():
    # ref: scale-down when demand exceeds ceiling, autoscaler.go:230-249
    nodes = tpu_cluster(n_hosts=1, chips_per_host=8)
    r = snapshot(nodes)
    s = make_job("j", cur=3)  # 12 chips requested > 8 available
    r.requested.add(ResourceList.make({"tpu": 12, "cpu": 3, "memory": "3Gi"}))
    assert scale_dry_run(r, s, 0, 0.97, scale_down=True) == -1
    assert r.requested["tpu"] == 8.0


def test_scale_down_respects_min_instance():
    nodes = tpu_cluster(n_hosts=1, chips_per_host=4)
    r = snapshot(nodes)
    s = make_job("j", min_i=2, cur=2)
    r.requested.add(ResourceList.make({"tpu": 8}))  # overcommitted
    assert scale_dry_run(r, s, 0, 0.97, scale_down=True) == 0


def test_fixed_point_fills_cluster():
    # ref: scaleAllJobsDryRun, autoscaler_internal_test.go:256-364
    r = snapshot(tpu_cluster(n_hosts=4, chips_per_host=4))  # 16 chips
    a = make_job("a", min_i=1, max_i=10, cur=1)
    b = make_job("b", min_i=1, max_i=10, cur=1)
    r.assign("host0", a.request())
    r.assign("host1", b.request())
    diff = scale_all_dry_run(r, [a, b], 0.97)
    # 2 placed + 2 more possible (16 chips / 4 per trainer = 4 trainers)
    assert diff["a"] + diff["b"] == 2
    assert abs(diff["a"] - diff["b"]) <= 1  # fair split


def test_fixed_point_favors_starved_job():
    r = snapshot(tpu_cluster(n_hosts=4, chips_per_host=4))
    rich = make_job("rich", min_i=1, max_i=4, cur=3)
    poor = make_job("poor", min_i=1, max_i=4, cur=1)
    for h in ("host0", "host1", "host2"):
        r.assign(h, rich.request())
    r.assign("host3", poor.request())
    diff = scale_all_dry_run(r, [rich, poor], 0.97)
    assert diff == {"rich": 0, "poor": 0} or diff["poor"] >= diff["rich"]


def test_autoscaler_end_to_end_with_fake_cluster():
    """Full loop against the fake provider: job grows to fill free chips."""
    cluster = FakeCluster(tpu_cluster(n_hosts=4, chips_per_host=4))
    job = make_job("grow", min_i=1, max_i=10, cur=1).job
    req = job.trainer_request()
    lim = job.trainer_limit()
    cluster.create_role("grow", "trainer", 1, req, lim)
    scaler = Autoscaler(cluster, AutoscalerConfig(loop_seconds=0.01))
    scaler.on_add(job)
    scaler._apply_event(scaler._events.get_nowait())
    target = scaler.step()
    assert target["grow"] == 4  # 16 chips / 4 per trainer
    assert cluster.get_trainer_parallelism("grow") == 4
    assert len([p for p in cluster.pods if p.phase == "Running"]) == 4
    # steady state: second pass changes nothing
    assert scaler.step() == {}
    assert job.status.scale_history[-1].to_replicas == 4


def test_make_room_for_pending_job():
    """Boss-tutorial scenario (doc/boss_tutorial.md:289-301): a new job with all
    pods pending forces running elastic jobs to shrink toward min."""
    cluster = FakeCluster(tpu_cluster(n_hosts=4, chips_per_host=4))
    hog = make_job("hog", min_i=1, max_i=4, cur=4).job
    cluster.create_role("hog", "trainer", 4, hog.trainer_request(), hog.trainer_limit())
    newbie = make_job("newbie", min_i=1, max_i=4, cur=1).job
    cluster.create_role("newbie", "trainer", 1, newbie.trainer_request(), newbie.trainer_limit())
    assert all(p.phase == "Pending" for p in cluster.job_pods("newbie"))

    scaler = Autoscaler(cluster, AutoscalerConfig(loop_seconds=0.01))
    scaler.on_add(hog)
    scaler.on_add(newbie)
    for _ in range(2):
        scaler._apply_event(scaler._events.get_nowait())
    for _ in range(5):  # a few control periods
        scaler.step()
    assert cluster.get_trainer_parallelism("hog") < 4
    assert all(p.phase == "Running" for p in cluster.job_pods("newbie"))
