"""Autoscaler core tests.

Mirrors the reference's pure-logic table tests
(`pkg/autoscaler_internal_test.go:96-438`): scale up/down under CPU/TPU/memory
pressure, fixed-point convergence, fulfillment math, sort order — all against
hand-built ClusterResource fixtures, no cluster.
"""

from edl_tpu.api import ResourceList, TrainingJob
from edl_tpu.api.validation import normalize
from edl_tpu.controller import (
    Autoscaler,
    AutoscalerConfig,
    FakeCluster,
    JobState,
    NodeInfo,
    fulfillment,
    scale_all_dry_run,
    scale_dry_run,
    sorted_jobs_by_fulfillment,
)
from edl_tpu.controller.cluster import inquire_resource


def make_job(name, min_i=2, max_i=10, chips=4, cpu="1", mem="1Gi", cur=2):
    """Job factory (ref: makeJob, autoscaler_internal_test.go:56-94)."""
    job = TrainingJob.from_dict(
        {
            "metadata": {"name": name},
            "spec": {
                "tpu": {"chips_per_trainer": chips},
                "trainer": {
                    "min_instance": min_i,
                    "max_instance": max_i,
                    "resources": {
                        "requests": {"cpu": cpu, "memory": mem},
                        "limits": {"cpu": cpu, "memory": mem},
                    },
                },
            },
        }
    )
    return JobState(job=normalize(job), current=cur)


def tpu_cluster(n_hosts=4, chips_per_host=4, cpu=16, mem_gi=64):
    """A v5e-pod-like fixture: n hosts x chips."""
    return [
        NodeInfo(
            name=f"host{i}",
            allocatable=ResourceList.make(
                {"cpu": cpu, "memory": f"{mem_gi}Gi", "tpu": chips_per_host}
            ),
        )
        for i in range(n_hosts)
    ]


def snapshot(nodes, pods=()):
    return inquire_resource(list(nodes), list(pods))


def test_fulfillment_math():
    # ref: autoscaler_internal_test.go:366-375
    assert fulfillment(make_job("a", min_i=2, max_i=10, cur=2)) == 0.0
    assert fulfillment(make_job("a", min_i=2, max_i=10, cur=10)) == 1.0
    assert fulfillment(make_job("a", min_i=2, max_i=6, cur=4)) == 0.5
    assert fulfillment(make_job("a", min_i=3, max_i=3, cur=3)) == 1.0


def test_sort_order_starved_first_with_hunger_tiebreak():
    # ref: autoscaler_internal_test.go:377-438
    starved = make_job("starved", min_i=2, max_i=10, cur=2)
    happy = make_job("happy", min_i=2, max_i=10, cur=10)
    mid_small = make_job("mid-small", min_i=2, max_i=6, cur=4, chips=4)
    mid_big = make_job("mid-big", min_i=2, max_i=6, cur=4, chips=8)
    order = [s.name for s in sorted_jobs_by_fulfillment([happy, mid_small, mid_big, starved])]
    assert order == ["starved", "mid-big", "mid-small", "happy"]


def test_scale_up_when_chips_free():
    r = snapshot(tpu_cluster(n_hosts=4))
    s = make_job("j", cur=2)
    # 2 trainers already placed -> account them
    r.assign("host0", s.request())
    r.assign("host1", s.request())
    assert scale_dry_run(r, s, 0, 0.97, scale_down=False) == 1
    assert r.requested["tpu"] == 12.0


def test_scale_up_blocked_by_chip_exhaustion():
    r = snapshot(tpu_cluster(n_hosts=2))  # 8 chips total
    s = make_job("j", cur=2)
    r.assign("host0", s.request())
    r.assign("host1", s.request())  # all 8 chips used
    assert scale_dry_run(r, s, 0, 0.97, scale_down=False) == 0


def test_scale_up_blocked_by_fragmentation():
    """6 chips free cluster-wide but only 2 per host: a 4-chip granule must NOT fit."""
    nodes = tpu_cluster(n_hosts=3, chips_per_host=4)
    r = snapshot(nodes)
    for h in ("host0", "host1", "host2"):
        r.assign(h, ResourceList.make({"tpu": 2}))  # fragment every host
    s = make_job("j", chips=4, cur=0)
    assert scale_dry_run(r, s, 0, 0.97, scale_down=False) == 0


def test_scale_up_blocked_by_cpu_ceiling():
    # ref: CPU headroom vs maxLoadDesired, autoscaler.go:271-273
    nodes = tpu_cluster(n_hosts=1, chips_per_host=16, cpu=10)
    r = snapshot(nodes)
    s = make_job("j", cpu="4", cur=0)
    assert scale_dry_run(r, s, 0, 0.97, scale_down=False) == 1
    assert scale_dry_run(r, s, 1, 0.97, scale_down=False) == 1
    # third trainer would need 12 > 0.97*10 CPUs
    assert scale_dry_run(r, s, 2, 0.97, scale_down=False) == 0


def test_scale_up_blocked_by_memory():
    nodes = tpu_cluster(n_hosts=1, chips_per_host=16, mem_gi=2)
    r = snapshot(nodes)
    s = make_job("j", mem="3Gi", cur=0)
    assert scale_dry_run(r, s, 0, 0.97, scale_down=False) == 0


def test_scale_down_on_overcommit():
    # ref: scale-down when demand exceeds ceiling, autoscaler.go:230-249
    nodes = tpu_cluster(n_hosts=1, chips_per_host=8)
    r = snapshot(nodes)
    s = make_job("j", cur=3)  # 12 chips requested > 8 available
    r.requested.add(ResourceList.make({"tpu": 12, "cpu": 3, "memory": "3Gi"}))
    assert scale_dry_run(r, s, 0, 0.97, scale_down=True) == -1
    assert r.requested["tpu"] == 8.0


def test_scale_down_respects_min_instance():
    nodes = tpu_cluster(n_hosts=1, chips_per_host=4)
    r = snapshot(nodes)
    s = make_job("j", min_i=2, cur=2)
    r.requested.add(ResourceList.make({"tpu": 8}))  # overcommitted
    assert scale_dry_run(r, s, 0, 0.97, scale_down=True) == 0


def test_fixed_point_fills_cluster():
    # ref: scaleAllJobsDryRun, autoscaler_internal_test.go:256-364
    r = snapshot(tpu_cluster(n_hosts=4, chips_per_host=4))  # 16 chips
    a = make_job("a", min_i=1, max_i=10, cur=1)
    b = make_job("b", min_i=1, max_i=10, cur=1)
    r.assign("host0", a.request())
    r.assign("host1", b.request())
    diff = scale_all_dry_run(r, [a, b], 0.97)
    # 2 placed + 2 more possible (16 chips / 4 per trainer = 4 trainers)
    assert diff["a"] + diff["b"] == 2
    assert abs(diff["a"] - diff["b"]) <= 1  # fair split


def test_fixed_point_favors_starved_job():
    r = snapshot(tpu_cluster(n_hosts=4, chips_per_host=4))
    rich = make_job("rich", min_i=1, max_i=4, cur=3)
    poor = make_job("poor", min_i=1, max_i=4, cur=1)
    for h in ("host0", "host1", "host2"):
        r.assign(h, rich.request())
    r.assign("host3", poor.request())
    diff = scale_all_dry_run(r, [rich, poor], 0.97)
    assert diff == {"rich": 0, "poor": 0} or diff["poor"] >= diff["rich"]


def test_autoscaler_end_to_end_with_fake_cluster():
    """Full loop against the fake provider: job grows to fill free chips."""
    cluster = FakeCluster(tpu_cluster(n_hosts=4, chips_per_host=4))
    job = make_job("grow", min_i=1, max_i=10, cur=1).job
    req = job.trainer_request()
    lim = job.trainer_limit()
    cluster.create_role("grow", "trainer", 1, req, lim)
    scaler = Autoscaler(cluster, AutoscalerConfig(loop_seconds=0.01))
    scaler.on_add(job)
    scaler._apply_event(scaler._events.get_nowait())
    target = scaler.step()
    assert target["grow"] == 4  # 16 chips / 4 per trainer
    assert cluster.get_trainer_parallelism("grow") == 4
    assert len([p for p in cluster.pods if p.phase == "Running"]) == 4
    # steady state: second pass changes nothing
    assert scaler.step() == {}
    assert job.status.scale_history[-1].to_replicas == 4


def test_make_room_for_pending_job():
    """Boss-tutorial scenario (doc/boss_tutorial.md:289-301): a new job with all
    pods pending forces running elastic jobs to shrink toward min."""
    cluster = FakeCluster(tpu_cluster(n_hosts=4, chips_per_host=4))
    hog = make_job("hog", min_i=1, max_i=4, cur=4).job
    cluster.create_role("hog", "trainer", 4, hog.trainer_request(), hog.trainer_limit())
    newbie = make_job("newbie", min_i=1, max_i=4, cur=1).job
    cluster.create_role("newbie", "trainer", 1, newbie.trainer_request(), newbie.trainer_limit())
    assert all(p.phase == "Pending" for p in cluster.job_pods("newbie"))

    scaler = Autoscaler(cluster, AutoscalerConfig(loop_seconds=0.01))
    scaler.on_add(hog)
    scaler.on_add(newbie)
    for _ in range(2):
        scaler._apply_event(scaler._events.get_nowait())
    for _ in range(5):  # a few control periods
        scaler.step()
    assert cluster.get_trainer_parallelism("hog") < 4
    assert all(p.phase == "Running" for p in cluster.job_pods("newbie"))


# -- property tests: invariants of the pure dry-run core -----------------------


def _random_cluster(rng, n_nodes):
    # Through the production snapshot path (inquire_resource), not a
    # hand-assembled ClusterResource — so the property tests exercise the
    # exact cluster shape the controller derives.
    nodes = [
        NodeInfo(
            name=f"n{i}",
            allocatable=ResourceList.make({
                "cpu": float(rng.choice([8, 16, 32])),
                "memory": float(rng.choice([2, 4, 8])) * 2**30,
                "tpu": float(rng.choice([0, 4, 4, 8])),
            }),
        )
        for i in range(n_nodes)
    ]
    return snapshot(nodes)


def _random_job(rng, i):
    lo = int(rng.integers(1, 4))
    hi = lo + int(rng.integers(0, 8))
    job = TrainingJob.from_dict({
        "metadata": {"name": f"j{i}"},
        "spec": {
            "tpu": {"chips_per_trainer": int(rng.choice([0, 4, 4, 8]))},
            "trainer": {
                "min_instance": lo, "max_instance": hi,
                "resources": {"requests": {
                    "cpu": str(int(rng.integers(1, 4))),
                    "memory": f"{int(rng.integers(1, 3))}Gi",
                }},
            },
        },
    })
    # current anywhere in [lo, hi]: above-floor starts make the scale-DOWN
    # arm reachable (an at-floor-only population can never shrink, which
    # would leave the floor invariant vacuously true).
    return JobState(job=job, current=int(rng.integers(lo, hi + 1)))


def test_scale_all_dry_run_invariants_random():
    """Random clusters x random elastic jobs: the fixed-point plan never
    exceeds max_instance, never shrinks below min(current, min_instance),
    never over-commits TPU chips when starting feasible, never worsens an
    infeasible start, and is deterministic. Some trials start deliberately
    OVER-committed — inquire counts PENDING pods' requests too, which is
    exactly what trips the scale-down arm."""
    import numpy as np

    rng = np.random.default_rng(7)
    downs = 0
    for trial in range(60):
        resource = _random_cluster(rng, int(rng.integers(1, 6)))
        states = [_random_job(rng, i) for i in range(int(rng.integers(1, 5)))]
        # Account the initial replicas as inquire would: place what fits on
        # nodes; with some probability keep the remainder as PENDING pods —
        # their requests count against the ceiling but hold no node.
        placed_states = []
        for s in states:
            pending_ok = rng.random() < 0.4
            placed = 0
            for _ in range(s.current):
                node = resource.search_assignable_node(s.request())
                if node is None:
                    if pending_ok:
                        resource.requested.add(s.request())
                        placed += 1
                    continue
                resource.assign(node, s.request())
                placed += 1
            s.current = placed
            if placed:
                placed_states.append(s)
        if not placed_states:
            continue
        states = placed_states

        diff = scale_all_dry_run(resource.copy(), states, max_load_desired=0.9)
        again = scale_all_dry_run(resource.copy(), states, max_load_desired=0.9)
        assert diff == again  # deterministic
        downs += sum(1 for v in diff.values() if v < 0)

        tpu_before = resource.requested.get_q("tpu")
        tpu_after = tpu_before
        for s in states:
            final = s.current + diff[s.name]
            assert final <= s.max_instance(), (trial, s.name, diff)
            assert final >= min(s.current, s.min_instance()), (trial, s.name, diff)
            tpu_after += diff[s.name] * s.request().get_q("tpu")
        # started feasible -> ends feasible; started over-committed -> the
        # plan must not be worse than the start
        cap = max(tpu_before, resource.total.get_q("tpu"))
        assert tpu_after <= cap + 1e-9, (trial, diff)
    # the population genuinely reaches the scale-down arm (non-vacuous)
    assert downs > 0


def test_make_room_dry_run_invariants_random():
    """make-room only ever shrinks, never below any job's floor, and
    terminates on arbitrary pending sets."""
    import numpy as np

    from edl_tpu.controller.autoscaler import make_room_dry_run

    rng = np.random.default_rng(11)
    for trial in range(40):
        resource = _random_cluster(rng, int(rng.integers(1, 6)))
        states = []
        for i in range(int(rng.integers(1, 5))):
            s = _random_job(rng, i)
            s.current = int(rng.integers(s.min_instance(), s.max_instance() + 1))
            placed = 0
            for _ in range(s.current):
                node = resource.search_assignable_node(s.request())
                if node is None:
                    break
                resource.assign(node, s.request())
                placed += 1
            s.current = placed
            if placed:
                states.append(s)
        if not states:
            continue
        pending = [
            ResourceList.make({"cpu": str(int(rng.integers(1, 8))),
                               "tpu": float(rng.choice([0, 4, 8]))})
            for _ in range(int(rng.integers(1, 4)))
        ]
        diff = make_room_dry_run(resource.copy(), states, pending)
        for s in states:
            assert diff[s.name] <= 0, (trial, diff)
            assert s.current + diff[s.name] >= min(s.current, s.min_instance()), (
                trial, s.name, diff,
            )


# -- serving-tier SLO pass -----------------------------------------------------


def make_serving_job(name, min_i=1, max_i=6, chips=4, cur=2,
                     p99=0.25, max_queue=8.0):
    """A serving-tier job: spec.serving set, same trainer resource shape."""
    job = TrainingJob.from_dict(
        {
            "metadata": {"name": name},
            "spec": {
                "tpu": {"chips_per_trainer": chips},
                "trainer": {
                    "min_instance": min_i,
                    "max_instance": max_i,
                    "resources": {
                        "requests": {"cpu": "1", "memory": "1Gi"},
                        "limits": {"cpu": "1", "memory": "1Gi"},
                    },
                },
                "serving": {
                    "model_dir": "/srv/model",
                    "buckets": [1, 8, 32],
                    "slo_p99_seconds": p99,
                    "max_queue_per_replica": max_queue,
                },
            },
        }
    )
    return normalize(job)


def breached_signal(queue=50.0):
    """A ServeSignal whose p99 sits far above any sane SLO."""
    from edl_tpu.serving.autoscale import ServeSignal

    return ServeSignal(
        latency_buckets=[(0.1, 0.0), (5.0, 1000.0), (float("inf"), 1000.0)],
        latency_count=1000.0, queue_depth=queue,
    )


def comfy_signal():
    from edl_tpu.serving.autoscale import ServeSignal

    return ServeSignal(
        latency_buckets=[(0.005, 1000.0), (float("inf"), 1000.0)],
        latency_count=1000.0, queue_depth=0.0,
    )


def serving_scaler(job, cur, n_hosts=4, signal=None):
    """Autoscaler over a FakeCluster with one serving job at ``cur``
    replicas and an injected scrape fake."""
    cluster = FakeCluster(tpu_cluster(n_hosts=n_hosts, chips_per_host=4))
    cluster.create_role(job.name, "trainer", cur,
                        job.trainer_request(), job.trainer_limit())
    scaler = Autoscaler(cluster, AutoscalerConfig(loop_seconds=0.01))
    scaler.on_add(job)
    scaler._apply_event(scaler._events.get_nowait())
    scaler.register_serving_endpoints(job.name, ["http://replica:0"])
    if signal is not None:
        scaler.serve_scrape = lambda url: signal
    return scaler, cluster


def test_serving_job_grows_on_breached_slo():
    job = make_serving_job("serve", cur=2)
    scaler, cluster = serving_scaler(job, cur=2, signal=breached_signal())
    target = scaler.step()
    assert target == {"serve": 3}
    assert cluster.get_trainer_parallelism("serve") == 3
    assert job.status.scale_history[-1].reason == "serving-slo"
    # SLO still breached next tick: grows one replica per pass (no jumps)
    assert scaler.step() == {"serve": 4}


def test_serving_job_shrinks_under_comfortable_slo():
    job = make_serving_job("serve", cur=3)
    scaler, cluster = serving_scaler(job, cur=3, signal=comfy_signal())
    assert scaler.step() == {"serve": 2}
    assert cluster.get_trainer_parallelism("serve") == 2


def test_serving_job_holds_without_scrapes():
    """No signals (all replicas unreachable / resolver empty): hold, never
    flap blind — and never fall through to the utilization fixed point,
    which would grow a serving job to fill free chips."""
    job = make_serving_job("serve", cur=2)
    scaler, cluster = serving_scaler(job, cur=2, signal=None)
    scaler.serve_scrape = lambda url: None
    assert scaler.step() == {}
    assert cluster.get_trainer_parallelism("serve") == 2
    # endpoints never registered at all -> same hold
    scaler._serve_endpoints.clear()
    assert scaler.step() == {}


def test_serving_grow_respects_max_and_node_fit():
    # at max_instance: breached SLO cannot push past the ceiling
    job = make_serving_job("serve", max_i=2, cur=2)
    scaler, cluster = serving_scaler(job, cur=2, signal=breached_signal())
    assert scaler.step() == {}
    # chips exhausted: 2-host cluster is full, the grow finds no node
    job2 = make_serving_job("serve2", cur=2)
    scaler2, cluster2 = serving_scaler(job2, cur=2, n_hosts=2,
                                       signal=breached_signal())
    assert scaler2.step() == {}
    assert cluster2.get_trainer_parallelism("serve2") == 2


def test_serving_shrink_respects_min():
    job = make_serving_job("serve", min_i=2, cur=2)
    scaler, cluster = serving_scaler(job, cur=2, signal=comfy_signal())
    assert scaler.step() == {}
    assert cluster.get_trainer_parallelism("serve") == 2


def test_serving_spend_is_visible_to_training_fixed_point():
    """Serving grows FIRST and accounts its chips into the snapshot; the
    training pass then sees one fewer free granule. 5 hosts x 4 chips, 12
    committed: serving 2->3 takes one of the two free granules, so training
    goes 1->2 — without the shared accounting it would have seen both free
    granules and planned 1->3."""
    cluster = FakeCluster(tpu_cluster(n_hosts=5, chips_per_host=4))
    serve = make_serving_job("serve", cur=2)
    train = make_job("train", min_i=1, max_i=10, cur=1).job
    cluster.create_role("serve", "trainer", 2,
                        serve.trainer_request(), serve.trainer_limit())
    cluster.create_role("train", "trainer", 1,
                        train.trainer_request(), train.trainer_limit())
    scaler = Autoscaler(cluster, AutoscalerConfig(loop_seconds=0.01))
    scaler.on_add(serve)
    scaler.on_add(train)
    for _ in range(2):
        scaler._apply_event(scaler._events.get_nowait())
    scaler.register_serving_endpoints("serve", ["http://replica:0"])
    scaler.serve_scrape = lambda url: breached_signal()
    target = scaler.step()
    assert target["serve"] == 3
    assert target["train"] == 2  # not 3: serving's grow ate a granule
    assert cluster.get_trainer_parallelism("serve") == 3
    assert cluster.get_trainer_parallelism("train") == 2


def test_make_room_shrinks_serving_above_floor():
    """A pending training job pulls capacity from a serving job sitting
    above its floor — serving participates in make-room like any elastic
    job (shrink-to-admit does not care what a replica computes)."""
    cluster = FakeCluster(tpu_cluster(n_hosts=4, chips_per_host=4))
    serve = make_serving_job("serve", min_i=1, max_i=4, cur=4)
    cluster.create_role("serve", "trainer", 4,
                        serve.trainer_request(), serve.trainer_limit())
    newbie = make_job("newbie", min_i=1, max_i=4, cur=1).job
    cluster.create_role("newbie", "trainer", 1,
                        newbie.trainer_request(), newbie.trainer_limit())
    assert all(p.phase == "Pending" for p in cluster.job_pods("newbie"))
    scaler = Autoscaler(cluster, AutoscalerConfig(loop_seconds=0.01))
    scaler.on_add(serve)
    scaler.on_add(newbie)
    for _ in range(2):
        scaler._apply_event(scaler._events.get_nowait())
    # no scrape fake: make-room mode never consults the SLO signal
    for _ in range(5):
        scaler.step()
    assert cluster.get_trainer_parallelism("serve") < 4
    assert all(p.phase == "Running" for p in cluster.job_pods("newbie"))
    reasons = {r.reason for r in serve.status.scale_history}
    assert reasons == {"make-room"}
