"""Serving tier: continuous batching, AOT bucket compiles, rolling swap,
HTTP frontend, SLO signal math, coordinator status publication.

The acceptance contract under test (ISSUE 13): every bucket executable is
AOT-compiled before the first request — the jit dispatch cache stays
EMPTY no matter how much traffic flows — and a model-version swap under
traffic drops no in-flight request.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from edl_tpu.models import fit_a_line
from edl_tpu.obs.metrics import MetricsRegistry, parse_prometheus
from edl_tpu.runtime.export import _serving_mesh, save_inference_model
from edl_tpu.serving import (
    ServeCompileError,
    ServeOverloadError,
    ServeSignal,
    ServingConfig,
    ServingReplica,
    ServingSLO,
    aggregate_signals,
    desired_replica_delta,
    histogram_quantile,
    pad_batch,
    pick_bucket,
    plan_chunks,
    split_rows,
    validate_buckets,
)
from edl_tpu.serving.worker import SERVING_KV_PREFIX


def export_fit_a_line(directory, step=100, scale=1.0, versioned=True):
    model = fit_a_line.MODEL
    mesh = _serving_mesh(model)
    params = model.init(jax.random.PRNGKey(0), mesh)
    if scale != 1.0:
        params = jax.tree_util.tree_map(lambda x: x * scale, params)
    save_inference_model(directory, "fit_a_line", params, step=step,
                         versioned=versioned)
    return params


def feature_row(seed=0):
    rng = np.random.default_rng(seed)
    return {"x": rng.standard_normal(13).astype(np.float32)}


@pytest.fixture
def replica_factory(tmp_path):
    """Builds started replicas against a fresh artifact; stops them all."""
    live = []
    export_dir = str(tmp_path / "art")
    export_fit_a_line(export_dir)

    def make(**overrides):
        kwargs = dict(model_dir=export_dir, buckets=(1, 4, 16),
                      max_batch_delay_s=0.002, version_poll_s=0.05)
        kwargs.update(overrides)
        replica = ServingReplica(ServingConfig(**kwargs),
                                 registry=MetricsRegistry())
        live.append(replica)
        return replica.start()

    make.export_dir = export_dir
    yield make
    for replica in live:
        replica.stop()


# -- batcher units -------------------------------------------------------------


def test_validate_buckets_rejects_bad_ladders():
    assert validate_buckets([1, 8, 32]) == (1, 8, 32)
    with pytest.raises(ValueError):
        validate_buckets(())
    with pytest.raises(ValueError):
        validate_buckets((0, 4))
    with pytest.raises(ValueError):
        validate_buckets((4, 4))
    with pytest.raises(ValueError):
        validate_buckets((8, 4))


def test_pick_bucket_smallest_that_fits():
    buckets = (1, 8, 32)
    assert pick_bucket(1, buckets) == 1
    assert pick_bucket(2, buckets) == 8
    assert pick_bucket(8, buckets) == 8
    assert pick_bucket(9, buckets) == 32
    # above the largest bucket: the dispatcher never coalesces past it,
    # but pick_bucket itself clamps rather than raising
    assert pick_bucket(64, buckets) == 32


def test_plan_chunks_covers_any_count():
    # chunk sizes are REQUEST counts (each chunk then pads to its bucket);
    # the sum always equals n — no request left behind
    assert plan_chunks(5, (1, 8, 32)) == [5]
    assert plan_chunks(40, (1, 8, 32)) == [32, 8]
    assert plan_chunks(70, (1, 8, 32)) == [32, 32, 6]
    assert plan_chunks(0, (1, 8, 32)) == []


def test_pad_batch_zero_pads_and_validates():
    avals = {"x": ((13,), np.dtype(np.float32))}
    rows = [feature_row(i) for i in range(3)]
    batch = pad_batch(rows, 8, avals)
    assert batch["x"].shape == (8, 13)
    np.testing.assert_array_equal(batch["x"][3:], 0.0)
    np.testing.assert_array_equal(batch["x"][0], rows[0]["x"])
    with pytest.raises(KeyError):
        pad_batch([{"y": np.zeros(13, np.float32)}], 8, avals)
    with pytest.raises(ValueError):
        pad_batch([{"x": np.zeros(7, np.float32)}], 8, avals)


def test_split_rows_inverts_padding():
    outputs = np.arange(16, dtype=np.float32).reshape(8, 2)
    rows = split_rows(outputs, 3)
    assert len(rows) == 3
    np.testing.assert_array_equal(rows[1], outputs[1])


# -- replica core --------------------------------------------------------------


def test_aot_contract_jit_cache_stays_empty(replica_factory):
    """THE acceptance criterion: all bucket executables compiled before the
    first request; serving any amount of traffic leaves the jit dispatch
    cache at zero entries (Compiled objects are dispatched directly)."""
    replica = replica_factory()
    assert replica.jit_cache_size() == 0
    results = [replica.predict(feature_row(i)) for i in range(10)]
    futs = [replica.submit(feature_row(i)) for i in range(20)]
    for f in futs:
        f.result(timeout=10)
    assert len(results) == 10
    assert replica.jit_cache_size() == 0
    # every bucket was compiled up front (compile gauge set per bucket)
    text = replica.registry.render_prometheus()
    for bucket in (1, 4, 16):
        assert f'edl_serve_compile_seconds{{bucket="{bucket}"}}' in text


def test_incompatible_bucket_fails_fast_at_startup(tmp_path):
    """The flip side of the AOT contract: a bucket the model's sharding
    can't compile (ctr's shard_map'd lookup needs batch % data-axis == 0,
    and the serving mesh has data=8) fails `start()` with a serving-level
    error naming the bucket — never a request-path surprise."""
    from edl_tpu.models import ctr

    model = ctr.make_model(sparse_dim=512)
    mesh = _serving_mesh(model)
    if dict(zip(mesh.axis_names, mesh.devices.shape)).get("data", 1) == 1:
        pytest.skip("needs a multi-device data axis to hit divisibility")
    d = str(tmp_path / "ctrart")
    save_inference_model(d, "ctr", model.init(jax.random.PRNGKey(0), mesh),
                         config={"sparse_dim": 512}, step=1, versioned=True)
    replica = ServingReplica(ServingConfig(model_dir=d, buckets=(1,),
                                           name="bad-bucket"))
    with pytest.raises(ServeCompileError, match="bucket 1"):
        replica.start()
    replica.stop()


def test_predictions_match_direct_model(replica_factory, tmp_path):
    from edl_tpu.runtime import load_inference_model

    replica = replica_factory()
    art = load_inference_model(replica_factory.export_dir)
    rows = [feature_row(i) for i in range(7)]
    served = [np.asarray(replica.predict(r)) for r in rows]
    direct = np.asarray(art.predict(
        {"x": np.stack([r["x"] for r in rows])}
    ))
    np.testing.assert_allclose(np.stack(served).ravel(), direct.ravel(),
                               rtol=1e-5, atol=1e-6)


def test_concurrent_submit_correct_per_request_rows(replica_factory):
    """64 threads race submit; every caller gets exactly its own row back
    (the scatter half of batching must not permute results)."""
    from edl_tpu.runtime import load_inference_model

    replica = replica_factory()
    art = load_inference_model(replica_factory.export_dir)
    rows = [feature_row(i) for i in range(64)]
    expected = np.asarray(art.predict(
        {"x": np.stack([r["x"] for r in rows])}
    )).reshape(64, -1)
    results = [None] * 64
    errors = []

    def call(i):
        try:
            results[i] = np.asarray(replica.predict(rows[i]))
        except Exception as e:  # pragma: no cover - surfaced via assert
            errors.append(e)

    threads = [threading.Thread(target=call, args=(i,)) for i in range(64)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    for i in range(64):
        np.testing.assert_allclose(np.asarray(results[i]).ravel(),
                                   expected[i].ravel(), rtol=1e-5, atol=1e-6)
    status = replica.status()
    assert status["completed"] == 64
    assert status["errors"] == 0
    # coalescing actually happened: fewer batches than requests
    assert sum(status["bucket_hits"].values()) < 64


def test_rejects_malformed_features(replica_factory):
    replica = replica_factory()
    with pytest.raises(KeyError):
        replica.submit({"nope": np.zeros(13, np.float32)})
    with pytest.raises(ValueError):
        replica.submit({"x": np.zeros(7, np.float32)})
    with pytest.raises(TypeError):
        replica.submit([1, 2, 3])
    # malformed requests are rejected synchronously, before the queue —
    # they never poison a batch that carries other callers' requests
    assert replica.predict(feature_row()) is not None


def test_overload_rejects_synchronously(tmp_path):
    export_dir = str(tmp_path / "art")
    export_fit_a_line(export_dir)
    replica = ServingReplica(
        ServingConfig(model_dir=export_dir, buckets=(1,), queue_capacity=2),
        registry=MetricsRegistry(),
    )
    # not started: dispatcher isn't draining, so the queue fills
    replica._started = True
    replica._feature_avals = {"x": ((13,), np.dtype(np.float32))}
    replica.submit(feature_row(0))
    replica.submit(feature_row(1))
    with pytest.raises(ServeOverloadError):
        replica.submit(feature_row(2))
    assert replica.status()["rejected"] == 1


def test_stop_drains_accepted_requests(replica_factory):
    """The zero-drop half of scale-down: stop(drain=True) serves every
    already-accepted request before the dispatch thread exits."""
    replica = replica_factory(max_batch_delay_s=0.0)
    futs = [replica.submit(feature_row(i)) for i in range(32)]
    replica.stop(drain=True)
    for f in futs:
        assert f.result(timeout=1) is not None  # already resolved
    assert replica.status()["completed"] == 32


def test_stop_without_drain_fails_queued(tmp_path):
    export_dir = str(tmp_path / "art")
    export_fit_a_line(export_dir)
    replica = ServingReplica(
        ServingConfig(model_dir=export_dir, buckets=(1,), queue_capacity=64),
        registry=MetricsRegistry(),
    )
    replica._started = True
    replica._feature_avals = {"x": ((13,), np.dtype(np.float32))}
    futs = [replica.submit(feature_row(i)) for i in range(4)]
    replica.stop(drain=False)
    for f in futs:
        with pytest.raises(RuntimeError):
            f.result(timeout=1)


def test_rolling_swap_under_traffic_drops_nothing(replica_factory):
    """Publish a new artifact version while requests flow: the watcher
    swaps params between batches; every in-flight request resolves, and
    post-swap predictions use the new weights."""
    replica = replica_factory()
    stop = threading.Event()
    failures = []
    served = [0]

    def traffic():
        i = 0
        while not stop.is_set():
            try:
                replica.predict(feature_row(i % 8))
                served[0] += 1
            except Exception as e:  # pragma: no cover - surfaced via assert
                failures.append(e)
                return
            i += 1

    t = threading.Thread(target=traffic)
    t.start()
    time.sleep(0.2)
    export_fit_a_line(replica_factory.export_dir, step=200, scale=2.0)
    deadline = time.monotonic() + 10
    while replica.status()["model_step"] != 200:
        assert time.monotonic() < deadline, "swap never landed"
        time.sleep(0.02)
    time.sleep(0.2)  # keep traffic flowing on the new version
    stop.set()
    t.join(timeout=10)
    assert not failures
    assert served[0] > 0
    status = replica.status()
    assert status["errors"] == 0
    assert status["swaps"] == 1
    assert status["last_swap_step"] == 200
    assert replica.jit_cache_size() == 0  # swap kept the AOT contract
    # doubled params -> doubled prediction
    row = feature_row(99)
    doubled = np.asarray(replica.predict(row))
    from edl_tpu.runtime import load_inference_model

    art = load_inference_model(replica_factory.export_dir)
    expected = np.asarray(art.predict({"x": row["x"][None]}))
    np.testing.assert_allclose(doubled.ravel(), expected.ravel(),
                               rtol=1e-5, atol=1e-6)


def test_stale_version_is_not_reswapped(replica_factory):
    replica = replica_factory()
    before = replica.status()
    time.sleep(0.3)  # several poll periods with nothing new published
    after = replica.status()
    assert after["swaps"] == before["swaps"] == 0
    assert after["version"] == before["version"]


# -- HTTP frontend -------------------------------------------------------------


def http_post(url, payload, timeout=10):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def test_http_predict_single_and_batch(replica_factory):
    replica = replica_factory(port=0)
    url = replica.url + "/predict"
    single = http_post(url, {"features": {"x": feature_row()["x"].tolist()}})
    assert isinstance(single["outputs"], list)  # one row, unwrapped
    assert single["model_step"] == 100
    assert single["version"].startswith("v")
    rows = [{"x": feature_row(i)["x"].tolist()} for i in range(5)]
    multi = http_post(url, {"features": rows})
    assert len(multi["outputs"]) == 5


def test_http_error_codes(replica_factory):
    replica = replica_factory(port=0)
    url = replica.url + "/predict"
    with pytest.raises(urllib.error.HTTPError) as e:
        http_post(url, {"features": {"x": [1.0, 2.0]}})  # bad shape
    assert e.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as e:
        http_post(url, {"nope": 1})
    assert e.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as e:
        http_post(replica.url + "/elsewhere", {"features": {}})
    assert e.value.code == 404


def test_http_metrics_and_health_share_the_port(replica_factory):
    replica = replica_factory(port=0)
    http_post(replica.url + "/predict",
              {"features": {"x": feature_row()["x"].tolist()}})
    with urllib.request.urlopen(replica.url + "/metrics", timeout=5) as r:
        families = parse_prometheus(r.read().decode())
    for family in ("edl_serve_request_latency_seconds",
                   "edl_serve_queue_depth",
                   "edl_serve_requests_total",
                   "edl_serve_batches_total",
                   "edl_serve_model_step"):
        assert family in families, family
    with urllib.request.urlopen(replica.url + "/healthz", timeout=5) as r:
        health = json.loads(r.read())
    assert health["completed"] >= 1


# -- autoscaler signal math ----------------------------------------------------


def test_histogram_quantile_interpolates():
    buckets = [(0.1, 50.0), (0.5, 90.0), (1.0, 100.0), (float("inf"), 100.0)]
    assert histogram_quantile(buckets, 0.5) == 0.1
    # p90 lands exactly at the 0.5 bound
    assert histogram_quantile(buckets, 0.9) == pytest.approx(0.5)
    # p95: halfway through the (0.5, 1.0] bucket
    assert histogram_quantile(buckets, 0.95) == pytest.approx(0.75)
    assert histogram_quantile([], 0.99) is None
    assert histogram_quantile([(0.1, 0.0), (float("inf"), 0.0)], 0.5) is None
    # mass in the +inf bucket clamps to the last finite bound
    assert histogram_quantile(
        [(0.1, 0.0), (float("inf"), 10.0)], 0.99
    ) == pytest.approx(0.1)


def sig(p99_bound, count=100.0, queue=0.0):
    """Signal whose whole mass sits below ``p99_bound``."""
    return ServeSignal(
        latency_buckets=[(p99_bound, count), (float("inf"), count)],
        latency_count=count, queue_depth=queue,
    )


def test_desired_delta_grows_on_breach_and_shrinks_with_hysteresis():
    slo = ServingSLO(p99_seconds=0.25, max_queue_per_replica=8.0)
    assert desired_replica_delta([], slo) == 0  # no scrapes: hold
    assert desired_replica_delta([sig(1.0)], slo) == 1  # p99 breach
    assert desired_replica_delta([sig(0.01, queue=50.0)], slo) == 1
    assert desired_replica_delta([sig(0.01, queue=0.0)], slo) == -1
    # comfortable p99 but queue above the shrink band: hold (hysteresis)
    assert desired_replica_delta([sig(0.01, queue=4.0)], slo) == 0
    # p99 in the dead band between shrink and grow thresholds: hold
    assert desired_replica_delta([sig(0.2)], slo) == 0


def test_aggregate_sums_buckets_across_replicas():
    """One drowning replica must dominate the tier p99, not be averaged
    away by idle peers."""
    idle = sig(0.01, count=10.0)
    drowning = ServeSignal(
        latency_buckets=[(0.01, 0.0), (5.0, 1000.0), (float("inf"), 1000.0)],
        latency_count=1000.0, queue_depth=100.0,
    )
    p99, queue = aggregate_signals([idle, drowning])
    assert p99 > 1.0
    assert queue == pytest.approx(50.0)
    slo = ServingSLO()
    assert desired_replica_delta([idle, drowning], slo) == 1


def test_scrape_serve_signal_end_to_end(replica_factory):
    from edl_tpu.serving import scrape_serve_signal

    replica = replica_factory(port=0)
    for i in range(6):
        replica.predict(feature_row(i))
    signal = scrape_serve_signal(replica.url)
    assert signal is not None
    assert signal.latency_count >= 6
    assert signal.latency_buckets[-1][0] == float("inf")
    # unreachable replica -> None, never an exception
    assert scrape_serve_signal("http://127.0.0.1:1/metrics") is None


# -- coordinator status publication + CLI --------------------------------------


def test_replica_publishes_status_to_coordinator_kv(tmp_path):
    from edl_tpu.coordinator.inprocess import InProcessCoordinator

    export_dir = str(tmp_path / "art")
    export_fit_a_line(export_dir)
    coord = InProcessCoordinator(heartbeat_ttl_sec=300.0)
    client = coord.client("serve-a")
    replica = ServingReplica(
        ServingConfig(model_dir=export_dir, buckets=(1, 4),
                      name="serve-a", version_poll_s=0.05,
                      publish_interval_s=0.0),
        client=client, registry=MetricsRegistry(),
    )
    replica.start()
    try:
        replica.predict(feature_row())
        deadline = time.monotonic() + 5
        raw = None
        while time.monotonic() < deadline:
            raw = client.kv_get(SERVING_KV_PREFIX + "serve-a")
            if raw and json.loads(raw).get("completed", 0) >= 1:
                break
            time.sleep(0.05)
        status = json.loads(raw)
        assert status["completed"] >= 1
        assert status["model_step"] == 100
        assert "serve-a" in client.members()
    finally:
        replica.stop()


def test_cli_status_renders_serving_section(tmp_path, capsys):
    from edl_tpu.cli import main as cli_main
    from edl_tpu.coordinator.inprocess import InProcessCoordinator
    from edl_tpu.coordinator.server import CoordinatorServer

    export_dir = str(tmp_path / "art")
    export_fit_a_line(export_dir)
    server = CoordinatorServer(port=0)
    server.start()
    try:
        from edl_tpu.coordinator.client import CoordinatorClient

        client = CoordinatorClient("127.0.0.1", server.port, worker="serve-b")
        replica = ServingReplica(
            ServingConfig(model_dir=export_dir, buckets=(1,),
                          name="serve-b", publish_interval_s=0.0),
            client=client, registry=MetricsRegistry(),
        )
        replica.start()
        try:
            replica.predict(feature_row())
            replica._publish_status(force=True)
            rc = cli_main(["status", "--host", "127.0.0.1",
                           "--port", str(server.port), "--json"])
            out = capsys.readouterr().out
            assert rc == 0
            payload = json.loads(out)
            serving = payload.get("serving") or {}
            assert "serve-b" in serving
            assert serving["serve-b"]["completed"] >= 1
        finally:
            replica.stop()
    finally:
        server.stop()
