"""Elasticity tests: cross-mesh checkpoint restore and the full rescale loop.

The single-host stand-in for the v5e-4 <-> v5e-16 story (BASELINE.md): a
worker trains on a 4-device mesh; a membership change arrives; it checkpoints,
rebuilds an 8-device mesh, restores (orbax reshards row-sharded tables on
load), and resumes from the leased shard queue with deterministic replay.
"""

import threading
import time

import jax
import numpy as np
import pytest

from edl_tpu.coordinator import InProcessCoordinator
from edl_tpu.models import ctr, fit_a_line
from edl_tpu.parallel import MeshSpec, build_mesh
from edl_tpu.runtime import Trainer, TrainerConfig
from edl_tpu.runtime.checkpoint import Checkpointer, abstract_like, live_state_specs
from edl_tpu.runtime.data import LeaseReader, SyntheticShardSource, shard_names
from edl_tpu.runtime.elastic import ElasticConfig, ElasticWorker


def small_ctr():
    return ctr.make_model(sparse_dim=4099)


def test_checkpoint_roundtrip_same_mesh(tmp_path):
    mesh = build_mesh(MeshSpec({"data": 8}))
    model = small_ctr()
    trainer = Trainer(model, mesh, TrainerConfig(optimizer="adagrad"))
    state = trainer.init_state()
    rng = np.random.default_rng(0)
    state, _ = trainer.train_step(state, trainer.place_batch(model.synthetic_batch(rng, 16)))

    ckpt = Checkpointer(str(tmp_path / "ck"))
    ckpt.save(int(state.step), state)
    ckpt.wait()

    restored = ckpt.restore(abstract_like(state), mesh, live_state_specs(state))
    for a, b in zip(jax.tree_util.tree_leaves(state), jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    ckpt.close()


def test_checkpoint_restores_across_mesh_sizes(tmp_path):
    """Save on 4 devices, restore on 8: shapes identical, shardings rebuilt."""
    model = small_ctr()
    mesh4 = build_mesh(MeshSpec({"data": 4}), jax.devices()[:4])
    tr4 = Trainer(model, mesh4, TrainerConfig(optimizer="adagrad"))
    state4 = tr4.init_state()
    rng = np.random.default_rng(1)
    for _ in range(3):
        state4, _ = tr4.train_step(state4, tr4.place_batch(model.synthetic_batch(rng, 16)))
    ckpt = Checkpointer(str(tmp_path / "ck"))
    ckpt.save(int(state4.step), state4)
    ckpt.wait()

    mesh8 = build_mesh(MeshSpec({"data": 8}))
    tr8 = Trainer(model, mesh8, TrainerConfig(optimizer="adagrad"))
    fresh8 = tr8.init_state()
    state8 = ckpt.restore(abstract_like(fresh8), mesh8, live_state_specs(fresh8))

    assert int(state8.step) == 3
    # table content identical, now split over 8 shards
    np.testing.assert_array_equal(
        np.asarray(state4.params["deep_table"]), np.asarray(state8.params["deep_table"])
    )
    # and the restored state can take a step on the new mesh
    state8, loss = tr8.train_step(state8, tr8.place_batch(model.synthetic_batch(rng, 16)))
    assert np.isfinite(float(loss))
    ckpt.close()


def test_lease_reader_replay_determinism():
    coord = InProcessCoordinator(task_lease_sec=30.0)
    c1 = coord.client("r1")
    c1.register()
    c1.add_tasks(shard_names("train", 2))
    model = fit_a_line.MODEL
    source = SyntheticShardSource(model, batch_size=8, batches_per_shard=3)

    # interrupt after 2 batches
    count = [0]
    reader = LeaseReader(c1, source, stop_check=lambda: count[0] >= 2)
    got1 = []
    for batch in reader:
        got1.append(batch["x"].copy())
        count[0] += 1
    assert reader.interrupted == "train/part-00000"

    # replay: the failed shard requeued to the BACK, so reader2 sees
    # part-00001's 3 batches first, then part-00000's identical replay.
    reader2 = LeaseReader(c1, source)
    got2 = [b["x"].copy() for b in reader2]
    assert reader2.exhausted
    assert set(reader2.completed) == set(shard_names("train", 2))
    assert len(got2) == 6
    np.testing.assert_array_equal(got1[0], got2[3])
    np.testing.assert_array_equal(got1[1], got2[4])


def test_elastic_worker_rescales_4_to_8(tmp_path):
    """The headline e2e: train at world=1 (4 devs), a second trainer joins,
    worker rescales to 8 devs, finishes the queue; loss keeps descending and
    recovery time is recorded."""
    coord = InProcessCoordinator(task_lease_sec=60.0, heartbeat_ttl_sec=60.0)
    model = fit_a_line.MODEL
    admin = coord.client("admin")
    admin.add_tasks(shard_names("fit", 6))

    worker_client = coord.client("trainer-0")
    source = SyntheticShardSource(model, batch_size=32, batches_per_shard=8)
    cfg = ElasticConfig(
        checkpoint_dir=str(tmp_path / "ck"),
        checkpoint_interval=5,
        heartbeat_interval=0.0,  # check epoch every batch
        rescale_barrier_timeout=30.0,
        trainer=TrainerConfig(optimizer="sgd", learning_rate=0.05),
    )
    worker = ElasticWorker(model, worker_client, source, cfg)

    # Second "trainer" joins shortly after training starts and follows the
    # rendezvous protocol (register -> sync at the observed epoch, resyncing
    # as instructed) — in the single-host sim its chips show up as the extra
    # local devices the planner grants at world=2.
    def joiner():
        # Join once training has made real progress (wall-clock sleeps flake
        # on loaded single-core runners: the queue can drain before 1 s).
        while worker.steps_done < 5 and not stop_flag.is_set():
            time.sleep(0.05)
        c = coord.client("trainer-1")
        info = c.register()
        epoch = info["epoch"]
        while not stop_flag.is_set():
            reply = c.sync(epoch, timeout=5.0)
            if reply.get("ok"):
                break
            epoch = reply.get("epoch", epoch)
        while not stop_flag.is_set():
            hb = c.heartbeat()
            if hb.get("ok") and hb["epoch"] != epoch:
                epoch = hb["epoch"]
                c.sync(epoch, timeout=5.0)
            time.sleep(0.3)

    stop_flag = threading.Event()
    t = threading.Thread(target=joiner, daemon=True)
    t.start()
    try:
        metrics = worker.run()
    finally:
        stop_flag.set()
        t.join(timeout=5)

    assert metrics["rescales"] >= 1, metrics
    assert worker.rescales[0].from_world == 1
    assert worker.rescales[0].to_world == 2
    assert metrics["max_recovery_seconds"] < 30.0, metrics
    # all shards completed exactly once overall (replays allowed, but the
    # queue drains and nothing is lost)
    st = admin.status()
    assert st["done"] == 6 and st["queued"] == 0 and st["leased"] == 0
    # the model actually learned through the rescale
    assert metrics["final_loss"] < 0.1, metrics
