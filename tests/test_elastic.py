"""Elasticity tests: cross-mesh checkpoint restore and the full rescale loop.

The single-host stand-in for the v5e-4 <-> v5e-16 story (BASELINE.md): a
worker trains on a 4-device mesh; a membership change arrives; it checkpoints,
rebuilds an 8-device mesh, restores (orbax reshards row-sharded tables on
load), and resumes from the leased shard queue with deterministic replay.
"""

import json
import threading
import time

import jax
import numpy as np
import pytest

from edl_tpu.coordinator import InProcessCoordinator
from edl_tpu.models import ctr, fit_a_line
from edl_tpu.parallel import MeshSpec, build_mesh
from edl_tpu.runtime import Trainer, TrainerConfig
from edl_tpu.runtime.checkpoint import Checkpointer, abstract_like, live_state_specs
from edl_tpu.runtime.data import LeaseReader, SyntheticShardSource, shard_names
from edl_tpu.runtime.elastic import ElasticConfig, ElasticWorker


def small_ctr():
    return ctr.make_model(sparse_dim=4099)


def test_checkpoint_roundtrip_same_mesh(tmp_path):
    mesh = build_mesh(MeshSpec({"data": 8}))
    model = small_ctr()
    trainer = Trainer(model, mesh, TrainerConfig(optimizer="adagrad"))
    state = trainer.init_state()
    rng = np.random.default_rng(0)
    state, _ = trainer.train_step(state, trainer.place_batch(model.synthetic_batch(rng, 16)))

    ckpt = Checkpointer(str(tmp_path / "ck"))
    ckpt.save(int(state.step), state)
    ckpt.wait()

    restored = ckpt.restore(abstract_like(state), mesh, live_state_specs(state))
    for a, b in zip(jax.tree_util.tree_leaves(state), jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    ckpt.close()


def test_checkpoint_restores_across_mesh_sizes(tmp_path):
    """Save on 4 devices, restore on 8: shapes identical, shardings rebuilt."""
    model = small_ctr()
    mesh4 = build_mesh(MeshSpec({"data": 4}), jax.devices()[:4])
    tr4 = Trainer(model, mesh4, TrainerConfig(optimizer="adagrad"))
    state4 = tr4.init_state()
    rng = np.random.default_rng(1)
    for _ in range(3):
        state4, _ = tr4.train_step(state4, tr4.place_batch(model.synthetic_batch(rng, 16)))
    ckpt = Checkpointer(str(tmp_path / "ck"))
    ckpt.save(int(state4.step), state4)
    ckpt.wait()

    mesh8 = build_mesh(MeshSpec({"data": 8}))
    tr8 = Trainer(model, mesh8, TrainerConfig(optimizer="adagrad"))
    fresh8 = tr8.init_state()
    state8 = ckpt.restore(abstract_like(fresh8), mesh8, live_state_specs(fresh8))

    assert int(state8.step) == 3
    # table content identical, now split over 8 shards
    np.testing.assert_array_equal(
        np.asarray(state4.params["deep_table"]), np.asarray(state8.params["deep_table"])
    )
    # and the restored state can take a step on the new mesh
    state8, loss = tr8.train_step(state8, tr8.place_batch(model.synthetic_batch(rng, 16)))
    assert np.isfinite(float(loss))
    ckpt.close()


def test_lease_reader_replay_determinism():
    coord = InProcessCoordinator(task_lease_sec=30.0)
    c1 = coord.client("r1")
    c1.register()
    c1.add_tasks(shard_names("train", 2))
    model = fit_a_line.MODEL
    source = SyntheticShardSource(model, batch_size=8, batches_per_shard=3)

    # interrupt after 2 batches
    count = [0]
    reader = LeaseReader(c1, source, stop_check=lambda: count[0] >= 2)
    got1 = []
    for batch in reader:
        got1.append(batch["x"].copy())
        count[0] += 1
    assert reader.interrupted == "train/part-00000"

    # replay: the failed shard requeued to the BACK, so reader2 sees
    # part-00001's 3 batches first, then part-00000's identical replay.
    reader2 = LeaseReader(c1, source)
    got2 = [b["x"].copy() for b in reader2]
    assert reader2.exhausted
    assert set(reader2.completed) == set(shard_names("train", 2))
    assert len(got2) == 6
    np.testing.assert_array_equal(got1[0], got2[3])
    np.testing.assert_array_equal(got1[1], got2[4])


def test_elastic_worker_rescales_4_to_8(tmp_path):
    """The headline e2e: train at world=1 (4 devs), a second trainer joins,
    worker rescales to 8 devs, finishes the queue; loss keeps descending and
    recovery time is recorded."""
    coord = InProcessCoordinator(task_lease_sec=60.0, heartbeat_ttl_sec=60.0)
    model = fit_a_line.MODEL
    admin = coord.client("admin")
    admin.add_tasks(shard_names("fit", 6))

    worker_client = coord.client("trainer-0")
    source = SyntheticShardSource(model, batch_size=32, batches_per_shard=8)
    cfg = ElasticConfig(
        checkpoint_dir=str(tmp_path / "ck"),
        checkpoint_interval=5,
        heartbeat_interval=0.0,  # check epoch every batch
        rescale_barrier_timeout=30.0,
        trainer=TrainerConfig(optimizer="sgd", learning_rate=0.05),
    )
    worker = ElasticWorker(model, worker_client, source, cfg)

    # Second "trainer" joins shortly after training starts and follows the
    # rendezvous protocol (register -> sync at the observed epoch, resyncing
    # as instructed) — in the single-host sim its chips show up as the extra
    # local devices the planner grants at world=2.
    def joiner():
        # Join once training has made real progress (wall-clock sleeps flake
        # on loaded single-core runners: the queue can drain before 1 s).
        while worker.steps_done < 5 and not stop_flag.is_set():
            time.sleep(0.05)
        c = coord.client("trainer-1")
        info = c.register()
        epoch = info["epoch"]
        while not stop_flag.is_set():
            reply = c.sync(epoch, timeout=5.0)
            if reply.get("ok"):
                break
            epoch = reply.get("epoch", epoch)
        while not stop_flag.is_set():
            hb = c.heartbeat()
            if hb.get("ok") and hb["epoch"] != epoch:
                epoch = hb["epoch"]
                c.sync(epoch, timeout=5.0)
            time.sleep(0.3)

    stop_flag = threading.Event()
    t = threading.Thread(target=joiner, daemon=True)
    t.start()
    try:
        metrics = worker.run()
    finally:
        stop_flag.set()
        t.join(timeout=5)

    assert metrics["rescales"] >= 1, metrics
    assert worker.rescales[0].from_world == 1
    assert worker.rescales[0].to_world == 2
    assert metrics["max_recovery_seconds"] < 30.0, metrics
    # the new-mesh executable was AOT-compiled during the drain window
    assert worker.rescales[0].compile_seconds > 0.0, worker.rescales
    # all shards completed exactly once overall (replays allowed, but the
    # queue drains and nothing is lost)
    st = admin.status()
    assert st["done"] == 6 and st["queued"] == 0 and st["leased"] == 0
    # the model actually learned through the rescale
    assert metrics["final_loss"] < 0.1, metrics


# -- completion lag: at-least-once across hard crashes (VERDICT r3 item 5) -----


def test_lease_reader_defer_completion_holds_leases():
    """defer_completion moves fully-read shards to `consumed` with leases
    still held; completion happens only when the caller commits them after a
    covering checkpoint."""
    coord = InProcessCoordinator(task_lease_sec=30.0)
    c = coord.client("r1")
    c.register()
    c.add_tasks(shard_names("lag", 2))
    source = SyntheticShardSource(fit_a_line.MODEL, batch_size=8, batches_per_shard=2)

    reader = LeaseReader(c, source, defer_completion=True)
    batches = list(reader)
    assert len(batches) == 4
    st = c.status()
    # nothing completed yet: a crash here must replay BOTH shards
    assert st["done"] == 0 and st["leased"] == 2
    held = reader.take_consumed()
    assert set(held) == set(shard_names("lag", 2))
    assert reader.take_consumed() == []  # drained
    for t in held:  # "checkpoint covered them" -> commit
        c.complete_task(t)
    st = c.status()
    assert st["done"] == 2 and st["leased"] == 0
    # queue drains only after the held leases commit
    reader2 = LeaseReader(c, source, defer_completion=True)
    assert list(reader2) == [] and reader2.exhausted


def test_lease_reader_prefetch_matches_sync():
    """The prefetch pipeline must yield exactly the sync reader's batches
    (same shards, same order, bit-identical data) while loading the next
    shard off-thread."""
    coord = InProcessCoordinator(task_lease_sec=30.0)
    model = fit_a_line.MODEL
    source = SyntheticShardSource(model, batch_size=8, batches_per_shard=3)

    c1 = coord.client("sync")
    c1.register()
    c1.add_tasks(shard_names("pf", 3))
    sync_batches = [b["x"].copy() for b in LeaseReader(c1, source)]

    coord2 = InProcessCoordinator(task_lease_sec=30.0)
    c2 = coord2.client("pre")
    c2.register()
    c2.add_tasks(shard_names("pf", 3))
    reader = LeaseReader(c2, source, prefetch=True)
    pre_batches = [b["x"].copy() for b in reader]
    assert reader.exhausted
    assert set(reader.completed) == set(shard_names("pf", 3))
    assert len(pre_batches) == len(sync_batches) == 9
    for a, b in zip(sync_batches, pre_batches):
        np.testing.assert_array_equal(a, b)


def test_lease_reader_prefetch_interrupt_fails_both_leases():
    """A rescale mid-shard under prefetch must fail BOTH held leases (current
    and prefetched) back to the queue — no lease may leak to expiry."""
    coord = InProcessCoordinator(task_lease_sec=30.0)
    c = coord.client("r")
    c.register()
    c.add_tasks(shard_names("int", 3))
    source = SyntheticShardSource(fit_a_line.MODEL, batch_size=8, batches_per_shard=3)
    count = [0]
    reader = LeaseReader(c, source, prefetch=True,
                         stop_check=lambda: count[0] >= 2)
    got = []
    for b in reader:
        got.append(b)
        count[0] += 1
    assert reader.interrupted is not None
    st = c.status()
    assert st["leased"] == 0, st  # both leases handed back immediately
    assert st["queued"] + st["done"] == 3


WORKER_CRASH_SRC = """
import json, os, sys
sys.path.insert(0, {repo!r})
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import time

from edl_tpu.coordinator.client import CoordinatorClient
from edl_tpu.models import fit_a_line
from edl_tpu.runtime import ElasticConfig, ElasticWorker, SyntheticShardSource
from edl_tpu.runtime.train_loop import TrainerConfig


class SlowSource(SyntheticShardSource):
    def read(self, shard):
        for b in super().read(shard):
            time.sleep(0.05)  # give the parent a window to SIGKILL mid-run
            yield b


client = CoordinatorClient(port=int(os.environ["PORT"]), worker=os.environ["NAME"])
source = SlowSource(fit_a_line.MODEL, batch_size=8, batches_per_shard=6)
cfg = ElasticConfig(
    checkpoint_dir=os.environ["CKPT"],
    checkpoint_interval=6,          # ~one shard per checkpoint
    heartbeat_interval=0.0,
    trainer=TrainerConfig(optimizer="sgd", learning_rate=0.05),
)
worker = ElasticWorker(fit_a_line.MODEL, client, source, cfg,
                       device_planner=lambda w: jax.devices())
metrics = worker.run()
print("METRICS " + json.dumps(metrics))
"""


def test_kill9_replays_exactly_uncommitted_shards(tmp_path):
    """Hard-crash a single-host elastic worker mid-run (SIGKILL — no cleanup
    path) and restart: completed shards are NOT retrained (their covering
    checkpoint restored) and every non-completed shard replays. This is the
    at-least-once guarantee immediate completion lacked (VERDICT r3 item 5;
    ref model: the master re-leases timed-out tasks, docker/paddle_k8s:30).
    """
    import os
    import subprocess
    import sys

    from edl_tpu.coordinator import CoordinatorServer

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    n_shards, batches_per_shard = 6, 6
    with CoordinatorServer(task_lease_sec=60.0, heartbeat_ttl_sec=60.0) as server:
        admin = server.client("admin")
        admin.add_tasks(shard_names("crash", n_shards))

        def spawn(name):
            env = dict(os.environ)
            env.update(PORT=str(server.port), NAME=name,
                       CKPT=str(tmp_path / "ck"))
            return subprocess.Popen(
                [sys.executable, "-c", WORKER_CRASH_SRC.format(repo=repo)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            )

        p1 = spawn("w0")
        deadline = time.time() + 240
        while time.time() < deadline:
            if int(admin.status().get("done", 0)) >= 2:
                break
            if p1.poll() is not None:
                out, err = p1.communicate()
                pytest.fail(f"worker finished before kill:\n{err[-2000:]}")
            time.sleep(0.02)
        else:
            pytest.fail("worker never committed 2 shards")
        p1.kill()  # SIGKILL: no atexit, no finally, leases left dangling
        p1.wait()

        done_at_kill = int(admin.status()["done"])
        # the dead worker's leases requeue (here: explicit leave in lieu of
        # waiting out the heartbeat TTL)
        server.client("w0").leave()

        p2 = spawn("w1")
        out, err = p2.communicate(timeout=240)
        assert p2.returncode == 0, f"restarted worker failed:\n{err[-3000:]}"
        line = [l for l in out.splitlines() if l.startswith("METRICS ")][0]
        metrics = json.loads(line[len("METRICS "):])

        st = admin.status()
    assert int(st["done"]) == n_shards and int(st["queued"]) == 0
    # Replay EXACTLY the shards no completion covered: each non-done shard
    # contributes its full batch count to the restarted worker, no more.
    expected_replay_steps = (n_shards - done_at_kill) * batches_per_shard
    assert metrics["steps"] == float(expected_replay_steps), (
        metrics, done_at_kill,
    )


def test_elastic_worker_wire_overflow_exits_for_warm_restart(tmp_path, monkeypatch):
    """A WireRestartRequired surfacing mid-run (multi-process codec overflow)
    must take the gang warm-restart exit (RESCALE_EXIT_CODE) after flushing
    durable state — not crash with a generic failure that burns the job's
    failure budget."""
    from edl_tpu.launcher.launch import RESCALE_EXIT_CODE
    from edl_tpu.runtime import SyntheticShardSource
    from edl_tpu.runtime.wire import WireRestartRequired

    coord = InProcessCoordinator(task_lease_sec=60.0, heartbeat_ttl_sec=60.0)
    client = coord.client("w0")
    client.register()
    client.add_tasks(shard_names("ov", 2))
    model = fit_a_line.MODEL
    worker = ElasticWorker(
        model, client,
        SyntheticShardSource(model, batch_size=8, batches_per_shard=2),
        ElasticConfig(checkpoint_dir=str(tmp_path / "ck"),
                      trainer=TrainerConfig(optimizer="sgd")),
        device_planner=lambda w: jax.devices(),
    )

    orig = Trainer.place_batch
    calls = [0]

    def overflow_on_third(self, batch):
        calls[0] += 1
        if calls[0] == 3:  # mid-second-shard: consumed + in-flight state
            raise WireRestartRequired("sparse")
        return orig(self, batch)

    monkeypatch.setattr(Trainer, "place_batch", overflow_on_third)
    with pytest.raises(SystemExit) as ei:
        worker.run()
    assert ei.value.code == RESCALE_EXIT_CODE
    # durable flush happened: the fully-consumed first shard committed
    st = client.status()
    assert int(st["done"]) == 1, st


def test_zero1_checkpoint_restores_across_mesh_sizes(tmp_path):
    """ZeRO-1 moments (data-axis sharded) must survive the rescale path:
    save on a 4-device mesh, restore on 8 — orbax reshards into the NEW
    mesh's ZeRO layout (live_state_specs of a fresh init carries it), and
    training resumes."""
    from jax.sharding import NamedSharding

    model = small_ctr()
    cfg = TrainerConfig(optimizer="adam", shard_opt_state=True)
    rng = np.random.default_rng(3)

    mesh4 = build_mesh(MeshSpec({"data": 4}), jax.devices()[:4])
    tr4 = Trainer(model, mesh4, cfg)
    state4 = tr4.init_state()
    for _ in range(2):
        state4, _ = tr4.train_step(
            state4, tr4.place_batch(model.synthetic_batch(rng, 16))
        )
    ckpt = Checkpointer(str(tmp_path / "ck"))
    ckpt.save(int(state4.step), state4)
    ckpt.wait()

    mesh8 = build_mesh(MeshSpec({"data": 8}))
    tr8 = Trainer(model, mesh8, cfg)
    fresh8 = tr8.init_state()
    state8 = ckpt.restore(abstract_like(fresh8), mesh8, live_state_specs(fresh8))
    assert int(state8.step) == 2

    # restored moments carry the 8-way ZeRO layout
    sharded = [
        leaf for leaf in jax.tree_util.tree_leaves(state8.opt_state)
        if isinstance(getattr(leaf, "sharding", None), NamedSharding)
        and any(s is not None for s in leaf.sharding.spec)
    ]
    assert sharded, "restored optimizer state lost its ZeRO sharding"
    # and training continues
    state8, loss = tr8.train_step(
        state8, tr8.place_batch(model.synthetic_batch(rng, 16))
    )
    assert np.isfinite(float(loss))
    ckpt.close()


# -- push-based epoch discovery (watch satellite) -------------------------------


def _watch_worker(tmp_path, coord, **cfg_kw):
    model = fit_a_line.MODEL
    cfg = ElasticConfig(
        checkpoint_dir=str(tmp_path / "ck"),
        heartbeat_interval=30.0,  # pull alone would take 30 s to notice
        **cfg_kw,
    )
    source = SyntheticShardSource(model, batch_size=8, batches_per_shard=2)
    return ElasticWorker(model, coord.client("trainer-0"), source, cfg)


def test_epoch_discovery_knob_is_validated():
    with pytest.raises(ValueError, match="epoch_discovery"):
        ElasticConfig(checkpoint_dir="x", epoch_discovery="telepathy")


def test_epoch_discovery_pull_disables_the_watch(tmp_path):
    coord = InProcessCoordinator(task_lease_sec=60.0, heartbeat_ttl_sec=60.0)
    worker = _watch_worker(tmp_path, coord, epoch_discovery="pull")
    assert worker._watch is None


def test_watch_interrupts_inside_the_heartbeat_interval(tmp_path):
    """The push win: with a 30 s heartbeat interval, a bump_epoch must still
    flip _epoch_changed() on the very next check — discovery rides the watch
    stream, not the pull cadence."""
    coord = InProcessCoordinator(task_lease_sec=60.0, heartbeat_ttl_sec=60.0)
    worker = _watch_worker(tmp_path, coord)
    worker._sync_membership()
    assert worker._watch is not None and worker._watch.connected
    # own registration epoch must not replay as a notification
    assert worker._epoch_changed() is False
    coord.bump_epoch()
    t0 = time.monotonic()
    assert worker._epoch_changed() is True
    assert time.monotonic() - t0 < 1.0
    assert worker._watch.notifies_total >= 1


def test_watch_dead_subscription_degrades_to_pull(tmp_path):
    """A broken watch is silent degradation, not a stall: _epoch_changed
    falls through to the pull path and still reports the move."""
    coord = InProcessCoordinator(task_lease_sec=60.0, heartbeat_ttl_sec=60.0)
    worker = _watch_worker(tmp_path, coord)
    worker._sync_membership()

    class DeadWatch:
        connected = False
        last_epoch = -1

        def poll(self, timeout=0.0):
            return []

        def subscribe(self, timeout=5.0):
            return False

        def close(self):
            pass

    worker._watch = DeadWatch()
    coord.bump_epoch()
    assert worker._epoch_changed(force=True) is True
