"""Transformer LM: sharding equivalence across dp/sp/tp meshes + training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.models import transformer
from edl_tpu.parallel import MeshSpec, build_mesh
from edl_tpu.runtime import Trainer, TrainerConfig

CFG = transformer.TransformerConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=8, d_ff=64, seq_len=16
)


def _loss_on(axes, batch):
    """Init on a single-device mesh deterministically, reshard to `axes`."""
    mesh = build_mesh(MeshSpec(axes))
    model = transformer.make_model(CFG)
    params = model.init(jax.random.PRNGKey(0), mesh)
    placed = {
        k: jax.device_put(
            jnp.asarray(v),
            jax.sharding.NamedSharding(mesh, model.batch_spec(mesh)[k]),
        )
        for k, v in batch.items()
    }
    return float(model.loss_fn(params, placed, mesh))


def test_loss_identical_across_mesh_layouts():
    """Same params/batch -> same loss whether sharded dp, sp, tp or mixed.

    This is the capability the reference's DistributeTranspiler could never
    offer: distribution changes the layout, not the math.
    """
    batch = transformer.synthetic_batch(CFG, np.random.default_rng(0), 8)
    ref = _loss_on({"data": 8}, batch)
    for axes in ({"seq": 8}, {"model": 8}, {"data": 2, "seq": 2, "model": 2},
                 {"data": 2, "seq": 4}, {"data": 4, "model": 2},
                 {"pipe": 2, "data": 2, "seq": 2},
                 {"pipe": 2, "seq": 2, "model": 2},
                 {"pipe": 2, "data": 4}):
        got = _loss_on(axes, batch)
        assert got == pytest.approx(ref, rel=2e-2), (axes, got, ref)


def test_train_step_decreases_loss_on_3d_mesh():
    mesh = build_mesh(MeshSpec({"data": 2, "seq": 2, "model": 2}))
    model = transformer.make_model(CFG)
    trainer = Trainer(model, mesh, TrainerConfig(optimizer="adam", learning_rate=1e-3))
    state = trainer.init_state()
    rng = np.random.default_rng(1)
    batch = model.synthetic_batch(rng, 8)
    placed = trainer.place_batch(batch)
    losses = []
    for _ in range(8):
        state, loss = trainer.train_step(state, placed)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    assert int(state.step) == 8


def test_param_shardings_land_on_axes():
    mesh = build_mesh(MeshSpec({"data": 2, "model": 4}))
    model = transformer.make_model(CFG)
    params = model.init(jax.random.PRNGKey(0), mesh)
    wqkv = params["blocks"]["wqkv"]
    # col-sharded over model: local shard of the head dim is H/tp
    assert wqkv.sharding.spec == jax.sharding.PartitionSpec(
        None, None, None, "model", None
    )
    assert params["embed"].sharding.spec == jax.sharding.PartitionSpec(None, None)


def test_invalid_divisibility_raises():
    mesh = build_mesh(MeshSpec({"model": 8}))
    bad = transformer.TransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=4, d_ff=64, seq_len=16
    )  # 4 heads cannot split over tp=8
    model = transformer.make_model(bad)
    with pytest.raises(ValueError):
        model.init(jax.random.PRNGKey(0), mesh)


@pytest.mark.parametrize(
    "axes",
    [{"data": 2, "seq": 2, "model": 2}, {"pipe": 2, "data": 2, "seq": 2}],
    ids=["dp-sp-tp", "pp-dp-sp"],
)
def test_remat_matches_no_remat(axes):
    """Per-block rematerialization must change memory, not math: identical
    loss; gradients equal to float-reassociation tolerance (recomputed
    activations fuse differently than stored ones, so bitwise equality is
    not guaranteed — a few ulps is). The pipe layout exercises checkpoint
    INSIDE a GPipe stage, the composition most likely to break."""
    import dataclasses

    mesh = build_mesh(MeshSpec(axes))
    plain = transformer.make_model(CFG)
    remat = transformer.make_model(dataclasses.replace(CFG, remat=True))

    key = jax.random.PRNGKey(0)
    params = plain.init(key, mesh)
    rng = np.random.default_rng(0)
    batch = plain.synthetic_batch(rng, 4)
    placed = {k: jax.device_put(v) for k, v in batch.items()}

    def run(model):
        fn = jax.jit(jax.value_and_grad(lambda p, b: model.loss_fn(p, b, mesh)))
        loss, grads = fn(params, placed)
        return float(loss), grads

    l0, g0 = run(plain)
    l1, g1 = run(remat)
    assert l0 == pytest.approx(l1, rel=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g0), jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-7)


def test_flash_attention_matches_dense_in_model():
    """The Pallas flash path (cfg.flash, default) must reproduce the dense
    attention model end to end — loss AND gradients — on an unsharded
    sequence (the case the kernel serves)."""
    import dataclasses

    mesh = build_mesh(MeshSpec({"data": 2}), jax.devices()[:2])
    flash = transformer.make_model(CFG)  # flash=True default
    dense = transformer.make_model(dataclasses.replace(CFG, flash=False))
    params = flash.init(jax.random.PRNGKey(0), mesh)
    batch = flash.synthetic_batch(np.random.default_rng(0), 4)
    placed = {
        k: jax.device_put(
            jnp.asarray(v),
            jax.sharding.NamedSharding(mesh, flash.batch_spec(mesh)[k]),
        )
        for k, v in batch.items()
    }

    def run(model):
        fn = jax.jit(jax.value_and_grad(lambda p, b: model.loss_fn(p, b, mesh)))
        loss, grads = fn(params, placed)
        return float(loss), grads

    lf, gf = run(flash)
    ld, gd = run(dense)
    # bf16-rounding tolerance: the dense path downcasts P to bf16 for the
    # PV matmul while the kernel accumulates in f32 throughout, so they
    # agree to bf16 precision, with flash on the more accurate side.
    assert lf == pytest.approx(ld, rel=2e-3)
    for a, b in zip(jax.tree_util.tree_leaves(gf),
                    jax.tree_util.tree_leaves(gd)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-2, atol=3e-3)


def test_unknown_pipeline_schedule_raises():
    mesh = build_mesh(MeshSpec({"pipe": 2, "data": 4}))
    import dataclasses

    bad = dataclasses.replace(CFG, n_layers=2, pipeline_schedule="1F1B ")
    model = transformer.make_model(bad)
    with pytest.raises(ValueError, match="pipeline_schedule"):
        model.init(jax.random.PRNGKey(0), mesh)
