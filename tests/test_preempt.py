"""Preemption-native capacity: advance-notice drains and straggler eviction.

Unit coverage for the revocation path's planks — the notice-budget policy
decision (fake clock), straggler quantile math (trailing window, hysteresis,
cooldown), replica-ring placement overrides (revoked ranks never HOLD a
replica), the watch client's preempt-frame handling (seq dedup, replay),
and the LeaseReader's replay-free boundary drain — plus the single-worker
e2e: a live ElasticWorker revoked mid-training drains inside its notice
with zero lost steps. The two-job revocation WAVE (scripted ChaosScenario)
lives in ``tests/test_chaos_preempt.py`` (`make chaos-preempt`).
"""

import threading
import time

import pytest

from edl_tpu.ckpt_plane.placement import (
    PLACEMENT_KEY, placement_map, replica_group,
)
from edl_tpu.coordinator import InProcessCoordinator
from edl_tpu.coordinator.watch import make_epoch_watch
from edl_tpu.models import fit_a_line
from edl_tpu.obs.instruments import PreemptInstruments
from edl_tpu.obs.metrics import MetricsRegistry
from edl_tpu.obs.tracing import Tracer
from edl_tpu.runtime.data import (
    LeaseReader, SyntheticShardSource, shard_names,
)
from edl_tpu.runtime.elastic import ElasticConfig, ElasticWorker
from edl_tpu.runtime.ft_policy import (
    DRAIN_SHRINK, PARK, RIDE_OUT, FTPolicy, FTPolicyConfig,
)
from edl_tpu.runtime.straggler import (
    StragglerConfig, StragglerDetector, nearest_rank_quantile,
)

pytestmark = [pytest.mark.chaos]


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# -- placement override: revoked ranks never hold a replica --------------------


def test_replica_group_excludes_revoked_ranks():
    for world in (2, 4, 6, 8):
        for k in (1, 2, 3):
            for revoked in ([0], [world - 1], [1, 2]):
                for rank in range(world):
                    group = replica_group(rank, world, k, exclude=revoked)
                    assert not set(group) & set(revoked), (
                        f"replica landed on revoked rank: world={world} "
                        f"k={k} rank={rank} revoked={revoked} -> {group}")
                    assert rank not in group


def test_replica_group_keeps_k_holders_when_survivors_suffice():
    # world 6, k=2, rank 0's natural ring is (1, 2); banning 1 must walk
    # PAST it to (2, 3), not shrink the group.
    assert replica_group(0, 6, 2, exclude=[1]) == [2, 3]


def test_replica_group_clamps_k_to_surviving_candidates():
    # world 3, rank 0, k=2: candidates are {1, 2}; revoking 2 leaves one.
    assert replica_group(0, 3, 2, exclude=[2]) == [1]
    # every candidate revoked: no holders, owner keeps the only copy.
    assert replica_group(0, 2, 1, exclude=[1]) == []


def test_placement_map_with_exclusions_covers_survivors_only():
    revoked = [1]
    m = placement_map(4, 2, exclude=revoked)
    assert set(m) == {0, 1, 2, 3}  # revoked ranks still OWN their shard
    for rank, group in m.items():
        assert not set(group) & set(revoked), (rank, group)


def test_publish_placement_documents_exclusions():
    from edl_tpu.ckpt_plane.placement import publish_placement
    import json

    coord = InProcessCoordinator()
    c = coord.client("w0")
    c.register()
    doc = publish_placement(c, epoch=3, world=4, k=1, exclude=[2])
    raw = c.kv_get(PLACEMENT_KEY.format(epoch=3))
    stored = json.loads(raw)
    assert stored == doc
    assert stored["excluded"] == [2]
    for group in stored["groups"].values():
        assert 2 not in group


# -- straggler quantile math ---------------------------------------------------


def test_nearest_rank_quantile_matches_by_hand():
    assert nearest_rank_quantile([], 0.95) == 0.0
    assert nearest_rank_quantile([3.0, 1.0, 2.0], 0.5) == 2.0
    assert nearest_rank_quantile([1.0, 2.0, 3.0, 4.0], 1.0) == 4.0


def _detector(**kw):
    clock = FakeClock()
    cfg = StragglerConfig(window_steps=32, min_samples=16,
                          consecutive_breaches=3, **kw)
    det = StragglerDetector(cfg, PreemptInstruments(MetricsRegistry()),
                            clock=clock)
    return det, clock


def test_uniform_noise_never_evicts():
    det, _ = _detector()
    # 3 hosts, same distribution with deterministic jitter.
    for i in range(64):
        for h, base in (("h0", 1.0), ("h1", 1.0), ("h2", 1.0)):
            det.note_step(h, base + 0.01 * ((i * 7 + hash(h) % 5) % 11))
        if i % 4 == 0:
            assert det.evaluate() == []


def test_sustained_p95_breach_evicts_after_hysteresis():
    det, _ = _detector()
    for i in range(40):
        det.note_step("good-a", 1.0)
        det.note_step("good-b", 1.0)
        det.note_step("slow", 2.0)  # 2x the fleet, persistently
    verdicts = []
    rounds = 0
    while not verdicts and rounds < 10:
        verdicts = det.evaluate()
        rounds += 1
    assert verdicts == ["slow"]
    # hysteresis: it took exactly consecutive_breaches evaluations.
    assert rounds == det.config.consecutive_breaches


def test_one_slow_step_never_evicts():
    """A single outlier step — GC pause, one bad batch — must not condemn
    the host: nearest-rank p95 over the window shrugs it off AND the
    breach streak requires consecutive evaluations."""
    det, _ = _detector()
    for i in range(40):
        det.note_step("h0", 1.0)
        det.note_step("h1", 1.0)
    det.note_step("h0", 50.0)  # one catastrophic step
    for _ in range(6):
        assert det.evaluate() == []


def test_single_breach_evaluation_resets_on_recovery():
    det, _ = _detector()
    for _ in range(32):
        det.note_step("h0", 2.0)
        det.note_step("h1", 1.0)
        det.note_step("h2", 1.0)
    assert det.evaluate() == []  # breach 1 of 3
    assert det.evaluate() == []  # breach 2 of 3
    # host recovers before the third evaluation: window refills healthy.
    for _ in range(32):
        det.note_step("h0", 1.0)
        det.note_step("h1", 1.0)
        det.note_step("h2", 1.0)
    for _ in range(6):
        assert det.evaluate() == []  # streak reset, never evicted


def test_cooldown_suppresses_repeat_verdicts():
    det, clock = _detector(cooldown_s=300.0)
    for _ in range(40):
        det.note_step("slow", 2.0)
        det.note_step("h1", 1.0)
        det.note_step("h2", 1.0)
    verdicts = []
    for _ in range(5):
        verdicts += det.evaluate()
    assert verdicts == ["slow"]  # one verdict, then cooldown
    clock.advance(301.0)
    verdicts = []
    for _ in range(5):
        verdicts += det.evaluate()
    assert verdicts == ["slow"]  # cooldown expired, still slow -> again


def test_fleet_of_one_is_never_evaluated():
    det, _ = _detector()
    for _ in range(64):
        det.note_step("only", 9.0)
    assert det.evaluate() == []


def test_evict_routes_through_preempt_notice():
    det, _ = _detector(notice_s=17.0)

    class FakeClient:
        def __init__(self):
            self.calls = []

        def preempt_notice(self, targets, notice_s=30.0, reason="preempt"):
            self.calls.append((list(targets), notice_s, reason))
            return list(targets)

    client = FakeClient()
    revoked = det.evict(client, ["slow-host"])
    assert revoked == ["slow-host"]
    assert client.calls == [(["slow-host"], 17.0, "straggler")]
    assert det.evictions == 1
    assert det.evict(client, []) == []


# -- the notice-budget decision ------------------------------------------------


def _policy(**cfg_kw):
    clock = FakeClock()
    from edl_tpu.obs.instruments import FTPolicyInstruments

    tracer = Tracer(component="test")
    p = FTPolicy(FTPolicyConfig(**cfg_kw), worker="wtest",
                 instruments=FTPolicyInstruments(MetricsRegistry()),
                 tracer=tracer, clock=clock)
    return p, clock, tracer


def test_notice_budget_decision_table():
    p, _, _ = _policy(notice_margin=1.0)
    # measured costs: ckpt 4 s, restore 2 s, replan 1 s -> drain 7 s
    for _ in range(4):
        p.note_checkpoint_cost(4.0)
        p.note_restore_cost(2.0)
        p.note_replan_cost(1.0)
    assert p.drain_cost() == pytest.approx(7.0)
    assert p.on_preempt_notice(60.0) == DRAIN_SHRINK  # budget >> drain
    assert p.on_preempt_notice(5.0) == PARK  # ckpt fits, full drain doesn't
    assert p.on_preempt_notice(2.0) == RIDE_OUT  # not even a ckpt fits
    assert p.on_preempt_notice(-1.0) == RIDE_OUT  # deadline already passed


def test_notice_margin_derates_the_budget():
    p, _, _ = _policy(notice_margin=2.0)
    for _ in range(4):
        p.note_checkpoint_cost(4.0)
        p.note_restore_cost(2.0)
        p.note_replan_cost(1.0)
    # drain prices at 7 s, a ckpt at 4 s. 6 s of notice is only 3 s of
    # derated budget: not even the ckpt fits -> ride out rather than miss
    # the deadline mid-save. 10 s derates to 5 s: ckpt yes, drain no.
    assert p.on_preempt_notice(6.0) == RIDE_OUT
    assert p.on_preempt_notice(10.0) == PARK


def test_cold_start_is_optimistic_drain():
    p, _, _ = _policy()
    # nothing measured: drain prices at 0 and any positive budget drains.
    assert p.on_preempt_notice(1.0) == DRAIN_SHRINK


def test_ft_decision_span_carries_notice_remaining():
    p, _, tracer = _policy()
    p.on_preempt_notice(42.0)
    spans = [s for s in tracer.spans if s.name == "ft_decision"]
    assert spans and spans[-1].attrs["notice_remaining_s"] == 42.0
    assert "drain_cost" in spans[-1].attrs
    assert "drain_cost" in p.state()


# -- watch client: preempt frames ----------------------------------------------


def test_preempt_frame_pushes_to_live_subscriber():
    coord = InProcessCoordinator()
    w0 = coord.client("w0")
    w0.register()
    watch = make_epoch_watch(w0, "watch")
    assert watch.subscribe()
    admin = coord.client("admin")
    admin.register()
    t0 = time.monotonic()
    assert admin.preempt_notice(["w0"], notice_s=30.0,
                                reason="spot") == ["w0"]
    watch.poll()
    notices = watch.take_preempts()
    assert len(notices) == 1
    n = notices[0]
    assert n["worker"] == "w0" and n["reason"] == "spot"
    assert n["notice_s"] == 30.0 and n["seq"] == 1
    assert t0 <= n["arrival"] <= n["deadline"] - 29.0
    assert watch.take_preempts() == []  # drained


def test_preempt_replays_to_late_subscriber_and_dedups():
    coord = InProcessCoordinator()
    w0 = coord.client("w0")
    w0.register()
    admin = coord.client("admin")
    admin.register()
    admin.preempt_notice(["w0"], notice_s=45.0, reason="maint")
    # Subscribe AFTER the notice: the pending revocation must replay.
    watch = make_epoch_watch(w0, "watch")
    assert watch.subscribe()
    watch.poll()
    assert [n["seq"] for n in watch.take_preempts()] == [1]
    # Resubscribe (dropped connection): the same frame replays but the
    # seq dedup drops it — at-least-once delivery, exactly-once action.
    assert watch.subscribe()
    watch.poll()
    assert watch.take_preempts() == []


def test_leave_consumes_the_notice_and_status_renders_it():
    coord = InProcessCoordinator()
    w0 = coord.client("w0")
    w0.register()
    admin = coord.client("admin")
    admin.register()
    admin.preempt_notice(["w0"], notice_s=30.0)
    st = admin.call("status")
    assert st["preempts"] == ["w0=30"]
    w0.leave()
    st = admin.call("status")
    assert st.get("preempts", []) == []


def test_preempt_notice_requires_targets():
    coord = InProcessCoordinator()
    admin = coord.client("admin")
    admin.register()
    reply = admin.call("preempt_notice", targets=[], notice_s=5.0)
    assert reply["ok"] is False and "targets" in reply["error"]


# -- LeaseReader: replay-free boundary drain -----------------------------------


def test_soft_stop_finishes_in_flight_shard_without_replay():
    coord = InProcessCoordinator(task_lease_sec=30.0)
    c = coord.client("r1")
    c.register()
    c.add_tasks(shard_names("drain", 3))
    model = fit_a_line.MODEL
    source = SyntheticShardSource(model, batch_size=8, batches_per_shard=4)

    count = [0]
    # Soft signal fires mid-shard-0 (after 2 of 4 batches) — the reader
    # must FINISH shard 0, complete it, and stop before leasing shard 1.
    reader = LeaseReader(c, source,
                         soft_stop_check=lambda: count[0] >= 2)
    for batch in reader:
        count[0] += 1
    assert reader.drained and reader.interrupted is None
    assert not reader.exhausted
    assert count[0] == 4  # the in-flight shard ran to its boundary
    assert reader.completed == ["drain/part-00000"]

    # Nothing failed back: a second reader sees exactly the two untouched
    # shards — zero replay.
    reader2 = LeaseReader(c, source)
    seen = 0
    for _ in reader2:
        seen += 1
    assert reader2.exhausted
    assert seen == 8
    assert set(reader2.completed) == {"drain/part-00001", "drain/part-00002"}


# -- e2e: a live worker revoked mid-training -----------------------------------


def test_elastic_worker_drains_on_notice_with_zero_steps_lost(tmp_path):
    """The single-job tentpole e2e: trainer-0 trains under world=2, the
    'scheduler' revokes it with 30 s notice, the policy picks drain-and-
    shrink, the worker finishes its in-flight shard, evacuates, leaves
    before the deadline, and a survivor drains the rest — with EXACT step
    accounting (nothing lost, nothing replayed)."""
    model = fit_a_line.MODEL
    n_shards, bps, batch = 6, 6, 16
    coord = InProcessCoordinator(task_lease_sec=60.0, heartbeat_ttl_sec=60.0)
    admin = coord.client("admin")
    admin.add_tasks(shard_names("spot", n_shards))

    def make_worker(name):
        return ElasticWorker(
            model, coord.client(name),
            SyntheticShardSource(model, batch_size=batch,
                                 batches_per_shard=bps),
            ElasticConfig(checkpoint_dir=str(tmp_path / "ck"),
                          checkpoint_interval=50,
                          heartbeat_interval=0.0,  # check watch every batch
                          rescale_barrier_timeout=30.0,
                          peer_replicas=1),
        )

    worker = make_worker("trainer-0")
    stop = threading.Event()

    def follow():
        """trainer-1: surviving member / replica-ring peer."""
        j = coord.client("trainer-1")
        info = j.register()
        epoch = info["epoch"]
        while not stop.is_set():
            reply = j.sync(epoch, timeout=5.0)
            if reply.get("ok"):
                break
            epoch = reply.get("epoch", epoch)
        while not stop.is_set():
            hb = j.heartbeat()
            if hb.get("ok") and hb["epoch"] != epoch:
                epoch = hb["epoch"]
                j.sync(epoch, timeout=5.0)
            time.sleep(0.02)

    follower = threading.Thread(target=follow, daemon=True)
    follower.start()

    def scheduler():
        t0 = time.time()
        while worker.steps_done < 3 and time.time() - t0 < 60:
            time.sleep(0.01)
        admin.preempt_notice(["trainer-0"], notice_s=30.0,
                             reason="spot-reclaim")

    # preempt instruments live in the global registry (cells persist
    # across tests in this process): assert deltas, not absolutes.
    notices0 = worker.preempt_obs.notices.value(reason="spot-reclaim")
    evict0 = worker.preempt_obs.evictions.value(trigger="revocation")

    sched = threading.Thread(target=scheduler, daemon=True)
    sched.start()
    try:
        doomed = worker.run()
    finally:
        sched.join(timeout=30)
    assert doomed["preempted"] == 1.0
    assert doomed["steps_lost"] == 0.0
    assert doomed["preempt_deadline_met"] == 1.0
    assert doomed["notice_to_drained_seconds"] < 30.0
    assert worker.preempt_obs.notices.value(reason="spot-reclaim") \
        == notices0 + 1
    assert worker.preempt_obs.evictions.value(trigger="revocation") \
        == evict0 + 1

    survivor = make_worker("trainer-2")
    try:
        rest = survivor.run()
    finally:
        stop.set()
        follower.join(timeout=10)
    # exact accounting: doomed + survivor == workload, zero replays.
    assert doomed["steps"] + rest["steps"] == n_shards * bps
    # the survivor restored the doomed worker's evacuated progress: its
    # state resumed at the doomed step count, not from zero.
    assert survivor._last_restore["source"] in ("peer", "blob")
