"""Watch-stream resilience: resume cursor, partition fallback, notify races.

The push path (EDL watch subscriptions) must not weaken any outage story
the pull path already passes: a coordinator SIGKILL+restart replays every
missed epoch exactly once through the resume cursor, a network partition
degrades to pull with a BOUNDED stall on the worker's step-check path,
and the notification fan-out survives concurrent bump/subscribe/cancel
churn. Everything here also rides the sanitizer lane (`make tsan-smoke`):
the watcher set is mutated from connection teardown while bumps iterate
it, which is exactly the interleaving TSan should see.
"""

import threading
import time

import pytest

from edl_tpu.coordinator import CoordinatorServer
from edl_tpu.coordinator.server import ShardedCoordinator
from edl_tpu.coordinator.watch import EpochWatch
from edl_tpu.testing import ChaosProxy

from tests.test_coordinator import has_toolchain

needs_native = pytest.mark.skipif(
    not has_toolchain(), reason="native toolchain unavailable"
)

pytestmark = [pytest.mark.chaos, pytest.mark.sanitizer, needs_native]


def _drain(watch, want, deadline_s=20.0):
    """Poll until ``want`` distinct epochs arrived or the deadline passes."""
    got = []
    deadline = time.monotonic() + deadline_s
    while len(got) < want and time.monotonic() < deadline:
        got += [e for e, _ in watch.poll(timeout=0.2)]
    return got


def test_watch_resume_cursor_replays_missed_epochs_across_kill_restart(tmp_path):
    """SIGKILL the coordinator while epochs keep moving: on reconnect the
    subscribe cursor replays exactly the missed window — nothing seen
    before the kill is redelivered, nothing after it is lost."""
    state = str(tmp_path / "coord-state.jsonl")
    server = CoordinatorServer(task_lease_sec=60.0, heartbeat_ttl_sec=60.0,
                               state_file=state, run_id="watchkill")
    server.start()
    try:
        ctl = server.client("admin")
        e0 = ctl.epoch()
        watch = EpochWatch(port=server.port, worker="w0")
        watch.last_epoch = e0  # nothing to replay on first subscribe
        assert watch.subscribe()

        assert ctl.bump_epoch() == e0 + 1
        assert ctl.bump_epoch() == e0 + 2
        assert _drain(watch, 2) == [e0 + 1, e0 + 2]
        ctl.close()

        server.kill()  # SIGKILL: the stream dies mid-subscription
        # the dead stream surfaces as empty polls, never an exception
        assert watch.poll(timeout=0.3) == []
        assert not watch.connected

        server.restart()  # journal recovery bumps the epoch on its own
        ctl = server.client("admin")
        e_restart = ctl.epoch()
        assert e_restart > e0 + 2
        e_final = ctl.bump_epoch()

        # poll() resubscribes with cursor=e0+2; the replay covers the
        # restart bump AND the post-restart bump, exactly once each
        missed = _drain(watch, e_final - (e0 + 2))
        assert missed == list(range(e0 + 3, e_final + 1)), missed
        assert watch.last_epoch == e_final
        assert watch.resubscribes >= 1
        # exactly-once observation: replays of epochs the cursor already
        # covered were dropped client-side, not surfaced again
        assert watch.poll(timeout=0.2) == []
        ctl.close()
    finally:
        server.stop()


def test_watch_partition_degrades_to_pull_without_stall():
    """A blackholed watch stream must cost the worker loop a BOUNDED stall
    per poll (the re-subscribe connect is capped at ~1 s) while the pull
    path keeps discovering epochs; heal reconnects and the bumped epoch
    arrives exactly once."""
    with CoordinatorServer(task_lease_sec=60.0, heartbeat_ttl_sec=60.0) as server:
        with ChaosProxy(server.port, seed=7) as proxy:
            watch = EpochWatch(port=proxy.port, worker="w0")
            ctl = server.client("admin")
            watch.last_epoch = ctl.epoch()
            assert watch.subscribe()

            proxy.partition()
            e1 = ctl.bump_epoch()  # dials the server directly, not the proxy

            # the step-check path: every poll through the dead subscription
            # returns promptly — the pull cadence owns liveness meanwhile
            stalls = []
            for _ in range(6):
                t0 = time.monotonic()
                assert watch.poll() == []
                stalls.append(time.monotonic() - t0)
                time.sleep(0.25)  # let the retry backoff become due again
            assert max(stalls) < 2.0, stalls
            assert not watch.connected
            # pull fallback is what the worker actually acts on: a direct
            # status round-trip sees the new epoch despite the dead stream
            assert ctl.epoch() == e1

            proxy.heal()
            assert _drain(watch, 1) == [e1]
            assert watch.connected and watch.resubscribes >= 1
            # at-least-once delivery, exactly-once observation
            assert watch.poll(timeout=0.2) == []
            ctl.close()


def test_watch_notify_hammer_concurrent_bumps_and_subscription_churn():
    """The notification fan-out under contention: one thread bumps epochs
    while watcher connections subscribe, poll, and tear down mid-stream.
    Every surviving watcher observes a strictly increasing epoch sequence
    ending at the final epoch — no lost, reordered, or doubled frames.
    (Under `make tsan-smoke` this is the race probe for the watcher-set
    mutation on connection close racing the bump fan-out.)"""
    with CoordinatorServer(task_lease_sec=60.0, heartbeat_ttl_sec=60.0) as server:
        ctl = server.client("admin")
        e0 = ctl.epoch()
        bumps = 30
        stop = threading.Event()

        def bumper():
            for _ in range(bumps):
                ctl.bump_epoch()
                time.sleep(0.002)
            stop.set()

        def churner():
            # subscriptions that connect and vanish mid-fanout: the server
            # must drop their fds without disturbing the stable watchers
            while not stop.is_set():
                w = EpochWatch(port=server.port, worker="churn")
                if w.subscribe(timeout=1.0):
                    w.poll()
                w.close()
                time.sleep(0.005)

        stable = []
        for i in range(3):
            w = EpochWatch(port=server.port, worker=f"stable{i}")
            w.last_epoch = e0
            assert w.subscribe()
            stable.append(w)

        threads = [threading.Thread(target=bumper),
                   threading.Thread(target=churner)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert stop.is_set(), "bumper never finished"

        e_final = ctl.epoch()
        assert e_final == e0 + bumps
        for w in stable:
            got = _drain(w, bumps)
            assert got == list(range(e0 + 1, e_final + 1)), got[:5]
            w.close()
        ctl.close()


def test_watch_on_sharded_root_delivers_through_redirect_topology():
    """Watch subscriptions live on the root of a partitioned control plane:
    a bump on the root reaches a watcher even while the same client's
    keyspace ops are being redirected to shards."""
    with ShardedCoordinator(num_shards=2, task_lease_sec=60.0,
                            heartbeat_ttl_sec=60.0) as sc:
        c = sc.client("w0")
        c.register()
        c.kv_put("alpha", "1")  # routed to a shard via redirect/shard map
        assert c.kv_get("alpha") == "1"

        watch = EpochWatch(port=sc.port, worker="w0")
        watch.last_epoch = c.epoch()
        assert watch.subscribe()
        e1 = c.bump_epoch()
        assert _drain(watch, 1) == [e1]
        watch.close()
        c.close()
