"""Persistent AOT compile cache: round-trip, eviction, and the Trainer
integration contract (a hit dispatches AOT and never touches the jit
dispatch cache).
"""

import jax
import numpy as np
import pytest

from edl_tpu.models import fit_a_line
from edl_tpu.parallel import local_mesh
from edl_tpu.runtime import Trainer, TrainerConfig
from edl_tpu.runtime.compile_cache import CompileCache, code_fingerprint


def _hits(cache):
    return cache.hits.value(tier="memory") + cache.hits.value(tier="disk")


def _misses(cache):
    return sum(cache.misses.value(reason=r)
               for r in ("absent", "stale", "corrupt"))


def _trainer(cache):
    return Trainer(fit_a_line.MODEL, local_mesh(),
                   TrainerConfig(optimizer="sgd", learning_rate=0.1),
                   compile_cache=cache)


def _avals(model, n=64):
    batch = model.synthetic_batch(np.random.default_rng(0), n)
    return batch, {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                   for k, v in batch.items()}


def test_round_trip_serves_identical_executable(tmp_path):
    mesh = local_mesh()

    def f(x):
        return x * 2.0 + 1.0

    aval = jax.ShapeDtypeStruct((8,), np.float32)
    compiled = jax.jit(f).lower(aval).compile()
    cache = CompileCache(str(tmp_path))
    key = cache.key(mesh, "test-config", repr(aval), "no-state")
    assert cache.load(key) is None  # absent
    assert cache.store(key, compiled)
    assert cache.entries() == 1

    # Memory tier: the very object back.
    assert cache.load(key) is compiled

    # Disk tier: drop the memory map, deserialize, execute, compare.
    cache.clear_memory()
    loaded = cache.load(key)
    assert loaded is not None and loaded is not compiled
    x = np.arange(8, dtype=np.float32)
    np.testing.assert_allclose(np.asarray(loaded(x)),
                               np.asarray(compiled(x)))


def test_key_separates_layout_config_and_avals(tmp_path):
    mesh = local_mesh()
    cache = CompileCache(str(tmp_path))
    base = cache.key(mesh, "cfg", "batch-sig", "state-sig")
    assert cache.key(mesh, "cfg2", "batch-sig", "state-sig") != base
    assert cache.key(mesh, "cfg", "batch-sig-64", "state-sig") != base
    assert cache.key(mesh, "cfg", "batch-sig", "state-sig-2") != base
    from edl_tpu.parallel import MeshSpec, build_mesh
    half = build_mesh(MeshSpec({"data": 4}), jax.devices()[:4])
    assert cache.key(half, "cfg", "batch-sig", "state-sig") != base
    assert cache.key(mesh, "cfg", "batch-sig", "state-sig") == base


def test_corrupted_entry_evicts_and_recompiles(tmp_path):
    mesh = local_mesh()
    compiled = jax.jit(lambda x: x + 1).lower(
        jax.ShapeDtypeStruct((4,), np.float32)).compile()
    cache = CompileCache(str(tmp_path))
    key = cache.key(mesh, "cfg", "b", "s")
    cache.store(key, compiled)
    cache.clear_memory()

    path = cache._path(key)
    with open(path, "r+b") as f:
        header = f.readline()
        f.write(b"\x00garbage\x00")  # tear the payload, keep the header
    before = cache.misses.value(reason="corrupt")
    assert cache.load(key) is None
    assert cache.misses.value(reason="corrupt") == before + 1
    import os
    assert not os.path.exists(path), "corrupt entry must be evicted"
    # and the slot is clean for a fresh store
    assert cache.store(key, compiled)
    cache.clear_memory()
    assert cache.load(key) is not None


def test_stale_fingerprint_evicts(tmp_path):
    mesh = local_mesh()
    compiled = jax.jit(lambda x: x - 1).lower(
        jax.ShapeDtypeStruct((4,), np.float32)).compile()
    writer = CompileCache(str(tmp_path), fingerprint="aaaa000011112222")
    key = writer.key(mesh, "cfg", "b", "s")
    writer.store(key, compiled)

    # Same directory, different code fingerprint — e.g. the package was
    # edited between the store and this process. Note the key itself also
    # embeds the fingerprint, so this models a *collision-free* stale read:
    # the reader probes the writer's key (warm-restart handoff file, say)
    # and must refuse the bytes.
    reader = CompileCache(str(tmp_path), fingerprint="bbbb333344445555")
    before = reader.misses.value(reason="stale")
    assert reader.load(key) is None
    assert reader.misses.value(reason="stale") == before + 1
    assert reader.entries() == 0


def test_default_fingerprint_is_code_fingerprint(tmp_path):
    cache = CompileCache(str(tmp_path))
    assert cache.fingerprint == code_fingerprint()
    assert len(cache.fingerprint) == 16


def test_trainer_warm_compile_miss_then_hit(tmp_path):
    cache = CompileCache(str(tmp_path))
    model = fit_a_line.MODEL
    batch, avals = _avals(model)

    t1 = _trainer(cache)
    s1 = t1.init_state()
    miss_seconds = t1.warm_compile(s1, avals)
    assert t1.last_compile_cache == "miss"
    assert cache.entries() == 1

    # A fresh Trainer (same config, same mesh, fresh init_state) keys
    # identically and is served without compiling.
    t2 = _trainer(cache)
    s2 = t2.init_state()
    hits_before = _hits(cache)
    hit_seconds = t2.warm_compile(s2, avals)
    assert t2.last_compile_cache == "hit"
    assert _hits(cache) == hits_before + 1
    assert hit_seconds < miss_seconds

    # The hit dispatches through the warm AOT path: jit cache unpolluted,
    # and the step matches a plain-jit trainer bit-for-bit.
    placed = t2.place_batch(batch)
    s2, loss = t2.train_step(s2, placed)
    size = t2._jit_cache_size()
    if size is not None:
        assert size == 0
    ref = Trainer(model, local_mesh(),
                  TrainerConfig(optimizer="sgd", learning_rate=0.1))
    _, ref_loss = ref.train_step(ref.init_state(), ref.place_batch(batch))
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
    assert int(s2.step) == 1


def test_trainer_disk_hit_across_cache_instances(tmp_path):
    """The warm-restart shape: a new CompileCache over the same directory
    (new process, same code) serves the executable from disk."""
    model = fit_a_line.MODEL
    _, avals = _avals(model)

    first = CompileCache(str(tmp_path))
    t1 = _trainer(first)
    t1.warm_compile(t1.init_state(), avals)
    assert t1.last_compile_cache == "miss"

    second = CompileCache(str(tmp_path))
    disk_before = second.hits.value(tier="disk")
    t2 = _trainer(second)
    t2.warm_compile(t2.init_state(), avals)
    assert t2.last_compile_cache == "hit"
    assert second.hits.value(tier="disk") == disk_before + 1


def test_trainer_without_cache_reports_off(tmp_path):
    model = fit_a_line.MODEL
    _, avals = _avals(model)
    t = Trainer(model, local_mesh(),
                TrainerConfig(optimizer="sgd", learning_rate=0.1))
    assert t.last_compile_cache == "off"
    t.warm_compile(t.init_state(), avals)
    assert t.last_compile_cache == "off"
