"""Tests for the job-spec type system.

Models the reference's black-box resource tests
(`pkg/resource/training_job_test.go:27-46` — NeedGPU/Elastic predicates) and
quantity tests (`pkg/utils_test.go:25-48`).
"""

import pytest

from edl_tpu.api import (
    JobPhase,
    ResourceList,
    TrainingJob,
    ValidationError,
    parse_quantity,
    set_defaults,
    validate,
)
from edl_tpu.api.validation import normalize

EXAMPLE_YAML = """
metadata:
  name: example
  namespace: default
spec:
  image: "edl-tpu/job:latest"
  port: 7164
  fault_tolerant: true
  passes: 2
  tpu:
    accelerator_type: v5e
    chips_per_trainer: 4
  trainer:
    entrypoint: "python train.py"
    workspace: "/workspace"
    min_instance: 2
    max_instance: 10
    resources:
      requests: {cpu: "500m", memory: "600Mi"}
      limits: {cpu: "1", memory: "1Gi"}
  coordinator:
    resources:
      requests: {cpu: "100m", memory: "256Mi"}
"""


def test_parse_quantity():
    assert parse_quantity("500m") == 0.5
    assert parse_quantity("1") == 1.0
    assert parse_quantity("30Gi") == 30 * 1024**3
    assert parse_quantity("2k") == 2000.0
    assert parse_quantity(4) == 4.0
    with pytest.raises(ValueError):
        parse_quantity("abc")


def test_resource_list_math():
    a = ResourceList.make({"cpu": "1", "memory": "1Gi"})
    b = ResourceList.make({"cpu": "500m", "memory": "1Gi", "tpu": 4})
    a.add(b)
    assert a["cpu"] == 1.5
    assert a["memory"] == 2 * 1024**3
    assert a["tpu"] == 4.0
    assert b.fits_within({"cpu": 1.0, "memory": 2**31, "tpu": 8.0})
    assert not b.fits_within({"cpu": 0.25, "memory": 2**31, "tpu": 8.0})


def test_from_yaml_and_predicates():
    job = TrainingJob.from_yaml(EXAMPLE_YAML)
    assert job.name == "example"
    assert job.spec.trainer.min_instance == 2
    assert job.spec.trainer.max_instance == 10
    assert job.elastic()
    assert job.need_tpu()
    req = job.trainer_request()
    assert req["tpu"] == 4.0
    assert req["cpu"] == 0.5


def test_not_elastic_when_range_collapsed():
    job = TrainingJob.from_yaml(EXAMPLE_YAML)
    job.spec.trainer.max_instance = job.spec.trainer.min_instance
    assert not job.elastic()


def test_defaults_force_fault_tolerant_for_elastic():
    job = TrainingJob.from_yaml(EXAMPLE_YAML)
    job.spec.fault_tolerant = False
    set_defaults(job)
    assert job.spec.fault_tolerant  # elastic => fault tolerant
    assert job.spec.trainer.image == "edl-tpu/job:latest"
    assert job.spec.parallelism == {"data": 4}


def test_validate_rejects_bad_ranges():
    job = TrainingJob.from_yaml(EXAMPLE_YAML)
    job.spec.trainer.min_instance = 5
    job.spec.trainer.max_instance = 2
    with pytest.raises(ValidationError):
        validate(job)


def test_validate_rejects_incompatible_mesh():
    job = TrainingJob.from_yaml(EXAMPLE_YAML)
    set_defaults(job)
    job.spec.parallelism = {"data": 3}  # 3 does not divide 4 chips
    with pytest.raises(ValidationError):
        validate(job)


def test_normalize_roundtrip():
    job = normalize(TrainingJob.from_yaml(EXAMPLE_YAML))
    again = TrainingJob.from_dict(job.to_dict())
    assert again.spec.to_dict() == job.spec.to_dict()
    assert job.status.phase == JobPhase.NONE
