"""Pipeline schedule vs sequential-stage oracle on the virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.parallel import MeshSpec, build_mesh
from edl_tpu.parallel.pipeline import pipeline_apply


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _stack_params(rng, n_stages, d):
    return {
        "w": jnp.asarray(rng.standard_normal((n_stages, d, d)) * 0.5, jnp.float32),
        "b": jnp.asarray(rng.standard_normal((n_stages, d)) * 0.1, jnp.float32),
    }


def _sequential(params, x, n_stages):
    for s in range(n_stages):
        x = _stage_fn({"w": params["w"][s], "b": params["b"][s]}, x)
    return x


@pytest.mark.parametrize("axes,microbatches", [
    ({"pipe": 4, "data": 2}, None),
    ({"pipe": 8}, 8),
    ({"pipe": 2, "data": 4}, 4),
])
def test_matches_sequential(axes, microbatches):
    rng = np.random.default_rng(0)
    n = axes["pipe"]
    params = _stack_params(rng, n, 8)
    x = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
    mesh = build_mesh(MeshSpec(axes))
    got = pipeline_apply(
        _stage_fn, params, x, mesh, microbatches=microbatches
    )
    want = _sequential(params, x, n)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5)


def test_gradients_match_sequential():
    rng = np.random.default_rng(1)
    mesh = build_mesh(MeshSpec({"pipe": 4, "data": 2}))
    params = _stack_params(rng, 4, 4)
    x = jnp.asarray(rng.standard_normal((8, 4)), jnp.float32)

    g_pipe = jax.grad(
        lambda p: jnp.sum(pipeline_apply(_stage_fn, p, x, mesh) ** 2)
    )(params)
    g_seq = jax.grad(lambda p: jnp.sum(_sequential(p, x, 4) ** 2))(params)
    for k in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(g_pipe[k]), np.asarray(g_seq[k]), atol=1e-4, rtol=1e-4
        )


def test_no_pipe_axis_falls_back():
    rng = np.random.default_rng(2)
    mesh = build_mesh(MeshSpec({"data": 8}))
    params = _stack_params(rng, 1, 4)
    x = jnp.asarray(rng.standard_normal((8, 4)), jnp.float32)
    got = pipeline_apply(_stage_fn, params, x, mesh)
    want = _sequential(params, x, 1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_no_pipe_axis_runs_all_stages():
    """A mesh without a pipe axis (e.g. post-rescale) must still apply every
    stage sequentially, not silently run only stage 0."""
    rng = np.random.default_rng(2)
    params = _stack_params(rng, 4, 8)
    x = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
    mesh = build_mesh(MeshSpec({"data": 8}))
    got = pipeline_apply(_stage_fn, params, x, mesh)
    want = _sequential(params, x, 4)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5)


def test_bubble_fraction_accounting():
    from edl_tpu.parallel.pipeline import bubble_fraction

    assert bubble_fraction("gpipe", 1, 4) == 0.0
    assert bubble_fraction("gpipe", 4, 4) == pytest.approx(3 / 7)
    assert bubble_fraction("1f1b", 4, 4) == pytest.approx(6 / 10)
    # 1f1b's bubble shrinks with M while its memory stays O(n) — the regime
    # the schedule exists for
    assert bubble_fraction("1f1b", 4, 32) < bubble_fraction("1f1b", 4, 8)
    with pytest.raises(ValueError):
        bubble_fraction("interleaved", 4, 4)


def test_bubble_fraction_interleaved():
    from edl_tpu.parallel.pipeline import bubble_fraction

    # v=1 degenerates to plain 1f1b exactly
    for n, m in [(2, 4), (4, 8), (4, 32), (8, 16)]:
        assert bubble_fraction("1f1b-interleaved", n, m, 1) == pytest.approx(
            bubble_fraction("1f1b", n, m)
        )
    # closed form: (n*v + n - 2) / (m*v + n*v + n - 2)
    assert bubble_fraction("1f1b-interleaved", 4, 8, 2) == pytest.approx(
        10 / 26
    )
    # interleaving strictly shrinks the bubble at fixed M for n >= 3...
    for n, m in [(4, 4), (4, 8), (8, 16)]:
        assert bubble_fraction("1f1b-interleaved", n, m, 2) < bubble_fraction(
            "1f1b", n, m
        )
        assert bubble_fraction("1f1b-interleaved", n, m, 4) < bubble_fraction(
            "1f1b-interleaved", n, m, 2
        )
    # ...but at n=2 the lockstep schedule exactly ties plain 1f1b
    assert bubble_fraction("1f1b-interleaved", 2, 8, 2) == pytest.approx(
        bubble_fraction("1f1b", 2, 8)
    )
    with pytest.raises(ValueError):
        bubble_fraction("1f1b-interleaved", 4, 8, 0)
    with pytest.raises(ValueError):
        bubble_fraction("gpipe", 4, 8, 2)


def test_stash_slots_accounting():
    from edl_tpu.parallel.pipeline import stash_slots

    assert stash_slots("gpipe", 1, 8) == 0
    # gpipe's stash grows with M; 1f1b's saturates at 2n-1
    assert stash_slots("gpipe", 4, 32) == 35
    assert stash_slots("1f1b", 4, 32) == 7
    assert stash_slots("1f1b", 4, 4) == 4  # min(M, 2n-1)
    # interleaved: v rings of min(M, 3n) — O(n*v), still M-independent
    assert stash_slots("1f1b-interleaved", 4, 32, 2) == 24
    assert stash_slots("1f1b-interleaved", 4, 8, 2) == 16
    # the M-independent schedules stay below gpipe at large M
    assert stash_slots("1f1b-interleaved", 4, 64, 4) < stash_slots(
        "gpipe", 4, 64
    )


def test_interleaved_layout():
    from edl_tpu.parallel.pipeline import interleaved_layout

    # identity at v=1
    np.testing.assert_array_equal(
        interleaved_layout(8, 4, 1), np.arange(8)
    )
    # n=2, v=2, Lc=2: rank 0 holds stages 0,2 (layers 0,1,4,5), rank 1
    # holds stages 1,3 (layers 2,3,6,7), chunk-major
    np.testing.assert_array_equal(
        interleaved_layout(8, 2, 2), [0, 1, 4, 5, 2, 3, 6, 7]
    )
    perm = interleaved_layout(16, 4, 2)
    assert sorted(perm.tolist()) == list(range(16))  # a permutation
    with pytest.raises(ValueError):
        interleaved_layout(6, 4, 2)  # 6 % 8 != 0


@pytest.mark.parametrize(
    "axes,microbatches",
    [({"pipe": 2, "data": 4}, 4), ({"pipe": 4, "data": 2}, 8),
     # the risky composition: the combined scan's per-tick jax.vjp runs
     # THROUGH ring attention's seq-axis ppermutes and the tensor-parallel
     # psums inside the stage function
     ({"pipe": 2, "seq": 2, "model": 2}, 4)],
    ids=["pp2-M4", "pp4-M8", "pp2-sp2-tp2"],
)
def test_1f1b_matches_gpipe_in_model(axes, microbatches):
    """Schedule choice must change memory/wall profile, not math: loss AND
    every gradient (stage, tail, embedding via dx) equal to reassociation
    tolerance between gpipe and the combined-scan 1f1b."""
    import dataclasses

    from edl_tpu.models import transformer

    cfg = transformer.TransformerConfig(
        vocab_size=64, d_model=32, n_layers=4, n_heads=8, d_ff=64,
        seq_len=16, microbatches=microbatches,
    )
    mesh = build_mesh(MeshSpec(axes))
    gpipe = transformer.make_model(cfg)
    onef1b = transformer.make_model(
        dataclasses.replace(cfg, pipeline_schedule="1f1b")
    )
    params = gpipe.init(jax.random.PRNGKey(0), mesh)
    batch = gpipe.synthetic_batch(np.random.default_rng(0), 16)
    placed = {
        k: jax.device_put(
            jnp.asarray(v),
            jax.sharding.NamedSharding(mesh, gpipe.batch_spec(mesh)[k]),
        )
        for k, v in batch.items()
    }

    def run(model):
        fn = jax.jit(jax.value_and_grad(
            lambda p, b: model.loss_fn(p, b, mesh)
        ))
        loss, grads = fn(params, placed)
        return float(loss), grads

    l_g, g_g = run(gpipe)
    l_1, g_1 = run(onef1b)
    assert l_g == pytest.approx(l_1, rel=1e-5)
    flat_g, _ = jax.tree_util.tree_flatten_with_path(g_g)
    flat_1 = jax.tree_util.tree_leaves(g_1)
    for (path, a), b in zip(flat_g, flat_1):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=5e-2, atol=2e-5,
            err_msg=str(path),
        )


def test_1f1b_matches_single_device_oracle():
    """1f1b on a pipe mesh vs the same model on one device: the schedule
    must be invisible to the optimizer."""
    import dataclasses

    from edl_tpu.models import transformer

    cfg = transformer.TransformerConfig(
        vocab_size=64, d_model=32, n_layers=4, n_heads=8, d_ff=64,
        seq_len=16,
    )
    batch = transformer.synthetic_batch(cfg, np.random.default_rng(0), 8)

    def loss_on(axes, schedule):
        n_dev = 1
        for v in axes.values():
            n_dev *= v
        mesh = build_mesh(MeshSpec(axes), jax.devices()[:n_dev])
        model = transformer.make_model(
            dataclasses.replace(cfg, pipeline_schedule=schedule)
        )
        params = model.init(jax.random.PRNGKey(0), mesh)
        placed = {
            k: jax.device_put(
                jnp.asarray(v),
                jax.sharding.NamedSharding(mesh, model.batch_spec(mesh)[k]),
            )
            for k, v in batch.items()
        }
        loss, grads = jax.jit(jax.value_and_grad(
            lambda p, b: model.loss_fn(p, b, mesh)
        ))(params, placed)
        return float(loss), grads

    l_ref, g_ref = loss_on({"data": 1}, "gpipe")
    l_pp, g_pp = loss_on({"pipe": 4, "data": 2}, "1f1b")
    assert l_pp == pytest.approx(l_ref, rel=2e-2)
    for a, b in zip(jax.tree_util.tree_leaves(g_ref),
                    jax.tree_util.tree_leaves(g_pp)):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=8e-2, atol=3e-4,
        )


def _run_model_loss_grads(cfg, axes, batch):
    """Init + value_and_grad of a transformer on a sub-mesh of ``axes``."""
    from edl_tpu.models import transformer

    n_dev = 1
    for s in axes.values():
        n_dev *= s
    mesh = build_mesh(MeshSpec(axes), jax.devices()[:n_dev])
    model = transformer.make_model(cfg)
    params = model.init(jax.random.PRNGKey(0), mesh)
    placed = {
        k: jax.device_put(
            jnp.asarray(v),
            jax.sharding.NamedSharding(mesh, model.batch_spec(mesh)[k]),
        )
        for k, v in batch.items()
    }
    loss, grads = jax.jit(jax.value_and_grad(
        lambda p, b: model.loss_fn(p, b, mesh)
    ))(params, placed)
    return float(loss), grads


def test_interleaved_matches_single_device_oracle():
    """Interleaved 1f1b (pp=4, v=2, M=8) vs the same logical model on one
    device. Both inits use the same key, so the logical layers are
    identical; the interleaved model stores blocks chunk-major, so its
    block grads map back to logical layer order through the inverse of
    interleaved_layout before comparison."""
    import dataclasses

    from edl_tpu.models import transformer
    from edl_tpu.parallel.pipeline import interleaved_layout

    cfg = transformer.TransformerConfig(
        vocab_size=64, d_model=32, n_layers=8, n_heads=8, d_ff=64,
        seq_len=16,
    )
    batch = transformer.synthetic_batch(cfg, np.random.default_rng(0), 16)

    l_ref, g_ref = _run_model_loss_grads(cfg, {"data": 1}, batch)
    l_il, g_il = _run_model_loss_grads(
        dataclasses.replace(
            cfg, pipeline_schedule="1f1b-interleaved", virtual_stages=2,
            microbatches=8,
        ),
        {"pipe": 4, "data": 2}, batch,
    )
    assert l_il == pytest.approx(l_ref, rel=2e-2)
    inv = np.argsort(interleaved_layout(8, 4, 2))
    for k, a in g_ref["blocks"].items():
        np.testing.assert_allclose(
            np.asarray(g_il["blocks"][k])[inv], np.asarray(a, np.float32),
            rtol=8e-2, atol=3e-4, err_msg=f"blocks[{k}]",
        )
    for k in ("embed", "pos", "lnf", "head"):
        np.testing.assert_allclose(
            np.asarray(g_il[k]), np.asarray(g_ref[k], np.float32),
            rtol=8e-2, atol=3e-4, err_msg=k,
        )


def test_interleaved_matches_gpipe_in_model():
    """gpipe and interleaved 1f1b on the same pp=4 mesh: schedule choice
    changes the timetable, not the math. Tighter tolerance than the oracle
    test since both sides run the same per-stage shard_map arithmetic."""
    import dataclasses

    from edl_tpu.models import transformer
    from edl_tpu.parallel.pipeline import interleaved_layout

    cfg = transformer.TransformerConfig(
        vocab_size=64, d_model=32, n_layers=8, n_heads=8, d_ff=64,
        seq_len=16, microbatches=8,
    )
    batch = transformer.synthetic_batch(cfg, np.random.default_rng(1), 16)
    axes = {"pipe": 4, "data": 2}

    l_g, g_g = _run_model_loss_grads(cfg, axes, batch)
    l_il, g_il = _run_model_loss_grads(
        dataclasses.replace(
            cfg, pipeline_schedule="1f1b-interleaved", virtual_stages=2,
        ),
        axes, batch,
    )
    assert l_il == pytest.approx(l_g, rel=1e-5)
    inv = np.argsort(interleaved_layout(8, 4, 2))
    for k, a in g_g["blocks"].items():
        np.testing.assert_allclose(
            np.asarray(g_il["blocks"][k])[inv], np.asarray(a, np.float32),
            rtol=5e-2, atol=2e-5, err_msg=f"blocks[{k}]",
        )
    for k in ("embed", "pos", "lnf", "head"):
        np.testing.assert_allclose(
            np.asarray(g_il[k]), np.asarray(g_g[k], np.float32),
            rtol=5e-2, atol=2e-5, err_msg=k,
        )


def test_interleaved_config_validation():
    from edl_tpu.models import transformer

    mesh = build_mesh(MeshSpec({"pipe": 4, "data": 2}))
    # v > 1 demands the interleaved schedule
    with pytest.raises(ValueError, match="virtual_stages"):
        transformer.make_model(
            vocab_size=64, d_model=32, n_layers=8, n_heads=8, d_ff=64,
            seq_len=16, virtual_stages=2,
        ).init(jax.random.PRNGKey(0), mesh)
    # layers must split evenly into pp*v chunks
    with pytest.raises(ValueError, match="n_layers"):
        transformer.make_model(
            vocab_size=64, d_model=32, n_layers=4, n_heads=8, d_ff=64,
            seq_len=16, pipeline_schedule="1f1b-interleaved",
            virtual_stages=2, microbatches=8,
        ).init(jax.random.PRNGKey(0), mesh)
    # microbatches inject in groups of pp under interleaving
    with pytest.raises(ValueError, match="microbatches"):
        transformer.make_model(
            vocab_size=64, d_model=32, n_layers=8, n_heads=8, d_ff=64,
            seq_len=16, pipeline_schedule="1f1b-interleaved",
            virtual_stages=2, microbatches=6,
        ).init(jax.random.PRNGKey(0), mesh)
