"""Pipeline schedule vs sequential-stage oracle on the virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.parallel import MeshSpec, build_mesh
from edl_tpu.parallel.pipeline import pipeline_apply


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _stack_params(rng, n_stages, d):
    return {
        "w": jnp.asarray(rng.standard_normal((n_stages, d, d)) * 0.5, jnp.float32),
        "b": jnp.asarray(rng.standard_normal((n_stages, d)) * 0.1, jnp.float32),
    }


def _sequential(params, x, n_stages):
    for s in range(n_stages):
        x = _stage_fn({"w": params["w"][s], "b": params["b"][s]}, x)
    return x


@pytest.mark.parametrize("axes,microbatches", [
    ({"pipe": 4, "data": 2}, None),
    ({"pipe": 8}, 8),
    ({"pipe": 2, "data": 4}, 4),
])
def test_matches_sequential(axes, microbatches):
    rng = np.random.default_rng(0)
    n = axes["pipe"]
    params = _stack_params(rng, n, 8)
    x = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
    mesh = build_mesh(MeshSpec(axes))
    got = pipeline_apply(
        _stage_fn, params, x, mesh, microbatches=microbatches
    )
    want = _sequential(params, x, n)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5)


def test_gradients_match_sequential():
    rng = np.random.default_rng(1)
    mesh = build_mesh(MeshSpec({"pipe": 4, "data": 2}))
    params = _stack_params(rng, 4, 4)
    x = jnp.asarray(rng.standard_normal((8, 4)), jnp.float32)

    g_pipe = jax.grad(
        lambda p: jnp.sum(pipeline_apply(_stage_fn, p, x, mesh) ** 2)
    )(params)
    g_seq = jax.grad(lambda p: jnp.sum(_sequential(p, x, 4) ** 2))(params)
    for k in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(g_pipe[k]), np.asarray(g_seq[k]), atol=1e-4, rtol=1e-4
        )


def test_no_pipe_axis_falls_back():
    rng = np.random.default_rng(2)
    mesh = build_mesh(MeshSpec({"data": 8}))
    params = _stack_params(rng, 1, 4)
    x = jnp.asarray(rng.standard_normal((8, 4)), jnp.float32)
    got = pipeline_apply(_stage_fn, params, x, mesh)
    want = _sequential(params, x, 1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_no_pipe_axis_runs_all_stages():
    """A mesh without a pipe axis (e.g. post-rescale) must still apply every
    stage sequentially, not silently run only stage 0."""
    rng = np.random.default_rng(2)
    params = _stack_params(rng, 4, 8)
    x = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
    mesh = build_mesh(MeshSpec({"data": 8}))
    got = pipeline_apply(_stage_fn, params, x, mesh)
    want = _sequential(params, x, 4)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5)


def test_bubble_fraction_accounting():
    from edl_tpu.parallel.pipeline import bubble_fraction

    assert bubble_fraction("gpipe", 1, 4) == 0.0
    assert bubble_fraction("gpipe", 4, 4) == pytest.approx(3 / 7)
    assert bubble_fraction("1f1b", 4, 4) == pytest.approx(6 / 10)
    # 1f1b's bubble shrinks with M while its memory stays O(n) — the regime
    # the schedule exists for
    assert bubble_fraction("1f1b", 4, 32) < bubble_fraction("1f1b", 4, 8)
    with pytest.raises(ValueError):
        bubble_fraction("interleaved", 4, 4)


@pytest.mark.parametrize(
    "axes,microbatches",
    [({"pipe": 2, "data": 4}, 4), ({"pipe": 4, "data": 2}, 8),
     # the risky composition: the combined scan's per-tick jax.vjp runs
     # THROUGH ring attention's seq-axis ppermutes and the tensor-parallel
     # psums inside the stage function
     ({"pipe": 2, "seq": 2, "model": 2}, 4)],
    ids=["pp2-M4", "pp4-M8", "pp2-sp2-tp2"],
)
def test_1f1b_matches_gpipe_in_model(axes, microbatches):
    """Schedule choice must change memory/wall profile, not math: loss AND
    every gradient (stage, tail, embedding via dx) equal to reassociation
    tolerance between gpipe and the combined-scan 1f1b."""
    import dataclasses

    from edl_tpu.models import transformer

    cfg = transformer.TransformerConfig(
        vocab_size=64, d_model=32, n_layers=4, n_heads=8, d_ff=64,
        seq_len=16, microbatches=microbatches,
    )
    mesh = build_mesh(MeshSpec(axes))
    gpipe = transformer.make_model(cfg)
    onef1b = transformer.make_model(
        dataclasses.replace(cfg, pipeline_schedule="1f1b")
    )
    params = gpipe.init(jax.random.PRNGKey(0), mesh)
    batch = gpipe.synthetic_batch(np.random.default_rng(0), 16)
    placed = {
        k: jax.device_put(
            jnp.asarray(v),
            jax.sharding.NamedSharding(mesh, gpipe.batch_spec(mesh)[k]),
        )
        for k, v in batch.items()
    }

    def run(model):
        fn = jax.jit(jax.value_and_grad(
            lambda p, b: model.loss_fn(p, b, mesh)
        ))
        loss, grads = fn(params, placed)
        return float(loss), grads

    l_g, g_g = run(gpipe)
    l_1, g_1 = run(onef1b)
    assert l_g == pytest.approx(l_1, rel=1e-5)
    flat_g, _ = jax.tree_util.tree_flatten_with_path(g_g)
    flat_1 = jax.tree_util.tree_leaves(g_1)
    for (path, a), b in zip(flat_g, flat_1):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=5e-2, atol=2e-5,
            err_msg=str(path),
        )


def test_1f1b_matches_single_device_oracle():
    """1f1b on a pipe mesh vs the same model on one device: the schedule
    must be invisible to the optimizer."""
    import dataclasses

    from edl_tpu.models import transformer

    cfg = transformer.TransformerConfig(
        vocab_size=64, d_model=32, n_layers=4, n_heads=8, d_ff=64,
        seq_len=16,
    )
    batch = transformer.synthetic_batch(cfg, np.random.default_rng(0), 8)

    def loss_on(axes, schedule):
        n_dev = 1
        for v in axes.values():
            n_dev *= v
        mesh = build_mesh(MeshSpec(axes), jax.devices()[:n_dev])
        model = transformer.make_model(
            dataclasses.replace(cfg, pipeline_schedule=schedule)
        )
        params = model.init(jax.random.PRNGKey(0), mesh)
        placed = {
            k: jax.device_put(
                jnp.asarray(v),
                jax.sharding.NamedSharding(mesh, model.batch_spec(mesh)[k]),
            )
            for k, v in batch.items()
        }
        loss, grads = jax.jit(jax.value_and_grad(
            lambda p, b: model.loss_fn(p, b, mesh)
        ))(params, placed)
        return float(loss), grads

    l_ref, g_ref = loss_on({"data": 1}, "gpipe")
    l_pp, g_pp = loss_on({"pipe": 4, "data": 2}, "1f1b")
    assert l_pp == pytest.approx(l_ref, rel=2e-2)
    for a, b in zip(jax.tree_util.tree_leaves(g_ref),
                    jax.tree_util.tree_leaves(g_pp)):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=8e-2, atol=3e-4,
        )
