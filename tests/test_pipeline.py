"""Pipeline schedule vs sequential-stage oracle on the virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.parallel import MeshSpec, build_mesh
from edl_tpu.parallel.pipeline import pipeline_apply


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _stack_params(rng, n_stages, d):
    return {
        "w": jnp.asarray(rng.standard_normal((n_stages, d, d)) * 0.5, jnp.float32),
        "b": jnp.asarray(rng.standard_normal((n_stages, d)) * 0.1, jnp.float32),
    }


def _sequential(params, x, n_stages):
    for s in range(n_stages):
        x = _stage_fn({"w": params["w"][s], "b": params["b"][s]}, x)
    return x


@pytest.mark.parametrize("axes,microbatches", [
    ({"pipe": 4, "data": 2}, None),
    ({"pipe": 8}, 8),
    ({"pipe": 2, "data": 4}, 4),
])
def test_matches_sequential(axes, microbatches):
    rng = np.random.default_rng(0)
    n = axes["pipe"]
    params = _stack_params(rng, n, 8)
    x = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
    mesh = build_mesh(MeshSpec(axes))
    got = pipeline_apply(
        _stage_fn, params, x, mesh, microbatches=microbatches
    )
    want = _sequential(params, x, n)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5)


def test_gradients_match_sequential():
    rng = np.random.default_rng(1)
    mesh = build_mesh(MeshSpec({"pipe": 4, "data": 2}))
    params = _stack_params(rng, 4, 4)
    x = jnp.asarray(rng.standard_normal((8, 4)), jnp.float32)

    g_pipe = jax.grad(
        lambda p: jnp.sum(pipeline_apply(_stage_fn, p, x, mesh) ** 2)
    )(params)
    g_seq = jax.grad(lambda p: jnp.sum(_sequential(p, x, 4) ** 2))(params)
    for k in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(g_pipe[k]), np.asarray(g_seq[k]), atol=1e-4, rtol=1e-4
        )


def test_no_pipe_axis_falls_back():
    rng = np.random.default_rng(2)
    mesh = build_mesh(MeshSpec({"data": 8}))
    params = _stack_params(rng, 1, 4)
    x = jnp.asarray(rng.standard_normal((8, 4)), jnp.float32)
    got = pipeline_apply(_stage_fn, params, x, mesh)
    want = _sequential(params, x, 1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_no_pipe_axis_runs_all_stages():
    """A mesh without a pipe axis (e.g. post-rescale) must still apply every
    stage sequentially, not silently run only stage 0."""
    rng = np.random.default_rng(2)
    params = _stack_params(rng, 4, 8)
    x = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
    mesh = build_mesh(MeshSpec({"data": 8}))
    got = pipeline_apply(_stage_fn, params, x, mesh)
    want = _sequential(params, x, 4)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5)
