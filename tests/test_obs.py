"""Telemetry-plane unit tests: registry/exposition round-trip, tracer and
rescale-timeline stitching, the stdlib HTTP endpoints, the coordinator
status bridge, structured logging, the collector's coordinator-health
block, and the `edl-tpu status` subcommand.

Everything here uses PRIVATE MetricsRegistry/Tracer instances — the
process-wide defaults stay untouched so these tests cannot contaminate
(or be contaminated by) the instrumented runtime code under test
elsewhere in the suite.
"""

import io
import json
import logging
import math
import threading
import urllib.error
import urllib.request

import pytest

from edl_tpu.controller import FakeCluster, JobStore, NodeInfo
from edl_tpu.api import ResourceList
from edl_tpu.obs.bridge import CoordinatorStatusBridge
from edl_tpu.obs.http import MetricsServer, scrape_metrics
from edl_tpu.obs.logs import JsonLogFormatter, configure_logging
from edl_tpu.obs.metrics import (
    DEFAULT_BUCKETS,
    MetricsRegistry,
    parse_prometheus,
)
from edl_tpu.obs.tracing import (
    RESCALE_PHASES,
    Span,
    Tracer,
    load_spans,
    rescale_timeline,
    rescale_trace_id,
)
from edl_tpu.tools.collector import Collector


# -- registry ------------------------------------------------------------------


def test_counter_gauge_histogram_basics():
    reg = MetricsRegistry()
    c = reg.counter("t_total", "help text")
    c.inc()
    c.inc(2.5)
    assert c.value() == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)

    g = reg.gauge("t_gauge")
    g.set(7.0)
    g.inc(-2.0)  # gauges may go down
    assert g.value() == 5.0

    h = reg.histogram("t_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(30.0)
    cell = h.cell()
    assert cell["count"] == 3.0
    assert cell["sum"] == pytest.approx(30.55)


def test_registry_get_or_create_shares_instruments():
    reg = MetricsRegistry()
    a = reg.counter("shared_total", "one")
    b = reg.counter("shared_total", "ignored on re-get")
    assert a is b
    a.inc()
    assert b.value() == 1.0
    # name collisions across kind or labelset are refused, not silently merged
    with pytest.raises(ValueError):
        reg.gauge("shared_total")
    with pytest.raises(ValueError):
        reg.counter("shared_total", labelnames=("op",))


def test_labels_must_match_declaration():
    reg = MetricsRegistry()
    c = reg.counter("lbl_total", labelnames=("op",))
    c.inc(op="a")
    c.inc(2, op="b")
    assert c.value(op="a") == 1.0
    assert c.value(op="b") == 2.0
    with pytest.raises(ValueError):
        c.inc()  # missing declared label
    with pytest.raises(ValueError):
        c.inc(op="a", extra="x")


def test_render_parse_roundtrip():
    reg = MetricsRegistry()
    reg.counter("rt_ops_total", "ops by kind", labelnames=("kind",)).inc(
        3, kind="write"
    )
    reg.gauge("rt_depth", "queue depth").set(4.0)
    h = reg.histogram("rt_lat_seconds", "latency", buckets=(0.01, 0.1, 1.0))
    h.observe(0.005)
    h.observe(0.05)
    h.observe(5.0)

    text = reg.render_prometheus()
    fams = parse_prometheus(text)

    assert fams["rt_ops_total"]["kind"] == "counter"
    assert fams["rt_ops_total"]["samples"]['rt_ops_total{kind="write"}'] == 3.0
    assert fams["rt_depth"]["samples"]["rt_depth"] == 4.0

    hist = fams["rt_lat_seconds"]
    assert hist["kind"] == "histogram"
    # cumulative buckets: 0.005 <= 0.01; 0.05 adds at le=0.1; 5.0 only at +Inf
    assert hist["samples"]['rt_lat_seconds_bucket{le="0.01"}'] == 1.0
    assert hist["samples"]['rt_lat_seconds_bucket{le="0.1"}'] == 2.0
    assert hist["samples"]['rt_lat_seconds_bucket{le="1"}'] == 2.0
    assert hist["samples"]['rt_lat_seconds_bucket{le="+Inf"}'] == 3.0
    assert hist["samples"]["rt_lat_seconds_count"] == 3.0
    assert hist["samples"]["rt_lat_seconds_sum"] == pytest.approx(5.055)


def test_label_values_escaped():
    reg = MetricsRegistry()
    g = reg.gauge("esc", labelnames=("path",))
    g.set(1.0, path='a"b\\c\nd')
    text = reg.render_prometheus()
    assert '\\"' in text and "\\\\" in text and "\\n" in text
    fams = parse_prometheus(text)  # and the escaped line still parses
    assert any(v == 1.0 for v in fams["esc"]["samples"].values())


def test_parse_rejects_garbage():
    with pytest.raises(ValueError):
        parse_prometheus("this is not exposition format\n")
    with pytest.raises(ValueError):
        parse_prometheus('unbalanced}bracket{ 1\n')


def test_collector_callback_runs_at_scrape_time():
    reg = MetricsRegistry()
    g = reg.gauge("pulled")
    calls = []

    def collect():
        calls.append(1)
        g.set(float(len(calls)))

    reg.register_collector(collect)
    assert parse_prometheus(reg.render_prometheus())["pulled"]["samples"][
        "pulled"
    ] == 1.0
    reg.snapshot()
    assert len(calls) == 2
    reg.unregister_collector(collect)
    reg.render_prometheus()
    assert len(calls) == 2


def test_snapshot_shape():
    reg = MetricsRegistry()
    reg.counter("s_total").inc(2)
    h = reg.histogram("s_seconds")
    h.observe(0.2)
    snap = reg.snapshot()
    assert snap["s_total"]["samples"] == [{"labels": {}, "value": 2.0}]
    assert snap["s_seconds"]["samples"][0]["count"] == 1
    assert snap["s_seconds"]["samples"][0]["sum"] == pytest.approx(0.2)
    assert len(DEFAULT_BUCKETS) > 5  # sanity: default latency buckets exist


# -- tracer + timeline ---------------------------------------------------------


def test_tracer_record_find_and_positive_clamp():
    tr = Tracer(component="worker")
    s = tr.record("drain", 100.0, 100.5, trace_id="rescale-e000007")
    assert s.seconds == pytest.approx(0.5)
    # zero/negative intervals clamp to strictly positive: "it happened"
    z = tr.record("checkpoint", 100.5, 100.5, trace_id="rescale-e000007")
    assert z.seconds > 0.0
    assert len(tr.find(trace_id="rescale-e000007")) == 2
    assert tr.find(name="drain")[0].component == "worker"
    assert tr.find(trace_id="other") == []


def test_tracer_span_context_and_event():
    tr = Tracer(component="controller")
    with tr.span("actuate", trace_id="t1", job="j"):
        pass
    with pytest.raises(RuntimeError):
        with tr.span("actuate", trace_id="t1"):
            raise RuntimeError("boom")
    spans = tr.find(name="actuate")
    assert len(spans) == 2
    assert spans[1].attrs["error"] == "RuntimeError"
    ev = tr.event("decided", trace_id="t1")
    assert ev.seconds >= 0.0


def test_tracer_sink_jsonl_and_load_spans(tmp_path):
    path = tmp_path / "events.jsonl"
    with open(path, "w") as sink:
        tr = Tracer(component="worker", sink=sink)
        tr.record("restore", 10.0, 11.0, trace_id="rescale-e000003")
        # foreign lines interleave in a shared pod stream; loader skips them
        sink.write('{"kind": "profiler_step", "seconds": 0.1}\n')
        sink.write("not json at all\n")
        tr.record("first_step", 11.0, 11.2, trace_id="rescale-e000003")
    spans = load_spans(str(path))
    assert [s["name"] for s in spans] == ["restore", "first_step"]
    assert all(s["kind"] == "span" for s in spans)
    assert spans[0]["seconds"] == pytest.approx(1.0)


def test_rescale_timeline_stitches_components_and_dedupes():
    tid = rescale_trace_id(4)
    assert tid == "rescale-e000004"
    spans = [
        # controller side observed the actuation
        dict(kind="span", name="actuate", start=0.0, end=0.1, seconds=0.1,
             trace_id=tid, component="controller"),
        # worker side: both sides timed "restore"; longest wins, repeat counted
        dict(kind="span", name="restore", start=1.0, end=1.5, seconds=0.5,
             trace_id=tid, component="worker"),
        dict(kind="span", name="restore", start=1.0, end=1.2, seconds=0.2,
             trace_id=tid, component="worker"),
        dict(kind="span", name="first_step", start=2.0, end=2.3, seconds=0.3,
             trace_id=tid, component="worker"),
        # unrelated trace and an id-less span are excluded
        dict(kind="span", name="restore", start=0.0, end=9.0, seconds=9.0,
             trace_id="rescale-e000009", component="worker"),
        dict(kind="span", name="stray", start=0.0, end=1.0, seconds=1.0,
             trace_id="", component="worker"),
    ]
    out = rescale_timeline(spans, trace_id=tid)
    assert set(out) == {tid}
    t = out[tid]
    assert t["components"] == ["controller", "worker"]
    assert t["span_count"] == 4
    assert t["phases"]["restore"]["seconds"] == pytest.approx(0.5)
    assert t["phases"]["restore"]["count"] == 2
    assert t["wall_seconds"] == pytest.approx(2.3)
    # no filter: both traces come back
    assert set(rescale_timeline(spans)) == {tid, "rescale-e000009"}


def test_rescale_phase_vocabulary_is_stable():
    # the bench artifact and the e2e test are written against these names
    assert RESCALE_PHASES == (
        "preempt_drain", "drain", "checkpoint", "replan", "warm_compile",
        "restore", "reshard", "first_step"
    )


def test_rescale_timeline_surfaces_unknown_phases():
    tid = "rescale-e000021"
    spans = [
        Span("drain", 1.0, 1.1, trace_id=tid, component="worker"),
        Span("teleport", 1.1, 1.2, trace_id=tid, component="worker"),
    ]
    t = rescale_timeline(spans)[tid]
    # the stray name is kept in phases AND called out, not dropped
    assert t["phases"]["teleport"]["seconds"] == pytest.approx(0.1)
    assert t["unknown_phases"] == ["teleport"]


# -- HTTP endpoints ------------------------------------------------------------


def test_metrics_server_endpoints():
    reg = MetricsRegistry()
    reg.counter("srv_total").inc(5)
    tr = Tracer(component="worker")
    tr.record("drain", 1.0, 2.0, trace_id="rescale-e000001")

    with MetricsServer(registry=reg, tracer=tr, host="127.0.0.1", port=0,
                       health=lambda: {"epoch": 3}) as srv:
        text = scrape_metrics(srv.url)
        fams = parse_prometheus(text)
        assert fams["srv_total"]["samples"]["srv_total"] == 5.0

        with urllib.request.urlopen(srv.url + "/healthz", timeout=5) as r:
            payload = json.loads(r.read().decode())
        assert payload["ok"] is True and payload["epoch"] == 3

        with urllib.request.urlopen(srv.url + "/spans", timeout=5) as r:
            lines = [json.loads(l) for l in r.read().decode().splitlines()]
        assert lines[0]["name"] == "drain"
        assert lines[0]["trace_id"] == "rescale-e000001"

        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(srv.url + "/nope", timeout=5)


def test_metrics_server_healthz_survives_broken_health_callable():
    reg = MetricsRegistry()

    def bad_health():
        raise RuntimeError("probe me anyway")

    with MetricsServer(registry=reg, host="127.0.0.1", health=bad_health) as srv:
        with urllib.request.urlopen(srv.url + "/healthz", timeout=5) as r:
            payload = json.loads(r.read().decode())
        assert payload["ok"] is False
        assert "RuntimeError" in payload["error"]


def test_concurrent_scrapes_do_not_corrupt():
    reg = MetricsRegistry()
    c = reg.counter("conc_total")
    errors = []

    with MetricsServer(registry=reg, host="127.0.0.1") as srv:

        def hammer():
            try:
                for _ in range(10):
                    c.inc()
                    parse_prometheus(scrape_metrics(srv.url))
            except Exception as e:  # noqa: BLE001 - collected for the assert
                errors.append(e)

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
    assert not errors
    assert c.value() == 40.0


# -- coordinator status bridge -------------------------------------------------


class _FakeStatusClient:
    """CoordinatorClient surface: call('status') with a scripted reply."""

    def __init__(self, reply):
        self.reply = reply

    def call(self, op, timeout=None):
        assert op == "status"
        if isinstance(self.reply, Exception):
            raise self.reply
        return self.reply


def test_bridge_publishes_status_and_per_worker_leases():
    reg = MetricsRegistry()
    client = _FakeStatusClient({
        "ok": True, "epoch": 4, "queued": 2, "leased": 3, "done": 7,
        "ops": 100, "uptime_seconds": 12.5,
        "lease_holders": ["trainer-0=2", "trainer-1=1", "garbage"],
    })
    bridge = CoordinatorStatusBridge(client, registry=reg).register()
    fams = parse_prometheus(reg.render_prometheus())
    assert fams["edl_coordinator_up"]["samples"]["edl_coordinator_up"] == 1.0
    assert fams["edl_coordinator_epoch"]["samples"]["edl_coordinator_epoch"] == 4.0
    assert fams["edl_coordinator_uptime_seconds"]["samples"][
        "edl_coordinator_uptime_seconds"] == 12.5
    leases = fams["edl_coordinator_worker_leases"]["samples"]
    assert leases['edl_coordinator_worker_leases{worker="trainer-0"}'] == 2.0
    assert leases['edl_coordinator_worker_leases{worker="trainer-1"}'] == 1.0

    # a worker whose leases all completed is zeroed, not left dangling stale
    client.reply = dict(client.reply, lease_holders=["trainer-1=4"])
    leases = parse_prometheus(reg.render_prometheus())[
        "edl_coordinator_worker_leases"]["samples"]
    assert leases['edl_coordinator_worker_leases{worker="trainer-0"}'] == 0.0
    assert leases['edl_coordinator_worker_leases{worker="trainer-1"}'] == 4.0
    bridge.unregister()


def test_bridge_unreachable_coordinator_reads_up_zero():
    reg = MetricsRegistry()
    client = _FakeStatusClient({
        "ok": True, "epoch": 9, "lease_holders": [],
    })
    bridge = CoordinatorStatusBridge(client, registry=reg).register()
    reg.render_prometheus()
    client.reply = OSError("connection refused")
    fams = parse_prometheus(reg.render_prometheus())
    assert fams["edl_coordinator_up"]["samples"]["edl_coordinator_up"] == 0.0
    # last-known values stay in place; staleness is signalled via `up`
    assert fams["edl_coordinator_epoch"]["samples"]["edl_coordinator_epoch"] == 9.0
    bridge.unregister()


# -- structured logging --------------------------------------------------------


def test_json_log_formatter_fields_and_extras():
    fmt = JsonLogFormatter()
    logger = logging.Logger("edl_tpu.test.obs")
    stream = io.StringIO()
    handler = logging.StreamHandler(stream)
    handler.setFormatter(fmt)
    logger.addHandler(handler)

    logger.info("hello %s", "world",
                extra={"epoch": 3, "mesh": (2, 4), "dev": object()})
    try:
        raise ValueError("boom")
    except ValueError:
        logger.exception("failed")

    lines = [json.loads(l) for l in stream.getvalue().splitlines()]
    assert lines[0]["msg"] == "hello world"
    assert lines[0]["level"] == "info"
    assert lines[0]["logger"] == "edl_tpu.test.obs"
    assert lines[0]["epoch"] == 3
    assert lines[0]["mesh"] == [2, 4]  # tuples serialize as JSON arrays
    assert lines[0]["dev"].startswith("<object")  # non-JSON extras -> repr
    assert math.isfinite(lines[0]["ts"])
    assert lines[1]["level"] == "error"
    assert "ValueError: boom" in lines[1]["exc"]


def test_configure_logging_json_stream():
    root = logging.getLogger()
    saved_handlers, saved_level = list(root.handlers), root.level
    stream = io.StringIO()
    try:
        configure_logging(level="warning", fmt="json", stream=stream)
        logging.getLogger("edl_tpu.obs.test").warning("structured %d", 7)
        rec = json.loads(stream.getvalue().strip())
        assert rec["msg"] == "structured 7"
        assert rec["logger"] == "edl_tpu.obs.test"
    finally:
        for h in list(root.handlers):
            root.removeHandler(h)
        for h in saved_handlers:
            root.addHandler(h)
        root.setLevel(saved_level)


# -- collector: coordinator-health block (supervised control plane) ------------


class _FakeSupervisor:
    """CoordinatorSupervisor surface: summary() -> Dict[str, float]."""

    def __init__(self):
        self.restarts = 0.0
        self.downtime = 0.0

    def summary(self):
        return {
            "restarts": self.restarts,
            "downtime_seconds": self.downtime,
            "last_restart_rc": -6.0 if self.restarts else -1.0,
        }


def _tiny_cluster():
    return FakeCluster([
        NodeInfo(name="h0", allocatable=ResourceList.make(
            {"cpu": 8, "memory": "32Gi", "tpu": 8})),
    ])


def test_collector_propagates_supervisor_health_and_roundtrips_jsonl():
    sup = _FakeSupervisor()
    sink = io.StringIO()
    collector = Collector(JobStore(), _tiny_cluster(), period_seconds=10.0,
                          sink=sink, supervisor=sup)
    s0 = collector.sample()
    assert s0.coordinator["restarts"] == 0.0
    assert s0.coordinator["downtime_seconds"] == 0.0

    # the coordinator dies and the supervisor resurrects it twice
    sup.restarts, sup.downtime = 2.0, 1.25
    s1 = collector.sample()
    assert s1.coordinator["restarts"] == 2.0
    assert s1.coordinator["downtime_seconds"] == 1.25
    assert s1.coordinator["last_restart_rc"] == -6.0

    # JSONL round-trip: the health block survives serialization intact
    lines = [json.loads(l) for l in sink.getvalue().splitlines()]
    assert len(lines) == 2
    assert lines[0]["coordinator"]["restarts"] == 0.0
    assert lines[1]["coordinator"] == {
        "restarts": 2.0, "downtime_seconds": 1.25, "last_restart_rc": -6.0,
    }


def test_collector_without_supervisor_emits_empty_health_block():
    sink = io.StringIO()
    collector = Collector(JobStore(), _tiny_cluster(), sink=sink)
    s = collector.sample()
    assert s.coordinator == {}
    assert json.loads(sink.getvalue().strip())["coordinator"] == {}


# -- `edl-tpu status` subcommand -----------------------------------------------


def test_cli_status_against_live_coordinator(capsys):
    from edl_tpu.cli import main
    from edl_tpu.coordinator import CoordinatorServer
    from edl_tpu.runtime import shard_names

    with CoordinatorServer() as server:
        w = server.client("trainer-0")
        w.register()
        w.add_tasks(shard_names("cli", 3))
        assert w.acquire_task() is not None

        rc = main(["status", "--port", str(server.port)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "ok" in out
        assert "queued" in out and "leased" in out
        assert "uptime_seconds" in out
        # the per-worker lease table renders the native lease_holders encoding
        assert "per-worker leases:" in out
        assert "trainer-0" in out

        rc = main(["status", "--port", str(server.port), "--json"])
        payload = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert payload["ok"] is True
        assert payload["leased"] == 1
        assert payload["lease_holders"] == ["trainer-0=1"]


def test_cli_status_unreachable_coordinator(capsys):
    from edl_tpu.cli import main

    rc = main(["status", "--port", "1", "--timeout", "0.5"])
    assert rc == 1
    assert "ERROR" in capsys.readouterr().err


def test_cli_status_renders_ft_policy_section(capsys):
    """Workers publish their live policy state to coordinator KV
    (edl/ft_policy/<worker>); `edl-tpu status` reads it back per member."""
    from edl_tpu.cli import main
    from edl_tpu.coordinator import CoordinatorServer

    with CoordinatorServer() as server:
        w = server.client("trainer-0")
        w.register()
        w.kv_put("edl/ft_policy/trainer-0", json.dumps({
            "policy": "adaptive", "mode": "park", "threshold": 4.2,
            "incidents": 3, "storm": False,
        }))

        rc = main(["status", "--port", str(server.port)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "fault-tolerance policy:" in out
        assert "policy=adaptive" in out and "mode=park" in out

        rc = main(["status", "--port", str(server.port), "--json"])
        payload = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert payload["ft_policy"]["trainer-0"]["mode"] == "park"
        assert payload["ft_policy"]["trainer-0"]["threshold"] == 4.2


def test_cli_status_renders_lm_serving_section(capsys):
    """LM replicas publish kind="lm" blobs under edl/serving/<member>;
    `edl-tpu status` renders the decode-native numbers (streams, tokens/s,
    KV block pool) instead of the batch tier's queue/bucket line, and
    --json carries the blob through verbatim."""
    from edl_tpu.cli import main
    from edl_tpu.coordinator import CoordinatorServer

    lm_blob = {
        "name": "lm-0", "kind": "lm", "model_step": 100, "version": 3,
        "active_streams": 2, "waiting_streams": 0, "completed": 7,
        "rejected": 1, "evicted": 0, "tokens_generated": 56,
        "tokens_per_s": 12.5, "batch_buckets": [1, 4],
        "seq_buckets": [64, 128],
        "kv": {"n_blocks": 64, "block_tokens": 16, "used_blocks": 9,
               "free_blocks": 55, "peak_blocks_used": 12, "streams": 2,
               "occupancy": 0.1406, "fragmentation": 0.42},
    }
    batch_blob = {
        "name": "serve-0", "kind": "batch", "model_step": 200, "version": 5,
        "queue_depth": 0, "bucket_hits": {"4": 3}, "last_swap_step": 100,
        "completed": 12,
    }
    with CoordinatorServer() as server:
        w = server.client("lm-0")
        w.register()
        w.kv_put("edl/serving/lm-0", json.dumps(lm_blob))
        w2 = server.client("serve-0")
        w2.register()
        w2.kv_put("edl/serving/serve-0", json.dumps(batch_blob))

        rc = main(["status", "--port", str(server.port)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "serving replicas:" in out
        # the LM line renders stream/token/KV state...
        assert "kind=lm" in out
        assert "tokens/s=12.5" in out
        assert "kv_blocks=9/64" in out and "frag=0.42" in out
        assert "streams=2" in out
        # ...while the batch replica keeps its queue/bucket rendering
        assert "queue=0" in out and "buckets=4:3" in out

        rc = main(["status", "--port", str(server.port), "--json"])
        payload = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert payload["serving"]["lm-0"]["kind"] == "lm"
        assert payload["serving"]["lm-0"]["kv"]["free_blocks"] == 55
        assert payload["serving"]["lm-0"]["tokens_generated"] == 56
        assert payload["serving"]["serve-0"]["kind"] == "batch"
