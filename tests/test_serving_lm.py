"""LM serving tier: seq-bucket ladder, paged KV-cache admission,
decode-step continuous batching, prefill/decode AOT, router migration.

The acceptance contract under test (ISSUE 20): batch membership changes
per token (join at a decode-step boundary, leave on EOS/max-tokens),
memory — not batch slots — is the admission currency (block-pool
exhaustion is a typed 429, seq-ladder overflow a typed 400), and the
``jit_cache_size() == 0`` AOT contract survives LM traffic across BOTH
phase executables.
"""

import json
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from edl_tpu.models import transformer
from edl_tpu.obs.metrics import MetricsRegistry
from edl_tpu.runtime.export import _serving_mesh, save_inference_model
from edl_tpu.serving import (
    BlockPool,
    KVCacheConfig,
    KVCacheExhaustedError,
    LMServeSignal,
    LMServingConfig,
    LMServingReplica,
    LMServingSLO,
    NoReplicaError,
    Router,
    SeqTooLongError,
    aggregate_lm_signals,
    desired_lm_replica_delta,
    pad_batch,
    pad_token_rows,
    pick_seq_bucket,
)

MODEL_KW = dict(vocab_size=61, d_model=16, n_layers=2, n_heads=2, d_ff=32,
                seq_len=64, flash=False)


@pytest.fixture(scope="module")
def lm_artifact(tmp_path_factory):
    directory = str(tmp_path_factory.mktemp("lm_art"))
    model = transformer.make_model(**MODEL_KW)
    mesh = _serving_mesh(model)
    params = model.init(jax.random.PRNGKey(0), mesh)
    save_inference_model(directory, "transformer", params,
                         config=MODEL_KW, step=100)
    return directory


@pytest.fixture
def lm_replica_factory(lm_artifact):
    """Builds started LM replicas against the module artifact; stops all."""
    live = []

    def make(**overrides):
        # batch_buckets=(1,) keeps the AOT compile count down on the
        # shared-artifact tests; tests that exercise batched decode
        # membership override to a real ladder.
        kwargs = dict(model_dir=lm_artifact, batch_buckets=(1,),
                      seq_buckets=(16, 32), kv_blocks=16, kv_block_tokens=8,
                      default_max_new_tokens=4,
                      name=f"lm-t{len(live)}")
        kwargs.update(overrides)
        replica = LMServingReplica(LMServingConfig(**kwargs),
                                   registry=MetricsRegistry())
        live.append(replica)
        return replica.start()

    yield make
    for replica in live:
        replica.stop()


# -- seq-bucket ladder units ---------------------------------------------------


def test_pick_seq_bucket_picks_smallest_fit():
    assert pick_seq_bucket(1, (16, 32)) == 16
    assert pick_seq_bucket(16, (16, 32)) == 16
    assert pick_seq_bucket(17, (16, 32)) == 32
    assert pick_seq_bucket(32, (16, 32)) == 32


def test_pick_seq_bucket_overflow_is_typed_rejection():
    """Unlike the batch axis (overflow splits into chunks), a sequence
    cannot split across executables — past the ladder is a hard typed
    reject, and the type subclasses ValueError for HTTP 400 mapping."""
    with pytest.raises(SeqTooLongError):
        pick_seq_bucket(33, (16, 32))
    assert issubclass(SeqTooLongError, ValueError)
    with pytest.raises(ValueError):
        pick_seq_bucket(0, (16, 32))


def test_pad_token_rows_pads_and_measures():
    tokens, lengths = pad_token_rows(
        [np.array([5, 6, 7]), np.array([9])], bucket=4, seq_bucket=8
    )
    assert tokens.shape == (4, 8) and tokens.dtype == np.int32
    assert lengths.tolist() == [3, 1, 0, 0]
    assert tokens[0, :3].tolist() == [5, 6, 7]
    assert tokens[0, 3:].tolist() == [0] * 5
    assert tokens[2].tolist() == [0] * 8  # dead tail slot


def test_pad_token_rows_rejects_overflow():
    with pytest.raises(SeqTooLongError):
        pad_token_rows([np.arange(9)], bucket=1, seq_bucket=8)
    with pytest.raises(ValueError):
        pad_token_rows([np.array([1])] * 3, bucket=2, seq_bucket=8)


def test_pad_batch_fast_path_matches_per_row_semantics():
    avals = {"x": ((3,), np.dtype(np.float32))}
    rows = [{"x": np.full(3, float(i), np.float32)} for i in range(2)]
    out = pad_batch(rows, 4, avals)
    assert out["x"].shape == (4, 3)
    assert out["x"][1].tolist() == [1.0, 1.0, 1.0]
    assert out["x"][2:].sum() == 0.0  # zero-padded tail


def test_pad_batch_mismatch_still_names_the_offender():
    """The np.stack fast path must fall back to the per-row walk that
    raises the diagnostic naming the bad request and feature."""
    avals = {"x": ((3,), np.dtype(np.float32))}
    good = {"x": np.zeros(3, np.float32)}
    with pytest.raises(ValueError, match="request 1"):
        pad_batch([good, {"x": np.zeros(2, np.float32)}], 4, avals)
    with pytest.raises(KeyError, match="request 1"):
        pad_batch([good, {"y": np.zeros(3, np.float32)}], 4, avals)


# -- paged KV-cache allocator --------------------------------------------------


def test_block_pool_reserves_ceil_blocks():
    pool = BlockPool(KVCacheConfig(n_blocks=8, block_tokens=4))
    assert pool.config.blocks_for(1) == 1
    assert pool.config.blocks_for(4) == 1
    assert pool.config.blocks_for(5) == 2
    table = pool.reserve("s1", 9)  # 3 blocks
    assert len(table) == 3
    assert pool.used_blocks() == 3 and pool.free_blocks() == 5


def test_block_pool_exhaustion_is_atomic():
    """A reservation the freelist cannot cover raises without claiming
    anything — no partial claims to unwind, no leaked blocks."""
    pool = BlockPool(KVCacheConfig(n_blocks=4, block_tokens=4))
    pool.reserve("s1", 12)  # 3 of 4 blocks
    with pytest.raises(KVCacheExhaustedError):
        pool.reserve("s2", 8)  # needs 2, only 1 free
    assert pool.free_blocks() == 1  # the failed reserve claimed nothing
    pool.reserve("s3", 4)  # the remaining block still works


def test_block_pool_release_recycles_and_is_idempotent():
    pool = BlockPool(KVCacheConfig(n_blocks=4, block_tokens=4))
    first = pool.reserve("s1", 16)
    assert pool.release("s1") == 4
    assert pool.release("s1") == 0  # double-free is a no-op
    assert pool.free_blocks() == 4
    # freelist recycling: the same physical blocks come back out
    assert sorted(pool.reserve("s2", 16)) == sorted(first)
    with pytest.raises(ValueError):
        pool.reserve("s2", 4)  # duplicate stream id


def test_block_pool_fragmentation_tracks_unwritten_budget():
    pool = BlockPool(KVCacheConfig(n_blocks=8, block_tokens=4))
    pool.reserve("s1", 16)  # 4 blocks = 16 token slots
    assert pool.fragmentation() == 1.0  # nothing written yet
    pool.note_tokens("s1", 8)
    assert pool.fragmentation() == pytest.approx(0.5)
    stats = pool.stats()
    assert stats["reserved_tokens"] == 16 and stats["written_tokens"] == 8
    assert stats["occupancy"] == pytest.approx(0.5)
    pool.release("s1")
    assert pool.fragmentation() == 0.0
    pool.note_tokens("s1", 99)  # racing update after release: no-op
    assert pool.stats()["streams"] == 0


def test_block_pool_reports_bytes_when_sized():
    pool = BlockPool(KVCacheConfig(n_blocks=4, block_tokens=4,
                                   bytes_per_token=128))
    pool.reserve("s1", 5)  # 2 blocks = 8 token slots
    assert pool.stats()["used_bytes"] == 8 * 128


# -- LM autoscale signal -------------------------------------------------------


def _lm_signal(p99_band, count, occupancy):
    buckets = [(0.01, 0.0), (0.1, 0.0), (float("inf"), 0.0)]
    buckets = [(b, count if b >= p99_band else 0.0) for b, _ in buckets]
    return LMServeSignal(token_latency_buckets=buckets, token_count=count,
                         kv_occupancy=occupancy)


def test_lm_occupancy_aggregates_by_max_not_mean():
    """One full pool rejects real traffic no matter how empty its
    neighbors are — streams cannot split across replicas."""
    sig_full = _lm_signal(0.01, 100, 0.95)
    sig_idle = _lm_signal(0.01, 100, 0.05)
    _, occupancy = aggregate_lm_signals([sig_full, sig_idle])
    assert occupancy == 0.95


def test_lm_delta_grows_on_kv_pressure_and_shrinks_with_hysteresis():
    slo = LMServingSLO(p99_token_seconds=0.1, max_kv_occupancy=0.85)
    assert desired_lm_replica_delta([_lm_signal(0.01, 100, 0.95)], slo) == 1
    assert desired_lm_replica_delta([_lm_signal(0.01, 100, 0.1)], slo) == -1
    # in the hysteresis band: hold
    assert desired_lm_replica_delta([_lm_signal(0.01, 100, 0.5)], slo) == 0
    assert desired_lm_replica_delta([], slo) == 0


# -- the decode engine ---------------------------------------------------------


def test_lm_replica_aot_contract_and_exact_token_accounting(
        lm_replica_factory):
    replica = lm_replica_factory(batch_buckets=(1, 2))
    assert replica.jit_cache_size() == 0
    rng = np.random.default_rng(0)
    handles = [replica.submit(rng.integers(1, 60, size=n), max_new_tokens=5)
               for n in (3, 7, 12)]
    results = [h.result(timeout=60) for h in handles]
    for r in results:
        assert len(r["tokens"]) == 5
        assert r["finish_reason"] == "length"
        assert r["model_step"] == 100
    # BOTH phase jits' dispatch caches still empty: prefill and decode
    # only ever dispatched pre-compiled executables
    assert replica.jit_cache_size() == 0
    status = replica.status()
    assert status["kind"] == "lm"
    assert status["completed"] == 3
    assert status["tokens_generated"] == 15
    assert status["kv"]["used_blocks"] == 0  # every reservation recycled


def test_lm_decode_matches_incremental_prefill_reference(lm_replica_factory):
    """The engine's KV-cache decode must emit exactly the tokens a naive
    re-prefill-per-token loop would — the cache is an optimization, not a
    different model."""
    replica = lm_replica_factory()
    prompt = np.asarray([7, 11, 13, 17, 19], dtype=np.int32)
    out = replica.generate(prompt, max_new_tokens=4)

    step_fn = jax.jit(transformer.make_prefill_step(
        transformer.TransformerConfig(**MODEL_KW)))
    seq, reference = list(prompt), []
    for _ in range(4):
        tokens = np.zeros((1, 16), np.int32)
        tokens[0, :len(seq)] = seq
        nxt, _, _ = step_fn(replica._art.params, tokens,
                            np.array([len(seq)], np.int32))
        reference.append(int(nxt[0]))
        seq.append(int(nxt[0]))
    assert out["tokens"] == reference


def test_eos_on_first_decode_step(lm_replica_factory):
    """A stream whose very first generated token is EOS retires at the
    prefill boundary: one token, finish_reason eos, blocks recycled."""
    replica = lm_replica_factory()
    prompt = np.asarray([3, 5, 8], dtype=np.int32)
    probe = replica.generate(prompt, max_new_tokens=1)
    first = probe["tokens"][0]
    out = replica.generate(prompt, max_new_tokens=6, eos_id=first)
    assert out["tokens"] == [first]
    assert out["finish_reason"] == "eos"
    assert replica.status()["kv"]["used_blocks"] == 0


def test_join_and_leave_on_the_same_step(lm_replica_factory):
    """Per-token membership: streams with budgets 1/2/3 admitted together
    — the budget-1 stream leaves at the prefill boundary exactly as the
    others join the decode batch; everyone's accounting stays exact."""
    replica = lm_replica_factory(batch_buckets=(1, 2))
    prompt = np.asarray([2, 4, 6], dtype=np.int32)
    handles = [replica.submit(prompt, max_new_tokens=budget)
               for budget in (1, 2, 3)]
    results = [h.result(timeout=60) for h in handles]
    assert [len(r["tokens"]) for r in results] == [1, 2, 3]
    # same prompt => identical greedy prefixes; the short streams are
    # prefixes of the long one (leaving early never perturbs neighbors)
    assert results[2]["tokens"][:1] == results[0]["tokens"]
    assert results[2]["tokens"][:2] == results[1]["tokens"]
    status = replica.status()
    assert status["completed"] == 3
    assert status["tokens_generated"] == 6
    assert status["active_streams"] == 0


def test_admission_rejections_are_typed(lm_replica_factory):
    replica = lm_replica_factory()
    # seq-ladder overflow: prompt + budget > largest bucket (32)
    with pytest.raises(SeqTooLongError):
        replica.submit(np.arange(1, 30), max_new_tokens=10)
    # pool exhaustion: 16 blocks x 8 tokens = 128 slots; four 32-budget
    # streams (4 blocks each) drain the freelist
    blockers = [replica.submit([1, 2], max_new_tokens=26) for _ in range(4)]
    with pytest.raises(KVCacheExhaustedError):
        replica.submit([1, 2], max_new_tokens=26)
    for h in blockers:
        h.result(timeout=120)
    # retirement recycled the blocks: admission works again
    replica.generate([1, 2], max_new_tokens=26)
    assert replica.status()["rejected"] == 2


def test_http_generate_maps_typed_errors(lm_replica_factory):
    replica = lm_replica_factory(port=0, kv_blocks=4, kv_block_tokens=8)

    def post(body):
        req = urllib.request.Request(
            replica.url + "/generate", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, None

    status, reply = post({"prompt": [5, 9, 11], "max_new_tokens": 3})
    assert status == 200
    assert len(reply["tokens"]) == 3
    assert reply["finish_reason"] == "length"
    # 400: the seq ladder can never hold it — retrying cannot help
    status, _ = post({"prompt": list(range(1, 30)), "max_new_tokens": 20})
    assert status == 400
    # 429: pool exhausted — retry elsewhere/later CAN help
    blocker = replica.submit([1, 2], max_new_tokens=28)  # 30 of 32 slots
    status, _ = post({"prompt": [1, 2, 3], "max_new_tokens": 10})
    assert status == 429
    blocker.result(timeout=120)
    status, _ = post({"prompt": "not-a-list"})
    assert status == 400


def test_replica_drain_on_stop(lm_artifact):
    replica = LMServingReplica(LMServingConfig(
        model_dir=lm_artifact, batch_buckets=(1,), seq_buckets=(16, 32),
        kv_blocks=16, kv_block_tokens=8, name="lm-drain",
    ), registry=MetricsRegistry()).start()
    handles = [replica.submit([3, 1, 4], max_new_tokens=6)
               for _ in range(3)]
    replica.stop(drain=True)  # every admitted stream resolves first
    for h in handles:
        r = h.result(timeout=1)
        assert len(r["tokens"]) == 6


# -- router: affinity + zero-drop migration ------------------------------------


def test_router_affinity_prefers_kv_headroom(lm_replica_factory):
    small = lm_replica_factory(kv_blocks=4, kv_block_tokens=8, name="lm-small")
    big = lm_replica_factory(kv_blocks=64, kv_block_tokens=8, name="lm-big")
    router = Router([small, big])
    # burn most of the small pool so headroom clearly differs
    blocker = small.submit([1, 2], max_new_tokens=20)
    results = [router.generate([5, 9], max_new_tokens=3) for _ in range(3)]
    assert all(len(r["tokens"]) == 3 for r in results)
    blocker.result(timeout=120)
    assert big.status()["completed"] == 3  # affinity routed to headroom
    assert small.status()["completed"] == 1


def test_router_migrates_streams_on_remove_with_zero_drops(
        lm_replica_factory):
    rep_a = lm_replica_factory(name="lm-mig-a", seq_buckets=(16, 64),
                               kv_blocks=64)
    rep_b = lm_replica_factory(name="lm-mig-b", seq_buckets=(16, 64),
                               kv_blocks=64)
    router = Router([rep_a, rep_b])
    rng = np.random.default_rng(1)
    # 40-token budgets: no stream can finish in the gap before the
    # rescale below, so the remove genuinely evicts mid-decode
    handles = [router.generate_async(rng.integers(1, 60, size=4),
                                     max_new_tokens=40)
               for _ in range(6)]
    removed = router.remove(rep_a.config.name)
    removed.stop()
    results = [h.result(timeout=120) for h in handles]
    stats = router.stats()
    assert stats["dropped_streams"] == 0
    # exact generated-token accounting across the migration: prefix
    # stitched to the resumed remainder, nothing dropped or doubled
    assert all(len(r["tokens"]) == 40 for r in results)
    assert stats["migrations"] >= 1  # the rescale actually moved streams
    assert all(r["finish_reason"] == "length" for r in results)


def test_router_migrated_stream_matches_unmigrated_tokens(
        lm_replica_factory):
    """The zero-drop contract is not just counts: a migrated stream's
    stitched token list must be EXACTLY what an unmigrated run yields
    (greedy decode is deterministic — re-prefilling prompt+generated on
    the target replica continues the same sequence)."""
    rep_a = lm_replica_factory(name="lm-ex-a")
    rep_b = lm_replica_factory(name="lm-ex-b")
    prompt = np.asarray([7, 3, 29], dtype=np.int32)
    reference = rep_b.generate(prompt, max_new_tokens=12)["tokens"]

    router = Router([rep_a])  # only rep_a takes the stream...
    handle = router.generate_async(prompt, max_new_tokens=12)
    router.add(rep_b)  # ...then the pool rescales under it
    router.remove(rep_a.config.name)
    result = handle.result(timeout=120)
    assert result["tokens"] == reference
    assert result["migrations"] >= 1


def test_router_raises_when_pool_has_no_lm_replica():
    router = Router()
    with pytest.raises(NoReplicaError):
        router.generate_async([1, 2, 3], max_new_tokens=2)
    with pytest.raises(NoReplicaError):
        router.submit({"x": np.zeros(13, np.float32)})


# -- config validation ---------------------------------------------------------


def test_lm_config_validates_ladders_and_pool(lm_artifact):
    with pytest.raises(ValueError):
        LMServingConfig(model_dir=lm_artifact, seq_buckets=(32, 16))
    with pytest.raises(ValueError):
        LMServingConfig(model_dir=lm_artifact, kv_blocks=1,
                        kv_block_tokens=1, seq_buckets=(16,))
    with pytest.raises(ValueError):
        LMServingConfig(model_dir=lm_artifact, default_max_new_tokens=0)
    # seq bucket beyond the model's trained positions fails at start
    replica = LMServingReplica(LMServingConfig(
        model_dir=lm_artifact, seq_buckets=(16, 128), kv_blocks=32,
        kv_block_tokens=8, name="lm-bad-seq",
    ))
    with pytest.raises(ValueError, match="seq_len"):
        replica.start()
