"""ResNet model family: architecture fidelity + training on a DP mesh.

The vision configuration from BASELINE.json ("ResNet-50 / ImageNet,
data-parallel, elastic 4<->16 TPU workers"); no reference twin exists
(wopeizl/edl ships no vision models), so fidelity is checked against the
canonical ResNet-50 parameter count instead of a reference file.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from edl_tpu.models import resnet
from edl_tpu.parallel import MeshSpec, build_mesh
from edl_tpu.runtime import Trainer, TrainerConfig


def _param_count(model, mesh) -> int:
    shapes = jax.eval_shape(lambda k: model.init(k, mesh), jax.random.PRNGKey(0))
    return sum(int(np.prod(s.shape)) for s in jax.tree_util.tree_leaves(shapes))


def test_resnet50_canonical_param_count():
    """25,557,032 — the canonical ResNet-50 count. GroupNorm's scale/bias
    match BatchNorm's affine params exactly (running stats are not
    trainable), so the substitution is count-preserving."""
    mesh = Mesh(np.array(jax.devices()[:1]), axis_names=("data",))
    assert _param_count(resnet.MODEL, mesh) == 25_557_032


def test_resnet18_basic_blocks_build():
    mesh = Mesh(np.array(jax.devices()[:1]), axis_names=("data",))
    model = resnet.make_model(depth=18, num_classes=10, image_size=32,
                              width=8, gn_groups=4)
    shapes = jax.eval_shape(lambda k: model.init(k, mesh), jax.random.PRNGKey(0))
    # basic blocks have no conv3
    assert "conv3" not in shapes["blocks"][0]
    assert "proj" not in shapes["blocks"][0]  # stage 0 block 0: same shape
    # first block of stage 1 downsamples -> needs the projection shortcut
    assert "proj" in shapes["blocks"][2]


def test_param_spec_structure_matches_params():
    mesh = Mesh(np.array(jax.devices()[:1]), axis_names=("data",))
    for model in (resnet.MODEL, resnet.make_model(resnet.TINY),
                  resnet.make_model(depth=18)):
        shapes = jax.eval_shape(lambda k: model.init(k, mesh),
                                jax.random.PRNGKey(0))
        spec = model.param_spec(mesh)
        assert (jax.tree_util.tree_structure(spec)
                == jax.tree_util.tree_structure(shapes))


def test_tiny_resnet_trains_on_dp_mesh():
    model = resnet.make_model(resnet.TINY)
    mesh = build_mesh(MeshSpec({"data": len(jax.devices())}))
    trainer = Trainer(model, mesh,
                      TrainerConfig(optimizer="adam", learning_rate=1e-3))
    state = trainer.init_state()
    rng = np.random.default_rng(0)
    first = last = None
    for _ in range(10):
        state, loss = trainer.train_step(
            state, trainer.place_batch(model.synthetic_batch(rng, 32))
        )
        first = float(loss) if first is None else first
        last = float(loss)
    assert np.isfinite(last)
    assert last < first  # learns the synthetic frequency patterns
    acc = float(resnet.accuracy(model, state.params,
                                model.synthetic_batch(rng, 128)))
    assert acc > 2.0 / model.config.num_classes  # clearly above chance


def test_forward_batch_invariance():
    """Same example alone vs inside a batch -> same logits (GroupNorm is
    batch-independent; BatchNorm would fail this, which is why it was
    swapped out for the elastic world)."""
    model = resnet.make_model(resnet.TINY)
    mesh = Mesh(np.array(jax.devices()[:1]), axis_names=("data",))
    params = model.init(jax.random.PRNGKey(0), mesh)
    batch = model.synthetic_batch(np.random.default_rng(1), 8)
    full = np.asarray(resnet.forward(model, params, batch["image"]))
    solo = np.asarray(resnet.forward(model, params, batch["image"][:1]))
    np.testing.assert_allclose(full[:1], solo, rtol=2e-4, atol=2e-4)


def test_loss_identical_across_mesh_sizes():
    """1-device vs 8-device DP mesh produce the same loss for the same
    params/batch (SPMD partitioning must not change the math)."""
    model = resnet.make_model(resnet.TINY)
    rng = np.random.default_rng(2)
    batch = model.synthetic_batch(rng, 16)
    losses = []
    for n in (1, len(jax.devices())):
        mesh = build_mesh(MeshSpec({"data": n}), jax.devices()[:n])
        trainer = Trainer(model, mesh, TrainerConfig(optimizer="sgd",
                                                     learning_rate=1e-2))
        state = trainer.init_state()
        _, loss = trainer.train_step(state, trainer.place_batch(batch))
        losses.append(float(loss))
    assert losses[0] == pytest.approx(losses[1], rel=1e-4)
