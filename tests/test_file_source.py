"""File-backed shard source: unit coverage + the uneven-shards e2e.

VERDICT r2 gap #3's done-criterion: a multi-process e2e training real,
genuinely uneven file shards end-to-end with a mid-run rescale — the case the
lockstep padding machinery (`edl_tpu/runtime/multihost.py`) was built for
(ref file readers: `example/fit_a_line/fluid/common.py:24-40`, per-trainer
shard download `example/ctr/ctr/train.py:221-227`).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from conftest import multiprocess_on_cpu
from edl_tpu.runtime.data import FileShardSource, shard_seed, write_shard


def _write_fit_shards(root, rows_per_shard):
    """Deterministic fit_a_line shards with explicit row counts."""
    from edl_tpu.models import fit_a_line

    for shard, rows in rows_per_shard.items():
        rng = np.random.default_rng(shard_seed(shard))
        write_shard(root, shard, fit_a_line.synthetic_batch(rng, rows))


# -- unit ----------------------------------------------------------------------


def test_write_and_read_roundtrip(tmp_path):
    root = str(tmp_path)
    rng = np.random.default_rng(0)
    arrays = {"x": rng.standard_normal((10, 3)).astype(np.float32),
              "y": np.arange(10, dtype=np.int32)}
    path = write_shard(root, "ds/part-00000", arrays)
    assert os.path.exists(path) and os.path.exists(path + ".meta.json")

    src = FileShardSource(root=root, batch_size=4)
    batches = list(src.read("ds/part-00000"))
    # 10 rows @ batch 4 -> 3 batches, tail padded by wrapping (static shapes)
    assert len(batches) == 3
    assert all(b["x"].shape == (4, 3) for b in batches)
    np.testing.assert_array_equal(batches[0]["y"], [0, 1, 2, 3])
    np.testing.assert_array_equal(batches[2]["y"], [8, 9, 0, 1])  # wrapped
    assert src.rows("ds/part-00000") == 10
    assert src.batch_count("ds/part-00000") == 3


def test_batch_count_metadata_without_sidecar(tmp_path):
    """A foreign writer without the sidecar still gets a correct (slower)
    batch_count from the file itself."""
    root = str(tmp_path)
    write_shard(root, "s0", {"x": np.zeros((7, 2), np.float32)})
    os.remove(os.path.join(root, "s0.npz.meta.json"))
    src = FileShardSource(root=root, batch_size=3)
    assert src.batch_count("s0") == 3  # ceil(7/3)
    assert src.batch_count("missing") == 0


def test_read_is_deterministic_replay(tmp_path):
    root = str(tmp_path)
    _write_fit_shards(root, {"a": 37})
    src = FileShardSource(root=root, batch_size=16)
    first = [b["x"].copy() for b in src.read("a")]
    again = [b["x"] for b in src.read("a")]
    for f, g in zip(first, again):
        np.testing.assert_array_equal(f, g)


def test_list_shards_walks_subdirs(tmp_path):
    root = str(tmp_path)
    _write_fit_shards(root, {"tr/part-00000": 4, "tr/part-00001": 4, "va/p": 4})
    src = FileShardSource(root=root, batch_size=2)
    assert src.list_shards() == ["tr/part-00000", "tr/part-00001", "va/p"]


def test_mismatched_rows_rejected(tmp_path):
    with pytest.raises(ValueError):
        write_shard(str(tmp_path), "bad",
                    {"x": np.zeros((3, 1)), "y": np.zeros((4,))})


def test_ctr_prepare_cli_writes_uneven_shards(tmp_path):
    """The flagship example's --prepare mode materializes deterministic,
    uneven click-log shards (ref: example/ctr/ctr/train.py:221-227)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo)
    out = subprocess.run(
        [sys.executable, os.path.join("examples", "ctr", "train.py"),
         "--prepare", "3", "--data-dir", str(tmp_path),
         "--batch-size", "32", "--rows-per-shard", "64",
         "--sparse-feature-dim", "1001"],
        capture_output=True, text=True, timeout=120, cwd=repo, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    info = json.loads(out.stdout.strip().splitlines()[-1])
    assert info["prepared"] == 3
    rows = list(info["rows"].values())
    assert len(set(rows)) > 1, f"shards should be uneven: {rows}"

    src = FileShardSource(root=str(tmp_path), batch_size=32)
    assert len(src.list_shards()) == 3
    batch = next(iter(src.read("criteo/part-00000")))
    assert set(batch) == {"dense", "sparse", "label"}
    assert batch["dense"].shape == (32, 13)
    assert batch["sparse"].shape == (32, 26)


# -- e2e: uneven file shards, multi-process, mid-run rescale -------------------


@multiprocess_on_cpu
def test_two_process_uneven_file_shards_with_midrun_rescale(tmp_path):
    """Two launcher-managed workers train genuinely uneven on-disk shards in
    lockstep; a third joins mid-run (epoch bump + expected_world), everyone
    warm-restarts to world 3, and the queue drains with all shards' data
    consumed exactly through the padding machinery."""
    from edl_tpu.coordinator import CoordinatorServer
    from edl_tpu.coordinator.server import ensure_built, free_port

    from tests.test_multihost import REPO, WORKER_SRC

    ensure_built()
    data_root = str(tmp_path / "data")
    # uneven on purpose: 16-row batches -> batch counts 3, 1, 2, 5, 1, ...
    # Enough shards that the world-2 phase outlives w2's spawn + bring-up.
    rows = {}
    sizes = [48, 16, 32, 80, 10, 55, 23, 64, 37, 48, 16, 90,
             41, 33, 17, 66, 29, 52, 75, 20, 88, 31, 44, 59] * 5
    for i, n in enumerate(sizes):
        rows[f"uci/part-{i:05d}"] = n
    _write_fit_shards(data_root, rows)

    jax_port = free_port()
    ckpt = str(tmp_path / "ck")
    entry_py = tmp_path / "entry.py"
    entry_py.write_text(WORKER_SRC.format(repo=REPO, jax_port=jax_port))
    launcher_src = f"""
import os, sys
sys.path.insert(0, {REPO!r})
from edl_tpu.launcher.launch import LaunchContext, start_trainer
ctx = LaunchContext.from_env()
sys.exit(start_trainer(ctx))
"""

    # Generous TTL: warm-restart recompiles (fresh python per incarnation)
    # can outlast a tight heartbeat window on a loaded single-core box, and
    # this test's rescale is JOIN-triggered, not expiry-triggered — a member
    # expiring mid-compile would only inject spurious extra rescales (the
    # one observed flake mode under full-suite load).
    with CoordinatorServer(heartbeat_ttl_sec=30.0, task_lease_sec=30.0) as server:
        admin = server.client("admin")
        admin.add_tasks(sorted(rows))
        admin.kv_put("edl/expected_world", "2")

        def spawn(name, num_trainers):
            env = dict(os.environ)
            env["EDL_COORDINATOR_ENDPOINT"] = server.address
            env["EDL_NUM_TRAINERS"] = str(num_trainers)
            env["EDL_ENTRY"] = f"{sys.executable} {entry_py}"
            env["WORKER_NAME"] = name
            env["CKPT_DIR"] = ckpt
            env["CKPT_INTERVAL"] = "8"
            env["FILE_SHARD_ROOT"] = data_root
            env["EDL_TERMINATION_LOG"] = str(tmp_path / f"term-{name}")
            return subprocess.Popen(
                [sys.executable, "-c", launcher_src], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )

        p0, p1 = spawn("w0", 2), spawn("w1", 2)
        # mid-run: wait for committed progress at world 2, then rescale to 3
        deadline = time.time() + 240
        while time.time() < deadline:
            if int(admin.status().get("done", 0)) >= 2:
                break
            time.sleep(0.2)
        else:
            pytest.fail("world-2 phase never committed progress")
        admin.kv_put("edl/expected_world", "3")
        # Nudge like the real actuator (publish AND bump): survivors park at
        # the world-3 rendezvous NOW instead of racing to drain the queue
        # before the joiner's (load-dependent) interpreter startup — the
        # one flake mode this test had under full-suite load.
        admin.bump_epoch()
        p2 = spawn("w2", 3)

        procs = (p0, p1, p2)
        outs = [p.communicate(timeout=420) for p in procs]
        st = server.client("probe").status()

    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, f"launcher failed:\n{err[-3000:]}\n{out[-2000:]}"
    finals = []
    for out, _ in outs:
        lines = [l for l in out.splitlines() if l.startswith("METRICS ")]
        assert lines, out
        finals.append(json.loads(lines[-1][len("METRICS "):]))
    assert all(m["world"] == 3.0 for m in finals), finals
    assert int(st["queued"]) == 0 and int(st["leased"]) == 0
    assert int(st["done"]) == len(rows)


def test_shuffle_is_deterministic_and_row_preserving(tmp_path):
    """Within-shard shuffling (ref: paddle.reader.shuffle with a 100x-batch
    buffer, example/ctr/ctr/train.py:124-126) must keep replays bit-identical
    — the permutation derives from (shard id, seed) — while actually
    reordering rows and dropping none."""
    root = str(tmp_path)
    rng = np.random.default_rng(7)
    arrays = {"x": rng.standard_normal((40, 3)).astype(np.float32),
              "y": np.arange(40, dtype=np.int32)}
    write_shard(root, "sh/part-00000", arrays)
    write_shard(root, "sh/part-00001",
                {"x": arrays["x"] + 1.0, "y": arrays["y"] + 100})

    plain = FileShardSource(root=root, batch_size=8)
    shuf = FileShardSource(root=root, batch_size=8, shuffle_seed=3)

    a = np.concatenate([b["y"] for b in shuf.read("sh/part-00000")])
    b = np.concatenate([b["y"] for b in shuf.read("sh/part-00000")])
    np.testing.assert_array_equal(a, b)  # replay: bit-identical
    order = np.concatenate([b["y"] for b in plain.read("sh/part-00000")])
    assert not np.array_equal(a, order)  # actually shuffled
    assert set(a.tolist()) == set(range(40))  # no rows dropped or duplicated

    # different shards (and different seeds) get different permutations
    other = np.concatenate([b["y"] for b in shuf.read("sh/part-00001")]) - 100
    assert not np.array_equal(a, other)
    shuf2 = FileShardSource(root=root, batch_size=8, shuffle_seed=4)
    c = np.concatenate([b["y"] for b in shuf2.read("sh/part-00000")])
    assert not np.array_equal(a, c)

    # rows stay aligned across keys under the permutation
    for batch in shuf.read("sh/part-00000"):
        np.testing.assert_array_equal(
            batch["x"], arrays["x"][batch["y"]]
        )
