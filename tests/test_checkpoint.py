"""Blob-store Checkpointer robustness: torn-write fallback.

A pod dying mid-upload leaves a truncated step directory that orbax still
lists but cannot read. ``restore(step=None)`` must demote to the previous
step with an explicit log line — a stale-but-valid restore point beats a
failed recovery — while an EXPLICIT step keeps exact-step semantics.
"""

import glob
import logging
import os

import jax
import numpy as np
import pytest

from edl_tpu.models import fit_a_line
from edl_tpu.parallel import MeshSpec, build_mesh
from edl_tpu.runtime import Trainer, TrainerConfig
from edl_tpu.runtime.checkpoint import (Checkpointer, abstract_like,
                                        live_state_specs)


def _truncate_step_dir(directory, step):
    """Corrupt one orbax step dir the way a killed uploader does: every
    non-empty file cut in half. The dir still lists in ``all_steps()``."""
    for f in glob.glob(os.path.join(directory, str(step), "**", "*"),
                       recursive=True):
        if os.path.isfile(f) and os.path.getsize(f) > 0:
            with open(f, "r+b") as fh:
                fh.truncate(os.path.getsize(f) // 2)


@pytest.fixture
def two_step_checkpoint(tmp_path):
    model = fit_a_line.MODEL
    mesh = build_mesh(MeshSpec({"data": 4}), jax.devices()[:4])
    trainer = Trainer(model, mesh, TrainerConfig(optimizer="sgd"))
    rng = np.random.default_rng(0)
    state = trainer.init_state()
    ck = Checkpointer(str(tmp_path / "ck"))
    saved = {}
    for ckpt_step in (1, 2):
        state, _ = trainer.train_step(
            state, trainer.place_batch(model.synthetic_batch(rng, 16)))
        ck.save(ckpt_step, state)
        ck.wait()
        # host snapshot: the next train_step donates (deletes) these buffers
        saved[ckpt_step] = jax.device_get(state)
    yield ck, trainer, mesh, saved
    ck.close()


def test_truncated_latest_step_falls_back_to_previous(two_step_checkpoint,
                                                      caplog):
    ck, trainer, mesh, saved = two_step_checkpoint
    _truncate_step_dir(ck.directory, 2)
    assert 2 in ck._mngr.all_steps()  # still listed — the trap this guards
    fresh = trainer.init_state()
    with caplog.at_level(logging.WARNING, logger="edl_tpu.runtime.checkpoint"):
        restored = ck.restore(abstract_like(fresh), mesh,
                              live_state_specs(fresh))
    assert any("unreadable" in r.message and "falling back" in r.message
               for r in caplog.records), caplog.records
    for a, b in zip(jax.tree_util.tree_leaves(saved[1]),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(restored.step) == int(saved[1].step)


def test_explicit_step_keeps_exact_semantics(two_step_checkpoint):
    """Asking for step 2 by name must surface its corruption, not silently
    hand back step 1."""
    ck, trainer, mesh, _ = two_step_checkpoint
    _truncate_step_dir(ck.directory, 2)
    fresh = trainer.init_state()
    with pytest.raises(Exception):
        ck.restore(abstract_like(fresh), mesh, live_state_specs(fresh), step=2)


def test_all_steps_corrupt_raises(two_step_checkpoint):
    ck, trainer, mesh, _ = two_step_checkpoint
    _truncate_step_dir(ck.directory, 1)
    _truncate_step_dir(ck.directory, 2)
    fresh = trainer.init_state()
    with pytest.raises(Exception):
        ck.restore(abstract_like(fresh), mesh, live_state_specs(fresh))


def test_empty_directory_still_raises_file_not_found(tmp_path):
    ck = Checkpointer(str(tmp_path / "empty"))
    model = fit_a_line.MODEL
    mesh = build_mesh(MeshSpec({"data": 4}), jax.devices()[:4])
    trainer = Trainer(model, mesh, TrainerConfig(optimizer="sgd"))
    fresh = trainer.init_state()
    with pytest.raises(FileNotFoundError):
        ck.restore(abstract_like(fresh), mesh, live_state_specs(fresh))
    ck.close()
