"""Flash attention (Pallas) vs the dense oracle: values and gradients.

On the CPU test platform the kernels run in Pallas interpret mode — the
identical program the TPU compiles, executed by the interpreter — so these
tests validate the kernel logic itself, not a CPU reimplementation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.ops import flash_attention
from edl_tpu.parallel.ring_attention import dense_attention


def rand_qkv(rng, B, S, H, D, dtype=jnp.float32, Sk=None):
    Sk = Sk or S
    q = jnp.asarray(rng.standard_normal((B, S, H, D)), dtype)
    k = jnp.asarray(rng.standard_normal((B, Sk, H, D)), dtype)
    v = jnp.asarray(rng.standard_normal((B, Sk, H, D)), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("shape", [
    (1, 16, 1, 8),    # tiny, single block
    (2, 64, 2, 16),   # multi-head
    (1, 300, 2, 32),  # unaligned S -> padding path, multiple q blocks
])
def test_matches_dense_oracle(shape, causal):
    B, S, H, D = shape
    rng = np.random.default_rng(0)
    q, k, v = rand_qkv(rng, B, S, H, D)
    got = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    want = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_multiple_kv_blocks_accumulate():
    """S larger than one K block: the online-softmax recurrence must fold
    several visiting blocks into one normalized result."""
    rng = np.random.default_rng(1)
    q, k, v = rand_qkv(rng, 1, 384, 1, 16)
    got = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    want = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_global_offsets_match_ring_semantics():
    """A (query block, key block) pair with global offsets must mask like
    the ring layer's global-position compare: keys strictly in the query
    block's future contribute nothing."""
    rng = np.random.default_rng(2)
    S = 32
    q, k, v = rand_qkv(rng, 1, S, 1, 8, Sk=S)
    # full sequence oracle over 2 shards' worth of positions
    q_full = jnp.concatenate([q, q], axis=1)
    k_full = jnp.concatenate([k, k], axis=1)
    v_full = jnp.concatenate([v, v], axis=1)
    want = dense_attention(q_full, k_full, v_full, causal=True)

    # shard 1's queries attending shard 0's keys (all visible) ...
    m0, l0 = _merge_piece(q, k, v, q_off=S, k_off=0)
    # ... merged with shard 1's own keys (causal within the block)
    m1, l1 = _merge_piece(q, k, v, q_off=S, k_off=S)
    out = _merge((m0, l0), (m1, l1))
    np.testing.assert_allclose(np.asarray(out), np.asarray(want[:, S:]),
                               rtol=2e-5, atol=2e-5)


def _merge_piece(q, k, v, q_off, k_off):
    """Unnormalized (num, den) for one K block via the kernel's lse output:
    reconstruct num = out * den from out and lse."""
    out = flash_attention(q, k, v, causal=True, q_offset=q_off,
                          k_offset=k_off)
    # recompute lse densely for the merge (test-side only)
    import math

    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(q.shape[-1])
    qpos = q_off + jnp.arange(q.shape[1])
    kpos = k_off + jnp.arange(k.shape[1])
    s = jnp.where(kpos[None, :] <= qpos[:, None], s, -1e30)
    lse = jax.nn.logsumexp(s, axis=-1)  # (B, H, Sq)
    return out, lse


def _merge(a, b):
    (oa, la), (ob, lb) = a, b
    m = jnp.maximum(la, lb)
    wa = jnp.exp(la - m)[..., None].transpose(0, 2, 1, 3)
    wb = jnp.exp(lb - m)[..., None].transpose(0, 2, 1, 3)
    return (oa * wa + ob * wb) / (wa + wb)


def test_gradients_match_dense_oracle():
    rng = np.random.default_rng(3)
    q, k, v = rand_qkv(rng, 1, 160, 2, 16)  # unaligned: padding in bwd too

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4,
            err_msg=f"d{name} mismatch",
        )


def test_bfloat16_inputs():
    rng = np.random.default_rng(4)
    q, k, v = rand_qkv(rng, 1, 64, 2, 16, dtype=jnp.bfloat16)
    got = flash_attention(q, k, v, causal=True)
    want = dense_attention(q, k, v, causal=True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=2e-2, atol=2e-2,
    )


def test_jit_and_traced_offsets():
    """Offsets may be traced scalars (the ring passes axis_index-derived
    values); the kernel must compile once and mask correctly."""
    rng = np.random.default_rng(5)
    q, k, v = rand_qkv(rng, 1, 32, 1, 8)

    @jax.jit
    def f(q, k, v, off):
        return flash_attention(q, k, v, causal=True, q_offset=off,
                               k_offset=0)

    # q_offset >= Sk: every key visible -> equals non-causal attention
    got = f(q, k, v, jnp.int32(32))
    want = dense_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_fully_masked_rows_within_live_block():
    """Ring-offset case: k_offset slightly above q_offset leaves the block
    'live' while some query rows have NO visible keys. Those rows must
    output exactly zero (and their gradients must vanish) — the masked-
    score sentinel colliding with the running-max init used to make them
    emit mean(V)."""
    rng = np.random.default_rng(6)
    S = 16
    q, k, v = rand_qkv(rng, 1, S, 1, 8)
    off = 5  # keys start 5 positions into the queries' future
    out = flash_attention(q, k, v, causal=True, q_offset=0, k_offset=off)
    # oracle: dense attention over globally-positioned scores
    import math

    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(8)
    qpos = jnp.arange(S)
    kpos = off + jnp.arange(S)
    mask = kpos[None, :] <= qpos[:, None]
    s = jnp.where(mask[None, None], s, -1e30)
    p = jnp.where(mask[None, None], jax.nn.softmax(s, axis=-1), 0.0)
    want = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    assert np.allclose(np.asarray(out)[0, :off], 0.0)  # rows with no keys

    g = jax.grad(lambda q: jnp.sum(
        flash_attention(q, k, v, causal=True, q_offset=0, k_offset=off) ** 2
    ))(q)
    assert np.allclose(np.asarray(g)[0, :off], 0.0)
    assert bool(np.isfinite(np.asarray(g)).all())


def test_randomized_shapes_and_offsets_property():
    """Property sweep over the input space the ring can produce: random
    (B, Sq, Sk, H, D), random global offsets (including key blocks fully
    or partially in the queries' future), values AND gradients vs a
    globally-positioned dense oracle."""
    import math

    rng = np.random.default_rng(42)
    for trial in range(8):
        B = int(rng.integers(1, 3))
        H = int(rng.integers(1, 3))
        D = int(rng.choice([4, 8, 16]))
        Sq = int(rng.integers(3, 70))
        Sk = int(rng.integers(3, 70))
        q_off = int(rng.integers(0, 50))
        k_off = int(rng.integers(0, 50))
        causal = bool(rng.integers(0, 2))
        q, k, v = rand_qkv(rng, B, Sq, H, D, Sk=Sk)

        def oracle(q, k, v):
            s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(D)
            if causal:
                qpos = q_off + jnp.arange(Sq)
                kpos = k_off + jnp.arange(Sk)
                mask = kpos[None, :] <= qpos[:, None]
                s = jnp.where(mask[None, None], s, -1e30)
                p = jnp.where(mask[None, None],
                              jax.nn.softmax(s, axis=-1), 0.0)
            else:
                p = jax.nn.softmax(s, axis=-1)
            return jnp.einsum("bhqk,bkhd->bqhd", p, v)

        got = flash_attention(q, k, v, causal=causal,
                              q_offset=q_off, k_offset=k_off)
        want = oracle(q, k, v)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=3e-5, atol=3e-5,
            err_msg=f"trial {trial}: B={B} Sq={Sq} Sk={Sk} H={H} D={D} "
                    f"qo={q_off} ko={k_off} causal={causal}",
        )
        gf = jax.grad(lambda q: jnp.sum(flash_attention(
            q, k, v, causal=causal, q_offset=q_off, k_offset=k_off) ** 2))(q)
        gd = jax.grad(lambda q: jnp.sum(oracle(q, k, v) ** 2))(q)
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gd), rtol=3e-4, atol=3e-4,
            err_msg=f"grad trial {trial}",
        )
