"""Ring attention vs dense oracle on the virtual 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.parallel import MeshSpec, build_mesh
from edl_tpu.parallel.ring_attention import dense_attention, ring_attention


def _qkv(rng, B=2, S=16, H=4, D=8, dtype=np.float32):
    q = rng.standard_normal((B, S, H, D)).astype(dtype)
    k = rng.standard_normal((B, S, H, D)).astype(dtype)
    v = rng.standard_normal((B, S, H, D)).astype(dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("axes", [{"seq": 8}, {"data": 2, "seq": 4}, {"data": 2, "seq": 2, "model": 2}])
def test_matches_dense(causal, axes):
    mesh = build_mesh(MeshSpec(axes))
    q, k, v = _qkv(np.random.default_rng(0))
    want = dense_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal)
    got = ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5)


def test_matches_dense_under_jit_with_sharded_inputs():
    mesh = build_mesh(MeshSpec({"data": 2, "seq": 4}))
    q, k, v = _qkv(np.random.default_rng(1))
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P("data", "seq", None, None))
    qs, ks, vs = (jax.device_put(jnp.asarray(x), sharding) for x in (q, k, v))
    f = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh))
    got = f(qs, ks, vs)
    want = dense_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5)


def test_gradients_match_dense():
    mesh = build_mesh(MeshSpec({"seq": 4, "model": 2}))
    q, k, v = _qkv(np.random.default_rng(2), B=1, S=8, H=2, D=4)
    q, k, v = jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd), atol=1e-4, rtol=1e-4)


def test_bfloat16_inputs():
    mesh = build_mesh(MeshSpec({"seq": 4}), jax.devices()[:4])
    q, k, v = _qkv(np.random.default_rng(3))
    qb, kb, vb = (jnp.asarray(x, jnp.bfloat16) for x in (q, k, v))
    got = ring_attention(qb, kb, vb, mesh)
    assert got.dtype == jnp.bfloat16
    want = dense_attention(qb, kb, vb)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), atol=3e-2, rtol=3e-2
    )


def test_no_seq_axis_falls_back_dense():
    mesh = build_mesh(MeshSpec({"data": 8}))
    q, k, v = _qkv(np.random.default_rng(4))
    got = ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mesh)
    want = dense_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("axes", [{"seq": 4, "data": 2}, {"seq": 8}])
def test_flash_ring_matches_dense(causal, axes):
    """Ring with the Pallas kernel as the per-hop block engine: per-hop
    (out, lse) pairs merged associatively must equal the dense oracle."""
    mesh = build_mesh(MeshSpec(axes))
    q, k, v = _qkv(np.random.default_rng(10))
    want = dense_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                           causal=causal)
    got = ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                         mesh, causal=causal, flash=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_flash_ring_gradients_match_dense():
    """Gradients flow through the kernel's custom VJP on BOTH outputs (the
    merge consumes lse, so its cotangent reaches dq/dk through the folded
    delta term)."""
    mesh = build_mesh(MeshSpec({"seq": 4, "data": 2}))
    q, k, v = _qkv(np.random.default_rng(11))
    q, k, v = jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, flash=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v) ** 2)

    gf = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4,
            err_msg=f"d{name} mismatch",
        )


def test_flash_ring_bfloat16_matches_einsum_ring():
    """bf16 hop precision: both ring engines carry f32 accumulators across
    hops and downcast once, so they must agree tightly even at bf16 input
    precision (the flash engine's partials stay f32 via return_lse)."""
    mesh = build_mesh(MeshSpec({"seq": 4, "data": 2}))
    q, k, v = _qkv(np.random.default_rng(12))
    qb = jnp.asarray(q, jnp.bfloat16)
    kb = jnp.asarray(k, jnp.bfloat16)
    vb = jnp.asarray(v, jnp.bfloat16)
    einsum_ring = ring_attention(qb, kb, vb, mesh, flash=False)
    flash_ring = ring_attention(qb, kb, vb, mesh, flash=True)
    assert flash_ring.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(flash_ring, np.float32), np.asarray(einsum_ring, np.float32),
        rtol=1e-2, atol=1e-2,
    )


def test_no_seq_axis_flash_runs_locally_under_jit():
    """flash=True with no seq axis: the kernel must run inside a shard_map
    on each device's batch shard (pallas has no SPMD partitioning rule;
    outside the manual region XLA would replicate sharded inputs)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = build_mesh(MeshSpec({"data": 8}))
    q, k, v = _qkv(np.random.default_rng(13), B=8)
    sh = NamedSharding(mesh, P("data"))
    qs, ks, vs = (jax.device_put(jnp.asarray(x), sh) for x in (q, k, v))
    f = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh, flash=True))
    got = f(qs, ks, vs)
    want = dense_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_no_seq_axis_flash_indivisible_batch_falls_back_global():
    """B=1 on a data=8 mesh: shard_map's divisibility would reject it; the
    entrypoint must fall back to the global kernel call and stay correct."""
    mesh = build_mesh(MeshSpec({"data": 8}))
    q, k, v = _qkv(np.random.default_rng(14), B=1)
    got = ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                         mesh, flash=True)
    want = dense_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)
