"""Autoscaler → coordinator actuation: the elastic story's two halves, joined.

VERDICT r2 gap #2's done-criterion: an e2e test where the AUTOSCALER (not a
test helper) rescales a live 2-process job to 3 and the workers warm-restart
into the new world (ref actuation: `pkg/autoscaler.go:339-376`; ref recovery
narrative: `doc/boss_tutorial.md:229-241`).
"""

import json
import os
import sys
import time

import pytest

from conftest import multiprocess_on_cpu
from edl_tpu.api.quantity import ResourceList
from edl_tpu.api.types import TrainingJob
from edl_tpu.api.validation import normalize
from edl_tpu.controller.actuation import EXPECTED_WORLD_KEY, CoordinatorActuator
from edl_tpu.controller.autoscaler import Autoscaler, AutoscalerConfig
from edl_tpu.controller.cluster import NodeInfo
from edl_tpu.controller.jobparser import parse_to_trainer
from edl_tpu.controller.process_cluster import ProcessCluster
from edl_tpu.coordinator import CoordinatorServer, InProcessCoordinator
from edl_tpu.coordinator.server import ensure_built, free_port

from tests.test_multihost import REPO, WORKER_SRC

LAUNCHER_SRC = """
import os, sys
sys.path.insert(0, {repo!r})
from edl_tpu.launcher.launch import LaunchContext, start_trainer
ctx = LaunchContext.from_env()
sys.exit(start_trainer(ctx))
"""


def test_actuator_publishes_world_and_nudges_epoch():
    """Unit: publish lands under EXPECTED_WORLD_KEY; nudge bumps the epoch
    and releases parked sync waiters (via the real wire protocol)."""
    ensure_built()
    with CoordinatorServer() as server:
        actuator = CoordinatorActuator()
        actuator.set_endpoint("job", "127.0.0.1", server.port)
        assert actuator.publish_expected_world("job", 3)
        probe = server.client("probe")
        assert probe.kv_get(EXPECTED_WORLD_KEY) == "3"
        before = probe.epoch()
        assert actuator.nudge("job")
        assert probe.epoch() == before + 1
        # unknown job: both no-op cleanly
        assert not actuator.publish_expected_world("ghost", 2)
        assert not actuator.nudge("ghost")


def test_actuator_tracks_endpoint_from_spec():
    job = normalize(TrainingJob.from_dict({
        "metadata": {"name": "j1", "namespace": "ns"},
        "spec": {"port": 7200, "trainer": {"min_instance": 1, "max_instance": 2}},
    }))
    actuator = CoordinatorActuator()
    actuator.track(job)
    assert actuator._endpoints["j1"] == ("j1-coordinator.ns", 7200)
    # an explicit endpoint registered first wins over track()
    actuator2 = CoordinatorActuator()
    actuator2.set_endpoint("j1", "127.0.0.1", 9999)
    actuator2.track(job)
    assert actuator2._endpoints["j1"] == ("127.0.0.1", 9999)


def test_inprocess_bump_epoch_matches_native():
    coord = InProcessCoordinator()
    c = coord.client("w0")
    c.register()
    before = int(c.register()["epoch"])
    assert c.bump_epoch() == before + 1  # int, like CoordinatorClient's


@multiprocess_on_cpu
def test_autoscaler_rescales_live_two_process_job_to_three(tmp_path):
    """Full loop: ProcessCluster runs 2 real trainer processes against a real
    coordinator; the Autoscaler sees free chips, decides 2→3, publishes
    edl/expected_world, actuates the provider (3rd process spawns), nudges the
    epoch — and every worker warm-restarts into a world-3 job that drains the
    queue."""
    ensure_built()
    jax_port = free_port()
    ckpt = str(tmp_path / "ck")

    entry_py = tmp_path / "entry.py"
    entry_py.write_text(WORKER_SRC.format(repo=REPO, jax_port=jax_port))
    launcher_py = tmp_path / "launcher.py"
    launcher_py.write_text(LAUNCHER_SRC.format(repo=REPO))

    with CoordinatorServer(heartbeat_ttl_sec=5.0) as server:
        admin = server.client("admin")
        # Enough shards that the world-2 phase outlives worker bring-up, few
        # enough that world 3 drains them within the test budget on one core.
        admin.add_tasks([f"mh/part-{i:05d}" for i in range(120)])

        job = normalize(TrainingJob.from_dict({
            "metadata": {"name": "asjob"},
            "spec": {
                "fault_tolerant": True,
                "tpu": {"chips_per_trainer": 4},
                "trainer": {
                    "min_instance": 2,
                    "max_instance": 3,
                    "entrypoint": f"{sys.executable} {launcher_py}",
                    "resources": {"requests": {"cpu": 1}},
                    "env": {
                        "EDL_COORDINATOR_ENDPOINT": server.address,
                        "EDL_ENTRY": f"{sys.executable} {entry_py}",
                        "CKPT_DIR": ckpt,
                        "BATCHES_PER_SHARD": "15",
                        # Commit early/often: the progress gate below watches
                        # the done-counter, which completion-lag ties to
                        # checkpoints (multihost.py checkpoint_and_commit).
                        "CKPT_INTERVAL": "60",
                        "EDL_TERMINATION_LOG": str(tmp_path / "term"),
                    },
                },
            },
        }))

        # 3 hosts x 4 chips: room for exactly 3 trainers.
        cluster = ProcessCluster(
            [NodeInfo(name=f"h{i}",
                      allocatable=ResourceList.make({"cpu": 16, "tpu": 4}))
             for i in range(3)],
            log_dir=str(tmp_path / "logs"),
        )
        trainer = parse_to_trainer(job)
        # Worker identity comes from EDL_POD_NAME, unique per spawned pod.
        scale_records = []
        try:
            cluster.create_role(job.name, "trainer", 2, trainer.requests,
                                trainer.limits, workload=trainer)

            # wait for real progress at world 2
            deadline = time.time() + 240
            while time.time() < deadline:
                if int(admin.status().get("done", 0)) >= 2:
                    break
                time.sleep(0.5)
            else:
                pytest.fail("world-2 job never made progress")

            # THE AUTOSCALER decides and actuates the rescale.
            actuator = CoordinatorActuator()
            actuator.set_endpoint(job.name, "127.0.0.1", server.port)
            scaler = Autoscaler(cluster, AutoscalerConfig(loop_seconds=0.5))
            scaler.actuator = actuator
            scaler.on_scaled = lambda name, rec: scale_records.append((name, rec))
            scaler.on_add(job)
            scaler.start()
            try:
                deadline = time.time() + 60
                while time.time() < deadline and not scale_records:
                    time.sleep(0.2)
            finally:
                scaler.stop()
            assert scale_records, "autoscaler never actuated"
            name, record = scale_records[0]
            assert name == "asjob"
            assert (record.from_replicas, record.to_replicas) == (2, 3)
            assert admin.kv_get(EXPECTED_WORLD_KEY) == "3"

            # all three launchers run to completion at world 3
            cluster.wait_all(timeout=420)
            pods = cluster.job_pods(job.name, "trainer")
            assert len(pods) == 3
            assert all(p.phase == "Succeeded" for p in pods), [
                (p.name, p.phase) for p in pods
            ]
            st = admin.status()
            assert int(st["queued"]) == 0 and int(st["leased"]) == 0
        finally:
            cluster.shutdown()

    # every worker's final incarnation reports world=3
    finals = {}
    for log_file in (tmp_path / "logs").iterdir():
        lines = [l for l in log_file.read_text().splitlines()
                 if l.startswith("METRICS ")]
        if lines:
            finals[log_file.name] = json.loads(lines[-1][len("METRICS "):])
    assert len(finals) == 3, finals.keys()
    assert all(m["world"] == 3.0 for m in finals.values()), finals
