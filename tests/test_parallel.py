"""Mesh, sharding, and sharded-embedding tests on the 8-device CPU harness."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from edl_tpu.parallel import (
    MeshSpec,
    ShardedEmbedding,
    build_mesh,
    local_mesh,
    shard_batch,
)


def test_mesh_spec_for_job():
    spec = MeshSpec.for_job({"data": 4}, num_trainers=2)
    assert spec.axes == {"data": 8}
    assert spec.size() == 8
    spec2 = MeshSpec.for_job({"data": 2, "model": 2}, num_trainers=2)
    assert spec2.axes == {"data": 4, "model": 2}


def test_build_mesh_shapes():
    mesh = build_mesh(MeshSpec({"data": 4, "model": 2}))
    assert mesh.shape == {"data": 4, "model": 2}
    assert mesh.axis_names == ("data", "model")
    with pytest.raises(ValueError):
        build_mesh(MeshSpec({"data": 3}))  # 3 != 8 devices


def test_shard_batch_places_on_data_axis():
    mesh = local_mesh()
    batch = {"x": np.ones((16, 4), np.float32), "y": np.zeros((16,), np.float32)}
    placed = shard_batch(batch, mesh)
    assert placed["x"].sharding.spec == P("data")
    np.testing.assert_allclose(np.asarray(placed["x"]), batch["x"])


def _reference_lookup(table, ids):
    return np.asarray(table)[np.asarray(ids)]


def test_sharded_embedding_same_axis_matches_dense():
    mesh = local_mesh()  # data: 8
    emb = ShardedEmbedding(vocab_size=64, features=16, shard_axis="data", batch_axis="data")
    table = emb.init(jax.random.PRNGKey(0), mesh)
    assert table.shape == (256, 16)  # padded to the rescale-stable multiple
    ids = jnp.arange(32) * 2 % 64
    ids = jax.device_put(ids, jax.sharding.NamedSharding(mesh, P("data")))
    out = jax.jit(lambda t, i: emb.apply(mesh, t, i))(table, ids)
    np.testing.assert_allclose(
        np.asarray(out), _reference_lookup(table, ids), rtol=1e-6
    )


def test_sharded_embedding_cross_axis_matches_dense():
    mesh = build_mesh(MeshSpec({"data": 2, "expert": 4}))
    emb = ShardedEmbedding(vocab_size=100, features=8, shard_axis="expert", batch_axis="data")
    table = emb.init(jax.random.PRNGKey(1), mesh)
    assert table.shape == (256, 8)  # padded to the rescale-stable multiple
    ids = jnp.array([[0, 5, 99], [17, 42, 63]] * 4, dtype=jnp.int32)  # (8, 3)
    out = jax.jit(lambda t, i: emb.apply(mesh, t, i))(table, ids)
    assert out.shape == (8, 3, 8)
    np.testing.assert_allclose(
        np.asarray(out), _reference_lookup(table, ids), rtol=1e-6
    )


def test_sharded_embedding_gradients_flow():
    """Backward = scatter-add through the collective (the sparse grad push)."""
    mesh = local_mesh()
    emb = ShardedEmbedding(vocab_size=32, features=4)
    table = emb.init(jax.random.PRNGKey(2), mesh)
    ids = jnp.arange(16, dtype=jnp.int32)  # each row hit once in first half

    def loss(t):
        return emb.apply(mesh, t, ids).sum()

    g = jax.jit(jax.grad(loss))(table)
    np.testing.assert_allclose(np.asarray(g[:16]), 1.0)
    np.testing.assert_allclose(np.asarray(g[16:]), 0.0)


def test_sharded_embedding_vocab_padding():
    mesh = local_mesh()  # 8 shards
    emb = ShardedEmbedding(vocab_size=30, features=4)
    table = emb.init(jax.random.PRNGKey(3), mesh)
    assert table.shape == (256, 4)  # padded to the rescale-stable multiple


# -- topology-aware device arrangement (VERDICT r3 weak #4) --------------------


class _FakeDev:
    """Simulated multi-host device: what arrange_devices keys on."""

    def __init__(self, id, process_index):
        self.id = id
        self.process_index = process_index
        self.coords = None

    def __repr__(self):
        return f"d{self.id}@p{self.process_index}"


def test_arrange_devices_keeps_model_axis_within_process():
    """On a simulated 4-host x 2-chip set, the innermost (model) axis must
    never straddle hosts — tensor-parallel collectives are latency-critical
    and belong on the fastest interconnect; only the outermost (data) axis
    may span the DCN tier."""
    from edl_tpu.parallel.mesh import arrange_devices

    devs = [_FakeDev(id=h * 2 + c, process_index=h) for h in range(4) for c in range(2)]
    # adversarial enumeration order: interleaved across hosts — a plain
    # reshape would pair devices from DIFFERENT hosts on the model axis
    shuffled = devs[::2] + devs[1::2]
    grid = arrange_devices(shuffled, (4, 2))  # (data, model)
    for row in grid:  # each model-axis pair: same process
        assert row[0].process_index == row[1].process_index, grid
    # data axis actually spans all hosts
    assert {grid[i, 0].process_index for i in range(4)} == {0, 1, 2, 3}


def test_arrange_devices_three_axes_process_locality():
    """(data=2, seq=2, model=2) over 2 hosts x 4 chips: model AND seq stay
    host-local; data spans hosts."""
    from edl_tpu.parallel.mesh import arrange_devices

    devs = [_FakeDev(id=h * 4 + c, process_index=h) for h in range(2) for c in range(4)]
    grid = arrange_devices(list(reversed(devs)), (2, 2, 2))
    for i in range(2):
        procs = {grid[i, j, k].process_index for j in range(2) for k in range(2)}
        assert len(procs) == 1, grid  # one host per data slice
    assert grid[0, 0, 0].process_index != grid[1, 0, 0].process_index


def test_arrange_devices_size_mismatch_fails_loudly():
    from edl_tpu.parallel.mesh import arrange_devices

    with pytest.raises(ValueError, match="needs 4 devices"):
        arrange_devices([_FakeDev(0, 0)], (2, 2))


def test_build_mesh_unchanged_on_single_process_cpu():
    """Real path: single-process virtual devices sort to enumeration order,
    so existing single-host meshes are unchanged."""
    from edl_tpu.parallel import MeshSpec, build_mesh

    devs = jax.devices()
    mesh = build_mesh(MeshSpec({"data": len(devs)}), devs)
    assert list(mesh.devices.flat) == devs
