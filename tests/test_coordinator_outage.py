"""Coordinator-outage resilience: retry policy, outbox, chaos proxy, restart.

The executable half of ``doc/robustness.md``: every row of the
failure→recovery table has a test here. Fast cases run in tier-1 and are
marked ``chaos``; the process-kill soak at the bottom is ``slow + chaos``
(``make chaos`` runs everything).

Determinism: every fault sequence comes from seeded RNGs — the
``RetryPolicy`` seed fixes the backoff jitter, the ``ChaosProxy`` seed
fixes which chunks get delayed/reset/dropped. A failing run replays
bit-identically.
"""

import sys
import threading
import time

import pytest

from edl_tpu.coordinator import (
    CoordinatorAuthError,
    CoordinatorServer,
    CoordinatorUnreachable,
    InProcessCoordinator,
    Outbox,
    OutboxClient,
    RetryPolicy,
)
from edl_tpu.coordinator.client import CoordinatorClient
from edl_tpu.coordinator.server import CoordinatorSupervisor, free_port
from edl_tpu.testing import ChaosProxy

from tests.test_coordinator import has_toolchain

needs_native = pytest.mark.skipif(
    not has_toolchain(), reason="native toolchain unavailable"
)

# Every outage test also rides the sanitizer lane (`make tsan-smoke`): the
# kill/restart/partition interleavings here are exactly what TSan should see.
pytestmark = [pytest.mark.chaos, pytest.mark.sanitizer]


# -- retry policy --------------------------------------------------------------


def test_retry_policy_deterministic_and_bounded():
    a = RetryPolicy(seed=7)
    b = RetryPolicy(seed=7)
    sa, sb = a.sleeps(), b.sleeps()
    seq = [next(sa) for _ in range(8)]
    assert seq == [next(sb) for _ in range(8)]
    # jittered exponential: positive, bounded by max_backoff * (1 + jitter)
    assert all(0 < s <= a.max_backoff * (1.0 + a.jitter) for s in seq)
    # a different seed jitters differently
    sc = RetryPolicy(seed=8).sleeps()
    assert seq != [next(sc) for _ in range(8)]


def test_retry_policy_backoff_grows():
    seq = []
    gen = RetryPolicy(seed=1, jitter=0.0).sleeps()
    for _ in range(5):
        seq.append(next(gen))
    assert seq == sorted(seq)  # no jitter -> pure exponential up to the cap


# -- typed errors / fail-fast auth ---------------------------------------------


def test_unreachable_raised_after_deadline():
    dead = free_port()
    t0 = time.monotonic()
    with pytest.raises(CoordinatorUnreachable):
        CoordinatorClient(port=dead, connect_timeout=0.5,
                          retry=RetryPolicy(deadline=0.5, seed=0))
    assert time.monotonic() - t0 < 5.0


@needs_native
def test_auth_error_fails_fast_no_retry():
    with CoordinatorServer(auth_token="right-secret") as server:
        c = CoordinatorClient(port=server.port, worker="w0",
                              token="wrong-secret",
                              retry=RetryPolicy(deadline=30.0, seed=0))
        t0 = time.monotonic()
        with pytest.raises(CoordinatorAuthError):
            c.register()
        # fail-fast: no backoff loop burned the 30 s retry budget
        assert time.monotonic() - t0 < 5.0
        assert c.retry_count == 0
        c.close()


@needs_native
def test_barrier_and_sync_distinguish_unreachable_from_timeout():
    with CoordinatorServer() as server:
        c = server.client("w0")
        c.register()
        late = server.client("w-late")
        late.register()  # a member that never reaches the sync point
        # live coordinator, missing peers: a genuine rendezvous timeout
        assert c.barrier("b", count=2, timeout=0.4) == {
            "ok": False, "error": "timeout"}
        epoch = int(c.status()["epoch"])
        reply = c.sync(epoch, timeout=0.4)
        assert reply.get("ok") is False
        assert reply.get("error") == "timeout" or reply.get("resync"), reply
        late.close()
        c.close()
        # second client outlives the server (the `with` exit stops it)
        c2 = CoordinatorClient(port=server.port, worker="w1",
                               retry=RetryPolicy(deadline=0.5, seed=0))
        c2.register()
    # dead coordinator: transport failure must NOT masquerade as "timeout"
    assert c2.barrier("b", count=2, timeout=0.4) == {
        "ok": False, "error": "unreachable"}
    assert c2.sync(0, timeout=0.4) == {"ok": False, "error": "unreachable"}
    c2.close()


# -- chaos proxy: transport faults ---------------------------------------------


@needs_native
def test_client_retries_through_proxy_resets():
    with CoordinatorServer() as server:
        with ChaosProxy(server.port, seed=11, reset_prob=0.2) as proxy:
            c = CoordinatorClient(port=proxy.port, worker="w0",
                                  retry=RetryPolicy(deadline=30.0, seed=11))
            c.register()
            for i in range(40):
                c.kv_put(f"k{i}", str(i))
            for i in range(40):
                assert c.kv_get(f"k{i}") == str(i)
            c.close()
        assert proxy.stats["resets"] > 0, proxy.stats
        assert proxy.stats["connections"] > 1  # re-dialed after resets


@needs_native
def test_chaos_proxy_is_deterministic():
    """Same seed + same request sequence -> same injected fault counts."""
    stats = []
    for _ in range(2):
        with CoordinatorServer() as server:
            with ChaosProxy(server.port, seed=5, reset_prob=0.15) as proxy:
                c = CoordinatorClient(port=proxy.port, worker="w0",
                                      retry=RetryPolicy(deadline=30.0, seed=5))
                c.register()
                for i in range(25):
                    c.kv_put(f"k{i}", "v")
                c.close()
                stats.append((proxy.stats["resets"], proxy.stats["drops"]))
    assert stats[0] == stats[1], stats


@needs_native
def test_partition_buffers_mutations_then_replays():
    """Outbox degraded mode end to end: mutations during a partition buffer,
    heal replays them in order, and the replayed completion is recorded
    exactly once (a second complete after reconnect replies duplicate)."""
    with CoordinatorServer(task_lease_sec=60.0, heartbeat_ttl_sec=60.0) as server:
        with ChaosProxy(server.port, seed=1) as proxy:
            raw = CoordinatorClient(port=proxy.port, worker="w0",
                                    retry=RetryPolicy(deadline=1.0, seed=1))
            c = OutboxClient(raw)
            c.register()
            c.add_tasks(["s0", "s1"])
            t = c.acquire_task()
            assert t == "s0"

            proxy.partition()
            reply = c.complete_task("s0")
            assert reply.get("buffered") is True
            c.kv_put("during-outage", "x")
            assert len(c.outbox) == 2
            assert c.unreachable and c.outage_seconds() >= 0.0
            # reads fail soft: the lease loop's poll path, not a crash
            soft = c.acquire()
            assert soft.get("task") is None and soft.get("unreachable")

            proxy.heal()
            # first successful guarded call replays the outbox
            deadline = time.monotonic() + 20.0
            while len(c.outbox) and time.monotonic() < deadline:
                c.heartbeat()
                time.sleep(0.05)
            assert len(c.outbox) == 0
            assert not c.unreachable

            st = c.status()
            assert int(st["done"]) == 1
            assert c.kv_get("during-outage") == "x"
            # duplicate completion after reconnect: idempotent, still done=1
            again = c.complete_task("s0")
            assert again.get("ok") and again.get("duplicate")
            assert int(c.status()["done"]) == 1
            summ = c.summary()
            assert summ["outages"] >= 1.0 and summ["replayed_ops"] >= 2.0
            raw.close()


# -- server-side idempotence / dedup -------------------------------------------


@needs_native
def test_complete_task_idempotent_and_requeue_tolerant():
    with CoordinatorServer() as server:
        c = server.client("w0")
        c.register()
        c.add_tasks(["a", "b"])
        assert c.acquire_task() == "a"
        assert c.complete_task("a").get("ok")
        dup = c.complete_task("a")
        assert dup.get("ok") and dup.get("duplicate")
        # requeued-but-unleased: lease dropped (fail_task), completion still
        # lands — the worker only completes after a covering checkpoint
        assert c.acquire_task() == "b"
        c.fail_task("b")
        back = c.complete_task("b")
        assert back.get("ok") and back.get("requeued")
        st = c.status()
        assert int(st["done"]) == 2 and int(st["queued"]) == 0
        # a task this run never heard of is still an error
        assert not c.complete_task("never-added").get("ok")
        c.close()


@needs_native
def test_acquire_req_id_dedup_returns_same_lease():
    with CoordinatorServer() as server:
        c = server.client("w0")
        c.register()
        c.add_tasks(["t0", "t1"])
        first = c.call("acquire_task", req_id="lost-reply-1")
        assert first["task"] == "t0"
        retry = c.call("acquire_task", req_id="lost-reply-1")
        assert retry["task"] == "t0" and retry.get("duplicate")
        st = c.status()
        assert int(st["leased"]) == 1, st  # no zombie second lease
        fresh = c.call("acquire_task", req_id="lost-reply-2")
        assert fresh["task"] == "t1"
        c.close()


@needs_native
def test_kv_incr_op_id_dedup_survives_restart(tmp_path):
    state = str(tmp_path / "state.jsonl")
    server = CoordinatorServer(state_file=state, run_id="r1")
    server.start()
    try:
        c = server.client("w0")
        assert c.call("kv_incr", key="budget", delta=1,
                      op_id="op-1")["value"] == 1
        # same op replayed against the SAME incarnation: no double count
        rep = c.call("kv_incr", key="budget", delta=1, op_id="op-1")
        assert rep["value"] == 1 and rep.get("duplicate")
        c.close()

        server.kill()  # SIGKILL: only the journal survives
        server.restart()
        c = server.client("w0")
        # replay across the restart: the marker was journaled with the value
        rep = c.call("kv_incr", key="budget", delta=1, op_id="op-1")
        assert rep["value"] == 1 and rep.get("duplicate")
        assert c.call("kv_incr", key="budget", delta=1,
                      op_id="op-2")["value"] == 2
        c.close()
    finally:
        server.stop()


def test_outbox_replay_stops_on_transport_failure():
    """A mid-replay outage keeps the tail buffered (nothing lost)."""

    class Flaky:
        def __init__(self):
            self.calls = 0

        def call(self, op, **fields):
            self.calls += 1
            if self.calls > 1:
                raise CoordinatorUnreachable("mid-replay outage")
            return {"ok": True}

    ob = Outbox()
    ob.add("complete_task", task="a")
    ob.add("complete_task", task="b")
    ob.add("kv_put", key="k", value="v")
    flaky = Flaky()
    assert ob.replay(flaky) == 1
    assert len(ob) == 2
    assert ob.pending()[0] == ("complete_task", {"task": "b"})


def test_outbox_client_over_inprocess_coordinator():
    """The facade composes with the in-process twin (same call surface)."""
    coord = InProcessCoordinator(task_lease_sec=30.0)
    c = OutboxClient(coord.client("w0"))
    c.register()
    c.add_tasks(["x"])
    assert c.acquire_task() == "x"
    assert c.complete_task("x").get("ok")
    dup = c.complete_task("x")
    assert dup.get("ok") and dup.get("duplicate")
    assert c.summary()["outages"] == 0.0


# -- supervision ---------------------------------------------------------------


@needs_native
def test_supervisor_restarts_killed_coordinator(tmp_path):
    state = str(tmp_path / "state.jsonl")
    server = CoordinatorServer(state_file=state, run_id="sup")
    server.start()
    sup = CoordinatorSupervisor(server, poll_interval=0.05)
    sup.start()
    try:
        c = server.client("seed")
        c.add_tasks(["t0", "t1"])
        epoch_before = int(c.status()["epoch"])
        c.close()

        server.kill()
        deadline = time.monotonic() + 20.0
        revived = {}
        while time.monotonic() < deadline:
            try:
                probe = server.client("probe")
                revived = probe.status()
                probe.close()
                if revived.get("ok"):
                    break
            except Exception:  # edl: noqa[EDL005] probe loop: any transport error just means "not yet back"
                pass
            time.sleep(0.1)
        assert revived.get("ok"), "supervisor never brought the coordinator back"
        # journal resumed (queue intact), epoch bumped by the restart
        assert int(revived["queued"]) == 2
        assert int(revived["epoch"]) > epoch_before
        # the counter increments on the watch thread AFTER the server is
        # observably back (like k8s status lag) — poll, don't snapshot
        while sup.summary()["restarts"] < 1.0 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert sup.summary()["restarts"] >= 1.0
    finally:
        sup.stop()


def test_process_cluster_restarts_failed_coordinator_role():
    from edl_tpu.api.quantity import ResourceList
    from edl_tpu.controller.cluster import NodeInfo
    from edl_tpu.controller.process_cluster import ProcessCluster

    class W:
        entrypoint = f"{sys.executable} -c 'import time; time.sleep(600)'"
        env = {}
        workspace = ""

    cluster = ProcessCluster(
        [NodeInfo(name="n0", allocatable=ResourceList.make({"cpu": 8}))])
    try:
        one_cpu = ResourceList.make({"cpu": 1})
        cluster.create_role("job", "coordinator", 1, one_cpu, one_cpu, W())
        pods = [p for p in cluster.pods if p.info.role == "coordinator"]
        assert len(pods) == 1 and pods[0].info.phase == "Running"
        cluster.kill_pod(pods[0].info.name)
        deadline = time.monotonic() + 10.0
        while (cluster.job_pods("job", "coordinator")[0].phase != "Failed"
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert cluster.job_pods("job", "coordinator")[0].phase == "Failed"
        assert cluster.restart_failed("job", role="coordinator") == 1
        replacement = cluster.job_pods("job", "coordinator")
        assert len(replacement) == 1 and replacement[0].phase == "Running"
        assert replacement[0].name != pods[0].info.name
    finally:
        cluster.shutdown()


# -- end-to-end: elastic worker rides real outages -----------------------------


def _counting_source(model, batch_size=8, batches_per_shard=4):
    from edl_tpu.runtime.data import SyntheticShardSource

    counts = {}

    class Counting(SyntheticShardSource):
        def read(self, shard):
            counts[shard] = counts.get(shard, 0) + 1
            return super().read(shard)

    return Counting(model, batch_size=batch_size,
                    batches_per_shard=batches_per_shard), counts


@needs_native
def test_elastic_worker_rides_5s_partition_exactly_once(tmp_path):
    """The seeded-partition acceptance case: a 5 s network partition mid-run
    neither kills the worker nor loses/duplicates a shard — every shard
    trains exactly once, the lease ledger balances, and the outage shows up
    in the worker's telemetry."""
    import jax

    from edl_tpu.models import fit_a_line
    from edl_tpu.runtime.data import shard_names
    from edl_tpu.runtime.elastic import ElasticConfig, ElasticWorker
    from edl_tpu.runtime.train_loop import TrainerConfig

    model = fit_a_line.MODEL
    shards = shard_names("px", 5)
    # Leases and membership must outlive the 5 s partition: TTLs at 60 s so
    # the only thing the outage interrupts is bookkeeping.
    with CoordinatorServer(task_lease_sec=60.0, heartbeat_ttl_sec=60.0) as server:
        admin = server.client("admin")
        admin.add_tasks(shards)

        with ChaosProxy(server.port, seed=42) as proxy:
            raw = CoordinatorClient(port=proxy.port, worker="w0",
                                    retry=RetryPolicy(deadline=2.0, seed=42))
            source, counts = _counting_source(model)
            cfg = ElasticConfig(
                checkpoint_dir=str(tmp_path / "ck"),
                checkpoint_interval=4,          # ~one shard per commit
                heartbeat_interval=0.0,         # poll the epoch every batch
                outage_budget=60.0,
                trainer=TrainerConfig(optimizer="sgd", learning_rate=0.05),
            )
            worker = ElasticWorker(model, raw, source, cfg,
                                   device_planner=lambda w: jax.devices())

            def chaos():
                while worker.steps_done < 3 and not done_flag.is_set():
                    time.sleep(0.02)
                proxy.partition()
                time.sleep(5.0)
                proxy.heal()

            done_flag = threading.Event()
            t = threading.Thread(target=chaos, daemon=True)
            t.start()
            try:
                metrics = worker.run()
            finally:
                done_flag.set()
                t.join(timeout=10)

        st = admin.status()
        admin.close()
    # ledger balanced: nothing lost, nothing leaked
    assert int(st["done"]) == len(shards)
    assert int(st["queued"]) == 0 and int(st["leased"]) == 0
    # exactly once: no shard read twice (leases outlived the partition)
    assert counts == {s: 1 for s in shards}, counts
    # the outage actually happened and was ridden out, not rescaled through
    assert metrics["outage_outages"] >= 1.0, metrics
    assert metrics["rescales"] == 0.0, metrics


@needs_native
def test_elastic_worker_survives_coordinator_kill_and_restart(tmp_path):
    """The SIGKILL acceptance case: the coordinator dies mid-run and comes
    back (same state file, same run_id). The worker rides the outage on its
    retry policy, adopts the restarted coordinator's bumped epoch without a
    spurious rescale, replays buffered completions, and the job converges
    with every shard trained exactly once."""
    import jax

    from edl_tpu.models import fit_a_line
    from edl_tpu.runtime.data import shard_names
    from edl_tpu.runtime.elastic import ElasticConfig, ElasticWorker
    from edl_tpu.runtime.train_loop import TrainerConfig

    model = fit_a_line.MODEL
    shards = shard_names("kx", 5)
    state = str(tmp_path / "coord-state.jsonl")
    server = CoordinatorServer(task_lease_sec=60.0, heartbeat_ttl_sec=60.0,
                               state_file=state, run_id="killrun")
    server.start()
    try:
        admin = server.client("admin")
        admin.add_tasks(shards)
        admin.close()

        raw = CoordinatorClient(port=server.port, worker="w0",
                                retry=RetryPolicy(deadline=20.0, seed=3))
        source, counts = _counting_source(model)
        cfg = ElasticConfig(
            checkpoint_dir=str(tmp_path / "ck"),
            checkpoint_interval=4,
            heartbeat_interval=0.0,
            outage_budget=60.0,
            trainer=TrainerConfig(optimizer="sgd", learning_rate=0.05),
        )
        worker = ElasticWorker(model, raw, source, cfg,
                               device_planner=lambda w: jax.devices())

        def chaos():
            while worker.steps_done < 3 and not done_flag.is_set():
                time.sleep(0.02)
            server.kill()          # SIGKILL: no graceful anything
            time.sleep(1.0)        # a real supervisor's restart latency
            server.restart()

        done_flag = threading.Event()
        t = threading.Thread(target=chaos, daemon=True)
        t.start()
        try:
            metrics = worker.run()
        finally:
            done_flag.set()
            t.join(timeout=30)

        probe = server.client("probe")
        st = probe.status()
        probe.close()
    finally:
        server.stop()
    assert int(st["done"]) == len(shards), st
    assert int(st["queued"]) == 0 and int(st["leased"]) == 0, st
    # exactly once per shard: restored leases stayed with their holder
    assert counts == {s: 1 for s in shards}, counts
    assert metrics["steps"] == float(5 * 4), metrics


# -- slow soak: sustained chaos + kill, multi-shard ----------------------------


@pytest.mark.slow
@needs_native
def test_soak_sustained_chaos_with_coordinator_kill(tmp_path):
    """Sustained seeded faults (delays + resets) AND a mid-run coordinator
    SIGKILL+restart over a bigger queue. At-least-once is the floor (a reset
    can kill a connection mid-acquire before the reply lands), exactly-once
    is the expectation under lease preservation — assert the ledger and
    that no shard trained more than twice (bounded replay)."""
    import jax

    from edl_tpu.models import fit_a_line
    from edl_tpu.runtime.data import shard_names
    from edl_tpu.runtime.elastic import ElasticConfig, ElasticWorker
    from edl_tpu.runtime.train_loop import TrainerConfig

    model = fit_a_line.MODEL
    shards = shard_names("soak", 12)
    state = str(tmp_path / "coord-state.jsonl")
    server = CoordinatorServer(task_lease_sec=60.0, heartbeat_ttl_sec=60.0,
                               state_file=state, run_id="soak")
    server.start()
    try:
        admin = server.client("admin")
        admin.add_tasks(shards)
        admin.close()

        with ChaosProxy(server.port, seed=99, delay_prob=0.2,
                        delay_range=(0.005, 0.05), reset_prob=0.05) as proxy:
            raw = CoordinatorClient(port=proxy.port, worker="w0",
                                    retry=RetryPolicy(deadline=20.0, seed=99))
            source, counts = _counting_source(model)
            cfg = ElasticConfig(
                checkpoint_dir=str(tmp_path / "ck"),
                checkpoint_interval=4,
                heartbeat_interval=0.0,
                outage_budget=60.0,
                trainer=TrainerConfig(optimizer="sgd", learning_rate=0.05),
            )
            worker = ElasticWorker(model, raw, source, cfg,
                                   device_planner=lambda w: jax.devices())

            def chaos():
                while worker.steps_done < 6 and not done_flag.is_set():
                    time.sleep(0.02)
                server.kill()
                time.sleep(1.5)
                server.restart()

            done_flag = threading.Event()
            t = threading.Thread(target=chaos, daemon=True)
            t.start()
            try:
                worker.run()
            finally:
                done_flag.set()
                t.join(timeout=30)
            assert proxy.stats["delays"] + proxy.stats["resets"] > 0

        probe = server.client("probe")
        st = probe.status()
        probe.close()
    finally:
        server.stop()
    assert int(st["done"]) == len(shards), st
    assert int(st["queued"]) == 0 and int(st["leased"]) == 0, st
    assert all(1 <= counts.get(s, 0) <= 2 for s in shards), counts
