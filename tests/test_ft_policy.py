"""Fault-tolerance policy engine: transitions, hysteresis, cost model.

Everything here runs in injected fake time — the policy's clock is a
parameter precisely so these decisions are testable without sleeping.
The composed cross-axis chaos e2e that exercises the policy against real
sockets and real SIGKILLs lives in ``tests/test_chaos_composed.py``.
"""

import itertools

import pytest

from edl_tpu.obs.instruments import FTPolicyInstruments
from edl_tpu.obs.metrics import MetricsRegistry, parse_prometheus
from edl_tpu.obs.tracing import Tracer
from edl_tpu.runtime.ft_policy import (
    PARK,
    RECONNECT,
    WAIT,
    WARM_RESTART,
    FTPolicy,
    FTPolicyConfig,
)

pytestmark = [pytest.mark.chaos]


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def make_policy(cfg=None, **kwargs):
    """Policy with isolated instruments/tracer so tests don't share the
    process registry's counters."""
    clock = kwargs.pop("clock", None) or FakeClock()
    reg = MetricsRegistry()
    tracer = Tracer(component="test")
    p = FTPolicy(cfg if cfg is not None else FTPolicyConfig(),
                 worker="wtest", instruments=FTPolicyInstruments(reg),
                 tracer=tracer, clock=clock)
    return p, clock, reg, tracer


# -- static escape hatch -------------------------------------------------------


def test_static_policy_reproduces_outage_budget():
    """policy="static" must behave exactly like the old fixed threshold,
    history or not."""
    cfg = FTPolicyConfig(policy="static", outage_budget=10.0, min_history=1)
    p, clock, _, _ = make_policy(cfg)
    # saturate history with long outages — static must not care
    for _ in range(8):
        p.on_outage(0.1)
        p.note_outage_closed(300.0)
        clock.advance(1.0)
    assert p.threshold() == 10.0
    assert p.on_outage(9.9) == WAIT
    assert p.on_outage(10.1) == PARK


def test_adaptive_cold_start_defers_to_static_budget():
    """Below min_history the adaptive rule is inert: a fleet upgrade changes
    nothing until evidence accumulates (this is what keeps the existing
    single-partition chaos tests byte-identical in behavior)."""
    p, _, _, _ = make_policy(FTPolicyConfig(outage_budget=60.0, min_history=3))
    p.note_outage_closed(0.5)
    p.note_outage_closed(0.4)
    assert p.threshold() == 60.0
    assert p.on_outage(59.0) == WAIT


# -- mode transitions ----------------------------------------------------------


def test_blip_history_waits_then_reconnects_in_place():
    """blip → in-place: short-outage history keeps the threshold above a
    fresh blip, so the worker rides it out and the close records the
    reconnect decision."""
    p, clock, _, _ = make_policy(FTPolicyConfig(min_history=3, min_wait=1.0))
    for _ in range(3):
        p.on_outage(0.2)
        p.note_outage_closed(0.5)
        clock.advance(60.0)  # spaced out: not a storm
    # threshold now adaptive: max(0.5 * 1.5, breakeven=0) clamped to min_wait
    assert p.threshold() == 1.0
    assert p.on_outage(0.6) == WAIT
    p.note_outage_closed(0.7)
    assert p.last_mode == RECONNECT
    assert p.decisions[PARK] == 0


def test_storm_outage_escalates_to_park_long_before_static_budget():
    """storm → park: once history shows outages are short, an outage that
    blows past the distribution escalates at the computed threshold, not
    at the static 60 s."""
    p, clock, _, _ = make_policy(
        FTPolicyConfig(outage_budget=60.0, min_history=3, min_wait=1.0))
    for _ in range(3):
        p.on_outage(0.2)
        p.note_outage_closed(0.5)
        clock.advance(60.0)
    t = p.threshold()
    assert t < 5.0  # the adaptive win: escalate in seconds, not a minute
    assert p.on_outage(t + 0.1) == PARK
    assert p.decisions[PARK] == 1


def test_multihost_escalation_terminal_is_warm_restart():
    p, _, _, _ = make_policy(FTPolicyConfig(policy="static", outage_budget=1.0))
    assert p.on_outage(0.5, escalate_mode=WARM_RESTART) == WAIT
    assert p.on_outage(1.5, escalate_mode=WARM_RESTART) == WARM_RESTART


# -- hysteresis ----------------------------------------------------------------


def test_hysteresis_flapping_input_cannot_flap_the_mode():
    """Oscillating elapsed readings (clock weirdness, interleaved pollers)
    after escalation keep reporting the terminal mode: the latch is
    monotone within an incident, so wait→park→wait→park is impossible."""
    p, _, _, _ = make_policy(FTPolicyConfig(policy="static", outage_budget=2.0))
    assert p.on_outage(1.0) == WAIT
    assert p.on_outage(2.5) == PARK
    for elapsed in (0.1, 3.0, 0.0, 2.1, 1.0):
        assert p.on_outage(elapsed) == PARK
    # exactly one park decision for the whole incident
    assert p.decisions[PARK] == 1


def test_hysteresis_threshold_frozen_at_incident_open():
    """Evidence arriving mid-incident cannot move the goalposts: the
    threshold the comparison uses is the one frozen when the incident
    opened, so the wait→escalate flip happens at most once and at a
    predictable point."""
    p, clock, _, _ = make_policy(FTPolicyConfig(min_history=3, min_wait=1.0))
    for _ in range(3):
        p.on_outage(0.1)
        p.note_outage_closed(0.5)
        clock.advance(60.0)
    frozen = p.threshold()
    assert p.on_outage(0.2) == WAIT  # incident opens; threshold freezes
    # a huge checkpoint cost would raise the NEXT incident's threshold...
    p.note_checkpoint_cost(50.0)
    p.note_restore_cost(50.0)
    assert p.threshold() > frozen
    # ...but not this one's: it escalates at the frozen value.
    assert p.on_outage(frozen + 0.1) == PARK


def test_incident_close_resets_the_ladder():
    p, _, _, _ = make_policy(FTPolicyConfig(policy="static", outage_budget=1.0))
    p.on_outage(0.5)
    assert p.on_outage(1.5) == PARK
    p.note_outage_closed(2.0)
    assert not p.incident_open
    # fresh incident starts back at WAIT with a fresh frozen threshold
    assert p.on_outage(0.5) == WAIT


# -- cost model ----------------------------------------------------------------


def test_park_breakeven_raises_threshold_when_parking_is_expensive():
    """Waiting must stay preferred while it is cheaper than the park
    round-trip: expensive checkpoints + lots of uncheckpointed steps push
    the threshold up."""
    cfg = FTPolicyConfig(min_history=1, min_wait=0.1, park_cost_factor=2.0)
    p, _, _, _ = make_policy(cfg)
    p.note_outage_closed(0.1)  # activate the adaptive rule
    cheap = p.threshold()
    p.note_checkpoint_cost(3.0)
    p.note_restore_cost(2.0)
    for _ in range(10):
        p.note_step(0.5)  # 10 uncheckpointed steps x 0.5 s
    assert p.restep_cost() == pytest.approx(5.0)
    assert p.park_breakeven() == pytest.approx(2.0 * (3.0 + 2.0 + 5.0))
    assert p.threshold() > cheap
    # a fresh durable checkpoint zeroes the re-step exposure
    p.note_checkpoint_cost(3.0)
    assert p.restep_cost() == 0.0


def test_threshold_is_capped_by_the_static_budget():
    """Adaptive may escalate sooner than the old budget, never later."""
    cfg = FTPolicyConfig(outage_budget=10.0, min_history=1)
    p, _, _, _ = make_policy(cfg)
    p.note_outage_closed(500.0)  # history says outages are enormous
    p.note_checkpoint_cost(500.0)
    assert p.threshold() == 10.0


def test_storm_detector_shortens_retry_deadline():
    cfg = FTPolicyConfig(min_history=3, storm_rate_per_min=6.0,
                         storm_retry_deadline=5.0)
    p, clock, _, _ = make_policy(cfg)
    assert p.retry_deadline() is None
    for _ in range(6):  # 6 incidents in ~5 fake seconds: a storm
        p.note_outage_closed(0.3)
        clock.advance(1.0)
    assert p.in_storm()
    assert p.retry_deadline() == 5.0
    # calm regime: same incident count spread over fake hours
    q, qclock, _, _ = make_policy(cfg)
    for _ in range(6):
        q.note_outage_closed(0.3)
        qclock.advance(600.0)
    assert not q.in_storm()
    assert q.retry_deadline() is None


# -- observability -------------------------------------------------------------


def test_decisions_surface_as_metrics_and_spans():
    p, _, reg, tracer = make_policy(
        FTPolicyConfig(policy="static", outage_budget=1.0))
    p.on_outage(0.5)
    p.on_outage(1.5)
    p.note_outage_closed(2.0)
    p.on_outage(0.2)
    p.note_outage_closed(0.3)
    families = parse_prometheus(reg.render_prometheus())
    incidents = families["edl_ft_policy_incidents_total"]["samples"]
    assert incidents["edl_ft_policy_incidents_total"] == 2.0
    decisions = families["edl_ft_policy_decisions_total"]["samples"]
    assert decisions['edl_ft_policy_decisions_total{mode="wait"}'] == 2.0
    assert decisions['edl_ft_policy_decisions_total{mode="park"}'] == 1.0
    assert decisions['edl_ft_policy_decisions_total{mode="reconnect"}'] == 1.0
    assert "edl_ft_policy_park_threshold_seconds" in families
    events = tracer.find(name="ft_decision")
    assert len(events) == 4
    # every decision span carries its inputs — the audit trail
    for ev in events:
        for key in ("mode", "threshold", "elapsed", "park_breakeven",
                    "failure_rate_per_min"):
            assert key in ev.attrs, ev.attrs
    assert {e.attrs["mode"] for e in events} == {WAIT, PARK, RECONNECT}


def test_state_dict_is_json_ready():
    import json

    p, _, _, _ = make_policy()
    p.on_outage(0.5)
    p.note_outage_closed(1.0)
    st = json.loads(json.dumps(p.state()))
    assert st["policy"] == "adaptive"
    assert st["mode"] == RECONNECT
    assert st["incidents"] == 1


# -- the mutant check ----------------------------------------------------------


def _run_trace(policy, trace, clock, park_overhead=2.0, wait_drag=0.1):
    """Replay a failure trace through a policy and price its choices.

    Cost model (explained, not tuned): waiting through an outage costs
    ``wait_drag`` per second (leased batches keep stepping, so degraded
    time is cheap but not free); escalating costs the time spent deciding
    plus ``park_overhead`` (checkpoint + restore + replayed steps).
    """
    cost = 0.0
    for duration, gap in trace:
        t = 0.0
        escalated = False
        while t < duration:
            t = min(duration, t + 0.1)
            if policy.on_outage(t) == PARK:
                escalated = True
                break
        if escalated:
            cost += t * wait_drag + park_overhead
        else:
            cost += duration * wait_drag
        policy.note_outage_closed(duration)
        clock.advance(gap)
    return cost


#: 8 blips then 3 storms — the regime change the adaptive rule exists for.
TRACE = [(0.4, 60.0)] * 8 + [(120.0, 60.0)] * 3


def test_mutant_forced_modes_measurably_underperform_adaptive():
    """A policy pinned to either pure strategy must cost measurably more
    than the adaptive one on a blips-then-storms trace: always-wait burns
    the full outage on every storm, always-park pays the park round-trip
    on every blip. If this assertion ever fails, the policy layer has
    stopped earning its complexity."""
    # budget 10 s: the operator's hard cap on degraded time. It also caps
    # history contamination — after the first 120 s storm lands in the
    # window the quantile explodes, and the clamp is what keeps storms
    # 2..3 escalating promptly instead of inheriting storm-sized patience.
    adaptive, clock_a, _, _ = make_policy(
        FTPolicyConfig(outage_budget=10.0, min_history=3, min_wait=1.0))
    cost_adaptive = _run_trace(adaptive, TRACE, clock_a)

    forced_wait, clock_w, _, _ = make_policy(
        FTPolicyConfig(policy="static", outage_budget=1000.0))
    cost_wait = _run_trace(forced_wait, TRACE, clock_w)

    forced_park, clock_p, _, _ = make_policy(
        FTPolicyConfig(policy="static", outage_budget=0.2))
    cost_park = _run_trace(forced_park, TRACE, clock_p)

    # adaptive waited through the blips and parked the storms
    assert adaptive.decisions[PARK] == 3
    assert adaptive.decisions[RECONNECT] == 8
    assert cost_adaptive < 0.7 * cost_wait, (cost_adaptive, cost_wait)
    assert cost_adaptive < 0.7 * cost_park, (cost_adaptive, cost_park)


# -- config validation (satellite: fail at construction) -----------------------


def test_elastic_config_rejects_bad_fault_tolerance_knobs(tmp_path):
    from edl_tpu.runtime.elastic import ElasticConfig

    ck = str(tmp_path / "ck")
    with pytest.raises(ValueError, match="outage_budget"):
        ElasticConfig(checkpoint_dir=ck, outage_budget=-5.0)
    with pytest.raises(ValueError, match="heartbeat_interval"):
        ElasticConfig(checkpoint_dir=ck, heartbeat_interval=-1.0)
    with pytest.raises(ValueError, match="heartbeat_jitter"):
        ElasticConfig(checkpoint_dir=ck, heartbeat_jitter=1.5)
    with pytest.raises(ValueError, match="checkpoint_interval"):
        ElasticConfig(checkpoint_dir=ck, checkpoint_interval=0)
    with pytest.raises(ValueError, match="pipeline_depth"):
        ElasticConfig(checkpoint_dir=ck, pipeline_depth=-1)
    with pytest.raises(ValueError, match="rescale_barrier_timeout"):
        ElasticConfig(checkpoint_dir=ck, rescale_barrier_timeout=0.0)
    with pytest.raises(ValueError, match="policy"):
        ElasticConfig(checkpoint_dir=ck, policy="yolo")
    # the boundary cases tests and production both rely on stay legal
    ElasticConfig(checkpoint_dir=ck, heartbeat_interval=0.0)
    ElasticConfig(checkpoint_dir=ck, heartbeat_jitter=0.0)
    ElasticConfig(checkpoint_dir=ck, policy="static")


def test_ft_policy_config_validation():
    with pytest.raises(ValueError, match="policy"):
        FTPolicyConfig(policy="aggressive")
    with pytest.raises(ValueError, match="outage_budget"):
        FTPolicyConfig(outage_budget=0.0)
    with pytest.raises(ValueError, match="min_history"):
        FTPolicyConfig(min_history=0)
    with pytest.raises(ValueError, match="residual_quantile"):
        FTPolicyConfig(residual_quantile=1.5)


# -- outbox incident callback (the policy's sensor feed) -----------------------


class _FlakyClient:
    """Raises CoordinatorError until told otherwise."""

    worker = "wflaky"

    def __init__(self):
        self.up = True

    def call(self, op, **fields):
        from edl_tpu.coordinator.client import CoordinatorUnreachable

        if not self.up:
            raise CoordinatorUnreachable("down")
        return {"ok": True, "op": op}

    def heartbeat(self):
        return self.call("heartbeat")

    def register(self, takeover=False):
        return self.call("register")

    def acquire(self):
        return self.call("acquire")

    def close(self):
        pass


def test_outbox_reports_per_incident_durations():
    """The on_outage_close hook fires once per incident with its duration —
    the per-incident signal the running-total gauge aggregates away."""
    from edl_tpu.coordinator.outbox import OutboxClient

    raw = _FlakyClient()
    client = OutboxClient(raw)
    closed = []
    client.on_outage_close = closed.append

    raw.up = False
    client.heartbeat()
    client.heartbeat()
    assert closed == []  # still down: incident open, nothing closed
    raw.up = True
    client.heartbeat()
    assert len(closed) == 1 and closed[0] >= 0.0
    raw.up = False
    client.complete_task("s1")  # buffered mutation opens incident #2
    raw.up = True
    client.heartbeat()
    assert len(closed) == 2
    assert client.outages == 2


# -- scripted scenarios (the composed-chaos conductor) -------------------------


def test_scenario_fires_steps_in_order_with_gates():
    from edl_tpu.testing.chaosproxy import ChaosScenario

    fired = []
    gate = {"open": False}
    sc = (ChaosScenario("unit")
          .register("a", lambda: fired.append("a"))
          .register("b", lambda tag: fired.append(f"b:{tag}"))
          .predicate("gate", lambda: gate["open"])
          .add("a")
          .add("b", when="gate", tag="x")
          .add("a", after=0.05))
    sc.start()
    import time as _time

    _time.sleep(0.1)
    assert fired == ["a"]  # step 2 is gated
    gate["open"] = True
    sc.join(timeout=5.0)
    assert sc.completed and sc.failed is None
    assert fired == ["a", "b:x", "a"]
    assert [e["action"] for e in sc.events] == ["a", "b", "a"]


def test_scenario_gate_timeout_fails_loudly():
    from edl_tpu.testing.chaosproxy import ChaosScenario

    sc = (ChaosScenario("stuck")
          .register("never", lambda: None)
          .predicate("no", lambda: False)
          .add("never", when="no", timeout=0.1))
    sc.start()
    sc.join(timeout=5.0)
    assert not sc.completed
    assert "never opened" in sc.failed


def test_scenario_spec_round_trips_through_json():
    from edl_tpu.testing.chaosproxy import ChaosScenario

    sc = (ChaosScenario("rt")
          .add("x.partition", when="warm", after=1.5, note="sever")
          .add("x.heal", after=2.0))
    clone = ChaosScenario.from_spec(sc.spec())
    assert [s.to_dict() for s in clone.steps] == [s.to_dict() for s in sc.steps]


def test_scenario_rejects_unregistered_names():
    from edl_tpu.testing.chaosproxy import ChaosScenario

    sc = ChaosScenario("bad").add("ghost")
    with pytest.raises(ValueError, match="ghost"):
        sc.start()


# -- restore-source break-even (checkpoint plane) ------------------------------


def test_restore_source_defaults_to_peer_until_both_measured():
    """Optimistic peer-first: an unreadable plane demotes to blob anyway,
    so guessing peer costs one failed in-memory probe at most."""
    p, _, _, _ = make_policy()
    assert p.restore_source() == "peer"
    p.note_restore_cost(5.0)  # only blob measured
    assert p.restore_source() == "peer"
    p.note_peer_restore(0.2)  # both measured, peer cheaper
    assert p.restore_source() == "peer"


def test_restore_source_flips_to_blob_when_measurably_cheaper():
    p, _, _, _ = make_policy()
    p.note_peer_restore(4.0)
    p.note_restore_cost(0.5)
    assert p.restore_source() == "blob"


def test_effective_restore_cost_prices_the_cheapest_source():
    p, _, _, _ = make_policy()
    assert p.effective_restore_cost() == 0.0
    p.note_restore_cost(5.0)
    assert p.effective_restore_cost() == 5.0
    p.note_peer_restore(0.5)
    assert p.effective_restore_cost() == 0.5
    # the park break-even reflects the fast source, not the blob read
    p.note_checkpoint_cost(1.0)
    cfg = p.config
    assert p.park_breakeven() == pytest.approx(
        cfg.park_cost_factor * (p._ckpt_ema + 0.5 + p.restep_cost()))


def test_note_peer_restore_records_decision_and_gauges():
    from edl_tpu.runtime.ft_policy import MODE_CODES, PEER_RESTORE

    p, _, reg, _ = make_policy()
    p.note_peer_restore(0.25)
    assert MODE_CODES[PEER_RESTORE] == 4
    families = parse_prometheus(reg.render_prometheus())
    decisions = families["edl_ft_policy_decisions_total"]["samples"]
    assert decisions['edl_ft_policy_decisions_total{mode="peer_restore"}'] == 1.0
    costs = families["edl_ft_policy_restore_cost_seconds"]["samples"]
    assert costs['edl_ft_policy_restore_cost_seconds{source="peer"}'] == 0.25
    st = p.state()
    assert st["restore_source"] == "peer"
    assert st["restore_cost_peer"] == 0.25
