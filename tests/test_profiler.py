"""Profiler tests: step accounting, summaries, trace capture, loop hookup."""

import glob
import io
import json
import math
import time

import numpy as np

from edl_tpu.models import fit_a_line
from edl_tpu.parallel import local_mesh
from edl_tpu.runtime import Trainer, TrainerConfig
from edl_tpu.tools import StepProfiler, annotate_step, annotation, device_memory_stats, trace


def test_step_profiler_records_and_summarizes():
    p = StepProfiler(warmup=1)
    p.start()
    for i in range(5):
        time.sleep(0.002)
        p.step(samples=32, loss=1.0 / (i + 1))
    assert len(p.records) == 5
    s = p.summary()
    assert s["steps"] == 5.0
    assert s["steady_steps"] == 4.0  # warmup step excluded
    assert s["samples_per_sec"] > 0
    assert s["step_time_p50_s"] <= s["step_time_p95_s"] <= s["step_time_max_s"]
    # warmup record still present for trace alignment
    assert p.records[0].step == 0


def test_step_profiler_sink_emits_jsonl():
    sink = io.StringIO()
    p = StepProfiler(sink=sink)
    p.start()
    p.step(samples=8, loss=0.5)
    p.step(samples=8)
    lines = [json.loads(l) for l in sink.getvalue().splitlines()]
    assert len(lines) == 2
    assert lines[0]["samples"] == 8
    assert lines[0]["loss"] == 0.5
    assert "loss" not in lines[1]


def test_step_profiler_window_bounds_memory():
    p = StepProfiler(warmup=0, window=10)
    p.start()
    for _ in range(50):
        p.step(samples=1)
    assert len(p.records) == 10
    assert p.summary()["steps"] == 50.0


def test_window_eviction_does_not_misclassify_steady():
    """Warmup is a per-record flag, not a list position: after the warmup
    record is evicted by the window, no steady record is dropped."""
    p = StepProfiler(warmup=1, window=5)
    p.start()
    for _ in range(20):
        p.step(samples=1)
    assert len(p.steady) == 5  # all surviving records are steady
    assert p.summary()["steady_steps"] == 5.0


def test_mark_warmup_flags_recompile_steps():
    p = StepProfiler(warmup=0)
    p.start()
    p.step(samples=1)
    p.mark_warmup()  # e.g. mesh rebuilt after rescale
    p.step(samples=1)
    p.step(samples=1)
    flags = [r.warmup for r in p.records]
    assert flags == [False, True, False]
    assert p.summary()["steady_steps"] == 2.0


def test_wrap_iterator_times_consumer():
    p = StepProfiler(warmup=0)
    data = [{"x": np.zeros((4, 2))} for _ in range(3)]
    out = list(p.wrap(iter(data)))
    assert len(out) == 3
    assert [r.samples for r in p.records] == [4, 4, 4]


def test_empty_profiler_summary():
    """Zero-step and warmup-only summaries: same keys as the populated case,
    every value a finite zero — never a ZeroDivisionError, inf, or NaN (a
    rescale can interrupt a worker before its first steady step, and the
    flush must still aggregate)."""
    keys = ("steps", "steady_steps", "samples_per_sec", "step_time_mean_s",
            "step_time_p50_s", "step_time_p95_s", "step_time_max_s")

    s = StepProfiler().summary()
    for k in keys:
        assert s[k] == 0.0 and math.isfinite(s[k]), (k, s)

    # warmup-only: records exist but none are steady — the old inf/NaN trap.
    p = StepProfiler(warmup=5)
    p.start()
    p.step(samples=8)
    p.step(samples=8)
    s = p.summary()
    assert s["steps"] == 2.0
    assert s["steady_steps"] == 0.0
    for k in keys:
        assert math.isfinite(s[k]), (k, s)
    assert s["samples_per_sec"] == 0.0


def test_trainer_run_with_profiler():
    mesh = local_mesh()
    trainer = Trainer(fit_a_line.MODEL, mesh, TrainerConfig(optimizer="sgd", learning_rate=0.1))
    state = trainer.init_state()
    rng = np.random.default_rng(0)
    prof = StepProfiler(warmup=1)

    def batches(n):
        for _ in range(n):
            yield fit_a_line.MODEL.synthetic_batch(rng, 64)

    state, metrics = trainer.run(state, batches(6), profiler=prof)
    assert len(prof.records) == 6
    s = prof.summary()
    assert s["steady_steps"] == 5.0
    # aggregate throughput in the same ballpark as the loop's own accounting
    assert s["samples_per_sec"] > 0


def test_collective_series_and_data_plane_summary():
    """The data-plane estimate rides the step records (`collective_ms` in the
    sink line) and the summary surfaces `grad_bytes_per_step` +
    `collective_time_est_mean_s` once `data_plane` is attached."""
    sink = io.StringIO()
    p = StepProfiler(warmup=0, sink=sink)
    p.data_plane = {"grad_bytes_per_step": 1024.0, "bytes_per_step": 1536.0}
    p.start()
    p.step(samples=8, collective_seconds=0.002)
    p.step(samples=8, collective_seconds=0.004)
    p.step(samples=8)  # estimate omitted — must not poison the mean
    lines = [json.loads(l) for l in sink.getvalue().splitlines()]
    assert lines[0]["collective_ms"] == 2.0
    assert lines[1]["collective_ms"] == 4.0
    assert "collective_ms" not in lines[2]
    s = p.summary()
    assert s["collective_time_est_mean_s"] == (0.002 + 0.004) / 2
    assert s["grad_bytes_per_step"] == 1024.0
    assert s["data_plane_bytes_per_step"] == 1536.0
    # without a data plane, the byte keys stay absent
    bare = StepProfiler(warmup=0)
    bare.start()
    bare.step(samples=8)
    assert "grad_bytes_per_step" not in bare.summary()
    assert "collective_time_est_mean_s" not in bare.summary()


def test_trainer_run_fills_data_plane():
    """Trainer.run wires its analytic data plane into the profiler: every
    step record carries the estimate and the summary reports bytes."""
    mesh = local_mesh()
    trainer = Trainer(
        fit_a_line.MODEL, mesh, TrainerConfig(optimizer="sgd", learning_rate=0.1)
    )
    state = trainer.init_state()
    rng = np.random.default_rng(0)
    prof = StepProfiler(warmup=0)

    def batches(n):
        for _ in range(n):
            yield fit_a_line.MODEL.synthetic_batch(rng, 64)

    _, metrics = trainer.run(state, batches(3), profiler=prof)
    assert prof.data_plane is not None
    assert prof.data_plane["grad_sync"] == trainer.grad_sync
    assert all(r.collective_seconds is not None for r in prof.records)
    assert prof.summary()["grad_bytes_per_step"] == metrics["grad_bytes_per_step"]


def test_annotations_are_usable_contexts():
    with annotation("edl/test-span"):
        pass
    with annotate_step(3):
        pass


def test_trace_captures_to_logdir(tmp_path):
    import jax.numpy as jnp

    logdir = str(tmp_path / "trace")
    with trace(logdir):
        (jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready()
    produced = glob.glob(logdir + "/**/*", recursive=True)
    assert produced, "profiler trace produced no files"


def test_device_memory_stats_shape():
    stats = device_memory_stats()
    # CPU backend usually exposes nothing; if it does, values are ints.
    for per_dev in stats.values():
        for v in per_dev.values():
            assert isinstance(v, int)


def test_summary_reports_mfu_when_model_given(monkeypatch):
    from edl_tpu.models import fit_a_line
    from edl_tpu.tools.profiler import StepProfiler

    # pin the no-peak path: the env override would add an mfu key
    monkeypatch.delenv("EDL_TPU_PEAK_TFLOPS", raising=False)
    prof = StepProfiler(warmup=0, model=fit_a_line.MODEL)
    prof.start()
    for _ in range(3):
        prof.step(64)
    s = prof.summary()
    assert s["tflops_per_sec"] > 0
    # per-sample flops x rate consistency: mfu_fields rounds to 3 decimals
    # but never rounds a positive achieved rate down to 0 (CPU-sim figures
    # for tiny models sit below a milli-TFLOP)
    expected = fit_a_line.MODEL.flops_per_step(1) * s["samples_per_sec"] / 1e12
    assert s["tflops_per_sec"] == (round(expected, 3) or expected)
    # CPU backend: no peak table entry, so no mfu key
    assert "mfu" not in s

    bare = StepProfiler(warmup=0)
    bare.start()
    bare.step(64)
    assert "tflops_per_sec" not in bare.summary()
