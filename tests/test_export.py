"""Inference export/serving: the save_inference_model equivalent.

Reference flow being mirrored: trainer 0 periodically saves an inference
artifact; a separate process loads it and predicts
(`example/ctr/ctr/train.py:169-180`, `fluid/fit_a_line.py:95-117`).
"""

import json
import os

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from edl_tpu import models as zoo
from edl_tpu.models import ctr, fit_a_line
from edl_tpu.parallel import MeshSpec, build_mesh
from edl_tpu.runtime import (
    ElasticConfig,
    ElasticWorker,
    PeriodicExporter,
    SyntheticShardSource,
    Trainer,
    TrainerConfig,
    load_inference_model,
    save_inference_model,
)
from edl_tpu.runtime.data import shard_names


def single_mesh():
    return Mesh(np.array(jax.devices()[:1]), axis_names=("data",))


def test_round_trip_predictions_match(tmp_path):
    mesh = single_mesh()
    model = fit_a_line.MODEL
    params = model.init(jax.random.PRNGKey(0), mesh)
    batch = model.synthetic_batch(np.random.default_rng(0), 16)
    direct = np.asarray(model.predict(params, batch, mesh))

    d = str(tmp_path / "fit")
    save_inference_model(d, "fit_a_line", params, step=7)
    art = load_inference_model(d, mesh=mesh)
    assert art.step == 7
    served = np.asarray(art.predict({"x": batch["x"]}))
    np.testing.assert_allclose(served, direct, rtol=1e-6)


def test_sharded_table_reshards_on_load(tmp_path):
    """Save from an expert-sharded 8-device mesh, serve on 1 device — the
    artifact is mesh-independent like a checkpoint."""
    train_mesh = build_mesh(MeshSpec({"data": 2, "expert": 4}))
    model = ctr.make_model(shard_axis="expert", sparse_dim=4096)
    params = model.init(jax.random.PRNGKey(1), train_mesh)
    batch = model.synthetic_batch(np.random.default_rng(1), 32)
    feats = {k: v for k, v in batch.items() if k != "label"}
    direct = np.asarray(model.predict(params, feats, train_mesh))

    d = str(tmp_path / "art")
    save_inference_model(
        d, "ctr", params,
        config={"shard_axis": "expert", "sparse_dim": 4096}, step=1,
    )
    # Serving mesh has no expert axis at all -> specs must still resolve
    # (P("expert") on a mesh lacking the axis would fail; the artifact's
    # config rebuilds the SAME model, and the default serving mesh is the
    # local data mesh, so rebuild with a 1-device expert axis).
    serve_mesh = build_mesh(MeshSpec({"data": 1, "expert": 1}),
                            jax.devices()[:1])
    art = load_inference_model(d, mesh=serve_mesh)
    served = np.asarray(art.predict(feats))
    np.testing.assert_allclose(served, direct, rtol=2e-3, atol=2e-3)


def test_bfloat16_leaves_round_trip(tmp_path):
    from ml_dtypes import bfloat16

    mesh = single_mesh()
    params = fit_a_line.MODEL.init(jax.random.PRNGKey(0), mesh)
    params = jax.tree_util.tree_map(
        lambda x: x.astype(jax.numpy.bfloat16), params
    )
    d = str(tmp_path / "bf16")
    save_inference_model(d, "fit_a_line", params)
    manifest = json.load(open(os.path.join(d, "manifest.json")))
    assert {e["dtype"] for e in manifest["leaves"]} == {"bfloat16"}
    art = load_inference_model(d, mesh=mesh)
    leaves = jax.tree_util.tree_leaves(art.params)
    assert all(l.dtype == bfloat16 for l in leaves)
    np.testing.assert_array_equal(
        np.asarray(leaves[0]).view(np.uint16),
        np.asarray(jax.tree_util.tree_leaves(params)[0]).view(np.uint16),
    )


def test_resolve_registry_and_config():
    assert zoo.resolve("mnist").name == "mnist"
    assert zoo.resolve("resnet50").name == "resnet50"  # registry alias
    m = zoo.resolve("resnet", {"depth": 18, "num_classes": 10,
                               "image_size": 32, "width": 8, "gn_groups": 4})
    assert m.name == "resnet18"
    with pytest.raises(KeyError):
        zoo.resolve("nope")
    with pytest.raises(TypeError):
        zoo.resolve("mnist", {"depth": 3})  # not configurable


def test_periodic_exporter_rank_and_interval(tmp_path):
    mesh = single_mesh()
    model = fit_a_line.MODEL
    trainer = Trainer(model, mesh, TrainerConfig(optimizer="sgd"))
    state = trainer.init_state()

    d0, d1 = str(tmp_path / "r0"), str(tmp_path / "r1")
    rank0 = PeriodicExporter(d0, "fit_a_line", interval=2, rank=0)
    rank1 = PeriodicExporter(d1, "fit_a_line", interval=2, rank=1)
    for step in (1, 2, 2, 3, 4):  # duplicate step 2 must not double-export
        rank0(step, state)
        rank1(step, state)
    assert rank0.exports == 2  # steps 2 and 4
    assert rank1.exports == 0  # trainer-0-only duty
    assert os.path.exists(os.path.join(d0, "manifest.json"))
    assert not os.path.exists(os.path.join(d1, "manifest.json"))


def test_replayed_steps_never_regress_published_artifact(tmp_path):
    """Post-restore replay (or a warm-restarted gang) re-visits old step
    numbers; neither the in-process high-water mark nor a fresh process may
    overwrite a newer published artifact with older weights."""
    mesh = single_mesh()
    model = fit_a_line.MODEL
    params = model.init(jax.random.PRNGKey(0), mesh)
    d = str(tmp_path / "serve")
    save_inference_model(d, "fit_a_line", params, step=10)
    # a fresh writer (simulating a warm-restarted process) replays step 4
    save_inference_model(d, "fit_a_line", params, step=4)
    assert load_inference_model(d, mesh=mesh).step == 10
    # in-process replay below the high-water mark is also skipped
    exp = PeriodicExporter(d, "fit_a_line", interval=2)

    class S:  # minimal state stand-in
        pass

    s = S()
    s.params = params
    exp(12, s)
    exp.wait()
    assert load_inference_model(d, mesh=mesh).step == 12
    exp._high_water = 12  # replay: calls at old steps are dropped pre-gather
    exp(4, s)
    exp.wait()
    assert load_inference_model(d, mesh=mesh).step == 12


def test_stepless_saves_stay_unique(tmp_path):
    """step=None saves must still give each export its own weights file —
    a poller holding the first manifest must never read the second save's
    bytes through it."""
    mesh = single_mesh()
    params = fit_a_line.MODEL.init(jax.random.PRNGKey(0), mesh)
    d = str(tmp_path / "serve")
    save_inference_model(d, "fit_a_line", params)
    first = json.load(open(os.path.join(d, "manifest.json")))["weights"]
    save_inference_model(d, "fit_a_line", params)
    second = json.load(open(os.path.join(d, "manifest.json")))["weights"]
    assert first != second
    assert os.path.exists(os.path.join(d, first))  # grace generation kept
    assert load_inference_model(d, mesh=mesh).step is None


def test_elastic_worker_exports_during_training(tmp_path):
    """The integration the reference has: training periodically publishes a
    servable artifact; a loader scores with it mid/post-run."""
    from edl_tpu.coordinator.inprocess import InProcessCoordinator

    model = fit_a_line.MODEL
    coord = InProcessCoordinator(task_lease_sec=300.0, heartbeat_ttl_sec=300.0)
    coord.add_tasks(shard_names("uci", 2))
    client = coord.client("w0")
    export_dir = str(tmp_path / "serve")
    exporter = PeriodicExporter(export_dir, "fit_a_line", interval=5)
    cfg = ElasticConfig(
        checkpoint_dir=str(tmp_path / "ck"),
        checkpoint_interval=100,
        step_callback=exporter,
        trainer=TrainerConfig(optimizer="sgd", learning_rate=1e-2),
    )
    source = SyntheticShardSource(model, batch_size=64, batches_per_shard=10)
    metrics = ElasticWorker(model, client, source, cfg).run()
    assert metrics["steps"] == 20.0
    exporter.wait()  # async write: make the final artifact durable

    art = load_inference_model(export_dir)
    assert art.step == 20  # latest export wins (interval 5 over 20 steps)
    batch = model.synthetic_batch(np.random.default_rng(5), 64)
    pred = np.asarray(art.predict({"x": batch["x"]}))
    # trained params: predictions correlate strongly with true targets
    corr = np.corrcoef(pred.ravel(), batch["y"].ravel())[0, 1]
    assert corr > 0.9


def test_gc_spares_exactly_the_previous_manifests_weights(tmp_path):
    """The grace generation is the file the just-replaced manifest named —
    mtime forgery or a lingering step-less 'final' save must not steal the
    slot from the file an in-flight reader may still be loading."""
    import time as _time

    mesh = single_mesh()
    model = fit_a_line.MODEL
    params = model.init(jax.random.PRNGKey(0), mesh)
    d = str(tmp_path / "gc")
    save_inference_model(d, "fit_a_line", params)  # params-final-<uuid>
    for step in (10, 20):
        save_inference_model(d, "fit_a_line", params, step=step)
    # forge a stale mtime ON THE GRACE file: mtime ordering would GC
    # params-20 (which the current manifest names) and keep params-10
    now = _time.time()
    os.utime(os.path.join(d, "params-10.npz"), (now + 100, now + 100))
    os.utime(os.path.join(d, "params-20.npz"), (now - 100, now - 100))
    save_inference_model(d, "fit_a_line", params, step=30)
    names = {p for p in os.listdir(d) if p.endswith(".npz")}
    # the stale final save and params-10 are unreachable from any manifest
    assert names == {"params-20.npz", "params-30.npz"}


def test_gc_sweeps_stale_tmp_files(tmp_path):
    import time as _time

    mesh = single_mesh()
    model = fit_a_line.MODEL
    params = model.init(jax.random.PRNGKey(0), mesh)
    d = str(tmp_path / "tmpsweep")
    save_inference_model(d, "fit_a_line", params, step=1)
    stale = os.path.join(d, "orphan.npz.tmp")
    fresh = os.path.join(d, "live.json.tmp")
    for p in (stale, fresh):
        with open(p, "w") as f:
            f.write("x")
    old = _time.time() - 3600
    os.utime(stale, (old, old))  # orphan from a dead writer
    save_inference_model(d, "fit_a_line", params, step=2)
    assert not os.path.exists(stale), "aged orphan tmp should be swept"
    assert os.path.exists(fresh), "recent tmp (concurrent writer) survives"


# -- versioned layout (the serving tier's swap-watcher contract) ---------------


def test_versioned_layout_latest_pointer_and_loader(tmp_path):
    from edl_tpu.runtime import (artifact_version, load_inference_model,
                                 resolve_artifact_dir)
    from edl_tpu.runtime.export import LATEST

    mesh = single_mesh()
    params = fit_a_line.MODEL.init(jax.random.PRNGKey(0), mesh)
    d = str(tmp_path / "vroot")
    save_inference_model(d, "fit_a_line", params, step=100, versioned=True)
    assert open(os.path.join(d, LATEST)).read() == "v0000000100"
    assert resolve_artifact_dir(d) == os.path.join(d, "v0000000100")
    assert artifact_version(d) == (100, "params-100.npz", "v0000000100")
    # the loader follows LATEST transparently
    assert load_inference_model(d, mesh=mesh).step == 100
    save_inference_model(d, "fit_a_line", params, step=200, versioned=True)
    assert artifact_version(d)[0] == 200
    assert load_inference_model(d, mesh=mesh).step == 200


def test_versioned_gc_keeps_latest_plus_grace(tmp_path):
    mesh = single_mesh()
    params = fit_a_line.MODEL.init(jax.random.PRNGKey(0), mesh)
    d = str(tmp_path / "vgc")
    for step in (1, 2, 3):
        save_inference_model(d, "fit_a_line", params, step=step,
                             versioned=True)
    vdirs = sorted(p for p in os.listdir(d) if p.startswith("v")
                   and os.path.isdir(os.path.join(d, p)))
    # LATEST's target + the generation it replaced; v0000000001 collected
    assert vdirs == ["v0000000002", "v0000000003"]


def test_versioned_regression_guard(tmp_path):
    from edl_tpu.runtime import artifact_version

    mesh = single_mesh()
    params = fit_a_line.MODEL.init(jax.random.PRNGKey(0), mesh)
    d = str(tmp_path / "vreg")
    save_inference_model(d, "fit_a_line", params, step=50, versioned=True)
    # a warm-restarted gang replaying step 10 must not regress LATEST
    save_inference_model(d, "fit_a_line", params, step=10, versioned=True)
    assert artifact_version(d)[0] == 50


def test_crash_mid_export_never_visible_to_readers(tmp_path):
    """An orphan version directory whose write died before the LATEST
    replace is invisible: artifact_version never names it, the loader keeps
    serving the previous complete artifact, and a later export sweeps it
    once aged."""
    import time as _time

    from edl_tpu.runtime import artifact_version, load_inference_model

    mesh = single_mesh()
    params = fit_a_line.MODEL.init(jax.random.PRNGKey(0), mesh)
    d = str(tmp_path / "vcrash")
    save_inference_model(d, "fit_a_line", params, step=100, versioned=True)
    # simulate a writer that died mid-export: directory exists, manifest
    # incomplete (never written), LATEST untouched
    orphan = os.path.join(d, "v0000000150")
    os.makedirs(orphan)
    with open(os.path.join(orphan, "params-150.npz"), "wb") as f:
        f.write(b"torn")
    assert artifact_version(d) == (100, "params-100.npz", "v0000000100")
    assert load_inference_model(d, mesh=mesh).step == 100
    # a fresh export leaves the RECENT orphan alone (could be a slow live
    # writer)...
    save_inference_model(d, "fit_a_line", params, step=200, versioned=True)
    assert os.path.isdir(orphan)
    # ...but sweeps it once aged past the tmp-sweep horizon
    old = _time.time() - 3600
    os.utime(orphan, (old, old))
    save_inference_model(d, "fit_a_line", params, step=300, versioned=True)
    assert not os.path.exists(orphan)
    assert artifact_version(d)[0] == 300


def test_periodic_exporter_versioned_mode(tmp_path):
    from edl_tpu.runtime import artifact_version, load_inference_model
    from edl_tpu.runtime.export import LATEST

    mesh = single_mesh()
    model = fit_a_line.MODEL
    trainer = Trainer(model, mesh, TrainerConfig(optimizer="sgd"))
    state = trainer.init_state()
    d = str(tmp_path / "vexp")
    exp = PeriodicExporter(d, "fit_a_line", interval=2, versioned=True)
    for step in (1, 2, 3, 4):
        exp(step, state)
    exp.wait()
    assert exp.exports == 2
    assert os.path.exists(os.path.join(d, LATEST))
    assert artifact_version(d)[0] == 4
    assert load_inference_model(d, mesh=mesh).step == 4


# -- serving-mesh derivation + thread-safe predict -----------------------------


def test_serving_mesh_adds_missing_axes_for_sharded_models(tmp_path):
    """An expert-sharded ctr table exported from an 8-device training mesh
    loads on the DEFAULT serving mesh (no mesh argument): _serving_mesh
    adds a size-1 axis for every spec axis the local data mesh lacks."""
    from edl_tpu.runtime.export import _serving_mesh

    train_mesh = build_mesh(MeshSpec({"data": 2, "expert": 4}))
    model = ctr.make_model(shard_axis="expert", sparse_dim=512)
    params = model.init(jax.random.PRNGKey(2), train_mesh)
    batch = model.synthetic_batch(np.random.default_rng(2), 16)
    feats = {k: v for k, v in batch.items() if k != "label"}
    direct = np.asarray(model.predict(params, feats, train_mesh))

    serve_mesh = _serving_mesh(model)
    assert "expert" in serve_mesh.axis_names
    assert dict(zip(serve_mesh.axis_names,
                    serve_mesh.devices.shape))["expert"] == 1

    d = str(tmp_path / "ctrart")
    save_inference_model(d, "ctr",
                         params,
                         config={"shard_axis": "expert", "sparse_dim": 512},
                         step=1)
    art = load_inference_model(d)  # default mesh path
    served = np.asarray(art.predict(feats))
    np.testing.assert_allclose(served, direct, rtol=2e-3, atol=2e-3)


def test_predict_caches_per_shape_and_counts_retraces(tmp_path):
    from edl_tpu.obs.metrics import get_registry

    mesh = single_mesh()
    model = fit_a_line.MODEL
    params = model.init(jax.random.PRNGKey(0), mesh)
    d = str(tmp_path / "cache")
    save_inference_model(d, "fit_a_line", params, step=1)
    art = load_inference_model(d, mesh=mesh)
    counter = get_registry().counter(
        "edl_trainer_retraces_total",
        "steady-state jit recompilations (shape/dtype churn in the hot loop)",
    )
    before = counter.value()
    x8 = np.zeros((8, 13), np.float32)
    art.predict({"x": x8})
    art.predict({"x": np.ones((8, 13), np.float32)})  # same shape: cached
    assert len(art._predict_cache) == 1
    assert counter.value() == before  # first shape is not a retrace
    art.predict({"x": np.zeros((16, 13), np.float32)})  # new shape
    assert len(art._predict_cache) == 2
    assert counter.value() == before + 1  # counted as a retrace


def test_predict_threaded_race_builds_one_executable(tmp_path):
    import threading

    mesh = single_mesh()
    model = fit_a_line.MODEL
    params = model.init(jax.random.PRNGKey(0), mesh)
    d = str(tmp_path / "race")
    save_inference_model(d, "fit_a_line", params, step=1)
    art = load_inference_model(d, mesh=mesh)
    barrier = threading.Barrier(8)
    errors = []

    def call():
        try:
            barrier.wait()
            for _ in range(4):
                art.predict({"x": np.zeros((4, 13), np.float32)})
        except Exception as e:  # pragma: no cover - surfaced via assert
            errors.append(e)

    threads = [threading.Thread(target=call) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert len(art._predict_cache) == 1
