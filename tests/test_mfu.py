"""FLOPs/MFU accounting (edl_tpu.tools.mfu + models' analytic formulas)."""

import dataclasses
import types

import jax
import numpy as np
import pytest

from edl_tpu.models import ctr, fit_a_line, mnist, resnet, transformer, word2vec
from edl_tpu.parallel import MeshSpec, build_mesh
from edl_tpu.tools.mfu import flops_per_step, mfu_fields, peak_tflops_per_chip


def test_every_zoo_model_declares_analytic_flops():
    for model in (ctr.MODEL, fit_a_line.MODEL, mnist.MODEL, resnet.MODEL,
                  word2vec.MODEL, transformer.MODEL):
        assert model.flops_per_step is not None, model.name
        f = model.flops_per_step(16)
        assert f > 0
        # linear in batch size by construction
        assert model.flops_per_step(32) == pytest.approx(2 * f)


def test_resnet50_matches_published_flops():
    # torchvision reports ~4.09 GMACs for ResNet-50 @ 224 => ~8.2 GFLOPs.
    fwd = resnet._flops_fwd_per_image(resnet.MODEL.config)
    assert 7.5e9 < fwd < 8.8e9


def test_transformer_flops_track_config():
    small = transformer.make_model(n_layers=2).flops_per_step(4)
    big = transformer.make_model(n_layers=4).flops_per_step(4)
    cfg = transformer.TransformerConfig()
    per_layer_fwd = (
        8 * cfg.d_model ** 2 + 4 * cfg.d_model * cfg.d_ff
        + 2 * cfg.seq_len * cfg.d_model
    )
    # adding 2 layers adds exactly their block FLOPs (head term constant)
    assert big - small == pytest.approx(3 * 2 * per_layer_fwd * cfg.seq_len * 4)


def test_peak_table_and_override(monkeypatch):
    v4 = types.SimpleNamespace(device_kind="TPU v4", platform="tpu")
    assert peak_tflops_per_chip(v4) == 275.0
    v6 = types.SimpleNamespace(device_kind="TPU v6e", platform="tpu")
    assert peak_tflops_per_chip(v6) == 918.0
    # the strings jax actually reports for v5e / Trillium
    v5l = types.SimpleNamespace(device_kind="TPU v5 lite", platform="tpu")
    assert peak_tflops_per_chip(v5l) == 197.0
    v6l = types.SimpleNamespace(device_kind="TPU v6 lite", platform="tpu")
    assert peak_tflops_per_chip(v6l) == 918.0
    cpu = types.SimpleNamespace(device_kind="cpu", platform="cpu")
    assert peak_tflops_per_chip(cpu) is None
    monkeypatch.setenv("EDL_TPU_PEAK_TFLOPS", "123.5")
    assert peak_tflops_per_chip(cpu) == 123.5


def test_mfu_fields_analytic():
    dev = types.SimpleNamespace(device_kind="TPU v4", platform="tpu")
    model = transformer.make_model(
        d_model=768, n_layers=12, n_heads=12, d_ff=3072, seq_len=1024
    )
    out = mfu_fields(model, 8, steps_per_sec=20.0, n_chips=1, device=dev)
    assert out["flops_method"] == "analytic"
    # 5.85e12 flops/step * 20 steps/s ~= 117 TF/s => ~42.5% of v4 peak
    assert out["tflops_per_sec"] == pytest.approx(116.9, rel=0.01)
    assert out["mfu"] == pytest.approx(0.425, abs=0.005)


def test_cost_analysis_fallback():
    mesh = build_mesh(MeshSpec({"data": 1}), jax.devices()[:1])
    bare = dataclasses.replace(fit_a_line.MODEL, flops_per_step=None)
    flops, method = flops_per_step(bare, 64, mesh)
    if flops is None:  # cost analysis availability varies by backend
        assert "unavailable" in method
    else:
        assert method == "xla_cost_analysis"
        # fwd+bwd of a (64, 13) linear regression: small but nonzero
        assert flops > 2 * 13 * 64


def test_mfu_fields_degrade_without_flops():
    bare = dataclasses.replace(fit_a_line.MODEL, flops_per_step=None)
    out = mfu_fields(bare, 64, steps_per_sec=10.0)
    assert out["model_flops"] is None
    assert out["mfu"] is None
