"""Multi-host SPMD training tests: lockstep rounds across real processes."""

import json
import os
import subprocess
import sys
import time

import pytest

from conftest import multiprocess_on_cpu
from edl_tpu.coordinator import CoordinatorServer
from edl_tpu.coordinator.server import ensure_built, free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER_SRC = """
import json, os, sys
sys.path.insert(0, {repo!r})
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")

from edl_tpu.launcher.launch import LaunchContext
from edl_tpu.launcher.discovery import wait_coordinator
from edl_tpu.models import fit_a_line
from edl_tpu.runtime import (
    ElasticConfig, FileShardSource, MultiHostWorker, SyntheticShardSource,
    distributed_init,
)
from edl_tpu.runtime.train_loop import TrainerConfig

ctx = LaunchContext.from_env()
client = wait_coordinator(ctx.coordinator_endpoint)
client.worker = os.environ.get("WORKER_NAME") or os.environ["EDL_POD_NAME"]
# 180 s: bring-up races the OTHER workers' first-jit compiles for this
# box's single core; 90 s flakes when suites run alongside (the outer
# communicate() deadlines still bound the test).
ident = distributed_init(ctx, client, timeout=180.0, jax_port={jax_port})
if os.environ.get("MODEL") == "ctr_small":
    from edl_tpu.models import ctr
    model = ctr.make_model(sparse_dim=503)
    model_ref, model_config = "ctr", {{"sparse_dim": 503}}
elif os.environ.get("MODEL") == "resnet_tiny":
    import dataclasses
    from edl_tpu.models import resnet
    model = resnet.make_model(resnet.TINY)
    # exports must rebuild TINY, not the default ResNet-50
    model_ref, model_config = "resnet", dataclasses.asdict(resnet.TINY)
else:
    model = fit_a_line.MODEL
    model_ref, model_config = "fit_a_line", None
exporter = None
if os.environ.get("EXPORT_DIR"):
    from edl_tpu.runtime import PeriodicExporter
    exporter = PeriodicExporter(
        os.environ["EXPORT_DIR"], model_ref,
        int(os.environ.get("EXPORT_INTERVAL", "5")),
        config=model_config,
        rank=ident.process_id if ident is not None else 0,
    )
if os.environ.get("FILE_SHARD_ROOT"):
    source = FileShardSource(root=os.environ["FILE_SHARD_ROOT"], batch_size=16)
else:
    source = SyntheticShardSource(model, batch_size=16,
                                  batches_per_shard=int(os.environ.get("BATCHES_PER_SHARD", "3")))
_sleep = float(os.environ.get("BATCH_SLEEP", "0"))
if _sleep:
    # Throttle for timing-sensitive tests: pins the workload's duration so a
    # "join mid-run" phase cannot end before the joiner's slow interpreter
    # startup, however fast the training path gets.
    class _Throttled:
        def __init__(self, inner):
            self.inner = inner
        def read(self, shard):
            import time as _t
            for b in self.inner.read(shard):
                _t.sleep(_sleep)
                yield b
        def batch_count(self, shard):
            return self.inner.batch_count(shard)
    source = _Throttled(source)
worker = MultiHostWorker(
    model,
    client,
    source,
    ElasticConfig(
        checkpoint_dir=os.environ["CKPT_DIR"],
        checkpoint_interval=int(os.environ.get("CKPT_INTERVAL", "1000")),
        rescale_barrier_timeout=30.0,
        step_callback=exporter,
        trainer=TrainerConfig(
            optimizer="sgd", learning_rate=0.05,
            wire_transport=os.environ.get("WIRE") == "1",
            wire_raw_keys=tuple(json.loads(os.environ.get("WIRE_RAW_KEYS", "[]"))),
        ),
    ),
)
metrics = worker.run()
if exporter is not None:
    exporter.wait()
    metrics["exports"] = exporter.exports
print("METRICS " + json.dumps(metrics))
"""


def spawn_worker(name, server, ckpt_dir, jax_port, num_trainers=2, extra_env=None):
    env = dict(os.environ)
    env["EDL_COORDINATOR_ENDPOINT"] = server.address
    env["EDL_NUM_TRAINERS"] = str(num_trainers)
    env["WORKER_NAME"] = name
    env["CKPT_DIR"] = ckpt_dir
    env.update(extra_env or {})
    src = WORKER_SRC.format(repo=REPO, jax_port=jax_port)
    return subprocess.Popen(
        [sys.executable, "-c", src], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )


@multiprocess_on_cpu
def test_two_process_lockstep_training(tmp_path):
    """Two processes drain one queue in lockstep on a single 4-device global
    mesh; both report identical step counts and the same final loss."""
    ensure_built()
    jax_port = free_port()
    with CoordinatorServer() as server:
        admin = server.client("admin")
        admin.add_tasks([f"mh/part-{i:05d}" for i in range(5)])  # odd: tail round
        procs = [
            spawn_worker(f"w{i}", server, str(tmp_path / "ck"), jax_port)
            for i in range(2)
        ]
        outs = [p.communicate(timeout=240) for p in procs]
        st = server.client("probe").status()
    metrics = []
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        line = [l for l in out.splitlines() if l.startswith("METRICS ")][0]
        metrics.append(json.loads(line[len("METRICS "):]))
    # lockstep: identical step counts; SPMD: identical (global) final loss
    assert metrics[0]["steps"] == metrics[1]["steps"] > 0
    assert metrics[0]["final_loss"] == pytest.approx(metrics[1]["final_loss"], abs=1e-6)
    assert metrics[0]["world"] == 2.0
    # 5 shards x 3 batches, tail round replicates -> 3 rounds x 3 steps
    assert metrics[0]["steps"] == 9.0
    # queue fully drained
    assert int(st["queued"]) == 0


def _run_two_process_ctr(tmp_path, tag, wire):
    jax_port = free_port()
    # Slack TTLs: the CTR first-step compile can outlast the default 10 s
    # heartbeat on a loaded single-core CI box, which would read as a
    # membership change and force a spurious rescale-restart.
    with CoordinatorServer(task_lease_sec=60.0, heartbeat_ttl_sec=60.0) as server:
        admin = server.client("admin")
        admin.add_tasks([f"wt/part-{i:05d}" for i in range(4)])
        extra = {
            "MODEL": "ctr_small",
            "WIRE": "1" if wire else "0",
            # dense floats would be lossy over bf16; keeping them raw makes
            # every encoded key (u24 sparse ids, u8 labels) EXACT, so wire
            # and raw transports must produce bit-identical training.
            "WIRE_RAW_KEYS": '["dense"]',
        }
        procs = [
            spawn_worker(f"w{i}", server, str(tmp_path / f"ck-{tag}"), jax_port,
                         extra_env=extra)
            for i in range(2)
        ]
        outs = [p.communicate(timeout=240) for p in procs]
    metrics = []
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        line = [l for l in out.splitlines() if l.startswith("METRICS ")][0]
        metrics.append(json.loads(line[len("METRICS "):]))
    return metrics


@multiprocess_on_cpu
def test_two_process_wire_transport_matches_raw(tmp_path):
    """VERDICT round-3 item 3: wire transport must serve multi-process jobs.
    The codec is negotiated once through the coordinator KV (rank 0 infers +
    publishes, rank 1 fetches), so both processes jit the identical decode
    program — and with exact encodings the training trajectory must match
    the raw-transport run bit-for-bit."""
    ensure_built()
    raw = _run_two_process_ctr(tmp_path, "raw", wire=False)
    wired = _run_two_process_ctr(tmp_path, "wire", wire=True)
    # both processes in lockstep within each run
    assert wired[0]["steps"] == wired[1]["steps"] == raw[0]["steps"] > 0
    assert wired[0]["final_loss"] == pytest.approx(wired[1]["final_loss"], abs=0)
    # wire transport changes the transport, not the math
    assert wired[0]["final_loss"] == pytest.approx(raw[0]["final_loss"], abs=1e-7)


@multiprocess_on_cpu
def test_elastic_rescale_one_to_two_processes(tmp_path):
    """The north-star path end-to-end: a world-1 job is joined by a second
    trainer; rank 0 detects the epoch bump, checkpoints, exits
    RESCALE_EXIT_CODE, the launcher relaunches it, and BOTH processes come
    back as one world-2 jax.distributed job that finishes the queue from the
    checkpoint."""
    ensure_built()
    jax_port = free_port()
    ckpt = str(tmp_path / "ck")
    launcher_src = f"""
import os, sys
sys.path.insert(0, {REPO!r})
from edl_tpu.launcher.launch import LaunchContext, start_trainer
ctx = LaunchContext.from_env()
sys.exit(start_trainer(ctx))
"""
    entry_py = tmp_path / "entry.py"
    entry_py.write_text(WORKER_SRC.format(repo=REPO, jax_port=jax_port))

    with CoordinatorServer(heartbeat_ttl_sec=5.0) as server:
        admin = server.client("admin")
        # The solo phase must outlive w1's interpreter+jax startup (tens of
        # seconds on a loaded box). Wall-clock is pinned by BATCH_SLEEP, not
        # by hoping training is slow: 120 shards x 40 x 10 ms >= ~48 s solo,
        # while the done>=2 join gate releases at the first checkpoint
        # commit (step 1000, ~25 rounds in).
        admin.add_tasks([f"mh/part-{i:05d}" for i in range(120)])
        admin.kv_put("edl/expected_world", "1")

        def spawn_launcher(name):
            env = dict(os.environ)
            env["EDL_COORDINATOR_ENDPOINT"] = server.address
            env["EDL_NUM_TRAINERS"] = "1"
            env["EDL_ENTRY"] = f"{sys.executable} {entry_py}"
            env["WORKER_NAME"] = name
            env["CKPT_DIR"] = ckpt
            env["BATCHES_PER_SHARD"] = "40"
            env["BATCH_SLEEP"] = "0.01"
            env["EDL_TERMINATION_LOG"] = str(tmp_path / f"term-{name}")
            return subprocess.Popen(
                [sys.executable, "-c", launcher_src], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )

        p0 = spawn_launcher("w0")
        # scale 1 -> 2 as soon as w0 has real progress (done shards), so the
        # queue cannot drain before the join on fast or slow boxes alike
        deadline = time.time() + 240
        while time.time() < deadline:
            if int(admin.status().get("done", 0)) >= 2:
                break
            time.sleep(0.5)
        else:
            pytest.fail("w0 never made progress")
        admin.kv_put("edl/expected_world", "2")
        p1 = spawn_launcher("w1")  # registration bumps the epoch -> w0 restarts

        outs = [p.communicate(timeout=300) for p in (p0, p1)]
        st = server.client("probe").status()
    for p, (out, err) in zip((p0, p1), outs):
        assert p.returncode == 0, f"launcher failed:\n{err[-3000:]}\n{out[-2000:]}"
    # both incarnations printed metrics; the final ones show world=2
    finals = []
    for out, _ in outs:
        lines = [l for l in out.splitlines() if l.startswith("METRICS ")]
        assert lines, out
        finals.append(json.loads(lines[-1][len("METRICS "):]))
    assert finals[0]["world"] == 2.0 and finals[1]["world"] == 2.0
    assert int(st["queued"]) == 0


def _inproc_client(tasks):
    """Real in-process coordinator (same contract as the C++ service) — no
    hand-rolled fake that could drift from the client surface."""
    from edl_tpu.coordinator import InProcessCoordinator

    coord = InProcessCoordinator()
    admin = coord.client("admin")
    admin.add_tasks(tasks)
    return coord.client("w0")


def _make_worker(client, tmp_path, batches_per_shard=3):
    from edl_tpu.models import fit_a_line
    from edl_tpu.runtime import ElasticConfig, MultiHostWorker, SyntheticShardSource

    return MultiHostWorker(
        fit_a_line.MODEL,
        client,
        SyntheticShardSource(fit_a_line.MODEL, batch_size=8,
                             batches_per_shard=batches_per_shard),
        ElasticConfig(checkpoint_dir=str(tmp_path / "ck")),
    )


def test_round_plan_gc_waits_for_collective(tmp_path):
    """ADVICE medium fix: plans are GC'd only once a later collective round
    proves every rank consumed them — never racing stragglers on wait-rounds."""
    client = _inproc_client(["s0", "s1", "s2", "s3"])
    ep = int(client.register()["epoch"])
    w = _make_worker(client, tmp_path)

    k = lambda r: f"edl/mh_round/{ep}/{r}"
    m0 = w._publish_round(epoch=ep, rnd=0, world=2)  # tasks round (not yet run)
    assert "tasks" in m0
    w._publish_round(epoch=ep, rnd=1, world=2)   # no collective seen yet:
    assert client.kv_get(k(0))                    # round 0 plan must survive
    assert client.kv_get(k(1))

    w._collective_hwm = 1                        # rounds 0-1 trained (barrier)
    w._publish_round(epoch=ep, rnd=2, world=2)
    assert client.kv_get(k(0)) is None           # now provably consumed
    assert client.kv_get(k(1)) is None
    assert client.kv_get(k(2))                   # current plan untouched


def test_round_plan_includes_lockstep_steps(tmp_path):
    """Rank 0 publishes the round's exact step count from source metadata
    (max over leased shards) so uneven shards cannot desync the collective."""
    client = _inproc_client(["a", "b"])
    ep = int(client.register()["epoch"])
    w = _make_worker(client, tmp_path, batches_per_shard=4)
    msg = w._publish_round(epoch=ep, rnd=0, world=2)
    assert sorted(msg["tasks"]) == ["a", "b"]
    assert msg["steps"] == 4
    assert json.loads(client.kv_get(f"edl/mh_round/{ep}/0"))["steps"] == 4


class _UnevenSource:
    """batch_count metadata with per-shard counts; read honors the counts
    except for shards listed in `lying` (metadata says n>0, read yields 0)."""

    def __init__(self, counts, lying=()):
        self.counts = counts
        self.lying = set(lying)

    def batch_count(self, shard):
        return self.counts[shard]

    def read(self, shard):
        if shard in self.lying:
            return
        for i in range(self.counts[shard]):
            yield {"x": shard, "i": i}


def test_publish_filters_empty_shards(tmp_path):
    """Genuinely empty shards are completed at publish time and never enter a
    plan, so no zero-step round (and no GC-race reopening) can occur."""
    client = _inproc_client(["e0", "full", "e1", "also"])
    ep = int(client.register()["epoch"])
    w = _make_worker(client, tmp_path)
    w.source = _UnevenSource({"e0": 0, "full": 3, "e1": 0, "also": 2})
    msg = w._publish_round(epoch=ep, rnd=0, world=4)
    assert sorted(msg["tasks"]) == ["also", "full"]
    assert msg["steps"] == 3
    st = client.status()
    assert int(st["done"]) == 2  # e0/e1 completed untrained (logged)


def test_padded_batches_cycles_short_shard(tmp_path):
    """A shard shorter than the round's step count pads by cycling its own
    batches — lockstep preserved, no data dropped."""
    client = _inproc_client([])
    w = _make_worker(client, tmp_path)
    w.source = _UnevenSource({"short": 2, "long": 5})
    got = list(w._padded_batches("short", ["short", "long"], steps=5))
    assert len(got) == 5
    assert [b["i"] for b in got] == [0, 1, 0, 1, 0]  # cycled


def test_padded_batches_falls_back_to_peer_shard(tmp_path):
    """Inconsistent metadata (count>0 but read empty) pads from a peer shard
    in the same plan instead of crashing the gang."""
    client = _inproc_client([])
    w = _make_worker(client, tmp_path)
    w.source = _UnevenSource({"bad": 3, "good": 3}, lying={"bad"})
    got = list(w._padded_batches("bad", ["bad", "good"], steps=3))
    assert len(got) == 3
    assert all(b["x"] == "good" for b in got)


def test_padded_batches_exits_when_all_shards_unreadable(tmp_path):
    """Every shard unreadable -> exit RESCALE_EXIT_CODE for a gang restart."""
    from edl_tpu.launcher.launch import RESCALE_EXIT_CODE

    client = _inproc_client([])
    w = _make_worker(client, tmp_path)
    w.source = _UnevenSource({"a": 2, "b": 2}, lying={"a", "b"})
    with pytest.raises(SystemExit) as ei:
        list(w._padded_batches("a", ["a", "b"], steps=2))
    assert ei.value.code == RESCALE_EXIT_CODE


class _NoMetaSource:
    """No batch_count attribute: forces the no-metadata lockstep path."""

    def __init__(self, model, counts):
        self.model = model
        self.counts = counts

    def read(self, shard):
        import numpy as np

        rng = np.random.default_rng(0)
        for _ in range(self.counts[shard]):
            yield self.model.synthetic_batch(rng, 8)


@multiprocess_on_cpu
def test_zero_step_round_requeues_before_completing(tmp_path):
    """Rank 0 observing a zero-step round (no-metadata path) must NOT complete
    the shards on its local observation alone — another rank may hold
    un-checkpointed updates from them (round-2 advisor finding e). First zero
    round requeues for replay; a shard zero a second time is genuinely empty
    and completes, so no livelock."""
    from edl_tpu.models import fit_a_line

    client = _inproc_client(["empty", "full"])
    w = _make_worker(client, tmp_path)
    w.source = _NoMetaSource(fit_a_line.MODEL, {"empty": 0, "full": 2})

    fails = []
    orig_fail = client.fail_task
    client.fail_task = lambda t: (fails.append(t), orig_fail(t))[1]

    metrics = w.run()
    assert fails == ["empty"]  # requeued once, not completed blind
    st = client.status()
    assert int(st["done"]) == 2 and int(st["queued"]) == 0
    assert metrics["steps"] == 2.0  # 'full' trained exactly its batches


def test_prefetch_iter_preserves_order_and_exceptions():
    """Batch-level read-ahead must be order-identical to plain iteration and
    re-raise producer exceptions (incl. SystemExit) in the consumer."""
    from edl_tpu.runtime.data import prefetch_iter

    assert list(prefetch_iter(iter(range(20)))) == list(range(20))

    def boom():
        yield 1
        yield 2
        raise SystemExit(75)

    got = []
    with pytest.raises(SystemExit) as ei:
        for x in prefetch_iter(boom()):
            got.append(x)
    assert got == [1, 2] and ei.value.code == 75


def test_multihost_prefetch_config_trains_identically(tmp_path):
    """ElasticConfig.prefetch on the lockstep worker: same steps, same
    completion bookkeeping as the synchronous path."""
    from edl_tpu.models import fit_a_line
    from edl_tpu.runtime import ElasticConfig, MultiHostWorker, SyntheticShardSource
    from edl_tpu.runtime.train_loop import TrainerConfig

    results = {}
    for tag, prefetch in (("sync", False), ("pre", True)):
        client = _inproc_client(["p0", "p1", "p2"])
        w = MultiHostWorker(
            fit_a_line.MODEL,
            client,
            SyntheticShardSource(fit_a_line.MODEL, batch_size=8,
                                 batches_per_shard=3),
            ElasticConfig(checkpoint_dir=str(tmp_path / f"ck-{tag}"),
                          prefetch=prefetch,
                          trainer=TrainerConfig(optimizer="sgd",
                                                learning_rate=0.05)),
        )
        m = w.run()
        st = client.status()
        results[tag] = (m["steps"], m["final_loss"], st["done"], st["queued"])
    assert results["sync"] == results["pre"]
    assert results["pre"][2] == 3  # all shards completed


@multiprocess_on_cpu
def test_two_process_export_gathers_sharded_tables(tmp_path):
    """Multi-host serving export: the CTR tables are row-sharded across the
    2-process global mesh (not fully addressable on any rank), so the
    gather must be the collective process_allgather path; rank 0 writes an
    artifact that then serves single-process."""
    import numpy as np

    from edl_tpu.runtime import load_inference_model

    ensure_built()
    jax_port = free_port()
    export_dir = str(tmp_path / "serve")
    with CoordinatorServer(task_lease_sec=60.0, heartbeat_ttl_sec=60.0) as server:
        admin = server.client("admin")
        admin.add_tasks([f"ex/part-{i:05d}" for i in range(4)])
        extra = {"MODEL": "ctr_small", "EXPORT_DIR": export_dir,
                 "EXPORT_INTERVAL": "3"}
        procs = [
            spawn_worker(f"w{i}", server, str(tmp_path / "ck"), jax_port,
                         extra_env=extra)
            for i in range(2)
        ]
        outs = [p.communicate(timeout=240) for p in procs]
    per_rank_exports = []
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        line = [l for l in out.splitlines() if l.startswith("METRICS ")][0]
        m = json.loads(line[len("METRICS "):])
        # 4 shards / 2 procs lockstep x 3 batches -> 6 steps on both ranks
        assert m["steps"] == 6.0
        per_rank_exports.append(m["exports"])
    assert sorted(per_rank_exports) == [0, 2]  # writer rank only: steps 3, 6

    art = load_inference_model(export_dir)
    assert art.step == 6
    assert art.config == {"sparse_dim": 503}
    batch = art.model.synthetic_batch(np.random.default_rng(3), 32)
    logits = np.asarray(art.predict(
        {"dense": batch["dense"], "sparse": batch["sparse"]}
    ))
    assert logits.shape == (32,) and np.isfinite(logits).all()


class _FlakyDrainClient:
    """fail_task fails per script; records the attempted tasks."""

    def __init__(self, outcomes):
        self.outcomes = list(outcomes)  # True = ok, Exception = raise
        self.attempted = []
        self.left = False

    def fail_task(self, task):
        self.attempted.append(task)
        out = self.outcomes.pop(0) if self.outcomes else True
        if isinstance(out, Exception):
            raise out

    def leave(self):
        self.left = True


def _drain_worker(tmp_path, client, shards):
    from edl_tpu.models import fit_a_line
    from edl_tpu.runtime import ElasticConfig
    from edl_tpu.runtime.multihost import MultiHostWorker

    w = MultiHostWorker(
        fit_a_line.MODEL, client, source=None,
        config=ElasticConfig(checkpoint_dir=str(tmp_path / "ck")),
    )
    w._uncommitted = list(shards)
    return w


@multiprocess_on_cpu
def test_graceful_leave_continues_past_transient_failure(tmp_path):
    from edl_tpu.coordinator import CoordinatorError

    client = _FlakyDrainClient([True, CoordinatorError("blip"), True, True])
    w = _drain_worker(tmp_path, client, ["s0", "s1", "s2", "s3"])
    with pytest.raises(SystemExit):
        w._graceful_leave()
    # one transient hiccup must not abandon the remaining requeues
    assert client.attempted == ["s0", "s1", "s2", "s3"]
    assert client.left
    assert w._uncommitted == []


@multiprocess_on_cpu
def test_graceful_leave_stops_when_coordinator_gone(tmp_path):
    from edl_tpu.coordinator import CoordinatorError

    client = _FlakyDrainClient(
        [CoordinatorError("down"), CoordinatorError("down"), True]
    )
    w = _drain_worker(tmp_path, client, ["s0", "s1", "s2", "s3"])
    with pytest.raises(SystemExit):
        w._graceful_leave()
    # two consecutive failures = coordinator gone; stop burning the pod's
    # termination grace on reconnect timeouts (TTL expiry covers the rest)
    assert client.attempted == ["s0", "s1"]
