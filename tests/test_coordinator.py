"""Coordinator tests, run against BOTH the native C++ service and its
pure-Python twin — same suite, same semantics (membership epochs, dense
re-ranking, lease requeue, barriers, KV).

Covers the behaviors the reference delegated to master/etcd: task leases that
requeue on timeout/departure (at-least-once), membership-driven epochs, and
real barriers replacing sleep-and-poll (docker/paddle_k8s:128-130,178).
"""

import threading
import time

import pytest

from edl_tpu.coordinator import CoordinatorServer, InProcessCoordinator
from edl_tpu.coordinator.server import ensure_built


def has_toolchain():
    try:
        ensure_built()
        return True
    except Exception:
        return False


@pytest.fixture(params=["inprocess", "native"])
def coord(request):
    """Yields a factory: client(worker_name) -> client object."""
    if request.param == "native":
        if not has_toolchain():
            pytest.skip("no C++ toolchain / build failed")
        server = CoordinatorServer(task_lease_sec=1.0, heartbeat_ttl_sec=1.5)
        server.start()
        yield server
        server.stop()
    else:
        yield InProcessCoordinator(task_lease_sec=1.0, heartbeat_ttl_sec=1.5)


def test_register_rank_epoch_world(coord):
    a = coord.client("worker-a")
    b = coord.client("worker-b")
    ra = a.register()
    rb = b.register()
    assert ra["rank"] == 0 and rb["rank"] == 1
    assert rb["world"] == 2
    assert rb["epoch"] > ra["epoch"]
    assert a.members() == ["worker-a", "worker-b"]
    a.leave()
    b.leave()


def test_leave_reranks_and_bumps_epoch(coord):
    names = ["w0", "w1", "w2"]
    clients = [coord.client(n) for n in names]
    for c in clients:
        c.register()
    epoch_before = clients[0].heartbeat()["epoch"]
    clients[0].leave()  # rank-0 departs
    hb = clients[1].heartbeat()
    assert hb["epoch"] > epoch_before
    assert hb["world"] == 2
    assert hb["rank"] == 0  # dense re-rank: w1 slides into rank 0
    assert clients[2].heartbeat()["rank"] == 1
    for c in clients[1:]:
        c.leave()


def test_heartbeat_expiry_drops_member(coord):
    a = coord.client("hb-a")
    b = coord.client("hb-b")
    a.register()
    b.register()
    # Only b heartbeats; a expires after ttl (1.5s).
    deadline = time.monotonic() + 3.0
    while time.monotonic() < deadline and "hb-a" in b.members():
        b.heartbeat()
        time.sleep(0.2)
    assert b.members() == ["hb-b"]
    assert b.heartbeat()["rank"] == 0
    b.leave()


def test_task_queue_lease_complete_and_requeue(coord):
    w = coord.client("tq-w")
    w.register()
    assert w.add_tasks(["shard-0", "shard-1", "shard-2"]) == 3
    t1 = w.acquire_task()
    assert t1 == "shard-0"
    w.complete_task(t1)
    t2 = w.acquire_task()
    w.fail_task(t2)  # explicit fail -> requeued at the back
    seen = {w.acquire_task(), w.acquire_task()}
    assert seen == {"shard-1", "shard-2"} - {t2} | {t2}
    # duplicates of completed tasks are not re-added
    assert w.add_tasks(["shard-0"]) == 0
    w.leave()


def test_lease_timeout_requeues(coord):
    w = coord.client("lt-w")
    w.register()
    w.add_tasks(["slow-shard"])
    t = w.acquire_task()
    assert t == "slow-shard"
    time.sleep(1.3)  # lease is 1.0s
    # after expiry another worker can take it
    w2 = coord.client("lt-w2")
    w2.register()
    got = None
    deadline = time.monotonic() + 2.0
    while got is None and time.monotonic() < deadline:
        got = w2.acquire_task()
        time.sleep(0.1)
    assert got == "slow-shard"
    w2.complete_task(got)
    w.leave()
    w2.leave()


def test_departed_worker_leases_requeue_immediately(coord):
    a = coord.client("dep-a")
    b = coord.client("dep-b")
    a.register()
    b.register()
    a.add_tasks(["chunk-x"])
    assert a.acquire_task() == "chunk-x"
    a.leave()  # departure returns the lease without waiting for expiry
    assert b.acquire_task() == "chunk-x"
    b.complete_task("chunk-x")
    b.leave()


def test_barrier_releases_all(coord):
    n = 3
    clients = [coord.client(f"bar-{i}") for i in range(n)]
    for c in clients:
        c.register()
    results = [None] * n

    def arrive(i):
        results[i] = clients[i].barrier("step-sync", n, timeout=10.0)

    threads = [threading.Thread(target=arrive, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=15)
    assert all(r is not None and r["ok"] for r in results), results
    # reusable: second generation works too
    threads = [threading.Thread(target=arrive, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=15)
    assert all(r["ok"] for r in results)
    for c in clients:
        c.leave()


def test_kv_roundtrip(coord):
    c = coord.client("kv-w")
    c.kv_put("checkpoint/latest", "step-1000")
    assert c.kv_get("checkpoint/latest") == "step-1000"
    c.kv_del("checkpoint/latest")
    assert c.kv_get("checkpoint/latest") is None
    assert c.kv_get("never-set") is None


def test_status_counts(coord):
    c = coord.client("st-w")
    c.register()
    st = c.status()
    assert st["ok"] and st["world"] >= 1
    c.leave()


def test_stale_worker_cannot_complete_others_lease(coord):
    """Lease ownership: after expiry + re-lease, the late original worker's
    complete must be rejected, not steal the new lease."""
    a = coord.client("own-a")
    b = coord.client("own-b")
    a.register()
    b.register()
    a.add_tasks(["contested"])
    assert a.acquire_task() == "contested"
    time.sleep(1.3)  # a's lease (1.0s) expires
    got = None
    deadline = time.monotonic() + 2.0
    while got is None and time.monotonic() < deadline:
        got = b.acquire_task()
        time.sleep(0.05)
    assert got == "contested"
    late = a.complete_task("contested")
    assert late["ok"] is False  # rejected: b owns it now
    assert b.complete_task("contested")["ok"] is True
    a.leave()
    b.leave()


def test_kv_non_ascii_and_control_chars_roundtrip(coord):
    c = coord.client("enc-w")
    c.kv_put("path", "café/中文")
    assert c.kv_get("path") == "café/中文"
    c.kv_put("ctl", "a\x01b\x0bc")
    assert c.kv_get("ctl") == "a\x01b\x0bc"


def test_sync_rendezvous_all_members(coord):
    """Epoch sync: released only when every member arrives; a joiner mid-wait
    forces resync with the new epoch."""
    a = coord.client("sy-a")
    b = coord.client("sy-b")
    ea = a.register()["epoch"]
    eb = b.register()["epoch"]
    results = {}

    def arrive(name, cli, epoch):
        results[name] = cli.sync(epoch, timeout=10.0)

    # a syncs at its stale epoch -> immediate resync reply
    stale = a.sync(ea, timeout=5.0)
    assert stale["ok"] is False and stale.get("resync") is True
    assert stale["epoch"] == eb

    ta = threading.Thread(target=arrive, args=("a", a, eb))
    tb = threading.Thread(target=arrive, args=("b", b, eb))
    ta.start()
    time.sleep(0.2)
    tb.start()
    ta.join(timeout=15)
    tb.join(timeout=15)
    assert results["a"]["ok"] and results["b"]["ok"], results
    assert results["a"]["world"] == 2
    a.leave()
    b.leave()


def test_sync_released_with_resync_on_join(coord):
    """A parked sync waiter is woken with resync when membership moves."""
    a = coord.client("syj-a")
    b = coord.client("syj-b")
    a.register()
    epoch = b.register()["epoch"]
    result = {}

    def waiter():
        # a parks: b never arrives at this epoch
        result["r"] = a.sync(epoch, timeout=10.0)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.3)
    c = coord.client("syj-c")  # join bumps the epoch -> waiter gets resync
    c.register()
    t.join(timeout=15)
    r = result["r"]
    assert r["ok"] is False and r.get("resync") is True and r["world"] == 3
    for cli in (a, b, c):
        cli.leave()


# -- deployability: bind address, durability, barrier contract ----------------


def _local_nonloopback_ip():
    import socket as _s

    try:
        with _s.socket(_s.AF_INET, _s.SOCK_DGRAM) as probe:
            probe.connect(("10.255.255.255", 1))  # no packets sent (UDP)
            return probe.getsockname()[0]
    except OSError:
        return None


def test_native_binds_all_interfaces_cross_interface_connect():
    """Trainers on other hosts dial the coordinator's service address — the
    listener must not be loopback-only (VERDICT missing #3a)."""
    if not has_toolchain():
        pytest.skip("no C++ toolchain")
    from edl_tpu.coordinator.client import CoordinatorClient

    ip = _local_nonloopback_ip()
    server = CoordinatorServer()
    server.start()
    try:
        assert server.client("probe").ping()
        if ip:  # connect via the machine's real interface, not loopback
            with CoordinatorClient(host=ip, port=server.port, worker="x") as c:
                assert c.ping()
    finally:
        server.stop()


def test_native_state_survives_kill_and_restart(tmp_path):
    """SIGKILL the coordinator mid-job and restart it on the same state file:
    the done-set survives (no full dataset replay), live leases requeue, and
    the epoch moves forward so reconnecting workers re-rendezvous (VERDICT
    missing #3b — the reference persisted this via its etcd sidecar,
    /root/reference/pkg/jobparser.go:167-184)."""
    if not has_toolchain():
        pytest.skip("no C++ toolchain")
    state = str(tmp_path / "coord-state.jsonl")
    port = None

    server = CoordinatorServer(state_file=state)
    server.start()
    port = server.port
    try:
        w = server.client("w0")
        epoch_before = int(w.register()["epoch"])
        w.add_tasks([f"t{i}" for i in range(6)])
        done_tasks = []
        for _ in range(2):
            t = w.acquire_task()
            w.complete_task(t)
            done_tasks.append(t)
        leased_not_done = w.acquire_task()  # live lease at crash time
        w.kv_put("edl/ckpt_meta", "step=200")
        time.sleep(0.3)  # allow the event loop's save point to run
    finally:
        server.kill()  # hard crash: no graceful shutdown path

    server2 = CoordinatorServer(port=port, state_file=state)
    server2.start()
    try:
        w = server2.client("w0")
        info = w.register()
        assert int(info["epoch"]) > epoch_before  # restart is a membership event
        st = w.status()
        assert int(st["done"]) == 2              # done-set survived: no replay
        assert int(st["queued"]) == 4            # 3 todo + 1 requeued live lease
        assert w.kv_get("edl/ckpt_meta") == "step=200"
        remaining = set()
        while True:
            t = w.acquire_task()
            if t is None:
                break
            remaining.add(t)
        assert leased_not_done in remaining      # at-least-once: lease replayed
        assert not remaining & set(done_tasks)   # completed work NOT replayed
    finally:
        server2.stop()


def test_barrier_count_mismatch_rejected(coord):
    """Two cohorts sharing a barrier name with different counts must not
    release each other: the first arrival of a cycle fixes the count
    (VERDICT weak #5)."""
    a = coord.client("a")
    b = coord.client("b")
    a.register()
    b.register()

    results = {}

    def arrive(cl, name, count, key):
        results[key] = cl.barrier(name, count=count)

    ta = threading.Thread(target=arrive, args=(a, "step", 2, "a"))
    ta.start()
    time.sleep(0.3)  # a arrived first: count fixed at 2
    mismatch = b.barrier("step", count=3)
    assert mismatch.get("ok") is False
    assert "mismatch" in mismatch.get("error", "")
    # agreeing cohort still completes
    ok = b.barrier("step", count=2)
    ta.join(timeout=10)
    assert ok.get("ok") is True
    assert results["a"].get("ok") is True
