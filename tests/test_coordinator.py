"""Coordinator tests, run against BOTH the native C++ service and its
pure-Python twin — same suite, same semantics (membership epochs, dense
re-ranking, lease requeue, barriers, KV).

Covers the behaviors the reference delegated to master/etcd: task leases that
requeue on timeout/departure (at-least-once), membership-driven epochs, and
real barriers replacing sleep-and-poll (docker/paddle_k8s:128-130,178).
"""

import threading
import time

import pytest

from edl_tpu.coordinator import CoordinatorServer, InProcessCoordinator
from edl_tpu.coordinator.server import ensure_built


def has_toolchain():
    try:
        ensure_built()
        return True
    except Exception:
        return False


@pytest.fixture(params=["inprocess", "native"])
def coord(request):
    """Yields a factory: client(worker_name) -> client object."""
    if request.param == "native":
        if not has_toolchain():
            pytest.skip("no C++ toolchain / build failed")
        server = CoordinatorServer(task_lease_sec=1.0, heartbeat_ttl_sec=1.5)
        server.start()
        yield server
        server.stop()
    else:
        yield InProcessCoordinator(task_lease_sec=1.0, heartbeat_ttl_sec=1.5)


def test_register_rank_epoch_world(coord):
    a = coord.client("worker-a")
    b = coord.client("worker-b")
    ra = a.register()
    rb = b.register()
    assert ra["rank"] == 0 and rb["rank"] == 1
    assert rb["world"] == 2
    assert rb["epoch"] > ra["epoch"]
    assert a.members() == ["worker-a", "worker-b"]
    a.leave()
    b.leave()


def test_leave_reranks_and_bumps_epoch(coord):
    names = ["w0", "w1", "w2"]
    clients = [coord.client(n) for n in names]
    for c in clients:
        c.register()
    epoch_before = clients[0].heartbeat()["epoch"]
    clients[0].leave()  # rank-0 departs
    hb = clients[1].heartbeat()
    assert hb["epoch"] > epoch_before
    assert hb["world"] == 2
    assert hb["rank"] == 0  # dense re-rank: w1 slides into rank 0
    assert clients[2].heartbeat()["rank"] == 1
    for c in clients[1:]:
        c.leave()


def test_heartbeat_expiry_drops_member(coord):
    a = coord.client("hb-a")
    b = coord.client("hb-b")
    a.register()
    b.register()
    # Only b heartbeats; a expires after ttl (1.5s).
    deadline = time.monotonic() + 3.0
    while time.monotonic() < deadline and "hb-a" in b.members():
        b.heartbeat()
        time.sleep(0.2)
    assert b.members() == ["hb-b"]
    assert b.heartbeat()["rank"] == 0
    b.leave()


def test_task_queue_lease_complete_and_requeue(coord):
    w = coord.client("tq-w")
    w.register()
    assert w.add_tasks(["shard-0", "shard-1", "shard-2"]) == 3
    t1 = w.acquire_task()
    assert t1 == "shard-0"
    w.complete_task(t1)
    t2 = w.acquire_task()
    w.fail_task(t2)  # explicit fail -> requeued at the back
    seen = {w.acquire_task(), w.acquire_task()}
    assert seen == {"shard-1", "shard-2"} - {t2} | {t2}
    # duplicates of completed tasks are not re-added
    assert w.add_tasks(["shard-0"]) == 0
    w.leave()


def test_lease_timeout_requeues(coord):
    w = coord.client("lt-w")
    w.register()
    w.add_tasks(["slow-shard"])
    t = w.acquire_task()
    assert t == "slow-shard"
    time.sleep(1.3)  # lease is 1.0s
    # after expiry another worker can take it
    w2 = coord.client("lt-w2")
    w2.register()
    got = None
    deadline = time.monotonic() + 2.0
    while got is None and time.monotonic() < deadline:
        got = w2.acquire_task()
        time.sleep(0.1)
    assert got == "slow-shard"
    w2.complete_task(got)
    w.leave()
    w2.leave()


def test_departed_worker_leases_requeue_immediately(coord):
    a = coord.client("dep-a")
    b = coord.client("dep-b")
    a.register()
    b.register()
    a.add_tasks(["chunk-x"])
    assert a.acquire_task() == "chunk-x"
    a.leave()  # departure returns the lease without waiting for expiry
    assert b.acquire_task() == "chunk-x"
    b.complete_task("chunk-x")
    b.leave()


@pytest.mark.sanitizer
def test_barrier_releases_all(coord):
    n = 3
    clients = [coord.client(f"bar-{i}") for i in range(n)]
    for c in clients:
        c.register()
    results = [None] * n

    def arrive(i):
        results[i] = clients[i].barrier("step-sync", n, timeout=10.0)

    threads = [threading.Thread(target=arrive, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=15)
    assert all(r is not None and r["ok"] for r in results), results
    # reusable: second generation works too
    threads = [threading.Thread(target=arrive, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=15)
    assert all(r["ok"] for r in results)
    for c in clients:
        c.leave()


def test_kv_roundtrip(coord):
    c = coord.client("kv-w")
    c.kv_put("checkpoint/latest", "step-1000")
    assert c.kv_get("checkpoint/latest") == "step-1000"
    c.kv_del("checkpoint/latest")
    assert c.kv_get("checkpoint/latest") is None
    assert c.kv_get("never-set") is None


def test_status_counts(coord):
    c = coord.client("st-w")
    c.register()
    st = c.status()
    assert st["ok"] and st["world"] >= 1
    c.leave()


def test_stale_worker_cannot_complete_others_lease(coord):
    """Lease ownership: after expiry + re-lease, the late original worker's
    complete must be rejected, not steal the new lease."""
    a = coord.client("own-a")
    b = coord.client("own-b")
    a.register()
    b.register()
    a.add_tasks(["contested"])
    assert a.acquire_task() == "contested"
    time.sleep(1.3)  # a's lease (1.0s) expires
    got = None
    deadline = time.monotonic() + 2.0
    while got is None and time.monotonic() < deadline:
        got = b.acquire_task()
        time.sleep(0.05)
    assert got == "contested"
    late = a.complete_task("contested")
    assert late["ok"] is False  # rejected: b owns it now
    assert b.complete_task("contested")["ok"] is True
    a.leave()
    b.leave()


def test_kv_non_ascii_and_control_chars_roundtrip(coord):
    c = coord.client("enc-w")
    c.kv_put("path", "café/中文")
    assert c.kv_get("path") == "café/中文"
    c.kv_put("ctl", "a\x01b\x0bc")
    assert c.kv_get("ctl") == "a\x01b\x0bc"


@pytest.mark.sanitizer
def test_sync_rendezvous_all_members(coord):
    """Epoch sync: released only when every member arrives; a joiner mid-wait
    forces resync with the new epoch."""
    a = coord.client("sy-a")
    b = coord.client("sy-b")
    ea = a.register()["epoch"]
    eb = b.register()["epoch"]
    results = {}

    def arrive(name, cli, epoch):
        results[name] = cli.sync(epoch, timeout=10.0)

    # a syncs at its stale epoch -> immediate resync reply
    stale = a.sync(ea, timeout=5.0)
    assert stale["ok"] is False and stale.get("resync") is True
    assert stale["epoch"] == eb

    ta = threading.Thread(target=arrive, args=("a", a, eb))
    tb = threading.Thread(target=arrive, args=("b", b, eb))
    ta.start()
    time.sleep(0.2)
    tb.start()
    ta.join(timeout=15)
    tb.join(timeout=15)
    assert results["a"]["ok"] and results["b"]["ok"], results
    assert results["a"]["world"] == 2
    a.leave()
    b.leave()


def test_sync_released_with_resync_on_join(coord):
    """A parked sync waiter is woken with resync when membership moves."""
    a = coord.client("syj-a")
    b = coord.client("syj-b")
    a.register()
    epoch = b.register()["epoch"]
    result = {}

    def waiter():
        # a parks: b never arrives at this epoch
        result["r"] = a.sync(epoch, timeout=10.0)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.3)
    c = coord.client("syj-c")  # join bumps the epoch -> waiter gets resync
    c.register()
    t.join(timeout=15)
    r = result["r"]
    assert r["ok"] is False and r.get("resync") is True and r["world"] == 3
    for cli in (a, b, c):
        cli.leave()


# -- deployability: bind address, durability, barrier contract ----------------


def _local_nonloopback_ip():
    import socket as _s

    try:
        with _s.socket(_s.AF_INET, _s.SOCK_DGRAM) as probe:
            probe.connect(("10.255.255.255", 1))  # no packets sent (UDP)
            return probe.getsockname()[0]
    except OSError:
        return None


def test_native_binds_all_interfaces_when_asked():
    """The pod launcher passes host=0.0.0.0 (trainers on other hosts dial the
    coordinator's service address) — that explicit opt-in must expose the
    port cross-interface."""
    if not has_toolchain():
        pytest.skip("no C++ toolchain")
    from edl_tpu.coordinator.client import CoordinatorClient

    ip = _local_nonloopback_ip()
    server = CoordinatorServer(host="0.0.0.0")
    server.start()
    try:
        assert server.client("probe").ping()
        if ip:  # connect via the machine's real interface, not loopback
            with CoordinatorClient(host=ip, port=server.port, worker="x") as c:
                assert c.ping()
    finally:
        server.stop()


def test_native_default_bind_is_loopback_only():
    """The protocol is unauthenticated, so the DEFAULT bind must be loopback:
    exposure beyond the host is a deployment decision the launcher makes
    explicitly (round-2 advisor finding d)."""
    if not has_toolchain():
        pytest.skip("no C++ toolchain")
    from edl_tpu.coordinator.client import CoordinatorClient, CoordinatorError

    ip = _local_nonloopback_ip()
    server = CoordinatorServer()  # no host argument: the default
    server.start()
    try:
        assert server.client("probe").ping()  # loopback works
        if ip:
            with pytest.raises(CoordinatorError):
                with CoordinatorClient(
                    host=ip, port=server.port, worker="x", connect_timeout=1.0
                ) as c:
                    c.ping()
    finally:
        server.stop()


@pytest.mark.sanitizer
def test_native_state_survives_kill_and_restart(tmp_path):
    """SIGKILL the coordinator mid-job and restart it on the same state file:
    the done-set survives (no full dataset replay), live leases are restored
    UNDER THEIR HOLDER with a fresh TTL (so a worker that rode out the outage
    keeps its shard and nobody double-trains it; a dead holder's shard
    requeues on expiry), and the epoch moves forward so reconnecting workers
    re-rendezvous (VERDICT missing #3b — the reference persisted this via its
    etcd sidecar, /root/reference/pkg/jobparser.go:167-184)."""
    if not has_toolchain():
        pytest.skip("no C++ toolchain")
    state = str(tmp_path / "coord-state.jsonl")
    port = None

    server = CoordinatorServer(state_file=state)
    server.start()
    port = server.port
    try:
        w = server.client("w0")
        epoch_before = int(w.register()["epoch"])
        w.add_tasks([f"t{i}" for i in range(6)])
        done_tasks = []
        for _ in range(2):
            t = w.acquire_task()
            w.complete_task(t)
            done_tasks.append(t)
        leased_not_done = w.acquire_task()  # live lease at crash time
        w.kv_put("edl/ckpt_meta", "step=200")
        # NO sleep: a mutating op's ack means the delta is already fsynced
        # (ack-after-durability) — kill -9 the instant the reply arrives.
    finally:
        server.kill()  # hard crash: no graceful shutdown path

    # Short lease TTL so the dead-holder expiry half of the semantics is
    # testable without a 16 s wait.
    server2 = CoordinatorServer(port=port, state_file=state,
                                task_lease_sec=0.5)
    server2.start()
    try:
        w = server2.client("w0")
        info = w.register()
        assert int(info["epoch"]) > epoch_before  # restart is a membership event
        st = w.status()
        assert int(st["done"]) == 2              # done-set survived: no replay
        assert int(st["queued"]) == 3            # untouched todo only
        assert int(st["leased"]) == 1            # live lease survived WITH holder
        assert w.kv_get("edl/ckpt_meta") == "step=200"
        # The surviving holder can complete its restored lease directly —
        # exactly what a worker draining its outbox after reconnect does.
        assert w.complete_task(leased_not_done).get("ok")
        remaining = set()
        while True:
            t = w.acquire_task()
            if t is None:
                break
            remaining.add(t)
            w.complete_task(t)
        assert len(remaining) == 3               # the 3 never-touched shards
        assert not remaining & set(done_tasks)   # completed work NOT replayed
        assert leased_not_done not in remaining  # ...and no double-assign
    finally:
        server2.stop()

    # Dead-holder path: crash again with w1 holding a lease, restart, and
    # let the restored lease EXPIRE (w1 never reconnects): the shard then
    # requeues for the survivors — at-least-once, nothing lost.
    server3 = CoordinatorServer(port=port, state_file=state,
                                task_lease_sec=0.5)
    server3.start()
    try:
        w1 = server3.client("w1")
        w1.register()
        w1.add_tasks(["orphan-shard"])
        orphan = w1.acquire_task()
        assert orphan == "orphan-shard"
    finally:
        server3.kill()
    server4 = CoordinatorServer(port=port, state_file=state,
                                task_lease_sec=0.5)
    server4.start()
    try:
        w = server4.client("w0")
        w.register()
        deadline = time.monotonic() + 10.0
        recovered = None
        while time.monotonic() < deadline:
            t = w.acquire_task()
            if t == orphan:
                recovered = t
                break
            if t is not None:
                w.complete_task(t)
            time.sleep(0.1)
        assert recovered == orphan  # expired orphan lease requeued
    finally:
        server4.stop()


def test_native_state_run_id_mismatch_discards(tmp_path):
    """A fresh run booted over ANOTHER run's state file must not resume its
    done-set — that would silently 'complete' the new job having trained
    nothing (round-2 advisor finding a). Same run-id resumes; different
    run-id discards."""
    if not has_toolchain():
        pytest.skip("no C++ toolchain")
    state = str(tmp_path / "coord-state.jsonl")

    server = CoordinatorServer(state_file=state, run_id="run-A")
    server.start()
    port = server.port
    try:
        w = server.client("w0")
        w.register()
        w.add_tasks(["s0", "s1", "s2"])
        w.complete_task(w.acquire_task())
    finally:
        server.kill()

    # Same run restarts (coordinator pod crash): resume, no replay of done.
    same = CoordinatorServer(port=port, state_file=state, run_id="run-A")
    same.start()
    try:
        st = same.client("w0").status()
        assert int(st["done"]) == 1 and int(st["queued"]) == 2
    finally:
        same.kill()

    # A DIFFERENT run reusing the workspace: old state must be discarded.
    fresh = CoordinatorServer(port=port, state_file=state, run_id="run-B")
    fresh.start()
    try:
        c = fresh.client("w0")
        st = c.status()
        assert int(st["done"]) == 0 and int(st["queued"]) == 0
        # The new run's own seeding + progress works and persists under B.
        c.add_tasks(["s0", "s1"])
        c.register()
        c.complete_task(c.acquire_task())
    finally:
        fresh.kill()

    # ...and B's file now resumes as B's, not A's.
    again = CoordinatorServer(port=port, state_file=state, run_id="run-B")
    again.start()
    try:
        st = again.client("w0").status()
        assert int(st["done"]) == 1 and int(st["queued"]) == 1
    finally:
        again.stop()


def test_native_delta_log_many_mutations_and_compaction(tmp_path):
    """The state file is a delta log, not an O(dataset) rewrite per mutation:
    thousands of completes stay cheap, the log compacts, and a kill -9 at any
    ack boundary restores exactly (round-2 advisor finding b)."""
    if not has_toolchain():
        pytest.skip("no C++ toolchain")
    import os

    state = str(tmp_path / "coord-state.jsonl")
    server = CoordinatorServer(state_file=state)
    server.start()
    port = server.port
    n_tasks, n_done, n_kv = 40, 30, 4000
    try:
        w = server.client("w0")
        w.register()
        w.add_tasks([f"t{i}" for i in range(n_tasks)])
        for _ in range(n_done):
            w.complete_task(w.acquire_task())
        # kv churn on ONE hot key: appended_records_ grows past both the
        # 1024-record floor and 2x the live-state size (live state stays ~70
        # entries), so the compaction branch MUST fire.
        for i in range(n_kv):
            w.kv_put("edl/ckpt_meta", f"step={i}")
        # Compaction fired: the log is O(live state + one compaction window
        # of deltas), far below the ~200KB an append-only history of 4000
        # kv_puts would occupy.
        assert os.path.getsize(state) < 120_000
    finally:
        server.kill()

    server2 = CoordinatorServer(port=port, state_file=state)
    server2.start()
    try:
        w2 = server2.client("w0")
        st = w2.status()
        assert int(st["done"]) == n_done
        assert int(st["queued"]) == n_tasks - n_done
        assert w2.kv_get("edl/ckpt_meta") == f"step={n_kv - 1}"
    finally:
        server2.stop()


def test_native_kv_del_persists(tmp_path):
    """kv_del must survive restart as a delta (a naive append-only load would
    resurrect deleted keys)."""
    if not has_toolchain():
        pytest.skip("no C++ toolchain")
    state = str(tmp_path / "coord-state.jsonl")
    server = CoordinatorServer(state_file=state)
    server.start()
    port = server.port
    try:
        w = server.client("w0")
        w.kv_put("keep", "1")
        w.kv_put("drop", "2")
        w.kv_del("drop")
    finally:
        server.kill()
    server2 = CoordinatorServer(port=port, state_file=state)
    server2.start()
    try:
        w = server2.client("w0")
        assert w.kv_get("keep") == "1"
        assert w.kv_get("drop") is None
    finally:
        server2.stop()


def test_native_unwritable_state_path_fails_fast(tmp_path):
    """With ack-after-durability, a never-writable state log would hold every
    reply forever — a misconfigured pod must crash loudly at boot instead of
    running silently non-durable (round-2 advisor finding c: failed writes
    are never silently dropped)."""
    if not has_toolchain():
        pytest.skip("no C++ toolchain")
    from edl_tpu.coordinator.client import CoordinatorError

    state = str(tmp_path / "no-such-dir" / "state.jsonl")  # parent missing
    server = CoordinatorServer(state_file=state)
    with pytest.raises(CoordinatorError, match="exited at startup"):
        server.start()


def test_barrier_count_mismatch_rejected(coord):
    """Two cohorts sharing a barrier name with different counts must not
    release each other: the first arrival of a cycle fixes the count
    (VERDICT weak #5)."""
    a = coord.client("a")
    b = coord.client("b")
    a.register()
    b.register()

    results = {}

    def arrive(cl, name, count, key):
        results[key] = cl.barrier(name, count=count)

    ta = threading.Thread(target=arrive, args=(a, "step", 2, "a"))
    ta.start()
    time.sleep(0.3)  # a arrived first: count fixed at 2
    mismatch = b.barrier("step", count=3)
    assert mismatch.get("ok") is False
    assert "mismatch" in mismatch.get("error", "")
    # agreeing cohort still completes
    ok = b.barrier("step", count=2)
    ta.join(timeout=10)
    assert ok.get("ok") is True
    assert results["a"].get("ok") is True


def test_heartbeat_renews_leases(coord):
    """A LIVE worker keeps its leases (etcd-keepalive semantics): heartbeats
    extend lease deadlines, so completion-lag holds — shards completed only
    after a covering checkpoint — can outlive task_lease_sec without healthy
    runs retraining shards. Expiry fires only when the heartbeat also stops
    (covered by test_lease_requeue_on_expiry)."""
    a = coord.client("alive")
    a.register()
    a.add_tasks(["renew0"])
    assert a.acquire_task() == "renew0"
    # fixture lease TTL is 1.0 s: hold the lease across 2.4 s of heartbeats
    for _ in range(6):
        time.sleep(0.4)
        a.heartbeat()
    st = a.status()
    assert int(st["leased"]) == 1 and int(st["queued"]) == 0, st
    assert a.complete_task("renew0").get("ok") is True
    a.leave()


def test_register_requeues_predecessors_leases(coord):
    """Register is an incarnation boundary: a warm-restarted worker (same
    pod name) must get its dead predecessor's leases REQUEUED, not renewed.
    Renewal-on-register let the successor's heartbeats keep stale leases
    alive forever — rank 0 then deadlocked in 'stop: wait' rounds on leases
    that were its own (caught live by the multi-job scale-down e2e)."""
    a = coord.client("podA")
    a.register(takeover=True)
    a.add_tasks(["inc0", "inc1", "inc2"])
    assert a.acquire_task() is not None
    assert a.acquire_task() is not None
    st = a.status()
    assert int(st["leased"]) == 2 and int(st["queued"]) == 1, st
    # a plain mid-run refresh must NOT forfeit in-flight leases (elastic
    # workers re-register after compile-stall expiry while still training)
    a.register()
    st = a.status()
    assert int(st["leased"]) == 2 and int(st["queued"]) == 1, st
    # the pod warm-restarts: a fresh incarnation claims the name
    a.register(takeover=True)
    st = a.status()
    assert int(st["leased"]) == 0 and int(st["queued"]) == 3, st
    # and can lease everything back itself (no double-lease residue)
    got = {a.acquire_task() for _ in range(3)}
    assert got == {"inc0", "inc1", "inc2"}
    a.leave()


def test_native_durability_random_ops_survive_kill(tmp_path):
    """Property test for the delta log: after ANY sequence of acked mutations
    and a kill -9 at an arbitrary point, a restart restores exactly the acked
    state — done-set and KV match a Python model; every non-done task is
    either back in the queue or restored as this worker's own live lease
    (never both, never neither). Ack-after-durability makes every kill point
    equivalent."""
    if not has_toolchain():
        pytest.skip("no C++ toolchain")
    import random

    rng = random.Random(0xED1)
    for trial in range(3):
        state = str(tmp_path / f"prop-{trial}.jsonl")
        model_done, model_kv, model_added = set(), {}, set()
        server = CoordinatorServer(state_file=state)
        server.start()
        port = server.port
        try:
            w = server.client("w0")
            w.register()
            leased = []
            n_ops = rng.randrange(40, 120)
            for i in range(n_ops):
                op = rng.random()
                if op < 0.25:
                    ts = [f"t{trial}-{rng.randrange(60)}" for _ in range(3)]
                    w.add_tasks(ts)
                    model_added.update(ts)
                elif op < 0.45:
                    t = w.acquire_task()
                    if t is not None:
                        leased.append(t)
                elif op < 0.65 and leased:
                    t = leased.pop(rng.randrange(len(leased)))
                    if w.complete_task(t).get("ok"):
                        model_done.add(t)
                elif op < 0.75 and leased:
                    w.fail_task(leased.pop(rng.randrange(len(leased))))
                elif op < 0.9:
                    k = f"k{rng.randrange(8)}"
                    v = f"v{i}"
                    w.kv_put(k, v)
                    model_kv[k] = v
                else:
                    k = f"k{rng.randrange(8)}"
                    w.kv_del(k)
                    model_kv.pop(k, None)
        finally:
            server.kill()  # arbitrary kill point: no graceful path

        server2 = CoordinatorServer(port=port, state_file=state)
        server2.start()
        try:
            w = server2.client("w0")
            w.register()
            st = w.status()
            assert int(st["done"]) == len(model_done), (trial, st)
            for k in (f"k{j}" for j in range(8)):
                assert w.kv_get(k) == model_kv.get(k), (trial, k)
            # Leases held at the kill are restored UNDER w0 (not requeued),
            # so they are not re-acquirable; everything else added-but-not-
            # done is leasable exactly once. Ledger balance: queue + own
            # leases == added - done, with no overlap.
            leftover = set(leased)
            assert int(st["leased"]) == len(leftover), (trial, st)
            remaining = set()
            while True:
                t = w.acquire_task()
                if t is None:
                    break
                remaining.add(t)
            assert remaining == model_added - model_done - leftover, trial
            assert not (remaining & leftover), trial
        finally:
            server2.stop()
