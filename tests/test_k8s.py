"""K8s backend tests against the in-process fake apiserver.

What the reference proves with its generated fake clientset
(`pkg/client/clientset/versioned/fake/`), we prove over real HTTP: the REST
client, watch streaming, K8sCluster's node/pod accounting + role
materialization + parallelism actuation, K8sJobStore CRUD/status/watch, and a
full controller loop driving a job to Running on the Kubernetes backend.
"""

import base64
import os
import textwrap
import time

import pytest

from edl_tpu.api.quantity import ResourceList
from edl_tpu.api.types import JobPhase, TrainingJob
from edl_tpu.controller.jobparser import (
    ROLE_COORDINATOR,
    ROLE_TRAINER,
    parse_to_coordinator,
    parse_to_trainer,
)
from edl_tpu.k8s import ApiClient, ApiError, K8sCluster, K8sJobStore, KubeConfig
from edl_tpu.k8s.cluster import resources_from_k8s, resources_to_k8s
from tests.fake_apiserver import FakeApiServer


JOB_YAML = textwrap.dedent(
    """
    metadata: {name: demo, namespace: default}
    spec:
      image: edl-tpu:latest
      fault_tolerant: true
      tpu: {accelerator_type: v5e, chips_per_trainer: 4}
      trainer:
        entrypoint: "python -m edl_tpu.launcher start_trainer"
        min_instance: 2
        max_instance: 4
        resources:
          requests: {cpu: 1, memory: 1Gi}
          limits: {cpu: 2, memory: 2Gi}
      data_shards: [s0, s1, s2, s3]
    """
)


@pytest.fixture()
def apiserver():
    srv = FakeApiServer()
    base = srv.serve()
    for i in range(4):
        srv.add_node(
            f"host{i}",
            {"cpu": "16", "memory": "64Gi", "google.com/tpu": "4"},
        )
    yield srv, base
    srv.close()


def _client(base: str) -> ApiClient:
    return ApiClient(KubeConfig(host=base), timeout=5.0)


# -- config --------------------------------------------------------------------


def test_kubeconfig_parsing(tmp_path):
    ca_pem = "-----BEGIN CERTIFICATE-----\nZZZZ\n-----END CERTIFICATE-----\n"
    kubeconfig = {
        "current-context": "prod",
        "contexts": [
            {"name": "prod",
             "context": {"cluster": "c1", "user": "u1", "namespace": "ml"}},
        ],
        "clusters": [
            {"name": "c1", "cluster": {
                "server": "https://10.0.0.1:6443",
                "certificate-authority-data":
                    base64.b64encode(ca_pem.encode()).decode(),
            }},
        ],
        "users": [{"name": "u1", "user": {"token": "sekrit"}}],
    }
    import yaml

    path = tmp_path / "config"
    path.write_text(yaml.safe_dump(kubeconfig))
    cfg = KubeConfig.from_kubeconfig(str(path))
    assert cfg.host == "https://10.0.0.1:6443"
    assert cfg.namespace == "ml"
    assert cfg.ca_cert_data == ca_pem
    assert cfg.auth_headers() == {"Authorization": "Bearer sekrit"}


def test_in_cluster_config(tmp_path, monkeypatch):
    (tmp_path / "token").write_text("tok-1\n")
    (tmp_path / "namespace").write_text("kube-system")
    monkeypatch.setenv("KUBERNETES_SERVICE_HOST", "10.96.0.1")
    monkeypatch.setenv("KUBERNETES_SERVICE_PORT", "443")
    cfg = KubeConfig.in_cluster(sa_dir=str(tmp_path))
    assert cfg.host == "https://10.96.0.1:443"
    assert cfg.namespace == "kube-system"
    assert cfg.bearer_token() == "tok-1"
    # token rotation: re-read per request
    (tmp_path / "token").write_text("tok-2")
    assert cfg.bearer_token() == "tok-2"


def test_bearer_token_sent_and_checked(apiserver):
    srv, base = apiserver
    srv.token = "letmein"
    ok = ApiClient(KubeConfig(host=base, token="letmein"), timeout=5.0)
    assert ok.get("/api/v1/nodes")["items"]
    bad = ApiClient(KubeConfig(host=base, token="wrong"), timeout=5.0)
    with pytest.raises(ApiError) as err:
        bad.get("/api/v1/nodes")
    assert err.value.status == 401


def test_quantity_roundtrip():
    rl = resources_from_k8s({"cpu": "500m", "memory": "2Gi", "google.com/tpu": "4"})
    assert rl.get_q("cpu") == 0.5
    assert rl.get_q("memory") == 2 * 2**30
    assert rl.get_q("tpu") == 4.0
    back = resources_to_k8s(rl)
    assert back["google.com/tpu"] == "4"
    assert resources_from_k8s(back) == rl


# -- K8sCluster ----------------------------------------------------------------


def _job() -> TrainingJob:
    from edl_tpu.api.validation import normalize

    return normalize(TrainingJob.from_yaml(JOB_YAML))


def test_inquire_scans_nodes_and_pods(apiserver):
    srv, base = apiserver
    cluster = K8sCluster(_client(base))
    snap = cluster.inquire()
    assert snap.total.get_q("tpu") == 16.0
    assert snap.total.get_q("cpu") == 64.0
    assert snap.free("tpu") == 16.0
    assert set(snap.node_idle) == {f"host{i}" for i in range(4)}


def test_create_role_and_scale(apiserver):
    srv, base = apiserver
    cluster = K8sCluster(_client(base))
    job = _job()
    trainer = parse_to_trainer(job)
    cluster.create_role(
        "demo", ROLE_TRAINER, trainer.replicas, trainer.requests,
        trainer.limits, workload=trainer,
    )
    pods = cluster.job_pods("demo", ROLE_TRAINER)
    assert len(pods) == 2
    assert all(p.phase == "Running" for p in pods)
    assert all(p.requests.get_q("tpu") == 4.0 for p in pods)
    assert cluster.get_trainer_parallelism("demo") == 2

    # scale actuation patches spec.parallelism; fake reconciles pods
    cluster.set_trainer_parallelism("demo", 4)
    assert cluster.get_trainer_parallelism("demo") == 4
    assert len(cluster.job_pods("demo", ROLE_TRAINER)) == 4
    # accounting reflects consumption: 4 trainers x 4 chips = all 16
    assert cluster.inquire().free("tpu") == 0.0

    cluster.set_trainer_parallelism("demo", 1)
    assert len(cluster.job_pods("demo", ROLE_TRAINER)) == 1

    with pytest.raises(KeyError):
        cluster.set_trainer_parallelism("nosuch", 3)


def test_coordinator_role_gets_deployment_and_service(apiserver):
    srv, base = apiserver
    cluster = K8sCluster(_client(base))
    job = _job()
    coord = parse_to_coordinator(job)
    cluster.create_role(
        "demo", ROLE_COORDINATOR, 1, coord.requests, coord.limits, workload=coord,
    )
    assert ("default", "demo-coordinator") in srv.deployments
    assert ("default", "demo-coordinator") in srv.services
    deployment = srv.deployments[("default", "demo-coordinator")]
    container = deployment["spec"]["template"]["spec"]["containers"][0]
    env = {e["name"]: e["value"] for e in container["env"]}
    assert env["EDL_JOB_NAME"] == "demo"
    assert env["EDL_ROLE"] == ROLE_COORDINATOR
    # adoption: re-creating is not an error (controller restart replay)
    cluster.create_role(
        "demo", ROLE_COORDINATOR, 1, coord.requests, coord.limits, workload=coord,
    )

    cluster.delete_role("demo", ROLE_COORDINATOR)
    assert ("default", "demo-coordinator") not in srv.deployments
    assert not cluster.job_pods("demo", ROLE_COORDINATOR)


def test_coordinator_state_pvc_mounts_claim(apiserver):
    """spec.coordinator.state_pvc swaps the pod-lifetime emptyDir for a
    PersistentVolumeClaim mount, so the durable state file survives pod
    RESCHEDULING (VERDICT r3 weak #5); without it emptyDir remains."""
    srv, base = apiserver
    cluster = K8sCluster(_client(base))

    job = _job()
    job.spec.coordinator.workspace = "/state"
    coord = parse_to_coordinator(job)
    cluster.create_role("demo", ROLE_COORDINATOR, 1, coord.requests,
                        coord.limits, workload=coord)
    pod_spec = srv.deployments[("default", "demo-coordinator")]["spec"][
        "template"]["spec"]
    assert pod_spec["volumes"] == [{"name": "coordinator-state", "emptyDir": {}}]
    cluster.delete_role("demo", ROLE_COORDINATOR)

    job.spec.coordinator.state_pvc = "demo-coord-state"
    coord = parse_to_coordinator(job)
    cluster.create_role("demo", ROLE_COORDINATOR, 1, coord.requests,
                        coord.limits, workload=coord)
    pod_spec = srv.deployments[("default", "demo-coordinator")]["spec"][
        "template"]["spec"]
    assert pod_spec["volumes"] == [{
        "name": "coordinator-state",
        "persistentVolumeClaim": {"claimName": "demo-coord-state"},
    }]
    mounts = pod_spec["containers"][0]["volumeMounts"]
    assert mounts == [{"name": "coordinator-state", "mountPath": "/state"}]
    cluster.delete_role("demo", ROLE_COORDINATOR)


def test_unplaceable_pods_stay_pending(apiserver):
    srv, base = apiserver
    cluster = K8sCluster(_client(base))
    job = _job()
    trainer = parse_to_trainer(job)
    # 5 trainers x 4 chips > 16 chips in the cluster -> one Pending
    cluster.create_role("demo", ROLE_TRAINER, 5, trainer.requests,
                        trainer.limits, workload=trainer)
    phases = sorted(p.phase for p in cluster.job_pods("demo", ROLE_TRAINER))
    assert phases.count("Running") == 4
    assert phases.count("Pending") == 1


# -- K8sJobStore ---------------------------------------------------------------


def test_store_crud_and_status_subresource(apiserver):
    srv, base = apiserver
    store = K8sJobStore(_client(base))
    job = _job()
    created = store.create(job)
    assert created.name == "demo"
    with pytest.raises(KeyError):
        store.create(job)  # duplicate

    got = store.get("demo")
    assert got.spec.trainer.min_instance == 2

    # spec update does not clobber status; status write is a subresource
    got.status.phase = JobPhase.RUNNING
    store.update_status("demo", got.status)
    got.spec.trainer.max_instance = 8
    store.update(got)
    again = store.get("demo")
    assert again.spec.trainer.max_instance == 8
    assert again.status.phase == JobPhase.RUNNING

    assert [j.name for j in store.list()] == ["demo"]
    store.delete("demo")
    with pytest.raises(KeyError):
        store.get("demo")


def test_store_watch_delivers_events(apiserver):
    srv, base = apiserver
    store = K8sJobStore(_client(base), watch_timeout_seconds=5.0)
    events = []

    class Recorder:
        def on_add(self, job):
            events.append(("add", job.name, job.status.phase))

        def on_update(self, job):
            events.append(("update", job.name, job.status.phase))

        def on_del(self, job):
            events.append(("del", job.name, job.status.phase))

    job = _job()
    store.create(job)
    store.watch(Recorder(), replay=True)  # replay delivers the existing job
    assert events[0] == ("add", "demo", JobPhase.NONE)

    status = store.get("demo").status
    status.phase = JobPhase.RUNNING
    store.update_status("demo", status)
    store.delete("demo")

    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline and len(events) < 3:
        time.sleep(0.05)
    store.stop()
    assert ("update", "demo", JobPhase.RUNNING) in events
    assert events[-1][0] == "del"


# -- full controller loop on the Kubernetes backend ----------------------------


def test_controller_loop_on_k8s_backend(apiserver):
    """The VERDICT's done-criterion: controller-loop test green against a
    mocked kubernetes apiserver, driving a TrainingJob to Running with real
    Deployments/Jobs/pods behind it (ref: `pkg/controller.go:110-148`)."""
    from edl_tpu.controller import Controller
    from edl_tpu.controller.updater import UpdaterConfig

    srv, base = apiserver
    api = _client(base)
    cluster = K8sCluster(api)
    store = K8sJobStore(api, watch_timeout_seconds=5.0)
    controller = Controller(
        cluster,
        store=store,
        updater_config=UpdaterConfig(convert_seconds=0.2, poll_seconds=0.05,
                                     create_timeout=10.0),
    )
    controller.start()
    try:
        store.create(_job())
        deadline = time.monotonic() + 15.0
        phase = None
        while time.monotonic() < deadline:
            phase = store.get("demo").status.phase
            if phase == JobPhase.RUNNING:
                break
            time.sleep(0.1)
        assert phase == JobPhase.RUNNING
        # materialized: coordinator Deployment+Service, trainer batch Job
        assert ("default", "demo-coordinator") in srv.deployments
        assert ("default", "demo-trainer") in srv.jobs
        # The autoscaler is live on this backend: with 16 free chips it may
        # grow the elastic job past min_instance=2 toward max_instance=4 by
        # patching spec.parallelism (ref: pkg/autoscaler.go:339-376).
        parallelism = cluster.get_trainer_parallelism("demo")
        assert 2 <= parallelism <= 4
        assert len(cluster.job_pods("demo", ROLE_TRAINER)) == parallelism

        # all trainers succeed -> job Succeeded, coordinator released
        with srv.lock:
            names = [k[1] for k, p in srv.pods.items()
                     if p["metadata"]["labels"].get("edl.tpu/role") == ROLE_TRAINER]
        for name in names:
            srv.set_pod_phase("default", name, "Succeeded")
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            phase = store.get("demo").status.phase
            if phase.terminal():
                break
            time.sleep(0.1)
        assert phase == JobPhase.SUCCEEDED
        assert ("default", "demo-coordinator") not in srv.deployments
    finally:
        controller.stop()
        store.stop()


def test_cli_run_selects_k8s_backend(apiserver, tmp_path):
    """``edl-tpu run --kubeconfig`` drives the job on the Kubernetes backend
    (ref CLI flag wiring: cmd/edl/edl.go:17-36)."""
    import yaml

    from edl_tpu.cli import main

    srv, base = apiserver
    kubeconfig = {
        "current-context": "test",
        "contexts": [{"name": "test",
                      "context": {"cluster": "fake", "user": "u"}}],
        "clusters": [{"name": "fake", "cluster": {"server": base}}],
        "users": [{"name": "u", "user": {}}],
    }
    cfg_path = tmp_path / "kubeconfig"
    cfg_path.write_text(yaml.safe_dump(kubeconfig))
    job_path = tmp_path / "job.yaml"
    job_path.write_text(JOB_YAML)

    # Succeed trainers as they materialize (the autoscaler may keep growing
    # the elastic job, so flip until the job itself reaches a terminal phase).
    def succeed_soon():
        deadline = time.monotonic() + 25.0
        while time.monotonic() < deadline:
            with srv.lock:
                tj = srv.trainingjobs.get(("default", "demo"))
                if tj and tj.get("status", {}).get("phase") in ("Succeeded",
                                                               "Failed"):
                    return
                for key, p in srv.pods.items():
                    if (p["metadata"]["labels"].get("edl.tpu/role") == ROLE_TRAINER
                            and p["status"]["phase"] == "Running"):
                        p["status"]["phase"] = "Succeeded"
            time.sleep(0.2)

    import threading

    flipper = threading.Thread(target=succeed_soon, daemon=True)
    flipper.start()
    rc = main([
        "run", "-f", str(job_path), "--kubeconfig", str(cfg_path),
        "--timeout", "30", "--collect-period", "60",
    ])
    flipper.join()
    assert rc == 0
    # the CRD object landed on the apiserver and reached Succeeded
    assert srv.trainingjobs[("default", "demo")]["status"]["phase"] == "Succeeded"


# -- real-apiserver failure modes (fault injection) ----------------------------


def test_status_conflict_retried_transparently(apiserver):
    """409 on the /status subresource (rv race with a concurrent writer):
    the store retries the merge patch; callers never see the conflict."""
    srv, base = apiserver
    store = K8sJobStore(_client(base))
    store.create(_job())
    status = store.get("demo").status
    status.phase = JobPhase.RUNNING
    srv.status_conflicts = 2  # two rejections, then accept
    out = store.update_status("demo", status)
    assert out.status.phase == JobPhase.RUNNING
    assert srv.status_conflicts == 0


def test_status_conflict_exhaustion_surfaces_and_updater_survives(apiserver):
    srv, base = apiserver
    store = K8sJobStore(_client(base))
    store.create(_job())
    status = store.get("demo").status
    status.phase = JobPhase.RUNNING
    srv.status_conflicts = 99
    with pytest.raises(ApiError) as ei:
        store.update_status("demo", status)
    assert ei.value.conflict
    srv.status_conflicts = 0

    # the updater's status writeback must absorb the same failure (the
    # next convert tick retries) instead of crashing the job actor
    from edl_tpu.controller import FakeCluster, NodeInfo
    from edl_tpu.controller.updater import JobUpdater

    cluster = FakeCluster(
        [NodeInfo("n0", ResourceList.make({"cpu": "8", "memory": "16Gi"}))]
    )
    updater = JobUpdater(store.get("demo"), cluster, store)
    srv.status_conflicts = 99
    updater._set_phase(JobPhase.CREATING)  # must not raise
    srv.status_conflicts = 0
    updater._set_phase(JobPhase.RUNNING)
    assert store.get("demo").status.phase == JobPhase.RUNNING


def test_watch_survives_midstream_410(apiserver):
    """etcd compaction mid-stream: the server emits ERROR/410 and closes;
    the informer must relist and keep delivering events, losing nothing."""
    srv, base = apiserver
    store = K8sJobStore(_client(base), watch_timeout_seconds=5.0)
    events = []

    class Recorder:
        def on_add(self, job):
            events.append(("add", job.name))

        def on_update(self, job):
            events.append(("update", job.name, job.status.phase))

        def on_del(self, job):
            events.append(("del", job.name))

    srv.watch_error_410_after = 1  # every stream dies after one event
    store.create(_job())
    store.watch(Recorder(), replay=True)
    assert ("add", "demo") in events

    for phase in (JobPhase.CREATING, JobPhase.RUNNING):
        status = store.get("demo").status
        status.phase = phase
        store.update_status("demo", status)
        time.sleep(0.1)
    job2 = _job()
    job2.name = "demo2"
    store.create(job2)

    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline and not any(
        e[0] == "add" and e[1] == "demo2" for e in events
    ):
        time.sleep(0.05)
    running_seen = any(
        e == ("update", "demo", JobPhase.RUNNING) for e in events
    )
    store.stop()
    assert any(e[0] == "add" and e[1] == "demo2" for e in events), events
    assert running_seen, events


def test_watch_tolerates_bookmarks_and_slow_lists(apiserver):
    """BOOKMARK events advance the rv cursor without notifying watchers;
    a slow LIST (loaded apiserver) delays but does not break the informer."""
    srv, base = apiserver
    srv.send_bookmarks = True
    srv.list_delay_sec = 0.5
    store = K8sJobStore(_client(base), watch_timeout_seconds=2.0)
    events = []

    class Recorder:
        def on_add(self, job):
            events.append(("add", job.name))

        def on_update(self, job):
            events.append(("update", job.name))

        def on_del(self, job):
            events.append(("del", job.name))

    store.create(_job())
    store.watch(Recorder(), replay=True)
    # let at least one idle-watch cycle of bookmarks flow
    time.sleep(1.0)
    n_before = len(events)
    status = store.get("demo").status
    status.phase = JobPhase.RUNNING
    store.update_status("demo", status)
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline and len(events) == n_before:
        time.sleep(0.05)
    store.stop()
    # bookmarks delivered no spurious watcher events
    assert [e for e in events[:n_before]] == [("add", "demo")]
    assert ("update", "demo") in events
