"""Revocation wave: two jobs drained by one scripted fault timeline.

The `make chaos-preempt` scenario. A spot reclaim rarely takes one host —
a capacity crunch revokes SLICES, often hitting several jobs in the same
minute. This test runs two independent training jobs (own coordinator, own
task queue, own replica peer) and conducts a scripted revocation wave
through :class:`ChaosScenario`: each job's doomed worker is revoked once it
is warm (progress-gated, not wall-clock-gated — deterministic across
machine speeds), drains inside its notice, and a survivor finishes the
queue. The contract under the wave is the same as for a single notice:
``steps_lost == 0`` and EXACT step accounting on both jobs, with the fired
fault timeline replayable from its JSON spec.
"""

import json
import threading

import pytest

from edl_tpu.coordinator import InProcessCoordinator
from edl_tpu.models import fit_a_line
from edl_tpu.runtime.data import SyntheticShardSource, shard_names
from edl_tpu.runtime.elastic import ElasticConfig, ElasticWorker
from edl_tpu.testing import ChaosScenario

pytestmark = [pytest.mark.chaos]

N_SHARDS, BPS, BATCH = 6, 6, 16


class _Job:
    """One training job: coordinator, doomed worker, follower peer."""

    def __init__(self, tag, tmp_path):
        self.tag = tag
        self.model = fit_a_line.MODEL
        self.coord = InProcessCoordinator(task_lease_sec=60.0,
                                          heartbeat_ttl_sec=60.0)
        self.admin = self.coord.client(f"admin-{tag}")
        self.admin.add_tasks(shard_names(f"wave-{tag}", N_SHARDS))
        self.workdir = tmp_path / tag
        self.doomed = self._worker("trainer-0")
        self.result = {}
        self._stop = threading.Event()
        self._threads = []

    def _worker(self, name):
        return ElasticWorker(
            self.model, self.coord.client(name),
            SyntheticShardSource(self.model, batch_size=BATCH,
                                 batches_per_shard=BPS),
            ElasticConfig(checkpoint_dir=str(self.workdir / "ck"),
                          checkpoint_interval=50,
                          heartbeat_interval=0.0,
                          rescale_barrier_timeout=30.0,
                          peer_replicas=1),
        )

    def _follow(self):
        import time
        j = self.coord.client("trainer-1")
        info = j.register()
        epoch = info["epoch"]
        while not self._stop.is_set():
            reply = j.sync(epoch, timeout=5.0)
            if reply.get("ok"):
                break
            epoch = reply.get("epoch", epoch)
        while not self._stop.is_set():
            hb = j.heartbeat()
            if hb.get("ok") and hb["epoch"] != epoch:
                epoch = hb["epoch"]
                j.sync(epoch, timeout=5.0)
            time.sleep(0.02)

    def start(self):
        def run():
            self.result.update(self.doomed.run())
        for target in (self._follow, run):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)

    def finish(self):
        # the doomed worker's run() thread ends when its drain completes.
        self._threads[1].join(timeout=120)
        assert not self._threads[1].is_alive(), f"job {self.tag} never drained"
        survivor = self._worker("trainer-2")
        rest = survivor.run()
        self._stop.set()
        self._threads[0].join(timeout=10)
        return rest, survivor


def test_revocation_wave_drains_two_jobs_with_zero_steps_lost(tmp_path):
    alpha = _Job("alpha", tmp_path)
    beta = _Job("beta", tmp_path)

    sc = (ChaosScenario("revocation-wave")
          .register_coordinator("alpha", alpha.admin)
          .register_coordinator("beta", beta.admin)
          .predicate("alpha_warm", lambda: alpha.doomed.steps_done >= 3)
          .predicate("beta_warm", lambda: beta.doomed.steps_done >= 3)
          .add("alpha.revoke", when="alpha_warm", worker="trainer-0",
               notice_s=30.0, reason="spot-wave")
          .add("beta.revoke", when="beta_warm", after=0.05,
               worker="trainer-0", notice_s=30.0, reason="spot-wave"))

    # the preempt instruments live in the global registry: both jobs (and
    # earlier tests in this process) share the counter cells, so the wave's
    # contribution is asserted as a delta.
    notices_before = alpha.doomed.preempt_obs.notices.value(
        reason="spot-wave")

    alpha.start()
    beta.start()
    sc.start()
    sc.join(timeout=120)
    assert sc.completed and sc.failed is None, sc.events
    assert [e["action"] for e in sc.events] == ["alpha.revoke", "beta.revoke"]

    for job in (alpha, beta):
        rest, _ = job.finish()
        doomed = job.result
        assert doomed["preempted"] == 1.0, (job.tag, doomed)
        assert doomed["steps_lost"] == 0.0
        assert doomed["preempt_deadline_met"] == 1.0
        assert doomed["notice_to_drained_seconds"] < 30.0
        # exact accounting: the wave lost nothing and replayed nothing.
        assert doomed["steps"] + rest["steps"] == N_SHARDS * BPS, job.tag

    assert alpha.doomed.preempt_obs.notices.value(reason="spot-wave") \
        == notices_before + 2  # one notice per job, none duplicated

    # the fired timeline replays: the spec round-trips through JSON with
    # the revocation kwargs (worker, notice_s, reason) intact.
    replay = ChaosScenario.from_spec(sc.spec())
    assert [s.to_dict() for s in replay.steps] == \
        [s.to_dict() for s in sc.steps]
    assert replay.steps[0].kwargs["worker"] == "trainer-0"
    assert json.loads(sc.spec())["name"] == "revocation-wave"
