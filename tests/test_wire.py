"""Wire codec + dedup-gather tests: compression integrity, gradient equality."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.models import ctr
from edl_tpu.parallel import local_mesh
from edl_tpu.parallel.embedding import dedup_gather
from edl_tpu.runtime import Trainer, TrainerConfig
from edl_tpu.runtime.wire import WireCodec, WireOverflowError


def test_infer_and_roundtrip_ctr_batch():
    batch = ctr.MODEL.synthetic_batch(np.random.default_rng(0), 64)
    codec = WireCodec.infer(batch)
    assert codec.keys["dense"].encoding == "bf16"
    assert codec.keys["sparse"].encoding == "u24"
    assert codec.keys["label"].encoding == "u8"
    enc = codec.encode(batch)
    dec = {k: np.asarray(v) for k, v in codec.decode(
        {k: jnp.asarray(v) for k, v in enc.items()}
    ).items()}
    np.testing.assert_array_equal(dec["sparse"], batch["sparse"])  # ints exact
    np.testing.assert_array_equal(dec["label"], batch["label"])
    np.testing.assert_allclose(dec["dense"], batch["dense"], rtol=8e-3)  # bf16
    assert dec["sparse"].dtype == batch["sparse"].dtype
    # the point: fewer bytes on the wire
    raw = sum(v.nbytes for v in batch.values())
    wired = sum(v.nbytes for v in enc.values())
    assert wired < 0.70 * raw


def test_encode_validates_range():
    batch = {"ids": np.array([0, 100], np.int32)}
    codec = WireCodec.infer(batch)
    assert codec.keys["ids"].encoding == "u8"
    with pytest.raises(WireOverflowError):
        codec.encode({"ids": np.array([0, 300], np.int32)})


def test_u24_boundary_values():
    batch = {"ids": np.array([0, (1 << 24) - 1, 12345678], np.int32)}
    codec = WireCodec.infer(batch)
    assert codec.keys["ids"].encoding == "u24"
    dec = codec.decode({k: jnp.asarray(v) for k, v in codec.encode(batch).items()})
    np.testing.assert_array_equal(np.asarray(dec["ids"]), batch["ids"])


def test_large_ints_stay_raw():
    batch = {"ids": np.array([0, 1 << 25], np.int64)}
    codec = WireCodec.infer(batch)
    assert codec.keys["ids"].encoding == "raw"


def test_trainer_wire_transport_matches_plain():
    mesh = local_mesh()
    model = ctr.make_model(sparse_dim=10007)
    rng = np.random.default_rng(0)
    batches = [model.synthetic_batch(rng, 64) for _ in range(4)]

    def train(wire):
        t = Trainer(model, mesh, TrainerConfig(
            optimizer="adagrad", learning_rate=0.05, wire_transport=wire))
        state = t.init_state()
        losses = []
        for b in batches:
            state, loss = t.train_step(state, t.place_batch(b))
            losses.append(float(loss))
        return losses

    plain, wired = train(False), train(True)
    # bf16 feature quantization: same trajectory within bf16 tolerance
    np.testing.assert_allclose(wired, plain, rtol=2e-2, atol=2e-2)


def test_dedup_gather_grads_match_plain():
    table = jnp.asarray(np.random.default_rng(0).standard_normal((97, 8)), jnp.float32)
    ids = jnp.asarray([3, 5, 3, 3, 96, 0, 5, 3], jnp.int32)  # heavy duplicates
    cot = jnp.asarray(np.random.default_rng(1).standard_normal((8, 8)), jnp.float32)

    def f_plain(t):
        return jnp.sum(t[ids] * cot)

    def f_dedup(t):
        return jnp.sum(dedup_gather(t, ids) * cot)

    np.testing.assert_array_equal(dedup_gather(table, ids), table[ids])
    g_plain = jax.grad(f_plain)(table)
    g_dedup = jax.grad(f_dedup)(table)
    np.testing.assert_allclose(np.asarray(g_dedup), np.asarray(g_plain),
                               rtol=1e-6, atol=1e-6)


def test_dedup_gather_all_same_id():
    table = jnp.ones((16, 4), jnp.float32)
    ids = jnp.zeros((32,), jnp.int32)
    g = jax.grad(lambda t: jnp.sum(dedup_gather(t, ids)))(table)
    assert float(g[0, 0]) == 32.0
    assert float(jnp.abs(g[1:]).max()) == 0.0


def test_cross_axis_lookup_grads_match_dense():
    """Cross-axis (expert-sharded) lookup: gradient must equal the dense
    single-device formulation — the check_vma=False path is hand-psummed."""
    from edl_tpu.parallel import MeshSpec, build_mesh
    from edl_tpu.parallel.embedding import ShardedEmbedding

    mesh = build_mesh(MeshSpec({"data": 2, "expert": 4}))
    emb = ShardedEmbedding(512, 8, "expert", "data")
    key = jax.random.PRNGKey(0)
    table = emb.init(key, mesh, scale=0.5)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 512, (16, 4)), jnp.int32)
    cot = jnp.asarray(np.random.default_rng(1).standard_normal((16, 4, 8)), jnp.float32)

    def f_sharded(t):
        return jnp.sum(emb.apply(mesh, t, ids) * cot)

    def f_dense(t):
        return jnp.sum(t[ids] * cot)

    host_table = np.asarray(table)
    np.testing.assert_allclose(
        np.asarray(emb.apply(mesh, table, ids)), host_table[np.asarray(ids)],
        rtol=1e-6)
    g_sharded = jax.grad(f_sharded)(table)
    g_dense = jax.grad(f_dense)(jnp.asarray(host_table))
    np.testing.assert_allclose(np.asarray(g_sharded), np.asarray(g_dense),
                               rtol=1e-5, atol=1e-5)


def test_same_axis_lookup_grads_match_dense():
    from edl_tpu.parallel import MeshSpec, build_mesh
    from edl_tpu.parallel.embedding import ShardedEmbedding

    mesh = build_mesh(MeshSpec({"data": 8}))
    emb = ShardedEmbedding(512, 8, "data", "data")
    table = emb.init(jax.random.PRNGKey(0), mesh, scale=0.5)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 512, (32,)), jnp.int32)
    cot = jnp.asarray(np.random.default_rng(1).standard_normal((32, 8)), jnp.float32)

    g_sharded = jax.grad(lambda t: jnp.sum(emb.apply(mesh, t, ids) * cot))(table)
    g_dense = jax.grad(lambda t: jnp.sum(t[ids] * cot))(jnp.asarray(np.asarray(table)))
    np.testing.assert_allclose(np.asarray(g_sharded), np.asarray(g_dense),
                               rtol=1e-5, atol=1e-5)


def test_dedup_gather_unsigned_and_empty_ids():
    table = jnp.ones((16, 4), jnp.float32)
    # uint32 ids: segment_max's unsigned identity is 0, which must not
    # corrupt row 0's gradient.
    ids_u = jnp.asarray([0, 0, 3], jnp.uint32)
    g = jax.grad(lambda t: jnp.sum(dedup_gather(t, ids_u)))(table)
    assert float(g[0, 0]) == 2.0 and float(g[3, 0]) == 1.0
    assert float(jnp.abs(g[1:3]).max()) == 0.0
    # empty ids: backward yields a zero table grad, not a shape error.
    ids_e = jnp.zeros((0,), jnp.int32)
    g0 = jax.grad(lambda t: jnp.sum(dedup_gather(t, ids_e)))(table)
    assert float(jnp.abs(g0).max()) == 0.0


def test_no_lossy_keys_keep_float_labels_raw():
    """ADVICE fix: regression targets consumed by a float32 loss must not be
    bf16-quantized by the wire codec; int labels keep exact encodings."""
    from edl_tpu.runtime.wire import WireCodec

    example = {
        "x": np.random.default_rng(0).standard_normal((8, 13)).astype(np.float32),
        "y": np.random.default_rng(1).standard_normal((8, 1)).astype(np.float32),
        "label": np.array([0, 1] * 4, dtype=np.int64),
    }
    codec = WireCodec.infer(example, no_lossy_keys=("y", "label"))
    assert codec.keys["x"].encoding == "bf16"
    assert codec.keys["y"].encoding == "raw"      # float target: exact
    assert codec.keys["label"].encoding == "u8"   # int label: exact anyway
    enc = codec.encode(example)
    np.testing.assert_array_equal(enc["y"], example["y"])


def test_trainer_wire_transport_keeps_model_labels_exact():
    """Trainer-level: fit_a_line declares label_keys=('y',); with wire
    transport on, the y that reaches the loss is bit-identical."""
    from edl_tpu.models import fit_a_line
    from edl_tpu.runtime.wire import WireCodec

    batch = fit_a_line.MODEL.synthetic_batch(np.random.default_rng(0), 16)
    codec = WireCodec.infer(batch, no_lossy_keys=fit_a_line.MODEL.label_keys)
    assert codec.keys["y"].encoding == "raw"
    assert codec.keys["x"].encoding == "bf16"
