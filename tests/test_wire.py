"""Wire codec + dedup-gather tests: compression integrity, gradient equality."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.models import ctr
from edl_tpu.parallel import local_mesh
from edl_tpu.parallel.embedding import dedup_gather
from edl_tpu.runtime import Trainer, TrainerConfig
from edl_tpu.runtime.wire import WireCodec, WireOverflowError


def test_infer_and_roundtrip_ctr_batch():
    batch = ctr.MODEL.synthetic_batch(np.random.default_rng(0), 64)
    codec = WireCodec.infer(batch)
    assert codec.keys["dense"].encoding == "bf16"
    assert codec.keys["sparse"].encoding == "u24"
    assert codec.keys["label"].encoding == "u8"
    enc = codec.encode(batch)
    dec = {k: np.asarray(v) for k, v in codec.decode(
        {k: jnp.asarray(v) for k, v in enc.items()}
    ).items()}
    np.testing.assert_array_equal(dec["sparse"], batch["sparse"])  # ints exact
    np.testing.assert_array_equal(dec["label"], batch["label"])
    np.testing.assert_allclose(dec["dense"], batch["dense"], rtol=8e-3)  # bf16
    assert dec["sparse"].dtype == batch["sparse"].dtype
    # the point: fewer bytes on the wire
    raw = sum(v.nbytes for v in batch.values())
    wired = sum(v.nbytes for v in enc.values())
    assert wired < 0.70 * raw


def test_encode_validates_range():
    batch = {"ids": np.array([0, 100], np.int32)}
    codec = WireCodec.infer(batch)
    assert codec.keys["ids"].encoding == "u8"
    with pytest.raises(WireOverflowError):
        codec.encode({"ids": np.array([0, 300], np.int32)})


def test_u24_boundary_values():
    batch = {"ids": np.array([0, (1 << 24) - 1, 12345678], np.int32)}
    codec = WireCodec.infer(batch)
    assert codec.keys["ids"].encoding == "u24"
    dec = codec.decode({k: jnp.asarray(v) for k, v in codec.encode(batch).items()})
    np.testing.assert_array_equal(np.asarray(dec["ids"]), batch["ids"])


def test_large_ints_stay_raw():
    batch = {"ids": np.array([0, 1 << 25], np.int64)}
    codec = WireCodec.infer(batch)
    assert codec.keys["ids"].encoding == "raw"


def test_trainer_wire_transport_matches_plain():
    mesh = local_mesh()
    model = ctr.make_model(sparse_dim=10007)
    rng = np.random.default_rng(0)
    batches = [model.synthetic_batch(rng, 64) for _ in range(4)]

    def train(wire):
        t = Trainer(model, mesh, TrainerConfig(
            optimizer="adagrad", learning_rate=0.05, wire_transport=wire))
        state = t.init_state()
        losses = []
        for b in batches:
            state, loss = t.train_step(state, t.place_batch(b))
            losses.append(float(loss))
        return losses

    plain, wired = train(False), train(True)
    # bf16 feature quantization: same trajectory within bf16 tolerance
    np.testing.assert_allclose(wired, plain, rtol=2e-2, atol=2e-2)


def test_dedup_gather_grads_match_plain():
    table = jnp.asarray(np.random.default_rng(0).standard_normal((97, 8)), jnp.float32)
    ids = jnp.asarray([3, 5, 3, 3, 96, 0, 5, 3], jnp.int32)  # heavy duplicates
    cot = jnp.asarray(np.random.default_rng(1).standard_normal((8, 8)), jnp.float32)

    def f_plain(t):
        return jnp.sum(t[ids] * cot)

    def f_dedup(t):
        return jnp.sum(dedup_gather(t, ids) * cot)

    np.testing.assert_array_equal(dedup_gather(table, ids), table[ids])
    g_plain = jax.grad(f_plain)(table)
    g_dedup = jax.grad(f_dedup)(table)
    np.testing.assert_allclose(np.asarray(g_dedup), np.asarray(g_plain),
                               rtol=1e-6, atol=1e-6)


def test_dedup_gather_all_same_id():
    table = jnp.ones((16, 4), jnp.float32)
    ids = jnp.zeros((32,), jnp.int32)
    g = jax.grad(lambda t: jnp.sum(dedup_gather(t, ids)))(table)
    assert float(g[0, 0]) == 32.0
    assert float(jnp.abs(g[1:]).max()) == 0.0


def test_cross_axis_lookup_grads_match_dense():
    """Cross-axis (expert-sharded) lookup: gradient must equal the dense
    single-device formulation — the check_vma=False path is hand-psummed."""
    from edl_tpu.parallel import MeshSpec, build_mesh
    from edl_tpu.parallel.embedding import ShardedEmbedding

    mesh = build_mesh(MeshSpec({"data": 2, "expert": 4}))
    emb = ShardedEmbedding(512, 8, "expert", "data")
    key = jax.random.PRNGKey(0)
    table = emb.init(key, mesh, scale=0.5)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 512, (16, 4)), jnp.int32)
    cot = jnp.asarray(np.random.default_rng(1).standard_normal((16, 4, 8)), jnp.float32)

    def f_sharded(t):
        return jnp.sum(emb.apply(mesh, t, ids) * cot)

    def f_dense(t):
        return jnp.sum(t[ids] * cot)

    host_table = np.asarray(table)
    np.testing.assert_allclose(
        np.asarray(emb.apply(mesh, table, ids)), host_table[np.asarray(ids)],
        rtol=1e-6)
    g_sharded = jax.grad(f_sharded)(table)
    g_dense = jax.grad(f_dense)(jnp.asarray(host_table))
    np.testing.assert_allclose(np.asarray(g_sharded), np.asarray(g_dense),
                               rtol=1e-5, atol=1e-5)


def test_same_axis_lookup_grads_match_dense():
    from edl_tpu.parallel import MeshSpec, build_mesh
    from edl_tpu.parallel.embedding import ShardedEmbedding

    mesh = build_mesh(MeshSpec({"data": 8}))
    emb = ShardedEmbedding(512, 8, "data", "data")
    table = emb.init(jax.random.PRNGKey(0), mesh, scale=0.5)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 512, (32,)), jnp.int32)
    cot = jnp.asarray(np.random.default_rng(1).standard_normal((32, 8)), jnp.float32)

    g_sharded = jax.grad(lambda t: jnp.sum(emb.apply(mesh, t, ids) * cot))(table)
    g_dense = jax.grad(lambda t: jnp.sum(t[ids] * cot))(jnp.asarray(np.asarray(table)))
    np.testing.assert_allclose(np.asarray(g_sharded), np.asarray(g_dense),
                               rtol=1e-5, atol=1e-5)


def test_dedup_gather_unsigned_and_empty_ids():
    table = jnp.ones((16, 4), jnp.float32)
    # uint32 ids: segment_max's unsigned identity is 0, which must not
    # corrupt row 0's gradient.
    ids_u = jnp.asarray([0, 0, 3], jnp.uint32)
    g = jax.grad(lambda t: jnp.sum(dedup_gather(t, ids_u)))(table)
    assert float(g[0, 0]) == 2.0 and float(g[3, 0]) == 1.0
    assert float(jnp.abs(g[1:3]).max()) == 0.0
    # empty ids: backward yields a zero table grad, not a shape error.
    ids_e = jnp.zeros((0,), jnp.int32)
    g0 = jax.grad(lambda t: jnp.sum(dedup_gather(t, ids_e)))(table)
    assert float(jnp.abs(g0).max()) == 0.0


def test_no_lossy_keys_keep_float_labels_raw():
    """ADVICE fix: regression targets consumed by a float32 loss must not be
    bf16-quantized by the wire codec; int labels keep exact encodings."""
    from edl_tpu.runtime.wire import WireCodec

    example = {
        "x": np.random.default_rng(0).standard_normal((8, 13)).astype(np.float32),
        "y": np.random.default_rng(1).standard_normal((8, 1)).astype(np.float32),
        "label": np.array([0, 1] * 4, dtype=np.int64),
    }
    codec = WireCodec.infer(example, no_lossy_keys=("y", "label"))
    assert codec.keys["x"].encoding == "bf16"
    assert codec.keys["y"].encoding == "raw"      # float target: exact
    assert codec.keys["label"].encoding == "u8"   # int label: exact anyway
    enc = codec.encode(example)
    np.testing.assert_array_equal(enc["y"], example["y"])


def test_trainer_wire_transport_keeps_model_labels_exact():
    """Trainer-level: fit_a_line declares label_keys=('y',); with wire
    transport on, the y that reaches the loss is bit-identical."""
    from edl_tpu.models import fit_a_line
    from edl_tpu.runtime.wire import WireCodec

    batch = fit_a_line.MODEL.synthetic_batch(np.random.default_rng(0), 16)
    codec = WireCodec.infer(batch, no_lossy_keys=fit_a_line.MODEL.label_keys)
    assert codec.keys["y"].encoding == "raw"
    assert codec.keys["x"].encoding == "bf16"


# -- cross-process codec agreement (VERDICT round-3 item 3) --------------------


def test_codec_spec_round_trip():
    """to_spec/from_spec must rebuild the IDENTICAL codec — peers use the
    spec to compile the same decode program."""
    batch = {
        "dense": np.zeros((4, 13), np.float32),
        "sparse": np.arange(4 * 26, dtype=np.int32).reshape(4, 26),
        "label": np.array([0, 1, 0, 1], np.int32),
    }
    codec = WireCodec.infer(batch, no_lossy_keys=("label",))
    twin = WireCodec.from_spec(codec.to_spec())
    assert {k: v.encoding for k, v in twin.keys.items()} == {
        k: v.encoding for k, v in codec.keys.items()
    }
    assert {k: v.dtype for k, v in twin.keys.items()} == {
        k: v.dtype for k, v in codec.keys.items()
    }
    enc = twin.encode(batch)
    dec = {k: np.asarray(v) for k, v in twin.decode(enc).items()}
    np.testing.assert_array_equal(dec["sparse"], batch["sparse"])


def test_codec_apply_floor_widens_ints_only():
    batch = {
        "ids": np.array([1, 2, 3], np.int32),      # fits u8
        "x": np.zeros((3,), np.float32),            # bf16
    }
    codec = WireCodec.infer(batch)
    assert codec.keys["ids"].encoding == "u8"
    floored = codec.apply_floor({"ids": "u24", "x": "raw"})
    assert floored.keys["ids"].encoding == "u24"   # widened
    assert floored.keys["x"].encoding == "bf16"    # floats unaffected
    # floor narrower than inference is a no-op
    assert codec.apply_floor({"ids": "u8"}).keys["ids"].encoding == "u8"


def test_kv_codec_channel_publish_fetch_floor():
    """Rank 0 publishes the (floored) codec under an epoch-scoped key; peers
    fetch the identical spec; overflow raises the persistent floor."""
    from edl_tpu.coordinator import InProcessCoordinator
    from edl_tpu.runtime.wire import KVCodecChannel

    coord = InProcessCoordinator()
    c0 = coord.client("r0")
    c1 = coord.client("r1")
    batch = {"ids": np.array([3, 7], np.int32)}

    ch0 = KVCodecChannel(c0, epoch=5)
    ch1 = KVCodecChannel(c1, epoch=5)
    published = ch0.publish(WireCodec.infer(batch))
    fetched = ch1.fetch(timeout=2.0)
    assert fetched.to_spec() == published.to_spec()
    assert fetched.keys["ids"].encoding == "u8"

    # Overflow on any rank widens the floor; the NEXT epoch's negotiation
    # starts from it, so the overflow cannot recur.
    ch1.raise_floor("ids", "u24")
    ch_next = KVCodecChannel(c0, epoch=6)
    renegotiated = ch_next.publish(WireCodec.infer(batch))
    assert renegotiated.keys["ids"].encoding == "u24"
    # floors only widen: a narrower late write is ignored
    ch1.raise_floor("ids", "u8")
    assert ch_next.floor() == {"ids": "u24"}

    # epoch scoping: a stale publish (older epoch) is invisible to the new
    # incarnation; rank-0-never-published resolves to a gang restart demand
    from edl_tpu.runtime.wire import WireRestartRequired
    import pytest as _pytest
    with _pytest.raises(WireRestartRequired):
        KVCodecChannel(c1, epoch=7).fetch(timeout=0.2)


def test_trainer_multiproc_overflow_raises_restart(monkeypatch):
    """In a multi-process job an overflow must NOT widen in place (peers
    would keep the old decode-jit): it publishes the widened floor and
    demands a gang warm-restart."""
    from edl_tpu.coordinator import InProcessCoordinator
    from edl_tpu.runtime.wire import KVCodecChannel, WireRestartRequired

    coord = InProcessCoordinator()
    ch = KVCodecChannel(coord.client("r0"), epoch=1)
    model = ctr.make_model(sparse_dim=200)
    mesh = local_mesh()
    trainer = Trainer(model, mesh, TrainerConfig(wire_transport=True),
                      codec_channel=ch)

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 0)

    rng = np.random.default_rng(0)
    small = model.synthetic_batch(rng, 8)
    small["sparse"] = np.clip(small["sparse"], 0, 199).astype(np.int32)
    # Negotiation happens on the first batch... but place_batch would also
    # shard onto the (single-process) mesh; only exercise the encode path.
    trainer._codec = None
    # First batch: rank 0 infers + publishes.
    import json as _json
    big = dict(small)
    big["sparse"] = small["sparse"].copy()
    try:
        trainer.place_batch(small)
    except Exception:
        pass  # sharding under fake process_count may fail; codec is set
    assert trainer._codec is not None
    published = coord.client("x").kv_get("edl/wire_codec")
    assert published is not None
    assert _json.loads(published)["epoch"] == 1

    big["sparse"][0, 0] = 2 ** 30  # overflows the inferred u8
    with pytest.raises(WireRestartRequired):
        trainer.place_batch(big)
    floor = _json.loads(coord.client("x").kv_get("edl/wire_floor"))
    # One widening step per restart (u8 -> u24 -> raw): the ladder bounds
    # renegotiation at two gang restarts per key, ever.
    assert floor["sparse"] == "u24"
