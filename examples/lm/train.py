"""Transformer LM elastic trainer — the long-context / multi-axis capstone.

No reference analog (the reference's model zoo tops out at a 5-gram embedding
window, `example/fit_a_line/train_ft.py:26`); this example exists because a
TPU-native framework's flagship workload is a transformer whose mesh layout
composes every axis the parallel layer ships:

    data   — batch sharding (gradients psum over ICI)
    seq    — ring-attention sequence/context parallelism for long inputs
    model  — megatron tensor parallelism
    pipe   — pipeline stages (GPipe default; --pipeline-schedule 1f1b
             for the O(pp)-activation combined schedule)

plus the two HBM levers: per-block rematerialization (``--remat``) and
ZeRO-1 optimizer-state sharding (``--zero1``).

Mesh axes come from ``EDL_MESH_AXES`` (the controller's env protocol) or
``--axes``; unlisted chips fold into the data axis. Runs standalone (no env):
spawns an in-process coordinator and trains the whole queue on the local
device mesh.

    python examples/lm/train.py --axes '{"seq": 2, "model": 2}' \
        --seq-len 512 --remat --zero1
"""

import argparse
import json
import os
import tempfile

from edl_tpu.launcher.launch import LaunchContext
from edl_tpu.models import transformer
from edl_tpu.runtime import ElasticConfig, ElasticWorker, SyntheticShardSource
from edl_tpu.runtime.data import pass_tasks, shard_names
from edl_tpu.runtime.train_loop import TrainerConfig


def parse_args():
    p = argparse.ArgumentParser(description="Transformer LM elastic training")
    p.add_argument("--vocab-size", type=int, default=8192)
    p.add_argument("--d-model", type=int, default=256)
    p.add_argument("--n-layers", type=int, default=4)
    p.add_argument("--n-heads", type=int, default=8)
    p.add_argument("--d-ff", type=int, default=1024)
    p.add_argument("--seq-len", type=int, default=256)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--batches-per-shard", type=int, default=4)
    p.add_argument("--shards", type=int, default=4)
    p.add_argument("--learning-rate", type=float, default=1e-3)
    p.add_argument("--axes", default=os.environ.get("EDL_MESH_AXES", "{}"),
                   help='non-data mesh axes, e.g. \'{"seq":2,"model":2}\'')
    p.add_argument("--remat", action="store_true",
                   help="per-block activation rematerialization")
    p.add_argument("--pipeline-schedule", default="gpipe",
                   choices=("gpipe", "1f1b"),
                   help="microbatch schedule on the pipe axis: gpipe "
                        "(default) or the O(pp)-activation 1f1b")
    p.add_argument("--microbatches", type=int, default=None,
                   help="pipeline microbatches (default: pipe size)")
    p.add_argument("--zero1", action="store_true",
                   help="shard optimizer moments over the data axis")
    p.add_argument("--moe-experts", type=int, default=0,
                   help="switch-routed experts over the expert axis "
                        "(0 = dense FFN)")
    p.add_argument("--moe-aux-weight", type=float, default=0.01,
                   help="load-balance aux weight when --moe-experts > 0 "
                        "(non-pipelined meshes)")
    p.add_argument("--num-passes", type=int,
                   default=os.environ.get("EDL_PASSES", "1"))
    return p.parse_args()


def main() -> None:
    args = parse_args()
    ctx = LaunchContext.from_env()
    # Drop the data axis: workers size it from their device count (world x
    # chips / fixed axes) — passing it through would double-count it in
    # _build_mesh (same rule as ctr/train.py).
    axes = {k: int(v) for k, v in json.loads(args.axes).items()
            if k != "data" and int(v) > 1}
    moe = int(args.moe_experts)
    # aux loss does not thread through pipeline hop buffers (transformer
    # validation rejects the combination) — drop it, not the run, when the
    # user asked for MoE over a pipe axis without naming an aux weight
    aux = args.moe_aux_weight if (moe and "pipe" not in axes) else 0.0
    model = transformer.make_model(
        vocab_size=args.vocab_size, d_model=args.d_model,
        n_layers=args.n_layers, n_heads=args.n_heads, d_ff=args.d_ff,
        seq_len=args.seq_len, remat=args.remat,
        pipeline_schedule=args.pipeline_schedule,
        microbatches=args.microbatches,
        moe_experts=moe,
        moe_aux_weight=aux,
        # tokens shard over the expert axis too (the efficient layout)
        batch_axis=("data", "expert") if moe else "data",
    )
    if moe and "pipe" in axes and args.moe_aux_weight:
        print("note: load-balance aux loss disabled on pipelined meshes")
    source = SyntheticShardSource(model, batch_size=args.batch_size,
                                  batches_per_shard=args.batches_per_shard)

    if os.environ.get("EDL_COORDINATOR_ENDPOINT"):  # cloud mode
        from edl_tpu.launcher.discovery import wait_coordinator
        from edl_tpu.runtime.distributed import distributed_init

        client = wait_coordinator(ctx.coordinator_endpoint)
        client.worker = f"{ctx.job_name}-worker-{os.getpid()}"
        ident = distributed_init(ctx, client)
        if int(args.num_passes) != ctx.passes:
            print(f"note: cloud mode seeds passes launcher-side "
                  f"(spec.passes={ctx.passes}); --num-passes "
                  f"{args.num_passes} has no effect here")
    else:  # local twin
        from edl_tpu.coordinator.inprocess import InProcessCoordinator

        ident = None
        # Single local worker: a lease expiring can only duplicate work, and
        # the first jit compile (remat especially) can stall tens of seconds
        # with no heartbeat in between — so leases are compile-stall tolerant.
        coord = InProcessCoordinator(task_lease_sec=300.0,
                                     heartbeat_ttl_sec=300.0)
        coord.add_tasks(pass_tasks(
            ctx.data_shards or shard_names("lm", args.shards),
            int(args.num_passes),
        ))
        client = coord.client("worker-0")
        ctx.checkpoint_dir = ctx.checkpoint_dir or tempfile.mkdtemp(prefix="edl-lm-")

    cfg = ElasticConfig(
        checkpoint_dir=ctx.checkpoint_dir,
        checkpoint_interval=ctx.checkpoint_interval,
        trainer=TrainerConfig(optimizer="adam",
                              learning_rate=args.learning_rate,
                              shard_opt_state=args.zero1,
                              batch_axis=model.config.batch_axis),
    )
    if ident is not None:
        from edl_tpu.runtime import MultiHostWorker

        worker = MultiHostWorker(model, client, source, cfg,
                                 mesh_axes=axes or None)
    else:
        worker = ElasticWorker(model, client, source, cfg,
                               mesh_axes=axes or None)
    metrics = worker.run()
    print(json.dumps({k: round(v, 4) for k, v in metrics.items()}))


if __name__ == "__main__":
    main()
