"""MNIST digit recognition: train / infer modes on a data-parallel mesh.

Twin of `example/fit_a_line/fluid/recognize_digits.py:20-189`: the reference
trains softmax/MLP/conv variants under the PS transpile pattern
(`:128-145`), saves an inference model each epoch, and has an `infer` mode
that loads it and classifies an image (`:147-173`). Here one jitted SPMD step
replaces the transpile; the inference artifact is a checkpoint the `infer`
mode restores to predict on a held-out batch, reporting accuracy.
"""

import argparse
import json
import tempfile

import numpy as np

from edl_tpu.models import mnist
from edl_tpu.parallel import local_mesh
from edl_tpu.runtime import Trainer, TrainerConfig
from edl_tpu.runtime.checkpoint import (
    Checkpointer,
    abstract_like,
    live_state_specs,
)
from edl_tpu.tools import StepProfiler


def parse_args():
    p = argparse.ArgumentParser(description="MNIST conv training")
    p.add_argument("mode", nargs="?", default="train", choices=["train", "infer"])
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--learning-rate", type=float, default=1e-3)
    p.add_argument("--model-dir", default=None,
                   help="checkpoint dir (ref: save_dirname, recognize_digits.py:119)")
    return p.parse_args()


def batches(model, rng, batch_size, n):
    for _ in range(n):
        yield model.synthetic_batch(rng, batch_size)


def train(args, model_dir: str) -> None:
    mesh = local_mesh()
    trainer = Trainer(
        mnist.MODEL, mesh,
        TrainerConfig(optimizer="adam", learning_rate=args.learning_rate),
    )
    state = trainer.init_state()
    rng = np.random.default_rng(0)
    prof = StepProfiler(warmup=1)
    state, metrics = trainer.run(
        state, batches(mnist.MODEL, rng, args.batch_size, args.steps), profiler=prof
    )
    ckpt = Checkpointer(model_dir)
    ckpt.save(int(state.step), state)
    ckpt.wait()
    out = {**{k: round(v, 4) for k, v in metrics.items()},
           "step_time_p50_s": round(prof.summary().get("step_time_p50_s", 0.0), 6),
           "model_dir": model_dir}
    print(json.dumps(out))


def infer(args, model_dir: str) -> None:
    mesh = local_mesh()
    trainer = Trainer(mnist.MODEL, mesh, TrainerConfig())
    fresh = trainer.init_state()
    ckpt = Checkpointer(model_dir)
    if ckpt.latest_step() is None:
        raise SystemExit(f"no checkpoint under {model_dir}; run train first")
    state = ckpt.restore(abstract_like(fresh), mesh, live_state_specs(fresh))
    batch = mnist.MODEL.synthetic_batch(np.random.default_rng(99), 512)
    placed = trainer.place_batch(batch)
    acc = float(mnist.accuracy(state.params, placed))
    print(json.dumps({"step": int(state.step), "accuracy": round(acc, 4)}))


def main() -> None:
    args = parse_args()
    model_dir = args.model_dir or tempfile.gettempdir() + "/edl-mnist-ckpt"
    if args.mode == "train":
        train(args, model_dir)
    else:
        infer(args, model_dir)


if __name__ == "__main__":
    main()
