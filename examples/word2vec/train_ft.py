"""Fault-tolerant elastic word2vec trainer — the reference's flagship demo.

Direct twin of `example/fit_a_line/train_ft.py:24-118`: the reference trains a
5-gram word-embedding model with etcd-discovered pservers
(`SGD(is_local=False, pserver_spec=etcd, use_etcd=True)`, `:105-110`) pulling
chunked tasks from the master queue via `cloud_reader` (`:111-114`). Here the
sparse-update pserver table is a mesh-sharded `ShardedEmbedding`, discovery is
the `EDL_*` env protocol pointing at the coordinator, shards are coordinator
leases, and elasticity is checkpoint-restore rescale.

Runs standalone (no env set): spawns an in-process coordinator and trains the
whole queue on the local device mesh.
"""

import json
import os
import tempfile

from edl_tpu.launcher.launch import LaunchContext
from edl_tpu.models import word2vec
from edl_tpu.runtime import ElasticConfig, ElasticWorker, SyntheticShardSource
from edl_tpu.runtime.data import shard_names
from edl_tpu.runtime.train_loop import TrainerConfig
from edl_tpu.tools import StepProfiler


def main() -> None:
    ctx = LaunchContext.from_env()
    model = word2vec.MODEL
    source = SyntheticShardSource(model, batch_size=512, batches_per_shard=10)

    ident = None
    if os.environ.get("EDL_COORDINATOR_ENDPOINT"):
        from edl_tpu.launcher.discovery import wait_coordinator
        from edl_tpu.runtime.distributed import distributed_init

        client = wait_coordinator(ctx.coordinator_endpoint)
        client.worker = f"{ctx.job_name}-worker-{os.getpid()}"
        ident = distributed_init(ctx, client)  # multi-host bring-up (None if 1 proc)
    else:  # hermetic demo mode
        from edl_tpu.coordinator.inprocess import InProcessCoordinator

        # Single local worker: compile-stall-tolerant leases (a first jit can
        # outlast the 16 s default with no heartbeat in between; expiry would
        # only duplicate work here).
        coord = InProcessCoordinator(task_lease_sec=300.0,
                                     heartbeat_ttl_sec=300.0)
        coord.add_tasks(ctx.data_shards or shard_names("imikolov", 8))
        client = coord.client("worker-0")
        ctx.checkpoint_dir = ctx.checkpoint_dir or tempfile.mkdtemp(prefix="edl-w2v-")

    prof = StepProfiler(warmup=1)
    cfg = ElasticConfig(
        checkpoint_dir=ctx.checkpoint_dir,
        checkpoint_interval=ctx.checkpoint_interval,
        # ref uses Adam(lr=3e-3) for this model (train_ft.py:102-104)
        trainer=TrainerConfig(optimizer="adam", learning_rate=3e-3),
    )
    if ident is not None:  # multi-host: lockstep rounds + warm-restart rescale
        from edl_tpu.runtime import MultiHostWorker

        worker = MultiHostWorker(model, client, source, cfg, profiler=prof)
    else:
        worker = ElasticWorker(model, client, source, cfg, profiler=prof)
    metrics = worker.run()
    print(json.dumps({k: round(v, 4) for k, v in metrics.items()}))


if __name__ == "__main__":
    main()
