"""CTR deep-wide elastic trainer — the flagship workload.

Equivalent of `example/ctr/ctr/train.py:28-239`: argparse config surface,
periodic checkpointing every N batches (rank 0's duty in the reference,
`train.py:169-180`; here orbax-style saves are coordinated by the runtime),
cloud vs local mode by env. The PS transpile + ParallelExecutor machinery
(`train.py:141-151,211-231`) has no equivalent: one jitted SPMD step covers
both, and elasticity is checkpoint-restore rescale instead of pserver-held
state.

Data modes (ref per-trainer shard download, `train.py:221-227`):

- default: hermetic ``SyntheticShardSource`` (batches derived from shard ids);
- ``--prepare N --data-dir D``: materialize N on-disk ``.npz`` click-log
  shards (deliberately uneven row counts unless ``--even``) and exit;
- ``--data-dir D``: train from those files via ``FileShardSource`` — real
  lockstep metadata, real uneven shards, deterministic replay.
"""

import argparse
import json
import os
import tempfile

import numpy as np

from edl_tpu.launcher.launch import LaunchContext
from edl_tpu.models import ctr
from edl_tpu.runtime import (
    ElasticConfig,
    ElasticWorker,
    FileShardSource,
    SyntheticShardSource,
    pass_tasks,
    write_shard,
)
from edl_tpu.runtime.data import shard_names, shard_seed
from edl_tpu.runtime.train_loop import TrainerConfig


def parse_args():
    # Config surface kept close to the reference's (train.py:28-117).
    parser = argparse.ArgumentParser(description="CTR deep-wide elastic training")
    parser.add_argument("--batch-size", type=int, default=8192)
    parser.add_argument("--sparse-feature-dim", type=int, default=ctr.SPARSE_DIM)
    parser.add_argument("--learning-rate", type=float, default=0.05)
    parser.add_argument("--batches-per-shard", type=int, default=50)
    parser.add_argument("--shard-axis", default="data",
                        help="mesh axis the sparse tables shard over")
    parser.add_argument("--data-dir", default=os.environ.get("EDL_DATA_DIR", ""),
                        help="train from .npz shards under this directory")
    parser.add_argument("--prepare", type=int, default=0, metavar="N",
                        help="write N click-log shards to --data-dir and exit")
    parser.add_argument("--rows-per-shard", type=int, default=0,
                        help="base rows per prepared shard "
                             "(default: 4 x batch size)")
    parser.add_argument("--even", action="store_true",
                        help="prepare equal-size shards (default: uneven)")
    # string default: argparse applies `type` to it lazily at parse time, so
    # a malformed EDL_PASSES yields a clean usage error, not a traceback.
    parser.add_argument("--num-passes", type=int,
                        default=os.environ.get("EDL_PASSES", "1"),
                        help="dataset epochs (ref --num_passes). Cloud mode "
                             "seeds passes launcher-side from spec.passes; "
                             "this flag drives the local twin")
    parser.add_argument("--shuffle-seed", type=int, default=None,
                        help="deterministic within-shard row shuffle "
                             "(ref paddle.reader.shuffle, train.py:124-126)")
    parser.add_argument("--prefetch", action="store_true",
                        help="load the next shard off-thread while training "
                             "(ref py_reader double buffering, train.py:120-129)")
    parser.add_argument("--wire-transport", action="store_true",
                        help="compact host->device batch codec (bf16/u8/u24)")
    parser.add_argument("--export-dir", default="",
                        help="write a serving artifact here periodically "
                             "(ref save_inference_model, train.py:169-180)")
    parser.add_argument("--export-interval", type=int, default=1000,
                        help="steps between exports (ref: every 1000 batches)")
    parser.add_argument("--infer", action="store_true",
                        help="load the --export-dir artifact and score a "
                             "held-out batch instead of training")
    return parser.parse_args()


def infer(args) -> None:
    """Serving-side half of the reference's save-then-infer flow."""
    from edl_tpu.runtime import load_inference_model

    art = load_inference_model(args.export_dir)
    batch = art.model.synthetic_batch(np.random.default_rng(123),
                                      args.batch_size)
    logits = np.asarray(art.predict({k: v for k, v in batch.items()
                                     if k != "label"}))
    prob = 1.0 / (1.0 + np.exp(-logits))
    # logloss against the held-out labels (the training objective)
    y = batch["label"].astype(np.float64)
    eps = 1e-7
    logloss = float(np.mean(
        -(y * np.log(prob + eps) + (1 - y) * np.log(1 - prob + eps))
    ))
    print(json.dumps({"step": art.step, "examples": int(logits.shape[0]),
                      "mean_ctr": round(float(prob.mean()), 4),
                      "logloss": round(logloss, 4)}))


def prepare(args) -> None:
    """Materialize deterministic click-log shards on disk.

    Shard i's rows derive from a seed of its id, so any trainer preparing
    the same dataset writes bit-identical files (the reference's downloaded
    shards are likewise immutable inputs). Row counts are uneven by default
    — the case the lockstep padding machinery exists for.
    """
    base = args.rows_per_shard or 4 * args.batch_size
    written = {}
    for shard in shard_names("criteo", args.prepare):
        rng = np.random.default_rng(shard_seed(shard))
        rows = base if args.even else base + int(rng.integers(0, base))
        batch = ctr.synthetic_batch(rng, rows, args.sparse_feature_dim)
        write_shard(args.data_dir, shard, batch)
        written[shard] = rows
    print(json.dumps({"prepared": len(written), "rows": written,
                      "data_dir": args.data_dir}))


def main() -> None:
    args = parse_args()
    if args.prepare:
        if not args.data_dir:
            raise SystemExit("--prepare requires --data-dir")
        prepare(args)
        return
    if args.infer:
        if not args.export_dir:
            raise SystemExit("--infer requires --export-dir")
        infer(args)
        return
    ctx = LaunchContext.from_env()
    model = ctr.make_model(shard_axis=args.shard_axis,
                           sparse_dim=args.sparse_feature_dim)
    if args.data_dir:
        source = FileShardSource(root=args.data_dir, batch_size=args.batch_size,
                                 shuffle_seed=args.shuffle_seed)
    else:
        source = SyntheticShardSource(model, batch_size=args.batch_size,
                                      batches_per_shard=args.batches_per_shard)

    ident = None
    if os.environ.get("EDL_COORDINATOR_ENDPOINT"):  # cloud mode (ref :192-203)
        from edl_tpu.launcher.discovery import wait_coordinator
        from edl_tpu.runtime.distributed import distributed_init

        client = wait_coordinator(ctx.coordinator_endpoint)
        client.worker = f"{ctx.job_name}-worker-{os.getpid()}"
        ident = distributed_init(ctx, client)  # multi-host bring-up (None if 1 proc)
        if args.num_passes != ctx.passes:
            print(f"note: cloud mode seeds passes launcher-side "
                  f"(spec.passes={ctx.passes}); --num-passes {args.num_passes} "
                  f"has no effect here")
    else:  # local twin
        from edl_tpu.coordinator.inprocess import InProcessCoordinator

        # Single local worker: lease expiry can only duplicate work, and the
        # first jit compile can stall past the 16 s default with no heartbeat
        # in between — compile-stall-tolerant leases avoid spurious replays.
        coord = InProcessCoordinator(task_lease_sec=300.0,
                                     heartbeat_ttl_sec=300.0)
        if args.data_dir:
            shards = ctx.data_shards or source.list_shards()
        else:
            shards = ctx.data_shards or shard_names("criteo", 4)
        # Multi-pass: each pass's visit of each shard is its own lease
        # (ref --num_passes loops the dataset, docker/paddle_k8s:205-216).
        coord.add_tasks(pass_tasks(shards, args.num_passes))
        client = coord.client("worker-0")
        ctx.checkpoint_dir = ctx.checkpoint_dir or tempfile.mkdtemp(prefix="edl-ctr-")

    exporter = None
    if args.export_dir:
        from edl_tpu.runtime import PeriodicExporter

        # Rank 0 only, like the reference's trainer-0 duty (train.py:169-180).
        exporter = PeriodicExporter(
            args.export_dir, "ctr", args.export_interval,
            config={"shard_axis": args.shard_axis,
                    "sparse_dim": args.sparse_feature_dim},
            rank=ident.process_id if ident is not None else 0,
        )
    cfg = ElasticConfig(
        checkpoint_dir=ctx.checkpoint_dir,
        checkpoint_interval=ctx.checkpoint_interval,
        prefetch=args.prefetch,
        step_callback=exporter,
        trainer=TrainerConfig(optimizer="adagrad",
                              learning_rate=args.learning_rate,
                              wire_transport=args.wire_transport),
    )
    mesh_axes = {k: v for k, v in ctx.mesh_axes.items() if k != "data"} or None
    if ident is not None:
        # Multi-host world: one global mesh, lockstep rounds; rescale is a
        # launcher warm restart (independent leasing would deadlock the
        # fixed-size jax.distributed world).
        from edl_tpu.runtime import MultiHostWorker

        worker = MultiHostWorker(model, client, source, cfg, mesh_axes=mesh_axes)
    else:
        worker = ElasticWorker(model, client, source, cfg, mesh_axes=mesh_axes)
    metrics = worker.run()
    if exporter is not None:
        exporter.wait()  # surface a failed background artifact write
        metrics["exports"] = float(exporter.exports)
    print(json.dumps({k: round(v, 4) for k, v in metrics.items()}))


if __name__ == "__main__":
    main()
