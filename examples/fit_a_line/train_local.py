"""Local fit_a_line training — the single-process twin.

Equivalent of `example/fit_a_line/train_local.py:41-109` (UCI-housing linear
regression, local SGD, per-pass checkpoint): same workload on the JAX backend
with the framework's Trainer + Checkpointer instead of Paddle v2 +
``save_parameter_to_tar``.
"""

import argparse
import json

import numpy as np

import jax

from edl_tpu.models import fit_a_line
from edl_tpu.parallel import MeshSpec, build_mesh
from edl_tpu.runtime import Checkpointer, Trainer, TrainerConfig


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--passes", type=int, default=10)
    parser.add_argument("--steps-per-pass", type=int, default=50)
    parser.add_argument("--batch-size", type=int, default=256)
    parser.add_argument("--lr", type=float, default=1e-2)
    parser.add_argument("--checkpoint-dir", default="")
    args = parser.parse_args()

    mesh = build_mesh(MeshSpec({"data": len(jax.devices())}))
    trainer = Trainer(fit_a_line.MODEL, mesh,
                      TrainerConfig(optimizer="sgd", learning_rate=args.lr))
    state = trainer.init_state()
    ckpt = Checkpointer(args.checkpoint_dir) if args.checkpoint_dir else None
    rng = np.random.default_rng(0)

    for pass_id in range(args.passes):
        batches = (
            fit_a_line.MODEL.synthetic_batch(rng, args.batch_size)
            for _ in range(args.steps_per_pass)
        )
        state, metrics = trainer.run(state, batches)
        print(json.dumps({"pass": pass_id, **{k: round(v, 4) for k, v in metrics.items()}}))
        if ckpt is not None:  # per-pass save (ref: train_local.py:95-96)
            ckpt.save(int(state.step), state)
    if ckpt is not None:
        ckpt.wait()


if __name__ == "__main__":
    main()
