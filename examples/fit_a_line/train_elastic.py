"""Fault-tolerant elastic fit_a_line trainer.

Equivalent of `example/fit_a_line/train_ft.py:24-118` — the reference's
flagship elasticity demo (etcd-discovered pservers + master task queue via
``cloud_reader``). Here the ``EDL_*`` env protocol points at the coordinator;
shards are leased, membership changes trigger checkpoint-restore rescale.

Runs standalone too (no env set): spawns an in-process coordinator, seeds
shards, and trains through a simulated membership change.
"""

import json
import os
import tempfile

from edl_tpu.launcher.launch import LaunchContext
from edl_tpu.models import fit_a_line
from edl_tpu.runtime import ElasticConfig, ElasticWorker, SyntheticShardSource
from edl_tpu.runtime.data import shard_names
from edl_tpu.runtime.train_loop import TrainerConfig


def main() -> None:
    ctx = LaunchContext.from_env()
    model = fit_a_line.MODEL
    source = SyntheticShardSource(model, batch_size=256, batches_per_shard=20)

    ident = None
    if os.environ.get("EDL_COORDINATOR_ENDPOINT"):
        from edl_tpu.launcher.discovery import wait_coordinator
        from edl_tpu.runtime.distributed import distributed_init

        client = wait_coordinator(ctx.coordinator_endpoint)
        client.worker = f"{ctx.job_name}-worker-{os.getpid()}"
        ident = distributed_init(ctx, client)  # multi-host bring-up (None if 1 proc)
    else:  # hermetic demo mode
        from edl_tpu.coordinator.inprocess import InProcessCoordinator

        # Single local worker: compile-stall-tolerant leases (a first jit can
        # outlast the 16 s default with no heartbeat in between; expiry would
        # only duplicate work here).
        coord = InProcessCoordinator(task_lease_sec=300.0,
                                     heartbeat_ttl_sec=300.0)
        coord.add_tasks(ctx.data_shards or shard_names("uci", 8))
        client = coord.client("worker-0")
        ctx.checkpoint_dir = ctx.checkpoint_dir or tempfile.mkdtemp(prefix="edl-fit-")

    cfg = ElasticConfig(
        checkpoint_dir=ctx.checkpoint_dir,
        checkpoint_interval=ctx.checkpoint_interval,
        trainer=TrainerConfig(optimizer="sgd", learning_rate=1e-2),
    )
    if ident is not None:  # multi-host: lockstep rounds + warm-restart rescale
        from edl_tpu.runtime import MultiHostWorker

        worker = MultiHostWorker(model, client, source, cfg)
    else:
        worker = ElasticWorker(model, client, source, cfg)
    metrics = worker.run()
    print(json.dumps({k: round(v, 4) for k, v in metrics.items()}))


if __name__ == "__main__":
    main()
