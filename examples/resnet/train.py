"""ResNet-50 elastic image-classification trainer.

The driver brief's vision configuration (`BASELINE.json` configs:
"ResNet-50 / ImageNet (data-parallel, elastic 4<->16 TPU workers)") — the
reference repo ships no vision workload, so this example extends the zoo
rather than twinning a reference file. Structure mirrors the other elastic
examples: coordinator-leased shards, checkpoint-restore rescale, and a
train / infer mode split like `examples/mnist/train.py` (the reference's
save-inference-then-infer pattern, `recognize_digits.py:147-173`).

Defaults use the TINY config (32px, width 8, 10 classes) so the example
runs on a CPU mesh; ``--imagenet`` selects the full ResNet-50/224px/1000
configuration for real chips.
"""

import argparse
import json
import os
import tempfile

import numpy as np

from edl_tpu.launcher.launch import LaunchContext
from edl_tpu.models import resnet
from edl_tpu.runtime import ElasticConfig, ElasticWorker, SyntheticShardSource
from edl_tpu.runtime.data import shard_names
from edl_tpu.runtime.train_loop import TrainerConfig


def parse_args():
    p = argparse.ArgumentParser(description="ResNet elastic training")
    p.add_argument("mode", nargs="?", default="train", choices=["train", "infer"])
    p.add_argument("--imagenet", action="store_true",
                   help="full ResNet-50/224px/1000-class configuration")
    p.add_argument("--depth", type=int, default=50, choices=sorted(resnet._STAGES))
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--batches-per-shard", type=int, default=10)
    p.add_argument("--learning-rate", type=float, default=1e-3)
    p.add_argument("--model-dir", default=None)
    return p.parse_args()


def make_model(args):
    if args.imagenet:
        cfg = resnet.ResNetConfig(depth=args.depth)
    else:
        cfg = resnet.ResNetConfig(
            depth=args.depth, num_classes=resnet.TINY.num_classes,
            image_size=resnet.TINY.image_size, width=resnet.TINY.width,
            gn_groups=resnet.TINY.gn_groups,
        )
    return resnet.make_model(cfg)


def train(args) -> None:
    ctx = LaunchContext.from_env()
    # Launcher-provided durable dir (EDL_CHECKPOINT_DIR from job.yaml) wins
    # over the fixed /tmp fallback (fixed so a flagless `train` then
    # `infer` round-trips), like the sibling elastic examples.
    model_dir = (args.model_dir or ctx.checkpoint_dir
                 or tempfile.gettempdir() + "/edl-resnet-ckpt")
    model = make_model(args)
    source = SyntheticShardSource(model, batch_size=args.batch_size,
                                 batches_per_shard=args.batches_per_shard)

    if os.environ.get("EDL_COORDINATOR_ENDPOINT"):
        from edl_tpu.launcher.discovery import wait_coordinator

        client = wait_coordinator(ctx.coordinator_endpoint)
        client.worker = f"{ctx.job_name}-worker-{os.getpid()}"
    else:  # hermetic demo mode
        from edl_tpu.coordinator.inprocess import InProcessCoordinator

        # Compile-stall-tolerant leases: a ResNet first-jit can outlast the
        # 16 s production lease with no heartbeat in between.
        coord = InProcessCoordinator(task_lease_sec=600.0,
                                     heartbeat_ttl_sec=600.0)
        coord.add_tasks(ctx.data_shards or shard_names("imagenet", 6))
        client = coord.client("worker-0")

    cfg = ElasticConfig(
        checkpoint_dir=model_dir,
        checkpoint_interval=ctx.checkpoint_interval,
        trainer=TrainerConfig(optimizer="adam",
                              learning_rate=args.learning_rate),
    )
    worker = ElasticWorker(model, client, source, cfg)
    metrics = worker.run()
    print(json.dumps({**{k: round(v, 4) for k, v in metrics.items()},
                      "model_dir": model_dir}))


def infer(args) -> None:
    model_dir = (args.model_dir or os.environ.get("EDL_CHECKPOINT_DIR")
                 or tempfile.gettempdir() + "/edl-resnet-ckpt")
    from edl_tpu.parallel import local_mesh
    from edl_tpu.runtime import Trainer
    from edl_tpu.runtime.checkpoint import (
        Checkpointer, abstract_like, live_state_specs,
    )

    model = make_model(args)
    mesh = local_mesh()
    trainer = Trainer(model, mesh, TrainerConfig())
    fresh = trainer.init_state()
    ckpt = Checkpointer(model_dir)
    if ckpt.latest_step() is None:
        raise SystemExit(f"no checkpoint under {model_dir}; run train first")
    state = ckpt.restore(abstract_like(fresh), mesh, live_state_specs(fresh))
    batch = model.synthetic_batch(np.random.default_rng(99), 128)
    placed = trainer.place_batch(batch)
    acc = float(resnet.accuracy(model, state.params, placed))
    print(json.dumps({"step": int(state.step), "accuracy": round(acc, 4)}))


def main() -> None:
    args = parse_args()
    if args.mode == "train":
        train(args)
    else:
        infer(args)


if __name__ == "__main__":
    main()
