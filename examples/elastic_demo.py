"""The elastic-rebalance experiment: the reference's published result, redone.

Reproduces the scenario behind the reference's boss tutorial numbers
(`doc/boss_tutorial.md:259-301`; BASELINE.md): an idle cluster, then

1. job1 (elastic 2..10) submitted — the autoscaler grows it to the cluster's
   capacity ceiling (ref: 18.4% -> 54.4% CPU util),
2. job2 (elastic 2..8) submitted — both share, utilization climbs
   (ref: -> 86.4%),
3. job3 submitted with NO free capacity — running jobs shrink to admit it;
   nothing stays pending (ref: job1 10->3, job2 8->4, new=4, 0 pending,
   -> 88.4%).

Here the schedulable currency is TPU chips on a hermetic FakeCluster; the
collector records the utilization trajectory exactly as the reference's
`collector.py` measurement harness did. Prints one JSON line per stage plus
a final summary line.
"""

from __future__ import annotations

import json
import sys
import time

from edl_tpu.api.quantity import ResourceList
from edl_tpu.api.types import TrainingJob
from edl_tpu.api.validation import normalize
from edl_tpu.controller import Controller
from edl_tpu.controller.autoscaler import AutoscalerConfig
from edl_tpu.controller.cluster import FakeCluster, NodeInfo
from edl_tpu.controller.updater import UpdaterConfig
from edl_tpu.tools.collector import Collector


def make_job(name: str, min_inst: int, max_inst: int) -> TrainingJob:
    return normalize(TrainingJob.from_dict({
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "image": "edl-tpu:latest",
            "passes": 1,
            "fault_tolerant": True,
            "tpu": {"accelerator_type": "v5e", "chips_per_trainer": 4},
            "trainer": {
                "entrypoint": "python examples/ctr/train.py",
                "min_instance": min_inst,
                "max_instance": max_inst,
                "resources": {
                    "requests": {"cpu": "1", "memory": "1Gi"},
                    "limits": {"cpu": "2", "memory": "2Gi"},
                },
            },
            "parallelism": {"data": 4},
        },
    }))


def wait_settled(controller, collector, seconds: float = 6.0) -> dict:
    """Let the autoscaler reach its fixed point, then sample."""
    time.sleep(seconds)
    s = collector.sample()
    return s.to_dict()


def main() -> int:
    # 10 hosts x 4 chips = 40 chips; job shapes chosen so job1's max (10
    # trainers x 4 chips) saturates the ceiling and job3 forces a rebalance.
    nodes = [
        NodeInfo(
            name=f"host{i}",
            allocatable=ResourceList.make({"cpu": 16.0, "memory": "64Gi", "tpu": 4}),
        )
        for i in range(10)
    ]
    cluster = FakeCluster(nodes)
    controller = Controller(
        cluster,
        max_load_desired=0.9,  # the deployed value (k8s/edl_controller.yaml)
        autoscaler_config=AutoscalerConfig(loop_seconds=0.5, max_load_desired=0.9),
        updater_config=UpdaterConfig(convert_seconds=0.5, poll_seconds=0.2),
    )
    controller.start()
    collector = Collector(controller.store, cluster, period_seconds=0.5)
    collector.start()

    trajectory = []

    def stage(label: str, sample: dict) -> None:
        entry = {
            "stage": label,
            "tpu_utilization": sample["tpu_utilization"],
            "pending_jobs": sample["pending_jobs"],
            "running_trainers": sample["running_trainers"],
        }
        trajectory.append(entry)
        print(json.dumps(entry))

    try:
        stage("idle", collector.sample().to_dict())

        controller.submit(make_job("job1", 2, 10))
        stage("job1-scaled", wait_settled(controller, collector))

        controller.submit(make_job("job2", 2, 8))
        stage("job2-admitted", wait_settled(controller, collector))

        controller.submit(make_job("job3", 4, 6))
        stage("job3-rebalanced", wait_settled(controller, collector, 10.0))

        final = trajectory[-1]
        ok = (
            trajectory[0]["tpu_utilization"] == 0.0
            and trajectory[1]["tpu_utilization"] > 0.5
            and trajectory[2]["tpu_utilization"] >= trajectory[1]["tpu_utilization"]
            and final["pending_jobs"] == 0
            and all(n >= 1 for n in final["running_trainers"].values())
            and len(final["running_trainers"]) == 3
        )
        print(json.dumps({
            "experiment": "elastic-rebalance",
            "ok": ok,
            "trajectory": [t["tpu_utilization"] for t in trajectory],
            "final_trainers": final["running_trainers"],
        }))
        return 0 if ok else 2
    finally:
        collector.stop()
        controller.stop()


if __name__ == "__main__":
    sys.exit(main())
