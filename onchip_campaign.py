"""Tunnel-watch runner: executes the on-chip validation campaign.

The axon tunnel serving the one real TPU goes hard-down for hours
(BENCH_NOTES.md); round 4 shipped its flagship Pallas code without a
single on-chip execution because the window never reopened. This runner
inverts the race: it probes the tunnel cheaply in a subprocess (so a
hanging ``jax.devices()`` can't wedge it) and, the moment the chip is
reachable, runs the campaign steps in priority order, capturing every
artifact. Progress is checkpointed to CAMPAIGN_STATUS.json so a restart
resumes where it left off instead of burning scarce tunnel time.

Usage:
  python onchip_campaign.py            # wait for tunnel, run all steps
  python onchip_campaign.py --once     # single probe, exit 1 if down
  EDL_CAMPAIGN_STEPS=flash_check,bench_flash python onchip_campaign.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
LOG_DIR = os.path.join(HERE, "onchip_logs")
STATUS_PATH = os.path.join(HERE, "CAMPAIGN_STATUS.json")
PROBE_INTERVAL = float(os.environ.get("EDL_PROBE_INTERVAL", "180"))
PROBE_TIMEOUT = float(os.environ.get("EDL_PROBE_TIMEOUT", "120"))
MAX_ATTEMPTS = int(os.environ.get("EDL_CAMPAIGN_ATTEMPTS", "3"))

#: name -> (argv, per-step timeout sec, stdout-JSON artifact or None).
#: Steps whose script writes its own artifact pass None. Priority order.
STEPS = [
    ("flash_check", [sys.executable, "onchip_flash_check.py"], 2400, None),
    ("bench_flash", [sys.executable, "bench_flash.py"], 3600,
     "BENCH_FLASH.json"),
    ("bench_synth", [sys.executable, "bench.py"], 2400,
     "BENCH_SYNTH_ONCHIP.json"),
    ("bench_file", [sys.executable, "bench.py"], 3000,
     "BENCH_FILE_ONCHIP.json"),
    ("flash_sweep", [sys.executable, "onchip_flash_sweep.py"], 3600, None),
    ("bench_lm", [sys.executable, "bench_lm.py"], 3600,
     "BENCH_LM_ONCHIP.json"),
    ("rescale_onchip", [sys.executable, "bench_rescale_onchip.py"], 2400,
     None),
]

STEP_ENV = {
    "bench_file": {"EDL_BENCH_MODE": "file"},
}


def log(msg: str) -> None:
    print(f"[campaign {time.strftime('%H:%M:%S')}] {msg}", flush=True)


def step_env(name: str) -> dict:
    env = dict(os.environ)
    # The axon plugin rides PYTHONPATH; append the repo so bare scripts
    # resolve `edl_tpu` (background shells don't inherit cwd sys.path).
    parts = [p for p in env.get("PYTHONPATH", "").split(":") if p]
    for need in ("/root/.axon_site", HERE):
        if need not in parts:
            parts.append(need)
    env["PYTHONPATH"] = ":".join(parts)
    # The runner just verified the tunnel; don't let a step sit in the
    # 300 s default dial loop if it flaps mid-campaign.
    env.setdefault("EDL_BENCH_INIT_TIMEOUT", "240")
    env.update(STEP_ENV.get(name, {}))
    return env


def tunnel_up() -> bool:
    """Probe jax.devices() in a throwaway subprocess with a hard timeout."""
    code = (
        "import jax; d = jax.devices(); "
        "assert any(x.platform != 'cpu' for x in d), d; print(d)"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            env=step_env("probe"), cwd=HERE, timeout=PROBE_TIMEOUT,
            capture_output=True, text=True,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def load_status() -> dict:
    try:
        with open(STATUS_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {"steps": {}}


def save_status(status: dict) -> None:
    tmp = STATUS_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(status, f, indent=1)
    os.replace(tmp, STATUS_PATH)


def extract_json_lines(text: str):
    out = []
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                out.append(json.loads(line))
            except ValueError:
                pass
    return out


def run_step(name: str, argv, timeout: float, artifact) -> dict:
    os.makedirs(LOG_DIR, exist_ok=True)
    log_path = os.path.join(LOG_DIR, f"{name}.log")
    t0 = time.time()
    try:
        r = subprocess.run(
            argv, env=step_env(name), cwd=HERE, timeout=timeout,
            capture_output=True, text=True,
        )
        rc, out, err = r.returncode, r.stdout, r.stderr
    except subprocess.TimeoutExpired as e:
        rc = -9
        out = (e.stdout or b"").decode("utf-8", "replace") \
            if isinstance(e.stdout, bytes) else (e.stdout or "")
        err = f"TIMEOUT after {timeout}s"
    with open(log_path, "w") as f:
        f.write(out)
        f.write("\n--- stderr ---\n")
        f.write(err[-20000:])

    records = extract_json_lines(out)
    # A step "ran" if it exited 0 AND produced at least one JSON record
    # that is not a backend-unavailable error.
    usable = [r_ for r_ in records if "error" not in r_]
    ok = rc == 0 and bool(usable)
    if ok and artifact:
        with open(os.path.join(HERE, artifact), "w") as f:
            if len(records) == 1:
                json.dump(records[0], f, indent=1)
            else:
                json.dump(records, f, indent=1)
    return {
        "ok": ok,
        "returncode": rc,
        "seconds": round(time.time() - t0, 1),
        "records": len(records),
        "errors": [r_["error"][:200] for r_ in records if "error" in r_],
        "log": os.path.relpath(log_path, HERE),
    }


def main() -> int:
    selected = os.environ.get("EDL_CAMPAIGN_STEPS")
    base_steps = STEPS
    if selected:
        want = set(selected.split(","))
        base_steps = [s for s in STEPS if s[0] in want]

    status = load_status()
    once = "--once" in sys.argv

    while True:
        # Re-scan each cycle: steps whose script doesn't exist yet (written
        # later in the round) join the campaign as soon as the file lands.
        steps = [
            s for s in base_steps
            if os.path.exists(os.path.join(HERE, s[1][1]))
        ]
        pending = [
            s for s in steps
            if not status["steps"].get(s[0], {}).get("ok")
            and status["steps"].get(s[0], {}).get("attempts", 0) < MAX_ATTEMPTS
        ]
        if not pending:
            failed = [
                s[0] for s in steps
                if not status["steps"].get(s[0], {}).get("ok")
            ]
            save_status(status)
            if failed:
                log(f"campaign finished with FAILED steps: {failed} "
                    f"(details in CAMPAIGN_STATUS.json)")
                return 2
            log("campaign complete")
            return 0
        if not tunnel_up():
            if once:
                log("tunnel down (--once)")
                return 1
            log(f"tunnel down; {len(pending)} steps pending; "
                f"sleeping {PROBE_INTERVAL:.0f}s")
            time.sleep(PROBE_INTERVAL)
            continue
        name, argv, timeout, artifact = pending[0]
        entry = status["steps"].setdefault(name, {"attempts": 0})
        entry["attempts"] += 1
        log(f"tunnel UP; running {name} (attempt {entry['attempts']})")
        result = run_step(name, argv, timeout, artifact)
        entry.update(result)
        entry["finished_at"] = time.strftime("%Y-%m-%dT%H:%M:%S")
        save_status(status)
        log(f"{name}: ok={result['ok']} rc={result['returncode']} "
            f"in {result['seconds']}s")


if __name__ == "__main__":
    sys.exit(main())
