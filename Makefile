# Developer entry points. The analyze target is the same command CI and
# pre-commit run; exit 1 means new findings or stale baseline entries.

PYTHON ?= python

.PHONY: analyze analyze-json baseline test chaos lint bench-pipeline

analyze:
	$(PYTHON) -m edl_tpu.analysis edl_tpu bench.py bench_rescale.py bench_pipeline.py

analyze-json:
	$(PYTHON) -m edl_tpu.analysis edl_tpu bench.py bench_rescale.py bench_pipeline.py --format json

## Regenerate accepted-debt baseline — only after consciously accepting or
## fixing findings; the diff IS the review artifact.
baseline:
	$(PYTHON) -m edl_tpu.analysis edl_tpu --write-baseline

test:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q -m 'not slow'

## Fault-injection suite: every chaos-marked test, INCLUDING the slow
## process-kill soaks tier-1 skips.
chaos:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q -m chaos

## Pipeline-schedule crossover sweep at CPU-sim scale; regenerates
## BENCH_PIPELINE.json (the artifact behind BENCH_NOTES.md's table).
bench-pipeline:
	$(PYTHON) bench_pipeline.py

lint: analyze
