# Developer entry points. The analyze target is the same command CI and
# pre-commit run; exit 1 means new findings or stale baseline entries.

PYTHON ?= python

ANALYZE_SCOPE = edl_tpu edl_tpu/serving edl_tpu/serving/kvcache.py edl_tpu/serving/router.py edl_tpu/ckpt_plane edl_tpu/parallel/planner.py edl_tpu/runtime/compile_cache.py bench.py bench_rescale.py bench_pipeline.py bench_coord.py bench_collective.py bench_serve.py

.PHONY: analyze analyze-json baseline test chaos chaos-composed chaos-preempt lint obs-smoke serve-smoke serve-lm-smoke ckpt-plane-smoke modelcheck modelcheck-native tsan-smoke bench-coord-smoke bench-replan-smoke bench-spot-smoke verify bench-pipeline bench-coord bench-collective bench-serve

analyze:
	$(PYTHON) -m edl_tpu.analysis $(ANALYZE_SCOPE)

analyze-json:
	$(PYTHON) -m edl_tpu.analysis $(ANALYZE_SCOPE) --format json

## Regenerate accepted-debt baseline — only after consciously accepting or
## fixing findings; the diff IS the review artifact.
baseline:
	$(PYTHON) -m edl_tpu.analysis edl_tpu --write-baseline

test:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q -m 'not slow'

## Fault-injection suite: every chaos-marked test, INCLUDING the slow
## process-kill soaks tier-1 skips.
chaos:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q -m chaos

## Composed cross-axis chaos: trainer SIGKILL x apiserver 409/410 x
## coordinator partitions, overlapping under one scripted ChaosScenario.
## Exercises the adaptive fault-tolerance policy end to end (blips
## reconnect in place, the storm checkpoint-and-parks) — see
## doc/robustness.md. Sanitizer-compatible: run with
## EDL_COORD_SANITIZER=tsan to put the native coordinator under TSan.
chaos-composed:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_chaos_composed.py -q -m chaos

## Revocation wave: two jobs revoked by one scripted ChaosScenario; both
## drain inside their notice with steps_lost == 0 and exact step
## accounting, and the fault timeline replays from its JSON spec.
chaos-preempt:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_chaos_preempt.py -q

## Telemetry-plane deploy gate: boots a worker with its /metrics endpoint
## against a real coordinator, scrapes over HTTP while training runs, and
## asserts every required metric family (worker, client, bridged
## coordinator) is present. See doc/observability.md.
obs-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) -m edl_tpu.obs

## Serving-path deploy gate: exports a real artifact, boots a ServingReplica
## with its HTTP frontend, pushes requests through POST /predict, swaps a
## model version mid-traffic, then scrapes /metrics and asserts the latency
## + queue-depth families (the autoscaler's signals), zero dropped requests,
## and the empty-jit-dispatch-cache AOT contract. See doc/serving.md.
serve-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) -m edl_tpu.serving

## LM-serving deploy gate: exports a small transformer, boots an
## LMServingReplica (prefill + decode AOT-compiled per (batch bucket, seq
## bucket)), decodes a concurrent prompt batch through POST /generate,
## then asserts zero dropped streams, exact token accounting, the
## edl_lm_* metric families, a fully-recycled KV block pool, and the
## empty-jit-dispatch-cache contract across both phases. See
## doc/serving.md ("LM serving").
serve-lm-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) -m edl_tpu.serving lm

## Checkpoint-plane deploy gate: trains a twin, replicates ZeRO shards to
## the coordinator's memory-resident store, kills the live state, peer-
## restores (zero blob reads) and finishes — final loss must EQUAL the
## twin's. Then drops a whole replica group and proves recovery demotes to
## the blob store with the identical result. See doc/robustness.md.
ckpt-plane-smoke:
	XLA_FLAGS=--xla_force_host_platform_device_count=4 JAX_PLATFORMS=cpu \
		$(PYTHON) -m edl_tpu.ckpt_plane

## Protocol behavior gate: bounded explicit-state exploration of every
## interleaving of the default faulty 2-worker schedule (crash+restart,
## duplicate delivery, batch frame) PLUS the EDL010 durability schedules
## (crash points between persistence effects: clean / pre-ack / torn tail
## / during compaction, recovery replay as a schedule step), each trace
## replayed against the in-process oracle (the durability rows use its
## file-backed persistence twin). Exit 1 on any invariant violation
## (epoch monotonicity, exactly-once across crash, acked-implies-durable,
## lease exclusivity, progress) or model/oracle divergence. See
## doc/analysis.md (EDL009 + EDL010).
modelcheck:
	JAX_PLATFORMS=cpu $(PYTHON) -m edl_tpu.analysis.modelcheck --timings

## Crash-injected native oracle lane: the same durability schedules, but
## every trace replays against the REAL edl-coordinator binary — the
## modeled crash point is realized by env-gated _exit(2) hooks in
## coordinator.cc (EDL_COORD_CRASH_AFTER_APPENDS / _CRASH_TORN /
## _CRASH_IN_SNAPSHOT), with a genuine kill + recovery-from-disk per
## trace. Proves the C++ journal replay (torn-tail truncation, dedup
## rebuild, snapshot+suffix equivalence) matches the model bit-for-bit.
## TSan-aware (EDL_COORD_SANITIZER=tsan instruments the binary); skips
## cleanly when no C++ toolchain is installed.
modelcheck-native:
	@if ! command -v $${CXX:-g++} >/dev/null 2>&1; then \
		echo "modelcheck-native: no C++ toolchain ($${CXX:-g++} not found) — skipping"; \
	else \
		JAX_PLATFORMS=cpu $(PYTHON) -m edl_tpu.analysis.modelcheck \
			--native --timings; \
	fi

## Native race gate: rebuild the coordinator under ThreadSanitizer and rerun
## the sanitizer-marked lane (chaos/outage/batch/hammer tests) against it.
## EDL_COORD_SANITIZER=tsan makes every CoordinatorServer in the run spawn
## the instrumented binary; a TSan report fails the child (exitcode=66) and
## the tests assert sanitizer_report() is clean. Skips cleanly when no C++
## toolchain is installed.
tsan-smoke:
	@if ! command -v $${CXX:-g++} >/dev/null 2>&1; then \
		echo "tsan-smoke: no C++ toolchain ($${CXX:-g++} not found) — skipping"; \
	else \
		EDL_COORD_SANITIZER=tsan JAX_PLATFORMS=cpu \
			$(PYTHON) -m pytest tests/ -q -m 'sanitizer and not slow'; \
	fi

## Bench-harness deploy gate: a <60 s slice of bench_coord.py — both
## topologies (single vs sharded, N=500, multiplexed connections) plus a
## fast pull-vs-push epoch-propagation pair — written to a throwaway path
## with plausibility assertions (every cell beats, push faster than pull).
## Catches harness rot without paying for the full sweep; skips cleanly
## when the native toolchain is absent.
bench-coord-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) bench_coord.py --smoke

## Replanner deploy gate: the live 8->6->8 rescale-with-layout-change arm
## ({dcn:2,data:4} -> {data:6} -> back through join/leave/re-join) plus the
## modeled sweep (planner must STRICTLY beat data-only resize at every
## point). Asserts the return leg is served by the persistent AOT compile
## cache (warm_compile ~ 0, compile_cache_hits_total >= 1) and every leg's
## recovery is phase-attributed; merges replan_arm/replan_sweep into
## BENCH_RESCALE.json + RESCALE_TIMELINE.json.
bench-replan-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) bench_rescale.py --replan

## Spot-revocation arm only: a worker revoked mid-training drains inside
## its notice (steps_lost == 0, peer-sourced restore on the shrunk
## replanned mesh); merges spot_arm into BENCH_RESCALE.json +
## RESCALE_TIMELINE.json.
bench-spot-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) bench_rescale.py --spot

## Everything a PR must pass: static analysis (EDL001-EDL010 vs baseline +
## protocol_schema.json ratchet), tier-1 tests, protocol + durability model
## checks (in-process AND crash-armed native oracle), serving smoke, TSan
## lane, revocation-wave chaos, bench-harness smokes (coordinator +
## replanner + spot drain). Tier-2 (slow, run before cutting a release):
## `make chaos` / `make chaos-composed`.
verify: analyze test modelcheck modelcheck-native serve-smoke serve-lm-smoke ckpt-plane-smoke tsan-smoke chaos-preempt bench-coord-smoke bench-replan-smoke bench-spot-smoke

## Pipeline-schedule crossover sweep at CPU-sim scale; regenerates
## BENCH_PIPELINE.json (the artifact behind BENCH_NOTES.md's table).
bench-pipeline:
	$(PYTHON) bench_pipeline.py

## Coordinator control-plane load bench at 100/1k/10k simulated workers;
## regenerates BENCH_COORD.json (doc/performance.md, control-plane section).
bench-coord:
	$(PYTHON) bench_coord.py

## Data-plane collective arms (implicit psum / explicit reduce-scatter /
## bucketed-overlap accumulation) on flat + hierarchical meshes;
## regenerates BENCH_COLLECTIVE.json (doc/performance.md, data-plane section).
bench-collective:
	$(PYTHON) bench_collective.py

## Serving-tier arms: open-loop load vs batching-on/off, per-bucket-config
## p50/p99 + QPS/chip, and rescale-under-traffic (replica added + drained
## mid-load, zero dropped requests); regenerates BENCH_SERVE.json.
bench-serve:
	$(PYTHON) bench_serve.py

lint: analyze
