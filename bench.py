"""Benchmark: CTR deep-wide steady-state throughput (samples/sec/chip).

The driver's headline metric (BASELINE.json): CTR samples/sec/chip at steady
state. The reference publishes no absolute throughput in-tree (its story is
cluster-utilization percentages, BASELINE.md), so ``vs_baseline`` compares
against this framework's own recorded static-mesh figure: read from
``BENCH_BASELINE.json`` at the repo root or the ``EDL_BENCH_BASELINE`` env
var; until one is recorded, vs_baseline is reported as 1.0 (self-relative).

Harness notes (round-4 hardening): the tunneled host<->device link swings
tens of percent between identical runs, so a single window (or best-of-few)
is noise. Each run times ``EDL_BENCH_WINDOWS`` (default 7) independent
windows and reports the MEDIAN of the best ``EDL_BENCH_KEEP`` (default 3) —
robust to both slow outliers (link stalls) and lucky spikes. Every window's
throughput is included in the JSON line so regressions can be diagnosed
from recorded artifacts instead of re-runs.

Modes (``EDL_BENCH_MODE``):
- ``synthetic`` (default) — pre-generated host batches; measures the
  jitted-step + host->device transport path (the headline number).
- ``file`` — batches come off real on-disk ``.npz`` shards through
  ``FileShardSource`` with prefetch + shuffle and coordinator leases: the
  full production data path, including file reads (VERDICT r3 weak #6).

``EDL_BENCH_RECORD_BASELINE=1`` re-records BENCH_BASELINE.json from THIS
run (forcing wire_transport off — the pre-wire static-mesh configuration)
so the baseline denominator shares the current harness.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import tempfile
import threading
import time


def probe_devices(init_timeout: float, allow_cpu: bool):
    """jax.devices() with a hard deadline and silent-CPU-fallback detection.

    The tunneled TPU link goes hard-down for hours at a time (BENCH_NOTES.md);
    jax.devices() then either raises UNAVAILABLE, HANGS in the dial loop, or
    — worst — silently falls back to the CPU backend, which would record a
    bogus huge regression against the TPU baseline. Returns (devices, None)
    on success or (None, reason) for the caller's explicit error record.
    """
    import jax

    probe: dict = {}

    def _init():
        try:
            probe["devices"] = jax.devices()
        except Exception as e:  # noqa: BLE001 — reported, not swallowed
            probe["error"] = e

    t = threading.Thread(target=_init, daemon=True)
    t.start()
    t.join(init_timeout)
    if "devices" not in probe:
        err = probe.get(
            "error", f"backend init did not complete within {init_timeout}s"
        )
        return None, f"accelerator backend unavailable: {err}"
    devices = probe["devices"]
    if not allow_cpu and all(d.platform == "cpu" for d in devices):
        return None, (
            "backend silently fell back to CPU (accelerator unavailable); "
            "refusing to record a CPU number against the TPU baseline — "
            "set EDL_BENCH_ALLOW_CPU=1 for deliberate CPU runs"
        )
    return devices, None


def _measure_windows(run_window, windows: int, keep: int):
    """Time ``windows`` runs of ``run_window`` (which must block until its
    work is device-complete); return (per-window samples/s list, median of
    the best ``keep``)."""
    times = []
    for _ in range(windows):
        t0 = time.perf_counter()
        samples = run_window()
        elapsed = time.perf_counter() - t0
        times.append(samples / elapsed)
    best = sorted(times, reverse=True)[: max(1, keep)]
    return times, statistics.median(best)


def main() -> None:
    batch_size = int(os.environ.get("EDL_BENCH_BATCH", "8192"))
    measure_steps = int(os.environ.get("EDL_BENCH_STEPS", "20"))
    windows = int(os.environ.get("EDL_BENCH_WINDOWS", "7"))
    keep = int(os.environ.get("EDL_BENCH_KEEP", "3"))
    mode = os.environ.get("EDL_BENCH_MODE", "synthetic")
    record_baseline = os.environ.get("EDL_BENCH_RECORD_BASELINE") == "1"
    warmup_steps = 5

    import jax
    import numpy as np

    devices, reason = probe_devices(
        init_timeout=float(os.environ.get("EDL_BENCH_INIT_TIMEOUT", "300")),
        allow_cpu=os.environ.get("EDL_BENCH_ALLOW_CPU") == "1",
    )
    if devices is None:
        print(
            json.dumps(
                {
                    "metric": "ctr_train_samples_per_sec_per_chip",
                    "value": 0.0,
                    "unit": "samples/s/chip",
                    "vs_baseline": 0.0,
                    "error": reason,
                }
            )
        )
        sys.stdout.flush()
        os._exit(0)  # the init thread may still be blocked dialing
    n_chips = len(devices)

    from edl_tpu.models import ctr
    from edl_tpu.parallel import MeshSpec, build_mesh
    from edl_tpu.runtime import Trainer, TrainerConfig

    mesh = build_mesh(MeshSpec({"data": n_chips}), devices)
    model = ctr.MODEL
    trainer = Trainer(
        model,
        mesh,
        TrainerConfig(optimizer="adagrad", learning_rate=0.05,
                      wire_transport=not record_baseline),
    )
    state = trainer.init_state()

    rng = np.random.default_rng(0)

    if mode == "file":
        from edl_tpu.coordinator import InProcessCoordinator
        from edl_tpu.runtime import (
            FileShardSource, LeaseReader, shard_names, write_shard,
        )

        data_dir = os.environ.get("EDL_BENCH_DATA_DIR") or tempfile.mkdtemp(
            prefix="edl-bench-"
        )
        rows_per_shard = measure_steps * batch_size // 4
        n_shards = 4 * (windows + 1)  # one window's worth per 4 shards
        shards = shard_names("bench", n_shards)
        existing = FileShardSource(root=data_dir, batch_size=batch_size)
        have = set(existing.list_shards())
        for shard in shards:
            # Per-shard (not count-based) reuse check: a dir written under a
            # different geometry regenerates rather than silently feeding the
            # wrong row budget; shard size changes are caught by row counts.
            if shard not in have or existing.rows(shard) != rows_per_shard:
                write_shard(data_dir, shard,
                            model.synthetic_batch(rng, rows_per_shard))
        source = FileShardSource(root=data_dir, batch_size=batch_size,
                                 shuffle_seed=0)
        coord = InProcessCoordinator(task_lease_sec=3600.0)
        client = coord.client("bench")
        client.register()
        client.add_tasks(shards)
        reader = iter(LeaseReader(client, source, prefetch=True))

        # warmup (compiles the jit against file-shaped batches)
        for _ in range(warmup_steps):
            state, loss = trainer.train_step(state, trainer.place_batch(next(reader)))
        jax.block_until_ready(loss)

        def run_window():
            nonlocal state, loss
            n = 0
            for _ in range(measure_steps):
                batch = next(reader, None)
                if batch is None:
                    break
                state, loss = trainer.train_step(state, trainer.place_batch(batch))
                n += 1
            jax.block_until_ready(loss)
            return n * batch_size

        metric = "ctr_train_samples_per_sec_per_chip_filefed"
    else:
        # Pre-generate host batches so data synthesis is off the timed path.
        host_batches = [model.synthetic_batch(rng, batch_size) for _ in range(4)]

        for i in range(warmup_steps):
            state, loss = trainer.train_step(
                state, trainer.place_batch(host_batches[i % 4])
            )
        jax.block_until_ready(loss)

        def run_window():
            nonlocal state, loss
            for i in range(measure_steps):
                state, loss = trainer.train_step(
                    state, trainer.place_batch(host_batches[i % 4])
                )
            jax.block_until_ready(loss)
            return measure_steps * batch_size

        metric = "ctr_train_samples_per_sec_per_chip"

    window_rates, samples_per_sec = _measure_windows(run_window, windows, keep)
    per_chip = samples_per_sec / n_chips

    here = os.path.dirname(os.path.abspath(__file__))
    baseline_file = os.path.join(here, "BENCH_BASELINE.json")
    if record_baseline:
        with open(baseline_file, "w") as f:
            json.dump(
                {
                    "samples_per_sec_per_chip": round(per_chip, 2),
                    "note": (
                        "static-mesh raw-transport CTR throughput recorded "
                        "under the round-4 harness (median of best "
                        f"{keep}/{windows} windows, {measure_steps} steps x "
                        f"batch {batch_size}); denominator for vs_baseline"
                    ),
                    "windows_samples_per_sec_per_chip": [
                        round(t / n_chips, 2) for t in window_rates
                    ],
                },
                f,
                indent=1,
            )

    baseline_per_chip = float(os.environ.get("EDL_BENCH_BASELINE", "0") or 0)
    if baseline_per_chip <= 0 and os.path.exists(baseline_file):
        with open(baseline_file) as f:
            baseline_per_chip = float(json.load(f).get("samples_per_sec_per_chip", 0))
    vs_baseline = per_chip / baseline_per_chip if baseline_per_chip > 0 else 1.0

    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(per_chip, 2),
                "unit": "samples/s/chip",
                "vs_baseline": round(vs_baseline, 4),
                "windows": [round(t / n_chips, 2) for t in window_rates],
                "median_of_best": keep,
            }
        )
    )


if __name__ == "__main__":
    main()
