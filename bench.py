"""Benchmark: CTR deep-wide steady-state throughput (samples/sec/chip).

The driver's headline metric (BASELINE.json): CTR samples/sec/chip at steady
state. The reference publishes no absolute throughput in-tree (its story is
cluster-utilization percentages, BASELINE.md), so ``vs_baseline`` compares
against this framework's own recorded static-mesh figure: read from
``BENCH_BASELINE.json`` at the repo root (written once a real-TPU number
exists) or the ``EDL_BENCH_BASELINE`` env var; until one is recorded,
vs_baseline is reported as 1.0 (self-relative).

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

from __future__ import annotations

import json
import os
import sys
import time


def main() -> None:
    batch_size = int(os.environ.get("EDL_BENCH_BATCH", "8192"))
    measure_steps = int(os.environ.get("EDL_BENCH_STEPS", "20"))
    # Repeat the measurement window and keep the best: host<->device link
    # bandwidth fluctuates heavily on shared/tunneled transports, and the
    # best window approximates the machine's true capability.
    windows = int(os.environ.get("EDL_BENCH_WINDOWS", "3"))
    warmup_steps = 5

    import jax
    import numpy as np

    devices = jax.devices()
    n_chips = len(devices)

    from edl_tpu.models import ctr
    from edl_tpu.parallel import MeshSpec, build_mesh
    from edl_tpu.runtime import Trainer, TrainerConfig

    mesh = build_mesh(MeshSpec({"data": n_chips}), devices)
    model = ctr.MODEL
    trainer = Trainer(
        model,
        mesh,
        TrainerConfig(optimizer="adagrad", learning_rate=0.05,
                      wire_transport=True),
    )
    state = trainer.init_state()

    rng = np.random.default_rng(0)
    # Pre-generate host batches so data synthesis is off the timed path.
    host_batches = [model.synthetic_batch(rng, batch_size) for _ in range(4)]

    for i in range(warmup_steps):
        state, loss = trainer.train_step(state, trainer.place_batch(host_batches[i % 4]))
    jax.block_until_ready(state.params["out"]["w"])

    best_elapsed = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        for i in range(measure_steps):
            state, loss = trainer.train_step(
                state, trainer.place_batch(host_batches[i % 4])
            )
        jax.block_until_ready(loss)
        best_elapsed = min(best_elapsed, time.perf_counter() - t0)

    samples_per_sec = measure_steps * batch_size / best_elapsed
    per_chip = samples_per_sec / n_chips

    baseline_per_chip = float(os.environ.get("EDL_BENCH_BASELINE", "0") or 0)
    if baseline_per_chip <= 0:
        baseline_file = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                     "BENCH_BASELINE.json")
        if os.path.exists(baseline_file):
            with open(baseline_file) as f:
                baseline_per_chip = float(json.load(f).get("samples_per_sec_per_chip", 0))
    vs_baseline = per_chip / baseline_per_chip if baseline_per_chip > 0 else 1.0

    print(
        json.dumps(
            {
                "metric": "ctr_train_samples_per_sec_per_chip",
                "value": round(per_chip, 2),
                "unit": "samples/s/chip",
                "vs_baseline": round(vs_baseline, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
