"""Benchmark: CTR deep-wide steady-state throughput (samples/sec/chip).

The driver's headline metric (BASELINE.json): CTR samples/sec/chip at steady
state. The reference publishes no absolute throughput in-tree (its story is
cluster-utilization percentages, BASELINE.md), so ``vs_baseline`` compares
against this framework's own static-mesh raw-transport configuration.

Harness notes (round-4 hardening, second iteration): the tunneled
host<->device link's absolute throughput swings by 2-3x across a day
(BENCH_NOTES.md records 60k-220k samples/s for the identical program), so
*any* comparison of numbers from two separate runs measures the link, not
the code — that is what the round-3 "26.5% regression" was. This harness
therefore measures BOTH arms in ONE process with interleaved windows:

- the **wire arm** — the framework's production transport (compact codec,
  decode fused into the jitted step) — is the reported ``value``;
- the **raw arm** — identical model/optimizer/mesh with raw host->device
  transport, i.e. the pre-wire static-mesh baseline configuration —
  is the denominator, re-measured under the same link conditions;
- ``vs_baseline`` = median of per-pair wire/raw ratios. Pair order
  alternates (wire-first on even pairs) so slow link drift cancels.

A paired interleaved A/B on the real chip (2026-07-30) showed wire/raw =
1.48x median with all 10 pairs > 1.12, while the same two configurations
benched ~5 minutes apart read 0.99 — cross-run comparison on this link is
meaningless, paired comparison is stable. Every window of both arms is
recorded in the JSON line so future regressions can be diagnosed from
artifacts alone.

Modes (``EDL_BENCH_MODE``):
- ``synthetic`` (default) — pre-generated host batches; paired wire/raw
  arms as above (the headline number).
- ``file`` — the wire arm feeds from real on-disk ``.npz`` shards through
  ``FileShardSource`` with prefetch + shuffle and coordinator leases (the
  full production data path, VERDICT r3 weak #6); the paired raw arm feeds
  pre-generated host batches with raw transport, so ``vs_baseline`` prices
  the whole data path + codec against the in-memory baseline. Caveat: the
  interleaved raw window gives the one-shard-deep prefetcher idle time, so
  up to 1 of the ~4 shard reads per wire window lands outside the timed
  span — the same one-shard head start the prefetcher holds in production
  steady state, but a bias to remember when comparing against the old
  back-to-back file harness.

A third paired measurement prices the input pipeline itself: the same
wire-transport configuration stepped through ``DevicePrefetcher``
(placement on a pump thread) vs placing synchronously, interleaved the
same way. Its ``pipelined`` record carries per-window ``place_ms`` /
``step_ms`` splits — see doc/performance.md for how to read them.

``EDL_BENCH_RECORD_BASELINE=1`` additionally writes the raw arm's absolute
numbers to BENCH_BASELINE.json (same run, same harness, same link).

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import tempfile
import threading
import time


def probe_devices(init_timeout: float, allow_cpu: bool):
    """jax.devices() with a hard deadline and silent-CPU-fallback detection.

    The tunneled TPU link goes hard-down for hours at a time (BENCH_NOTES.md);
    jax.devices() then either raises UNAVAILABLE, HANGS in the dial loop, or
    — worst — silently falls back to the CPU backend, which would record a
    bogus huge regression against the TPU baseline. Returns (devices, None)
    on success or (None, reason) for the caller's explicit error record.
    """
    import jax

    probe: dict = {}

    def _init():
        try:
            probe["devices"] = jax.devices()
        except Exception as e:  # edl: noqa[EDL005] reported to the caller via probe['error'], not swallowed
            probe["error"] = e

    t = threading.Thread(target=_init, daemon=True)
    t.start()
    t.join(init_timeout)
    if "devices" not in probe:
        err = probe.get(
            "error", f"backend init did not complete within {init_timeout}s"
        )
        return None, f"accelerator backend unavailable: {err}"
    devices = probe["devices"]
    if not allow_cpu and all(d.platform == "cpu" for d in devices):
        return None, (
            "backend silently fell back to CPU (accelerator unavailable); "
            "refusing to record a CPU number against the TPU baseline — "
            "set EDL_BENCH_ALLOW_CPU=1 for deliberate CPU runs"
        )
    return devices, None


def _reset_backend_cache() -> None:
    """Best-effort clear of jax's backend cache between init attempts, so a
    retry actually re-dials instead of replaying the cached failure (or the
    cached silent CPU fallback). jax's cache internals move between
    versions; failure to clear just makes the next attempt a fast no-op."""
    try:
        from jax._src import xla_bridge

        xla_bridge.backends.cache_clear()  # type: ignore[attr-defined]
    except Exception:  # edl: noqa[EDL005] optional cache clear; next attempt degrades to a no-op
        pass


def probe_devices_with_retry(allow_cpu: bool):
    """Retry ``probe_devices`` with geometric backoff until an env-tunable
    total budget (EDL_BENCH_INIT_BUDGET_S, default 1500 s ~= 25 min) runs
    out. The tunnel flaps on minute scales (BENCH_NOTES.md records
    hours-long outages punctuated by brief recoveries), so a single 300 s
    window converts a transient flap into a bare 0.0 artifact; the loop
    converts it into either a late success or an error record with the full
    attempt history as evidence.

    Returns (devices, attempts, reason): ``attempts`` is a list of
    {at_unix, elapsed_s, outcome} dicts — one per dial — to be embedded in
    the emitted JSON on success AND error. Caveat: a HUNG attempt leaks its
    daemon dial thread (jax holds no cancellation handle); each retry
    starts a fresh thread against a cleared backend cache.
    """
    budget = float(os.environ.get("EDL_BENCH_INIT_BUDGET_S", "1500"))
    window = float(os.environ.get("EDL_BENCH_INIT_TIMEOUT", "300"))
    start = time.time()
    attempts: list = []
    reason = "backend init budget exhausted before any attempt"
    k = 0
    while True:
        at = time.time()
        devices, reason = probe_devices(
            init_timeout=min(window, max(10.0, budget - (at - start))),
            allow_cpu=allow_cpu,
        )
        attempts.append({
            "at_unix": round(at, 3),
            "elapsed_s": round(time.time() - at, 3),
            "outcome": "ok" if devices is not None else reason,
        })
        if devices is not None:
            return devices, attempts, None
        backoff = min(240.0, 15.0 * (1.5 ** k))
        k += 1
        if time.time() - start + backoff >= budget:
            return None, attempts, reason
        time.sleep(backoff)
        _reset_backend_cache()


def probe_or_exit(metric: str, unit: str = ""):
    """Shared bench preamble: platform override, retrying device probe, and
    — when the accelerator stays unreachable through the whole init budget
    — one flushed error-JSON line (with the per-attempt history) followed
    by a hard exit (a dial thread may still be blocked). Returns
    ``(devices, attempts)`` on success; callers embed ``attempts`` in their
    emitted JSON as ``init_attempts``. Keeps the dial-budget/CPU-guard
    semantics in one place for bench.py / bench_lm.py / bench_flash.py /
    onchip_flash_check.py / onchip_flash_sweep.py."""
    import jax

    if os.environ.get("EDL_BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["EDL_BENCH_PLATFORM"])
    devices, attempts, reason = probe_devices_with_retry(
        allow_cpu=os.environ.get("EDL_BENCH_ALLOW_CPU") == "1"
        or os.environ.get("EDL_BENCH_PLATFORM") == "cpu",
    )
    if devices is None:
        record = {"metric": metric, "value": 0.0, "vs_baseline": 0.0,
                  "error": reason, "init_attempts": attempts}
        if unit:
            record["unit"] = unit
        print(json.dumps(record))
        sys.stdout.flush()
        os._exit(0)
    return devices, attempts


def median_of_best(rates, keep: int) -> float:
    return statistics.median(sorted(rates, reverse=True)[: max(1, keep)])


def main() -> None:
    batch_size = int(os.environ.get("EDL_BENCH_BATCH", "8192"))
    measure_steps = int(os.environ.get("EDL_BENCH_STEPS", "20"))
    windows = int(os.environ.get("EDL_BENCH_WINDOWS", "7"))
    keep = int(os.environ.get("EDL_BENCH_KEEP", "3"))
    mode = os.environ.get("EDL_BENCH_MODE", "synthetic")
    record_baseline = os.environ.get("EDL_BENCH_RECORD_BASELINE") == "1"
    warmup_steps = 5

    import jax
    import numpy as np

    devices, init_attempts = probe_or_exit(
        "ctr_train_samples_per_sec_per_chip", "samples/s/chip"
    )
    n_chips = len(devices)

    from edl_tpu.models import ctr
    from edl_tpu.parallel import MeshSpec, build_mesh
    from edl_tpu.runtime import Trainer, TrainerConfig

    mesh = build_mesh(MeshSpec({"data": n_chips}), devices)
    model = ctr.MODEL
    rng = np.random.default_rng(0)
    host_batches = [model.synthetic_batch(rng, batch_size) for _ in range(4)]

    def make_arm(wire: bool):
        trainer = Trainer(
            model,
            mesh,
            TrainerConfig(optimizer="adagrad", learning_rate=0.05,
                          wire_transport=wire),
        )
        return {"trainer": trainer, "state": trainer.init_state(), "loss": None}

    def synthetic_window(arm, steps=measure_steps):
        trainer = arm["trainer"]
        state = arm["state"]
        loss = arm["loss"]  # tolerate steps=0 (EDL_BENCH_STEPS=0 probes)
        for i in range(steps):
            state, loss = trainer.train_step(
                state, trainer.place_batch(host_batches[i % 4])
            )
        if loss is not None:
            jax.block_until_ready(loss)
        arm["state"], arm["loss"] = state, loss
        return steps * batch_size

    wire_arm = make_arm(wire=True)
    raw_arm = make_arm(wire=False)

    if mode == "file":
        from edl_tpu.coordinator import InProcessCoordinator
        from edl_tpu.runtime import (
            FileShardSource, LeaseReader, shard_names, write_shard,
        )

        data_dir = os.environ.get("EDL_BENCH_DATA_DIR") or tempfile.mkdtemp(
            prefix="edl-bench-"
        )
        rows_per_shard = measure_steps * batch_size // 4
        n_shards = 4 * (windows + 1)  # one window's worth per 4 shards
        shards = shard_names("bench", n_shards)
        existing = FileShardSource(root=data_dir, batch_size=batch_size)
        have = set(existing.list_shards())
        for shard in shards:
            # Per-shard (not count-based) reuse check: a dir written under a
            # different geometry regenerates rather than silently feeding the
            # wrong row budget; shard size changes are caught by row counts.
            if shard not in have or existing.rows(shard) != rows_per_shard:
                write_shard(data_dir, shard,
                            model.synthetic_batch(rng, rows_per_shard))
        source = FileShardSource(root=data_dir, batch_size=batch_size,
                                 shuffle_seed=0)
        coord = InProcessCoordinator(task_lease_sec=3600.0)
        client = coord.client("bench")
        client.register()
        client.add_tasks(shards)
        reader = iter(LeaseReader(client, source, prefetch=True))

        def measured_window(arm):
            trainer = arm["trainer"]
            state = arm["state"]
            loss = arm["loss"]  # keeps block_until_ready sane on a dry reader
            n = 0
            for _ in range(measure_steps):
                batch = next(reader, None)
                if batch is None:
                    break
                state, loss = trainer.train_step(state, trainer.place_batch(batch))
                n += 1
            if loss is not None:
                jax.block_until_ready(loss)
            arm["state"], arm["loss"] = state, loss
            return n * batch_size

        # warmup compiles the wire jit against file-shaped batches
        for _ in range(warmup_steps):
            wire_arm["state"], wire_arm["loss"] = wire_arm["trainer"].train_step(
                wire_arm["state"], wire_arm["trainer"].place_batch(next(reader))
            )
        jax.block_until_ready(wire_arm["loss"])
        metric = "ctr_train_samples_per_sec_per_chip_filefed"
    else:
        measured_window = synthetic_window
        synthetic_window(wire_arm, steps=warmup_steps)
        metric = "ctr_train_samples_per_sec_per_chip"

    synthetic_window(raw_arm, steps=warmup_steps)

    def timed(run, arm):
        t0 = time.perf_counter()
        samples = run(arm)
        elapsed = time.perf_counter() - t0
        return samples / elapsed if samples else 0.0

    wire_rates, raw_rates, ratios = [], [], []
    for k in range(windows):
        # Alternate order so slow link drift cancels out of the pair ratios.
        if k % 2 == 0:
            w = timed(measured_window, wire_arm)
            r = timed(synthetic_window, raw_arm)
        else:
            r = timed(synthetic_window, raw_arm)
            w = timed(measured_window, wire_arm)
        wire_rates.append(w)
        raw_rates.append(r)
        if w and r:
            ratios.append(w / r)

    per_chip = median_of_best(wire_rates, keep) / n_chips
    raw_per_chip = median_of_best(raw_rates, keep) / n_chips
    vs_baseline = statistics.median(ratios) if ratios else 1.0

    # -- paired pipelined-vs-synchronous arm ------------------------------------
    # Same interleaved-window pairing as wire/raw, now pricing the input
    # pipeline itself: one wire-transport trainer stepped through
    # DevicePrefetcher (encode + H2D placement on a pump thread) vs the same
    # trainer placing synchronously on the dispatch thread. Each window
    # reports its place/step split: place_ms is the placement WORK either
    # way; the sync arm pays it inside the wall (step_ms = wall - place),
    # the pipelined arm overlaps it (step_ms ~= wall).
    from edl_tpu.runtime.pipeline import DevicePrefetcher

    pipe_arm = make_arm(wire=True)
    synthetic_window(pipe_arm, steps=warmup_steps)

    def window_batches():
        return (host_batches[i % 4] for i in range(measure_steps))

    def pipelined_window(arm):
        trainer, state, loss = arm["trainer"], arm["state"], arm["loss"]
        n, place = 0, 0.0
        with DevicePrefetcher(window_batches(), trainer.place_bound,
                              depth=2) as pf:
            for item in pf:
                placed, step_fn = item.payload
                state, loss = step_fn(state, placed)
                n += 1
                place += item.place_seconds
        if loss is not None:
            jax.block_until_ready(loss)
        arm["state"], arm["loss"] = state, loss
        return n * batch_size, place

    def sync_split_window(arm):
        trainer, state, loss = arm["trainer"], arm["state"], arm["loss"]
        n, place = 0, 0.0
        for batch in window_batches():
            t0 = time.perf_counter()
            placed, step_fn = trainer.place_bound(batch)
            place += time.perf_counter() - t0
            state, loss = step_fn(state, placed)
            n += 1
        if loss is not None:
            jax.block_until_ready(loss)
        arm["state"], arm["loss"] = state, loss
        return n * batch_size, place

    def timed_split(run, arm):
        t0 = time.perf_counter()
        samples, place = run(arm)
        elapsed = max(time.perf_counter() - t0, 1e-9)
        return samples / elapsed if samples else 0.0, place * 1e3, elapsed * 1e3

    pipe_rates, sync_rates, pipe_ratios = [], [], []
    pipe_place_ms, sync_place_ms, pipe_step_ms, sync_step_ms = [], [], [], []
    for k in range(windows):
        if k % 2 == 0:
            p_rate, p_place, p_wall = timed_split(pipelined_window, pipe_arm)
            s_rate, s_place, s_wall = timed_split(sync_split_window, pipe_arm)
        else:
            s_rate, s_place, s_wall = timed_split(sync_split_window, pipe_arm)
            p_rate, p_place, p_wall = timed_split(pipelined_window, pipe_arm)
        pipe_rates.append(p_rate)
        sync_rates.append(s_rate)
        pipe_place_ms.append(p_place)
        sync_place_ms.append(s_place)
        pipe_step_ms.append(p_wall)  # placement overlapped: wall ~= step time
        sync_step_ms.append(s_wall - s_place)
        if p_rate and s_rate:
            pipe_ratios.append(p_rate / s_rate)

    pipelined = {
        "value": round(median_of_best(pipe_rates, keep) / n_chips, 2),
        "vs_sync": round(statistics.median(pipe_ratios), 4) if pipe_ratios else 1.0,
        "windows": [round(t / n_chips, 2) for t in pipe_rates],
        "windows_sync": [round(t / n_chips, 2) for t in sync_rates],
        "place_ms": [round(t, 2) for t in pipe_place_ms],
        "place_ms_sync": [round(t, 2) for t in sync_place_ms],
        "step_ms": [round(t, 2) for t in pipe_step_ms],
        "step_ms_sync": [round(t, 2) for t in sync_step_ms],
        "paired_ratios": [round(r, 4) for r in pipe_ratios],
    }

    # Analytic data-plane accounting for the measured configuration
    # (Trainer.data_plane): gradient bytes-on-wire per step and the
    # bandwidth-model collective estimate, next to the measured rates —
    # the same closed form bench_collective.py sweeps across grad_sync
    # modes and mesh hierarchies.
    plane = wire_arm["trainer"].data_plane(wire_arm["state"].params)
    data_plane = {
        "grad_sync": plane["grad_sync"],
        "grad_bytes_per_step": plane["grad_bytes_per_step"],
        "bytes_per_step": plane["bytes_per_step"],
        "collective_ms_est": round(plane["collective_seconds"] * 1e3, 4),
    }

    from edl_tpu.tools.mfu import mfu_fields

    accounting = mfu_fields(
        model,
        batch_size,
        steps_per_sec=median_of_best(wire_rates, keep) / batch_size,
        n_chips=n_chips,
        device=devices[0],
        mesh=mesh,
    )

    here = os.path.dirname(os.path.abspath(__file__))
    if record_baseline:
        with open(os.path.join(here, "BENCH_BASELINE.json"), "w") as f:
            json.dump(
                {
                    "samples_per_sec_per_chip": round(raw_per_chip, 2),
                    "note": (
                        "static-mesh raw-transport CTR throughput: the raw "
                        "arm of the paired harness (median of best "
                        f"{keep}/{windows} windows, {measure_steps} steps x "
                        f"batch {batch_size}). Absolute level is "
                        "link-condition-dependent; the honest comparison is "
                        "each run's paired vs_baseline, not this number."
                    ),
                    "windows_samples_per_sec_per_chip": [
                        round(t / n_chips, 2) for t in raw_rates
                    ],
                },
                f,
                indent=1,
            )

    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(per_chip, 2),
                "unit": "samples/s/chip",
                "vs_baseline": round(vs_baseline, 4),
                "baseline_arm_value": round(raw_per_chip, 2),
                "windows": [round(t / n_chips, 2) for t in wire_rates],
                "windows_baseline_arm": [
                    round(t / n_chips, 2) for t in raw_rates
                ],
                "paired_ratios": [round(r, 4) for r in ratios],
                "pipelined": pipelined,
                "data_plane": data_plane,
                "median_of_best": keep,
                "init_attempts": init_attempts,
                **accounting,
                "pairing": (
                    "vs_baseline = median per-pair ratio of interleaved "
                    "wire/raw windows in one process (cross-run comparison "
                    "is link-noise on this tunnel; see BENCH_NOTES.md)"
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
