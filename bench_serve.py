"""Serving-tier bench: continuous batching vs none, bucket configs, and
rescale-under-traffic — regenerates BENCH_SERVE.json.

Each batching arm runs two phases against one replica:

- **open-loop latency** — requests arrive on a Poisson schedule at a rate
  below single-replica capacity and do NOT slow down when the server lags
  (closed-loop generators hide overload by self-throttling); p50/p99 are
  honest service latencies, not backlog artifacts.
- **burst throughput** — all requests submitted at once; wall-clock to
  drain the queue gives saturated QPS (and QPS/chip). This is where
  continuous batching pays: the same request count collapses into ~N/32
  device dispatches instead of N.

Arms:

- ``batching_on``  — the full bucket ladder + coalesce window.
- ``batching_off`` — bucket ladder (1,), zero coalesce delay: every request
  is its own batch (the naive frontend this package replaces).
- one ``batching_on`` run per bucket configuration (the bucket table).
- ``rescale_under_traffic`` — a 2-replica pool behind the real
  :class:`~edl_tpu.serving.router.Router` (shallowest-queue affinity);
  mid-load a third replica joins (AOT-compiles, then takes traffic) and
  one replica drains out. Every accepted request must resolve: the
  zero-dropped-requests number IS the result.

LM arms (the decode-native tier, same chips):

- ``lm_serving`` — one LMServingReplica, three phases: open-loop Poisson
  *stream* arrivals for honest p50/p99 PER-TOKEN latency (scraped from
  the replica's own `edl_lm_token_latency_seconds` histogram — the bench
  dogfoods the autoscaler's signal path); a continuous-batching burst
  (all streams at once, per-token join/leave); and the same workload
  gang-scheduled in static waves (a wave admits together and the next
  waits for the slowest stream — the pre-continuous-batching baseline).
  Continuous must beat static on tokens/s at equal chips: the paired
  delta is the result. KV-block occupancy and peak are reported from the
  block pool's own stats.
- ``lm_rescale_under_decode`` — a 2-replica LM pool behind the Router;
  mid-decode a pre-compiled third replica joins and one replica is
  removed, its live streams evicted and migrated (prefix-stitched).
  ``dropped_streams`` must be 0 and every stream's token count exact.

CPU-sim caveat (same discipline as the sibling benches): numbers are
generated on the CPU backend with virtual devices, so absolute latency is
meaningless next to a real TPU pod — the comparisons (batching on/off,
bucket shapes, drop counts under rescale) are the portable part.
QPS/chip divides by `jax.device_count()` per the MLPerf-style per-chip
accounting the TPU-pod papers report.
"""

from __future__ import annotations

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import json
import tempfile
import threading
import time
from typing import Dict, List

import numpy as np

OUT = os.path.join(os.path.dirname(__file__), "BENCH_SERVE.json")

RATE_QPS = 120.0  # below single-replica CPU-sim capacity (~300 QPS)
N_REQUESTS = 360
BURST_REQUESTS = 512
BUCKET_CONFIGS = ((1, 8, 32), (1, 4, 16), (8, 32))

# LM tier: a small transformer the CPU backend decodes in milliseconds —
# per-chip absolute numbers are sim-only, the paired comparisons portable.
LM_MODEL_KW = dict(vocab_size=64, d_model=32, n_layers=2, n_heads=2,
                   d_ff=64, seq_len=64, flash=False)
LM_BATCH_BUCKETS = (1, 4, 8)
LM_SEQ_BUCKETS = (16, 32)
LM_KV_BLOCKS = 256
LM_KV_BLOCK_TOKENS = 8
LM_N_STREAMS = 48          # continuous-vs-static burst size
LM_OPEN_STREAMS = 24       # open-loop per-token-latency phase
LM_STREAM_RATE = 6.0       # Poisson stream arrivals/s, below capacity
LM_RESCALE_STREAMS = 32
LM_RESCALE_NEW_TOKENS = 40


def _export_artifact(directory: str, scale: float = 1.0, step: int = 100):
    import jax

    from edl_tpu.models import fit_a_line
    from edl_tpu.runtime.export import _serving_mesh, save_inference_model

    model = fit_a_line.MODEL
    mesh = _serving_mesh(model)
    params = model.init(jax.random.PRNGKey(0), mesh)
    if scale != 1.0:
        params = jax.tree_util.tree_map(lambda x: x * scale, params)
    save_inference_model(directory, "fit_a_line", params, step=step,
                         versioned=True)


def _requests(n: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    features = [{"x": rng.standard_normal(13).astype(np.float32)}
                for _ in range(n)]
    # exponential inter-arrivals -> Poisson arrivals at RATE_QPS
    gaps = rng.exponential(1.0 / RATE_QPS, size=n)
    return features, np.cumsum(gaps)


def _percentiles(latencies: List[float]) -> Dict[str, float]:
    arr = np.asarray(latencies)
    return {
        "p50_ms": round(float(np.percentile(arr, 50)) * 1e3, 3),
        "p99_ms": round(float(np.percentile(arr, 99)) * 1e3, 3),
        "mean_ms": round(float(arr.mean()) * 1e3, 3),
    }


def _open_loop(submit, n: int, seed: int = 0):
    """Fire ``n`` requests on the open-loop schedule; returns
    ([(future, record)], submit_errors). Completion time is stamped by a
    done-callback AT resolution — measuring at collection time would
    charge early requests for the whole submission window."""
    features, arrivals = _requests(n, seed)
    t0 = time.monotonic()
    futures, errors = [], 0
    for feat, due in zip(features, arrivals):
        delay = t0 + due - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        try:
            t_submit = time.monotonic()
            fut = submit(feat)
            record = {"t_submit": t_submit, "t_done": None}
            fut.add_done_callback(
                lambda f, r=record: r.__setitem__("t_done", time.monotonic())
            )
            futures.append((fut, record))
        except Exception:  # edl: noqa[EDL005] overload rejections are a measured outcome of the open-loop arm, reported as submit errors in the results
            errors += 1
    return futures, errors


def run_arm(name: str, buckets, max_delay_s: float) -> Dict:
    import jax

    from edl_tpu.serving import ServingConfig, ServingReplica

    with tempfile.TemporaryDirectory() as td:
        _export_artifact(td)
        replica = ServingReplica(ServingConfig(
            model_dir=td, buckets=buckets, max_batch_delay_s=max_delay_s,
            queue_capacity=4096, name=f"bench-{name}",
        )).start()
        try:
            # phase 1: open-loop latency below capacity
            futures, submit_errors = _open_loop(replica.submit, N_REQUESTS)
            latencies = []
            failed = 0
            for fut, record in futures:
                try:
                    fut.result(timeout=60)
                    latencies.append(record["t_done"] - record["t_submit"])
                except Exception:  # edl: noqa[EDL005] per-request failures are a measured outcome, reported as the arm's failed count
                    failed += 1
            # phase 2: burst throughput — everything enqueued at once
            feats, _ = _requests(BURST_REQUESTS, seed=2)
            t_burst = time.monotonic()
            burst = [replica.submit(f) for f in feats]
            for fut in burst:
                fut.result(timeout=120)
            burst_wall = time.monotonic() - t_burst
            status = replica.status()
        finally:
            replica.stop()
    qps = BURST_REQUESTS / burst_wall if burst_wall > 0 else 0.0
    chips = jax.device_count()
    return {
        "buckets": list(buckets),
        "max_batch_delay_ms": max_delay_s * 1e3,
        "open_loop": {
            "requests": N_REQUESTS,
            "offered_qps": RATE_QPS,
            "completed": len(latencies),
            "failed": failed + submit_errors,
            "latency": _percentiles(latencies),
        },
        "burst": {
            "requests": BURST_REQUESTS,
            "wall_seconds": round(burst_wall, 3),
            "qps": round(qps, 1),
            "qps_per_chip": round(qps / chips, 2),
        },
        "bucket_hits": status["bucket_hits"],
        "batches": sum(status["bucket_hits"].values()),
        "mean_batch_size": round(
            status["completed"] / max(1, sum(status["bucket_hits"].values())), 2
        ),
    }


def run_rescale_arm() -> Dict:
    from edl_tpu.serving import Router, ServingConfig, ServingReplica

    buckets = (1, 8, 32)
    with tempfile.TemporaryDirectory() as td:
        _export_artifact(td)
        made = []

        def make(i):
            replica = ServingReplica(ServingConfig(
                model_dir=td, buckets=buckets, max_batch_delay_s=0.005,
                queue_capacity=4096, name=f"bench-rescale-{i}",
            )).start()
            made.append(replica)
            return replica

        # the real control-plane Router (shallowest-queue affinity +
        # overload failover), not the round-robin stand-in it replaced
        pool = Router([make(0), make(1)], name="bench-rescale")
        timeline = []

        def rescale_script():
            # grow mid-traffic: the new replica AOT-compiles its buckets
            # BEFORE joining the pool (the warm-join discipline)
            time.sleep(0.4)
            pool.add(make(2))
            timeline.append("t+0.4s grow 2->3 (replica pre-compiled)")
            # shrink mid-traffic: remove from routing, then drain — every
            # request already accepted by the leaving replica completes
            time.sleep(0.4)
            leaving = pool.remove("bench-rescale-0")
            timeline.append("t+0.8s shrink 3->2 (drained, zero aborts)")
            leaving.stop(drain=True)

        script = threading.Thread(target=rescale_script)
        script.start()
        t_start = time.monotonic()
        futures, submit_errors = _open_loop(pool.submit, N_REQUESTS, seed=1)
        latencies, dropped = [], 0
        for fut, record in futures:
            try:
                fut.result(timeout=60)
                latencies.append(record["t_done"] - record["t_submit"])
            except Exception:  # edl: noqa[EDL005] a dropped in-flight request is THE metric of the rescale arm (must be zero); counted, and non-zero fails the bench exit code
                dropped += 1
        wall = time.monotonic() - t_start
        script.join()
        completed_per_replica = {}
        for replica in made:
            status = replica.status()
            completed_per_replica[status["name"]] = status["completed"]
            replica.stop()
    return {
        "buckets": list(buckets),
        "requests": N_REQUESTS,
        "accepted": len(futures),
        "submit_rejections": submit_errors,
        "completed": len(latencies),
        "dropped_in_flight": dropped,
        "timeline": timeline,
        "completed_per_replica": completed_per_replica,
        "achieved_qps": round(len(latencies) / wall, 1) if wall else 0.0,
        "latency": _percentiles(latencies),
    }


# -- the LM tier ---------------------------------------------------------------


def _export_lm_artifact(directory: str) -> None:
    import jax

    from edl_tpu.models import transformer
    from edl_tpu.runtime.export import _serving_mesh, save_inference_model

    model = transformer.make_model(**LM_MODEL_KW)
    mesh = _serving_mesh(model)
    params = model.init(jax.random.PRNGKey(0), mesh)
    save_inference_model(directory, "transformer", params,
                         config=LM_MODEL_KW, step=100)


def _lm_workload(n: int, seed: int = 0):
    """(prompt, max_new_tokens) pairs with varied prompt lengths AND
    varied budgets — length variance is exactly what static batching pays
    for (every wave waits for its slowest stream)."""
    rng = np.random.default_rng(seed)
    return [
        (rng.integers(1, 60, size=int(rng.integers(3, 9))),
         int(rng.integers(4, 21)))
        for _ in range(n)
    ]


def run_lm_arm() -> Dict:
    import jax

    from edl_tpu.serving import LMServingConfig, LMServingReplica
    from edl_tpu.serving.autoscale import histogram_quantile, scrape_lm_signal

    with tempfile.TemporaryDirectory() as td:
        _export_lm_artifact(td)
        replica = LMServingReplica(LMServingConfig(
            model_dir=td, batch_buckets=LM_BATCH_BUCKETS,
            seq_buckets=LM_SEQ_BUCKETS, kv_blocks=LM_KV_BLOCKS,
            kv_block_tokens=LM_KV_BLOCK_TOKENS, port=0, name="bench-lm",
        )).start()
        try:
            # phase 1: open-loop Poisson STREAM arrivals below capacity;
            # per-token p50/p99 scraped from the replica's own histogram
            # (the same family the LM autoscaler scales on)
            rng = np.random.default_rng(3)
            arrivals = np.cumsum(
                rng.exponential(1.0 / LM_STREAM_RATE, size=LM_OPEN_STREAMS)
            )
            t0 = time.monotonic()
            handles = []
            for i, due in enumerate(arrivals):
                delay = t0 + due - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                handles.append(replica.submit(
                    rng.integers(1, 60, size=3 + i % 6), max_new_tokens=8,
                ))
            for h in handles:
                h.result(timeout=120)
            sig = scrape_lm_signal(replica.url)
            p50 = histogram_quantile(sig.token_latency_buckets, 0.5)
            p99 = histogram_quantile(sig.token_latency_buckets, 0.99)
            open_loop = {
                "streams": LM_OPEN_STREAMS,
                "offered_streams_per_s": LM_STREAM_RATE,
                "tokens": int(sig.token_count),
                "token_latency": {
                    "p50_ms": round(p50 * 1e3, 3) if p50 is not None else None,
                    "p99_ms": round(p99 * 1e3, 3) if p99 is not None else None,
                },
            }

            # phase 2: continuous batching — every stream admitted at
            # once, membership changes per token
            work = _lm_workload(LM_N_STREAMS, seed=4)
            t_burst = time.monotonic()
            handles = [replica.submit(p, max_new_tokens=m) for p, m in work]
            occupancy_peak_window = replica.status()["kv"]["occupancy"]
            cont_tokens = sum(len(h.result(timeout=300)["tokens"])
                              for h in handles)
            cont_wall = time.monotonic() - t_burst

            # phase 3: the SAME workload gang-scheduled in static waves —
            # a wave admits together and the next waits for its slowest
            # stream (the pre-continuous-batching baseline)
            wave = LM_BATCH_BUCKETS[-1]
            t_static = time.monotonic()
            static_tokens = 0
            for i in range(0, len(work), wave):
                hs = [replica.submit(p, max_new_tokens=m)
                      for p, m in work[i:i + wave]]
                static_tokens += sum(len(h.result(timeout=300)["tokens"])
                                     for h in hs)
            static_wall = time.monotonic() - t_static
            kv = replica.status()["kv"]
        finally:
            replica.stop()
    chips = jax.device_count()
    cont_tps = cont_tokens / cont_wall if cont_wall > 0 else 0.0
    static_tps = static_tokens / static_wall if static_wall > 0 else 0.0
    return {
        "model": {k: LM_MODEL_KW[k]
                  for k in ("d_model", "n_layers", "n_heads", "seq_len")},
        "batch_buckets": list(LM_BATCH_BUCKETS),
        "seq_buckets": list(LM_SEQ_BUCKETS),
        "open_loop": open_loop,
        "continuous": {
            "streams": LM_N_STREAMS,
            "tokens": cont_tokens,
            "wall_seconds": round(cont_wall, 3),
            "tokens_per_s": round(cont_tps, 1),
            "tokens_per_s_per_chip": round(cont_tps / chips, 2),
        },
        "static_waves": {
            "streams": LM_N_STREAMS,
            "wave_size": wave,
            "tokens": static_tokens,
            "wall_seconds": round(static_wall, 3),
            "tokens_per_s": round(static_tps, 1),
            "tokens_per_s_per_chip": round(static_tps / chips, 2),
        },
        "continuous_speedup": round(cont_tps / static_tps, 2)
        if static_tps else None,
        "kv": {
            "n_blocks": kv["n_blocks"],
            "block_tokens": kv["block_tokens"],
            "peak_blocks_used": kv["peak_blocks_used"],
            "peak_occupancy": round(
                kv["peak_blocks_used"] / kv["n_blocks"], 4
            ),
            "burst_occupancy": occupancy_peak_window,
        },
    }


def run_lm_rescale_arm() -> Dict:
    from edl_tpu.serving import LMServingConfig, LMServingReplica, Router

    # the 64-token capacity bucket keeps streams decoding long enough
    # that the pool provably changes size mid-decode
    seq_buckets = (16, 64)
    with tempfile.TemporaryDirectory() as td:
        _export_lm_artifact(td)

        def make_lm(i):
            return LMServingReplica(LMServingConfig(
                model_dir=td, batch_buckets=LM_BATCH_BUCKETS,
                seq_buckets=seq_buckets, kv_blocks=LM_KV_BLOCKS,
                kv_block_tokens=LM_KV_BLOCK_TOKENS, name=f"bench-lm-{i}",
            )).start()

        # the joining replica compiles BEFORE the traffic starts: rescale
        # measures membership change, not compile time (warm-join)
        rep_a, rep_b, rep_c = make_lm(0), make_lm(1), make_lm(2)
        router = Router([rep_a, rep_b], name="bench-lm-rescale")
        rng = np.random.default_rng(5)
        t_start = time.monotonic()
        handles = [
            router.generate_async(rng.integers(1, 60, size=int(n)),
                                  max_new_tokens=LM_RESCALE_NEW_TOKENS)
            for n in rng.integers(3, 9, size=LM_RESCALE_STREAMS)
        ]
        timeline = []
        time.sleep(0.15)
        router.add(rep_c)
        timeline.append("t+0.15s grow 2->3 (replica pre-compiled)")
        time.sleep(0.15)
        removed = router.remove(rep_a.config.name)
        timeline.append(
            "t+0.30s shrink 3->2 (streams evicted + migrated mid-decode)"
        )
        removed.stop()
        results = [h.result(timeout=300) for h in handles]
        wall = time.monotonic() - t_start
        stats = router.stats()
        per_replica = {r.config.name: r.status()["completed"]
                       for r in (rep_a, rep_b, rep_c)}
        for r in (rep_b, rep_c):
            r.stop()
    tokens = sum(len(r["tokens"]) for r in results)
    exact = all(len(r["tokens"]) == LM_RESCALE_NEW_TOKENS for r in results)
    return {
        "streams": LM_RESCALE_STREAMS,
        "max_new_tokens": LM_RESCALE_NEW_TOKENS,
        "timeline": timeline,
        "dropped_streams": stats["dropped_streams"],
        "migrations": stats["migrations"],
        "migrated_tokens": stats["migrated_tokens"],
        "tokens_generated": tokens,
        "exact_token_accounting": exact,
        "completed_per_replica": per_replica,
        "tokens_per_s": round(tokens / wall, 1) if wall else 0.0,
    }


def main() -> int:
    import jax

    results = {
        "bench": "serving tier: continuous batching + rescale-under-traffic",
        "env": {
            "backend": jax.default_backend(),
            "devices": jax.device_count(),
            "note": ("CPU-sim: absolute latencies are not TPU numbers; "
                     "batching-on/off deltas, bucket shapes and drop "
                     "counts are the portable comparisons"),
        },
        "offered_load_qps": RATE_QPS,
        "arms": {},
        "bucket_table": [],
    }
    print(f"== batching on (buckets {BUCKET_CONFIGS[0]}) ==")
    on = run_arm("on", BUCKET_CONFIGS[0], 0.005)
    print(json.dumps({**on["open_loop"]["latency"], **on["burst"]}))
    results["arms"]["batching_on"] = on
    print("== batching off (bucket ladder (1,), no coalesce) ==")
    off = run_arm("off", (1,), 0.0)
    print(json.dumps({**off["open_loop"]["latency"], **off["burst"]}))
    results["arms"]["batching_off"] = off
    for buckets in BUCKET_CONFIGS:
        print(f"== bucket config {buckets} ==")
        arm = run_arm(f"buckets-{'-'.join(map(str, buckets))}", buckets, 0.005)
        results["bucket_table"].append(arm)
    print("== rescale under traffic ==")
    rescale = run_rescale_arm()
    print(json.dumps({k: rescale[k] for k in
                      ("accepted", "completed", "dropped_in_flight")}))
    results["arms"]["rescale_under_traffic"] = rescale
    print("== LM serving: continuous vs static batching ==")
    lm = run_lm_arm()
    print(json.dumps({
        "continuous_tokens_per_s": lm["continuous"]["tokens_per_s"],
        "static_tokens_per_s": lm["static_waves"]["tokens_per_s"],
        "speedup": lm["continuous_speedup"],
        "token_p99_ms": lm["open_loop"]["token_latency"]["p99_ms"],
    }))
    results["arms"]["lm_serving"] = lm
    print("== LM rescale under decode ==")
    lm_rescale = run_lm_rescale_arm()
    print(json.dumps({k: lm_rescale[k] for k in
                      ("dropped_streams", "migrations",
                       "exact_token_accounting")}))
    results["arms"]["lm_rescale_under_decode"] = lm_rescale
    with open(OUT, "w") as f:
        json.dump(results, f, indent=2)
        f.write("\n")
    print(f"wrote {OUT}")
    failures = []
    if rescale["dropped_in_flight"] != 0:
        failures.append("batch rescale dropped in-flight requests")
    if lm["continuous"]["tokens_per_s"] <= lm["static_waves"]["tokens_per_s"]:
        failures.append("continuous batching did not beat static waves")
    if lm_rescale["dropped_streams"] != 0:
        failures.append("LM rescale dropped streams")
    if not lm_rescale["exact_token_accounting"]:
        failures.append("LM rescale token accounting inexact")
    for f in failures:
        print(f"FAILED: {f}")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
