"""Serving-tier bench: continuous batching vs none, bucket configs, and
rescale-under-traffic — regenerates BENCH_SERVE.json.

Each batching arm runs two phases against one replica:

- **open-loop latency** — requests arrive on a Poisson schedule at a rate
  below single-replica capacity and do NOT slow down when the server lags
  (closed-loop generators hide overload by self-throttling); p50/p99 are
  honest service latencies, not backlog artifacts.
- **burst throughput** — all requests submitted at once; wall-clock to
  drain the queue gives saturated QPS (and QPS/chip). This is where
  continuous batching pays: the same request count collapses into ~N/32
  device dispatches instead of N.

Arms:

- ``batching_on``  — the full bucket ladder + coalesce window.
- ``batching_off`` — bucket ladder (1,), zero coalesce delay: every request
  is its own batch (the naive frontend this package replaces).
- one ``batching_on`` run per bucket configuration (the bucket table).
- ``rescale_under_traffic`` — a 2-replica pool behind a round-robin router;
  mid-load a third replica joins (AOT-compiles, then takes traffic) and
  one replica drains out. Every accepted request must resolve: the
  zero-dropped-requests number IS the result.

CPU-sim caveat (same discipline as the sibling benches): numbers are
generated on the CPU backend with virtual devices, so absolute latency is
meaningless next to a real TPU pod — the comparisons (batching on/off,
bucket shapes, drop counts under rescale) are the portable part.
QPS/chip divides by `jax.device_count()` per the MLPerf-style per-chip
accounting the TPU-pod papers report.
"""

from __future__ import annotations

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import json
import tempfile
import threading
import time
from typing import Dict, List

import numpy as np

OUT = os.path.join(os.path.dirname(__file__), "BENCH_SERVE.json")

RATE_QPS = 120.0  # below single-replica CPU-sim capacity (~300 QPS)
N_REQUESTS = 360
BURST_REQUESTS = 512
BUCKET_CONFIGS = ((1, 8, 32), (1, 4, 16), (8, 32))


def _export_artifact(directory: str, scale: float = 1.0, step: int = 100):
    import jax

    from edl_tpu.models import fit_a_line
    from edl_tpu.runtime.export import _serving_mesh, save_inference_model

    model = fit_a_line.MODEL
    mesh = _serving_mesh(model)
    params = model.init(jax.random.PRNGKey(0), mesh)
    if scale != 1.0:
        params = jax.tree_util.tree_map(lambda x: x * scale, params)
    save_inference_model(directory, "fit_a_line", params, step=step,
                         versioned=True)


def _requests(n: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    features = [{"x": rng.standard_normal(13).astype(np.float32)}
                for _ in range(n)]
    # exponential inter-arrivals -> Poisson arrivals at RATE_QPS
    gaps = rng.exponential(1.0 / RATE_QPS, size=n)
    return features, np.cumsum(gaps)


def _percentiles(latencies: List[float]) -> Dict[str, float]:
    arr = np.asarray(latencies)
    return {
        "p50_ms": round(float(np.percentile(arr, 50)) * 1e3, 3),
        "p99_ms": round(float(np.percentile(arr, 99)) * 1e3, 3),
        "mean_ms": round(float(arr.mean()) * 1e3, 3),
    }


def _open_loop(submit, n: int, seed: int = 0):
    """Fire ``n`` requests on the open-loop schedule; returns
    ([(future, record)], submit_errors). Completion time is stamped by a
    done-callback AT resolution — measuring at collection time would
    charge early requests for the whole submission window."""
    features, arrivals = _requests(n, seed)
    t0 = time.monotonic()
    futures, errors = [], 0
    for feat, due in zip(features, arrivals):
        delay = t0 + due - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        try:
            t_submit = time.monotonic()
            fut = submit(feat)
            record = {"t_submit": t_submit, "t_done": None}
            fut.add_done_callback(
                lambda f, r=record: r.__setitem__("t_done", time.monotonic())
            )
            futures.append((fut, record))
        except Exception:  # edl: noqa[EDL005] overload rejections are a measured outcome of the open-loop arm, reported as submit errors in the results
            errors += 1
    return futures, errors


def run_arm(name: str, buckets, max_delay_s: float) -> Dict:
    import jax

    from edl_tpu.serving import ServingConfig, ServingReplica

    with tempfile.TemporaryDirectory() as td:
        _export_artifact(td)
        replica = ServingReplica(ServingConfig(
            model_dir=td, buckets=buckets, max_batch_delay_s=max_delay_s,
            queue_capacity=4096, name=f"bench-{name}",
        )).start()
        try:
            # phase 1: open-loop latency below capacity
            futures, submit_errors = _open_loop(replica.submit, N_REQUESTS)
            latencies = []
            failed = 0
            for fut, record in futures:
                try:
                    fut.result(timeout=60)
                    latencies.append(record["t_done"] - record["t_submit"])
                except Exception:  # edl: noqa[EDL005] per-request failures are a measured outcome, reported as the arm's failed count
                    failed += 1
            # phase 2: burst throughput — everything enqueued at once
            feats, _ = _requests(BURST_REQUESTS, seed=2)
            t_burst = time.monotonic()
            burst = [replica.submit(f) for f in feats]
            for fut in burst:
                fut.result(timeout=120)
            burst_wall = time.monotonic() - t_burst
            status = replica.status()
        finally:
            replica.stop()
    qps = BURST_REQUESTS / burst_wall if burst_wall > 0 else 0.0
    chips = jax.device_count()
    return {
        "buckets": list(buckets),
        "max_batch_delay_ms": max_delay_s * 1e3,
        "open_loop": {
            "requests": N_REQUESTS,
            "offered_qps": RATE_QPS,
            "completed": len(latencies),
            "failed": failed + submit_errors,
            "latency": _percentiles(latencies),
        },
        "burst": {
            "requests": BURST_REQUESTS,
            "wall_seconds": round(burst_wall, 3),
            "qps": round(qps, 1),
            "qps_per_chip": round(qps / chips, 2),
        },
        "bucket_hits": status["bucket_hits"],
        "batches": sum(status["bucket_hits"].values()),
        "mean_batch_size": round(
            status["completed"] / max(1, sum(status["bucket_hits"].values())), 2
        ),
    }


class _Router:
    """Round-robin over a mutable replica pool — the bench's stand-in for
    the controller's service endpoints. Rescale = pool mutation."""

    def __init__(self, replicas):
        self.replicas = list(replicas)
        self._lock = threading.Lock()
        self._i = 0

    def submit(self, features):
        with self._lock:
            replica = self.replicas[self._i % len(self.replicas)]
            self._i += 1
        return replica.submit(features)

    def add(self, replica):
        with self._lock:
            self.replicas.append(replica)

    def remove(self):
        with self._lock:
            return self.replicas.pop(0)


def run_rescale_arm() -> Dict:
    from edl_tpu.serving import ServingConfig, ServingReplica

    buckets = (1, 8, 32)
    with tempfile.TemporaryDirectory() as td:
        _export_artifact(td)

        def make(i):
            return ServingReplica(ServingConfig(
                model_dir=td, buckets=buckets, max_batch_delay_s=0.005,
                queue_capacity=4096, name=f"bench-rescale-{i}",
            )).start()

        pool = _Router([make(0), make(1)])
        timeline = []
        stopped = []

        def rescale_script():
            # grow mid-traffic: the new replica AOT-compiles its buckets
            # BEFORE joining the pool (the warm-join discipline)
            time.sleep(0.4)
            replica = make(2)
            pool.add(replica)
            timeline.append("t+0.4s grow 2->3 (replica pre-compiled)")
            # shrink mid-traffic: remove from routing, then drain — every
            # request already accepted by the leaving replica completes
            time.sleep(0.4)
            leaving = pool.remove()
            timeline.append("t+0.8s shrink 3->2 (drained, zero aborts)")
            leaving.stop(drain=True)
            stopped.append(leaving)

        script = threading.Thread(target=rescale_script)
        script.start()
        t_start = time.monotonic()
        futures, submit_errors = _open_loop(pool.submit, N_REQUESTS, seed=1)
        latencies, dropped = [], 0
        for fut, record in futures:
            try:
                fut.result(timeout=60)
                latencies.append(record["t_done"] - record["t_submit"])
            except Exception:  # edl: noqa[EDL005] a dropped in-flight request is THE metric of the rescale arm (must be zero); counted, and non-zero fails the bench exit code
                dropped += 1
        wall = time.monotonic() - t_start
        script.join()
        completed_per_replica = {}
        for replica in pool.replicas + stopped:
            status = replica.status()
            completed_per_replica[status["name"]] = status["completed"]
            replica.stop()
    return {
        "buckets": list(buckets),
        "requests": N_REQUESTS,
        "accepted": len(futures),
        "submit_rejections": submit_errors,
        "completed": len(latencies),
        "dropped_in_flight": dropped,
        "timeline": timeline,
        "completed_per_replica": completed_per_replica,
        "achieved_qps": round(len(latencies) / wall, 1) if wall else 0.0,
        "latency": _percentiles(latencies),
    }


def main() -> int:
    import jax

    results = {
        "bench": "serving tier: continuous batching + rescale-under-traffic",
        "env": {
            "backend": jax.default_backend(),
            "devices": jax.device_count(),
            "note": ("CPU-sim: absolute latencies are not TPU numbers; "
                     "batching-on/off deltas, bucket shapes and drop "
                     "counts are the portable comparisons"),
        },
        "offered_load_qps": RATE_QPS,
        "arms": {},
        "bucket_table": [],
    }
    print(f"== batching on (buckets {BUCKET_CONFIGS[0]}) ==")
    on = run_arm("on", BUCKET_CONFIGS[0], 0.005)
    print(json.dumps({**on["open_loop"]["latency"], **on["burst"]}))
    results["arms"]["batching_on"] = on
    print("== batching off (bucket ladder (1,), no coalesce) ==")
    off = run_arm("off", (1,), 0.0)
    print(json.dumps({**off["open_loop"]["latency"], **off["burst"]}))
    results["arms"]["batching_off"] = off
    for buckets in BUCKET_CONFIGS:
        print(f"== bucket config {buckets} ==")
        arm = run_arm(f"buckets-{'-'.join(map(str, buckets))}", buckets, 0.005)
        results["bucket_table"].append(arm)
    print("== rescale under traffic ==")
    rescale = run_rescale_arm()
    print(json.dumps({k: rescale[k] for k in
                      ("accepted", "completed", "dropped_in_flight")}))
    results["arms"]["rescale_under_traffic"] = rescale
    with open(OUT, "w") as f:
        json.dump(results, f, indent=2)
        f.write("\n")
    print(f"wrote {OUT}")
    return 0 if rescale["dropped_in_flight"] == 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
