#!/bin/sh
# Build both images (ref: docker/build.sh). Run from the repo root.
set -e
TAG="${TAG:-latest}"
docker build -f deploy/Dockerfile.controller -t "edl-tpu-controller:${TAG}" .
docker build -f deploy/Dockerfile.trainer -t "edl-tpu:${TAG}" .
echo "built edl-tpu-controller:${TAG} and edl-tpu:${TAG}"
