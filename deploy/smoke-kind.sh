#!/bin/sh
# Real-cluster smoke test: install the CRD/RBAC/controller into a kind
# cluster, submit the fit_a_line elastic job, and wait for Succeeded.
#
# The fake-apiserver tests (tests/test_k8s.py) validate the client against
# OUR model of the apiserver; this script validates it against a REAL one —
# the same role minikube played for the reference (doc/install.md:37-47).
# It needs `kind`, `kubectl`, and `docker` on PATH and cannot run in the
# hermetic CI image (no container runtime, no network); run it from a
# workstation and keep doc/smoke-kind.md's transcript current.
#
# Usage: deploy/smoke-kind.sh [--keep]   (from the repo root)
set -eu

CLUSTER="${EDL_SMOKE_CLUSTER:-edl-tpu-smoke}"
KEEP=0
[ "${1:-}" = "--keep" ] && KEEP=1

need() { command -v "$1" >/dev/null || { echo "missing: $1" >&2; exit 2; }; }
need kind
need kubectl
need docker

cleanup() {
    [ "$KEEP" = 1 ] && { echo "keeping cluster $CLUSTER"; return; }
    kind delete cluster --name "$CLUSTER" >/dev/null 2>&1 || true
}
trap cleanup EXIT

echo "==> kind cluster"
kind get clusters 2>/dev/null | grep -qx "$CLUSTER" || \
    kind create cluster --name "$CLUSTER" --wait 120s

echo "==> build + load images"
TAG=smoke sh deploy/build.sh
kind load docker-image "edl-tpu-controller:smoke" --name "$CLUSTER"
kind load docker-image "edl-tpu:smoke" --name "$CLUSTER"

echo "==> install CRD + RBAC + controller"
kubectl apply -f deploy/crd.yaml
kubectl apply -f deploy/rbac.yaml
# pin the smoke tag and never pull (images are side-loaded)
sed -e 's|image: edl-tpu:latest|image: edl-tpu-controller:smoke|' \
    deploy/controller.yaml | kubectl apply -f -
kubectl -n kube-system patch deployment edl-tpu-controller --type=json -p '[
  {"op":"add","path":"/spec/template/spec/containers/0/imagePullPolicy","value":"Never"}
]' >/dev/null 2>&1 || true
kubectl -n kube-system rollout status deployment/edl-tpu-controller --timeout=180s

echo "==> submit fit_a_line job"
# retag to the side-loaded image: :smoke defaults to IfNotPresent, so the
# kind node uses the loaded image instead of pulling (which would fail)
sed 's|image: edl-tpu:latest|image: edl-tpu:smoke|' \
    examples/fit_a_line/job.yaml | kubectl apply -f -

echo "==> wait for Succeeded"
deadline=$(( $(date +%s) + 600 ))
while :; do
    phase="$(kubectl get trainingjob fit-a-line \
        -o jsonpath='{.status.phase}' 2>/dev/null || true)"
    echo "   phase=${phase:-<none>}"
    [ "$phase" = "Succeeded" ] && break
    if [ "$phase" = "Failed" ] || [ "$(date +%s)" -gt "$deadline" ]; then
        echo "SMOKE FAILED (phase=${phase:-timeout})" >&2
        kubectl get pods -A -l edl.tpu/job-name=fit-a-line -o wide || true
        kubectl -n kube-system logs deployment/edl-tpu-controller --tail=100 || true
        exit 1
    fi
    sleep 5
done

echo "SMOKE OK: fit-a-line reached Succeeded on a real apiserver"
