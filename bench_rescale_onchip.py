"""On-chip warm-restart pricing: the rescale terms the CPU sim can't see.

BENCH_RESCALE.json proves the <30 s / >=90 % north-star on the 8-device CPU
simulation mesh — but a REAL rescale pays TPU runtime bring-up and XLA
recompilation, which the sim prices at CPU rates (VERDICT r4 weak #7). This
bench measures the full single-chip warm-restart path with two separate OS
processes on the live backend, exactly what a pod pays after
``RESCALE_EXIT_CODE=75``:

  phase A (doomed pod):   backend init -> trainer build+compile -> train ->
                          checkpoint -> exit(75)
  phase B (restarted pod): backend init -> trainer build -> restore ->
                          first step (recompile) -> ready

``recovery_seconds`` = A's stop decision (checkpoint start) through B's
first optimizer step, the elastic-budget span. Every term is itemized so a
>30 s result indicts a specific cost. The JAX persistent compilation cache
is enabled for phase B by default (the framework's recommended deployment
config — a warm restart re-runs the SAME program, so the compile term
should be a cache hit); EDL_RESCALE_NO_COMPILE_CACHE=1 prices the cold
path. Writes BENCH_RESCALE_ONCHIP.json; prints one JSON line.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time


def phase_env(workdir: str) -> dict:
    env = dict(os.environ)
    if os.environ.get("EDL_RESCALE_NO_COMPILE_CACHE") != "1":
        env["JAX_COMPILATION_CACHE_DIR"] = os.path.join(workdir, "xla-cache")
        # cache even fast-compiling programs (default threshold 1s)
        env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0"
    return env


def run_phase(phase: str, workdir: str, timeout: float) -> dict:
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--phase", phase, workdir],
        env=phase_env(workdir), timeout=timeout,
        capture_output=True, text=True,
    )
    marks_path = os.path.join(workdir, f"{phase}.json")
    if not os.path.exists(marks_path):
        raise RuntimeError(
            f"phase {phase} left no marks (rc={out.returncode}): "
            f"{out.stderr[-800:]}"
        )
    with open(marks_path) as f:
        marks = json.load(f)
    marks["returncode"] = out.returncode
    return marks


def _phase_main(phase: str, workdir: str) -> None:
    """Runs inside each pod subprocess; writes monotonic-ish wall marks
    keyed off time.time() so the parent can splice A and B timelines."""
    marks = {"start": time.time()}

    import jax

    if os.environ.get("EDL_BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["EDL_BENCH_PLATFORM"])

    from bench import probe_devices
    from edl_tpu.models import ctr
    from edl_tpu.parallel import MeshSpec, build_mesh
    from edl_tpu.runtime import Trainer, TrainerConfig
    from edl_tpu.runtime.checkpoint import (
        Checkpointer, abstract_like, live_state_specs,
    )
    import numpy as np

    devices, reason = probe_devices(
        init_timeout=float(os.environ.get("EDL_BENCH_INIT_TIMEOUT", "300")),
        allow_cpu=os.environ.get("EDL_BENCH_ALLOW_CPU") == "1",
    )
    if devices is None:
        marks["error"] = reason
        with open(os.path.join(workdir, f"{phase}.json"), "w") as f:
            json.dump(marks, f)
        os._exit(3)
    marks["backend_ready"] = time.time()
    marks["backend"] = devices[0].platform

    batch_size = int(os.environ.get("EDL_RESCALE_BATCH", "8192"))
    model = ctr.MODEL
    mesh = build_mesh(MeshSpec({"data": len(devices)}), devices)
    trainer = Trainer(model, mesh,
                      TrainerConfig(optimizer="adagrad", learning_rate=0.05))
    rng = np.random.default_rng(0)
    batch = trainer.place_batch(model.synthetic_batch(rng, batch_size))
    ckpt = Checkpointer(os.path.join(workdir, "ck"))

    if phase == "train":
        state = trainer.init_state()
        state, loss = trainer.train_step(state, batch)
        jax.block_until_ready(loss)
        marks["first_step_done"] = time.time()  # includes train compile
        for _ in range(10):
            state, loss = trainer.train_step(state, batch)
        jax.block_until_ready(loss)
        marks["steady_done"] = time.time()
        # the stop decision: SIGTERM/rescale arrived; checkpoint and leave
        marks["stop_decision"] = time.time()
        ckpt.save(int(state.step), state)
        ckpt.wait()
        marks["checkpoint_done"] = time.time()
        with open(os.path.join(workdir, f"{phase}.json"), "w") as f:
            json.dump(marks, f)
        os._exit(75)  # RESCALE_EXIT_CODE
    else:  # restore
        fresh = trainer.init_state()  # param alloc, no step compile yet
        marks["state_built"] = time.time()
        state = ckpt.restore(abstract_like(fresh), mesh,
                             live_state_specs(fresh))
        marks["restore_done"] = time.time()
        state, loss = trainer.train_step(state, batch)
        jax.block_until_ready(loss)
        marks["first_step_done"] = time.time()
        with open(os.path.join(workdir, f"{phase}.json"), "w") as f:
            json.dump(marks, f)
        os._exit(0)


def main() -> None:
    if "--phase" in sys.argv:
        i = sys.argv.index("--phase")
        _phase_main(sys.argv[i + 1], sys.argv[i + 2])
        return

    workdir = tempfile.mkdtemp(prefix="edl-rescale-onchip-")
    timeout = float(os.environ.get("EDL_RESCALE_TIMEOUT", "900"))
    t_gap0 = time.time()
    a = run_phase("train", workdir, timeout)
    t_gap1 = time.time()
    if "error" in a:
        print(json.dumps({"metric": "onchip_warm_restart_recovery_seconds",
                          "error": a["error"]}))
        return
    if a["returncode"] != 75:
        print(json.dumps({"metric": "onchip_warm_restart_recovery_seconds",
                          "error": f"train phase rc={a['returncode']} != 75"}))
        return
    b = run_phase("restore", workdir, timeout)
    if "error" in b:
        print(json.dumps({"metric": "onchip_warm_restart_recovery_seconds",
                          "error": b["error"]}))
        return

    # pod-runtime respawn gap: parent splice minus A's post-mark teardown
    recovery = b["first_step_done"] - a["stop_decision"]
    result = {
        "metric": "onchip_warm_restart_recovery_seconds",
        "value": round(recovery, 3),
        "unit": "seconds",
        "pass_under_30s": recovery < 30.0,
        "backend": b.get("backend"),
        "compile_cache": os.environ.get("EDL_RESCALE_NO_COMPILE_CACHE") != "1",
        "terms": {
            "A_checkpoint_seconds": round(
                a["checkpoint_done"] - a["stop_decision"], 3),
            "A_exit_to_B_spawn_seconds": round(b["start"] -
                                               a["checkpoint_done"], 3),
            "B_backend_init_seconds": round(b["backend_ready"] - b["start"],
                                            3),
            "B_trainer_build_seconds": round(b["state_built"] -
                                             b["backend_ready"], 3),
            "B_restore_seconds": round(b["restore_done"] - b["state_built"],
                                       3),
            "B_first_step_seconds": round(b["first_step_done"] -
                                          b["restore_done"], 3),
        },
        "reference_terms": {
            "A_cold_backend_init_seconds": round(
                a["backend_ready"] - a["start"], 3),
            "A_cold_first_step_seconds": round(
                a["first_step_done"] - a["backend_ready"], 3),
            "parent_overhead_seconds": round(t_gap1 - t_gap0 -
                                             (a["checkpoint_done"] -
                                              a["start"]), 3),
        },
        "note": (
            "recovery = checkpoint start in the doomed pod through first "
            "optimizer step in a fresh OS process on the live backend; "
            "B_first_step is the XLA compile term (persistent cache on "
            "unless EDL_RESCALE_NO_COMPILE_CACHE=1)"
        ),
    }
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "BENCH_RESCALE_ONCHIP.json"), "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
