"""edl_tpu — a TPU-native elastic deep-learning training framework.

A ground-up re-design of the capabilities of wopeizl/edl (an elastic-scheduling
system for distributed DL jobs on Kubernetes, built around PaddlePaddle parameter
servers) for TPU hardware and the JAX/XLA stack:

- The parameter-server data plane (C++ `paddle pserver`, sparse port pools, gRPC
  gradient servers) is replaced by SPMD training under ``jax.jit`` over a
  ``jax.sharding.Mesh`` — gradients ride ICI all-reduces inserted by XLA, and
  large embedding tables are sharded across the mesh instead of living in a
  separate pserver process (reference: docker/paddle_k8s:3-12,
  pkg/jobparser.go:232-247).
- The fault-tolerant master + etcd sidecar (reference: pkg/jobparser.go:167-227,
  /usr/bin/master in docker/paddle_k8s:26-32) becomes a single native C++
  coordinator service (`native/coordinator`) providing membership epochs, rank
  assignment, a leased data-shard task queue, barriers and a small KV store.
- "Parallelism++" elasticity (reference: pkg/autoscaler.go:361-362 rewriting
  TrainerJob.Spec.Parallelism) becomes checkpoint-restore mesh rescale: on a
  membership epoch change workers checkpoint asynchronously, re-initialize the
  mesh at the new world size, restore, and resume from the task queue.
- The cluster autoscaler (reference: pkg/autoscaler.go) keeps its pure
  fixed-point dry-run core but scores TPU slice quota instead of nvidia.com/gpu.

Package layout:
  api/         TrainingJob spec types, defaults, validation   (ref: pkg/resource, pkg/apis)
  controller/  controller, per-job updater, autoscaler, cluster (ref: pkg/*.go, pkg/updater)
  coordinator/ Python client + in-process server for the C++ coordinator (ref: master+etcd)
  runtime/     elastic trainer runtime: mesh, train loop, data leases, checkpoints
  parallel/    sharding helpers: dp/tp/sp mesh axes, sharded embeddings
  ops/         Pallas TPU kernels for hot ops
  models/      fit_a_line, MNIST, word2vec, CTR deep-wide (flagship), ResNet
  launcher/    pod/process role launcher + discovery           (ref: docker/paddle_k8s, k8s_tools.py)
  tools/       collector metrics harness                       (ref: example/fit_a_line/collector.py)
"""

__version__ = "0.1.0"
