"""SARIF 2.1.0 serialization for analysis findings.

``--format sarif`` emits one run with the full rule catalog, so CI viewers
(GitHub code scanning et al.) render findings as inline annotations with
rule help text. Two deliberate choices:

- **Baselined findings are emitted as suppressed results** (``suppressions``
  with ``kind: external``) rather than dropped: the debt stays visible in
  the SARIF view exactly like ``--show-baselined`` in text mode, without
  failing the CI gate.
- **``partialFingerprints.edlFingerprint/v1``** carries the same
  sha256-prefix fingerprint the baseline uses, so a SARIF consumer's
  dedup/tracking agrees with ``analysis_baseline.json`` about which
  findings are "the same" across commits.

``from_sarif`` inverts ``to_sarif`` for the round-trip tests — it is a
test aid, not a general SARIF reader (it assumes our own producer's
shape).
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

from edl_tpu.analysis.baseline import fingerprint
from edl_tpu.analysis.core import Finding

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)
TOOL_NAME = "edl-analysis"


def _result(finding: Finding, baselined: bool) -> Dict[str, Any]:
    result: Dict[str, Any] = {
        "ruleId": finding.rule,
        "level": "warning",
        "message": {"text": finding.message},
        "locations": [
            {
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": finding.path,
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {
                        "startLine": max(1, finding.line),
                        # SARIF columns are 1-based; Finding.col is 0-based
                        "startColumn": finding.col + 1,
                    },
                }
            }
        ],
        "partialFingerprints": {
            "edlFingerprint/v1": fingerprint(finding),
        },
    }
    if finding.symbol:
        result["properties"] = {"symbol": finding.symbol}
    if baselined:
        result["suppressions"] = [
            {"kind": "external", "justification": "accepted in analysis_baseline.json"}
        ]
    return result


def to_sarif(
    new: Sequence[Finding],
    baselined: Sequence[Finding] = (),
) -> Dict[str, Any]:
    """Build the SARIF 2.1.0 document for one analysis run."""
    from edl_tpu.analysis.checkers import ALL_CHECKERS

    rules = [
        {
            "id": cls.rule,
            "name": cls.info.name,
            "shortDescription": {"text": cls.info.description},
        }
        for cls in ALL_CHECKERS
    ]
    results = [_result(f, baselined=False) for f in new]
    results.extend(_result(f, baselined=True) for f in baselined)
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": TOOL_NAME,
                        "informationUri": "doc/analysis.md",
                        "rules": rules,
                    }
                },
                "results": results,
            }
        ],
    }


def from_sarif(doc: Dict[str, Any]) -> Tuple[List[Finding], List[Finding]]:
    """Invert :func:`to_sarif`: (new, baselined) findings, in emit order."""
    new: List[Finding] = []
    baselined: List[Finding] = []
    for run in doc.get("runs", ()):
        for result in run.get("results", ()):
            loc = result["locations"][0]["physicalLocation"]
            region = loc.get("region", {})
            finding = Finding(
                rule=result["ruleId"],
                path=loc["artifactLocation"]["uri"],
                line=int(region.get("startLine", 1)),
                col=int(region.get("startColumn", 1)) - 1,
                message=result["message"]["text"],
                symbol=result.get("properties", {}).get("symbol", ""),
            )
            if result.get("suppressions"):
                baselined.append(finding)
            else:
                new.append(finding)
    return new, baselined
