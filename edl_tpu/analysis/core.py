"""Core types for the analysis engine: findings, parsed files, suppression.

A ``SourceFile`` is one parsed Python file plus the derived indexes every
checker needs: raw lines (for ``# edl: noqa`` scanning) and a line->symbol
interval map (so findings carry a stable ``Class.method`` symbol instead of
a line number in their identity — see ``baseline.fingerprint``).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

#: ``# edl: noqa`` suppresses every rule on its line; ``# edl: noqa[EDL001]``
#: (comma-separated for several) suppresses just those. Anything after the
#: bracket is the human justification — encouraged, not parsed.
_NOQA_RE = re.compile(
    r"#\s*edl:\s*noqa(?:\[\s*([A-Za-z0-9_,\s]+?)\s*\])?", re.IGNORECASE
)


@dataclass(frozen=True)
class Finding:
    """One rule violation at a specific site."""

    rule: str  # "EDL001"
    path: str  # repo-relative posix path
    line: int  # 1-based
    col: int  # 0-based
    message: str  # stable text: no line numbers, no volatile state
    symbol: str = ""  # innermost enclosing "Class.method" (or "" at module level)

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col + 1}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "symbol": self.symbol,
        }


class SourceFile:
    """A parsed source file with the indexes checkers share."""

    def __init__(self, path: str, relpath: str, text: str):
        self.path = path
        self.relpath = relpath.replace("\\", "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self._noqa: Optional[Dict[int, Optional[Set[str]]]] = None
        self._symbols: Optional[List[Tuple[int, int, str]]] = None

    # -- suppression -----------------------------------------------------------

    @property
    def noqa(self) -> Dict[int, Optional[Set[str]]]:
        """line -> None (blanket) or set of uppercased rule ids."""
        if self._noqa is None:
            table: Dict[int, Optional[Set[str]]] = {}
            for i, line in enumerate(self.lines, start=1):
                if "edl" not in line:  # cheap pre-filter
                    continue
                m = _NOQA_RE.search(line)
                if not m:
                    continue
                if m.group(1) is None:
                    table[i] = None
                else:
                    rules = {
                        r.strip().upper()
                        for r in m.group(1).split(",")
                        if r.strip()
                    }
                    # Merge with an earlier marker on the same line.
                    prev = table.get(i, set())
                    table[i] = None if prev is None else (prev | rules)
            self._noqa = table
        return self._noqa

    def is_suppressed(self, finding: Finding) -> bool:
        if finding.line not in self.noqa:
            return False
        rules = self.noqa[finding.line]
        return rules is None or finding.rule.upper() in rules

    # -- symbols ---------------------------------------------------------------

    @property
    def symbols(self) -> List[Tuple[int, int, str]]:
        """(start, end, qualname) for every def/class, outermost first."""
        if self._symbols is None:
            out: List[Tuple[int, int, str]] = []

            def visit(node: ast.AST, prefix: str) -> None:
                for child in ast.iter_child_nodes(node):
                    if isinstance(
                        child,
                        (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                    ):
                        qual = f"{prefix}.{child.name}" if prefix else child.name
                        out.append(
                            (child.lineno, child.end_lineno or child.lineno, qual)
                        )
                        visit(child, qual)
                    else:
                        visit(child, prefix)

            visit(self.tree, "")
            self._symbols = out
        return self._symbols

    def symbol_at(self, line: int) -> str:
        """Innermost def/class enclosing ``line`` ("" at module level)."""
        best = ""
        best_span = None
        for start, end, qual in self.symbols:
            if start <= line <= end:
                span = end - start
                if best_span is None or span <= best_span:
                    best, best_span = qual, span
        return best


# -- shared AST helpers --------------------------------------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain; None for anything dynamic."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def is_self_attr(node: ast.AST) -> Optional[str]:
    """Attribute name if ``node`` is ``self.<attr>``, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def self_attr_root(node: ast.AST) -> Optional[str]:
    """Root attribute for writes through ``self``: ``self.a`` -> "a",
    ``self.a[k]`` -> "a", ``self.a[k].b`` -> "a" (mutation of shared
    containers counts as a write to the owning attribute)."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        attr = is_self_attr(node)
        if attr is not None:
            return attr
        node = node.value
    return None


@dataclass
class RuleInfo:
    """Static metadata for --list-rules and the docs."""

    rule: str
    name: str
    description: str
    example: str = field(default="", repr=False)
